"""Persistence: PostgREST-shaped stores for route requests/results.

Schema follows the Laravel migrations plus the runtime drift the Flask
service writes (SURVEY.md §2.2): ``route_requests`` (origin_id, stops
jsonb, status, engine, vehicle_id, driver_age, request_time) and
``route_results`` (request_id FK-cascade, total_distance, total_duration,
optimized_order, legs, geometry, eta_minutes_ml, eta_completion_time_ml).

Two implementations behind one interface:

- ``InMemoryStore`` — hermetic default (the generalization of the
  reference's sqlite-:memory: test trick, SURVEY.md §4); also what makes
  history work out of the box with no Supabase account.
- ``PostgRESTStore`` — the reference's runtime path (Supabase service-role
  writes, embedded-resource selects, FK-cascade delete,
  ``Flaskr/routes.py:134-182,193-250,386-405``).
"""

from __future__ import annotations

import collections
import datetime as dt
import random
import threading
import time
import uuid
from typing import Deque, Dict, List, Optional, Protocol, Tuple

from routest_tpu.obs import get_registry
from routest_tpu.obs.trace import trace_span
from routest_tpu.utils.logging import get_logger

_log = get_logger("routest_tpu.serve.store")


class StoreUnavailable(RuntimeError):
    """The store's circuit breaker is open: fail fast instead of
    stacking timeouts against a dead backend. Read handlers surface
    this as an explicit ``degraded: true`` response marker."""


class Store(Protocol):
    def insert_request(self, row: Dict) -> str: ...
    def insert_result(self, row: Dict) -> None: ...
    def list_history(self, limit: int,
                     engine: Optional[str] = None) -> List[Dict]: ...
    def get_request(self, req_id: str) -> Optional[Dict]: ...
    def delete_request(self, req_id: str) -> bool: ...
    def ping(self) -> bool: ...
    @property
    def kind(self) -> str: ...


def _now_iso() -> str:
    return dt.datetime.now(dt.timezone.utc).isoformat()


class InMemoryStore:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._requests: Dict[str, Dict] = {}
        self._results: Dict[str, List[Dict]] = {}

    def insert_request(self, row: Dict) -> str:
        # A caller-supplied id is honored (the resilience layer mints
        # ids for journaled writes so results can reference their
        # request before the replay lands), as PostgREST would.
        req_id = str(row.get("id") or uuid.uuid4())
        with self._lock:
            self._requests[req_id] = {
                "request_time": _now_iso(),
                **row,
                "id": req_id,
            }
        return req_id

    def insert_result(self, row: Dict) -> None:
        result = {"id": str(uuid.uuid4()), "created_at": _now_iso(), **row}
        with self._lock:
            req_id = row.get("request_id")
            if req_id not in self._requests:
                raise KeyError(f"route_requests.{req_id} does not exist")
            self._results.setdefault(req_id, []).append(result)

    def list_history(self, limit: int,
                     engine: Optional[str] = None) -> List[Dict]:
        with self._lock:
            rows = sorted(self._requests.values(),
                          key=lambda r: r["request_time"], reverse=True)
            if engine is not None:
                rows = [r for r in rows if r.get("engine") == engine]
            rows = rows[:limit]
            return [
                {**r, "route_results": list(self._results.get(r["id"], ()))}
                for r in rows
            ]

    def get_request(self, req_id: str) -> Optional[Dict]:
        with self._lock:
            r = self._requests.get(req_id)
            if r is None:
                return None
            return {**r, "route_results": list(self._results.get(req_id, ()))}

    def delete_request(self, req_id: str) -> bool:
        with self._lock:
            existed = req_id in self._requests
            self._requests.pop(req_id, None)
            self._results.pop(req_id, None)  # FK cascade
            return existed

    def ping(self) -> bool:
        return True

    @property
    def kind(self) -> str:
        return "memory"


class PostgRESTStore:
    """Supabase PostgREST client, request-shape compatible with the
    reference service."""

    def __init__(self, url: str, service_key: str, timeout: float = 20.0) -> None:
        import requests  # gated: serving extra

        self._requests_lib = requests
        self._rest = f"{url.rstrip('/')}/rest/v1"
        self._headers = {
            "apikey": service_key,
            "Authorization": f"Bearer {service_key}",
            "Content-Type": "application/json",
            "Prefer": "return=representation",
        }
        self._timeout = timeout

    def insert_request(self, row: Dict) -> str:
        r = self._requests_lib.post(f"{self._rest}/route_requests",
                                    headers=self._headers, json=row,
                                    timeout=self._timeout)
        r.raise_for_status()
        return r.json()[0]["id"]

    def insert_result(self, row: Dict) -> None:
        r = self._requests_lib.post(f"{self._rest}/route_results",
                                    headers=self._headers, json=row,
                                    timeout=self._timeout)
        r.raise_for_status()

    _HISTORY_SELECT = (
        "id,request_time,origin_id,stops,engine,vehicle_id,driver_age,"
        "route_results(id,total_distance,total_duration,optimized_order,"
        "created_at,eta_minutes_ml,eta_completion_time_ml)"
    )
    _DETAIL_SELECT = (
        "id,origin_id,stops,status,request_time,engine,vehicle_id,driver_age,"
        "route_results(id,total_distance,total_duration,optimized_order,legs,"
        "created_at,eta_minutes_ml,eta_completion_time_ml,geometry)"
    )

    def list_history(self, limit: int,
                     engine: Optional[str] = None) -> List[Dict]:
        params = {"select": self._HISTORY_SELECT,
                  "order": "request_time.desc", "limit": str(limit)}
        if engine is not None:
            params["engine"] = f"eq.{engine}"  # PostgREST filter syntax
        r = self._requests_lib.get(
            f"{self._rest}/route_requests", headers=self._headers,
            params=params,
            timeout=self._timeout,
        )
        r.raise_for_status()
        return r.json()

    def get_request(self, req_id: str) -> Optional[Dict]:
        r = self._requests_lib.get(
            f"{self._rest}/route_requests", headers=self._headers,
            params={"select": self._DETAIL_SELECT, "id": f"eq.{req_id}",
                    "limit": "1"},
            timeout=self._timeout,
        )
        r.raise_for_status()
        rows = r.json()
        return rows[0] if rows else None

    def delete_request(self, req_id: str) -> bool:
        # Keep Prefer: return=representation so PostgREST returns the
        # deleted rows — a 204/empty body means nothing matched, which must
        # surface as not-found (parity with InMemoryStore).
        r = self._requests_lib.delete(
            f"{self._rest}/route_requests", headers=self._headers,
            params={"id": f"eq.{req_id}"}, timeout=10,
        )
        if r.status_code not in (200, 204):
            return False
        try:
            return bool(r.json())
        except ValueError:
            return False

    def ping(self) -> bool:
        try:
            r = self._requests_lib.get(
                f"{self._rest}/route_requests", headers=self._headers,
                params={"select": "id", "limit": "1"}, timeout=3,
            )
            return 200 <= r.status_code < 300
        except Exception as e:
            # Visible, not swallowed: a store outage used to vanish here
            # (health said "error" with no trace of why).
            _log.warning("store_ping_failed", backend="postgrest",
                         error=f"{type(e).__name__}: {e}")
            get_registry().counter(
                "rtpu_store_errors_total",
                "Store backend call failures, by operation.",
                ("op",)).labels(op="ping").inc()
            return False

    @property
    def kind(self) -> str:
        return "postgrest"


def _is_transient(e: BaseException) -> bool:
    """Failure classification: transient errors are retried, charged to
    the breaker, and (for writes) journaled; everything else — FK
    violations, 4xx responses — is the caller's problem and raises
    immediately (retrying a logic error just triples its latency).

    The response-status check comes FIRST: ``requests.HTTPError``
    subclasses OSError, so a 409 would otherwise read as a dead socket.
    Duck-typed so the requests dependency stays optional."""
    response = getattr(e, "response", None)
    status = getattr(response, "status_code", None)
    if isinstance(status, int):
        return status >= 500  # 5xx = backend's fault; 4xx = ours
    if isinstance(e, (ConnectionError, TimeoutError, OSError)):
        return True
    from routest_tpu.chaos import ChaosError

    return isinstance(e, ChaosError)


class ResilientStore:
    """Degraded-mode decorator: bounded retry with jittered backoff, a
    failure-threshold circuit breaker, and a bounded in-memory
    write-behind journal that replays on recovery.

    Semantics (docs/ROBUSTNESS.md has the full table):

    - every backend attempt passes the ``store.http`` chaos point, so
      injected faults exercise exactly these paths;
    - transient failures retry up to ``retries`` times with jittered
      exponential backoff; ``breaker_threshold`` consecutive transient
      failures open the breaker for ``cooldown_s``;
    - breaker open: READS fail fast with :class:`StoreUnavailable`
      (handlers answer with ``degraded: true``); WRITES append to the
      journal and succeed locally — ``insert_request`` mints the row id
      up front so dependent ``insert_result`` rows keep their FK;
    - the first successful backend call after an outage (a read, a
      half-open probe, or ``ping`` from the health poller) replays the
      journal FIFO; a replay failure re-opens the breaker and keeps the
      remaining entries;
    - the journal is bounded (``journal_limit``): overflow drops the
      OLDEST entry and counts ``rtpu_store_journal_dropped_total`` —
      bounded loss, never unbounded memory.
    """

    def __init__(self, inner: Store, retries: int = 2,
                 backoff_base_s: float = 0.05, backoff_cap_s: float = 1.0,
                 breaker_threshold: int = 3, cooldown_s: float = 5.0,
                 journal_limit: int = 512) -> None:
        self._inner = inner
        self._retries = max(0, retries)
        self._backoff_base_s = backoff_base_s
        self._backoff_cap_s = backoff_cap_s
        self._threshold = max(1, breaker_threshold)
        self._cooldown_s = cooldown_s
        self._journal_limit = max(1, journal_limit)
        self._journal: Deque[Tuple[str, Dict]] = collections.deque()
        self._lock = threading.Lock()
        self._replay_lock = threading.Lock()
        self._failures = 0
        self._open_until = 0.0
        self._open = False
        self._rng = random.Random()
        reg = get_registry()
        self._m_errors = reg.counter(
            "rtpu_store_errors_total",
            "Store backend call failures, by operation.", ("op",))
        self._m_retries = reg.counter(
            "rtpu_store_retries_total", "Store attempts retried.")
        self._m_breaker_opens = reg.counter(
            "rtpu_store_breaker_opens_total",
            "Times the store circuit breaker opened.")
        self._m_breaker_state = reg.gauge(
            "rtpu_store_breaker_open",
            "1 while the store circuit breaker is open.")
        self._m_journal_depth = reg.gauge(
            "rtpu_store_journal_depth", "Writes awaiting replay.")
        self._m_replayed = reg.counter(
            "rtpu_store_journal_replayed_total",
            "Journaled writes replayed to the backend.")
        self._m_dropped = reg.counter(
            "rtpu_store_journal_dropped_total",
            "Journaled writes lost to the bound (oldest dropped).")
        self._m_journaled = reg.counter(
            "rtpu_store_journal_writes_total",
            "Writes diverted to the journal (backend unavailable). "
            "Counts as budget burn for the store-dependency SLO: a "
            "breaker-open write succeeds locally without erroring, so "
            "the error counter alone goes quiet mid-outage.")

    # ── breaker bookkeeping ───────────────────────────────────────────

    def _breaker_blocks(self) -> bool:
        """True while open and cooling down; after cooldown the next
        call through is the half-open probe."""
        with self._lock:
            if not self._open:
                return False
            return time.monotonic() < self._open_until

    def _note_failure(self, op: str, e: BaseException) -> None:
        self._m_errors.labels(op=op).inc()
        opened = False
        with self._lock:
            self._failures += 1
            if self._failures >= self._threshold and not self._open:
                self._open = True
                opened = True
            if self._open:
                self._open_until = time.monotonic() + self._cooldown_s
        if opened:
            self._m_breaker_opens.inc()
            self._m_breaker_state.set(1)
            _log.warning("store_breaker_opened", backend=self._inner.kind,
                         failures=self._failures,
                         cooldown_s=self._cooldown_s)
            # Postmortem trigger: the breaker opening marks the moment
            # the outage became policy (fail-fast + journal) — capture
            # the evidence while the offending requests are still in
            # the recorder/span rings. Rate-limited inside trigger().
            from routest_tpu.obs.recorder import get_recorder

            get_recorder().trigger("store_breaker_open", {
                "backend": self._inner.kind,
                "consecutive_failures": self._failures,
                "last_error": f"{type(e).__name__}: {e}",
            })
        else:
            _log.warning("store_error", op=op, backend=self._inner.kind,
                         error=f"{type(e).__name__}: {e}")

    def _note_success(self) -> None:
        closed = False
        with self._lock:
            self._failures = 0
            if self._open:
                self._open = False
                closed = True
        if closed:
            self._m_breaker_state.set(0)
            _log.info("store_breaker_closed", backend=self._inner.kind)
        if self._journal:
            self._replay_journal()

    # ── write-behind journal ──────────────────────────────────────────

    def _journal_write(self, op: str, row: Dict) -> None:
        with self._lock:
            if len(self._journal) >= self._journal_limit:
                self._journal.popleft()
                self._m_dropped.inc()
            self._journal.append((op, dict(row)))
            depth = len(self._journal)
        self._m_journaled.inc()
        self._m_journal_depth.set(depth)
        _log.warning("store_write_journaled", op=op, journal_depth=depth)

    def _replay_journal(self) -> int:
        """FIFO replay; stops (and re-opens the breaker) on the first
        failure so order is preserved. Returns entries replayed."""
        if not self._replay_lock.acquire(blocking=False):
            return 0  # one replayer at a time; the next success retries
        replayed = 0
        try:
            while True:
                with self._lock:
                    if not self._journal or self._open:
                        break
                    op, row = self._journal[0]
                try:
                    self._attempt(op, row)
                except Exception as e:
                    if _is_transient(e):
                        self._note_failure(op, e)
                        break
                    # Permanent (e.g. the request row was deleted while
                    # its result sat journaled): drop it or it wedges
                    # the queue forever.
                    _log.error("store_journal_entry_failed", op=op,
                               error=f"{type(e).__name__}: {e}")
                    self._m_dropped.inc()
                    with self._lock:
                        if self._journal and self._journal[0] == (op, row):
                            self._journal.popleft()
                    continue
                with self._lock:
                    if self._journal and self._journal[0] == (op, row):
                        self._journal.popleft()
                    depth = len(self._journal)
                replayed += 1
                self._m_replayed.inc()
                self._m_journal_depth.set(depth)
        finally:
            self._replay_lock.release()
        if replayed:
            _log.info("store_journal_replayed", replayed=replayed,
                      remaining=len(self._journal))
        return replayed

    def _attempt(self, op: str, row: Dict):
        from routest_tpu.chaos import inject as chaos_inject

        chaos_inject("store.http")
        if op == "insert_request":
            return self._inner.insert_request(row)
        return self._inner.insert_result(row)

    # ── call plumbing ─────────────────────────────────────────────────

    def _call(self, op: str, fn, *args):
        """Reads (and delete): retry → fail fast when the breaker is
        open → raise. The caller sees StoreUnavailable only for
        breaker-open fast-fails; a genuine error after retries keeps
        its type (→ 500, not a degraded marker)."""
        from routest_tpu.chaos import inject as chaos_inject

        if self._breaker_blocks():
            raise StoreUnavailable(f"store breaker open ({op})")
        last: Optional[BaseException] = None
        for attempt in range(self._retries + 1):
            try:
                chaos_inject("store.http")
                out = fn(*args)
            except Exception as e:
                if not _is_transient(e):
                    self._m_errors.labels(op=op).inc()
                    raise
                last = e
                self._note_failure(op, e)
                if self._breaker_blocks():
                    break  # threshold hit mid-op: stop hammering
                if attempt < self._retries:
                    self._m_retries.inc()
                    self._sleep_backoff(attempt)
            else:
                self._note_success()
                return out
        if self._breaker_blocks():
            raise StoreUnavailable(f"store breaker open ({op})") from last
        raise last

    def _sleep_backoff(self, attempt: int) -> None:
        delay = min(self._backoff_cap_s,
                    self._backoff_base_s * (2 ** attempt))
        # Full jitter (AWS-style): desynchronizes retry storms across
        # handler threads hammering the same dead backend.
        time.sleep(delay * self._rng.random())

    def _write(self, op: str, row: Dict):
        """Writes: same retry path, but a transient dead-end lands in
        the journal instead of failing the request — the route response
        still carries a valid request id."""
        if self._breaker_blocks():
            self._journal_write(op, row)
            return None
        last: Optional[BaseException] = None
        for attempt in range(self._retries + 1):
            try:
                out = self._attempt(op, row)
            except Exception as e:
                if not _is_transient(e):
                    self._m_errors.labels(op=op).inc()
                    raise
                last = e
                self._note_failure(op, e)
                if self._breaker_blocks():
                    break
                if attempt < self._retries:
                    self._m_retries.inc()
                    self._sleep_backoff(attempt)
            else:
                self._note_success()
                return out
        self._journal_write(op, row)
        return None

    # ── Store interface ───────────────────────────────────────────────

    def insert_request(self, row: Dict) -> str:
        # Mint the id up front so the journaled row and any dependent
        # result rows agree on it whether or not the backend is up.
        row = dict(row)
        if not row.get("id"):
            row["id"] = str(uuid.uuid4())
        if "request_time" not in row:
            row["request_time"] = _now_iso()  # journal keeps true time
        out = self._write("insert_request", row)
        return str(out) if out is not None else row["id"]

    def insert_result(self, row: Dict) -> None:
        self._write("insert_result", dict(row))

    def list_history(self, limit: int,
                     engine: Optional[str] = None) -> List[Dict]:
        return self._call("list_history", self._inner.list_history,
                          limit, engine)

    def get_request(self, req_id: str) -> Optional[Dict]:
        return self._call("get_request", self._inner.get_request, req_id)

    def delete_request(self, req_id: str) -> bool:
        return self._call("delete_request", self._inner.delete_request,
                          req_id)

    def ping(self) -> bool:
        """Health probe — doubles as the breaker's half-open driver:
        once the cooldown passes, a ping reaches the backend and a
        success closes the breaker + replays the journal. While cooling
        down it answers False instantly (fail fast, no timeout stack)."""
        from routest_tpu.chaos import inject as chaos_inject

        if self._breaker_blocks():
            return False
        try:
            chaos_inject("store.http")
            ok = bool(self._inner.ping())
        except Exception as e:
            if not _is_transient(e):
                raise
            self._note_failure("ping", e)
            return False
        if ok:
            self._note_success()
        else:
            self._note_failure("ping", ConnectionError("ping returned False"))
        return ok

    # ── introspection ─────────────────────────────────────────────────

    @property
    def degraded(self) -> bool:
        with self._lock:
            return self._open or bool(self._journal)

    def resilience(self) -> Dict:
        with self._lock:
            return {
                "breaker": "open" if self._open else "closed",
                "consecutive_failures": self._failures,
                "journal_depth": len(self._journal),
                "journal_limit": self._journal_limit,
            }

    @property
    def kind(self) -> str:
        return self._inner.kind


class TracedStore:
    """Store decorator: every operation becomes a child span of the
    ambient request trace plus one observation in the process registry's
    ``rtpu_store_op_seconds{op,backend}`` histogram — persistence
    latency was previously invisible inside handler time. Pure
    pass-through otherwise (same Protocol, same exceptions)."""

    def __init__(self, inner: Store) -> None:
        self._inner = inner
        self._hist = get_registry().histogram(
            "rtpu_store_op_seconds", "Store operation latency.",
            ("op", "backend"))

    def _call(self, op: str, fn, *args):
        t0 = time.perf_counter()
        with trace_span(f"store.{op}", backend=self._inner.kind):
            try:
                return fn(*args)
            finally:
                self._hist.labels(op=op, backend=self._inner.kind).observe(
                    time.perf_counter() - t0)

    def insert_request(self, row: Dict) -> str:
        return self._call("insert_request", self._inner.insert_request, row)

    def insert_result(self, row: Dict) -> None:
        return self._call("insert_result", self._inner.insert_result, row)

    def list_history(self, limit: int,
                     engine: Optional[str] = None) -> List[Dict]:
        return self._call("list_history", self._inner.list_history,
                          limit, engine)

    def get_request(self, req_id: str) -> Optional[Dict]:
        return self._call("get_request", self._inner.get_request, req_id)

    def delete_request(self, req_id: str) -> bool:
        return self._call("delete_request", self._inner.delete_request,
                          req_id)

    def ping(self) -> bool:
        return self._call("ping", self._inner.ping)

    @property
    def degraded(self) -> bool:
        return bool(getattr(self._inner, "degraded", False))

    @property
    def resilience(self):
        # The inner ResilientStore's snapshot method, or None for a
        # bare store (health reports resilience only when it exists).
        return getattr(self._inner, "resilience", None)

    @property
    def kind(self) -> str:
        return self._inner.kind


def make_store(supabase_url: Optional[str],
               service_key: Optional[str]) -> Store:
    """Backend → resilience layer → tracing, outermost last. Retry /
    breaker / journal knobs are env-tunable (``RTPU_STORE_*``) with
    boot-safe parsing (a malformed value keeps the default)."""
    import os

    def _num(name, default, cast):
        raw = os.environ.get(name)
        if not raw:
            return default
        try:
            return cast(raw)
        except ValueError:
            return default

    inner: Store
    if supabase_url and service_key:
        inner = PostgRESTStore(supabase_url, service_key)
    else:
        inner = InMemoryStore()
    resilient = ResilientStore(
        inner,
        retries=_num("RTPU_STORE_RETRIES", 2, int),
        backoff_base_s=_num("RTPU_STORE_BACKOFF_MS", 50.0, float) / 1000.0,
        breaker_threshold=_num("RTPU_STORE_BREAKER_AFTER", 3, int),
        cooldown_s=_num("RTPU_STORE_COOLDOWN_S", 5.0, float),
        journal_limit=_num("RTPU_STORE_JOURNAL", 512, int),
    )
    return TracedStore(resilient)
