from routest_tpu.serve.app import create_app  # noqa: F401
