"""SSE event bus: per-channel pub/sub feeding ``GET /api/realtime_feed``.

The reference publishes tracker updates through flask-sse → Redis
(``Flaskr/routes.py:86``, ``__init__.py:25-28``). Redis exists to fan out
across processes; a single-process server gets identical semantics from an
in-memory bus. ``RedisBus`` keeps the cross-process path when a
``REDIS_URL`` is configured and the redis client is importable — the same
degraded-not-down behavior the reference's health check reports when Redis
is absent.
"""

from __future__ import annotations

import json
import queue
import threading
from typing import Dict, Iterator, List, Optional


class InMemoryBus:
    """Per-channel fan-out with bounded subscriber queues.

    Events carry per-channel monotonically increasing ids and a bounded
    replay ring, so an SSE client reconnecting with ``Last-Event-ID``
    resumes without losing ticks (the reference's flask-sse + the
    dashboard's backoff reconnect silently drop whatever was published
    while disconnected). Replay and live delivery are serialized under
    one lock: publish assigns the id, appends history, and snapshots
    subscribers atomically — a concurrent subscriber either replays an
    event from history or receives it live, never both, never neither.
    """

    MAX_CHANNELS = 1024  # replay-state cap (channel names are client data)

    def __init__(self, max_queue: int = 256, history: int = 64) -> None:
        self._lock = threading.Lock()
        self._subscribers: Dict[str, List[queue.Queue]] = {}
        self._max_queue = max_queue
        self._history_len = history
        self._next_id: Dict[str, int] = {}
        self._history: Dict[str, List] = {}  # channel -> [(id, data), …]
        self._last_pub: Dict[str, float] = {}

    def _evict_stale_locked(self, now: float,
                            incoming: Optional[str] = None) -> None:
        """Channel names come from clients (route_id), so replay state
        must be bounded: at MAX_CHANNELS, drop the least-recently
        published channels WITHOUT live subscribers (their resume
        window is long gone anyway). ``incoming`` is the channel about
        to be inserted — counting it keeps the bound exact instead of
        settling one past the cap (eviction runs before insertion)."""
        overflow = len(self._history) - self.MAX_CHANNELS
        if incoming is not None and incoming not in self._history:
            overflow += 1
        if overflow <= 0:
            return
        idle = sorted(
            (ch for ch in self._history if not self._subscribers.get(ch)),
            key=lambda ch: self._last_pub.get(ch, 0.0))
        for ch in idle[:overflow]:
            self._history.pop(ch, None)
            self._next_id.pop(ch, None)
            self._last_pub.pop(ch, None)

    def publish(self, channel: str, data: dict) -> int:
        import time as _time

        with self._lock:
            now = _time.monotonic()
            self._evict_stale_locked(now, incoming=channel)
            event_id = self._next_id.get(channel, 0) + 1
            self._next_id[channel] = event_id
            self._last_pub[channel] = now
            ring = self._history.setdefault(channel, [])
            ring.append((event_id, data))
            del ring[: max(0, len(ring) - self._history_len)]
            subs = list(self._subscribers.get(channel, ()))
        delivered = 0
        for q in subs:
            try:
                q.put_nowait((event_id, data))
                delivered += 1
            except queue.Full:
                # Slow consumer: drop oldest, keep the stream live.
                try:
                    q.get_nowait()
                    q.put_nowait((event_id, data))
                    delivered += 1
                except (queue.Empty, queue.Full):
                    pass
        return delivered

    def subscribe(self, channel: str,
                  last_event_id: Optional[int] = None) -> "Subscription":
        q: queue.Queue = queue.Queue(maxsize=self._max_queue)
        with self._lock:
            if last_event_id is not None:
                for event_id, data in self._history.get(channel, ()):
                    if event_id > last_event_id:
                        try:
                            q.put_nowait((event_id, data))
                        except queue.Full:
                            break
            self._subscribers.setdefault(channel, []).append(q)
        return Subscription(self, channel, q)

    def _unsubscribe(self, channel: str, q: queue.Queue) -> None:
        with self._lock:
            subs = self._subscribers.get(channel)
            if subs and q in subs:
                subs.remove(q)
                if not subs:
                    del self._subscribers[channel]

    def ping(self) -> bool:
        return True

    @property
    def kind(self) -> str:
        return "memory"


class Subscription:
    def __init__(self, bus: InMemoryBus, channel: str, q: queue.Queue) -> None:
        self._bus = bus
        self.channel = channel
        self._queue = q
        self.last_id: Optional[int] = None  # id of the last get()'s event

    def get(self, timeout: Optional[float] = None) -> Optional[dict]:
        try:
            event_id, data = self._queue.get(timeout=timeout)
        except queue.Empty:
            return None
        self.last_id = event_id
        return data

    def close(self) -> None:
        self._bus._unsubscribe(self.channel, self._queue)

    def __enter__(self) -> "Subscription":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


class RedisBus:
    """Redis-backed bus with the same interface (used when REDIS_URL is set
    and the redis client is available — optional dependency)."""

    def __init__(self, url: str) -> None:
        import redis  # gated import: not in the base environment

        self._redis = redis.Redis.from_url(url, socket_timeout=2,
                                           socket_connect_timeout=2)

    def publish(self, channel: str, data: dict) -> int:
        return int(self._redis.publish(channel, json.dumps(data)))

    def subscribe(self, channel: str):
        pubsub = self._redis.pubsub()
        pubsub.subscribe(channel)
        return _RedisSubscription(pubsub)

    def ping(self) -> bool:
        try:
            return bool(self._redis.ping())
        except Exception:  # rtpulint: disable=broad-except-unlogged -- health probe: any backend failure maps to unhealthy=False
            return False

    @property
    def kind(self) -> str:
        return "redis"


class _RedisSubscription:
    def __init__(self, pubsub) -> None:
        self._pubsub = pubsub

    def get(self, timeout: Optional[float] = None) -> Optional[dict]:
        msg = self._pubsub.get_message(ignore_subscribe_messages=True,
                                       timeout=timeout or 0)
        if msg and msg.get("type") == "message":
            return json.loads(msg["data"])
        return None

    def close(self) -> None:
        self._pubsub.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def make_bus(redis_url: Optional[str]):
    """Bus from the REDIS_URL scheme: ``redis(s)://`` → RedisBus,
    ``tcp://`` → the hermetic cross-process broker (``serve/netbus.py``),
    unset/unreachable → in-memory (single-process). The serving path's
    NetBus gets subscriber auto-reconnect (``RTPU_NETBUS_RECONNECT_S``,
    default 30 s of broker downtime before an SSE stream gives up) and
    the bounded publish replay buffer — SSE survives a broker restart."""
    import os

    from routest_tpu.utils.logging import get_logger

    if redis_url:
        try:
            if redis_url.startswith("tcp://"):
                from routest_tpu.serve.netbus import NetBus

                try:
                    reconnect_s = float(
                        os.environ.get("RTPU_NETBUS_RECONNECT_S") or 30.0)
                except ValueError:
                    reconnect_s = 30.0
                bus = NetBus(redis_url, reconnect_s=reconnect_s)
            else:
                bus = RedisBus(redis_url)
            if bus.ping():
                return bus
            get_logger("routest_tpu.serve.bus").warning(
                "bus_unreachable", url=redis_url,
                fallback="in-memory (single-process SSE only)")
        except Exception as e:
            # Visible degrade: the configured cross-process bus is gone;
            # in-memory keeps SSE working within this process only.
            get_logger("routest_tpu.serve.bus").warning(
                "bus_unavailable", url=redis_url,
                error=f"{type(e).__name__}: {e}",
                fallback="in-memory (single-process SSE only)")
    return InMemoryBus()


def sse_stream(subscription, keepalive_s: float = 15.0,
               max_events: Optional[int] = None) -> Iterator[bytes]:
    """Subscription → text/event-stream byte chunks (SSE wire format).

    A subscription that reports ``closed`` (cross-process backend died or
    dropped us) ENDS the stream instead of keepaliving forever — the
    browser's EventSource then reconnects with backoff (the dashboard's
    retry loop), landing on a live subscription.
    """
    sent = 0
    with subscription:
        while max_events is None or sent < max_events:
            data = subscription.get(timeout=keepalive_s)
            if data is None:
                if getattr(subscription, "closed", False):
                    return
                yield b": keepalive\n\n"
                continue
            # ``id:`` lines make EventSource reconnects resumable via
            # Last-Event-ID; backends without event ids (Redis pub/sub
            # has no history) just omit them.
            event_id = getattr(subscription, "last_id", None)
            prefix = f"id: {event_id}\n".encode() if event_id is not None \
                else b""
            yield prefix + f"data: {json.dumps(data)}\n\n".encode()
            sent += 1
