"""Auth: the Laravel Breeze capability, token-based and hermetic.

The reference ships Laravel's stock Breeze API scaffold
(``routes/auth.php:11-36`` + ``app/Http/Controllers/Auth/*`` — register,
login, logout, forgot/reset password, email verification) guarding
``GET /api/user`` via Sanctum (``routes/api.php:11-14``). At runtime the
reference bypasses it entirely (SURVEY.md §1: Flask talks to Supabase
directly), but the capability is part of the component inventory, so it
exists here as a first-class serving module:

- personal-access-token auth (Sanctum's API mode): ``Authorization:
  Bearer <token>`` issued at register/login, revoked at logout;
- PBKDF2-HMAC-SHA256 password hashing (Laravel uses bcrypt; same
  contract, stdlib-only);
- password reset and email verification flows are hermetic BY DEFAULT:
  where Breeze emails a link, these endpoints RETURN the token/link
  payload directly — no SMTP dependency, same state machine. The
  verify-email URL carries Laravel's two path ingredients (user id +
  sha1(email)) AND is signed like Laravel's ``signed`` middleware: an
  ``expires`` timestamp plus an HMAC-SHA256 ``signature`` over a server
  secret (``ROUTEST_APP_KEY``, else a per-process random key), so a
  link cannot be forged from a known email or replayed after expiry.
  Exception: under ``ROUTEST_AUTH=require`` the reset
  token is written to the server log instead of the response, so the
  bearer gate cannot be bypassed by an anonymous forgot-password call.
  With a mail transport configured (``serve/mail.py``,
  ``ROUTEST_MAIL_FILE``), both flows instead deliver the secret by
  mail only — the reference's mail-driver behavior.

Status-code parity with Breeze: validation failures are 422 (including
bad credentials — Laravel's ValidationException), missing/invalid
bearer tokens are 401, logout and verification success are 204/200.

Auth stays OFF the data-plane endpoints by default (the reference's
runtime behavior). ``ROUTEST_AUTH=require`` turns on bearer enforcement
for the destructive route (``DELETE /api/history/<id>``), the gate the
reference never built.
"""

from __future__ import annotations

import datetime as dt
import hashlib
import hmac
import os
import secrets
import threading
import time
import uuid
from typing import Dict, Optional, Tuple

from routest_tpu.utils.logging import get_logger

_PBKDF2_ITERS = 60_000
_RESET_TTL_S = 3600.0
_MAX_TOKENS_PER_USER = 16  # oldest sessions evicted beyond this


def _hash_password(password: str, salt: bytes) -> bytes:
    return hashlib.pbkdf2_hmac("sha256", password.encode(), salt, _PBKDF2_ITERS)


def verify_email_hash(email: str) -> str:
    """Laravel's verification-URL hash ingredient: sha1 of the email."""
    return hashlib.sha1(email.encode()).hexdigest()


class AuthService:
    """In-memory user/token store with the Breeze state machine.

    Thread-safe (the dev server is threaded); hermetic by design, like
    ``InMemoryStore`` — a PostgREST-backed variant would slot in behind
    the same interface the way ``store.py`` does it.
    """

    # Signed verify-email links stay valid this long (Laravel's default
    # is 60 minutes — ``Auth/VerifyEmail::verificationUrl``).
    VERIFY_TTL_S = 3600.0

    def __init__(self, required: bool = False,
                 secret: Optional[str] = None) -> None:
        self.required = required
        # Signing key for verification URLs. A per-process random key is
        # the hermetic default (links survive as long as the process, like
        # every other in-memory credential here); set ROUTEST_APP_KEY for
        # links that survive restarts / multi-replica fleets.
        self._secret = (secret or os.environ.get("ROUTEST_APP_KEY")
                        or secrets.token_hex(32)).encode()
        self._lock = threading.Lock()
        self._users: Dict[str, dict] = {}          # email -> user row
        self._tokens: Dict[str, str] = {}          # bearer token -> email
        self._resets: Dict[str, Tuple[str, float]] = {}  # token -> (email, expiry)
        self._attempts: Dict[str, Tuple[int, float]] = {}  # throttle key -> (count, window expiry)

    # ── registration / login ───────────────────────────────────────────

    def register(self, name: str, email: str, password: str) -> Tuple[dict, str]:
        """Create a user and issue a token. Raises ValueError on invalid
        input or duplicate email (both 422 in Breeze)."""
        if not name or not email or "@" not in email:
            raise ValueError("name and a valid email are required")
        if not password or len(password) < 8:
            raise ValueError("password must be at least 8 characters")
        # Hash outside the lock: PBKDF2 is tens of ms and must not
        # serialize every concurrent auth operation behind it.
        salt = secrets.token_bytes(16)
        password_hash = _hash_password(password, salt)
        with self._lock:
            if email in self._users:
                raise ValueError("email already registered")
            user = {
                "id": str(uuid.uuid4()),
                "name": name,
                "email": email,
                "salt": salt,
                "password_hash": password_hash,
                "email_verified_at": None,
                "created_at": dt.datetime.now(dt.timezone.utc).isoformat(),
            }
            self._users[email] = user
            token = self._issue_token_locked(email)
        return self._public(user), token

    # Breeze login throttling (reference
    # ``app/Http/Requests/Auth/LoginRequest.php:45-70``): 5 attempts per
    # email+source key, 60 s decay window, lockout surfaces the seconds
    # remaining; a successful login clears the key.
    THROTTLE_ATTEMPTS = 5
    THROTTLE_DECAY_S = 60.0

    def _throttle_check(self, key: str, now: float) -> None:
        count, expires = self._attempts.get(key, (0, 0.0))
        if expires <= now:
            return
        if count >= self.THROTTLE_ATTEMPTS:
            seconds = max(1, int(expires - now))
            raise ValueError(
                f"too many login attempts. please try again in "
                f"{seconds} seconds")

    def _throttle_hit(self, key: str, now: float) -> None:
        if len(self._attempts) > 10_000:
            # Unauthenticated attackers control the key space (junk
            # emails): purge lapsed windows, and if a live flood keeps
            # the table over the cap anyway, HARD-evict the soonest-to-
            # expire half. The cost is forgetting some attackers'
            # counters early — bounded memory wins; the O(n log n)
            # amortizes to O(log n) per hit (one sort per ~5k inserts).
            self._attempts = {k: v for k, v in self._attempts.items()
                              if v[1] > now}
            if len(self._attempts) > 10_000:
                keep = sorted(self._attempts.items(),
                              key=lambda kv: kv[1][1], reverse=True)[:5_000]
                self._attempts = dict(keep)
        count, expires = self._attempts.get(key, (0, 0.0))
        if expires <= now:  # window lapsed: start a fresh one
            count, expires = 0, now + self.THROTTLE_DECAY_S
        self._attempts[key] = (count + 1, expires)

    def login(self, email: str, password: str,
              source: str = "", now: Optional[float] = None) -> Tuple[dict, str]:
        """Raises ValueError on bad credentials (Breeze: 422 auth.failed)
        or on lockout (Breeze throttle, ``LoginRequest.php:62-70``).
        ``source`` is the caller's network identity (Breeze keys the
        limiter by email|ip so one address can't lock out a victim's
        account globally)."""
        now = time.time() if now is None else now
        key = f"{(email or '').lower()}|{source}"
        with self._lock:
            self._throttle_check(key, now)
            user = self._users.get(email or "")
            # Snapshot the credentials; hash outside the lock (see register).
            salt = user["salt"] if user else b"\0" * 16
            want = user["password_hash"] if user else b""
        got = _hash_password(password or "", salt)
        if user is None or not hmac.compare_digest(want, got):
            with self._lock:
                self._throttle_hit(key, now)
            raise ValueError("these credentials do not match our records")
        with self._lock:
            # Password may have rotated between hash and issue; re-check.
            current = self._users.get(email)
            if current is None or current["password_hash"] != want:
                self._throttle_hit(key, now)
                raise ValueError("these credentials do not match our records")
            token = self._issue_token_locked(email)
            self._attempts.pop(key, None)  # success clears the limiter
        return self._public(user), token

    def logout(self, token: str) -> bool:
        with self._lock:
            return self._tokens.pop(token, None) is not None

    def user_for_token(self, token: Optional[str]) -> Optional[dict]:
        with self._lock:
            email = self._tokens.get(token or "")
            user = self._users.get(email) if email else None
            return self._public(user) if user else None

    def user_from_request(self, request) -> Optional[dict]:
        """Resolve the request's identity: bearer token first (Sanctum
        API mode), else the session cookie (Sanctum stateful SPA mode,
        ``laravel/bootstrap/app.php:14-21``). Cookie-sourced identity
        on an UNSAFE method additionally requires the double-submit
        CSRF proof — the ``X-XSRF-TOKEN`` header must equal the
        ``XSRF-TOKEN`` cookie the SPA read (Sanctum's
        ``EnsureFrontendRequestsAreStateful`` behavior)."""
        user = self.user_for_token(bearer_token(request))
        if user is not None:
            return user
        token = request.cookies.get(SESSION_COOKIE)
        if not token:
            return None
        user = self.user_for_token(token)
        if user is None:
            return None
        if request.method not in ("GET", "HEAD", "OPTIONS") \
                and not _csrf_ok(request):
            return None
        return user

    # ── password reset ─────────────────────────────────────────────────

    def forgot_password(self, email: str, *, now: Optional[float] = None) -> Optional[str]:
        """Issue a reset token; None for unknown emails (Breeze responds
        identically either way, to avoid account enumeration)."""
        import time

        t = now or time.time()
        with self._lock:
            # Prune expired entries and invalidate the user's previous
            # token (Laravel keeps at most one live reset per user) —
            # keeps _resets bounded on a long-running server.
            self._resets = {k: v for k, v in self._resets.items()
                            if v[1] > t and v[0] != email}
            if email not in self._users:
                return None
            token = secrets.token_urlsafe(32)
            self._resets[token] = (email, t + _RESET_TTL_S)
            return token

    def reset_password(self, token: str, email: str, password: str,
                       *, now: Optional[float] = None) -> None:
        """Raises ValueError on invalid/expired/mismatched token."""
        import time

        if not password or len(password) < 8:
            raise ValueError("password must be at least 8 characters")
        salt = secrets.token_bytes(16)
        password_hash = _hash_password(password, salt)  # outside the lock
        with self._lock:
            entry = self._resets.get(token or "")
            if entry is None or entry[0] != email or (now or time.time()) > entry[1]:
                raise ValueError("this password reset token is invalid")
            del self._resets[token]
            user = self._users[email]
            user["salt"] = salt
            user["password_hash"] = password_hash
            # Laravel revokes existing sessions on reset.
            for t in [t for t, e in self._tokens.items() if e == email]:
                del self._tokens[t]

    # ── email verification ─────────────────────────────────────────────

    def _verify_signature(self, user_id: str, email_hash: str,
                          expires: int) -> str:
        msg = f"{user_id}|{email_hash}|{expires}".encode()
        return hmac.new(self._secret, msg, hashlib.sha256).hexdigest()

    def signed_verify_url(self, user_id: str, email: str,
                          *, now: Optional[float] = None) -> str:
        """Laravel-style signed verification URL: the two path
        ingredients (id + sha1(email)) plus ``expires`` and an
        HMAC-SHA256 ``signature`` over the server secret covering all
        three — tampering with any component invalidates the link."""
        expires = int((time.time() if now is None else now)
                      + self.VERIFY_TTL_S)
        email_hash = verify_email_hash(email)
        sig = self._verify_signature(user_id, email_hash, expires)
        return (f"/api/auth/verify-email/{user_id}/{email_hash}"
                f"?expires={expires}&signature={sig}")

    def verify_email(self, token: str, user_id: str, email_hash: str,
                     expires: Optional[str] = None,
                     signature: Optional[str] = None,
                     *, now: Optional[float] = None) -> bool:
        """Mark the bearer's email verified. The link must carry a
        valid, unexpired HMAC signature (Laravel's signed-URL check) on
        top of the id+hash match — ``sha1(email)`` alone is forgeable
        by anyone who knows the address."""
        try:
            exp = int(expires or "")
        except ValueError:
            raise ValueError("invalid verification link")
        # Signature check BEFORE expiry: a tampered link reads as
        # invalid, not expired, regardless of its claimed timestamp.
        want = self._verify_signature(user_id, email_hash, exp)
        if not hmac.compare_digest(want, signature or ""):
            raise ValueError("invalid verification link")
        if (time.time() if now is None else now) > exp:
            raise ValueError("verification link expired")
        with self._lock:
            email = self._tokens.get(token or "")
            user = self._users.get(email) if email else None
            if user is None:
                raise PermissionError("unauthenticated")
            if user["id"] != user_id or \
                    not hmac.compare_digest(verify_email_hash(email), email_hash):
                raise ValueError("invalid verification link")
            user["email_verified_at"] = dt.datetime.now(dt.timezone.utc).isoformat()
            return True

    # ── helpers ────────────────────────────────────────────────────────

    def _issue_token_locked(self, email: str) -> str:
        # Cap live sessions per user (dicts iterate in insertion order,
        # so the first matches are the oldest): bounds _tokens on a
        # long-running server instead of growing one entry per login.
        mine = [t for t, e in self._tokens.items() if e == email]
        for stale in mine[: max(0, len(mine) + 1 - _MAX_TOKENS_PER_USER)]:
            del self._tokens[stale]
        token = secrets.token_urlsafe(40)
        self._tokens[token] = email
        return token

    @staticmethod
    def _public(user: dict) -> dict:
        return {k: user[k] for k in
                ("id", "name", "email", "email_verified_at", "created_at")}


# Sanctum SPA-mode cookie names: the XSRF token is readable (the SPA
# echoes it in a header — double submit); the session id is HttpOnly.
XSRF_COOKIE = "XSRF-TOKEN"
SESSION_COOKIE = "routest_session"


def _csrf_ok(request) -> bool:
    """Double-submit proof: X-XSRF-TOKEN header equals the XSRF-TOKEN
    cookie. Compared as bytes — ``hmac.compare_digest`` raises on
    non-ASCII str, and both values are attacker-controlled, so a weird
    byte must mean 401, never a 500."""
    cookie = request.cookies.get(XSRF_COOKIE, "")
    header = request.headers.get("X-XSRF-TOKEN", "")
    return bool(cookie) and hmac.compare_digest(
        cookie.encode("utf-8", "surrogateescape"),
        header.encode("utf-8", "surrogateescape"))


def secure_cookies(request) -> bool:
    """Whether session/XSRF cookies should carry ``Secure`` (ADVICE r5:
    a session cookie without it leaks over any plain-HTTP subresource).
    True when the request arrived over HTTPS — directly or behind a
    TLS-terminating proxy (``X-Forwarded-Proto``) — or when
    ``ROUTEST_SECURE_COOKIES`` forces it for deploys whose proxy strips
    forwarding headers."""
    if os.environ.get("ROUTEST_SECURE_COOKIES"):
        return True
    return (request.scheme == "https"
            or request.headers.get("X-Forwarded-Proto", "") == "https")


def bearer_token(request) -> Optional[str]:
    header = request.headers.get("Authorization", "")
    return header[7:] if header.startswith("Bearer ") else None


UNAUTHENTICATED = ({"message": "unauthenticated"}, 401)


def validation_error(e: Exception):
    """Breeze-shaped 422 with the message keyed under the field it names."""
    msg = str(e)
    field = "password" if "password" in msg else "email"
    return {"message": msg, "errors": {field: [msg]}}, 422


def mount_auth(app, auth: AuthService, mailer=None) -> None:
    """Register the Breeze-parity endpoints on the serving app.

    ``mailer`` (serve/mail.py) is the reference's mail-driver seam:
    when configured, reset tokens and verification links travel by
    mail only — the responses match Breeze's (status strings, no
    secrets), like PasswordResetLinkController / EmailVerification-
    NotificationController behind a real MAIL_MAILER. When None
    (hermetic default), the flows keep their in-band token behavior
    (module docstring)."""
    from routest_tpu.serve.wsgi import get_json, json_response

    @app.route("/sanctum/csrf-cookie", methods=("GET",))
    def csrf_cookie(request):
        # Sanctum's stateful-SPA handshake: the SPA fetches this first;
        # the readable XSRF-TOKEN cookie is echoed back as the
        # X-XSRF-TOKEN header on subsequent unsafe requests.
        from werkzeug.wrappers import Response

        resp = Response("", 204)
        resp.set_cookie(XSRF_COOKIE, secrets.token_urlsafe(24),
                        samesite="Lax", path="/",
                        secure=secure_cookies(request))
        return resp

    def _session_login_wanted(request) -> bool:
        """SPA-mode signature on a credential request: the CSRF pair
        (cookie + matching header) is present — bearer-only clients
        never send it, so they keep getting plain token responses."""
        return _csrf_ok(request)

    def _credential_response(request, user, token, status):
        payload = {"user": user, "token": token}
        if not _session_login_wanted(request):
            return payload, status
        # SPA mode: the session ALSO rides an HttpOnly cookie, so the
        # frontend needs no token storage (Sanctum stateful behavior);
        # the body keeps the token for wire-shape compatibility.
        resp = json_response(payload, status)
        resp.set_cookie(SESSION_COOKIE, token, httponly=True,
                        samesite="Lax", path="/",
                        secure=secure_cookies(request))
        return resp

    @app.route("/api/auth/register", methods=("POST",))
    def register(request):
        body = get_json(request) or {}
        try:
            user, token = auth.register(
                str(body.get("name") or ""), str(body.get("email") or ""),
                str(body.get("password") or ""))
        except ValueError as e:
            return validation_error(e)
        return _credential_response(request, user, token, 201)

    @app.route("/api/auth/login", methods=("POST",))
    def login(request):
        body = get_json(request) or {}
        try:
            user, token = auth.login(str(body.get("email") or ""),
                                     str(body.get("password") or ""),
                                     source=request.remote_addr or "")
        except ValueError as e:
            return validation_error(e)
        return _credential_response(request, user, token, 200)

    @app.route("/api/auth/logout", methods=("POST",))
    def logout(request):
        token = bearer_token(request)
        if token is None:
            # cookie-sourced logout is an unsafe method like any other:
            # it needs the double-submit proof (the docstring invariant)
            if not _csrf_ok(request):
                return UNAUTHENTICATED
            token = request.cookies.get(SESSION_COOKIE) or ""
        if not auth.logout(token):
            return UNAUTHENTICATED
        from werkzeug.wrappers import Response

        resp = Response("", 204)
        resp.delete_cookie(SESSION_COOKIE, path="/")
        return resp

    @app.route("/api/user", methods=("GET",))
    def current_user(request):
        user = auth.user_from_request(request)
        if user is None:
            return UNAUTHENTICATED
        return user, 200

    @app.route("/api/auth/forgot-password", methods=("POST",))
    def forgot_password(request):
        body = get_json(request) or {}
        token = auth.forgot_password(str(body.get("email") or ""))
        # Hermetic stand-in for the reset email: identical anti-enumeration
        # response either way. The token itself is returned ONLY when auth
        # is not enforced (dev/test convenience); under ROUTEST_AUTH=require
        # handing it to an anonymous caller would let anyone take over any
        # account whose email they know — there it goes to the server log
        # (the "mailbox"), never the HTTP response.
        payload = {"status": "We have emailed your password reset link."}
        if token is not None:
            if mailer is not None:
                # Reference behavior: the token travels by mail only.
                email = str(body.get("email") or "")
                mailer.send(
                    email, "Reset Password Notification",
                    "Use this token with POST /api/auth/reset-password: "
                    + token)
            elif auth.required:
                # JsonLogger json-escapes fields, so an attacker-chosen
                # email cannot inject forged lines into the token stream.
                get_logger("routest.auth").info(
                    "password_reset_token_issued",
                    email=str(body.get("email") or ""), token=token)
            else:
                payload["reset_token"] = token
        return payload, 200

    @app.route("/api/auth/reset-password", methods=("POST",))
    def reset_password(request):
        body = get_json(request) or {}
        try:
            auth.reset_password(str(body.get("token") or ""),
                                str(body.get("email") or ""),
                                str(body.get("password") or ""))
        except ValueError as e:
            return validation_error(e)
        return {"status": "Your password has been reset."}, 200

    @app.route("/api/auth/email/verification-notification", methods=("POST",))
    def send_verification(request):
        user = auth.user_from_request(request)
        if user is None:
            return UNAUTHENTICATED
        verify_url = auth.signed_verify_url(user["id"], user["email"])
        if mailer is not None:
            # Reference behavior: link travels by mail; the response is
            # just the Breeze status string.
            mailer.send(user["email"], "Verify Email Address",
                        "Open this link while authenticated: "
                        + verify_url)
            return {"status": "verification-link-sent"}, 200
        # Hermetic stand-in for the verification email.
        return {"status": "verification-link-sent",
                "verify_url": verify_url}, 200

    @app.route("/api/auth/verify-email/<user_id>/<email_hash>", methods=("GET",))
    def verify_email(request, user_id, email_hash):
        # resolve the token like user_from_request: bearer first, then
        # the SPA session cookie (a GET is safe — no CSRF proof needed),
        # so cookie-mode users can open the link they were mailed
        token = bearer_token(request) \
            or request.cookies.get(SESSION_COOKIE) or ""
        try:
            auth.verify_email(token, user_id, email_hash,
                              expires=request.args.get("expires"),
                              signature=request.args.get("signature"))
        except PermissionError:
            return UNAUTHENTICATED
        except ValueError as e:
            return {"message": str(e)}, 403
        return {"verified": True}, 200
