"""Driver simulator: replays a computed route as live tracker updates.

Mirrors the reference's behavior (``Flaskr/utils.py:229-251``): a daemon
thread walks the route geometry, emitting the remaining-route payload on
each tick with a random 2-5 s interval. One design fix: the reference
POSTs to its own ``/api/update_tracker`` over HTTP just to get a request
context for the publish; here the tick publishes straight to the bus
(``update_tracker`` remains available for real GPS sources).
"""

from __future__ import annotations

import datetime as dt
import random
import threading
from typing import Callable, Optional


def format_sse_data(data: dict) -> dict:
    """Tracker payload → SSE event shape (``Flaskr/utils.py:253-267``)."""
    pickup_time = dt.datetime.fromisoformat(data["pickup_time"])
    completion_time = pickup_time + dt.timedelta(seconds=float(data["duration"]))
    return {
        "destinations": data["destinations"],
        "remaining_routes": data["route"],
        "overall_duration": data["duration"],
        "overall_travel_distance": data["distance"],
        "overall_estimated_completion_time": completion_time.isoformat(),
        "total_trips": data.get("trips", 1),
        "assigned_driver": data["driver_name"],
        "transport_mode": data["vehicle_type"],
        "start_time": data["pickup_time"],
    }


def simulate_route(
    data: dict,
    publish: Callable[[str, dict], object],
    tick_range_s: tuple = (2.0, 5.0),
    rng: Optional[random.Random] = None,
) -> int:
    """Run one simulation to completion (blocking). Returns ticks sent.

    ``publish(channel, event)`` receives the formatted SSE event; the
    channel is the driver name, as in the reference (``route_id`` =
    ``driver_details.driver_name``, ``Flaskr/utils.py:237``).
    """
    rng = rng or random.Random()
    pickup_time = dt.datetime.now()
    route_points = list(data["route_details"]["geometry"]["coordinates"])
    props = data["route_details"]["properties"]
    destinations = props["destinations"]
    driver = data["driver_details"]

    ticks = 0
    while route_points:
        payload = {
            "route_id": driver["driver_name"],
            "route": list(route_points),
            "destinations": destinations,
            "driver_name": driver["driver_name"],
            "vehicle_type": driver["vehicle_type"],
            "duration": props["summary"]["duration"],
            "distance": props["summary"]["distance"],
            "trips": props["summary"].get("trips", 1),
            "pickup_time": pickup_time.isoformat(),
        }
        route_points.pop(0)
        publish(str(payload["route_id"]), format_sse_data(payload))
        ticks += 1
        if route_points:
            threading.Event().wait(rng.uniform(*tick_range_s))
    return ticks


def start_simulation(data: dict, publish,
                     tick_range_s: tuple = (2.0, 5.0),
                     rng: Optional[random.Random] = None,
                     seed: Optional[int] = None) -> threading.Thread:
    """Run :func:`simulate_route` on a daemon thread.

    ``rng`` (or ``seed``, which builds one) threads a seeded generator
    through to the tick-interval jitter, so probe scenarios and tests
    replay bit-identically — the same determinism convention as the
    chaos engine and loadgen. Unseeded callers keep the historical
    fresh-``random.Random()`` behavior."""
    if rng is None and seed is not None:
        rng = random.Random(int(seed))

    def run():
        try:
            simulate_route(data, publish, tick_range_s, rng=rng)
        except Exception as e:  # daemon thread: never die silently
            from routest_tpu.utils.logging import get_logger

            get_logger("routest_tpu.sim").error(
                "simulate_route_failed", error=f"{type(e).__name__}: {e}")

    thread = threading.Thread(target=run, daemon=True)
    thread.start()
    return thread
