"""CPU baseline: the golden-RMSE reference the TPU model must match.

BASELINE.json demands "RMSE ≤ CPU-baseline RMSE", but the reference never
committed the baseline (empty ``notebooks/``, LFS-pointer model —
SURVEY.md §6). So the baseline is built here: a sklearn
HistGradientBoostingRegressor (the same model family as the reference's
XGBoost artifact) trained on the same 12-feature matrix. Its eval RMSE is
frozen to ``artifacts/baseline.json`` and the test suite asserts the JAX
model stays within tolerance of it.
"""

from __future__ import annotations

import json
import os
import time
from typing import Dict, Optional

import numpy as np

from routest_tpu.data.features import batch_from_mapping


def train_cpu_baseline(train_data: Dict[str, np.ndarray],
                       eval_data: Dict[str, np.ndarray]) -> Dict:
    from sklearn.ensemble import HistGradientBoostingRegressor

    x_train = batch_from_mapping(train_data)
    y_train = np.asarray(train_data["eta_minutes"], np.float64)
    x_eval = batch_from_mapping(eval_data)
    y_eval = np.asarray(eval_data["eta_minutes"], np.float64)

    model = HistGradientBoostingRegressor(
        max_iter=300, learning_rate=0.08, max_depth=None, random_state=0
    )
    t0 = time.time()
    model.fit(x_train, y_train)
    fit_s = time.time() - t0

    pred = model.predict(x_eval)
    rmse = float(np.sqrt(np.mean((pred - y_eval) ** 2)))

    # Single-row latency — the reference's serving mode (one HTTP request =
    # one model row, ``Flaskr/ml.py:51-53``): measures config 1 of
    # BASELINE.json.
    one = x_eval[:1]
    for _ in range(3):
        model.predict(one)
    t0 = time.time()
    n_single = 200
    for i in range(n_single):
        model.predict(x_eval[i % len(x_eval): i % len(x_eval) + 1])
    single_row_s = (time.time() - t0) / n_single

    # Bulk CPU throughput for context.
    t0 = time.time()
    model.predict(x_eval)
    bulk_s = time.time() - t0

    return {
        "model": "sklearn.HistGradientBoostingRegressor(max_iter=300)",
        "rmse_minutes": rmse,
        "fit_seconds": fit_s,
        "single_row_latency_s": single_row_s,
        "single_row_preds_per_sec": 1.0 / single_row_s,
        "bulk_preds_per_sec": len(x_eval) / bulk_s,
        "n_train": len(y_train),
        "n_eval": len(y_eval),
        "_model_obj": model,
    }


def baseline_path() -> str:
    return os.path.join(
        os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
        "artifacts",
        "baseline.json",
    )


def save_baseline(metrics: Dict, path: Optional[str] = None) -> str:
    path = path or baseline_path()
    os.makedirs(os.path.dirname(path), exist_ok=True)
    public = {k: v for k, v in metrics.items() if not k.startswith("_")}
    with open(path, "w") as f:
        json.dump(public, f, indent=2)
    return path


def load_baseline(path: Optional[str] = None) -> Optional[Dict]:
    path = path or baseline_path()
    if not os.path.exists(path):
        return None
    with open(path) as f:
        return json.load(f)
