from routest_tpu.train.loop import TrainState, fit, make_train_step, rmse  # noqa: F401
