"""Training loop: the ``notebooks/`` capability the reference never built.

The reference repo gestures at "ML model training and evaluation" as
"Coming Soon" (``README.md:13-18``) and ships empty ``notebooks/`` and
``data/`` directories. This module is that missing training loop, done
TPU-first: a jitted/pjit-able train step (batch sharded over the mesh
``data`` axis, params replicated — pure data parallelism; XLA inserts the
gradient psum), optax AdamW, Huber loss, RMSE eval.
"""

from __future__ import annotations

import dataclasses
import functools
import time
from typing import Callable, Dict, Iterator, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

from routest_tpu.core.config import TrainConfig
from routest_tpu.core.mesh import MeshRuntime, pad_rows, pad_to_multiple
from routest_tpu.models.eta_mlp import EtaMLP, Params, fit_normalizer
from routest_tpu.data.features import batch_from_mapping
from routest_tpu.obs import get_registry
from routest_tpu.utils.logging import get_logger

_log = get_logger("routest_tpu.train")


class TrainState(NamedTuple):
    params: Params
    opt_state: optax.OptState
    step: jax.Array


class Batch(NamedTuple):
    features: jax.Array  # (B, 12)
    targets: jax.Array   # (B,) eta minutes
    weights: jax.Array   # (B,) 0/1 mask — padded rows get 0


def _decay_mask(params: Params):
    """Weight-decay only matrix weights: never the frozen normalizer stats
    (they receive no gradient, but decoupled decay would still erode them)
    and not biases."""
    return {
        "layers": [{"w": True, "b": False} for _ in params["layers"]],
        "norm": {"mean": False, "std": False},
    }


def make_optimizer(cfg: TrainConfig, total_steps: int = 1000) -> optax.GradientTransformation:
    warmup = max(1, min(100, total_steps // 10))
    schedule = optax.warmup_cosine_decay_schedule(
        init_value=0.0,
        peak_value=cfg.learning_rate,
        warmup_steps=warmup,
        decay_steps=max(total_steps, warmup + 1),
        end_value=cfg.learning_rate * 0.05,
    )
    return optax.chain(
        optax.clip_by_global_norm(1.0),
        optax.adamw(schedule, weight_decay=cfg.weight_decay, mask=_decay_mask),
    )


def loss_fn(model: EtaMLP, params: Params, batch: Batch) -> jax.Array:
    denom = jnp.maximum(batch.weights.sum(), 1.0)
    if getattr(model, "quantiles", ()):
        # Pinball (quantile) loss, averaged over the head axis: the unique
        # proper scoring rule whose minimizer is the target quantile, so
        # calibration is a property of convergence, not a regularizer.
        pred = model.apply_quantiles(params, batch.features)   # (B, Q)
        q = jnp.asarray(model.quantiles, pred.dtype)
        err = batch.targets[:, None] - pred
        per_row = jnp.maximum(q * err, (q - 1.0) * err).mean(axis=-1)
    else:
        pred = model.apply(params, batch.features)
        # Huber on minutes: robust to the log-normal noise tail.
        per_row = optax.huber_loss(pred, batch.targets, delta=10.0)
    return (per_row * batch.weights).sum() / denom


def make_train_step(model: EtaMLP, optimizer: optax.GradientTransformation,
                    runtime: Optional[MeshRuntime] = None) -> Callable:
    """Build the jitted train step.

    With a ``MeshRuntime``, in/out shardings pin the batch to the data axis
    and the state replicated; XLA turns the grad reduction into a psum over
    ICI. Without one, plain jit (single device).
    """

    def step(state: TrainState, batch: Batch) -> Tuple[TrainState, jax.Array]:
        loss, grads = jax.value_and_grad(lambda p: loss_fn(model, p, batch))(state.params)
        updates, opt_state = optimizer.update(grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        return TrainState(params, opt_state, state.step + 1), loss

    if runtime is None:
        return jax.jit(step, donate_argnums=(0,))

    replicated = NamedSharding(runtime.mesh, P())
    batch_sh = NamedSharding(runtime.mesh, P(runtime.data_axis))
    return jax.jit(
        step,
        in_shardings=(replicated, Batch(batch_sh, batch_sh, batch_sh)),
        out_shardings=(replicated, replicated),
        donate_argnums=(0,),
    )


def make_eval_fn(model: EtaMLP, runtime: Optional[MeshRuntime] = None) -> Callable:
    """Masked sum-of-squared-error + count, for exact RMSE over padded shards."""

    def sse(params: Params, batch: Batch) -> Tuple[jax.Array, jax.Array]:
        pred = model.apply(params, batch.features)
        err = (pred - batch.targets) ** 2 * batch.weights
        return err.sum(), batch.weights.sum()

    if runtime is None:
        return jax.jit(sse)
    replicated = NamedSharding(runtime.mesh, P())
    batch_sh = NamedSharding(runtime.mesh, P(runtime.data_axis))
    return jax.jit(
        sse,
        in_shardings=(replicated, Batch(batch_sh, batch_sh, batch_sh)),
        out_shardings=(replicated, replicated),
    )


@functools.lru_cache(maxsize=16)
def _cached_eval_fn(model: EtaMLP, runtime: Optional[MeshRuntime]):
    """Eval functions are jitted once per (model, runtime); repeated rmse()
    calls (per-epoch eval) must not recompile."""
    return make_eval_fn(model, runtime)


def _minibatches(features: np.ndarray, targets: np.ndarray, batch_size: int,
                 rng: np.random.Generator, n_shards: int) -> Iterator[Batch]:
    n = len(targets)
    perm = rng.permutation(n)
    for start in range(0, n, batch_size):
        idx = perm[start:start + batch_size]
        rows = pad_to_multiple(len(idx), max(n_shards, 1))
        f = pad_rows(features[idx], rows)
        t = pad_rows(targets[idx], rows)
        w = pad_rows(np.ones(len(idx), np.float32), rows)
        yield Batch(jnp.asarray(f), jnp.asarray(t), jnp.asarray(w))


@dataclasses.dataclass
class FitResult:
    state: TrainState
    train_losses: list
    eval_rmse: float


def rmse(model: EtaMLP, params: Params, data: Dict[str, np.ndarray],
         runtime: Optional[MeshRuntime] = None, batch_size: int = 65536) -> float:
    """Exact RMSE of the model on a dataset dict (synthetic.py schema)."""
    features = batch_from_mapping(data)
    targets = np.asarray(data["eta_minutes"], np.float32)
    eval_fn = _cached_eval_fn(model, runtime)
    n_shards = runtime.n_data if runtime else 1
    total_sse, total_n = 0.0, 0.0
    n = len(targets)
    for start in range(0, n, batch_size):
        sl = slice(start, min(start + batch_size, n))
        rows = pad_to_multiple(sl.stop - sl.start, max(n_shards, 1))
        batch = Batch(
            jnp.asarray(pad_rows(features[sl], rows)),
            jnp.asarray(pad_rows(targets[sl], rows)),
            jnp.asarray(pad_rows(np.ones(sl.stop - sl.start, np.float32), rows)),
        )
        if runtime is not None:
            batch = Batch(*runtime.shard_batch(tuple(batch)))
        s, c = eval_fn(params, batch)
        total_sse += float(s)
        total_n += float(c)
    return float(np.sqrt(total_sse / max(total_n, 1.0)))


def fit(
    model: EtaMLP,
    train_data: Dict[str, np.ndarray],
    eval_data: Dict[str, np.ndarray],
    cfg: Optional[TrainConfig] = None,
    runtime: Optional[MeshRuntime] = None,
    log_every: int = 0,
) -> FitResult:
    """Full training run on a synthetic.py-schema dataset dict."""
    cfg = cfg or TrainConfig()
    features = batch_from_mapping(train_data)
    targets = np.asarray(train_data["eta_minutes"], np.float32)
    if len(targets) == 0:
        raise ValueError("fit: training set is empty")

    mean, std = fit_normalizer(features)
    key = jax.random.PRNGKey(cfg.seed)
    params = model.init(key, norm_mean=mean, norm_std=std)
    steps_per_epoch = max(1, (len(targets) + cfg.batch_size - 1) // cfg.batch_size)
    optimizer = make_optimizer(cfg, total_steps=cfg.epochs * steps_per_epoch)
    state = TrainState(params, optimizer.init(params), jnp.zeros((), jnp.int32))
    if runtime is not None:
        state = TrainState(*runtime.replicate(tuple(state)))

    start_epoch = 0
    if cfg.checkpoint_dir:
        from routest_tpu.train import checkpoint as ckpt

        found = ckpt.latest_checkpoint_step(cfg.checkpoint_dir)
        if found is not None:
            start_epoch, latest = found
            state = TrainState(*ckpt.restore_checkpoint(latest, tuple(state)))
            if runtime is not None:
                state = TrainState(*runtime.replicate(tuple(state)))
            if log_every:
                _log.info("train_resumed", checkpoint=latest,
                          epoch=start_epoch)

    step_fn = make_train_step(model, optimizer, runtime)
    n_shards = runtime.n_data if runtime else 1

    end_epoch = cfg.epochs
    if cfg.stop_after_epochs is not None:
        # Elastic/preemptible slice: this invocation trains a bounded
        # number of epochs of the FULL schedule (optimizer decay above
        # is built from cfg.epochs, so resumed slices stay on the
        # uninterrupted trajectory). 0 is a valid budget: restore,
        # train nothing, evaluate.
        if cfg.stop_after_epochs < 0:
            raise ValueError("stop_after_epochs must be >= 0")
        end_epoch = min(cfg.epochs, start_epoch + cfg.stop_after_epochs)

    losses = []
    saved_epoch = start_epoch  # nothing new to persist until we train
    # Train observability rides the same process-wide registry as
    # serving: per-epoch step time + loss are scrapeable/exportable
    # identically whether this runs in a notebook or under the server's
    # ensure-model bootstrap.
    reg = get_registry()
    m_epoch_s = reg.histogram("rtpu_train_epoch_seconds",
                              "Wall time per training epoch.")
    m_loss = reg.gauge("rtpu_train_loss", "Last epoch's training loss.")
    m_epochs = reg.counter("rtpu_train_epochs_total",
                           "Training epochs completed.")
    for epoch in range(start_epoch, end_epoch):
        t_epoch = time.perf_counter()
        # per-epoch rng: deterministic shuffles that are stable across a
        # resume (epoch k shuffles identically whether or not we restarted)
        rng = np.random.default_rng(cfg.seed + 1 + epoch)
        for batch in _minibatches(features, targets, cfg.batch_size, rng, n_shards):
            if runtime is not None:
                batch = Batch(*runtime.shard_batch(tuple(batch)))
            state, loss = step_fn(state, batch)
        losses.append(float(loss))
        epoch_s = time.perf_counter() - t_epoch
        m_epoch_s.observe(epoch_s)
        m_loss.set(losses[-1])
        m_epochs.inc()
        if log_every and (epoch + 1) % log_every == 0:
            _log.info("train_epoch", epoch=epoch + 1, epochs=cfg.epochs,
                      loss=round(losses[-1], 4),
                      epoch_seconds=round(epoch_s, 3))
        if (cfg.checkpoint_dir and cfg.checkpoint_every_epochs
                and (epoch + 1) % cfg.checkpoint_every_epochs == 0):
            from routest_tpu.train import checkpoint as ckpt

            ckpt.save_checkpoint(cfg.checkpoint_dir, epoch + 1, tuple(state))
            saved_epoch = epoch + 1

    if (cfg.checkpoint_dir and cfg.stop_after_epochs is not None
            and saved_epoch != end_epoch):
        # An elastic slice always persists its endpoint (including the
        # schedule-completing one): ending between periodic saves would
        # otherwise make the next invocation redo — and with a budget
        # below checkpoint_every_epochs, redo FOREVER — the work this
        # slice just did.
        from routest_tpu.train import checkpoint as ckpt

        ckpt.save_checkpoint(cfg.checkpoint_dir, end_epoch, tuple(state))

    eval_rmse = rmse(model, state.params, eval_data, runtime)
    return FitResult(state=state, train_losses=losses, eval_rmse=eval_rmse)
