"""Checkpointing & model-artifact IO.

The reference's entire persistence story for the model is "lazily unpickle
``xgb_eta_model.pkl``, path overridable via ``ETA_MODEL_PATH``"
(``Flaskr/ml.py:6-21``; SURVEY.md §5.4). Here:

- training checkpoints (params + optimizer state + step) go through Orbax;
- the *serving artifact* is a single msgpack file (flax serialization) of
  the params pytree plus a small JSON header with the model config — no
  pickle, loadable without trusting the file;
- ``ETA_MODEL_PATH`` still points at the serving artifact, for env parity.
"""

from __future__ import annotations

import dataclasses
import json
import os
import threading
from typing import Optional, Tuple

import jax
import numpy as np
from flax import serialization

from routest_tpu.models.eta_mlp import EtaMLP, Params

MAGIC = b"RTPU1\n"
ARTIFACT_VERSION = 2
QUANTILE_ARTIFACT_VERSION = 3


def _write_artifact(path: str, magic: bytes, header: dict,
                    blob: bytes) -> None:
    """Shared artifact writer: magic prefix + one-line JSON header +
    binary blob — the layout every artifact family speaks (see
    :func:`_read_artifact`).

    Written temp-then-rename: hot-reload watchers (the ETA service's and
    the road router's) stat these paths on live traffic, so a reader
    must never observe a half-written file — os.replace makes the swap
    atomic on POSIX."""
    path = os.path.abspath(path)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    # pid alone is not unique enough: two threads in one process (e.g.
    # concurrent trainers in tests) would interleave writes to the same
    # temp file before os.replace.
    tmp = f"{path}.tmp{os.getpid()}.{threading.get_ident()}"
    try:
        with open(tmp, "wb") as f:
            f.write(magic)
            f.write(json.dumps(header).encode() + b"\n")
            f.write(blob)
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def _params_blob(params) -> bytes:
    """Params pytree → msgpack bytes (host copies, no device refs)."""
    return serialization.msgpack_serialize(
        jax.tree_util.tree_map(np.asarray, params))


def _read_artifact(path: str, magic: bytes, fmt: str, versions,
                   kind: str, retrain_hint: str):
    """Shared artifact reader: magic prefix + one-line JSON header +
    binary blob, with format/version validation. All three artifact
    families (eta msgpack, road-GNN msgpack, StableHLO export) speak
    this layout; keeping ONE reader keeps their error contracts in sync.
    Returns (header, blob)."""
    with open(path, "rb") as f:
        if f.read(len(magic)) != magic:
            raise ValueError(f"{path}: not a {kind}")
        header = json.loads(f.readline().decode())
        blob = f.read()
    if header.get("format") != fmt:
        raise ValueError(f"{path}: unknown artifact format "
                         f"{header.get('format')}")
    if header.get("version") not in versions:
        expected = "/".join(f"v{v}" for v in versions)
        raise ValueError(
            f"{path}: artifact version {header.get('version')} is "
            f"incompatible (expects {expected}); {retrain_hint}")
    return header, blob


def save_model(path: str, model: EtaMLP, params: Params) -> None:
    """Serving artifact: MAGIC + json header line + msgpack params."""
    header_dict = {
        "format": "routest_tpu.eta_mlp",
        # v2: internal one-hot expansion + [pace, overhead] heads
        # (first layer is 42-wide, output is 2-wide). v1 artifacts
        # (12-wide input, 1 head) are incompatible and rejected on load.
        # v3 = v2 + quantile heads (output 2·Q-wide); point models keep
        # writing v2 so older builds load them unchanged.
        "version": ARTIFACT_VERSION,
        "hidden": list(model.hidden),
        "n_features": model.n_features,
        "compute_dtype": np.dtype(model.policy.compute_dtype).name,
    }
    if model.quantiles:
        header_dict["version"] = QUANTILE_ARTIFACT_VERSION
        header_dict["quantiles"] = list(model.quantiles)
    _write_artifact(path, MAGIC, header_dict, _params_blob(params))


def load_model(path: str) -> Tuple[EtaMLP, Params]:
    header, blob = _read_artifact(
        path, MAGIC, "routest_tpu.eta_mlp",
        (ARTIFACT_VERSION, QUANTILE_ARTIFACT_VERSION),
        kind="routest_tpu model artifact",
        retrain_hint="retrain via scripts/train_eta.py")
    version = header.get("version")
    quantiles = tuple(header.get("quantiles", ()))
    if version == QUANTILE_ARTIFACT_VERSION and not quantiles:
        raise ValueError(f"{path}: v{QUANTILE_ARTIFACT_VERSION} artifact "
                         f"missing its quantiles header")
    import jax.numpy as jnp

    from routest_tpu.core.dtypes import DEFAULT_POLICY
    import dataclasses as _dc

    compute = header.get("compute_dtype", "bfloat16")
    policy = _dc.replace(DEFAULT_POLICY, compute_dtype=jnp.dtype(compute).type)
    model = EtaMLP(hidden=tuple(header["hidden"]), n_features=header["n_features"],
                   policy=policy, quantiles=quantiles)
    params = serialization.msgpack_restore(blob)
    params = jax.tree_util.tree_map(lambda x: np.asarray(x), params)
    return model, params


EXPORT_MAGIC = b"RTPUX1\n"
EXPORT_VERSION = 1


def export_serving_fn(path: str, model: EtaMLP, params: Params,
                      platforms: Tuple[str, ...] = ("cpu", "tpu")) -> None:
    """AOT-export the serving forward as serialized StableHLO.

    The msgpack artifact (``save_model``) needs this package's model
    code to rebuild the forward; this artifact does not — the traced
    computation with the params baked in as constants IS the file, with
    a symbolic batch dimension so one export covers every batch bucket.
    That pins the serving numerics against model-code drift (the
    deployed function can't change when ``eta_mlp.py`` does) and drops
    the Python model from the serving dependency chain — the TPU-native
    analog of exporting the reference's pickled booster to a
    self-contained format. Multi-platform by default: the same file
    serves the CPU conftest backend and the TPU.

    Layout mirrors ``save_model``: EXPORT_MAGIC + JSON header line
    (n_features / quantiles / platforms — what the serving layer needs
    without executing anything) + the StableHLO bytes.
    """
    from jax import export as jax_export

    quantiles = tuple(getattr(model, "quantiles", ()) or ())
    forward = model.apply_quantiles if quantiles else model.apply
    host_params = jax.tree_util.tree_map(np.asarray, params)

    def fn(x):
        return forward(host_params, x)

    (batch,) = jax_export.symbolic_shape("b")
    spec = jax.ShapeDtypeStruct((batch, model.n_features), np.float32)
    exported = jax_export.export(jax.jit(fn), platforms=tuple(platforms))(spec)
    _write_artifact(path, EXPORT_MAGIC, {
        "format": "routest_tpu.eta_stablehlo",
        "version": EXPORT_VERSION,
        "n_features": model.n_features,
        "quantiles": list(quantiles),
        "platforms": list(platforms),
        "hidden": list(model.hidden),  # informational; not needed to run
    }, exported.serialize())


class ExportedServingModel:
    """A deserialized AOT export, shaped like a model for the serving
    layer: ``n_features``/``quantiles`` attributes + ``__call__``.
    No params pytree exists — weights are constants inside the program."""

    def __init__(self, call, header: dict) -> None:
        self._call = call
        self.header = header
        self.n_features = int(header["n_features"])
        self.quantiles = tuple(header.get("quantiles", ()))
        self.hidden = tuple(header.get("hidden", ()))

    @property
    def call(self):
        """The raw traceable program — what the serving layer hands to
        ``jax.jit`` for per-bucket AOT compiles (with mesh shardings
        when a runtime is present)."""
        return self._call

    def __call__(self, x):
        return self._call(x)


def backend_platforms(backend: Optional[str] = None) -> Tuple[str, ...]:
    """jax backend name → the export-platform names it can execute.
    Vocabularies differ on GPU: ``jax.default_backend()`` says "gpu",
    exports say "cuda"/"rocm"."""
    backend = backend or jax.default_backend()
    if backend == "gpu":
        return ("cuda", "rocm")
    return (backend,)


def load_exported_serving_fn(path: str) -> ExportedServingModel:
    """Deserialize an ``export_serving_fn`` artifact. Raises ValueError
    for wrong magic/format/version (same contract as ``load_model``)."""
    from jax import export as jax_export

    header, blob = _read_artifact(
        path, EXPORT_MAGIC, "routest_tpu.eta_stablehlo", (EXPORT_VERSION,),
        kind="routest_tpu AOT export",
        retrain_hint="re-export via scripts/export_model.py")
    exported = jax_export.deserialize(blob)
    runnable = backend_platforms()
    if not any(p in exported.platforms for p in runnable):
        raise ValueError(
            f"{path}: exported for platforms {list(exported.platforms)}, "
            f"but the running backend is {jax.default_backend()}; "
            f"re-export with --platforms {','.join(runnable)}")
    # Same contract as EtaMLP.__post_init__: a quantile head must carry
    # the median, or every per-request ``q.index(0.5)`` in the serving
    # layer would raise (500s) instead of the graceful (None, None)
    # degrade. Reject the foreign/hand-edited artifact at load time.
    quantiles = header.get("quantiles") or []
    if quantiles and 0.5 not in quantiles:
        raise ValueError(
            f"{path}: quantile export lacks the 0.5 median "
            f"(quantiles={quantiles}); serving requires it")
    return ExportedServingModel(exported.call, header)


def default_model_path(cfg=None) -> str:
    """Resolution order: explicit ModelConfig.model_path (set from
    ETA_MODEL_PATH by ``load_config``), then the env var directly, then the
    in-repo artifact location (mirrors ``Flaskr/ml.py:6-9`` behavior)."""
    if cfg is not None and getattr(cfg, "model_path", None):
        return cfg.model_path
    return os.getenv("ETA_MODEL_PATH") or os.path.join(
        os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
        "artifacts",
        "eta_mlp.msgpack",
    )


# ── Road-GNN serving artifact ─────────────────────────────────────────────
#
# Same MAGIC + header + msgpack layout as the ETA artifact, different
# format tag. The header carries a fingerprint of the TRAINING graph's
# node set (count + coordinate checksum): the model's message passing is
# anchored to node embeddings, so serving it over a different node set
# would silently produce garbage — the router refuses mismatched graphs
# and falls back to free-flow physics.

GNN_ARTIFACT_VERSION = 1


def graph_fingerprint(node_coords: np.ndarray, senders: np.ndarray,
                      receivers: np.ndarray, length_m: np.ndarray) -> dict:
    """Nodes AND edges: the GNN's aggregation depends on the topology it
    was trained over, so an edge-set drift (not just a node drift) must
    also fail the serving-compatibility check."""
    import zlib

    def crc(a, dtype):
        return int(zlib.crc32(np.ascontiguousarray(
            np.asarray(a, dtype)).tobytes()))

    return {
        "n_nodes": int(np.asarray(node_coords).shape[0]),
        "coords_crc32": crc(node_coords, np.float32),
        "n_edges": int(len(senders)),
        "edges_crc32": crc(senders, np.int32) ^ crc(receivers, np.int32)
        ^ crc(length_m, np.float32),
    }


def save_gnn(path: str, model, params, graph: dict) -> None:
    _write_artifact(path, MAGIC, {
        "format": "routest_tpu.road_gnn",
        "version": GNN_ARTIFACT_VERSION,
        "hidden": int(model.hidden),
        "n_rounds": int(model.n_rounds),
        "n_nodes": int(model.n_nodes),
        "compute_dtype": np.dtype(model.policy.compute_dtype).name,
        "graph": graph_fingerprint(
            graph["node_coords"], graph["senders"], graph["receivers"],
            graph["length_m"]),
    }, _params_blob(params))


def load_gnn(path: str):
    """→ (RoadGNN, params, graph fingerprint dict)."""
    from routest_tpu.models.gnn import RoadGNN

    header, blob = _read_artifact(
        path, MAGIC, "routest_tpu.road_gnn", (GNN_ARTIFACT_VERSION,),
        kind="routest_tpu model artifact",
        retrain_hint="retrain via scripts/train_gnn.py")
    import jax.numpy as jnp

    from routest_tpu.core.dtypes import DEFAULT_POLICY

    compute = header.get("compute_dtype", "bfloat16")
    policy = dataclasses.replace(DEFAULT_POLICY,
                                 compute_dtype=jnp.dtype(compute).type)
    model = RoadGNN(n_nodes=header["n_nodes"], hidden=header["hidden"],
                    n_rounds=header["n_rounds"], policy=policy)
    params = serialization.msgpack_restore(blob)
    params = jax.tree_util.tree_map(np.asarray, params)
    # Feature-ABI gate: an artifact trained against an older
    # edge_feature_array layout would pass the graph fingerprint and
    # then shape-crash inside apply ON THE REQUEST PATH. The message
    # MLP's input width pins the trained feature count; reject here so
    # the router's loader degrades to the next pricer instead.
    from routest_tpu.models.gnn import N_EDGE_FEATURES

    f_in = int(params["msg"][0]["w"].shape[0]) - 2 * int(header["hidden"])
    if f_in != N_EDGE_FEATURES:
        raise ValueError(
            f"{path}: trained with {f_in} edge features, this build uses "
            f"{N_EDGE_FEATURES}; retrain via scripts/train_gnn.py")
    return model, params, header.get("graph") or {}


def default_gnn_path() -> str:
    """``ROAD_GNN_PATH`` env override, then the in-repo artifact."""
    return os.getenv("ROAD_GNN_PATH") or os.path.join(
        os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
        "artifacts",
        "road_gnn.msgpack",
    )


# ── Route-transformer serving artifact ────────────────────────────────────

TRANSFORMER_ARTIFACT_VERSION = 1


def save_transformer(path: str, model, params, graph: dict,
                     seq_len: int) -> None:
    """Route-transformer leg-cost artifact — same fingerprinting contract
    as the road GNN: the router serves it only when its training graph
    matches the routable (post-bridge) graph. ``seq_len`` (the trained
    route length) is recorded so serving can chunk longer tours into
    in-distribution windows."""
    _write_artifact(path, MAGIC, {
        "format": "routest_tpu.route_transformer",
        "version": TRANSFORMER_ARTIFACT_VERSION,
        "d_model": int(model.d_model),
        "n_heads": int(model.n_heads),
        "n_layers": int(model.n_layers),
        "d_mlp": int(model.d_mlp),
        "seq_len": int(seq_len),
        "graph": graph_fingerprint(
            graph["node_coords"], graph["senders"], graph["receivers"],
            graph["length_m"]),
    }, _params_blob(params))


def load_transformer(path: str):
    """→ (RouteTransformer, params, meta) where meta carries the graph
    fingerprint and the trained ``seq_len``."""
    from routest_tpu.models.route_transformer import RouteTransformer

    header, blob = _read_artifact(
        path, MAGIC, "routest_tpu.route_transformer",
        (TRANSFORMER_ARTIFACT_VERSION,),
        kind="routest_tpu model artifact",
        retrain_hint="retrain via scripts/train_transformer.py")
    model = RouteTransformer(d_model=header["d_model"],
                             n_heads=header["n_heads"],
                             n_layers=header["n_layers"],
                             d_mlp=header["d_mlp"])
    params = serialization.msgpack_restore(blob)
    params = jax.tree_util.tree_map(np.asarray, params)
    # Same feature-ABI gate as load_gnn: the embed matrix pins the
    # trained edge-feature count.
    f_in = int(params["embed"]["w"].shape[0])
    if f_in != model.n_features:
        raise ValueError(
            f"{path}: trained with {f_in} edge features, this build uses "
            f"{model.n_features}; retrain via scripts/train_transformer.py")
    return model, params, {"graph": header.get("graph") or {},
                           "seq_len": int(header.get("seq_len", 24))}


def default_transformer_path() -> str:
    """``ROUTE_TRANSFORMER_PATH`` env override, then the in-repo artifact."""
    return os.getenv("ROUTE_TRANSFORMER_PATH") or os.path.join(
        os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
        "artifacts",
        "route_transformer.msgpack",
    )


# ── Orbax training checkpoints ────────────────────────────────────────────

def save_checkpoint(ckpt_dir: str, step: int, state) -> None:
    import orbax.checkpoint as ocp

    path = os.path.join(os.path.abspath(ckpt_dir), f"step_{step:08d}")
    ckptr = ocp.StandardCheckpointer()
    host_state = jax.tree_util.tree_map(np.asarray, state)
    ckptr.save(path, host_state, force=True)
    ckptr.wait_until_finished()


def latest_checkpoint_step(ckpt_dir: str) -> Optional[Tuple[int, str]]:
    """Newest COMPLETE checkpoint as ``(step, path)``. A crash mid-save
    leaves Orbax tmp dirs (``step_N.orbax-checkpoint-tmp-*``) behind —
    exactly the scenario resume exists for — so only cleanly-named
    numeric steps count. The step number is parsed here, the one place
    that owns the ``step_%08d`` naming scheme."""
    if not os.path.isdir(ckpt_dir):
        return None
    best: Optional[Tuple[int, str]] = None
    for d in os.listdir(ckpt_dir):
        if not d.startswith("step_"):
            continue
        suffix = d[len("step_"):]
        if not suffix.isdigit():
            continue  # tmp/incomplete entries
        step = int(suffix)
        if best is None or step > best[0]:
            best = (step, d)
    return (best[0], os.path.join(ckpt_dir, best[1])) if best else None


def latest_checkpoint(ckpt_dir: str) -> Optional[str]:
    found = latest_checkpoint_step(ckpt_dir)
    return found[1] if found else None


def restore_checkpoint(path: str, target):
    import orbax.checkpoint as ocp

    ckptr = ocp.StandardCheckpointer()
    host_target = jax.tree_util.tree_map(np.asarray, target)
    return ckptr.restore(path, host_target)
