"""Synthetic Metro Manila road graph for GNN leg-cost learning.

BASELINE.json config 4 calls for "road-graph GNN training over the full
data/raw/ network" — but the reference's ``data/raw/`` is empty
(SURVEY.md §0), so the graph, like the delivery dataset, must be
generated. The generator produces a road network with the right
statistics for an urban grid:

- intersection nodes sampled over the Metro Manila bounding box, with
  density clustered around the 21 seed sites (``data/locations.py``);
- edges from k-nearest-neighbor connection (symmetrized), giving mean
  degree ≈ 2k — arterial-plus-side-street territory;
- per-edge features: length (haversine), road class (one-hot of
  arterial/collector/local), speed limit;
- per-edge observed travel time from a ground-truth congestion model
  (length / class-speed, rush-hour and class interactions) with
  log-normal noise — the learning target.

Everything is flat numpy arrays (senders/receivers/features), ready to
shard across the mesh edge-wise.
"""

from __future__ import annotations

from typing import Dict

import numpy as np

from routest_tpu.data.locations import coords_array

# Metro Manila bounding box (covers all 21 seed sites with margin).
LAT_RANGE = (14.38, 14.70)
LON_RANGE = (120.94, 121.12)

ROAD_CLASSES = ("arterial", "collector", "local")
_CLASS_SPEED_MPS = np.asarray([11.1, 8.3, 5.6])   # 40 / 30 / 20 km/h
_CLASS_RUSH_SENSITIVITY = np.asarray([0.8, 0.5, 0.25])


def haversine_np(lat1, lon1, lat2, lon2):
    """Great-circle meters, vectorized numpy (host-side twin of
    ``data.geo``'s jnp version; public — the road router builds on it)."""
    r = 6_371_008.8
    lat1, lon1, lat2, lon2 = map(np.radians, (lat1, lon1, lat2, lon2))
    a = (np.sin((lat2 - lat1) / 2) ** 2
         + np.cos(lat1) * np.cos(lat2) * np.sin((lon2 - lon1) / 2) ** 2)
    return 2 * r * np.arcsin(np.sqrt(np.clip(a, 0, 1)))


_haversine_np = haversine_np  # internal alias (existing call sites)


def true_edge_time_s(length_m: np.ndarray, road_class: np.ndarray,
                     hour: np.ndarray) -> np.ndarray:
    """Ground-truth travel time per edge (no noise)."""
    base = length_m / _CLASS_SPEED_MPS[road_class]
    h = hour.astype(np.float64)
    rush = (np.exp(-0.5 * ((h - 8.0) / 1.6) ** 2)
            + np.exp(-0.5 * ((h - 18.0) / 1.8) ** 2))
    congestion = 1.0 + _CLASS_RUSH_SENSITIVITY[road_class] * rush
    night = np.where((h >= 22) | (h <= 5), 0.85, 1.0)
    return base * congestion * night + 4.0  # signalized-intersection overhead


def knn_neighbors(coords: np.ndarray, k: int) -> np.ndarray:
    """(N, 2) → (N, k) nearest-neighbor indices.

    Brute force up to 8,192 nodes — EXACT and byte-stable, which the
    serving graph's fingerprint depends on (2,048-node default). Above
    that, a cell-hashed search: the O(N²) distance matrix would need
    20 GB at 50k nodes (the metro-scale benchmark regime), while cells
    sized for ~2 points each make the search O(N·k). The cell pass is
    exact too (rings expand until k candidates can't be beaten), just
    not guaranteed byte-identical in tie order — fine for new graphs,
    which fingerprint whatever they get.
    """
    n = len(coords)
    if n <= 8192:
        d2 = ((coords[:, None, :] - coords[None, :, :]) ** 2).sum(-1)
        np.fill_diagonal(d2, np.inf)
        return np.argsort(d2, axis=1)[:, :k]

    lat_min, lon_min = coords.min(axis=0)
    lat_max, lon_max = coords.max(axis=0)
    # ~2 points per cell on average
    n_cells = max(1, int(np.sqrt(n / 2.0)))
    cw_lat = (lat_max - lat_min) / n_cells + 1e-9
    cw_lon = (lon_max - lon_min) / n_cells + 1e-9
    ix = np.minimum(((coords[:, 0] - lat_min) / cw_lat).astype(np.int64),
                    n_cells - 1)
    iy = np.minimum(((coords[:, 1] - lon_min) / cw_lon).astype(np.int64),
                    n_cells - 1)
    cell = ix * n_cells + iy
    order = np.argsort(cell, kind="stable")
    sorted_cell = cell[order]
    starts = np.searchsorted(sorted_cell, np.arange(n_cells * n_cells))
    ends = np.searchsorted(sorted_cell, np.arange(n_cells * n_cells), "right")

    out = np.empty((n, k), np.int64)
    for i in range(n):
        r = 1
        while True:
            x0, x1 = max(ix[i] - r, 0), min(ix[i] + r, n_cells - 1)
            y0, y1 = max(iy[i] - r, 0), min(iy[i] + r, n_cells - 1)
            # order[] is cell-sorted, so within row cx the cells y0..y1
            # are one contiguous slice
            cand = np.concatenate([
                order[starts[cx * n_cells + y0]: ends[cx * n_cells + y1]]
                for cx in range(x0, x1 + 1)
            ])
            cand = cand[cand != i]
            if len(cand) >= k:
                d2 = ((coords[cand] - coords[i]) ** 2).sum(axis=1)
                kth = np.sqrt(np.partition(d2, k - 1)[k - 1])
                # Exactness: the window is guaranteed to cover at least
                # (r-1)·cell_width around the point (it may sit at its
                # cell's edge); accept only when the kth neighbor lies
                # within that covered radius — otherwise a nearer point
                # could hide one ring further out.
                if kth <= (r - 1) * min(cw_lat, cw_lon) or r >= n_cells:
                    out[i] = cand[np.argsort(d2, kind="stable")[:k]]
                    break
            elif r >= n_cells:  # degenerate: take what exists, pad w/ self
                d2 = ((coords[cand] - coords[i]) ** 2).sum(axis=1)
                top = cand[np.argsort(d2, kind="stable")]
                out[i] = np.concatenate(
                    [top, np.full(k - len(top), i, np.int64)])[:k]
                break
            r += 1
    return out


def add_congestion_observations(graph: Dict[str, np.ndarray], seed: int = 0,
                                noise_sigma: float = 0.06,
                                samples_per_edge: int = 1) -> Dict[str, np.ndarray]:
    """Congestion-overlay training targets for ANY road graph.

    Takes a topology-only graph dict (``senders``/``length_m``/
    ``road_class`` — e.g. an OSM extract from ``data/osm.py``, which
    carries no travel-time labels) and adds the per-edge observation
    columns the GNN trains on: a sampled observation ``hour``, the
    ground-truth congestion-model time (``true_edge_time_s`` — rush-hour
    peaks, class sensitivity, night discount), and log-normally noised
    observed time. In production these columns would come from fleet
    telemetry; the overlay is the stand-in that makes learned leg costs
    trainable on arbitrary real road networks, not only on the synthetic
    generator whose observations are baked in (the round-2 gap: OSM
    ingest and GNN serving were mutually exclusive).

    ``samples_per_edge > 1`` tiles the edge arrays, drawing an
    independent observation hour per copy — small extracts need several
    observations per edge to expose the congestion curve's shape. The
    serving fingerprint must be computed from the UN-tiled graph (the
    topology serving aggregates over), so pass the base dict to
    ``save_gnn`` and the tiled one only to the training batch.
    """
    rng = np.random.default_rng(seed)
    out = dict(graph)
    if samples_per_edge > 1:
        for key in ("senders", "receivers", "length_m", "road_class",
                    "speed_limit"):
            if key in out:
                out[key] = np.tile(np.asarray(out[key]), samples_per_edge)
    n_edges = len(out["senders"])
    road_class = np.asarray(out["road_class"], np.int32)
    length_m = np.asarray(out["length_m"], np.float32)
    hour = rng.integers(0, 24, size=n_edges).astype(np.int32)
    t_true = true_edge_time_s(length_m, road_class, hour)
    time_s = (t_true * rng.lognormal(0.0, noise_sigma, n_edges)).astype(np.float32)
    out["hour"] = hour
    out["time_s"] = time_s
    out["time_true_s"] = t_true.astype(np.float32)
    return out


def subdivide_graph(graph: Dict[str, np.ndarray], bends_per_edge: int = 2,
                    jitter: float = 0.08, oneway_frac: float = 0.0,
                    seed: int = 0) -> Dict[str, np.ndarray]:
    """Intersection graph → OSM-extract *topology*: every street gains
    ``bends_per_edge`` degree-2 geometry nodes (the defining shape of a
    real extract, where ``load_osm`` keeps every ``<nd>`` bend as a
    vertex — 70-85% of a real city's nodes are degree-2 chain
    vertices), with perpendicular jitter so chains curve like streets,
    and ``oneway_frac`` of streets keeping only their forward
    direction. Chain vertices multiply the hop diameter by
    ``bends_per_edge + 1``, which is exactly the regime that breaks
    diameter-bound relaxation and that the partition overlay
    (``optimize/hierarchy.py``) is built for.

    Returns a topology-only graph dict (no congestion columns — pipe
    through :func:`add_congestion_observations` for training data).
    """
    rng = np.random.default_rng(seed)
    coords = np.asarray(graph["node_coords"], np.float64)
    senders = np.asarray(graph["senders"], np.int64)
    receivers = np.asarray(graph["receivers"], np.int64)
    road_class = np.asarray(graph["road_class"], np.int32)
    speed_limit = np.asarray(
        graph.get("speed_limit", _CLASS_SPEED_MPS[road_class]), np.float32)
    n = len(coords)
    k = int(bends_per_edge)

    # Unique undirected streets; attrs from each street's first edge.
    key = np.minimum(senders, receivers) * n + np.maximum(senders, receivers)
    _, first = np.unique(key, return_index=True)
    a, b = senders[first], receivers[first]
    u = len(a)
    cls_u, spd_u = road_class[first], speed_limit[first]

    # Bend coordinates: linear interpolation + perpendicular jitter.
    t = ((np.arange(k) + 1) / (k + 1))[None, :, None]         # (1, k, 1)
    bends = coords[a][:, None, :] * (1 - t) + coords[b][:, None, :] * t
    d = coords[b] - coords[a]
    norm = np.sqrt((d ** 2).sum(axis=1, keepdims=True)) + 1e-12
    perp = np.stack([-d[:, 1], d[:, 0]], axis=1) / norm
    amp = norm[:, :1] * jitter
    bends += perp[:, None, :] * (rng.standard_normal((u, k, 1)) * amp[:, None])
    new_coords = np.concatenate(
        [coords, bends.reshape(-1, 2)]).astype(np.float32)

    # Chains: a → bend_0 → … → bend_{k-1} → b (and back, unless oneway).
    bend_ids = n + (np.arange(u)[:, None] * k + np.arange(k)[None, :])
    seq = np.concatenate([a[:, None], bend_ids, b[:, None]], axis=1)
    fwd_s, fwd_r = seq[:, :-1], seq[:, 1:]                    # (U, k+1)
    keep_rev = rng.random(u) >= oneway_frac
    new_s = np.concatenate([fwd_s.reshape(-1), fwd_r[keep_rev].reshape(-1)])
    new_r = np.concatenate([fwd_r.reshape(-1), fwd_s[keep_rev].reshape(-1)])
    reps = np.concatenate([np.repeat(np.arange(u), k + 1),
                           np.repeat(np.arange(u)[keep_rev], k + 1)])
    length = haversine_np(new_coords[new_s, 0], new_coords[new_s, 1],
                          new_coords[new_r, 0], new_coords[new_r, 1])
    return {
        "node_coords": new_coords,
        "senders": new_s.astype(np.int32),
        "receivers": new_r.astype(np.int32),
        "length_m": length.astype(np.float32),
        "road_class": cls_u[reps],
        "speed_limit": spd_u[reps],
    }


def generate_road_graph(n_nodes: int = 4096, k: int = 4, seed: int = 0,
                        noise_sigma: float = 0.06) -> Dict[str, np.ndarray]:
    """Graph dict: node_coords (N,2), senders/receivers (E,), edge feature
    arrays, observed times, plus a train-time ``hour`` per edge sample."""
    rng = np.random.default_rng(seed)

    # Node positions: 70% clustered around seed sites, 30% uniform fill.
    sites = coords_array()
    n_cluster = int(n_nodes * 0.7)
    centers = sites[rng.integers(0, len(sites), n_cluster)]
    cluster = centers + rng.normal(0, 0.012, size=(n_cluster, 2))
    uniform = np.stack([
        rng.uniform(*LAT_RANGE, n_nodes - n_cluster),
        rng.uniform(*LON_RANGE, n_nodes - n_cluster),
    ], axis=1)
    coords = np.concatenate([cluster, uniform]).astype(np.float32)
    coords[:, 0] = np.clip(coords[:, 0], *LAT_RANGE)
    coords[:, 1] = np.clip(coords[:, 1], *LON_RANGE)

    nbrs = knn_neighbors(coords, k)
    senders = np.repeat(np.arange(n_nodes), k)
    receivers = nbrs.reshape(-1)
    # symmetrize + dedupe
    pairs = np.stack([np.minimum(senders, receivers),
                      np.maximum(senders, receivers)], axis=1)
    pairs = np.unique(pairs, axis=0)
    senders = np.concatenate([pairs[:, 0], pairs[:, 1]]).astype(np.int32)
    receivers = np.concatenate([pairs[:, 1], pairs[:, 0]]).astype(np.int32)

    length_m = _haversine_np(
        coords[senders, 0], coords[senders, 1],
        coords[receivers, 0], coords[receivers, 1],
    ).astype(np.float32) * 1.2  # street grid vs straight line

    n_edges = len(senders)
    road_class = rng.choice(len(ROAD_CLASSES), size=n_edges,
                            p=[0.2, 0.35, 0.45]).astype(np.int32)
    speed_limit = _CLASS_SPEED_MPS[road_class].astype(np.float32)
    hour = rng.integers(0, 24, size=n_edges).astype(np.int32)

    t_true = true_edge_time_s(length_m, road_class, hour)
    time_s = (t_true * rng.lognormal(0.0, noise_sigma, n_edges)).astype(np.float32)

    return {
        "node_coords": coords,
        "senders": senders,
        "receivers": receivers,
        "length_m": length_m,
        "road_class": road_class,
        "speed_limit": speed_limit,
        "hour": hour,
        "time_s": time_s,
        "time_true_s": t_true.astype(np.float32),
    }
