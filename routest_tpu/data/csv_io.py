"""CSV dataset ingest/export — the ``data/`` capability the reference
leaves empty (``data/.gitkeep``; SURVEY.md §7.3 item 1: "no data, no
model").

Schema (one header + one row per delivery):

    weather,traffic,weekday,hour,distance_km,driver_age,eta_minutes

``weather``/``traffic`` are category names from the 12-feature ABI
vocabularies (``data/features.py``); unknown names map to index -1
(all-zero one-hot group), matching ``vocab_index``. ``load_csv`` returns
the same dataset-dict schema as ``data/synthetic.py``, so it feeds
``train.loop.fit`` directly.

The format is PLAIN comma-separated — no quoting, no embedded commas
(every value is a vocab name or a number, so none are ever needed) —
and both parsers treat it identically: the header is validated verbatim
before parsing, a row without exactly 7 fields is an error naming the
line, and quote characters are ordinary text (an unknown category).

Ingest goes through the native parser (``routest_tpu/native``) when the
toolchain is available — one C pass, no per-row Python objects — and an
identical-contract Python fallback otherwise (parity enforced by
``tests/test_native.py``).
"""

from __future__ import annotations

import csv
import re
from typing import Dict

import numpy as np

# The shared numeric grammar (see _load_csv_python): plain decimal with
# optional sign/fraction/exponent, at most 63 chars — exactly what the
# native parser's charset pre-check + strtod full-consume accepts.
# re.ASCII: \d must mean [0-9] only (float() would happily parse Unicode
# digits the native parser rejects).
_NUMERIC_RE = re.compile(r"^[+-]?(\d+\.?\d*|\.\d+)([eE][+-]?\d+)?$", re.ASCII)
_MAX_NUMERIC_LEN = 63

from routest_tpu.data.features import TRAFFIC_CATEGORIES, WEATHER_CATEGORIES

COLUMNS = ("weather", "traffic", "weekday", "hour",
           "distance_km", "driver_age", "eta_minutes")


def save_csv(path: str, data: Dict[str, np.ndarray]) -> None:
    """Dataset dict → CSV file (the export half of the pipeline)."""
    w = np.asarray(data["weather_idx"])
    t = np.asarray(data["traffic_idx"])
    with open(path, "w", newline="") as f:
        out = csv.writer(f)
        out.writerow(COLUMNS)
        for i in range(len(w)):
            out.writerow([
                WEATHER_CATEGORIES[w[i]] if 0 <= w[i] < len(WEATHER_CATEGORIES)
                else "Unknown",
                TRAFFIC_CATEGORIES[t[i]] if 0 <= t[i] < len(TRAFFIC_CATEGORIES)
                else "Unknown",
                int(data["weekday"][i]), int(data["hour"][i]),
                f"{float(data['distance_km'][i]):.6g}",
                f"{float(data['driver_age'][i]):.6g}",
                f"{float(data['eta_minutes'][i]):.6g}",
            ])


def _check_header(path: str) -> None:
    """Validate the verbatim header (both parse paths route through here)."""
    with open(path) as f:
        for line in f:
            first = line.strip("\r\n")
            if first:
                break
        else:
            first = ""
    if first != ",".join(COLUMNS):
        raise ValueError(
            f"{path}:1: bad header (expected {','.join(COLUMNS)!r})")


def load_csv(path: str, *, force_python: bool = False) -> Dict[str, np.ndarray]:
    """CSV file → dataset dict (native parser when available)."""
    _check_header(path)
    if not force_python:
        from routest_tpu import native

        if native.available():
            return native.parse_csv(path, WEATHER_CATEGORIES, TRAFFIC_CATEGORIES)
    return _load_csv_python(path)


def _load_csv_python(path: str) -> Dict[str, np.ndarray]:
    w_lut = {v: i for i, v in enumerate(WEATHER_CATEGORIES)}
    t_lut = {v: i for i, v in enumerate(TRAFFIC_CATEGORIES)}
    cols: Dict[str, list] = {k: [] for k in (
        "weather_idx", "traffic_idx", "weekday", "hour",
        "distance_km", "driver_age", "eta_minutes")}
    with open(path, newline="") as f:
        header_seen = False
        for lineno, line in enumerate(f, start=1):
            # Native-parser parity: its 4096-byte fgets buffer rejects any
            # physical line of 4095+ content BYTES (code -4) — count bytes,
            # not codepoints, or non-ASCII categories parse-or-error
            # differently under the two parsers.
            content_len = len(line.encode("utf-8", "surrogateescape")) \
                - (1 if line.endswith("\n") else 0)
            if content_len >= 4095:
                raise ValueError(f"{path}:{lineno}: line exceeds 4094 bytes")
            line = line.strip("\r\n")
            if not line:
                continue
            if not header_seen:
                header_seen = True
                continue
            # Plain split, mirroring the native parser exactly: the
            # schema has no quoting (see module docstring), so a
            # csv.reader's quote handling would DIVERGE from native on
            # malformed quote-bearing input, not add capability.
            row = line.split(",")
            if len(row) != 7:
                raise ValueError(f"{path}:{lineno}: expected 7 fields")
            try:
                # _NUMERIC_RE + range guards keep this grammar and the
                # native parser's byte-for-byte identical (no python-isms
                # like '1_0', no strtod-isms like hex or padding; f32/i32
                # overflow is an error, not silent inf/garbage).
                if not all(len(row[i]) <= _MAX_NUMERIC_LEN
                           and _NUMERIC_RE.match(row[i])
                           for i in (2, 3, 4, 5, 6)):
                    raise ValueError
                numeric = [float(row[i]) for i in (2, 3, 4, 5, 6)]
                if not all(np.isfinite(v) and abs(v) <= 3.0e38 for v in numeric):
                    raise ValueError
                if any(abs(v) > 2**31 - 1 for v in numeric[:2]):
                    raise ValueError
                cols["weekday"].append(int(numeric[0]))
                cols["hour"].append(int(numeric[1]))
                cols["distance_km"].append(numeric[2])
                cols["driver_age"].append(numeric[3])
                cols["eta_minutes"].append(numeric[4])
            except (ValueError, OverflowError):
                raise ValueError(f"{path}:{lineno}: non-numeric field") from None
            cols["weather_idx"].append(w_lut.get(row[0], -1))
            cols["traffic_idx"].append(t_lut.get(row[1], -1))
    return {
        "weather_idx": np.asarray(cols["weather_idx"], np.int32),
        "traffic_idx": np.asarray(cols["traffic_idx"], np.int32),
        "weekday": np.asarray(cols["weekday"], np.int32),
        "hour": np.asarray(cols["hour"], np.int32),
        "distance_km": np.asarray(cols["distance_km"], np.float32),
        "driver_age": np.asarray(cols["driver_age"], np.float32),
        "eta_minutes": np.asarray(cols["eta_minutes"], np.float32),
    }
