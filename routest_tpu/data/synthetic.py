"""Synthetic delivery dataset generator.

The reference's ``data/`` and ``notebooks/`` are empty (SURVEY.md §0) and
its trained model is an unmaterialized LFS pointer, so the training-data
capability has to be *created*: a generator whose schema exactly matches
the 12-feature contract of ``Flaskr/ml.py:35-48`` (weather/traffic
categories, weekday, hour, distance_km, driver_age → ETA minutes).

The ground-truth ETA surface is principled, not arbitrary: travel time =
distance × pace, where pace (min/km) depends on traffic tier, rush-hour
bumps, weather multipliers, a weekend discount, a slight driver-age
U-curve, plus a fixed handling overhead and multiplicative log-normal
noise. It is deliberately non-linear (interactions between traffic, hour
and distance) so tree ensembles and MLPs separate from linear baselines —
giving the RMSE comparison teeth.
"""

from __future__ import annotations

from typing import Dict, Optional

import numpy as np

from routest_tpu.data.features import TRAFFIC_CATEGORIES, WEATHER_CATEGORIES

# Pace in minutes per km by traffic tier (index aligned with
# TRAFFIC_CATEGORIES = High, Jam, Low, Medium); -1 (unknown) gets the value
# at index 4.
_TRAFFIC_PACE = np.asarray([4.1, 6.3, 2.0, 3.0, 3.4], dtype=np.float64)
# Weather multiplier (Cloudy, Stormy, Sunny, Windy, unknown e.g. "Fog").
_WEATHER_MULT = np.asarray([1.04, 1.38, 1.0, 1.09, 1.18], dtype=np.float64)

HANDLING_OVERHEAD_MIN = 6.0  # parking + handoff per delivery
NOISE_SIGMA = 0.08           # log-normal multiplicative noise


def true_eta_minutes(
    weather_idx: np.ndarray,
    traffic_idx: np.ndarray,
    weekday: np.ndarray,
    hour: np.ndarray,
    distance_km: np.ndarray,
    driver_age: np.ndarray,
) -> np.ndarray:
    """Noise-free ground-truth ETA surface (numpy, float64)."""
    pace = _TRAFFIC_PACE[np.where(traffic_idx < 0, 4, traffic_idx)]
    wmult = _WEATHER_MULT[np.where(weather_idx < 0, 4, weather_idx)]
    # Rush-hour congestion: gaussian bumps at 08:00 and 18:00; scaled so the
    # effect interacts with the traffic tier (jammed roads jam harder).
    h = hour.astype(np.float64)
    rush = 1.0 + 0.35 * (
        np.exp(-0.5 * ((h - 8.0) / 1.6) ** 2) + np.exp(-0.5 * ((h - 18.0) / 1.8) ** 2)
    ) * (pace / _TRAFFIC_PACE[3])
    # Night discount: free-flowing roads after 22:00 / before 05:00.
    night = np.where((h >= 22.0) | (h <= 5.0), 0.85, 1.0)
    weekend = np.where(weekday >= 5, 0.88, 1.0)
    # Driver-age U-curve, mild: fastest around 35.
    age = driver_age.astype(np.float64)
    age_mult = 1.0 + 0.00035 * (age - 35.0) ** 2
    # Long hauls spend a larger share on arterials: pace decays toward 65%
    # of the urban pace as distance grows.
    dist = distance_km.astype(np.float64)
    arterial = 0.65 + 0.35 * np.exp(-dist / 18.0)
    travel = dist * pace * arterial * rush * night * weekend * wmult * age_mult
    return HANDLING_OVERHEAD_MIN + travel


def generate_dataset(
    n: int,
    seed: int = 0,
    unknown_frac: float = 0.03,
    noise_sigma: Optional[float] = None,
) -> Dict[str, np.ndarray]:
    """Sample n delivery records.

    ``unknown_frac`` of rows get out-of-vocabulary weather/traffic
    (index -1, like "Fog"), exercising the all-zero one-hot path the
    reference exhibits for unknown categories.
    """
    rng = np.random.default_rng(seed)
    sigma = NOISE_SIGMA if noise_sigma is None else noise_sigma

    weather_idx = rng.integers(0, len(WEATHER_CATEGORIES), size=n).astype(np.int32)
    traffic_idx = rng.integers(0, len(TRAFFIC_CATEGORIES), size=n).astype(np.int32)
    unk_w = rng.random(n) < unknown_frac
    unk_t = rng.random(n) < unknown_frac
    weather_idx[unk_w] = -1
    traffic_idx[unk_t] = -1

    weekday = rng.integers(0, 7, size=n).astype(np.int32)
    # Deliveries cluster in business hours: mixture of daytime normal and
    # uniform tail.
    day = np.clip(rng.normal(13.0, 4.0, size=n), 0, 23)
    uni = rng.uniform(0, 24, size=n)
    hour = np.where(rng.random(n) < 0.85, day, uni).astype(np.int32)

    # Urban delivery leg lengths: log-normal, clipped to [0.3, 80] km
    # (Metro Manila scale — cf. the 21 seed sites spanning ~30 km).
    distance_km = np.clip(rng.lognormal(1.7, 0.75, size=n), 0.3, 80.0).astype(np.float32)
    driver_age = np.clip(rng.normal(36.0, 9.0, size=n), 18.0, 65.0).astype(np.float32)

    eta_true = true_eta_minutes(weather_idx, traffic_idx, weekday, hour, distance_km, driver_age)
    noise = rng.lognormal(mean=0.0, sigma=sigma, size=n)
    eta_minutes = (eta_true * noise).astype(np.float32)

    return {
        "weather_idx": weather_idx,
        "traffic_idx": traffic_idx,
        "weekday": weekday,
        "hour": hour,
        "distance_km": distance_km,
        "driver_age": driver_age,
        "eta_minutes": eta_minutes,
        "eta_true": eta_true.astype(np.float32),
    }


def train_eval_split(data: Dict[str, np.ndarray], eval_frac: float = 0.1,
                     seed: int = 1):
    n = len(data["eta_minutes"])
    rng = np.random.default_rng(seed)
    perm = rng.permutation(n)
    n_eval = max(1, int(n * eval_frac))
    eval_idx, train_idx = perm[:n_eval], perm[n_eval:]
    take = lambda idx: {k: v[idx] for k, v in data.items()}
    return take(train_idx), take(eval_idx)
