from routest_tpu.data.features import (  # noqa: F401
    FEATURE_NAMES,
    N_FEATURES,
    TRAFFIC_CATEGORIES,
    WEATHER_CATEGORIES,
    encode_features,
    encode_request,
    encode_requests,
    vocab_index,
)
from routest_tpu.data.locations import SEED_LOCATIONS, locations_table  # noqa: F401
