"""On-device geodesic math: haversine matrices, polylines, road heuristics.

The reference outsources all of this to OpenRouteService / OSRM over HTTPS
(``Flaskr/utils.py:55,97,151``). Here the distance matrix is one fused XLA
computation on device — the host↔accelerator boundary replaces the
service↔ORS HTTP boundary (SURVEY.md §5.8) — with per-profile road-factor
and speed heuristics standing in for real road network traversal (a static
road graph is the planned upgrade; SURVEY.md §7.3 item 5).
"""

from __future__ import annotations

from typing import Dict, Tuple

import jax.numpy as jnp
import numpy as np

EARTH_RADIUS_M = 6_371_008.8

# Vehicle-type → routing profile, as the reference maps them
# (``Flaskr/utils.py:22-29``).
VEHICLE_PROFILES: Dict[str, str] = {
    "car": "driving-car",
    "truck": "driving-hgv",
    "hgv": "driving-hgv",
    "motorcycle": "driving-car",
    "bike": "cycling-regular",
    "roadbike": "cycling-road",
    "foot": "foot-walking",
}
DEFAULT_PROFILE = "driving-car"

# Heuristic stand-ins for a road engine: straight-line→road-network
# inflation factor and mean speed (m/s) per profile. Metro Manila urban
# grid detour factors are typically 1.3-1.5.
PROFILE_ROAD_FACTOR: Dict[str, float] = {
    "driving-car": 1.42,
    "driving-hgv": 1.48,
    "cycling-regular": 1.38,
    "cycling-road": 1.35,
    "foot-walking": 1.25,
}
PROFILE_SPEED_MPS: Dict[str, float] = {
    "driving-car": 8.3,      # ~30 km/h urban average
    "driving-hgv": 6.9,
    "cycling-regular": 4.2,
    "cycling-road": 5.5,
    "foot-walking": 1.4,
}


def profile_for_vehicle(vehicle_type: str) -> str:
    return VEHICLE_PROFILES.get((vehicle_type or "car").lower().strip(), DEFAULT_PROFILE)


def haversine_m(lat1, lon1, lat2, lon2):
    """Great-circle distance in meters; works elementwise on jnp arrays."""
    lat1, lon1, lat2, lon2 = (jnp.radians(x) for x in (lat1, lon1, lat2, lon2))
    dlat = lat2 - lat1
    dlon = lon2 - lon1
    a = jnp.sin(dlat / 2.0) ** 2 + jnp.cos(lat1) * jnp.cos(lat2) * jnp.sin(dlon / 2.0) ** 2
    return 2.0 * EARTH_RADIUS_M * jnp.arcsin(jnp.sqrt(jnp.clip(a, 0.0, 1.0)))


def distance_matrix_m(points_latlon: jnp.ndarray, road_factor: float = 1.0) -> jnp.ndarray:
    """(N, 2) [lat, lon] → (N, N) pairwise road-ish distance in meters.

    One broadcasted haversine — the on-device replacement for the ORS
    matrix call (``Flaskr/utils.py:97-103``); O(N²) but N is tiny per
    problem; batching across problems is where the mesh parallelism goes.
    """
    lat = points_latlon[:, 0]
    lon = points_latlon[:, 1]
    d = haversine_m(lat[:, None], lon[:, None], lat[None, :], lon[None, :])
    return d * road_factor


def great_circle_interpolate(p0: Tuple[float, float], p1: Tuple[float, float],
                             n_points: int) -> np.ndarray:
    """Host-side densified polyline between two [lat, lon] points.

    Returns (n_points, 2) as [lon, lat] — GeoJSON coordinate order, which
    is what the reference's combined Feature geometry uses
    (``Flaskr/utils.py:162,180``).
    """
    lat0, lon0 = np.radians(p0[0]), np.radians(p0[1])
    lat1, lon1 = np.radians(p1[0]), np.radians(p1[1])
    d = 2.0 * np.arcsin(
        np.sqrt(
            np.clip(
                np.sin((lat1 - lat0) / 2.0) ** 2
                + np.cos(lat0) * np.cos(lat1) * np.sin((lon1 - lon0) / 2.0) ** 2,
                0.0,
                1.0,
            )
        )
    )
    t = np.linspace(0.0, 1.0, max(2, n_points))
    if d < 1e-9:
        lats = np.full_like(t, p0[0])
        lons = np.full_like(t, p0[1])
    else:
        a = np.sin((1.0 - t) * d) / np.sin(d)
        b = np.sin(t * d) / np.sin(d)
        x = a * np.cos(lat0) * np.cos(lon0) + b * np.cos(lat1) * np.cos(lon1)
        y = a * np.cos(lat0) * np.sin(lon0) + b * np.cos(lat1) * np.sin(lon1)
        z = a * np.sin(lat0) + b * np.sin(lat1)
        lats = np.degrees(np.arctan2(z, np.sqrt(x * x + y * y)))
        lons = np.degrees(np.arctan2(y, x))
    return np.stack([lons, lats], axis=-1)


def bearing_deg(p0: Tuple[float, float], p1: Tuple[float, float]) -> float:
    """Initial bearing from p0 to p1 (degrees, [lat, lon] inputs)."""
    lat0, lon0 = np.radians(p0[0]), np.radians(p0[1])
    lat1, lon1 = np.radians(p1[0]), np.radians(p1[1])
    dlon = lon1 - lon0
    x = np.sin(dlon) * np.cos(lat1)
    y = np.cos(lat0) * np.sin(lat1) - np.sin(lat0) * np.cos(lat1) * np.cos(dlon)
    return float((np.degrees(np.arctan2(x, y)) + 360.0) % 360.0)
