"""The 12-feature ETA input encoding, vectorized for TPU.

Feature contract (order and semantics) mirrors the reference's only ground
truth about its model input, ``Flaskr/ml.py:35-48`` (SURVEY.md Appendix B):

``weather_Cloudy, weather_Stormy, weather_Sunny, weather_Windy,
traffic_High, traffic_Jam, traffic_Low, traffic_Medium,
weekday_ordered (0-6), hour_ordered (0-23), distance_km, driver_age``

One-hots encode *unknown* category values (e.g. weather "Fog") as all-zeros
in their group — ``jax.nn.one_hot`` with index -1 gives exactly that.
The reference builds one pandas row per HTTP request; here the encoder is a
pure ``jnp`` transform over whole OD batches so it fuses into the model's
first matmul under jit.
"""

from __future__ import annotations

from typing import Iterable, Mapping, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

WEATHER_CATEGORIES: tuple = ("Cloudy", "Stormy", "Sunny", "Windy")
TRAFFIC_CATEGORIES: tuple = ("High", "Jam", "Low", "Medium")

FEATURE_NAMES: tuple = tuple(
    [f"weather_{w}" for w in WEATHER_CATEGORIES]
    + [f"traffic_{t}" for t in TRAFFIC_CATEGORIES]
    + ["weekday_ordered", "hour_ordered", "distance_km", "driver_age"]
)
N_FEATURES = len(FEATURE_NAMES)  # 12

# Defaults match the reference endpoints (``Flaskr/routes.py:103-104,371-372``).
DEFAULT_WEATHER = "Sunny"
DEFAULT_TRAFFIC = "Low"
DEFAULT_DRIVER_AGE = 30.0


def vocab_index(values: Iterable[str], vocab: Sequence[str]) -> np.ndarray:
    """Host-side string→index; unknown values map to -1 (⇒ all-zero one-hot)."""
    lookup = {v: i for i, v in enumerate(vocab)}
    return np.asarray([lookup.get(v, -1) for v in values], dtype=np.int32)


def encode_features(
    weather_idx: jax.Array,
    traffic_idx: jax.Array,
    weekday: jax.Array,
    hour: jax.Array,
    distance_km: jax.Array,
    driver_age: jax.Array,
    dtype=jnp.float32,
) -> jax.Array:
    """(N,) index/scalar arrays → (N, 12) feature matrix.

    Pure jnp; safe under jit/vmap/pjit. Index -1 in either categorical
    column produces an all-zero one-hot group, matching the reference's
    handling of unknown categories.
    """
    weather_oh = jax.nn.one_hot(weather_idx, len(WEATHER_CATEGORIES), dtype=dtype)
    traffic_oh = jax.nn.one_hot(traffic_idx, len(TRAFFIC_CATEGORIES), dtype=dtype)
    scalars = jnp.stack(
        [
            weekday.astype(dtype),
            hour.astype(dtype),
            distance_km.astype(dtype),
            driver_age.astype(dtype),
        ],
        axis=-1,
    )
    return jnp.concatenate([weather_oh, traffic_oh, scalars], axis=-1)


def encode_requests(
    weather: Sequence[str],
    traffic: Sequence[str],
    weekday: Sequence[int],
    hour: Sequence[int],
    distance_km: Sequence[float],
    driver_age: Sequence[float],
) -> np.ndarray:
    """Host-side batch encode (numpy in, numpy out) — the serving path's
    pre-device step. Kept in numpy so the batcher can concatenate cheaply
    before a single device transfer."""
    return batch_from_mapping(
        {
            "weather_idx": vocab_index(weather, WEATHER_CATEGORIES),
            "traffic_idx": vocab_index(traffic, TRAFFIC_CATEGORIES),
            "weekday": weekday,
            "hour": hour,
            "distance_km": distance_km,
            "driver_age": driver_age,
        }
    )


def encode_request(
    *,
    weather: Optional[str] = None,
    traffic: Optional[str] = None,
    distance_m: float = 0.0,
    weekday: int = 0,
    hour: int = 0,
    driver_age: Optional[float] = None,
) -> np.ndarray:
    """Single request → (1, 12) row, applying the reference's defaults."""
    return encode_requests(
        weather=[weather or DEFAULT_WEATHER],
        traffic=[traffic or DEFAULT_TRAFFIC],
        weekday=[weekday],
        hour=[hour],
        distance_km=[float(distance_m or 0.0) / 1000.0],
        driver_age=[float(driver_age) if driver_age is not None else DEFAULT_DRIVER_AGE],
    )


def batch_from_mapping(batch: Mapping[str, np.ndarray]) -> np.ndarray:
    """Dataset-dict (synthetic.py schema) → (N, 12) features.

    Host-side featurization used by the training loop, the serving
    batcher, and the CPU baseline — no device round-trip for a
    one-hot/concat. Uses the native encoder (``routest_tpu/native``,
    single C pass) when the toolchain is available, numpy otherwise;
    ``ROUTEST_NATIVE=0`` forces numpy.
    """
    from routest_tpu import native

    if native.available():
        return native.encode_batch(
            np.asarray(batch["weather_idx"]), np.asarray(batch["traffic_idx"]),
            np.asarray(batch["weekday"]), np.asarray(batch["hour"]),
            np.asarray(batch["distance_km"]), np.asarray(batch["driver_age"]))
    w = np.asarray(batch["weather_idx"], dtype=np.int64)
    t = np.asarray(batch["traffic_idx"], dtype=np.int64)
    n = len(w)
    out = np.zeros((n, N_FEATURES), dtype=np.float32)
    rows = np.arange(n)
    valid_w = w >= 0
    out[rows[valid_w], w[valid_w]] = 1.0
    valid_t = t >= 0
    out[rows[valid_t], len(WEATHER_CATEGORIES) + t[valid_t]] = 1.0
    base = len(WEATHER_CATEGORIES) + len(TRAFFIC_CATEGORIES)
    out[:, base + 0] = np.asarray(batch["weekday"], dtype=np.float32)
    out[:, base + 1] = np.asarray(batch["hour"], dtype=np.float32)
    out[:, base + 2] = np.asarray(batch["distance_km"], dtype=np.float32)
    out[:, base + 3] = np.asarray(batch["driver_age"], dtype=np.float32)
    return out
