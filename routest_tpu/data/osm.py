"""OSM-format road-network ingest → the road-graph dict schema.

The reference rents its street network from ORS/OSRM SaaS
(``Flaskr/utils.py:55,97,151``); this framework routes on-device over a
graph dict (``optimize/road_router.py``). Round 1 could only *generate*
synthetic networks — this module closes the real-streets path: parse an
OpenStreetMap XML extract (``.osm``, optionally gzipped) into the same
flat-array schema, so ``RoadRouter(graph=load_osm(path))`` routes over
actual street geometry. The synthetic generator remains the default for
hermetic environments.

Parsing model (stdlib ``xml.etree.iterparse``, element-by-element so a
city extract does not balloon host memory):

- ``<node id lat lon>`` — coordinate store;
- ``<way>`` with a ``highway`` tag in the drivable set — split into one
  edge per consecutive ``<nd>`` pair (finest granularity: every bend is
  a graph vertex, lengths are true haversine);
- ``oneway=yes/-1`` respected, ``junction=roundabout/circular``
  implies one-way when no explicit tag; everything else symmetrized;
- ``maxspeed`` parsed ("50", "50 km/h", "30 mph"), else the class
  default; highway class mapped onto the 3-class scheme the GNN and
  free-flow pricer share (arterial / collector / local).

Only nodes referenced by kept ways survive, re-indexed contiguously.
"""

from __future__ import annotations

import gzip
import math
import os
import xml.etree.ElementTree as ET
from typing import Dict, IO, Tuple

import numpy as np

from routest_tpu.data.road_graph import _CLASS_SPEED_MPS, haversine_np

# highway=* → road class (0 arterial, 1 collector, 2 local).
_HIGHWAY_CLASS = {
    "motorway": 0, "motorway_link": 0, "trunk": 0, "trunk_link": 0,
    "primary": 0, "primary_link": 0,
    "secondary": 1, "secondary_link": 1, "tertiary": 1, "tertiary_link": 1,
    "unclassified": 2, "residential": 2, "living_street": 2, "service": 2,
}

_MPH_TO_MPS = 0.44704
_KMH_TO_MPS = 1.0 / 3.6


def _parse_maxspeed(value: str) -> float:
    """OSM maxspeed text → m/s; raises ValueError on non-numeric forms
    (``"walk"``, ``"none"``, zone refs) so the caller falls back.

    Deliberately stricter than bare ``float()``: hex forms, digit
    underscores, and inf/nan are rejected too — they never appear in
    real OSM data, and the native scanner
    (``native/fastfeat.cpp:parse_float``) applies the identical rule so
    the two paths stay observably identical."""

    def strict(text: str) -> float:
        if not text or any(c not in "0123456789.+-eE" for c in text):
            raise ValueError(f"non-numeric maxspeed: {text!r}")
        out = float(text)
        if not math.isfinite(out):
            raise ValueError(f"non-finite maxspeed: {text!r}")
        return out

    text = value.strip().lower()
    if text.endswith("mph"):
        return strict(text[:-3].strip()) * _MPH_TO_MPS
    if text.endswith("km/h"):
        text = text[:-4].strip()
    return strict(text) * _KMH_TO_MPS


def _open(path: str) -> IO[bytes]:
    if path.endswith(".gz"):
        return gzip.open(path, "rb")
    return open(path, "rb")


def load_osm(path: str) -> Dict[str, np.ndarray]:
    """Parse an OSM XML extract into the road-graph dict schema.

    Returns the arrays ``RoadRouter`` consumes: ``node_coords`` (N, 2)
    lat/lon, ``senders``/``receivers``/``length_m``/``road_class``/
    ``speed_limit`` (E,). Raises ValueError for malformed XML or an
    extract with no drivable ways.
    """
    if not os.path.exists(path):
        raise FileNotFoundError(path)

    # Native fast path (routest_tpu/native: C++ scanner, ~10× the
    # ElementTree walk on metro extracts): exact-parity semantics,
    # verified by tests; ANY parser anomaly returns None and this
    # function proceeds with the ElementTree path below, which owns the
    # slow-path semantics and all error messages. ROUTEST_NATIVE=0
    # disables, like every native path.
    from routest_tpu import native

    if native.available():
        # The scanner needs the (decompressed) bytes in memory; cap the
        # slurp so a country-scale extract streams through the O(1)-
        # memory ElementTree path below instead of ballooning host RSS.
        try:
            cap = int(os.environ.get("ROUTEST_NATIVE_OSM_MAX_BYTES",
                                     str(256 * 1024 * 1024)))
        except ValueError:  # malformed knob degrades like every other
            cap = 256 * 1024 * 1024
        with _open(path) as f:
            buf = f.read(cap + 1)
        parsed = (native.parse_osm(buf, _CLASS_SPEED_MPS)
                  if len(buf) <= cap else None)
        del buf
        if parsed is not None:
            senders = parsed["senders"]
            receivers = parsed["receivers"]
            node_coords = parsed["node_coords"]
            parsed["length_m"] = haversine_np(
                node_coords[senders, 0], node_coords[senders, 1],
                node_coords[receivers, 0], node_coords[receivers, 1],
            ).astype(np.float32)
            return parsed

    coords: Dict[int, Tuple[float, float]] = {}
    # per edge: (from_osm_id, to_osm_id, road_class, speed, both_ways)
    segments = []

    way_nodes = []
    way_tags: Dict[str, str] = {}
    root = None
    try:
        with _open(path) as f:
            for event, elem in ET.iterparse(f, events=("start", "end")):
                if event == "start":
                    if root is None:
                        root = elem  # the <osm> element accumulates children
                    if elem.tag == "way":
                        way_nodes = []
                        way_tags = {}
                    continue
                if elem.tag == "node":
                    try:
                        coords[int(elem.get("id"))] = (
                            float(elem.get("lat")), float(elem.get("lon")))
                    except (TypeError, ValueError):
                        pass  # nodes without coordinates cannot carry edges
                elif elem.tag == "nd":
                    ref = elem.get("ref")
                    if ref is not None:
                        way_nodes.append(int(ref))
                elif elem.tag == "tag":
                    k, v = elem.get("k"), elem.get("v")
                    if k is not None and v is not None:
                        way_tags[k] = v
                elif elem.tag == "way":
                    _ingest_way(way_nodes, way_tags, segments)
                # elem.clear() alone is NOT enough: the root keeps an
                # (emptied) child per element, linear in file size. Drop
                # completed top-level children from the root itself so a
                # metro extract streams in O(1) element memory.
                if root is not None and elem is not root:
                    elem.clear()
                    if len(root) and root[-1] is elem:
                        del root[-1]
    except ET.ParseError as e:
        raise ValueError(f"{path}: malformed OSM XML: {e}") from None

    if not segments:
        raise ValueError(f"{path}: no drivable highway ways found")

    # Compact referenced nodes → contiguous indices.
    used = sorted({n for s in segments for n in s[:2] if n in coords})
    index = {osm_id: i for i, osm_id in enumerate(used)}
    node_coords = np.asarray([coords[i] for i in used], np.float32)

    senders, receivers, road_class, speed = [], [], [], []
    for a, b, cls, spd, both in segments:
        if a not in index or b not in index or a == b:
            continue  # refs outside the extract boundary
        senders.append(index[a])
        receivers.append(index[b])
        road_class.append(cls)
        speed.append(spd)
        if both:
            senders.append(index[b])
            receivers.append(index[a])
            road_class.append(cls)
            speed.append(spd)

    if not senders:
        raise ValueError(f"{path}: drivable ways reference no in-extract nodes")

    senders = np.asarray(senders, np.int32)
    receivers = np.asarray(receivers, np.int32)
    length_m = haversine_np(
        node_coords[senders, 0], node_coords[senders, 1],
        node_coords[receivers, 0], node_coords[receivers, 1],
    ).astype(np.float32)
    return {
        "node_coords": node_coords,
        "senders": senders,
        "receivers": receivers,
        "length_m": length_m,
        "road_class": np.asarray(road_class, np.int32),
        "speed_limit": np.asarray(speed, np.float32),
    }


# road class → representative highway tag (inverse of _HIGHWAY_CLASS for
# the writer; load_osm maps these back to the same class).
_CLASS_HIGHWAY = {0: "primary", 1: "secondary", 2: "residential"}


def save_osm(path: str, graph: Dict[str, np.ndarray]) -> None:
    """Inverse of :func:`load_osm`: write a road-graph dict as an OSM XML
    extract (gzipped when ``path`` ends in ``.gz``).

    Every directed edge becomes a two-node ``oneway`` way carrying its
    class (highway tag) and speed (maxspeed, km/h), so topology, classes
    and speed limits round-trip exactly. Lengths do NOT: ``load_osm``
    recomputes pure haversine from coordinates, while generated graphs
    carry a street-detour factor in ``length_m`` — a property of their
    lengths, not their geometry. Used to exercise the real-extract
    ingest path at metro scale without shipping a real (licensed) city
    extract.
    """
    coords = np.asarray(graph["node_coords"], np.float64)
    senders = np.asarray(graph["senders"])
    receivers = np.asarray(graph["receivers"])
    road_class = np.asarray(graph["road_class"])
    speed = np.asarray(graph["speed_limit"], np.float64)

    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "wt", encoding="utf-8") as f:
        f.write('<?xml version="1.0" encoding="UTF-8"?>\n')
        f.write('<osm version="0.6" generator="routest_tpu.data.osm">\n')
        for i, (lat, lon) in enumerate(coords):
            f.write(f'  <node id="{i + 1}" lat="{lat:.7f}" '
                    f'lon="{lon:.7f}"/>\n')
        for e in range(len(senders)):
            highway = _CLASS_HIGHWAY[int(road_class[e])]
            kmh = speed[e] * 3.6
            f.write(
                f'  <way id="{len(coords) + e + 1}">\n'
                f'    <nd ref="{int(senders[e]) + 1}"/>\n'
                f'    <nd ref="{int(receivers[e]) + 1}"/>\n'
                f'    <tag k="highway" v="{highway}"/>\n'
                f'    <tag k="maxspeed" v="{kmh:.8g}"/>\n'
                f'    <tag k="oneway" v="yes"/>\n'
                f'  </way>\n')
        f.write("</osm>\n")


def _ingest_way(way_nodes, way_tags, segments) -> None:
    highway = way_tags.get("highway")
    cls = _HIGHWAY_CLASS.get(highway) if highway else None
    if cls is None or len(way_nodes) < 2:
        return
    speed = float(_CLASS_SPEED_MPS[cls])
    if "maxspeed" in way_tags:
        try:
            speed = _parse_maxspeed(way_tags["maxspeed"])
        except ValueError:
            pass  # non-numeric maxspeed: keep the class default
    oneway_tag = way_tags.get("oneway")
    if oneway_tag is None and way_tags.get("junction", "").lower() in (
            "roundabout", "circular"):
        # OSM semantics: junction=roundabout implies oneway=yes in
        # drawing order unless an explicit oneway tag overrides it.
        oneway_tag = "yes"
    oneway = (oneway_tag or "no").lower()
    pairs = zip(way_nodes[:-1], way_nodes[1:])
    if oneway == "-1":  # rare: oneway against drawing direction
        pairs = zip(way_nodes[1:], way_nodes[:-1])
    both = oneway not in ("yes", "true", "1", "-1")
    for a, b in pairs:
        segments.append((a, b, cls, speed, both))
