"""Canonical demo dataset: 21 Metro Manila sites.

Same facts the reference seeds into its ``locations`` table
(``backend/laravel/database/seeders/LocationsTableSeeder.php:13-35``):
one warehouse origin plus twenty malls. UUIDs here are deterministic
(uuid5 of the name) so hermetic tests and the in-memory store are stable
across runs, unlike the reference's random-per-seed uuid4s.
"""

from __future__ import annotations

import uuid
from typing import Dict, List, Tuple

import numpy as np

_NAMESPACE = uuid.UUID("9f2c1a34-7b1d-4c5e-9a61-0d4f2b8a6c33")

SEED_LOCATIONS: Tuple[Tuple[str, float, float], ...] = (
    ("Main Warehouse - Mandaluyong", 14.5836, 121.0409),
    ("SM Mall of Asia", 14.5352, 120.9822),
    ("Greenbelt Mall", 14.5516, 121.0233),
    ("SM Megamall", 14.5833, 121.0567),
    ("Market! Market!", 14.5536, 121.0546),
    ("Robinsons Galleria", 14.5896, 121.0614),
    ("SM North EDSA", 14.6556, 121.0313),
    ("Trinoma Mall", 14.6537, 121.0321),
    ("Gateway Mall", 14.6206, 121.0526),
    ("SM City Manila", 14.5881, 120.9814),
    ("Lucky Chinatown Mall", 14.6054, 120.9734),
    ("SM Aura Premier", 14.5456, 121.0559),
    ("Robinsons Place Manila", 14.5730, 120.9820),
    ("Ayala Malls Vertis North", 14.6543, 121.0327),
    ("Fisher Mall", 14.6300, 121.0045),
    ("SM City Sta. Mesa", 14.6031, 121.0275),
    ("Alabang Town Center", 14.4269, 121.0314),
    ("Festival Mall Alabang", 14.4143, 121.0438),
    ("Eastwood Mall", 14.6101, 121.0791),
    ("Robinsons Magnolia", 14.6162, 121.0336),
    ("Venice Grand Canal Mall", 14.5404, 121.0530),
)


def location_id(name: str) -> str:
    return str(uuid.uuid5(_NAMESPACE, name))


def locations_table() -> List[Dict]:
    """Rows shaped like Laravel's ``GET /api/locations`` response
    (``routes/api.php:7-9``: id, name, latitude, longitude, created_at)."""
    return [
        {
            "id": location_id(name),
            "name": name,
            "latitude": lat,
            "longitude": lon,
            "created_at": "2025-08-12T14:40:39+00:00",
        }
        for name, lat, lon in SEED_LOCATIONS
    ]


def coords_array() -> np.ndarray:
    """(21, 2) [lat, lon] array for on-device distance matrices."""
    return np.asarray([[lat, lon] for _, lat, lon in SEED_LOCATIONS], dtype=np.float32)
