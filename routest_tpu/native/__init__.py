"""ctypes bindings for the native data-plane library (fastfeat.cpp).

Build-on-first-use: ``load()`` compiles the shared library with g++ into
a content-addressed cache (so edits to the .cpp invalidate stale builds)
and binds the C ABI. Everything here degrades gracefully — ``load()``
returns None when no toolchain is available and callers fall back to the
numpy implementations, keeping the framework pure-Python-installable
(SURVEY.md §2: the reference has zero native components; this library is
additive runtime, never a dependency).
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import threading
from typing import Optional

import numpy as np

_SRC = os.path.join(os.path.dirname(os.path.abspath(__file__)), "fastfeat.cpp")
_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False


def _cache_dir() -> Optional[str]:
    """Per-user 0700 cache dir. The .so path must not be forgeable by
    another local user (a planted library would be dlopen'd into this
    process), so anything not owned by us / group- or world-writable is
    rejected (shared policy: ``utils/paths.secure_user_cache_dir``).
    ROUTEST_NATIVE_CACHE overrides (explicit operator choice)."""
    base = os.environ.get("ROUTEST_NATIVE_CACHE")
    if base:
        try:
            os.makedirs(base, exist_ok=True)
        except OSError:
            return None  # unusable override: fall back to numpy, not a crash
        return base
    from routest_tpu.utils.paths import secure_user_cache_dir

    return secure_user_cache_dir("routest_tpu_native")


def _build() -> Optional[str]:
    cache = _cache_dir()
    if cache is None:
        return None
    with open(_SRC, "rb") as f:
        src = f.read()
    tag = hashlib.sha256(src).hexdigest()[:16]
    out = os.path.join(cache, f"fastfeat-{tag}.so")
    if os.path.exists(out):
        return out
    tmp = out + f".tmp{os.getpid()}"
    cmd = ["g++", "-O3", "-shared", "-fPIC", "-std=c++17", _SRC, "-o", tmp]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        os.replace(tmp, out)  # atomic: concurrent builders race benignly
        return out
    except (OSError, subprocess.SubprocessError):
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return None


def load() -> Optional[ctypes.CDLL]:
    """The bound library, building it if needed; None when unavailable."""
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if os.environ.get("ROUTEST_NATIVE") == "0":
            return None
        path = _build()
        if path is None:
            return None
        try:
            lib = ctypes.CDLL(path)
        except OSError:
            return None
        i32p = np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS")
        f32p = np.ctypeslib.ndpointer(np.float32, flags="C_CONTIGUOUS")
        lib.ff_abi_version.restype = ctypes.c_int
        lib.ff_encode_batch.argtypes = [
            i32p, i32p, i32p, i32p, f32p, f32p, ctypes.c_int64, f32p]
        lib.ff_count_rows.argtypes = [ctypes.c_char_p]
        lib.ff_count_rows.restype = ctypes.c_int64
        lib.ff_parse_csv.argtypes = [
            ctypes.c_char_p,
            ctypes.c_char_p, ctypes.c_int, ctypes.c_char_p, ctypes.c_int,
            ctypes.c_int64,
            i32p, i32p, i32p, i32p, f32p, f32p, f32p,
            ctypes.POINTER(ctypes.c_int64)]
        lib.ff_parse_csv.restype = ctypes.c_int64
        lib.ff_osm_parse.argtypes = [
            ctypes.c_char_p, ctypes.c_int64,
            np.ctypeslib.ndpointer(np.float32, flags="C_CONTIGUOUS")]
        lib.ff_osm_parse.restype = ctypes.POINTER(_FfOsmResult)
        lib.ff_osm_free.argtypes = [ctypes.POINTER(_FfOsmResult)]
        if lib.ff_abi_version() != 2:
            return None
        _lib = lib
        return _lib


def available() -> bool:
    return load() is not None


def encode_batch(weather_idx: np.ndarray, traffic_idx: np.ndarray,
                 weekday: np.ndarray, hour: np.ndarray,
                 distance_km: np.ndarray, driver_age: np.ndarray) -> np.ndarray:
    """Native 12-feature encode; caller guarantees ``available()``."""
    lib = load()
    assert lib is not None, "native library unavailable"
    n = len(weather_idx)
    out = np.empty((n, 12), np.float32)
    lib.ff_encode_batch(
        np.ascontiguousarray(weather_idx, np.int32),
        np.ascontiguousarray(traffic_idx, np.int32),
        np.ascontiguousarray(weekday, np.int32),
        np.ascontiguousarray(hour, np.int32),
        np.ascontiguousarray(distance_km, np.float32),
        np.ascontiguousarray(driver_age, np.float32),
        n, out)
    return out


class _FfOsmResult(ctypes.Structure):
    _fields_ = [
        ("code", ctypes.c_int32),
        ("n_nodes", ctypes.c_int32),
        ("n_edges", ctypes.c_int64),
        ("lat", ctypes.POINTER(ctypes.c_double)),
        ("lon", ctypes.POINTER(ctypes.c_double)),
        ("senders", ctypes.POINTER(ctypes.c_int32)),
        ("receivers", ctypes.POINTER(ctypes.c_int32)),
        ("cls", ctypes.POINTER(ctypes.c_int32)),
        ("speed", ctypes.POINTER(ctypes.c_float)),
    ]


def parse_osm(buf: bytes, class_speed_mps) -> Optional[dict]:
    """Native OSM XML parse → partial road-graph dict (topology, classes,
    speeds; lengths are computed by the caller from coordinates, same as
    the Python path). Returns None when the parser reports ANY anomaly —
    the caller falls back to the ElementTree path, which owns both the
    slow-path semantics and the error messages. Caller guarantees
    ``available()``."""
    lib = load()
    assert lib is not None, "native library unavailable"
    speeds = np.ascontiguousarray(class_speed_mps, np.float32)
    assert len(speeds) == 3
    ptr = lib.ff_osm_parse(buf, len(buf), speeds)
    if not ptr:
        return None
    try:
        r = ptr.contents
        if r.code != 0 or r.n_edges == 0:
            return None
        n, e = int(r.n_nodes), int(r.n_edges)
        lat = np.ctypeslib.as_array(r.lat, (n,)).copy()
        lon = np.ctypeslib.as_array(r.lon, (n,)).copy()
        out = {
            "node_coords": np.stack([lat, lon], axis=1).astype(np.float32),
            "senders": np.ctypeslib.as_array(r.senders, (e,)).copy(),
            "receivers": np.ctypeslib.as_array(r.receivers, (e,)).copy(),
            "road_class": np.ctypeslib.as_array(r.cls, (e,)).copy(),
            "speed_limit": np.ctypeslib.as_array(r.speed, (e,)).copy(),
        }
        return out
    finally:
        lib.ff_osm_free(ptr)


def _pack_vocab(vocab) -> bytes:
    return b"".join(v.encode() + b"\0" for v in vocab)


def parse_csv(path: str, weather_vocab, traffic_vocab):
    """Native CSV ingest → dataset-dict columns. Caller guarantees
    ``available()``. Raises ValueError with the offending line on
    malformed rows (same contract as the Python fallback)."""
    lib = load()
    assert lib is not None, "native library unavailable"
    cap = lib.ff_count_rows(path.encode())
    if cap < 0:
        raise FileNotFoundError(path)
    cols = {
        "weather_idx": np.empty(cap, np.int32),
        "traffic_idx": np.empty(cap, np.int32),
        "weekday": np.empty(cap, np.int32),
        "hour": np.empty(cap, np.int32),
        "distance_km": np.empty(cap, np.float32),
        "driver_age": np.empty(cap, np.float32),
        "eta_minutes": np.empty(cap, np.float32),
    }
    err_line = ctypes.c_int64(0)
    n = lib.ff_parse_csv(
        path.encode(),
        _pack_vocab(weather_vocab), len(weather_vocab),
        _pack_vocab(traffic_vocab), len(traffic_vocab),
        cap,
        cols["weather_idx"], cols["traffic_idx"], cols["weekday"],
        cols["hour"], cols["distance_km"], cols["driver_age"],
        cols["eta_minutes"], ctypes.byref(err_line))
    if n == -1:
        raise FileNotFoundError(path)
    if n == -2:
        raise ValueError(f"{path}:{err_line.value}: expected 7 fields")
    if n == -4:
        raise ValueError(f"{path}:{err_line.value}: line exceeds 4094 bytes")
    if n == -3:
        raise ValueError(f"{path}:{err_line.value}: non-numeric field")
    return {k: v[:n] for k, v in cols.items()}
