// fastfeat: native data-plane for routest_tpu.
//
// The reference outsources its data pipeline entirely (data/ and
// notebooks/ are empty; one pandas row per HTTP request in
// Flaskr/ml.py:35-51). This framework's training/serving pipeline is
// host-side numpy by default; this library is the native runtime for the
// two hot host paths, bound via ctypes (routest_tpu/native/__init__.py):
//
//   ff_encode_batch  — categorical/scalar columns -> the 12-feature ABI
//                      matrix (SURVEY.md Appendix B), row-major f32.
//   ff_parse_csv     — delivery-history CSV -> column arrays, one pass,
//                      no per-row Python objects. Schema documented in
//                      routest_tpu/data/csv_io.py.
//
// Plain C ABI (extern "C"), no Python.h dependency: the same .so loads
// from any runtime. Built on demand by native/build.py with g++ -O3.

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <cmath>

extern "C" {

// ── feature encoding ────────────────────────────────────────────────────
// Column order (SURVEY.md Appendix B, Flaskr/ml.py:35-48):
//   weather_{Cloudy,Stormy,Sunny,Windy}, traffic_{High,Jam,Low,Medium},
//   weekday_ordered, hour_ordered, distance_km, driver_age
// weather_idx/traffic_idx use -1 for unknown categories => all-zero group.
void ff_encode_batch(const int32_t* weather_idx, const int32_t* traffic_idx,
                     const int32_t* weekday, const int32_t* hour,
                     const float* distance_km, const float* driver_age,
                     int64_t n, float* out /* n x 12, row-major */) {
    for (int64_t i = 0; i < n; ++i) {
        float* row = out + i * 12;
        memset(row, 0, 12 * sizeof(float));
        const int32_t w = weather_idx[i];
        if (w >= 0 && w < 4) row[w] = 1.0f;
        const int32_t t = traffic_idx[i];
        if (t >= 0 && t < 4) row[4 + t] = 1.0f;
        row[8] = (float)weekday[i];
        row[9] = (float)hour[i];
        row[10] = distance_km[i];
        row[11] = driver_age[i];
    }
}

// ── CSV ingest ──────────────────────────────────────────────────────────
// Expected header (validated by the Python wrapper):
//   weather,traffic,weekday,hour,distance_km,driver_age,eta_minutes
// weather/traffic are category NAMES; this parser maps them against the
// vocab tables passed in (entries are NUL-separated, count given), with
// unknown -> -1, matching vocab_index() in data/features.py.

struct FFVocab {
    const char* entries[16];
    int count;
};

static void ff_build_vocab(FFVocab* v, const char* packed, int count) {
    v->count = count > 16 ? 16 : count;
    const char* p = packed;
    for (int i = 0; i < v->count; ++i) {
        v->entries[i] = p;
        p += strlen(p) + 1;
    }
}

static int ff_vocab_lookup(const FFVocab* v, const char* s, int len) {
    for (int i = 0; i < v->count; ++i) {
        if ((int)strlen(v->entries[i]) == len &&
            memcmp(v->entries[i], s, (size_t)len) == 0)
            return i;
    }
    return -1;
}

// Counts data rows (lines after the first non-empty line). Returns -1 on
// open failure. Lets the caller allocate exact-size numpy arrays.
int64_t ff_count_rows(const char* path) {
    FILE* f = fopen(path, "rb");
    if (!f) return -1;
    int64_t lines = 0;
    char buf[1 << 16];
    size_t got;
    char last = '\n';
    while ((got = fread(buf, 1, sizeof(buf), f)) > 0) {
        for (size_t i = 0; i < got; ++i)
            if (buf[i] == '\n') ++lines;
        last = buf[got - 1];
    }
    fclose(f);
    if (last != '\n') ++lines;        // unterminated final line
    return lines > 0 ? lines - 1 : 0; // minus header
}

// Parses up to `cap` data rows into the output arrays. Returns the number
// of rows parsed, or a negative error code: -1 open failure, -2 a row had
// the wrong number of fields, -3 a numeric field failed to parse (the
// offending 1-based line number is written to *err_line for -2/-3).
int64_t ff_parse_csv(const char* path,
                     const char* weather_vocab, int n_weather,
                     const char* traffic_vocab, int n_traffic,
                     int64_t cap,
                     int32_t* weather_idx, int32_t* traffic_idx,
                     int32_t* weekday, int32_t* hour,
                     float* distance_km, float* driver_age,
                     float* eta_minutes, int64_t* err_line) {
    FFVocab wv, tv;
    ff_build_vocab(&wv, weather_vocab, n_weather);
    ff_build_vocab(&tv, traffic_vocab, n_traffic);
    *err_line = 0;

    FILE* f = fopen(path, "rb");
    if (!f) return -1;

    char line[4096];
    int64_t row = 0, lineno = 0;
    bool header = true;
    while (fgets(line, sizeof(line), f)) {
        ++lineno;
        size_t len = strlen(line);
        if (len == sizeof(line) - 1 && line[len - 1] != '\n') {
            // Overlong physical line: fgets would silently split it into
            // bogus rows. No valid row in this 7-field schema approaches
            // 4 KB, so reject instead of mis-parsing. Distinct code so
            // the Python fallback can mirror the exact same contract.
            fclose(f);
            *err_line = lineno;
            return -4;
        }
        while (len && (line[len - 1] == '\n' || line[len - 1] == '\r'))
            line[--len] = '\0';
        if (len == 0) continue;
        if (header) { header = false; continue; }
        if (row >= cap) break;

        // exactly 7 comma-separated fields (6 commas), then split
        int commas = 0;
        for (size_t i = 0; i < len; ++i)
            if (line[i] == ',') ++commas;
        if (commas != 6) {
            fclose(f);
            *err_line = lineno;
            return -2;
        }
        const char* fields[7];
        int flen[7];
        int nf = 0;
        const char* start = line;
        for (size_t i = 0; i <= len; ++i) {
            if (i == len || line[i] == ',') {
                fields[nf] = start;
                flen[nf] = (int)(line + i - start);
                ++nf;
                start = line + i + 1;
            }
        }

        weather_idx[row] = ff_vocab_lookup(&wv, fields[0], flen[0]);
        traffic_idx[row] = ff_vocab_lookup(&tv, fields[1], flen[1]);

        char tmp[64];
        char* end;
        const int numeric[5] = {2, 3, 4, 5, 6};
        double vals[5];
        for (int k = 0; k < 5; ++k) {
            int fi = numeric[k];
            int l = flen[fi];
            if (l > 63) {
                // No representable value in this schema needs 64 chars;
                // reject instead of silently truncating (the Python
                // fallback enforces the same cap).
                fclose(f);
                *err_line = lineno;
                return -3;
            }
            // Strict decimal grammar, identical to the Python fallback's
            // regex: digits/sign/dot/exponent only. This rejects what
            // strtod would otherwise quietly accept beyond the shared
            // contract — leading whitespace, hex (0x10), inf/nan.
            bool ok = l > 0;
            for (int c = 0; c < l && ok; ++c) {
                char ch = fields[fi][c];
                ok = (ch >= '0' && ch <= '9') || ch == '+' || ch == '-' ||
                     ch == '.' || ch == 'e' || ch == 'E';
            }
            memcpy(tmp, fields[fi], (size_t)l);
            tmp[l] = '\0';
            vals[k] = ok ? strtod(tmp, &end) : 0.0;
            // float32 range guard: values that would overflow to inf in
            // the f32 output columns are rejected, not silently mangled.
            if (!ok || end == tmp || *end != '\0' || !std::isfinite(vals[k]) ||
                vals[k] > 3.0e38 || vals[k] < -3.0e38) {
                fclose(f);
                *err_line = lineno;
                return -3;
            }
        }
        // weekday/hour become int32: an out-of-range double->int32 cast
        // is UB in C++, so range-check instead of silently corrupting.
        if (vals[0] < -2147483647.0 || vals[0] > 2147483647.0 ||
            vals[1] < -2147483647.0 || vals[1] > 2147483647.0) {
            fclose(f);
            *err_line = lineno;
            return -3;
        }
        weekday[row] = (int32_t)vals[0];
        hour[row] = (int32_t)vals[1];
        distance_km[row] = (float)vals[2];
        driver_age[row] = (float)vals[3];
        eta_minutes[row] = (float)vals[4];
        ++row;
    }
    fclose(f);
    return row;
}

// ── OSM XML road-network parsing ───────────────────────────────────────
// Native fast path for routest_tpu/data/osm.py:load_osm — same observable
// semantics (drivable-highway filter, maxspeed parsing, oneway handling,
// used-node compaction in ascending-osm-id order, document-order edge
// emission) so the Python wrapper can assert exact parity. On ANY parse
// anomaly the parser returns a nonzero code and Python falls back to the
// ElementTree path, which owns the error messages.

}  // extern "C"

#include <algorithm>
#include <cctype>
#include <string>
#include <unordered_map>
#include <vector>

namespace {

struct OsmSegment {
    int64_t a, b;
    int32_t cls;
    float speed;
    uint8_t both;
};

// Mirrors data/osm.py:_HIGHWAY_CLASS exactly.
int32_t highway_class(const std::string& v) {
    static const std::unordered_map<std::string, int32_t> m = {
        {"motorway", 0}, {"motorway_link", 0}, {"trunk", 0},
        {"trunk_link", 0}, {"primary", 0}, {"primary_link", 0},
        {"secondary", 1}, {"secondary_link", 1}, {"tertiary", 1},
        {"tertiary_link", 1}, {"unclassified", 2}, {"residential", 2},
        {"living_street", 2}, {"service", 2},
    };
    auto it = m.find(v);
    return it == m.end() ? -1 : it->second;
}

std::string lower_strip(const std::string& s) {
    size_t b = 0, e = s.size();
    while (b < e && std::isspace((unsigned char)s[b])) ++b;
    while (e > b && std::isspace((unsigned char)s[e - 1])) --e;
    std::string out = s.substr(b, e - b);
    for (char& c : out) c = (char)std::tolower((unsigned char)c);
    return out;
}

std::string to_lower(const std::string& s) {
    std::string out = s;
    for (char& c : out) c = (char)std::tolower((unsigned char)c);
    return out;
}

// Strict decimal float parse. Deliberately NARROWER than both strtod and
// Python float(): hex forms ("0x20"), inf/nan, and digit underscores
// ("1_0") are rejected — the Python path's _parse_maxspeed applies the
// same strictness so the two stay observably identical (none of these
// forms appear in real OSM data; they only matter for parity).
// Consumed-value guards: the scanner does NOT decode XML entity
// references (ElementTree does), and strtod/strtoll accept forms
// (hex floats, "inf") that Python's parse rejects while Python accepts
// forms ("1_0") strtod rejects. Rather than reimplement either quirk
// set, any consumed value outside the boring charset makes the whole
// parse return code 1 so load_osm falls back to the ElementTree path,
// which owns the exact semantics. Display-only values (names etc.) are
// never consumed, so real extracts with "Fifth &amp; Main" street
// names keep the fast path.
bool plain_numeric(const std::string& s) {
    if (s.empty()) return false;
    for (char c : s)
        if (!((c >= '0' && c <= '9') || c == '+' || c == '-' ||
              c == '.' || c == 'e' || c == 'E'))
            return false;
    return true;
}

bool entity_free(const std::string& s) {
    return s.find('&') == std::string::npos;
}

bool parse_float(const std::string& s, double* out) {
    if (s.empty()) return false;
    for (char c : s) {
        if (!(std::isdigit((unsigned char)c) || c == '.' || c == '+' ||
              c == '-' || c == 'e' || c == 'E'))
            return false;
    }
    char* end = nullptr;
    *out = strtod(s.c_str(), &end);
    return end && *end == '\0' && std::isfinite(*out);
}

// data/osm.py:_parse_maxspeed: "50" | "50 km/h" (kmh) | "30 mph".
bool parse_maxspeed(const std::string& raw, double* mps) {
    std::string t = lower_strip(raw);
    double v;
    if (t.size() > 3 && t.compare(t.size() - 3, 3, "mph") == 0) {
        if (!parse_float(lower_strip(t.substr(0, t.size() - 3)), &v))
            return false;
        *mps = v * 0.44704;
        return true;
    }
    if (t.size() > 4 && t.compare(t.size() - 4, 4, "km/h") == 0)
        t = lower_strip(t.substr(0, t.size() - 4));
    if (!parse_float(t, &v)) return false;
    *mps = v / 3.6;
    return true;
}

struct Scanner {
    const char* p;
    const char* end;

    // Parse attributes of the tag at p (p just past the name) until the
    // closing '>'; returns false on EOF/malformation. Handles both quote
    // styles. Leaves p past '>'.
    bool attrs(std::vector<std::pair<std::string, std::string>>* out) {
        out->clear();
        while (p < end) {
            while (p < end && std::isspace((unsigned char)*p)) ++p;
            if (p >= end) return false;
            if (*p == '/' || *p == '?') { ++p; continue; }
            if (*p == '>') { ++p; return true; }
            const char* ks = p;
            while (p < end && *p != '=' && *p != '>' &&
                   !std::isspace((unsigned char)*p)) ++p;
            if (p >= end || *p != '=') return false;
            std::string key(ks, p - ks);
            ++p;
            if (p >= end || (*p != '"' && *p != '\'')) return false;
            const char q = *p++;
            const char* vs = p;
            while (p < end && *p != q) ++p;
            if (p >= end) return false;
            out->emplace_back(std::move(key), std::string(vs, p - vs));
            ++p;
        }
        return false;
    }
};

}  // namespace

extern "C" {

struct FfOsmResult {
    int32_t code;        // 0 ok; 1 malformed; 2 nothing drivable/usable
    int32_t n_nodes;
    int64_t n_edges;
    double* lat;
    double* lon;
    int32_t* senders;
    int32_t* receivers;
    int32_t* cls;
    float* speed;
};

void ff_osm_free(FfOsmResult* r) {
    if (!r) return;
    free(r->lat); free(r->lon); free(r->senders); free(r->receivers);
    free(r->cls); free(r->speed); free(r);
}

FfOsmResult* ff_osm_parse(const char* buf, int64_t len,
                          const float* class_speed /* 3 defaults, m/s */) {
    FfOsmResult* res = (FfOsmResult*)calloc(1, sizeof(FfOsmResult));
    if (!res) return nullptr;
    std::unordered_map<int64_t, std::pair<double, double>> coords;
    std::vector<OsmSegment> segments;

    Scanner sc{buf, buf + len};
    std::vector<std::pair<std::string, std::string>> at;
    bool in_way = false;
    bool root_seen = false, root_closed = false;
    std::string root_name;
    std::vector<int64_t> way_nodes;
    int32_t way_cls = -1;
    std::string way_maxspeed;      // raw LAST maxspeed tag value
    bool way_has_maxspeed = false;
    std::string way_oneway;
    bool way_has_oneway = false;
    std::string way_junction;      // junction=roundabout implies oneway

    auto flush_way = [&]() {
        if (way_cls < 0 || way_nodes.size() < 2) return;
        // Python keeps the LAST maxspeed tag and falls back to the class
        // default only if THAT value fails to parse — so parse at flush,
        // not per tag.
        double spd = (double)class_speed[way_cls];
        double mps;
        if (way_has_maxspeed && parse_maxspeed(way_maxspeed, &mps))
            spd = mps;
        // Python lowercases WITHOUT stripping ("yes " stays two-way).
        // No explicit oneway tag: junction=roundabout/circular implies
        // one-way in drawing order (data/osm.py:_ingest_way parity).
        std::string ow;
        if (way_has_oneway) {
            ow = to_lower(way_oneway);
        } else {
            std::string j = to_lower(way_junction);
            ow = (j == "roundabout" || j == "circular") ? "yes" : "no";
        }
        bool rev = ow == "-1";
        bool both = !(ow == "yes" || ow == "true" || ow == "1" || rev);
        for (size_t i = 0; i + 1 < way_nodes.size(); ++i) {
            int64_t a = way_nodes[i], b = way_nodes[i + 1];
            if (rev) { int64_t t = a; a = b; b = t; }
            segments.push_back({a, b, way_cls, (float)spd,
                                (uint8_t)(both ? 1 : 0)});
        }
    };

    while (sc.p < sc.end) {
        const char* lt = (const char*)memchr(sc.p, '<', sc.end - sc.p);
        if (!lt) break;
        sc.p = lt + 1;
        if (sc.p >= sc.end) { res->code = 1; return res; }
        if (*sc.p == '!') {  // comment/decl: skip past it wholesale so a
            // '<' inside "<!-- ... -->" can't be misread as a tag
            if (sc.end - sc.p >= 3 && sc.p[1] == '-' && sc.p[2] == '-') {
                const char* close = nullptr;
                for (const char* q = sc.p + 3; q + 2 < sc.end; ++q)
                    if (q[0] == '-' && q[1] == '-' && q[2] == '>') {
                        close = q + 3;
                        break;
                    }
                if (!close) { res->code = 1; return res; }
                sc.p = close;
            }
            continue;
        }
        if (*sc.p == '?') continue;  // xml declaration
        bool closing = *sc.p == '/';
        if (closing) ++sc.p;
        const char* ns = sc.p;
        while (sc.p < sc.end && !std::isspace((unsigned char)*sc.p) &&
               *sc.p != '>' && *sc.p != '/') ++sc.p;
        std::string name(ns, sc.p - ns);
        if (closing) {
            if (name == "way") {
                if (!in_way) { res->code = 1; return res; }
                flush_way();
                in_way = false;
            }
            if (root_seen && name == root_name) root_closed = true;
            continue;  // skip to '>' via next memchr
        }
        if (!root_seen) {
            root_seen = true;
            root_name = name;
        }
        if (!sc.attrs(&at)) { res->code = 1; return res; }
        if (name == "node") {
            int64_t id = 0; double la = 0, lo = 0;
            bool has_id = false, has_la = false, has_lo = false;
            for (auto& kv : at) {
                double v;
                if (kv.first == "id") {
                    if (!plain_numeric(kv.second)) { res->code = 1; return res; }
                    char* e = nullptr;
                    id = strtoll(kv.second.c_str(), &e, 10);
                    has_id = e && *e == '\0' && !kv.second.empty();
                } else if (kv.first == "lat" || kv.first == "lon") {
                    if (!plain_numeric(kv.second)) { res->code = 1; return res; }
                    if (parse_float(kv.second, &v)) {
                        if (kv.first == "lat") { la = v; has_la = true; }
                        else { lo = v; has_lo = true; }
                    }
                }
            }
            if (has_id && has_la && has_lo) coords[id] = {la, lo};
        } else if (name == "way") {
            if (in_way) { res->code = 1; return res; }  // unclosed way
            in_way = true;
            way_nodes.clear();
            way_cls = -1;
            way_has_maxspeed = false;
            way_oneway.clear();
            way_has_oneway = false;
            way_junction.clear();
        } else if (name == "nd" && in_way) {
            for (auto& kv : at)
                if (kv.first == "ref") {
                    if (!plain_numeric(kv.second)) { res->code = 1; return res; }
                    char* e = nullptr;
                    int64_t r = strtoll(kv.second.c_str(), &e, 10);
                    if (e && *e == '\0' && !kv.second.empty())
                        way_nodes.push_back(r);
                }
        } else if (name == "tag" && in_way) {
            std::string k, v;
            bool has_v = false;
            for (auto& kv : at) {
                if (kv.first == "k") k = kv.second;
                else if (kv.first == "v") { v = kv.second; has_v = true; }
            }
            if (!has_v) continue;  // Python skips tags with no v attribute
            // An entity reference in a key, or in a value one of the
            // consumed keys would read, decodes differently under
            // ElementTree: fall back rather than diverge.
            if (!entity_free(k)) { res->code = 1; return res; }
            if (k == "highway" || k == "maxspeed" || k == "oneway" ||
                k == "junction") {
                if (!entity_free(v)) { res->code = 1; return res; }
            }
            if (k == "highway") way_cls = highway_class(v);
            else if (k == "maxspeed") {
                way_maxspeed = v;       // last tag wins; parsed at flush
                way_has_maxspeed = true;
            } else if (k == "oneway") {
                way_oneway = v;
                way_has_oneway = true;
            } else if (k == "junction") way_junction = v;
        }
    }
    // Truncated document (no root close, or a way left open at EOF):
    // the ElementTree path raises — never hand back a silently partial
    // street network.
    if (!root_seen || !root_closed || in_way) { res->code = 1; return res; }
    if (segments.empty()) { res->code = 2; return res; }

    // Used-node compaction in ascending osm-id order (matches Python's
    // sorted-set indexing exactly).
    std::vector<int64_t> used;
    used.reserve(coords.size());
    {
        std::unordered_map<int64_t, uint8_t> seen;
        for (auto& s : segments) {
            for (int64_t ref : {s.a, s.b}) {
                if (coords.count(ref) && !seen.count(ref)) {
                    seen[ref] = 1;
                    used.push_back(ref);
                }
            }
        }
    }
    std::sort(used.begin(), used.end());
    std::unordered_map<int64_t, int32_t> index;
    index.reserve(used.size());
    for (size_t i = 0; i < used.size(); ++i)
        index[used[i]] = (int32_t)i;

    std::vector<int32_t> snd, rcv, cls;
    std::vector<float> spd;
    for (auto& s : segments) {
        auto ia = index.find(s.a), ib = index.find(s.b);
        if (ia == index.end() || ib == index.end() || s.a == s.b) continue;
        snd.push_back(ia->second); rcv.push_back(ib->second);
        cls.push_back(s.cls); spd.push_back(s.speed);
        if (s.both) {
            snd.push_back(ib->second); rcv.push_back(ia->second);
            cls.push_back(s.cls); spd.push_back(s.speed);
        }
    }
    if (snd.empty()) { res->code = 2; return res; }

    res->n_nodes = (int32_t)used.size();
    res->n_edges = (int64_t)snd.size();
    res->lat = (double*)malloc(sizeof(double) * used.size());
    res->lon = (double*)malloc(sizeof(double) * used.size());
    res->senders = (int32_t*)malloc(sizeof(int32_t) * snd.size());
    res->receivers = (int32_t*)malloc(sizeof(int32_t) * snd.size());
    res->cls = (int32_t*)malloc(sizeof(int32_t) * snd.size());
    res->speed = (float*)malloc(sizeof(float) * snd.size());
    if (!res->lat || !res->lon || !res->senders || !res->receivers ||
        !res->cls || !res->speed) {
        ff_osm_free(res);
        return nullptr;
    }
    for (size_t i = 0; i < used.size(); ++i) {
        res->lat[i] = coords[used[i]].first;
        res->lon[i] = coords[used[i]].second;
    }
    memcpy(res->senders, snd.data(), sizeof(int32_t) * snd.size());
    memcpy(res->receivers, rcv.data(), sizeof(int32_t) * rcv.size());
    memcpy(res->cls, cls.data(), sizeof(int32_t) * cls.size());
    memcpy(res->speed, spd.data(), sizeof(float) * spd.size());
    return res;
}

// ── version stamp (cache invalidation for the build wrapper) ───────────
int ff_abi_version() { return 2; }

}  // extern "C"
