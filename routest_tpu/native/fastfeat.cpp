// fastfeat: native data-plane for routest_tpu.
//
// The reference outsources its data pipeline entirely (data/ and
// notebooks/ are empty; one pandas row per HTTP request in
// Flaskr/ml.py:35-51). This framework's training/serving pipeline is
// host-side numpy by default; this library is the native runtime for the
// two hot host paths, bound via ctypes (routest_tpu/native/__init__.py):
//
//   ff_encode_batch  — categorical/scalar columns -> the 12-feature ABI
//                      matrix (SURVEY.md Appendix B), row-major f32.
//   ff_parse_csv     — delivery-history CSV -> column arrays, one pass,
//                      no per-row Python objects. Schema documented in
//                      routest_tpu/data/csv_io.py.
//
// Plain C ABI (extern "C"), no Python.h dependency: the same .so loads
// from any runtime. Built on demand by native/build.py with g++ -O3.

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <cmath>

extern "C" {

// ── feature encoding ────────────────────────────────────────────────────
// Column order (SURVEY.md Appendix B, Flaskr/ml.py:35-48):
//   weather_{Cloudy,Stormy,Sunny,Windy}, traffic_{High,Jam,Low,Medium},
//   weekday_ordered, hour_ordered, distance_km, driver_age
// weather_idx/traffic_idx use -1 for unknown categories => all-zero group.
void ff_encode_batch(const int32_t* weather_idx, const int32_t* traffic_idx,
                     const int32_t* weekday, const int32_t* hour,
                     const float* distance_km, const float* driver_age,
                     int64_t n, float* out /* n x 12, row-major */) {
    for (int64_t i = 0; i < n; ++i) {
        float* row = out + i * 12;
        memset(row, 0, 12 * sizeof(float));
        const int32_t w = weather_idx[i];
        if (w >= 0 && w < 4) row[w] = 1.0f;
        const int32_t t = traffic_idx[i];
        if (t >= 0 && t < 4) row[4 + t] = 1.0f;
        row[8] = (float)weekday[i];
        row[9] = (float)hour[i];
        row[10] = distance_km[i];
        row[11] = driver_age[i];
    }
}

// ── CSV ingest ──────────────────────────────────────────────────────────
// Expected header (validated by the Python wrapper):
//   weather,traffic,weekday,hour,distance_km,driver_age,eta_minutes
// weather/traffic are category NAMES; this parser maps them against the
// vocab tables passed in (entries are NUL-separated, count given), with
// unknown -> -1, matching vocab_index() in data/features.py.

struct FFVocab {
    const char* entries[16];
    int count;
};

static void ff_build_vocab(FFVocab* v, const char* packed, int count) {
    v->count = count > 16 ? 16 : count;
    const char* p = packed;
    for (int i = 0; i < v->count; ++i) {
        v->entries[i] = p;
        p += strlen(p) + 1;
    }
}

static int ff_vocab_lookup(const FFVocab* v, const char* s, int len) {
    for (int i = 0; i < v->count; ++i) {
        if ((int)strlen(v->entries[i]) == len &&
            memcmp(v->entries[i], s, (size_t)len) == 0)
            return i;
    }
    return -1;
}

// Counts data rows (lines after the first non-empty line). Returns -1 on
// open failure. Lets the caller allocate exact-size numpy arrays.
int64_t ff_count_rows(const char* path) {
    FILE* f = fopen(path, "rb");
    if (!f) return -1;
    int64_t lines = 0;
    char buf[1 << 16];
    size_t got;
    char last = '\n';
    while ((got = fread(buf, 1, sizeof(buf), f)) > 0) {
        for (size_t i = 0; i < got; ++i)
            if (buf[i] == '\n') ++lines;
        last = buf[got - 1];
    }
    fclose(f);
    if (last != '\n') ++lines;        // unterminated final line
    return lines > 0 ? lines - 1 : 0; // minus header
}

// Parses up to `cap` data rows into the output arrays. Returns the number
// of rows parsed, or a negative error code: -1 open failure, -2 a row had
// the wrong number of fields, -3 a numeric field failed to parse (the
// offending 1-based line number is written to *err_line for -2/-3).
int64_t ff_parse_csv(const char* path,
                     const char* weather_vocab, int n_weather,
                     const char* traffic_vocab, int n_traffic,
                     int64_t cap,
                     int32_t* weather_idx, int32_t* traffic_idx,
                     int32_t* weekday, int32_t* hour,
                     float* distance_km, float* driver_age,
                     float* eta_minutes, int64_t* err_line) {
    FFVocab wv, tv;
    ff_build_vocab(&wv, weather_vocab, n_weather);
    ff_build_vocab(&tv, traffic_vocab, n_traffic);
    *err_line = 0;

    FILE* f = fopen(path, "rb");
    if (!f) return -1;

    char line[4096];
    int64_t row = 0, lineno = 0;
    bool header = true;
    while (fgets(line, sizeof(line), f)) {
        ++lineno;
        size_t len = strlen(line);
        if (len == sizeof(line) - 1 && line[len - 1] != '\n') {
            // Overlong physical line: fgets would silently split it into
            // bogus rows. No valid row in this 7-field schema approaches
            // 4 KB, so reject instead of mis-parsing. Distinct code so
            // the Python fallback can mirror the exact same contract.
            fclose(f);
            *err_line = lineno;
            return -4;
        }
        while (len && (line[len - 1] == '\n' || line[len - 1] == '\r'))
            line[--len] = '\0';
        if (len == 0) continue;
        if (header) { header = false; continue; }
        if (row >= cap) break;

        // exactly 7 comma-separated fields (6 commas), then split
        int commas = 0;
        for (size_t i = 0; i < len; ++i)
            if (line[i] == ',') ++commas;
        if (commas != 6) {
            fclose(f);
            *err_line = lineno;
            return -2;
        }
        const char* fields[7];
        int flen[7];
        int nf = 0;
        const char* start = line;
        for (size_t i = 0; i <= len; ++i) {
            if (i == len || line[i] == ',') {
                fields[nf] = start;
                flen[nf] = (int)(line + i - start);
                ++nf;
                start = line + i + 1;
            }
        }

        weather_idx[row] = ff_vocab_lookup(&wv, fields[0], flen[0]);
        traffic_idx[row] = ff_vocab_lookup(&tv, fields[1], flen[1]);

        char tmp[64];
        char* end;
        const int numeric[5] = {2, 3, 4, 5, 6};
        double vals[5];
        for (int k = 0; k < 5; ++k) {
            int fi = numeric[k];
            int l = flen[fi];
            if (l > 63) {
                // No representable value in this schema needs 64 chars;
                // reject instead of silently truncating (the Python
                // fallback enforces the same cap).
                fclose(f);
                *err_line = lineno;
                return -3;
            }
            // Strict decimal grammar, identical to the Python fallback's
            // regex: digits/sign/dot/exponent only. This rejects what
            // strtod would otherwise quietly accept beyond the shared
            // contract — leading whitespace, hex (0x10), inf/nan.
            bool ok = l > 0;
            for (int c = 0; c < l && ok; ++c) {
                char ch = fields[fi][c];
                ok = (ch >= '0' && ch <= '9') || ch == '+' || ch == '-' ||
                     ch == '.' || ch == 'e' || ch == 'E';
            }
            memcpy(tmp, fields[fi], (size_t)l);
            tmp[l] = '\0';
            vals[k] = ok ? strtod(tmp, &end) : 0.0;
            // float32 range guard: values that would overflow to inf in
            // the f32 output columns are rejected, not silently mangled.
            if (!ok || end == tmp || *end != '\0' || !std::isfinite(vals[k]) ||
                vals[k] > 3.0e38 || vals[k] < -3.0e38) {
                fclose(f);
                *err_line = lineno;
                return -3;
            }
        }
        // weekday/hour become int32: an out-of-range double->int32 cast
        // is UB in C++, so range-check instead of silently corrupting.
        if (vals[0] < -2147483647.0 || vals[0] > 2147483647.0 ||
            vals[1] < -2147483647.0 || vals[1] > 2147483647.0) {
            fclose(f);
            *err_line = lineno;
            return -3;
        }
        weekday[row] = (int32_t)vals[0];
        hour[row] = (int32_t)vals[1];
        distance_km[row] = (float)vals[2];
        driver_age[row] = (float)vals[3];
        eta_minutes[row] = (float)vals[4];
        ++row;
    }
    fclose(f);
    return row;
}

// ── version stamp (cache invalidation for the build wrapper) ───────────
int ff_abi_version() { return 1; }

}  // extern "C"
