"""rtpulint: project-native static analysis for routest-tpu.

``python -m routest_tpu.analysis [--gate] [--json] [--rule ID ...]``
runs two rule families over the whole package in one process, one AST
parse per file:

- **Invariant lints** (pure AST): ``silent-except``, ``bare-print``,
  ``broad-except-unlogged``, ``blocking-call-under-lock``,
  ``thread-unmanaged``, and the JAX hazards ``jit-impure-host-call``,
  ``jit-host-pull``, ``jit-donated-reuse``.
- **Drift detectors** (code ↔ registry cross-reference):
  ``env-knob-undeclared`` / ``env-knob-undocumented`` (reads vs
  core/config.py and the docs knob tables), ``metric-undocumented`` /
  ``metric-stale-doc`` (registered families vs docs/OBSERVABILITY.md,
  both directions), ``api-route-undocumented`` (serve/ route strings
  vs docs/API.md), and ``chaos-point-undocumented`` /
  ``chaos-point-collision`` (inject() names vs docs/ROBUSTNESS.md).

Findings carry a rule id, severity, and a one-line fix hint;
grandfathered findings live in ``analysis/baseline.json`` (reason
required per entry); deliberate waivers use
``# rtpulint: disable=<rule> -- <reason>`` at the site. See
docs/ANALYSIS.md for the catalog and the adding-a-rule recipe.
"""

from routest_tpu.analysis.engine import (  # noqa: F401
    AnalysisResult,
    Corpus,
    Finding,
    Rule,
    all_rules,
    analyze,
    load_baseline,
    load_corpus,
    repo_root,
)

__all__ = [
    "AnalysisResult",
    "Corpus",
    "Finding",
    "Rule",
    "all_rules",
    "analyze",
    "load_baseline",
    "load_corpus",
    "repo_root",
]
