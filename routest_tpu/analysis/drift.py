"""Drift detectors: code cross-referenced against its registries.

The invariants that actually rot here are not style — they are the
contracts between the code and its registries: every ``RTPU_*`` /
``ROUTEST_*`` env knob read anywhere must be declared in
``core/config.py`` (the single typed registry) and documented in a docs
knob table; every ``rtpu_*`` metric family registered must appear in
docs/OBSERVABILITY.md and vice versa; every ``/api/*`` route string in
``serve/`` must have a docs/API.md row; every chaos point name passed
to the chaos layer must be unique across modules and documented in
docs/ROBUSTNESS.md. Each detector extracts its facts from the shared
ASTs (never from comments/strings-by-grep) and anchors findings at the
offending read/registration site — or at the stale doc line for the
doc→code direction.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterator, List, Optional, Set, Tuple

from routest_tpu.analysis.engine import (
    Corpus, Finding, Rule, call_leaf, dotted_name, register,
)

ENV_NAME_RE = re.compile(r"^(?:RTPU|ROUTEST)_[A-Z0-9_]*[A-Z0-9]$")
ENV_TOKEN_RE = re.compile(r"\b(?:RTPU|ROUTEST)_[A-Z0-9_]*[A-Z0-9]\b")
METRIC_NAME_RE = re.compile(r"^rtpu_[a-z0-9_]*[a-z0-9]$")
METRIC_TOKEN_RE = re.compile(r"\brtpu_[a-z0-9_]*[a-z0-9]\b")

CONFIG_REL = "routest_tpu/core/config.py"


def _env_reads(corpus: Corpus) -> List[Tuple[str, str, int]]:
    """(knob, file, line) for every env-name string literal used as a
    call argument, subscript index, or comparison operand — i.e. an
    actual read/probe site, never a docstring or comment mention."""
    out: List[Tuple[str, str, int]] = []
    for sf in corpus.files:
        for node in sf.nodes():
            args: List[ast.AST] = []
            if isinstance(node, ast.Call):
                args = list(node.args) + [k.value for k in node.keywords]
            elif isinstance(node, ast.Subscript):
                args = [node.slice]
            elif isinstance(node, ast.Compare):
                args = [node.left] + list(node.comparators)
            for a in args:
                if (isinstance(a, ast.Constant) and isinstance(a.value, str)
                        and ENV_NAME_RE.match(a.value)):
                    out.append((a.value, sf.relpath, a.lineno))
    return out


def _first_sites(reads: List[Tuple[str, str, int]]
                 ) -> Dict[str, Tuple[str, int]]:
    sites: Dict[str, Tuple[str, int]] = {}
    for name, file, line in sorted(reads, key=lambda r: (r[0], r[1], r[2])):
        sites.setdefault(name, (file, line))
    return sites


@register(
    "env-knob-undeclared", "error",
    "an RTPU_*/ROUTEST_* env var is read outside core/config.py but "
    "never declared there — the typed config registry is how a deploy "
    "discovers the knob exists",
    "add the knob to the matching Config dataclass loader, or to the "
    "KNOWN_KNOBS registry in core/config.py when it is read lazily at "
    "its use site")
def env_knob_undeclared(rule: Rule, corpus: Corpus) -> Iterator[Finding]:
    cfg = corpus.file(CONFIG_REL)
    if cfg is None:
        return
    declared = set(ENV_TOKEN_RE.findall(cfg.text))
    reads = [(n, f, ln) for n, f, ln in _env_reads(corpus)
             if f != CONFIG_REL]
    for name, (file, line) in sorted(_first_sites(reads).items()):
        if name not in declared:
            yield rule.finding(
                file, line,
                f"env knob `{name}` is read here but not declared in "
                f"core/config.py")


@register(
    "env-knob-undocumented", "error",
    "an RTPU_*/ROUTEST_* env var is read by the package but appears in "
    "no docs/*.md knob table — operators cannot tune what the docs "
    "don't name",
    "add a row to the owning subsystem's knob table, or to the "
    "complete knob reference in docs/ARCHITECTURE.md (appendix)")
def env_knob_undocumented(rule: Rule, corpus: Corpus) -> Iterator[Finding]:
    if not corpus.docs:
        return  # no docs checkout (installed package) — nothing to check
    documented: Set[str] = set()
    for text in corpus.docs.values():
        documented |= set(ENV_TOKEN_RE.findall(text))
    for name, (file, line) in sorted(
            _first_sites(_env_reads(corpus)).items()):
        if name not in documented:
            yield rule.finding(
                file, line,
                f"env knob `{name}` is read here but documented in no "
                f"docs/*.md")


# ---------------------------------------------------------------------------
# Metric families ↔ docs/OBSERVABILITY.md

def _registered_metrics(corpus: Corpus) -> Dict[str, Tuple[str, int]]:
    """family name -> first (file, line) registration site, extracted
    from ``.counter("rtpu_…")`` / ``.gauge`` / ``.histogram`` calls."""
    out: Dict[str, Tuple[str, int]] = {}
    for sf in corpus.files:
        for node in sf.nodes():
            if not isinstance(node, ast.Call):
                continue
            if call_leaf(node) not in ("counter", "gauge", "histogram"):
                continue
            if not isinstance(node.func, ast.Attribute):
                continue
            if not node.args:
                continue
            a = node.args[0]
            if (isinstance(a, ast.Constant) and isinstance(a.value, str)
                    and METRIC_NAME_RE.match(a.value)):
                key = a.value
                if (key not in out
                        or (sf.relpath, a.lineno) < out[key]):
                    out[key] = (sf.relpath, a.lineno)
    return out


@register(
    "metric-undocumented", "error",
    "an rtpu_* metric family is registered in code but absent from "
    "docs/OBSERVABILITY.md — dashboards and alerts are built from the "
    "doc, so an undocumented family is invisible telemetry",
    "add the family to the metric reference table in "
    "docs/OBSERVABILITY.md")
def metric_undocumented(rule: Rule, corpus: Corpus) -> Iterator[Finding]:
    doc = corpus.doc("OBSERVABILITY.md")
    if not doc:
        return
    documented = set(METRIC_TOKEN_RE.findall(doc))
    for name, (file, line) in sorted(_registered_metrics(corpus).items()):
        if name not in documented:
            yield rule.finding(
                file, line,
                f"metric family `{name}` is registered here but not "
                f"documented in docs/OBSERVABILITY.md")


# Prometheus exposition suffixes: a doc may legitimately show
# `<family>_bucket` / `_sum` / `_count` sample lines for a histogram.
_EXPOSITION_SUFFIXES = ("_bucket", "_sum", "_count")


@register(
    "metric-stale-doc", "error",
    "docs/OBSERVABILITY.md names an rtpu_* metric family that no code "
    "registers — a dashboard built from that row queries nothing",
    "remove the stale row, or rename it to the family the code "
    "actually registers")
def metric_stale_doc(rule: Rule, corpus: Corpus) -> Iterator[Finding]:
    doc = corpus.doc("OBSERVABILITY.md")
    if not doc:
        return
    registered = set(_registered_metrics(corpus))
    seen: Set[str] = set()
    for token in METRIC_TOKEN_RE.findall(doc):
        if token in seen:
            continue
        seen.add(token)
        base = token
        for suf in _EXPOSITION_SUFFIXES:
            if token.endswith(suf) and token[:-len(suf)] in registered:
                base = token[:-len(suf)]
                break
        if base in registered:
            continue
        yield rule.finding(
            "docs/OBSERVABILITY.md",
            corpus.doc_line_of("OBSERVABILITY.md", token),
            f"documented metric family `{token}` is registered nowhere "
            f"in the package")


# ---------------------------------------------------------------------------
# /api/* routes ↔ docs/API.md

@register(
    "api-route-undocumented", "error",
    "an /api/* route string in serve/ has no docs/API.md row — the API "
    "reference is the wire contract the frontend and the gateway "
    "tests are written against",
    "add a row to the matching docs/API.md table")
def api_route_undocumented(rule: Rule, corpus: Corpus) -> Iterator[Finding]:
    doc = corpus.doc("API.md")
    if not doc:
        return
    seen: Set[str] = set()
    for sf in corpus.files:
        if not sf.relpath.startswith("routest_tpu/serve/"):
            continue
        for node in sf.nodes():
            if not (isinstance(node, ast.Constant)
                    and isinstance(node.value, str)):
                continue
            v = node.value
            if not v.startswith("/api/") or " " in v or "\n" in v:
                continue
            # Parameterized registrations (`/api/history/<req_id>`)
            # document as `<id>`-style rows: compare the static prefix.
            prefix = v.split("<", 1)[0]
            if prefix in seen:
                continue
            seen.add(prefix)
            if prefix not in doc:
                yield rule.finding(
                    sf.relpath, node.lineno,
                    f"route `{v}` has no docs/API.md row")


# ---------------------------------------------------------------------------
# Chaos points ↔ docs/ROBUSTNESS.md + uniqueness

def _chaos_points(corpus: Corpus
                  ) -> List[Tuple[str, bool, str, int]]:
    """(point-or-prefix, is_prefix, file, line) for every literal (or
    f-string-prefixed) name passed to the chaos layer's ``inject()``."""
    out: List[Tuple[str, bool, str, int]] = []
    for sf in corpus.files:
        for node in sf.nodes():
            if not isinstance(node, ast.Call):
                continue
            # Direct `inject(...)`, aliased `chaos_inject(...)`, and
            # method-form `engine.inject(...)` all reach the chaos layer.
            if call_leaf(node) not in ("inject", "chaos_inject") \
                    or not node.args:
                continue
            a = node.args[0]
            if isinstance(a, ast.Constant) and isinstance(a.value, str):
                if re.match(r"^[a-z][a-z0-9_.]*$", a.value):
                    out.append((a.value, False, sf.relpath, a.lineno))
            elif isinstance(a, ast.JoinedStr) and a.values:
                head = a.values[0]
                if (isinstance(head, ast.Constant)
                        and isinstance(head.value, str)
                        and re.match(r"^[a-z][a-z0-9_.]*\.$", head.value)):
                    out.append((head.value.rstrip("."), True,
                                sf.relpath, a.lineno))
    return out


@register(
    "chaos-point-undocumented", "error",
    "a chaos fault-point name is injected in code but missing from the "
    "docs/ROBUSTNESS.md fault-point table — an undocumented point "
    "cannot be targeted by an operator's RTPU_CHAOS_SPEC",
    "add the point to the fault-point table in docs/ROBUSTNESS.md")
def chaos_point_undocumented(rule: Rule, corpus: Corpus
                             ) -> Iterator[Finding]:
    doc = corpus.doc("ROBUSTNESS.md")
    if not doc:
        return
    seen: Set[str] = set()
    for name, _is_prefix, file, line in _chaos_points(corpus):
        if name in seen:
            continue
        seen.add(name)
        if name not in doc:
            yield rule.finding(
                file, line,
                f"chaos point `{name}` has no docs/ROBUSTNESS.md row")


@register(
    "chaos-point-collision", "error",
    "the same chaos point name is injected from two different modules "
    "— a spec targeting it would fire at an unintended boundary too, "
    "and injection counters for the two boundaries merge",
    "rename one of the points (convention: `<subsystem>.<operation>`)")
def chaos_point_collision(rule: Rule, corpus: Corpus) -> Iterator[Finding]:
    by_name: Dict[str, Dict[str, int]] = {}
    for name, is_prefix, file, line in _chaos_points(corpus):
        if is_prefix:
            continue  # per-replica/per-version dynamic families
        by_name.setdefault(name, {}).setdefault(file, line)
    for name, files in sorted(by_name.items()):
        if len(files) <= 1:
            continue
        ordered = sorted(files.items())
        first = ordered[0][0]
        for file, line in ordered[1:]:
            yield rule.finding(
                file, line,
                f"chaos point `{name}` is also injected from {first} — "
                f"point names must be unique per boundary")


# ---------------------------------------------------------------------------
# Change-ledger kinds ↔ LEDGER_KINDS registry + docs/OBSERVABILITY.md

LEDGER_REL = "routest_tpu/obs/ledger.py"
LEDGER_KIND_RE = re.compile(r"^[a-z][a-z_]*\.[a-z][a-z_]*$")


def _ledger_registered_kinds(corpus: Corpus) -> Set[str]:
    """Keys of the ``LEDGER_KINDS`` dict literal in obs/ledger.py —
    the typed registry every ``record_change`` kind must come from."""
    sf = corpus.file(LEDGER_REL)
    if sf is None:
        return set()
    kinds: Set[str] = set()
    for node in sf.nodes():
        if not isinstance(node, ast.Assign):
            continue
        if not any(isinstance(t, ast.Name) and t.id == "LEDGER_KINDS"
                   for t in node.targets):
            continue
        if isinstance(node.value, ast.Dict):
            for key in node.value.keys:
                if (isinstance(key, ast.Constant)
                        and isinstance(key.value, str)):
                    kinds.add(key.value)
    return kinds


def _ledger_kind_sites(corpus: Corpus) -> List[Tuple[str, str, int]]:
    """(kind, file, line) for every literal kind passed to the change
    ledger — ``record_change("…")`` helper calls and ``.record("…")``
    method calls whose kind matches the ledger grammar."""
    out: List[Tuple[str, str, int]] = []
    for sf in corpus.files:
        if sf.relpath == LEDGER_REL:
            continue  # the registry itself (docstrings, defaults)
        for node in sf.nodes():
            if not isinstance(node, ast.Call):
                continue
            if call_leaf(node) != "record_change":
                continue
            a: Optional[ast.AST] = node.args[0] if node.args else None
            if a is None:
                for kw in node.keywords:
                    if kw.arg == "kind":
                        a = kw.value
                        break
            if (isinstance(a, ast.Constant) and isinstance(a.value, str)
                    and LEDGER_KIND_RE.match(a.value)):
                out.append((a.value, sf.relpath, a.lineno))
    return out


def _ledger_doc_section(corpus: Corpus) -> Tuple[str, int]:
    """The "Change ledger" section of docs/OBSERVABILITY.md (text,
    first-line offset) — the doc→code direction only scans there, so
    chaos points and metric names elsewhere never false-positive."""
    doc = corpus.doc("OBSERVABILITY.md")
    if not doc:
        return "", 0
    lines = doc.splitlines()
    start = end = None
    for i, line in enumerate(lines):
        if start is None:
            if line.startswith("#") and "change ledger" in line.lower():
                start = i
        elif line.startswith("## "):
            end = i
            break
    if start is None:
        return "", 0
    return "\n".join(lines[start:end]), start


@register(
    "ledger-kind-unregistered", "error",
    "a change-ledger event kind is recorded in code but missing from "
    "the LEDGER_KINDS registry in obs/ledger.py — the suspect ranker "
    "and the /api/changes consumers only know registered kinds",
    "add the kind (with a one-line description) to LEDGER_KINDS in "
    "routest_tpu/obs/ledger.py")
def ledger_kind_unregistered(rule: Rule, corpus: Corpus
                             ) -> Iterator[Finding]:
    registered = _ledger_registered_kinds(corpus)
    if not registered:
        return
    for kind, file, line in _ledger_kind_sites(corpus):
        if kind not in registered:
            yield rule.finding(
                file, line,
                f"ledger kind `{kind}` is recorded here but not "
                f"registered in LEDGER_KINDS")


@register(
    "ledger-kind-undocumented", "error",
    "a change-ledger event kind recorded in code has no row in the "
    "docs/OBSERVABILITY.md change-ledger table — incident responders "
    "triage suspects by that table",
    "add the kind to the event-kind table under \"Change ledger & "
    "incident correlation\" in docs/OBSERVABILITY.md")
def ledger_kind_undocumented(rule: Rule, corpus: Corpus
                             ) -> Iterator[Finding]:
    doc = corpus.doc("OBSERVABILITY.md")
    if not doc:
        return
    seen: Set[str] = set()
    for kind, file, line in _ledger_kind_sites(corpus):
        if kind in seen:
            continue
        seen.add(kind)
        if kind not in doc:
            yield rule.finding(
                file, line,
                f"ledger kind `{kind}` is recorded here but not "
                f"documented in docs/OBSERVABILITY.md")


@register(
    "ledger-kind-stale-doc", "error",
    "the docs/OBSERVABILITY.md change-ledger table names an event kind "
    "that the LEDGER_KINDS registry doesn't know — a responder would "
    "filter /api/changes on a kind that never occurs",
    "remove the stale row, or register the kind in LEDGER_KINDS in "
    "routest_tpu/obs/ledger.py")
def ledger_kind_stale_doc(rule: Rule, corpus: Corpus
                          ) -> Iterator[Finding]:
    section, offset = _ledger_doc_section(corpus)
    if not section:
        return
    registered = _ledger_registered_kinds(corpus)
    if not registered:
        return
    seen: Set[str] = set()
    for i, line in enumerate(section.splitlines()):
        # Kinds live in the FIRST column of the event-kind table;
        # prose (the `rtpu.changes` channel, module paths) is exempt.
        if not line.lstrip().startswith("|"):
            continue
        first_cell = line.split("|")[1] if "|" in line else ""
        for token in re.findall(r"`([a-z][a-z_]*\.[a-z_.]*[a-z])`",
                                first_cell):
            if token in seen or not LEDGER_KIND_RE.match(token):
                continue
            seen.add(token)
            if token not in registered:
                yield rule.finding(
                    "docs/OBSERVABILITY.md", offset + i + 1,
                    f"documented ledger kind `{token}` is not in "
                    f"LEDGER_KINDS")
