"""Invariant lints: pure-AST rules over the package.

These encode conventions the runtime stack already relies on — loud
failure (no silent broad excepts, no unlogged degradation), structured
logging (no bare prints outside the JsonLogger emitter), and lock/thread
discipline on the serving hot paths (no blocking IO while holding a
lock, no unmanaged threads). The first two migrated here from the
standalone AST sweeps ``tests/test_no_silent_excepts.py`` /
``tests/test_no_bare_print.py`` and now cover the whole package instead
of a hand-listed subdirectory set.
"""

from __future__ import annotations

import ast
from typing import Iterator, Optional, Set

from routest_tpu.analysis.engine import (
    Corpus, Finding, Rule, call_leaf, dotted_name, exc_type_names, register,
)

BROAD = {"Exception", "BaseException"}

# The logger's emitter is the one sanctioned print call site: it is how
# JSON lines physically reach stderr. The lint CLI is the other: its
# stdout IS its interface (diagnostics a human or CI reads directly).
PRINT_ALLOWED = {"routest_tpu/utils/logging.py",
                 "routest_tpu/analysis/__main__.py"}

# Handler body verbs that make a broad catch "loud": structured logging
# and metric mutation. A ``raise`` or any use of the bound exception
# variable (propagating the error into surfaced state, e.g.
# ``self._error = f"{e}"``) also qualifies — see broad-except-unlogged.
_LOGGY = {"log", "warning", "error", "exception", "info", "debug",
          "critical", "warn"}
_METRIC = {"inc", "dec", "set", "observe", "labels"}

# Known-blocking calls that must not run while a lock is held: the
# serving hot paths (gateway _pick, batcher submit/flush, fastlane,
# route cache) all contend on these locks, so one blocked holder
# convoys every request behind it.
_BLOCKING_DOTTED = {"time.sleep", "socket.create_connection"}
_BLOCKING_PREFIX = ("subprocess.", "requests.", "urllib.request.")
_BLOCKING_LEAF = {"sendall", "recv", "recvfrom", "accept", "connect",
                  "urlopen", "getresponse", "block_until_ready"}


def _is_broad(handler: ast.ExceptHandler) -> bool:
    names = exc_type_names(handler.type)
    return bool(names & BROAD) or "<bare>" in names


@register(
    "silent-except", "error",
    "an `except` catching Exception/BaseException (or bare) whose body "
    "is only `pass` — invisible degradation: the failure leaves no log "
    "line, no metric, no surfaced state",
    "log a JsonLogger event, count a metric, or narrow the caught type "
    "to the specific expected error")
def silent_except(rule: Rule, corpus: Corpus) -> Iterator[Finding]:
    for sf in corpus.files:
        for node in sf.nodes():
            if not isinstance(node, ast.ExceptHandler):
                continue
            if not _is_broad(node):
                continue
            if all(isinstance(s, ast.Pass) for s in node.body):
                yield rule.finding(
                    sf.relpath, node.lineno,
                    "silent broad except: body is only `pass`")


@register(
    "bare-print", "error",
    "a bare `print()` call inside the package — ad-hoc status prints "
    "bypass the structured JsonLogger (only utils/logging.py, the "
    "emitter itself, may print)",
    "use utils.logging.get_logger(...) / JsonLogger instead")
def bare_print(rule: Rule, corpus: Corpus) -> Iterator[Finding]:
    for sf in corpus.files:
        if sf.relpath in PRINT_ALLOWED:
            continue
        for node in sf.nodes():
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Name)
                    and node.func.id == "print"):
                yield rule.finding(sf.relpath, node.lineno,
                                   "bare print() call")


@register(
    "broad-except-unlogged", "error",
    "a broad `except Exception` handler that neither logs, counts a "
    "metric, re-raises, nor uses the bound exception — the error is "
    "swallowed with no trace of what went wrong",
    "log/count the failure, propagate `e` into surfaced state, or add "
    "a `# rtpulint: disable=broad-except-unlogged -- <why>` if the "
    "swallow is the contract (e.g. a health probe mapping any failure "
    "to `unhealthy`)")
def broad_except_unlogged(rule: Rule, corpus: Corpus) -> Iterator[Finding]:
    for sf in corpus.files:
        for node in sf.nodes():
            if not isinstance(node, ast.ExceptHandler):
                continue
            if not _is_broad(node):
                continue
            if all(isinstance(s, ast.Pass) for s in node.body):
                continue  # that's silent-except's finding
            if _handler_is_loud(node):
                continue
            yield rule.finding(
                sf.relpath, node.lineno,
                "broad except swallows the error without logging, "
                "counting, or using the exception")


def _handler_is_loud(handler: ast.ExceptHandler) -> bool:
    for stmt in handler.body:
        for sub in ast.walk(stmt):
            if isinstance(sub, ast.Raise):
                return True
            if (isinstance(sub, ast.Call)
                    and isinstance(sub.func, ast.Attribute)
                    and sub.func.attr in (_LOGGY | _METRIC)):
                return True
            if (isinstance(sub, ast.Call)
                    and isinstance(sub.func, ast.Name)
                    and sub.func.id in _LOGGY):
                return True
            if (handler.name and isinstance(sub, ast.Name)
                    and sub.id == handler.name):
                return True  # the error is captured into state
    return False


def _lockish(with_node: ast.With) -> Optional[str]:
    """The dotted name of the first with-item that looks like a lock
    (``self._lock``, ``cache_lock``, ``threading.Lock()``…), else None.

    Lexical by design: a lock released early via ``lock.release()`` in
    the body (or acquire/try/finally-release outside a ``with``) is NOT
    modeled — tests/test_analysis.py documents both as accepted
    false-negative/false-positive guards.
    """
    for item in with_node.items:
        name = dotted_name(item.context_expr).lower()
        if "lock" in name or "mutex" in name:
            return dotted_name(item.context_expr)
    return None


@register(
    "blocking-call-under-lock", "error",
    "a known-blocking call (`time.sleep`, socket/HTTP IO, subprocess, "
    "device `.block_until_ready()`) lexically inside a `with <lock>:` "
    "body — one blocked holder convoys every thread contending on that "
    "lock",
    "move the blocking work outside the critical section (snapshot "
    "state under the lock, block after releasing), or suppress with a "
    "reason if the lock IS the serialization point for this IO")
def blocking_call_under_lock(rule: Rule, corpus: Corpus) -> Iterator[Finding]:
    for sf in corpus.files:
        for node in sf.nodes():
            if not isinstance(node, ast.With):
                continue
            lock_name = _lockish(node)
            if lock_name is None:
                continue
            for sub in ast.walk(node):
                if not isinstance(sub, ast.Call):
                    continue
                dn = dotted_name(sub.func)
                leaf = call_leaf(sub)
                blocking = (
                    dn in _BLOCKING_DOTTED
                    or any(dn.startswith(p) for p in _BLOCKING_PREFIX)
                    or leaf in _BLOCKING_LEAF)
                if blocking:
                    yield rule.finding(
                        sf.relpath, sub.lineno,
                        f"blocking call `{dn or leaf}` while holding "
                        f"`{lock_name}`")


@register(
    "thread-unmanaged", "warning",
    "a `threading.Thread(...)` constructed with no `daemon=` decision "
    "and no `.join()` in the enclosing scope — at interpreter exit a "
    "forgotten non-daemon thread hangs shutdown; the codebase "
    "convention is explicit daemon=True for background loops and "
    "join() for owned workers",
    "pass `daemon=True` (background loop) or join the thread before "
    "the owning scope exits")
def thread_unmanaged(rule: Rule, corpus: Corpus) -> Iterator[Finding]:
    for sf in corpus.files:
        for node in sf.nodes():
            if not isinstance(node, ast.Call):
                continue
            leaf = call_leaf(node)
            if leaf != "Thread":
                continue
            dn = dotted_name(node.func)
            if dn not in ("Thread", "threading.Thread"):
                continue
            if any(kw.arg == "daemon" for kw in node.keywords):
                continue
            scope = _enclosing_function(sf, node)
            if scope is not None and _scope_joins(scope):
                continue
            yield rule.finding(
                sf.relpath, node.lineno,
                "Thread() without a daemon= decision or a join() in "
                "the enclosing scope")


def _enclosing_function(sf, node: ast.AST):
    for anc in sf.ancestors(node):
        if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return anc
    return None


def _scope_joins(scope: ast.AST) -> bool:
    for sub in ast.walk(scope):
        if (isinstance(sub, ast.Call)
                and isinstance(sub.func, ast.Attribute)
                and sub.func.attr == "join"
                and not sub.args):
            # str.join always takes an argument; a bare `.join()` (or
            # `.join(timeout=...)`) is the Thread API.
            return True
        if (isinstance(sub, ast.Call)
                and isinstance(sub.func, ast.Attribute)
                and sub.func.attr == "join"
                and all(isinstance(a, ast.Constant)
                        and isinstance(a.value, (int, float))
                        for a in sub.args)):
            return True  # join(5.0) — a timeout, not a separator
    return False
