"""CLI: ``python -m routest_tpu.analysis [--gate] [--json] [--rule …]``.

Exit codes: 0 = clean (in ``--gate`` mode: zero unbaselined findings
AND a structurally valid baseline), 1 = findings / invalid baseline,
2 = usage error. Human output is one ``file:line: [rule] severity:
message (fix: hint)`` diagnostic per finding; ``--json`` emits the full
machine-readable result instead.
"""

from __future__ import annotations

import argparse
import json
import sys
import time

from routest_tpu.analysis.engine import (
    all_rules, analyze, load_corpus,
)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m routest_tpu.analysis",
        description="rtpulint: invariant lints + registry drift "
                    "detectors for routest-tpu")
    parser.add_argument("--gate", action="store_true",
                        help="CI mode: fail on any unbaselined finding "
                             "or invalid baseline entry")
    parser.add_argument("--json", action="store_true",
                        help="emit the machine-readable result")
    parser.add_argument("--rule", action="append", default=None,
                        metavar="ID",
                        help="run only this rule (repeatable)")
    parser.add_argument("--root", default=None,
                        help="repo root (default: the checkout holding "
                             "routest_tpu/)")
    parser.add_argument("--baseline", default=None,
                        help="baseline path (default: "
                             "routest_tpu/analysis/baseline.json)")
    parser.add_argument("--no-baseline", action="store_true",
                        help="report baselined findings as findings")
    parser.add_argument("--list-rules", action="store_true",
                        help="print the rule catalog and exit")
    args = parser.parse_args(argv)

    if args.list_rules:
        for rule_id, rule in sorted(all_rules().items()):
            print(f"{rule_id} [{rule.severity}]\n    {rule.description}")
        return 0

    t0 = time.perf_counter()
    try:
        corpus = load_corpus(args.root)
        result = analyze(corpus, rules=args.rule,
                         baseline_path=args.baseline,
                         use_baseline=not args.no_baseline)
    except KeyError as e:
        print(f"error: {e.args[0]}", file=sys.stderr)
        return 2
    elapsed = time.perf_counter() - t0

    if args.json:
        out = result.as_dict()
        out["elapsed_s"] = round(elapsed, 3)
        print(json.dumps(out, indent=2))
    else:
        for f in result.findings:
            print(f.format())
        for err in result.baseline_errors:
            print(f"baseline: error: {err}")
        for e in result.stale_baseline:
            print(f"baseline: stale entry {e.rule} {e.file}:{e.line} "
                  f"(matches nothing — prune it)")
        verdict = "GATE OK" if result.gate_ok else (
            f"{len(result.findings)} finding(s)"
            + (f", {len(result.baseline_errors)} baseline error(s)"
               if result.baseline_errors else ""))
        print(f"rtpulint: {verdict} — {result.files_scanned} files, "
              f"{len(result.rules_run)} rules, "
              f"{len(result.baselined)} baselined, "
              f"{len(result.suppressed)} suppressed, "
              f"{elapsed:.2f}s")
    return 0 if result.gate_ok else 1


if __name__ == "__main__":
    sys.exit(main())
