"""JAX hazard lints: host impurity inside traced code, donated-buffer
reuse.

A ``@jit``-ted function runs its Python body ONCE at trace time; host
calls inside it (``time.time()``, ``datetime.now()``, host RNG) bake a
constant into the compiled program and silently stop varying — the
classic "my timestamp never changes" production bug. Host ``np.``
conversion of a traced argument either crashes at trace time or forces
a device sync; and a buffer passed through ``donate_argnums`` is dead
the moment the compiled call dispatches — touching it afterwards reads
garbage (TPU) or deleted-array errors (CPU jax).
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Set, Tuple

from routest_tpu.analysis.engine import (
    Corpus, Finding, Rule, call_leaf, dotted_name, register,
)

# Host calls whose value is frozen at trace time inside jit.
_IMPURE_DOTTED = {
    "time.time", "time.monotonic", "time.perf_counter",
    "time.process_time", "time.time_ns", "datetime.now",
    "datetime.utcnow", "datetime.datetime.now", "datetime.datetime.utcnow",
}
_IMPURE_PREFIX = ("np.random.", "numpy.random.", "random.")

# Host-side numpy pulls that force/crash on traced values.
_HOST_PULL = {"np.asarray", "np.array", "np.frombuffer", "np.copy",
              "numpy.asarray", "numpy.array"}


def _jit_decorated(fn: ast.AST) -> bool:
    if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
        return False
    for dec in fn.decorator_list:
        target = dec.func if isinstance(dec, ast.Call) else dec
        dn = dotted_name(target)
        if dn in ("jit", "jax.jit"):
            return True
        # functools.partial(jax.jit, static_argnums=...) decorator form.
        if isinstance(dec, ast.Call) and dn in ("partial",
                                                "functools.partial"):
            if dec.args and dotted_name(dec.args[0]) in ("jit", "jax.jit"):
                return True
    return False


def _jitted_functions(sf) -> List[ast.AST]:
    """Functions traced by jit: decorator form plus the call form
    ``x = jax.jit(fn, ...)`` naming a function defined in this file."""
    by_name: Dict[str, ast.AST] = {}
    out: List[ast.AST] = []
    for node in sf.nodes():
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            by_name.setdefault(node.name, node)
            if _jit_decorated(node):
                out.append(node)
    for node in sf.nodes():
        if (isinstance(node, ast.Call)
                and dotted_name(node.func) in ("jit", "jax.jit")
                and node.args and isinstance(node.args[0], ast.Name)):
            fn = by_name.get(node.args[0].id)
            if fn is not None and fn not in out:
                out.append(fn)
    return out


@register(
    "jit-impure-host-call", "error",
    "`time.time()` / `datetime.now()` / host RNG inside a jit-traced "
    "function — the call runs once at trace time and its result is "
    "baked into the compiled program as a constant",
    "hoist the host call out of the jitted function and pass the value "
    "in as an argument (RNG: thread a `jax.random` key)")
def jit_impure_host_call(rule: Rule, corpus: Corpus) -> Iterator[Finding]:
    for sf in corpus.files:
        for fn in _jitted_functions(sf):
            for sub in ast.walk(fn):
                if not isinstance(sub, ast.Call):
                    continue
                dn = dotted_name(sub.func)
                if (dn in _IMPURE_DOTTED
                        or any(dn.startswith(p) for p in _IMPURE_PREFIX)):
                    yield rule.finding(
                        sf.relpath, sub.lineno,
                        f"host call `{dn}` inside jitted "
                        f"`{getattr(fn, 'name', '?')}` is evaluated at "
                        f"trace time only")


@register(
    "jit-host-pull", "error",
    "host `np.` conversion (or `.block_until_ready()`) applied to a "
    "traced argument inside a jit-traced function — it either raises a "
    "TracerConversionError at trace time or forces a host sync",
    "keep the math in jax.numpy inside jit; convert on the host before "
    "calling, or return the value and convert after")
def jit_host_pull(rule: Rule, corpus: Corpus) -> Iterator[Finding]:
    for sf in corpus.files:
        for fn in _jitted_functions(sf):
            params = _param_names(fn)
            for sub in ast.walk(fn):
                if not isinstance(sub, ast.Call):
                    continue
                dn = dotted_name(sub.func)
                if (dn in _HOST_PULL and sub.args
                        and isinstance(sub.args[0], ast.Name)
                        and sub.args[0].id in params):
                    yield rule.finding(
                        sf.relpath, sub.lineno,
                        f"`{dn}({sub.args[0].id})` converts a traced "
                        f"argument of jitted "
                        f"`{getattr(fn, 'name', '?')}` on the host")
                elif call_leaf(sub) == "block_until_ready":
                    yield rule.finding(
                        sf.relpath, sub.lineno,
                        f"`.block_until_ready()` inside jitted "
                        f"`{getattr(fn, 'name', '?')}` is meaningless "
                        f"under trace (and a sync point outside it)")


def _param_names(fn: ast.AST) -> Set[str]:
    a = fn.args
    names = [p.arg for p in
             list(a.posonlyargs) + list(a.args) + list(a.kwonlyargs)]
    if a.vararg:
        names.append(a.vararg.arg)
    if a.kwarg:
        names.append(a.kwarg.arg)
    return set(names)


@register(
    "jit-donated-reuse", "error",
    "a buffer passed at a `donate_argnums` position of a compiled call "
    "is referenced again afterwards — donation hands the buffer's "
    "memory to XLA, so the old array is dead the moment the call "
    "dispatches",
    "stop touching the donated array after the call (use the call's "
    "result), or drop donate_argnums for this argument")
def jit_donated_reuse(rule: Rule, corpus: Corpus) -> Iterator[Finding]:
    for sf in corpus.files:
        scopes: List[ast.AST] = [sf.tree] + [
            n for n in sf.nodes()
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]
        for scope in scopes:
            yield from _donated_reuse_in_scope(rule, sf, scope)


def _donated_indices(call: ast.Call) -> Optional[Tuple[int, ...]]:
    if dotted_name(call.func) not in ("jit", "jax.jit"):
        return None
    for kw in call.keywords:
        if kw.arg != "donate_argnums":
            continue
        v = kw.value
        if isinstance(v, ast.Constant) and isinstance(v.value, int):
            return (v.value,)
        if isinstance(v, (ast.Tuple, ast.List)):
            out = []
            for elt in v.elts:
                if (isinstance(elt, ast.Constant)
                        and isinstance(elt.value, int)):
                    out.append(elt.value)
            return tuple(out)
    return None


def _scope_nodes(scope: ast.AST) -> List[ast.AST]:
    """Nodes lexically in ``scope``, not descending into nested
    function definitions (each gets its own scope pass)."""
    out: List[ast.AST] = []
    stack = list(ast.iter_child_nodes(scope))
    while stack:
        node = stack.pop()
        out.append(node)
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            stack.extend(ast.iter_child_nodes(node))
    return out


def _donated_reuse_in_scope(rule: Rule, sf, scope: ast.AST
                            ) -> Iterator[Finding]:
    # Pass 1: names bound to jit(..., donate_argnums=...) in this scope.
    jitted: Dict[str, Tuple[int, ...]] = {}
    nodes = _scope_nodes(scope)
    for node in nodes:
        if (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and isinstance(node.value, ast.Call)):
            idx = _donated_indices(node.value)
            if idx:
                jitted[node.targets[0].id] = idx
    if not jitted:
        return
    # Pass 2: calls of those names → (buffer var, call line).
    donations: List[Tuple[str, int]] = []
    for node in nodes:
        if (isinstance(node, ast.Call) and isinstance(node.func, ast.Name)
                and node.func.id in jitted):
            for i in jitted[node.func.id]:
                if i < len(node.args) and isinstance(node.args[i], ast.Name):
                    donations.append((node.args[i].id, node.lineno))
    if not donations:
        return
    # Pass 3: loads of a donated var strictly after its donating call,
    # with no rebinding in between.
    stores: Dict[str, List[int]] = {}
    loads: Dict[str, List[int]] = {}
    for node in nodes:
        if isinstance(node, ast.Name):
            (stores if isinstance(node.ctx, ast.Store) else loads) \
                .setdefault(node.id, []).append(node.lineno)
    for var, call_line in donations:
        # >= : `buf = compiled(buf, …)` rebinds on the call's own line —
        # the store target receives the result, so later loads are safe.
        rebinds = [ln for ln in stores.get(var, []) if ln >= call_line]
        first_rebind = min(rebinds) if rebinds else None
        for ln in sorted(loads.get(var, [])):
            if ln <= call_line:
                continue
            if first_rebind is not None and ln >= first_rebind:
                break
            yield rule.finding(
                sf.relpath, ln,
                f"`{var}` was donated to a compiled call on line "
                f"{call_line} and is reused here")
            break  # one finding per donation is enough signal
