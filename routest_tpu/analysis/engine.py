"""rtpulint engine: one parse per file, rules visit shared trees.

The runtime stack enforces its conventions with chaos injection, SLO
gates, and verified swaps — this package enforces them *at rest*. The
engine is deliberately small: a corpus loader that parses every package
file exactly once (plus the docs/registries the drift rules
cross-reference), a rule registry, suppression comments, and a
checked-in baseline so the gate is zero-new-findings from day one.

Vocabulary:

- **Rule** — one named invariant (``silent-except``,
  ``env-knob-undeclared``, …). Each rule walks the shared corpus and
  yields :class:`Finding`\\ s with a file:line anchor, a severity, and a
  one-line fix hint.
- **Suppression** — ``# rtpulint: disable=<rule>[,<rule>…] -- <reason>``
  on the offending line (or a standalone comment on the line directly
  above). The reason is REQUIRED: a suppression without one does not
  suppress and is itself reported (``bad-suppression``).
- **Baseline** — ``analysis/baseline.json``: grandfathered findings
  keyed by (rule, file, line), each entry carrying a mandatory
  ``reason``. Baselined findings don't fail the gate; stale entries
  (matching nothing) are reported so the file shrinks over time.

See docs/ANALYSIS.md for the rule catalog and the adding-a-rule recipe.
"""

from __future__ import annotations

import ast
import dataclasses
import json
import os
import re
from typing import Dict, Iterable, Iterator, List, Optional, Sequence, Set, Tuple

SUPPRESS_RE = re.compile(
    r"#\s*rtpulint:\s*disable=([A-Za-z0-9_,\-]+)"
    r"(?:\s*--\s*(\S.*))?")


@dataclasses.dataclass(frozen=True)
class Finding:
    """One diagnostic: ``file:line: [rule] severity: message``."""

    rule: str
    file: str          # repo-relative posix path
    line: int
    message: str
    hint: str = ""
    severity: str = "error"

    def format(self) -> str:
        tail = f"  (fix: {self.hint})" if self.hint else ""
        return (f"{self.file}:{self.line}: [{self.rule}] "
                f"{self.severity}: {self.message}{tail}")

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)


@dataclasses.dataclass(frozen=True)
class Suppression:
    rules: Tuple[str, ...]   # rule ids, or ("all",)
    reason: str              # empty ⇒ invalid (bad-suppression)
    line: int                # the comment's own line

    def covers(self, rule: str) -> bool:
        return bool(self.reason) and ("all" in self.rules
                                      or rule in self.rules)


class SourceFile:
    """One parsed package file, shared by every rule.

    ``tree`` is parsed once; ``nodes()`` memoizes the full walk so N
    rules cost one traversal, not N. ``parent_of`` gives lexical
    parents (filled during the single walk) for rules that need the
    enclosing function/with statement.
    """

    def __init__(self, path: str, relpath: str, text: str) -> None:
        self.path = path
        self.relpath = relpath
        self.text = text
        self.lines = text.splitlines()
        self.tree = ast.parse(text, filename=path)
        self._all_nodes: Optional[List[ast.AST]] = None
        self._parents: Dict[int, ast.AST] = {}
        # line -> active suppressions (comment's own line, plus the
        # next line for standalone comments).
        self.suppressions: Dict[int, List[Suppression]] = {}
        self.bad_suppressions: List[int] = []
        self._scan_suppressions()

    def _scan_suppressions(self) -> None:
        for i, raw in enumerate(self.lines, start=1):
            m = SUPPRESS_RE.search(raw)
            if not m:
                continue
            rules = tuple(r.strip() for r in m.group(1).split(",")
                          if r.strip())
            reason = (m.group(2) or "").strip()
            if not reason or not rules:
                self.bad_suppressions.append(i)
                continue
            sup = Suppression(rules=rules, reason=reason, line=i)
            self.suppressions.setdefault(i, []).append(sup)
            if raw.lstrip().startswith("#"):
                # Standalone comment: covers the line it precedes.
                self.suppressions.setdefault(i + 1, []).append(sup)

    def suppressed(self, rule: str, line: int) -> bool:
        return any(s.covers(rule) for s in self.suppressions.get(line, ()))

    def nodes(self) -> List[ast.AST]:
        """Every AST node, single cached walk; fills parent links."""
        if self._all_nodes is None:
            out: List[ast.AST] = []
            stack: List[ast.AST] = [self.tree]
            while stack:
                node = stack.pop()
                out.append(node)
                for child in ast.iter_child_nodes(node):
                    self._parents[id(child)] = node
                    stack.append(child)
            self._all_nodes = out
        return self._all_nodes

    def parent_of(self, node: ast.AST) -> Optional[ast.AST]:
        self.nodes()
        return self._parents.get(id(node))

    def ancestors(self, node: ast.AST) -> Iterator[ast.AST]:
        cur = self.parent_of(node)
        while cur is not None:
            yield cur
            cur = self.parent_of(cur)


class Corpus:
    """Everything the rules look at: the parsed package plus the
    registries the drift detectors cross-reference (``core/config.py``
    source, ``docs/*.md`` text)."""

    def __init__(self, root: str, files: List[SourceFile],
                 docs: Dict[str, str]) -> None:
        self.root = root
        self.files = files
        self.docs = docs            # "API.md" -> text (empty if absent)
        self._by_rel = {f.relpath: f for f in files}

    def file(self, relpath: str) -> Optional[SourceFile]:
        return self._by_rel.get(relpath)

    def doc(self, name: str) -> str:
        return self.docs.get(name, "")

    def doc_line_of(self, name: str, token: str) -> int:
        """First line of ``token`` in docs/<name> (1-based; 1 when
        absent) — anchors findings inside doc files."""
        for i, line in enumerate(self.doc(name).splitlines(), start=1):
            if token in line:
                return i
        return 1


def repo_root() -> str:
    """The directory holding ``routest_tpu/`` (and, in a checkout,
    ``docs/``)."""
    import routest_tpu

    pkg = os.path.dirname(os.path.abspath(routest_tpu.__file__))
    return os.path.dirname(pkg)


def load_corpus(root: Optional[str] = None) -> Corpus:
    root = os.path.abspath(root or repo_root())
    pkg_root = os.path.join(root, "routest_tpu")
    files: List[SourceFile] = []
    for dirpath, dirnames, filenames in os.walk(pkg_root):
        dirnames[:] = sorted(d for d in dirnames if d != "__pycache__")
        for name in sorted(filenames):
            if not name.endswith(".py"):
                continue
            path = os.path.join(dirpath, name)
            rel = os.path.relpath(path, root).replace(os.sep, "/")
            with open(path, "r", encoding="utf-8") as f:
                files.append(SourceFile(path, rel, f.read()))
    docs: Dict[str, str] = {}
    docs_dir = os.path.join(root, "docs")
    if os.path.isdir(docs_dir):
        for name in sorted(os.listdir(docs_dir)):
            if name.endswith(".md"):
                with open(os.path.join(docs_dir, name), "r",
                          encoding="utf-8") as f:
                    docs[name] = f.read()
    return Corpus(root, files, docs)


@dataclasses.dataclass(frozen=True)
class Rule:
    """One registered invariant. ``check`` yields raw findings; the
    engine applies suppressions and the baseline afterwards."""

    id: str
    severity: str
    description: str
    hint: str
    check: "RuleFn"

    def finding(self, file: str, line: int, message: str,
                hint: Optional[str] = None) -> Finding:
        return Finding(rule=self.id, file=file, line=line, message=message,
                       hint=self.hint if hint is None else hint,
                       severity=self.severity)


RuleFn = "Callable[[Rule, Corpus], Iterator[Finding]]"

_REGISTRY: "Dict[str, Rule]" = {}


def register(rule_id: str, severity: str, description: str, hint: str):
    """Decorator: register ``fn(rule, corpus) -> Iterator[Finding]``."""

    def wrap(fn):
        if rule_id in _REGISTRY:
            raise ValueError(f"duplicate rule id {rule_id!r}")
        _REGISTRY[rule_id] = Rule(id=rule_id, severity=severity,
                                  description=description, hint=hint,
                                  check=fn)
        return fn

    return wrap


def all_rules() -> Dict[str, Rule]:
    # Importing the rule modules populates the registry exactly once.
    from routest_tpu.analysis import drift, invariants, jaxrules  # noqa: F401

    return dict(_REGISTRY)


# ---------------------------------------------------------------------------
# Baseline

BASELINE_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "baseline.json")


@dataclasses.dataclass(frozen=True)
class BaselineEntry:
    rule: str
    file: str
    line: int
    reason: str

    def key(self) -> Tuple[str, str, int]:
        return (self.rule, self.file, self.line)


def load_baseline(path: Optional[str] = None
                  ) -> Tuple[List[BaselineEntry], List[str]]:
    """→ (entries, errors). Errors are structural problems — a missing
    reason, a malformed entry — that must fail the gate: an undocumented
    grandfather defeats the point of grandfathering."""
    path = path or BASELINE_PATH
    if not os.path.exists(path):
        return [], []
    try:
        with open(path, "r", encoding="utf-8") as f:
            raw = json.load(f)
    except (OSError, ValueError) as e:
        return [], [f"baseline unreadable: {e}"]
    entries: List[BaselineEntry] = []
    errors: List[str] = []
    for i, item in enumerate(raw if isinstance(raw, list) else []):
        if not isinstance(item, dict):
            errors.append(f"baseline[{i}]: not an object")
            continue
        rule = item.get("rule")
        file = item.get("file")
        line = item.get("line")
        reason = (item.get("reason") or "").strip()
        if not (isinstance(rule, str) and isinstance(file, str)
                and isinstance(line, int)):
            errors.append(f"baseline[{i}]: needs rule/file/line")
            continue
        if not reason:
            errors.append(
                f"baseline[{i}] ({rule} {file}:{line}): reason required")
            continue
        entries.append(BaselineEntry(rule=rule, file=file, line=line,
                                     reason=reason))
    if not isinstance(raw, list):
        errors.append("baseline must be a JSON list")
    return entries, errors


# ---------------------------------------------------------------------------
# Analysis run

@dataclasses.dataclass
class AnalysisResult:
    findings: List[Finding]          # actionable: unsuppressed+unbaselined
    suppressed: List[Finding]
    baselined: List[Finding]
    stale_baseline: List[BaselineEntry]
    baseline_errors: List[str]
    files_scanned: int
    rules_run: Tuple[str, ...]

    @property
    def gate_ok(self) -> bool:
        return not self.findings and not self.baseline_errors

    def as_dict(self) -> dict:
        return {
            "gate_ok": self.gate_ok,
            "files_scanned": self.files_scanned,
            "rules_run": list(self.rules_run),
            "findings": [f.as_dict() for f in self.findings],
            "suppressed": [f.as_dict() for f in self.suppressed],
            "baselined": [f.as_dict() for f in self.baselined],
            "stale_baseline": [dataclasses.asdict(e)
                               for e in self.stale_baseline],
            "baseline_errors": list(self.baseline_errors),
        }


def analyze(corpus: Optional[Corpus] = None,
            rules: Optional[Sequence[str]] = None,
            baseline_path: Optional[str] = None,
            use_baseline: bool = True) -> AnalysisResult:
    """Run rules over the corpus, apply suppressions + baseline."""
    corpus = corpus or load_corpus()
    registry = all_rules()
    if rules:
        unknown = sorted(set(rules) - set(registry))
        if unknown:
            raise KeyError(f"unknown rule(s): {', '.join(unknown)} "
                           f"(have: {', '.join(sorted(registry))})")
        selected = [registry[r] for r in rules]
    else:
        selected = [registry[r] for r in sorted(registry)]

    raw: List[Finding] = []
    for rule in selected:
        raw.extend(rule.check(rule, corpus))

    # Suppression comments missing a reason are findings themselves
    # (the required-reason contract), regardless of rule selection.
    bad_sup = registry.get("bad-suppression")
    if bad_sup is not None:
        for sf in corpus.files:
            for line in sf.bad_suppressions:
                raw.append(bad_sup.finding(
                    sf.relpath, line,
                    "rtpulint suppression without a reason "
                    "(or without rule ids) — it is being IGNORED"))

    entries, baseline_errors = ([], []) if not use_baseline else \
        load_baseline(baseline_path)
    by_key = {e.key(): e for e in entries}
    matched: Set[Tuple[str, str, int]] = set()

    findings: List[Finding] = []
    suppressed: List[Finding] = []
    baselined: List[Finding] = []
    for f in sorted(raw, key=lambda f: (f.file, f.line, f.rule)):
        sf = corpus.file(f.file)
        if sf is not None and sf.suppressed(f.rule, f.line):
            suppressed.append(f)
            continue
        key = (f.rule, f.file, f.line)
        if key in by_key:
            matched.add(key)
            baselined.append(f)
            continue
        findings.append(f)
    stale = [e for e in entries if e.key() not in matched]
    return AnalysisResult(
        findings=findings, suppressed=suppressed, baselined=baselined,
        stale_baseline=stale, baseline_errors=baseline_errors,
        files_scanned=len(corpus.files),
        rules_run=tuple(r.id for r in selected))


# ---------------------------------------------------------------------------
# Shared AST helpers (used by rule modules)

def dotted_name(node: ast.AST) -> str:
    """``a.b.c`` for Name/Attribute chains; "" when not a plain chain."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        base = dotted_name(node.value)
        return f"{base}.{node.attr}" if base else node.attr
    if isinstance(node, ast.Call):
        return dotted_name(node.func)
    return ""


def call_leaf(node: ast.Call) -> str:
    """The rightmost name of the call target (``sendall`` for
    ``self._conn.sendall``)."""
    fn = node.func
    if isinstance(fn, ast.Attribute):
        return fn.attr
    if isinstance(fn, ast.Name):
        return fn.id
    return ""


def exc_type_names(node: Optional[ast.AST]) -> Set[str]:
    """Exception-type expr → dotted-name leaves; bare ⇒ {"<bare>"}."""
    if node is None:
        return {"<bare>"}
    if isinstance(node, ast.Tuple):
        out: Set[str] = set()
        for elt in node.elts:
            out |= exc_type_names(elt)
        return out
    if isinstance(node, ast.Name):
        return {node.id}
    if isinstance(node, ast.Attribute):
        return {node.attr}
    return {"<expr>"}


# The bad-suppression pseudo-rule lives here so the engine can always
# emit it (it has no check of its own — the scanner feeds it).
register(
    "bad-suppression", "error",
    "a `# rtpulint: disable=` comment must name rule ids and carry a "
    "`-- <reason>`; without one it is ignored, which silently re-arms "
    "the lint it meant to waive",
    "write `# rtpulint: disable=<rule> -- <why this is safe here>`",
)(lambda rule, corpus: iter(()))
