"""Capacity- and range-constrained greedy VRP as XLA control flow.

Semantics-compatible with the reference's solver (``Flaskr/utils.py:111-139``,
SURVEY.md §7.3 item 3), whose observable behavior is:

- multi-trip: while stops remain, open a trip at the origin;
- candidates are scanned in order of distance **from the origin** (the
  reference sorts once per trip while ``current`` is still the origin);
- a candidate is accepted if the trip's load stays within
  ``vehicle_capacity`` AND trip distance + leg + return-to-origin stays
  within ``maximum_distance``; on accept only the leg (not the return) is
  added to the running trip distance;
- accepted stops are visited in scan order; the trip implicitly returns to
  the origin; leftovers spill into the next trip.

Two deliberate deviations, both safety fixes rather than behavior changes:

- stops that are *individually* infeasible (demand > capacity, or
  origin→stop→origin > maximum_distance) are marked unroutable and skipped;
  the reference's loop never terminates on such input;
- everything is fixed-shape: ``order``/``trip_ids`` are -1-padded arrays,
  so the whole solve jits, vmaps over problem batches, and shards over the
  mesh data axis — batch-of-problems is the parallel axis (one VRP is
  sequential by nature).

The sequential inner structure is a ``lax.while_loop`` over trips with a
``lax.scan`` over origin-sorted candidates inside — data-dependent control
flow the XLA-native way, no Python loops in the hot path.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np


class VRPSolution(NamedTuple):
    order: jax.Array      # (N,) destination indices in visit order, -1 padded
    trip_ids: jax.Array   # (N,) trip index per position in ``order``, -1 padded
    n_trips: jax.Array    # () int32
    n_routed: jax.Array   # () int32 — how many stops were placed
    unroutable: jax.Array  # (N,) bool — individually infeasible stops


class _TripState(NamedTuple):
    visited: jax.Array
    order: jax.Array
    trip_ids: jax.Array
    pos: jax.Array
    trip: jax.Array


class _ScanState(NamedTuple):
    current: jax.Array    # current node in all_points indexing (0 = origin)
    load: jax.Array
    trip_dist: jax.Array
    accepted_any: jax.Array
    st: _TripState


@functools.partial(jax.jit, static_argnames=())
def greedy_vrp(
    dist: jax.Array,       # (N+1, N+1) distance matrix, row/col 0 = origin
    demands: jax.Array,    # (N,) payload per destination
    capacity: jax.Array,   # () vehicle capacity
    max_distance: jax.Array,  # () max trip distance (incl. return leg check)
) -> VRPSolution:
    n = dist.shape[0] - 1
    demands = demands.astype(dist.dtype)

    # Individually infeasible stops would make the reference's loop spin
    # forever; mask them out up front.
    roundtrip = dist[0, 1:] + dist[1:, 0]
    unroutable = (demands > capacity) | (roundtrip > max_distance)

    # The reference sorts candidates by distance-from-origin (the sort key
    # is evaluated before ``current`` moves), so the scan order is the same
    # for every trip and can be computed once.
    scan_order = jnp.argsort(dist[0, 1:])  # destination indices 0..n-1

    init = _TripState(
        visited=unroutable,  # treat unroutable as pre-visited
        order=jnp.full((n,), -1, jnp.int32),
        trip_ids=jnp.full((n,), -1, jnp.int32),
        pos=jnp.zeros((), jnp.int32),
        trip=jnp.zeros((), jnp.int32),
    )

    def trips_remain(st: _TripState) -> jax.Array:
        return ~st.visited.all()

    def run_trip(st: _TripState) -> _TripState:
        def visit(s: _ScanState, j: jax.Array):
            node = j + 1  # all_points index of destination j
            leg = dist[s.current, node]
            accept = (
                ~s.st.visited[j]
                & (s.load + demands[j] <= capacity)
                & (s.trip_dist + leg + dist[node, 0] <= max_distance)
            )
            st2 = s.st
            st2 = st2._replace(
                visited=st2.visited.at[j].set(st2.visited[j] | accept),
                order=st2.order.at[st2.pos].set(
                    jnp.where(accept, j, st2.order[st2.pos])
                ),
                trip_ids=st2.trip_ids.at[st2.pos].set(
                    jnp.where(accept, st2.trip, st2.trip_ids[st2.pos])
                ),
                pos=st2.pos + accept.astype(jnp.int32),
            )
            return (
                _ScanState(
                    current=jnp.where(accept, node, s.current),
                    load=s.load + jnp.where(accept, demands[j], 0.0),
                    trip_dist=s.trip_dist + jnp.where(accept, leg, 0.0),
                    accepted_any=s.accepted_any | accept,
                    st=st2,
                ),
                None,
            )

        scan_init = _ScanState(
            current=jnp.zeros((), jnp.int32),
            load=jnp.zeros((), dist.dtype),
            trip_dist=jnp.zeros((), dist.dtype),
            accepted_any=jnp.zeros((), jnp.bool_),
            st=st,
        )
        out, _ = jax.lax.scan(visit, scan_init, scan_order)
        # advance the trip counter only if the trip placed something
        # (it always does for feasible stops, but stay safe).
        return out.st._replace(trip=out.st.trip + out.accepted_any.astype(jnp.int32))

    final = jax.lax.while_loop(trips_remain, run_trip, init)
    return VRPSolution(
        order=final.order,
        trip_ids=final.trip_ids,
        n_trips=final.trip,
        n_routed=final.pos,
        unroutable=unroutable,
    )


# Batched solve: many problems at once — the mesh-parallel axis.
greedy_vrp_batch = jax.jit(jax.vmap(greedy_vrp, in_axes=(0, 0, 0, 0)))


@jax.jit
def refine_2opt(dist: jax.Array, order: jax.Array,
                trip_ids: jax.Array) -> jax.Array:
    """2-opt local search over a greedy solution — beyond-reference
    quality at zero ABI cost.

    The reference stops at greedy nearest-neighbor (``Flaskr/utils.py:
    111-139``); this pass repeatedly reverses the tour segment whose
    reversal shortens the route most, until no improving move remains.
    All moves stay inside one trip (positions of a trip are contiguous in
    the greedy output), so per-trip load is untouched, and each move
    strictly shortens that trip's closed tour — feasibility under
    ``maximum_distance`` is preserved because the greedy tour already
    satisfied it.

    Requires a symmetric distance matrix (the classic 2-opt delta
    evaluates a segment reversal in O(1) only when d[a,b] == d[b,a]);
    ``geo.distance_matrix_m`` is haversine-based and symmetric.

    Fixed-shape XLA control flow: one ``lax.while_loop`` whose body
    evaluates all O(N²) candidate deltas as gathers and applies the best
    via an index permutation — jittable, vmappable, shardable like the
    solver itself.

    Returns the refined ``order`` (same -1 padding; ``trip_ids`` are
    unchanged by construction).
    """
    n = order.shape[0]
    pos = jnp.arange(n)

    def deltas(order):
        nodes = jnp.where(order >= 0, order + 1, 0)
        same_prev = jnp.concatenate(
            [jnp.zeros((1,), jnp.bool_), trip_ids[1:] == trip_ids[:-1]])
        prev = jnp.where(
            same_prev, jnp.concatenate([jnp.zeros((1,), nodes.dtype), nodes[:-1]]), 0)
        same_next = jnp.concatenate(
            [trip_ids[:-1] == trip_ids[1:], jnp.zeros((1,), jnp.bool_)])
        nxt = jnp.where(
            same_next, jnp.concatenate([nodes[1:], jnp.zeros((1,), nodes.dtype)]), 0)
        # delta(i, j) = cost of reversing positions i..j within one trip
        d = (dist[prev[:, None], nodes[None, :]]
             + dist[nodes[:, None], nxt[None, :]]
             - dist[prev, nodes][:, None]
             - dist[nodes, nxt][None, :])
        valid = ((pos[:, None] < pos[None, :])
                 & (trip_ids[:, None] == trip_ids[None, :])
                 & (trip_ids >= 0)[:, None])
        return jnp.where(valid, d, jnp.inf)

    def best_move(order):
        d = deltas(order).reshape(-1)
        flat = jnp.argmin(d)
        return flat, d[flat]

    # The best move is carried in the loop state so the O(N²) delta
    # matrix is evaluated once per iteration (XLA does not CSE between a
    # while_loop's cond and body).
    def improving(state):
        _, _, best_delta, it = state
        return (best_delta < -1e-3) & (it < n * n)

    def apply_best(state):
        order, flat, _, it = state
        i, j = flat // n, flat % n
        perm = jnp.where((pos >= i) & (pos <= j), i + j - pos, pos)
        order = order[perm]
        flat2, delta2 = best_move(order)
        return order, flat2, delta2, it + 1

    flat0, delta0 = best_move(order)
    refined, _, _, _ = jax.lax.while_loop(
        improving, apply_best, (order, flat0, delta0, jnp.zeros((), jnp.int32)))
    return refined


refine_2opt_batch = jax.jit(jax.vmap(refine_2opt, in_axes=(0, 0, 0)))


class _RelocateOut(NamedTuple):
    order: jax.Array
    trip_ids: jax.Array


class _TourViews(NamedTuple):
    """Fixed-shape per-position views over a (order, trip_ids) tour —
    the shared analysis prologue of every cross-trip refiner (relocate,
    swap, Or-opt-2). Padded positions are zeroed via the masks."""

    active: jax.Array     # (N,) bool — position holds a stop
    nodes: jax.Array      # (N,) all_points index of the stop (0 if pad)
    dem: jax.Array        # (N,) demand at the position
    same_prev: jax.Array  # (N,) previous position is same trip
    prev: jax.Array       # (N,) previous node along the trip (0 = origin)
    same_next: jax.Array  # (N,) next position is same trip
    nxt: jax.Array        # (N,) next node along the trip (0 = origin)
    loads: jax.Array      # (T=N,) per-trip load
    tripdist: jax.Array   # (T=N,) per-trip closed-tour distance


def _tour_views(dist: jax.Array, demands: jax.Array, order: jax.Array,
                trip_ids: jax.Array) -> _TourViews:
    n = order.shape[0]
    pos = jnp.arange(n)
    active = order >= 0
    nodes = jnp.where(active, order + 1, 0)
    dem = jnp.where(active, demands[jnp.clip(order, 0)], 0.0)
    same_prev = jnp.concatenate(
        [jnp.zeros((1,), jnp.bool_),
         (trip_ids[1:] == trip_ids[:-1]) & (trip_ids[1:] >= 0)])
    prev = jnp.where(
        same_prev,
        jnp.concatenate([jnp.zeros((1,), nodes.dtype), nodes[:-1]]), 0)
    same_next = jnp.concatenate(
        [(trip_ids[:-1] == trip_ids[1:]) & (trip_ids[:-1] >= 0),
         jnp.zeros((1,), jnp.bool_)])
    nxt = jnp.where(
        same_next,
        jnp.concatenate([nodes[1:], jnp.zeros((1,), nodes.dtype)]), 0)
    # Per-trip load and closed-tour distance (one-hot segment sums;
    # T = N upper-bounds the trip count).
    tid_oh = ((trip_ids[None, :] == pos[:, None]) & active[None, :])
    loads = (tid_oh * dem[None, :]).sum(axis=1)
    leg_in = jnp.where(active, dist[prev, nodes], 0.0)
    ret = jnp.where(active & ~same_next, dist[nodes, 0], 0.0)
    tripdist = (tid_oh * (leg_in + ret)[None, :]).sum(axis=1)
    return _TourViews(active, nodes, dem, same_prev, prev, same_next, nxt,
                      loads, tripdist)


@jax.jit
def refine_relocate(dist: jax.Array, demands: jax.Array, capacity: jax.Array,
                    max_distance: jax.Array, order: jax.Array,
                    trip_ids: jax.Array) -> _RelocateOut:
    """Cross-trip relocate (Or-opt-1): move one stop anywhere — including
    into ANOTHER trip — when it shortens the total tour and stays
    feasible.

    2-opt (above) can never move a stop across trips, so multi-trip
    greedy solutions keep whatever trip assignment nearest-neighbor
    produced (the reference never refines at all, ``Flaskr/utils.py:
    111-139``). This pass evaluates every (stop i, insertion slot) pair:
    slots are "after position j" and "before the head of j's trip"
    (distinct in cost and trip membership via the origin legs), checks
    target-trip capacity and max-distance feasibility, applies the best
    improving move as an index rotation, and repeats to fixpoint.

    Fixed-shape throughout: O(N²) move deltas per iteration as gathers,
    one ``lax.while_loop`` — jittable, vmappable, mesh-shardable.
    Requires a symmetric distance matrix like ``refine_2opt``. Trips stay
    contiguous position-ranges by construction; emptied trips simply
    vanish (ids stay, ``solve_host`` compacts).
    """
    n = order.shape[0]
    pos = jnp.arange(n)
    demands = demands.astype(dist.dtype)
    big = jnp.asarray(jnp.inf, dist.dtype)

    def analyze(order, trip_ids):
        """Best move: (delta, i, target_pos, tgt_trip)."""
        v = _tour_views(dist, demands, order, trip_ids)
        active, nodes, dem = v.active, v.nodes, v.dem
        same_prev, prev, nxt = v.same_prev, v.prev, v.nxt
        loads, tripdist = v.loads, v.tripdist

        # Removal gain of stop at position i.
        gain = dist[prev, nodes] + dist[nodes, nxt] - dist[prev, nxt]  # (N,)

        # Insertion costs: [i, j] = stop i into slot j.
        ins_after = (dist[nodes[None, :], nodes[:, None]]
                     + dist[nodes[:, None], nxt[None, :]]
                     - dist[nodes, nxt][None, :])
        ins_head = (dist[0, nodes][:, None]
                    + dist[nodes[:, None], nodes[None, :]]
                    - dist[0, nodes][None, :])
        costs = jnp.stack([ins_after, ins_head])                # (2, N, N)

        src = trip_ids[:, None]                                  # by i
        tgt = trip_ids[None, :]                                  # by j
        same_trip = src == tgt
        delta = costs - gain[:, None][None, :, :]

        # Feasibility per move.
        cap_ok = jnp.where(
            same_trip, True,
            loads[jnp.clip(tgt, 0)] + dem[:, None] <= capacity)
        newdist = jnp.where(
            same_trip,
            tripdist[jnp.clip(src, 0)] + costs - gain[:, None],
            tripdist[jnp.clip(tgt, 0)] + costs)
        dist_ok = newdist <= max_distance + 1e-3

        both_active = active[:, None] & active[None, :]
        not_self = pos[:, None] != pos[None, :]
        # after-mode no-op: inserting i right back after its predecessor
        after_noop = same_trip & (pos[None, :] == pos[:, None] - 1)
        valid_after = both_active & not_self & ~after_noop
        head_j = active & ~same_prev  # j is the first stop of its trip
        valid_head = both_active & not_self & head_j[None, :]
        valid = jnp.stack([valid_after, valid_head]) & cap_ok & dist_ok

        scored = jnp.where(valid, delta, big)
        flat = jnp.argmin(scored.reshape(-1))
        best_delta = scored.reshape(-1)[flat]
        mode = flat // (n * n)
        ij = flat % (n * n)
        i, j = ij // n, ij % n
        # Final flat position of the moved element (see module docstring
        # derivation): insert-before-head(j) occupies the same flat slot
        # as insert-after(j-1); only the trip id differs.
        t_after = jnp.where(i < j, j, j + 1)
        t_head = jnp.where(i < j, j - 1, j)
        target = jnp.where(mode == 0, t_after, t_head)
        return best_delta, i, target, trip_ids[j]

    def improving(state):
        order, trip_ids, delta, i, t, tgt_trip, it = state
        return (delta < -1e-3) & (it < n * n)

    def apply_move(state):
        order, trip_ids, delta, i, t, tgt_trip, it = state
        fwd = (pos >= i) & (pos < t)          # i <= p < t: shift left
        bwd = (pos > t) & (pos <= i)          # t < p <= i: shift right
        perm = jnp.where(fwd, pos + 1, jnp.where(bwd, pos - 1, pos))
        perm = jnp.where(pos == t, i, perm)
        order = order[perm]
        trip_ids = trip_ids[perm].at[t].set(tgt_trip)
        delta2, i2, t2, tgt2 = analyze(order, trip_ids)
        return order, trip_ids, delta2, i2, t2, tgt2, it + 1

    d0, i0, t0, g0 = analyze(order, trip_ids)
    out = jax.lax.while_loop(
        improving, apply_move,
        (order, trip_ids, d0, i0, t0, g0, jnp.zeros((), jnp.int32)))
    return _RelocateOut(order=out[0], trip_ids=out[1])


refine_relocate_batch = jax.jit(
    jax.vmap(refine_relocate, in_axes=(0, 0, 0, 0, 0, 0)))


@jax.jit
def refine_swap(dist: jax.Array, demands: jax.Array, capacity: jax.Array,
                max_distance: jax.Array, order: jax.Array,
                trip_ids: jax.Array) -> jax.Array:
    """Cross-trip SWAP (exchange): trade one stop between two trips.

    The move relocate cannot make: when BOTH trips are at capacity, no
    single stop can move anywhere (inserting it overloads the target),
    yet exchanging a misassigned pair is feasible — loads change by the
    demand DIFFERENCE only. Swaps are restricted to pairs in different
    trips (cross-trip is the gap being closed; same-trip resequencing
    belongs to 2-opt, and cross-trip pairs share no tour edges so the
    O(1) delta formulas are exact).

    Fixed-shape like its siblings: all O(N²) exchange deltas per
    iteration as gathers, best feasible improving swap applied as two
    scatters (``trip_ids`` are positional and unchanged), loop to
    fixpoint. Requires a symmetric distance matrix.

    Returns the refined ``order``.
    """
    n = order.shape[0]
    pos = jnp.arange(n)
    demands = demands.astype(dist.dtype)
    big = jnp.asarray(jnp.inf, dist.dtype)

    def analyze(order):
        v = _tour_views(dist, demands, order, trip_ids)
        active, nodes, dem = v.active, v.nodes, v.dem
        prev, nxt = v.prev, v.nxt
        loads, tripdist = v.loads, v.tripdist

        # replace_cost[i, j] = new edge cost at position i if node_j sat
        # there; replace_cost[i, i]-diagonal is the current cost
        rc = (dist[prev[:, None], nodes[None, :]]
              + dist[nodes[None, :], nxt[:, None]])              # (N, N)
        cur = dist[prev, nodes] + dist[nodes, nxt]               # (N,)
        delta_at = rc - cur[:, None]         # [i, j]: put j's node at i
        delta = delta_at + delta_at.T        # full swap of positions i, j

        src = trip_ids[:, None]
        tgt = trip_ids[None, :]
        diff_trip = (src != tgt) & active[:, None] & active[None, :]
        dd = dem[:, None] - dem[None, :]     # [i, j]: load change at j's trip
        cap_ok = ((loads[jnp.clip(src, 0)] - dd <= capacity)
                  & (loads[jnp.clip(tgt, 0)] + dd <= capacity))
        dist_ok = ((tripdist[jnp.clip(src, 0)] + delta_at <= max_distance + 1e-3)
                   & (tripdist[jnp.clip(tgt, 0)] + delta_at.T
                      <= max_distance + 1e-3))
        scored = jnp.where(diff_trip & cap_ok & dist_ok
                           & (pos[:, None] < pos[None, :]), delta, big)
        flat = jnp.argmin(scored.reshape(-1))
        return scored.reshape(-1)[flat], flat // n, flat % n

    def improving(state):
        order, delta, i, j, it = state
        return (delta < -1e-3) & (it < n * n)

    def apply_swap(state):
        order, _, i, j, it = state
        oi, oj = order[i], order[j]
        order = order.at[i].set(oj).at[j].set(oi)
        delta2, i2, j2 = analyze(order)
        return order, delta2, i2, j2, it + 1

    d0, i0, j0 = analyze(order)
    out = jax.lax.while_loop(
        improving, apply_swap, (order, d0, i0, j0, jnp.zeros((), jnp.int32)))
    return out[0]


refine_swap_batch = jax.jit(
    jax.vmap(refine_swap, in_axes=(0, 0, 0, 0, 0, 0)))


def _refine_oropt_impl(dist: jax.Array, demands: jax.Array,
                       capacity: jax.Array, max_distance: jax.Array,
                       order: jax.Array, trip_ids: jax.Array,
                       seg_len: int) -> _RelocateOut:
    """Or-opt-L: relocate an ADJACENT SEGMENT of ``seg_len`` stops as one
    unit — within a trip or across trips — when it shortens the tour and
    stays feasible.

    The move the other passes cannot make: relocate (Or-opt-1) moves one
    stop at a time, so a misplaced segment whose first stop only pays
    off once the rest follows sits at a local optimum; swap exchanges
    1-for-1; 2-opt reverses within a trip. Moving the segment keeps its
    internal legs (orientation preserved — reversals are 2-opt's job)
    and re-prices only the three boundary legs.

    Same fixed-shape recipe as :func:`refine_relocate`: O(N²)
    segment/slot deltas as gathers, best improving move applied as an
    index permutation, ``lax.while_loop`` to fixpoint. Symmetric matrix
    assumed, like the other refiners. ``seg_len`` is static (one
    compiled program per length; the standard Or-opt family is 2 and 3).
    """
    n = order.shape[0]
    k = seg_len - 1  # shift from segment start to segment end
    pos = jnp.arange(n)
    demands = demands.astype(dist.dtype)
    big = jnp.asarray(jnp.inf, dist.dtype)

    def _shift(a, by):
        return jnp.concatenate([a[by:], jnp.zeros((by,), a.dtype)]) \
            if by else a

    def analyze(order, trip_ids):
        v = _tour_views(dist, demands, order, trip_ids)
        active, nodes, dem = v.active, v.nodes, v.dem
        same_prev, prev, same_next, nxt = (v.same_prev, v.prev,
                                           v.same_next, v.nxt)
        loads, tripdist = v.loads, v.tripdist

        # Segment [i, i+k]: end node / end next-link rolled so lane i
        # carries the whole segment; windowed demand / contiguity /
        # internal-leg sums via static shifts.
        s_end = _shift(nodes, k)
        nxt_end = _shift(nxt, k)
        seg_ok = active
        seg_dem = dem
        edge = jnp.where(same_next, dist[nodes, _shift(nodes, 1)], 0.0)
        internal = jnp.zeros_like(edge)
        for step in range(k):
            seg_ok = seg_ok & _shift(same_next, step)
            seg_dem = seg_dem + _shift(dem, step + 1)
            internal = internal + _shift(edge, step)
        internal = jnp.where(seg_ok, internal, 0.0)

        # Removal gain of the segment (internal legs travel with it).
        gain = dist[prev, nodes] + dist[s_end, nxt_end] - dist[prev, nxt_end]

        # Insertion: after stop j, or before the head of j's trip.
        ins_after = (dist[nodes[None, :], nodes[:, None]]
                     + dist[s_end[:, None], nxt[None, :]]
                     - dist[nodes, nxt][None, :])
        ins_head = (dist[0, nodes][:, None]
                    + dist[s_end[:, None], nodes[None, :]]
                    - dist[0, nodes][None, :])
        costs = jnp.stack([ins_after, ins_head])               # (2, N, N)

        src = trip_ids[:, None]
        tgt = trip_ids[None, :]
        same_trip = src == tgt
        delta = costs - gain[:, None][None, :, :]

        cap_ok = jnp.where(
            same_trip, True,
            loads[jnp.clip(tgt, 0)] + seg_dem[:, None] <= capacity)
        # Cross-trip, the segment's INTERNAL legs move into the target
        # trip too (boundary-only `costs` doesn't count them; same-trip
        # they cancel inside gain).
        newdist = jnp.where(
            same_trip,
            tripdist[jnp.clip(src, 0)] + costs - gain[:, None],
            tripdist[jnp.clip(tgt, 0)] + costs
            + internal[:, None][None, :, :])
        dist_ok = newdist <= max_distance + 1e-3

        # j must lie outside the segment's own positions [i, i+k].
        outside = ((pos[None, :] < pos[:, None])
                   | (pos[None, :] > pos[:, None] + k))
        valid_base = seg_ok[:, None] & active[None, :] & outside
        # after-mode no-op: back after the segment's own predecessor
        after_noop = same_trip & (pos[None, :] == pos[:, None] - 1)
        head_j = active & ~same_prev
        valid = jnp.stack([valid_base & ~after_noop,
                           valid_base & head_j[None, :]]) & cap_ok & dist_ok

        scored = jnp.where(valid, delta, big)
        flat = jnp.argmin(scored.reshape(-1))
        best_delta = scored.reshape(-1)[flat]
        mode = flat // (n * n)
        ij = flat % (n * n)
        i, j = ij // n, ij % n
        # Final START position of the block of seg_len (worked examples
        # for both directions and both modes in tests).
        t_after = jnp.where(i < j, j - k, j + 1)
        t_head = jnp.where(i < j, j - seg_len, j)
        target = jnp.where(mode == 0, t_after, t_head)
        return best_delta, i, target, trip_ids[j]

    def improving(state):
        order, trip_ids, delta, i, t, tgt_trip, it = state
        return (delta < -1e-3) & (it < n * n)

    def apply_move(state):
        order, trip_ids, delta, i, t, tgt_trip, it = state
        fwd = (pos >= i) & (pos < t)                 # block moved forward
        bwd = (pos > t + k) & (pos <= i + k)         # block moved backward
        perm = jnp.where(fwd, pos + seg_len,
                         jnp.where(bwd, pos - seg_len, pos))
        in_block = (pos >= t) & (pos <= t + k)
        perm = jnp.where(in_block, i + (pos - t), perm)
        order = order[perm]
        trip_ids = jnp.where(in_block, tgt_trip, trip_ids[perm])
        delta2, i2, t2, tgt2 = analyze(order, trip_ids)
        return order, trip_ids, delta2, i2, t2, tgt2, it + 1

    d0, i0, t0, g0 = analyze(order, trip_ids)
    out = jax.lax.while_loop(
        improving, apply_move,
        (order, trip_ids, d0, i0, t0, g0, jnp.zeros((), jnp.int32)))
    return _RelocateOut(order=out[0], trip_ids=out[1])


# seg_len must stay OUT of the traced arguments (it drives array shifts
# and permutation arithmetic), so each length gets its own jitted
# partial — closure-captured, never a tracer.
_OROPT_JIT: dict = {}


def refine_oropt(dist, demands, capacity, max_distance, order, trip_ids,
                 *, seg_len: int = 2) -> _RelocateOut:
    fn = _OROPT_JIT.get(seg_len)
    if fn is None:
        # NOT functools.partial (jax.jit unwraps partials and TRACES
        # their bound keywords) and NOT a default argument (defaults get
        # traced too): a true closure variable is the only form that
        # keeps seg_len a Python int through tracing.
        def _make(length: int):
            def _fixed(d, dm, c, m, o, t):
                return _refine_oropt_impl(d, dm, c, m, o, t, length)

            return jax.jit(_fixed)

        fn = _make(int(seg_len))
        _OROPT_JIT[seg_len] = fn
    return fn(dist, demands, capacity, max_distance, order, trip_ids)


def refine_oropt2(dist, demands, capacity, max_distance, order, trip_ids):
    """Or-opt with the classic pair segment (back-compat name)."""
    return refine_oropt(dist, demands, capacity, max_distance, order,
                        trip_ids, seg_len=2)


def refine_oropt3(dist, demands, capacity, max_distance, order, trip_ids):
    return refine_oropt(dist, demands, capacity, max_distance, order,
                        trip_ids, seg_len=3)


refine_oropt2_batch = jax.jit(
    jax.vmap(refine_oropt2, in_axes=(0, 0, 0, 0, 0, 0)))
refine_oropt3_batch = jax.jit(
    jax.vmap(refine_oropt3, in_axes=(0, 0, 0, 0, 0, 0)))


def trips_cost(dist: np.ndarray, trips) -> float:
    """Host-side total closed-tour distance of a trips-list (the
    ``solve_host`` output form): Σ over trips of origin → stops → origin.
    The single cost oracle shared by benchmarks and tests so they score
    exactly the objective the refiners minimize."""
    total = 0.0
    for trip in trips:
        if not trip:
            continue
        total += float(dist[0, trip[0] + 1])
        for a, b in zip(trip[:-1], trip[1:]):
            total += float(dist[a + 1, b + 1])
        total += float(dist[trip[-1] + 1, 0])
    return total


def tour_cost(dist: np.ndarray, order: np.ndarray,
              trip_ids: np.ndarray) -> float:
    """(order, trip_ids)-form view of :func:`trips_cost` — converts the
    padded solver arrays to a trips-list and delegates, so there is one
    cost oracle, not two."""
    trips: list = []
    last_tid = None
    for o, t in zip(order, trip_ids):
        if o < 0:
            break
        if t != last_tid:
            trips.append([])
            last_tid = t
        trips[-1].append(int(o))
    return trips_cost(dist, trips)


def _unpack_solution(order: np.ndarray, trip_ids: np.ndarray,
                     n_routed: int, unroutable: np.ndarray,
                     n_real: int) -> dict:
    """Padded solver arrays → host dict (shared by single and batch).
    ``n_real`` masks batch padding out of the unroutable report."""
    trips: list = []
    for pos in range(n_routed):
        tid = int(trip_ids[pos])
        while len(trips) <= tid:
            trips.append([])
        trips[tid].append(int(order[pos]))
    # relocate may empty a trip entirely; compact so trip counts stay dense
    trips = [t for t in trips if t]
    return {
        "trips": trips,
        "optimized_order": [int(i) for i in order[:n_routed]],
        "n_trips": len(trips),
        "unroutable": [int(i) for i in np.flatnonzero(unroutable[:n_real])],
    }


def solve_host_batch(dists, demands, capacities, max_distances,
                     refine: bool = False,
                     max_refine_rounds: int = 4) -> list:
    """Solve MANY VRPs in one device call — the batch-of-problems axis
    the module docstring promises, on the serving path.

    Inputs are per-problem lists (matrices of varying size); problems
    pad to the batch's max stop count (next power of two, so request
    mixes reuse one compiled program). Padded stops get infinite demand,
    which the solver's feasibility mask treats as pre-visited — they can
    never be routed, cost nothing, and are sliced out of the report.

    ``refine=True`` runs the same 2-opt → relocate → swap → Or-opt-2
    rounds as ``solve_host``, vmapped across the batch; rounds are fixed at
    ``max_refine_rounds`` for the whole batch (every move is
    strictly-no-worse, so extra rounds are no-ops for converged
    problems — per-problem early exit would force host sync per round).
    """
    b = len(dists)
    if b == 0:
        return []
    caps_np = np.asarray(capacities, np.float32)
    maxd_np = np.asarray(max_distances, np.float32)
    # Non-finite constraints make the feasibility mask vacuous (NaN
    # compares False both ways; inf capacity lets the padded phantom
    # stops through) and the while_loop would spin forever / route
    # phantoms. The request path rejects these in _parse_problem; guard
    # the library boundary too.
    if not (np.isfinite(caps_np).all() and np.isfinite(maxd_np).all()):
        raise ValueError("solve_host_batch: capacity/max_distance must be "
                         "finite")
    n_real = [d.shape[0] - 1 for d in dists]
    p = 1 << max(0, (max(n_real) - 1)).bit_length()  # padded stop count
    # Pad the BATCH axis too (dummy all-unroutable problems, sliced off
    # below): otherwise every distinct problem count compiles a fresh
    # while_loop program on the serving path.
    b_pad = 1 << max(0, (b - 1)).bit_length()

    # Padded stops must be structurally unroutable regardless of the
    # problem's constraints: infinite demand (> any finite capacity) AND
    # a huge origin round trip (> any finite max_distance) — belt and
    # suspenders, since either alone can be defeated by extreme but
    # finite inputs on one side.
    _FAR = np.float32(1e30)
    dist_b = np.full((b_pad, p + 1, p + 1), _FAR, np.float32)
    dem_b = np.full((b_pad, p), np.inf, np.float32)
    for i, (d, dem, n) in enumerate(zip(dists, demands, n_real)):
        dist_b[i, : n + 1, : n + 1] = d
        dem_b[i, :n] = dem
    cap_b = jnp.asarray(np.concatenate(
        [caps_np, np.ones(b_pad - b, np.float32)]))
    maxd_b = jnp.asarray(np.concatenate(
        [maxd_np, np.ones(b_pad - b, np.float32)]))
    dist_j = jnp.asarray(dist_b)
    dem_j = jnp.asarray(dem_b)

    sol = greedy_vrp_batch(dist_j, dem_j, cap_b, maxd_b)
    order_j, trips_j = sol.order, sol.trip_ids
    if refine:
        for _ in range(max_refine_rounds):
            order_j = refine_2opt_batch(dist_j, order_j, trips_j)
            order_j, trips_j = refine_relocate_batch(
                dist_j, dem_j, cap_b, maxd_b, order_j, trips_j)
            order_j = refine_swap_batch(
                dist_j, dem_j, cap_b, maxd_b, order_j, trips_j)
            order_j, trips_j = refine_oropt2_batch(
                dist_j, dem_j, cap_b, maxd_b, order_j, trips_j)
            order_j, trips_j = refine_oropt3_batch(
                dist_j, dem_j, cap_b, maxd_b, order_j, trips_j)

    order = np.asarray(order_j)
    trip_ids = np.asarray(trips_j)
    n_routed = np.asarray(sol.n_routed)
    unroutable = np.asarray(sol.unroutable)
    return [
        _unpack_solution(order[i], trip_ids[i], int(n_routed[i]),
                         unroutable[i], n_real[i])
        for i in range(b)
    ]


# ── dispatch variants: time windows + demand spillover ────────────────
#
# The dispatch subsystem (routest_tpu/dispatch/) serves VRPs whose
# stops may carry service time windows and whose demand mix may not fit
# the vehicle at all. Both are handled WITHOUT breaking the fixed shape
# the batcher depends on: infeasible-but-reachable stops spill into a
# single "next-trip penalty lane" appended after the real trips, where
# window lateness accumulates into a scalar penalty instead of an
# exception. Only stops that cannot physically be served (origin round
# trip exceeds the budget) are unroutable.

# Finite "no deadline" sentinel. NOT inf: the test/serving environment
# arms jax_debug_nans, and inf would meet subtraction in the lateness
# term (arrive - tw_close) producing -inf paths that trip it; 1e30 is
# far beyond any real clock and float32-safe (2e30 << float32 max).
NO_WINDOW = 1e30


class DispatchSolution(NamedTuple):
    order: jax.Array      # (N,) stop indices in visit order, -1 padded;
    #                       positions [0, n_routed) are the real trips,
    #                       [n_routed, n_routed + n_spilled) the penalty lane
    trip_ids: jax.Array   # (N,) trip index per position (lane = n_trips)
    n_trips: jax.Array    # () int32 — real trips, penalty lane excluded
    n_routed: jax.Array   # () int32 — stops placed in real trips
    n_spilled: jax.Array  # () int32 — stops placed in the penalty lane
    unroutable: jax.Array  # (N,) bool — physically unservable stops
    spilled: jax.Array    # (N,) bool — reachable but infeasible stops
    penalty: jax.Array    # () total window lateness in the penalty lane


class _DispTripState(NamedTuple):
    visited: jax.Array
    order: jax.Array
    trip_ids: jax.Array
    pos: jax.Array
    trip: jax.Array
    t: jax.Array          # global clock (same unit as ``dist``)
    progress: jax.Array   # last trip accepted ≥ 1 stop


class _DispScanState(NamedTuple):
    current: jax.Array
    load: jax.Array
    trip_dist: jax.Array
    accepted_any: jax.Array
    st: _DispTripState


@jax.jit
def greedy_vrp_dispatch(
    dist: jax.Array,         # (N+1, N+1) cost matrix, row/col 0 = origin
    demands: jax.Array,      # (N,) payload per stop
    capacity: jax.Array,     # () vehicle capacity
    max_distance: jax.Array,  # () max per-trip cost (incl. return check)
    tw_open: jax.Array,      # (N,) earliest service clock per stop
    tw_close: jax.Array,     # (N,) latest service clock (NO_WINDOW = none)
) -> DispatchSolution:
    """Greedy VRP with time windows and a demand-spillover penalty lane.

    Same scan discipline as :func:`greedy_vrp` (origin-sorted candidates,
    capacity + trip-budget acceptance, only the leg accumulates) plus a
    global clock ``t`` that advances through every trip INCLUDING return
    legs: a candidate's arrival is ``max(t + leg, tw_open[j])`` (early
    arrival waits) and acceptance additionally requires
    ``arrive <= tw_close[j]``. Because ``t`` only grows, a trip that
    accepts nothing can never be followed by one that does — the main
    loop ends on the first empty trip instead of testing windows forever.

    Stops left over (window already closed, or demand > capacity while
    still reachable) spill into ONE penalty-lane trip appended after the
    real trips: visited in scan order on the same running clock, with
    total lateness past each stop's window accumulated into ``penalty``.
    The lane keeps the output shape fixed (batcher/vmap requirement) and
    gives the re-optimizer an honest objective — lateness is a cost, not
    an exception. Only stops whose origin round trip exceeds
    ``max_distance`` are unroutable (physically unservable).
    """
    n = dist.shape[0] - 1
    demands = demands.astype(dist.dtype)
    tw_open = tw_open.astype(dist.dtype)
    tw_close = tw_close.astype(dist.dtype)

    roundtrip = dist[0, 1:] + dist[1:, 0]
    unreachable = roundtrip > max_distance
    over_cap = (demands > capacity) & ~unreachable

    scan_order = jnp.argsort(dist[0, 1:])

    init = _DispTripState(
        # over-capacity stops skip the real trips and go straight to the
        # penalty lane; unreachable stops are dropped entirely.
        visited=unreachable | over_cap,
        order=jnp.full((n,), -1, jnp.int32),
        trip_ids=jnp.full((n,), -1, jnp.int32),
        pos=jnp.zeros((), jnp.int32),
        trip=jnp.zeros((), jnp.int32),
        t=jnp.zeros((), dist.dtype),
        progress=jnp.ones((), jnp.bool_),
    )

    def trips_remain(st: _DispTripState) -> jax.Array:
        return (~st.visited.all()) & st.progress

    def run_trip(st: _DispTripState) -> _DispTripState:
        def visit(s: _DispScanState, j: jax.Array):
            node = j + 1
            leg = dist[s.current, node]
            arrive = jnp.maximum(s.st.t + leg, tw_open[j])
            accept = (
                ~s.st.visited[j]
                & (s.load + demands[j] <= capacity)
                & (s.trip_dist + leg + dist[node, 0] <= max_distance)
                & (arrive <= tw_close[j])
            )
            st2 = s.st
            st2 = st2._replace(
                visited=st2.visited.at[j].set(st2.visited[j] | accept),
                order=st2.order.at[st2.pos].set(
                    jnp.where(accept, j, st2.order[st2.pos])
                ),
                trip_ids=st2.trip_ids.at[st2.pos].set(
                    jnp.where(accept, st2.trip, st2.trip_ids[st2.pos])
                ),
                pos=st2.pos + accept.astype(jnp.int32),
                t=jnp.where(accept, arrive, st2.t),
            )
            return (
                _DispScanState(
                    current=jnp.where(accept, node, s.current),
                    load=s.load + jnp.where(accept, demands[j], 0.0),
                    trip_dist=s.trip_dist + jnp.where(accept, leg, 0.0),
                    accepted_any=s.accepted_any | accept,
                    st=st2,
                ),
                None,
            )

        scan_init = _DispScanState(
            current=jnp.zeros((), jnp.int32),
            load=jnp.zeros((), dist.dtype),
            trip_dist=jnp.zeros((), dist.dtype),
            accepted_any=jnp.zeros((), jnp.bool_),
            st=st,
        )
        out, _ = jax.lax.scan(visit, scan_init, scan_order)
        st3 = out.st
        # the clock pays the return leg (dist[0, 0] == 0 on empty trips)
        return st3._replace(
            trip=st3.trip + out.accepted_any.astype(jnp.int32),
            t=st3.t + dist[out.current, 0],
            progress=out.accepted_any,
        )

    main = jax.lax.while_loop(trips_remain, run_trip, init)

    # Penalty lane: everything reachable that the real trips could not
    # take — over-capacity stops plus window-expired leftovers. Batch
    # padding never lands here (padded stops are unreachable by
    # construction, see solve_host_dispatch_batch).
    spilled = ~unreachable & (over_cap | ~main.visited)

    def place(s, j):
        current, t, pos, order, trip_ids, penalty = s
        take = spilled[j]
        node = j + 1
        arrive = jnp.maximum(t + dist[current, node], tw_open[j])
        late = jnp.maximum(arrive - tw_close[j], 0.0)
        order = order.at[pos].set(jnp.where(take, j, order[pos]))
        trip_ids = trip_ids.at[pos].set(
            jnp.where(take, main.trip, trip_ids[pos]))
        return (
            jnp.where(take, node, current),
            jnp.where(take, arrive, t),
            pos + take.astype(jnp.int32),
            order,
            trip_ids,
            penalty + jnp.where(take, late, 0.0),
        ), None

    lane_init = (jnp.zeros((), jnp.int32), main.t, main.pos,
                 main.order, main.trip_ids, jnp.zeros((), dist.dtype))
    (_, _, pos_end, order, trip_ids, penalty), _ = jax.lax.scan(
        place, lane_init, scan_order)

    return DispatchSolution(
        order=order,
        trip_ids=trip_ids,
        n_trips=main.trip,
        n_routed=main.pos,
        n_spilled=pos_end - main.pos,
        unroutable=unreachable,
        spilled=spilled,
        penalty=penalty,
    )


greedy_vrp_dispatch_batch = jax.jit(
    jax.vmap(greedy_vrp_dispatch, in_axes=(0, 0, 0, 0, 0, 0)))


def greedy_vrp_tw(dist, demands, capacity, max_distance, tw_open,
                  tw_close) -> DispatchSolution:
    """Time-window variant (naming alias of the unified dispatch core)."""
    return greedy_vrp_dispatch(dist, demands, capacity, max_distance,
                               tw_open, tw_close)


def greedy_vrp_spill(dist, demands, capacity,
                     max_distance) -> DispatchSolution:
    """Pure demand-spillover variant: no windows (all open from clock 0,
    closing at the NO_WINDOW sentinel), so the only spill source is
    demand > capacity on reachable stops."""
    n = dist.shape[0] - 1
    return greedy_vrp_dispatch(
        dist, demands, capacity, max_distance,
        jnp.zeros((n,), dist.dtype),
        jnp.full((n,), NO_WINDOW, dist.dtype))


def _unpack_dispatch(sol: DispatchSolution, n_real: int) -> dict:
    """DispatchSolution → host dict (shared by single and batch)."""
    order = np.asarray(sol.order)
    trip_ids = np.asarray(sol.trip_ids)
    n_routed = int(sol.n_routed)
    n_spilled = int(sol.n_spilled)
    trips: list = []
    for pos in range(n_routed):
        tid = int(trip_ids[pos])
        while len(trips) <= tid:
            trips.append([])
        trips[tid].append(int(order[pos]))
    trips = [t for t in trips if t]
    unroutable = np.asarray(sol.unroutable)[:n_real]
    spilled = np.asarray(sol.spilled)[:n_real]
    return {
        "trips": trips,
        "optimized_order": [int(i) for i in order[:n_routed]],
        "n_trips": len(trips),
        "spill_lane": [int(i) for i in
                       order[n_routed:n_routed + n_spilled]],
        "spilled": [int(i) for i in np.flatnonzero(spilled)],
        "penalty": float(sol.penalty),
        "unroutable": [int(i) for i in np.flatnonzero(unroutable)],
    }


def solve_host_dispatch(dist: np.ndarray, demands: np.ndarray,
                        capacity: float, max_distance: float,
                        tw_open=None, tw_close=None) -> dict:
    """Host wrapper for the dispatch core: numpy in, plain python out.

    ``tw_open``/``tw_close`` default to the no-window problem (spillover
    only). For window-free problems whose demands all fit the vehicle,
    the real trips match :func:`solve_host` exactly — the parity the
    dispatch probe kind and tests lean on."""
    n = len(demands)
    if not (np.isfinite(np.float32(capacity))
            and np.isfinite(np.float32(max_distance))):
        raise ValueError("solve_host_dispatch: capacity/max_distance "
                         "must be finite")
    open_j = jnp.asarray(
        np.zeros(n, np.float32) if tw_open is None else tw_open,
        jnp.float32)
    close_j = jnp.asarray(
        np.full(n, NO_WINDOW, np.float32) if tw_close is None else tw_close,
        jnp.float32)
    sol = greedy_vrp_dispatch(
        jnp.asarray(dist, jnp.float32), jnp.asarray(demands, jnp.float32),
        jnp.asarray(capacity, jnp.float32),
        jnp.asarray(max_distance, jnp.float32), open_j, close_j)
    return _unpack_dispatch(sol, n)


def solve_host_dispatch_batch(dists, demands, capacities, max_distances,
                              tw_opens=None, tw_closes=None) -> list:
    """Batched dispatch solve — the device program behind the dispatch
    batcher. Same padding recipe as :func:`solve_host_batch` (stops to
    the batch-max power of two, batch axis to a power of two, padded
    stops structurally unreachable so they land in ``unroutable``, never
    the spill lane); window pads are open-from-0 / never-closing, which
    is irrelevant once the stop is unreachable."""
    b = len(dists)
    if b == 0:
        return []
    caps_np = np.asarray(capacities, np.float32)
    maxd_np = np.asarray(max_distances, np.float32)
    if not (np.isfinite(caps_np).all() and np.isfinite(maxd_np).all()):
        raise ValueError("solve_host_dispatch_batch: capacity/"
                         "max_distance must be finite")
    n_real = [d.shape[0] - 1 for d in dists]
    p = 1 << max(0, (max(n_real) - 1)).bit_length()
    b_pad = 1 << max(0, (b - 1)).bit_length()

    _FAR = np.float32(1e30)
    dist_b = np.full((b_pad, p + 1, p + 1), _FAR, np.float32)
    dem_b = np.full((b_pad, p), _FAR, np.float32)
    open_b = np.zeros((b_pad, p), np.float32)
    close_b = np.full((b_pad, p), np.float32(NO_WINDOW), np.float32)
    for i, (d, dem, n) in enumerate(zip(dists, demands, n_real)):
        dist_b[i, : n + 1, : n + 1] = d
        dem_b[i, :n] = dem
        if tw_opens is not None and tw_opens[i] is not None:
            open_b[i, :n] = np.asarray(tw_opens[i], np.float32)
        if tw_closes is not None and tw_closes[i] is not None:
            close_b[i, :n] = np.asarray(tw_closes[i], np.float32)
    cap_b = jnp.asarray(np.concatenate(
        [caps_np, np.ones(b_pad - b, np.float32)]))
    maxd_b = jnp.asarray(np.concatenate(
        [maxd_np, np.ones(b_pad - b, np.float32)]))

    sols = greedy_vrp_dispatch_batch(
        jnp.asarray(dist_b), jnp.asarray(dem_b), cap_b, maxd_b,
        jnp.asarray(open_b), jnp.asarray(close_b))
    return [
        _unpack_dispatch(
            DispatchSolution(*(leaf[i] for leaf in sols)), n_real[i])
        for i in range(b)
    ]


def solve_host(dist: np.ndarray, demands: np.ndarray, capacity: float,
               max_distance: float, refine: bool = False,
               max_refine_rounds: int = 4) -> dict:
    """Host-friendly wrapper: numpy in, plain python out (trips as lists).

    ``refine=True`` alternates intra-trip 2-opt with cross-trip
    relocate, cross-trip swap, and adjacent-pair Or-opt-2 until none
    improves (opt-in so the default keeps exact reference-greedy
    observable semantics). The moves compose: relocate fixes greedy's
    trip assignment, swap untangles pairs that capacity blocks relocate
    from moving, Or-opt-2 moves pairs whose first stop only pays off
    once its partner follows, 2-opt re-sequences the changed trips."""
    dist_j = jnp.asarray(dist, jnp.float32)
    dem_j = jnp.asarray(demands, jnp.float32)
    cap_j = jnp.asarray(capacity, jnp.float32)
    maxd_j = jnp.asarray(max_distance, jnp.float32)
    sol = greedy_vrp(dist_j, dem_j, cap_j, maxd_j)
    if refine:
        order_j, trips_j = sol.order, sol.trip_ids
        cost = tour_cost(dist, np.asarray(order_j), np.asarray(trips_j))
        for _ in range(max_refine_rounds):
            order_j = refine_2opt(dist_j, order_j, trips_j)
            order_j, trips_j = refine_relocate(
                dist_j, dem_j, cap_j, maxd_j, order_j, trips_j)
            order_j = refine_swap(
                dist_j, dem_j, cap_j, maxd_j, order_j, trips_j)
            order_j, trips_j = refine_oropt2(
                dist_j, dem_j, cap_j, maxd_j, order_j, trips_j)
            order_j, trips_j = refine_oropt3(
                dist_j, dem_j, cap_j, maxd_j, order_j, trips_j)
            new_cost = tour_cost(dist, np.asarray(order_j), np.asarray(trips_j))
            if new_cost >= cost - 1e-3:
                break
            cost = new_cost
        sol = sol._replace(order=order_j, trip_ids=trips_j)
    return _unpack_solution(np.asarray(sol.order), np.asarray(sol.trip_ids),
                            int(sol.n_routed), np.asarray(sol.unroutable),
                            len(demands))
