from routest_tpu.optimize.vrp import (  # noqa: F401
    greedy_vrp,
    greedy_vrp_batch,
    refine_2opt,
    refine_relocate,
    refine_swap,
    solve_host,
    trips_cost,
)
from routest_tpu.optimize.engine import (  # noqa: F401
    optimize_route,
    optimize_route_batch,
    travel_matrix,
)
from routest_tpu.optimize.ranking import rank_routes  # noqa: F401
