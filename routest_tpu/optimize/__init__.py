from routest_tpu.optimize.vrp import greedy_vrp, greedy_vrp_batch  # noqa: F401
from routest_tpu.optimize.engine import optimize_route  # noqa: F401
