"""Top-k candidate-route ranking (BASELINE.json config 3).

The reference returns exactly one greedy order per request. This module
generalizes that into the batched form TPUs are good at: materialize many
candidate visit orders (exhaustive for small N, sampled + greedy seed
otherwise), score them all in one fused device computation (path distance
via gathers + the ETA model over the 12-feature encoding), and take the
top-k. The candidate axis is the mesh-parallel axis — scoring 10k
permutations is one pjit call, not 10k ORS requests.
"""

from __future__ import annotations

import itertools
import math
from typing import Dict, NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

from routest_tpu.data.features import encode_features
from routest_tpu.models.eta_mlp import EtaMLP, Params


class RankedRoutes(NamedTuple):
    orders: np.ndarray      # (k, N) visit orders, best first
    distances_m: np.ndarray  # (k,)
    etas_min: np.ndarray     # (k,) model ETA per candidate (nan if no model)


def perturbed_greedy_orders(dist: np.ndarray, k: int, seed: int = 0,
                            strength: float = 0.35) -> np.ndarray:
    """(K, N) nearest-neighbor tours under multiplicatively noised costs.

    The informed candidate generator: each candidate is a full greedy
    nearest-neighbor construction on ``dist * (1 + strength·U[0,1))`` —
    so every sample is a structurally plausible tour, unlike uniform
    permutations, which at N ≥ 10 are essentially all terrible
    (Pr[random tour near-optimal] ~ 1/N!). Candidate 0 uses zero noise,
    i.e. the plain greedy-NN tour. One vmapped ``lax.scan`` builds all K
    tours on device — the candidate axis is the parallel axis.
    """
    keys = jax.random.split(jax.random.PRNGKey(seed), k)
    scale = jnp.concatenate(
        [jnp.zeros((1,)), jnp.full((k - 1,), strength)]) if k > 1 \
        else jnp.zeros((1,))
    return np.asarray(_perturbed_greedy_kernel(
        jnp.asarray(dist, jnp.float32), keys, scale), np.int32)


@jax.jit
def _perturbed_greedy_kernel(dist: jax.Array, keys: jax.Array,
                             scale: jax.Array) -> jax.Array:
    # Module-level jit keyed on (n, k) shapes only — a closure rebuilt per
    # call would re-trace (and re-compile) on every invocation.
    n = dist.shape[0] - 1

    def one(key, s):
        noisy = dist * (1.0 + s * jax.random.uniform(key, dist.shape))

        def step(carry, _):
            current, visited = carry
            cand = jnp.where(visited, jnp.inf, noisy[current, 1:])
            j = jnp.argmin(cand).astype(jnp.int32)
            return (j + 1, visited.at[j].set(True)), j

        (_, _), order = jax.lax.scan(
            step, (jnp.zeros((), jnp.int32), jnp.zeros((n,), bool)),
            None, length=n)
        return order

    return jax.vmap(one)(keys, scale)


def candidate_permutations(n_stops: int, max_candidates: int = 4096,
                           seed: int = 0,
                           greedy_order: Optional[np.ndarray] = None,
                           dist: Optional[np.ndarray] = None) -> np.ndarray:
    """(K, N) candidate visit orders, deduplicated.

    Exhaustive when N! fits the budget. Otherwise, with a distance
    matrix: perturbed-greedy construction (plus a 25% uniform-random tail
    for diversity) — informed sampling replacing the old uniform draw,
    which planted the greedy seed in a sea of uniformly terrible tours.
    Without ``dist`` (no matrix available), uniform sampling as before.
    The externally supplied ``greedy_order`` (e.g. the VRP engine's
    refined order) is always included when given.
    """
    if math.factorial(n_stops) <= max_candidates:
        return np.asarray(list(itertools.permutations(range(n_stops))),
                          dtype=np.int32)
    rng = np.random.default_rng(seed)
    if dist is not None:
        n_uniform = max_candidates // 4  # may be 0 at tiny budgets
        informed = perturbed_greedy_orders(
            dist, max_candidates - n_uniform, seed=seed)
        tail = (np.stack([rng.permutation(n_stops)
                          for _ in range(n_uniform)]).astype(np.int32)
                if n_uniform else np.empty((0, n_stops), np.int32))
        perms = np.concatenate([informed, tail])
    else:
        perms = np.stack(
            [rng.permutation(n_stops) for _ in range(max_candidates)]
        ).astype(np.int32)
    if greedy_order is not None and len(greedy_order) == n_stops:
        perms[-1] = np.asarray(greedy_order, np.int32)
    # duplicates (perturbed greedy converges on good tours) waste score
    # slots and would surface twice in the top-k
    return np.unique(perms, axis=0)


def path_distances(dist: jax.Array, perms: jax.Array,
                   return_to_origin: bool = True) -> jax.Array:
    """(N+1,N+1) matrix, (K,N) perms (destination indices) → (K,) meters.

    Pure gathers — one fused XLA op over the whole candidate set.
    """
    nodes = perms + 1                                 # all_points indexing
    k = perms.shape[0]
    origin = jnp.zeros((k, 1), nodes.dtype)
    seq = jnp.concatenate(
        [origin, nodes] + ([origin] if return_to_origin else []), axis=1
    )
    legs = dist[seq[:, :-1], seq[:, 1:]]
    return legs.sum(axis=1)


def rank_routes(
    dist: np.ndarray,
    k: int = 5,
    *,
    model: Optional[EtaMLP] = None,
    params: Optional[Params] = None,
    context: Optional[Dict] = None,
    speed_mps: float = 8.3,
    max_candidates: int = 4096,
    greedy_order: Optional[np.ndarray] = None,
    return_to_origin: bool = True,
    runtime=None,
) -> RankedRoutes:
    """Score candidates and return the k best.

    Ranking key: model ETA when a model is given (the ML engine path),
    else path duration at profile speed. ``context`` carries the
    weather/traffic/weekday/hour/driver_age the 12-feature encoding needs.

    With a ``MeshRuntime``, the candidate axis shards over the mesh
    ``data`` axis (SURVEY.md §5.7: the candidate-set axis is this
    framework's long-sequence analog) — XLA propagates the sharding
    through the gathers, the model matmuls, and the final top_k, which
    becomes a per-shard top-k plus an all-gather of the survivors.
    Padded candidates get +inf scores so they can never surface.
    """
    n = dist.shape[0] - 1
    perms = candidate_permutations(n, max_candidates,
                                   greedy_order=greedy_order, dist=dist)
    n_real = perms.shape[0]
    pad_penalty = None
    if runtime is not None:
        from routest_tpu.core.mesh import pad_to_multiple

        padded_k = pad_to_multiple(n_real, runtime.n_data)
        if padded_k != n_real:
            perms = np.concatenate(
                [perms, np.repeat(perms[:1], padded_k - n_real, axis=0)]
            )
            penalty = np.zeros(padded_k, np.float32)
            penalty[n_real:] = np.float32(3.4e38)
            pad_penalty = jax.device_put(jnp.asarray(penalty),
                                         runtime.batch_sharding())
        perms_dev = jax.device_put(jnp.asarray(perms), runtime.batch_sharding())
    else:
        perms_dev = jnp.asarray(perms)
    d = path_distances(jnp.asarray(dist, jnp.float32), perms_dev,
                       return_to_origin)

    if model is not None and params is not None:
        ctx = context or {}
        kk = perms.shape[0]
        feats = encode_features(
            jnp.full((kk,), int(ctx.get("weather_idx", 2))),
            jnp.full((kk,), int(ctx.get("traffic_idx", 2))),
            jnp.full((kk,), int(ctx.get("weekday", 0))),
            jnp.full((kk,), int(ctx.get("hour", 12))),
            d / 1000.0,
            jnp.full((kk,), float(ctx.get("driver_age", 30.0))),
        )
        etas = model.apply(params, feats)
        score = etas
    else:
        # host-side nan fill: keeps jax_debug_nans clean (no device nans)
        etas = np.full(d.shape, np.nan, np.float32)
        score = d / speed_mps

    if pad_penalty is not None:
        score = score + pad_penalty

    k = min(k, n_real)
    _, best = jax.lax.top_k(-score, k)
    best = np.asarray(best)
    return RankedRoutes(
        orders=np.asarray(perms)[best],
        distances_m=np.asarray(d)[best],
        etas_min=np.asarray(etas)[best],
    )
