"""Road-graph shortest-path routing on device.

The reference outsources real road routing to ORS/OSRM SaaS
(``Flaskr/utils.py:55,97,151``); this framework's base engine
approximates legs with great-circle polylines × road factor
(``optimize/engine.py``). This module closes that gap on-device
(SURVEY.md §7.3 item 5 — "road network without ORS"): legs are true
shortest paths over a road graph, with geometry that follows the
street network and durations from the graph's per-edge travel times.

The solver is a **batched multi-source Bellman-Ford relaxation**
expressed as XLA control flow: per iteration, every edge proposes
``dist[s] + w`` to its receiver and a scatter-min folds the proposals —
one ``lax.while_loop`` whose body is two gathers and a scatter over the
(S, N) distance table. That maps the irregular graph problem onto the
TPU's strength (wide vectorized updates, no per-node host loops) and
vmaps/shards along the source axis like every other batch in this
framework. Predecessors are recovered after convergence with one more
edge sweep (an edge lies on a shortest path iff it is *tight*:
``dist[s] + w == dist[r]``), keeping the hot loop free of argmin
bookkeeping.

Path *reconstruction* (walking predecessors into polylines) is
host-side — it is O(path length) pointer chasing on tiny data, exactly
the kind of work that does not belong on the accelerator.
"""

from __future__ import annotations

import functools
import os
import threading
import time
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from routest_tpu.data.road_graph import (
    _CLASS_SPEED_MPS,
    generate_road_graph,
    haversine_np,
)
from routest_tpu.optimize.hierarchy import (
    HierarchicalIndex,
    hier_cache_path,
    hier_min_nodes,
    relax_from,
    tight_pred,
)
from routest_tpu.obs.efficiency import get_ledger
from routest_tpu.obs.ledger import record_change
from routest_tpu.obs.trace import trace_span
from routest_tpu.utils.logging import get_logger

_INF = jnp.float32(3e38)

_metrics = None


def _router_metrics():
    """Process-registry families for the router hot path, created
    lazily (importing the obs registry at module import would make the
    optimizer depend on serving wiring). Phase labels: ``snap``
    (lat/lon → node), ``solve`` (the fused device program incl. fetch),
    ``matrix`` (device duration table), ``walk`` (host predecessor
    walk per leg) — histogram exemplars link a slow solve to its trace
    id like every other stage histogram."""
    global _metrics
    if _metrics is None:
        from routest_tpu.obs import get_registry

        reg = get_registry()
        _metrics = {
            "phase": reg.histogram(
                "rtpu_router_phase_seconds",
                "Road-router request-path phase latency.", ("phase",)),
            "info": reg.gauge(
                "rtpu_router_overlay_info",
                "Overlay build stats by level and stat.",
                ("level", "stat")),
            "build": reg.gauge(
                "rtpu_router_overlay_build_seconds",
                "Overlay precompute seconds by level.", ("level",)),
            "swaps": reg.counter(
                "rtpu_road_model_swaps_total",
                "Road-GNN hot-swap attempts, by result "
                "(accepted / rejected / removed).", ("result",)),
            "model_gen": reg.gauge(
                "rtpu_road_model_generation",
                "Generation id of the live road-GNN leg pricer "
                "(monotonic per process; bumps on every swap)."),
            "batch_dispatches": reg.counter(
                "rtpu_router_batch_dispatches_total",
                "Merged solve dispatches through the router batcher."),
            "batch_rows": reg.counter(
                "rtpu_router_batch_rows_total",
                "Source rows solved through merged dispatches."),
            "batch_merged": reg.counter(
                "rtpu_router_batch_merged_requests_total",
                "Requests that shared a dispatch with at least one "
                "other request."),
        }
    return _metrics


@functools.partial(jax.jit, static_argnames=("n_rounds",))
def _time_table(bf_senders: jax.Array, pred: jax.Array, time_bf: jax.Array,
                dist: jax.Array, *, n_rounds: int) -> jax.Array:
    """(S, N) travel seconds along every shortest-path tree, on device.

    Matrix consumers need durations for every (source, node) pair; the
    host-side predecessor walk is O(path length) PER PAIR — seconds of
    pointer chasing at metro scale. Pointer doubling turns the whole
    table into ``n_rounds = ceil(log2(N))`` rounds of two (S, N)
    gathers: each round, every node's accumulated time and parent jump
    twice as far up its tree. Sums re-associate (tree order instead of
    walk order), so values match the walk to f32 rounding, not
    bitwise. Unreachable nodes (no predecessor, infinite distance)
    come back INF like the distance table."""
    rows = jnp.arange(pred.shape[0])[:, None]
    has_pred = pred >= 0
    safe = jnp.maximum(pred, 0)
    parent = jnp.where(has_pred, bf_senders[safe],
                       jnp.arange(pred.shape[1])[None, :])
    acc = jnp.where(has_pred, time_bf[safe], 0.0)

    # Fixed point after ceil(log2(tree depth)) rounds — the street-graph
    # diameter, typically far below the n_rounds=log2(N) bound; exit as
    # soon as every pointer reaches its root (one cheap compare per
    # round vs. the gathers it saves).
    def keep_going(state):
        _, _, changed, i = state
        return changed & (i < n_rounds)

    def body(state):
        acc, parent, _, i = state
        new_parent = parent[rows, parent]
        return (acc + acc[rows, parent], new_parent,
                jnp.any(new_parent != parent), i + 1)

    acc, parent, _, _ = jax.lax.while_loop(
        keep_going, body,
        (acc, parent, jnp.asarray(True), jnp.zeros((), jnp.int32)))
    # A predecessor CYCLE (possible with zero-length-edge ties — the
    # case _walk defends against) must surface as unreachable like the
    # walk does, not as a plausible partial sum. "Still moving" is NOT
    # a sufficient test: an even-length cycle squares to a spurious
    # fixed point where its nodes become their own parents. The sound
    # invariant: a finished chain ends at a TRUE root — a node with no
    # predecessor. Anything whose final parent still has a predecessor
    # sits in (or chains into) a cycle.
    bad_root = jnp.take_along_axis(has_pred, parent, axis=1)
    return jnp.where((dist < 1e37) & ~bad_root, acc, jnp.inf)

# Flat-relaxation sweeps run over hierarchy distances before
# predecessor recovery: the overlay's re-associated sums round a few
# ulps away from the sweep's own ``dist[s] + w`` assignments; an
# UNROLLED sweep re-anchors ties near-bitwise (values are already
# exact, so this is O(1), not O(diameter)). The sweeps now run on the
# CONTRACTED graph (chain interiors are synthesized from the fill
# structure, not relaxed in), and since the input values are exact the
# single default sweep re-anchors every node whose assignment matters
# — tight_edges' min-slack + 1 cm merge slack absorbs the one-op
# rounding that remains. Each sweep is a full (S, Nc)×Ec pass (~40 ms
# at 250k on one core), a first-order term in metro warm latency.
def _polish_sweeps() -> int:
    try:
        return max(1, int(os.environ.get("ROUTEST_POLISH_SWEEPS", "1")))
    except ValueError:
        return 1


@functools.partial(jax.jit, static_argnames=("n_nodes", "max_iters"))
def _bellman_ford(senders: jax.Array, receivers: jax.Array, w: jax.Array,
                  sources: jax.Array, *, n_nodes: int,
                  max_iters: int) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """(S,) source nodes → (S, N) distances, (S, N) predecessor edges,
    and a scalar bool: True iff the loop CONVERGED (a sweep changed
    nothing) rather than exhausting ``max_iters`` — the caller must not
    trust distances when it is False.

    Edge arrays MUST be sorted by receiver: the sweep folds proposals
    with ``segment_min(indices_are_sorted=True)``, which benchmarks 1.6×
    faster than the equivalent scatter-min on TPU at metro scale (50k
    nodes / 243k edges: 1.13 s vs 1.81 s for a 16-source batch).
    Returned predecessor ids index the SORTED edge order — the caller
    maps them back through its sort permutation.

    The sweep and recovery primitives live in ``optimize/hierarchy.py``
    (``relax_from`` / ``tight_pred``) — the partition overlay composes
    the same kernels with a different initial table.
    """
    n_src = sources.shape[0]
    dist0 = jnp.full((n_src, n_nodes), _INF).at[
        jnp.arange(n_src), sources].set(0.0)
    dist, converged = relax_from(senders, receivers, w, dist0,
                                 n_nodes=n_nodes, max_iters=max_iters)
    pred = tight_pred(senders, receivers, w, dist, sources, n_nodes=n_nodes)
    return dist, pred, converged


def _road_swap_divergence() -> float:
    """Verified road-GNN hot-swap bound (median absolute edge-seconds
    divergence from the live pricer; 0 disables the compare — the
    finiteness gate always holds). Mirrors ``RTPU_SWAP_MAX_DIV`` on the
    ETA model (docs/ROBUSTNESS.md "Safe change delivery")."""
    try:
        return float(os.environ.get("RTPU_ROAD_SWAP_MAX_DIV", "600"))
    except ValueError:
        return 600.0


class _LiveMetric:
    """One immutable live-traffic metric generation (docs/ARCHITECTURE
    "Live traffic"): the blended per-edge travel seconds, the
    customized time-metric overlay (when the router has one), and the
    fused solve for it. Built OFF-PATH by ``install_live_metric`` and
    installed with a single reference flip — requests snapshot
    ``router._live`` once, so a flip can never tear a solve."""

    __slots__ = ("epoch", "gen", "time_s", "d_time_bf", "hier", "solve",
                 "aot", "route", "installed_unix", "timings")

    def __init__(self, epoch: int, time_s: np.ndarray, d_time_bf,
                 hier, solve, aot: Dict[int, object], route: bool,
                 timings: Dict, gen: int = 0) -> None:
        self.epoch = int(epoch)
        # Router-internal monotonic install counter: the route
        # fastlane keys on (epoch, gen) so even a caller that reuses
        # an epoch number (two customizer instances both starting at
        # 1) can never alias two different metrics onto one cache key.
        self.gen = int(gen)
        self.time_s = time_s
        self.d_time_bf = d_time_bf
        self.hier = hier
        self.solve = solve
        self.aot = aot
        self.route = route
        self.installed_unix = time.time()
        self.timings = timings


def _batcher_config() -> Tuple[bool, int, float]:
    """(enabled, max merged rows, window seconds) for the solve
    batcher (``ROUTEST_ROUTER_BATCH`` on/off,
    ``ROUTEST_ROUTER_BATCH_MAX``, ``ROUTEST_ROUTER_BATCH_WINDOW_MS``)."""
    raw = os.environ.get("ROUTEST_ROUTER_BATCH", "1").strip().lower()
    enabled = raw not in ("0", "off", "false", "no")
    try:
        max_rows = max(1, int(os.environ.get(
            "ROUTEST_ROUTER_BATCH_MAX", "32")))
    except ValueError:
        max_rows = 32
    try:
        window_ms = float(os.environ.get(
            "ROUTEST_ROUTER_BATCH_WINDOW_MS", "0"))
    except ValueError:
        window_ms = 0.0
    return enabled, max_rows, max(0.0, window_ms) / 1000.0


class _BatchEntry:
    __slots__ = ("sources", "live", "key", "event", "dist", "pred", "error",
                 "dispatch_rows", "dispatch_requests", "t_q")

    def __init__(self, sources: np.ndarray, live, key) -> None:
        self.sources = sources
        self.live = live
        self.key = key
        self.event = threading.Event()
        self.dist = self.pred = None
        self.error: Optional[BaseException] = None
        # Stamped by _dispatch: how big the merged device dispatch that
        # carried this entry actually was (trace provenance — a slow
        # solve span says whether it rode a 1-row or a 32-row merge).
        self.dispatch_rows = 0
        self.dispatch_requests = 0
        # Enqueue stamp for the goodput ledger's queue/compute split.
        self.t_q = time.monotonic()


class _SolveBatcher:
    """Cross-request solve coalescing: concurrent :meth:`shortest`
    callers whose metric generation matches merge into ONE padded
    device dispatch. The solver's source axis is batched by design, so
    merged results are bitwise what lone solves return — the merge only
    amortizes dispatch + fetch, the way the ETA ``DynamicBatcher``
    amortizes scoring (docs/ARCHITECTURE.md "Serving").

    Zero added latency by construction with the default 0 ms window: a
    lone request dispatches immediately; arrivals during an in-flight
    solve queue and drain as the NEXT merged batch (the natural-
    batching regime — occupancy grows exactly when the device is the
    bottleneck). ``window_s > 0`` adds a fixed pre-drain wait for
    benchmarking forced batch shapes.

    Requests under different live-metric epochs never share a dispatch
    (their edge weights differ); the leader drains one epoch group per
    round and keeps going until the queue is empty, so mixed-epoch
    bursts around a metric flip drain in arrival order."""

    def __init__(self, router: "RoadRouter", max_rows: int,
                 window_s: float) -> None:
        self._router = router
        self.max_rows = int(max_rows)
        self.window_s = float(window_s)
        self._lock = threading.Lock()
        self._queue: List[_BatchEntry] = []
        self._busy = False
        self._dispatches = 0
        self._rows = 0
        self._requests = 0
        self._merged_requests = 0
        self._max_occupancy = 0

    def stats(self) -> Dict:
        with self._lock:
            d = max(1, self._dispatches)
            return {"max_rows": self.max_rows,
                    "window_ms": round(self.window_s * 1000, 3),
                    "dispatches": self._dispatches,
                    "rows": self._rows,
                    "requests": self._requests,
                    "merged_requests": self._merged_requests,
                    "max_occupancy": self._max_occupancy,
                    "mean_rows_per_dispatch": round(self._rows / d, 3)}

    def solve(self, sources: np.ndarray, live):
        """One caller's solve through the merge queue, traced: the span
        records how many rows rode the merged dispatch that carried it
        (``dispatch_rows``/``merged_requests``) — the provenance a
        tail-sampled slow route trace needs to say whether the solve
        was a lone dispatch or amortized across a merge."""
        with trace_span("router.batch_solve", rows=len(sources)) as span:
            entry = self._solve_entry(sources, live)
            span.set_attr("dispatch_rows", entry.dispatch_rows)
            span.set_attr("merged_requests", entry.dispatch_requests)
            return entry.dist, entry.pred

    def _solve_entry(self, sources: np.ndarray, live) -> "_BatchEntry":
        key = live.epoch if (live is not None and live.route) else 0
        entry = _BatchEntry(sources, live if key else None, key)
        with self._lock:
            self._queue.append(entry)
            self._requests += 1
            leader = not self._busy
            if leader:
                self._busy = True
        if not leader:
            if not entry.event.wait(120.0):
                raise TimeoutError("router solve batcher wedged")
            if entry.error is not None:
                raise entry.error
            return entry
        drain_error: Optional[BaseException] = None
        try:
            if self.window_s > 0:
                time.sleep(self.window_s)
            while True:
                with self._lock:
                    if not self._queue:
                        # Clearing the flag and observing the empty
                        # queue must be ONE atomic step: an arrival in
                        # between would otherwise wait on a leader that
                        # already left.
                        self._busy = False
                        break
                    k0 = self._queue[0].key
                    batch: List[_BatchEntry] = []
                    rest: List[_BatchEntry] = []
                    rows = 0
                    for it in self._queue:
                        if (it.key == k0
                                and rows + len(it.sources) <= self.max_rows):
                            batch.append(it)
                            rows += len(it.sources)
                        else:
                            rest.append(it)
                    self._queue = rest
                    self._dispatches += 1
                    self._rows += rows
                    self._max_occupancy = max(self._max_occupancy, rows)
                    if len(batch) > 1:
                        self._merged_requests += len(batch)
                m = _router_metrics()
                m["batch_dispatches"].inc()
                m["batch_rows"].inc(rows)
                if len(batch) > 1:
                    m["batch_merged"].inc(len(batch))
                self._dispatch(batch)
        except BaseException as e:  # drain-loop bug: fail loudly, not hung
            drain_error = e
            raise
        finally:
            if drain_error:
                with self._lock:
                    # Never leave the flag stuck: if the drain loop
                    # itself died, surviving queue entries error out
                    # rather than hang their threads.
                    leftovers = list(self._queue)
                    self._queue = []
                    self._busy = False
            else:
                leftovers = []
            for it in leftovers:
                if not it.event.is_set():
                    it.error = drain_error
                    it.event.set()
        if entry.error is not None:
            raise entry.error
        return entry

    def _dispatch(self, batch: List[_BatchEntry]) -> None:
        merged = (batch[0].sources if len(batch) == 1
                  else np.concatenate([it.sources for it in batch]))
        queue_s = max(0.0, time.monotonic() - min(it.t_q for it in batch))
        t0 = time.perf_counter()
        try:
            dist, pred = self._router._solve_rows(merged, batch[0].live)
        except BaseException as e:  # propagate to every merged caller
            for it in batch:
                it.error = e
                it.event.set()
            return
        # _solve_rows pads the source axis to the next pow2 — that IS
        # the launched batch the goodput ledger accounts against.
        n = len(merged)
        bucket = 1 << max(0, n - 1).bit_length()
        get_ledger().record(
            "route_solve", real_rows=n, padded_rows=bucket, bucket=bucket,
            queue_s=queue_s, compute_s=time.perf_counter() - t0)
        pos = 0
        for it in batch:
            m = len(it.sources)
            it.dist = dist[pos:pos + m]
            it.pred = pred[pos:pos + m]
            it.dispatch_rows = len(merged)
            it.dispatch_requests = len(batch)
            pos += m
            it.event.set()


class RoadRouter:
    """Routable road network: snap → batched shortest paths → polylines."""

    def __init__(self, graph: Optional[Dict[str, np.ndarray]] = None,
                 n_nodes: int = 2048, seed: int = 0,
                 use_gnn: bool = True,
                 gnn_path: Optional[str] = None,
                 use_transformer: bool = True,
                 transformer_path: Optional[str] = None) -> None:
        g = graph if graph is not None else generate_road_graph(
            n_nodes=n_nodes, seed=seed)
        self.coords = np.asarray(g["node_coords"], np.float32)   # (N, 2)
        senders = np.asarray(g["senders"], np.int32)
        receivers = np.asarray(g["receivers"], np.int32)
        length = np.asarray(g["length_m"], np.float32)
        road_class = np.asarray(g["road_class"], np.int32)
        speed_limit = np.asarray(
            g.get("speed_limit", _CLASS_SPEED_MPS[road_class]), np.float32)
        n_edges_raw = len(senders)
        senders, receivers, length, road_class, speed_limit = \
            self._bridge_components(senders, receivers, length, road_class,
                                    speed_limit)
        self._was_bridged = len(senders) != n_edges_raw
        # GNN compatibility is checked against the POST-bridge graph —
        # the edge set messages actually aggregate over at serving time.
        # Training must therefore run on the same bridged arrays
        # (``graph_dict()``), which makes learned costs work on real OSM
        # extracts too: bridging is deterministic, so trainer and server
        # agree on the fingerprint.
        from routest_tpu.train.checkpoint import graph_fingerprint

        self._fingerprint = graph_fingerprint(
            self.coords, senders, receivers, length)
        self.senders, self.receivers = senders, receivers
        self.length_m = length
        self.road_class = road_class
        self.speed_limit = speed_limit
        # Fallback leg pricing: free-flow physics (length / speed limit +
        # intersection overhead). Deliberately NOT the data generator's
        # congestion formula — the request path must not depend on the
        # synthetic ground truth it is supposed to predict.
        self.freeflow_time_s = (
            length / np.maximum(self.speed_limit, 0.1) + 4.0
        ).astype(np.float32)
        self.time_s = self.freeflow_time_s  # back-compat alias
        self.n_nodes = len(self.coords)
        # Bellman-Ford needs ≥ diameter sweeps; a kNN street grid's hop
        # diameter is O(√N) — 4√N is a comfortable first bound, and the
        # loop exits early once converged. ``shortest`` re-runs with the
        # exact N-1 bound if this heuristic is ever exhausted.
        self.max_iters = int(4 * np.sqrt(self.n_nodes)) + 8
        # Device-resident graph arrays: uploaded once, not per request.
        # Original edge order (the GNN's training/feature order):
        self._d_senders = jnp.asarray(self.senders)
        self._d_receivers = jnp.asarray(self.receivers)
        self._d_length = jnp.asarray(self.length_m)
        self._d_speed = jnp.asarray(self.speed_limit)
        # Receiver-sorted copies for the shortest-path sweep (segment_min
        # with indices_are_sorted — see _bellman_ford); predecessor ids
        # come back in this order and map through _bf_perm.
        self._bf_perm = np.argsort(self.receivers, kind="stable").astype(np.int32)
        self._bf_senders = jnp.asarray(self.senders[self._bf_perm])
        self._bf_receivers = jnp.asarray(self.receivers[self._bf_perm])
        self._bf_length = jnp.asarray(self.length_m[self._bf_perm])
        # Metro-scale graphs route through the two-level partition
        # overlay (``optimize/hierarchy.py``): the flat sweep's
        # iteration count is the graph's hop diameter, which crosses
        # from "fine" to "seconds per solve" around tens of thousands
        # of nodes. The overlay answers the same queries exactly in
        # O(cells-across) sweeps after a one-time batched precompute.
        self._hier: Optional[HierarchicalIndex] = None
        hmin = hier_min_nodes()
        if hmin and self.n_nodes >= hmin:
            cache = hier_cache_path(self._fingerprint)
            if cache and os.path.exists(cache):
                self._hier = HierarchicalIndex.load(
                    cache, fingerprint=self._fingerprint)
            if self._hier is None:
                self._hier = HierarchicalIndex.build(
                    self.coords, self.senders, self.receivers,
                    self.length_m, cache_path=cache,
                    fingerprint=self._fingerprint)
        self._aot: Dict[int, object] = {}
        self._aot_compile_s = 0.0
        if self._hier is not None:
            # Overlay query + polish sweeps + predecessor recovery
            # fused into ONE jitted program: a warm solve is a single
            # dispatch + fetch instead of three dispatches. Through the
            # axon tunnel each dispatch costs a host round trip (~70 ms
            # measured), which dominated metro-scale warm latency; it
            # also collapses three per-bucket compiles into one.
            self._overlay_solve = self._make_overlay_solve(self._hier)
            # AOT-compile the query entry per (graph, overlay) shape at
            # init (``jit(...).lower().compile()``): warm latency then
            # excludes dispatch/trace overhead and the FIRST request of
            # a replica's life stops paying the multi-second trace +
            # compile (4.8 s recorded at 250k). With the persistent XLA
            # compile cache on, the executable round-trips disk across
            # processes, so a fleet boot pays it once per machine.
            t0 = time.perf_counter()
            L = self._hier.n_levels
            for bucket in self._aot_buckets():
                spec = (jnp.zeros((L, bucket), jnp.int32),
                        jnp.zeros((L + 1, bucket, 2), jnp.int32),
                        jnp.zeros((L + 1, bucket, 2), jnp.float32),
                        jnp.zeros((bucket,), jnp.int32))
                self._aot[bucket] = self._overlay_solve.lower(
                    *spec).compile()
            self._aot_compile_s = round(time.perf_counter() - t0, 3)
            self._publish_overlay_metrics()
        # Cross-request solve batching (concurrent request_route /
        # matrix traffic shares compiled dispatches) + the route-level
        # fastlane (Zipf-skewed OD traffic mostly skips the solver).
        enabled, max_rows, window_s = _batcher_config()
        self._solve_batcher: Optional[_SolveBatcher] = (
            _SolveBatcher(self, max_rows, window_s) if enabled else None)
        from routest_tpu.optimize.route_cache import (RouteCache,
                                                      route_cache_config)

        rc_on, rc_bytes, rc_ttl = route_cache_config()
        self._route_cache: Optional[RouteCache] = (
            RouteCache(rc_bytes, rc_ttl) if rc_on else None)
        # Learned leg costs: load the trained road-GNN when its training
        # graph fingerprint matches this router's node set.
        self._hour_times: Dict[int, np.ndarray] = {}
        self._gnn_lock = threading.Lock()
        # Learned leg models hot-reload like the ETA model: each request
        # entry point stats the artifact and re-runs the fingerprint-
        # gated loader when the file changed — a retrained GNN or
        # transformer goes live without a restart. Mtimes are recorded
        # even for rejected artifacts so a bad file isn't re-parsed on
        # every request.
        from routest_tpu.train.checkpoint import (default_gnn_path,
                                                  default_transformer_path)

        self._gnn_path = ((gnn_path or default_gnn_path())
                          if use_gnn else None)
        self._transformer_path = (
            (transformer_path or default_transformer_path())
            if use_transformer else None)
        self._gnn_mtime_ns: Optional[int] = None
        self._transformer_mtime_ns: Optional[int] = None
        self._gnn = None
        self._transformer = None
        # Live-traffic metric (routest_tpu/live): installed by the
        # customizer, snapshotted once per request batch. None = frozen
        # world (free-flow / GNN pricing, distance-metric routing).
        self._live: Optional[_LiveMetric] = None
        self._live_installs = 0  # monotonic; part of the route-cache key
        self._live_lock = threading.Lock()  # serializes installs only
        # Serializes reloads only — model loading happens OUTSIDE the
        # cache lock so a retrain never stalls concurrent requests.
        self._reload_lock = threading.Lock()
        self._model_gen = 0  # bumped per swap: stale cache writes discard
        self._maybe_reload_models()

    @staticmethod
    def _aot_buckets() -> List[int]:
        """Source-bucket sizes to AOT-compile at init.
        ``ROUTEST_ROUTER_AOT``: "auto" (default — the serving
        point-to-point bucket and the bench/matrix 16-waypoint bucket),
        "off"/"0" to disable, or a comma list of waypoint counts
        (rounded up to their power-of-two buckets)."""
        raw = os.environ.get("ROUTEST_ROUTER_AOT", "auto").strip().lower()
        if raw in ("", "0", "off", "false", "no"):
            return []
        if raw == "auto":
            return [2, 16]
        out = set()
        for tok in raw.split(","):
            tok = tok.strip()
            if tok.isdigit() and int(tok) > 0:
                out.add(1 << max(0, (int(tok) - 1).bit_length()))
        return sorted(out)

    def _publish_overlay_metrics(self) -> None:
        """Overlay build stats → the process registry: per-level
        ``rtpu_router_overlay_info{level, stat}`` gauges plus
        ``rtpu_router_overlay_build_seconds{level}`` — the provenance a
        dashboard (or a postmortem bundle) reads without a /api/health
        round trip."""
        if self._hier is None:
            return
        m = _router_metrics()
        for lvl in self._hier.stats.get("levels", []):
            level = str(lvl.get("level", 1))
            for stat in ("n_cells", "c_max", "b_max", "n_overlay_nodes",
                         "n_overlay_edges", "clique_edges_kept",
                         "clique_edges_pruned"):
                if stat in lvl:
                    m["info"].labels(level=level, stat=stat).set(lvl[stat])
            m["build"].labels(level=level).set(lvl.get("build_s", 0.0))
        m["info"].labels(level="top", stat="n_overlay_nodes").set(
            self._hier.stats.get("top_nodes", 0))
        m["info"].labels(level="top", stat="n_overlay_edges").set(
            self._hier.stats.get("top_edges", 0))

    @property
    def leg_cost_model(self) -> str:
        """"gnn" when learned per-edge times serve requests, else
        "freeflow"."""
        return "gnn" if self._gnn is not None else "freeflow"

    @property
    def solver_info(self) -> Dict:
        """Which shortest-path regime serves this graph, with the
        overlay's build stats when the partition hierarchy is active —
        ONE shape shared by the health gauge and the scale benchmark.
        ``overlay.levels`` carries the per-level breakdown,
        ``overlay.loaded_from_cache``/``cache_version`` the provenance,
        ``aot_buckets`` the solve shapes compiled at init."""
        if self._hier is not None:
            from routest_tpu.optimize.hierarchy import _CACHE_VERSION

            info = {"solver": "hierarchy",
                    "overlay": dict(self._hier.stats)}
            info["overlay"].setdefault("loaded_from_cache", False)
            info["overlay"]["cache_version"] = _CACHE_VERSION
            info["hub_labels"] = self._hier._labels is not None
            info["aot_buckets"] = sorted(self._aot)
            if self._aot:
                info["aot_compile_s"] = self._aot_compile_s
        else:
            info = {"solver": "flat_bf", "max_iters_bound": self.max_iters}
        # Routing fast-path provenance (docs/PERFORMANCE.md §7): the
        # solve batcher's merged-dispatch stats and the route
        # fastlane's hit/byte counters, for health and the serving
        # bench artifact.
        if self._solve_batcher is not None:
            info["batch"] = self._solve_batcher.stats()
        if self._route_cache is not None:
            info["route_cache"] = self._route_cache.stats()
        if self._live is not None:
            info["live"] = self.live_info
        return info

    # ── live traffic: metric install / flip ───────────────────────────

    @property
    def live_epoch(self) -> int:
        """Metric generation currently serving (0 = no live metric)."""
        live = self._live
        return live.epoch if live is not None else 0

    @property
    def live_info(self) -> Optional[Dict]:
        """Health/bench view of the installed live metric."""
        live = self._live
        if live is None:
            return None
        return {"epoch": live.epoch, "route_metric": live.route,
                "installed_unix": round(live.installed_unix, 3),
                **live.timings}

    def live_metric_export(self) -> Optional[np.ndarray]:
        """The (E,) blended edge seconds the live generation serves —
        what the bench's scipy oracle re-solves against."""
        live = self._live
        return None if live is None else live.time_s

    def install_live_metric(self, time_s: np.ndarray, epoch: int, *,
                            route: bool = True) -> Dict:
        """Build and atomically flip to a new live metric generation.

        ``time_s`` is the blended per-edge travel seconds (original
        edge order). Everything expensive — overlay customization
        (``HierarchicalIndex.customize``: partition + contraction
        reused, boundary tables re-priced), the fused solve's
        trace/compile for the AOT buckets — happens BEFORE the flip, on
        the caller's (customizer) thread, so requests keep solving the
        previous generation with zero blip and the flip itself is one
        reference assignment. ``route=False`` installs the metric for
        leg PRICING only (ETAs shift, chosen routes stay on the
        distance metric). Raises on a bad metric or a failed
        customization — the previous generation keeps serving.
        """
        time_s = np.array(time_s, np.float32, copy=True)
        if time_s.shape != self.length_m.shape:
            raise ValueError(
                f"live metric has {time_s.shape} entries, graph has "
                f"{self.length_m.shape}")
        # Same physical floor as every learned pricer: no edge beats
        # free-flow at an arterial ceiling, and non-finite/absurd
        # estimates degrade to physics instead of poisoning the metric.
        bad = ~np.isfinite(time_s) | (time_s <= 0)
        if bad.any():
            time_s[bad] = self.freeflow_time_s[bad]
        np.maximum(time_s, self.length_m / 16.7, out=time_s)
        timings: Dict = {}
        hier_live = solve = None
        aot: Dict[int, object] = {}
        d_time_bf = jnp.asarray(time_s[self._bf_perm])
        if self._hier is not None and route:
            t0 = time.perf_counter()
            hier_live = self._hier.customize(time_s)
            timings["customize_s"] = round(time.perf_counter() - t0, 3)
            timings["full_build_s"] = self._hier.stats.get("build_s", 0.0)
            solve = self._make_overlay_solve(hier_live)
            t0 = time.perf_counter()
            L = hier_live.n_levels
            for bucket in self._aot_buckets():
                spec = (jnp.zeros((L, bucket), jnp.int32),
                        jnp.zeros((L + 1, bucket, 2), jnp.int32),
                        jnp.zeros((L + 1, bucket, 2), jnp.float32),
                        jnp.zeros((bucket,), jnp.int32))
                aot[bucket] = solve.lower(*spec).compile()
            timings["aot_s"] = round(time.perf_counter() - t0, 3)
        with self._live_lock:
            self._live_installs += 1
            live = _LiveMetric(epoch, time_s, d_time_bf, hier_live,
                               solve, aot, route, timings,
                               gen=self._live_installs)
            self._live = live
        from routest_tpu.live import set_metric_epoch

        set_metric_epoch(live.epoch)
        get_logger("routest.road").info(
            "live_metric_installed", epoch=live.epoch, route=route,
            **timings)
        return dict(timings, epoch=live.epoch)

    def _make_overlay_solve(self, hier: HierarchicalIndex):
        """Fused overlay query + CONTRACTED-graph polish/predecessor
        recovery + exact chain synthesis — one jitted program, one
        dispatch per warm solve (``HierarchicalIndex.full_solve_fn``).
        Shared by the distance overlay (init) and every live-metric
        generation (customizer — the customized index carries its own
        re-priced contracted weights and fill offsets). Polish sweeps
        no longer couple to the contraction cap: chain interiors are
        synthesized from the fill structure, not relaxed in."""
        return jax.jit(hier.full_solve_fn(_polish_sweeps()))

    def graph_dict(self) -> Dict[str, np.ndarray]:
        """The (post-bridge) routable graph — the EXACT arrays serving
        aggregates over, and therefore the arrays the GNN must train on
        (``scripts/train_gnn.py`` consumes this; the saved artifact's
        fingerprint then matches ``_load_gnn``'s check)."""
        return {
            "node_coords": self.coords,
            "senders": self.senders,
            "receivers": self.receivers,
            "length_m": self.length_m,
            "road_class": self.road_class,
            "speed_limit": self.speed_limit,
        }

    def _load_leg_model(self, loader, resolved: str, tag: str):
        """Shared load-and-fingerprint-gate for learned leg-cost
        artifacts (road GNN, route transformer). The artifact is
        optional by design (same contract as the ETA model's
        ``(None, None)`` fallback, ``Flaskr/ml.py:25-26``): any failure
        degrades to the next pricer down, never an error. Returns
        (model, params, meta) or None; ``meta`` may be the fingerprint
        itself or a dict carrying it under "graph"."""
        try:
            model, params, meta = loader(resolved)
        except FileNotFoundError:
            return None
        except Exception as e:  # corrupt/foreign artifact: degrade, log
            get_logger("routest.road").warning(
                f"{tag}_artifact_unusable", path=resolved,
                error=f"{type(e).__name__}: {e}")
            return None
        fp = meta.get("graph", meta) if isinstance(meta, dict) else meta
        if fp != self._fingerprint:
            # Expected whenever a custom/test graph is routed; debug only.
            get_logger("routest.road").debug(
                f"{tag}_graph_mismatch", path=resolved,
                artifact=fp, router=self._fingerprint)
            return None
        from routest_tpu.core.dtypes import backend_compute_policy

        # Leg pricers serve per request: on the CPU fallback backend,
        # bf16 compute is emulation — same swap the ETA service applies.
        return backend_compute_policy(model), params, meta

    def _load_gnn(self, path: str):
        from routest_tpu.train.checkpoint import load_gnn

        loaded = self._load_leg_model(load_gnn, path, "road_gnn")
        if loaded is None:
            return None
        model, params, _meta = loaded
        return model, params

    @property
    def has_transformer(self) -> bool:
        return self._transformer is not None

    @staticmethod
    def _mtime_ns(path: Optional[str]) -> Optional[int]:
        if not path:
            return None
        try:
            return os.stat(path).st_mtime_ns
        except OSError:
            return None

    def _maybe_reload_models(self) -> None:
        """Reload the GNN / transformer when their artifact files changed
        (two stats per call — cheap enough to run per request). Same
        degradation contract as initial load: a rejected replacement
        simply isn't served; a DELETED artifact stops serving (pricing
        falls down the stack, matching a fresh process's behavior).
        Artifacts are written atomically (``_write_artifact``'s
        temp-then-rename), so a changed mtime always means a complete
        file. Deserialization runs outside the cache lock — only the
        final reference swap (and the generation bump that invalidates
        in-flight cache writes) holds it; a second thread arriving
        mid-reload just serves the current models."""
        if not (self._gnn_path or self._transformer_path):
            return
        if not self._reload_lock.acquire(blocking=False):
            return  # another request is already reloading
        try:
            m = self._mtime_ns(self._gnn_path)
            if self._gnn_path and m != self._gnn_mtime_ns:
                new_gnn = (self._load_gnn(self._gnn_path)
                           if m is not None else None)
                # Verified hot-swap (the continuous-retrain landing
                # zone, docs/ARCHITECTURE.md "Live traffic"): when a
                # model is already serving, a REPLACEMENT artifact must
                # score the graph finitely and stay within the
                # divergence bound before the generation flips — a
                # corrupt/degenerate retrain keeps the old pricer
                # serving. A DELETED artifact still stops serving
                # (matches a fresh process), and the first-ever install
                # only needs finiteness.
                accept, verdict = self._verify_gnn_swap(new_gnn, m)
                swaps = _router_metrics()["swaps"]
                if accept:
                    with self._gnn_lock:
                        self._gnn = new_gnn
                        self._gnn_mtime_ns = m
                        self._model_gen += 1
                        self._hour_times.clear()
                        gen = self._model_gen
                    swaps.labels(result=verdict.pop("result",
                                                    "accepted")).inc()
                    _router_metrics()["model_gen"].set(gen)
                    record_change("model.road_swap",
                                  detail={"generation": gen,
                                          "path": self._gnn_path})
                    get_logger("routest.road").info(
                        "road_model_swapped", generation=gen,
                        path=self._gnn_path, **verdict)
                else:
                    with self._gnn_lock:
                        # Remember the bad mtime so the artifact is not
                        # re-verified on every request until it changes.
                        self._gnn_mtime_ns = m
                    swaps.labels(result="rejected").inc()
                    get_logger("routest.road").warning(
                        "road_model_swap_rejected", path=self._gnn_path,
                        **verdict)
            m = self._mtime_ns(self._transformer_path)
            if self._transformer_path and m != self._transformer_mtime_ns:
                new_tf = (self._load_transformer(self._transformer_path)
                          if m is not None else None)
                with self._gnn_lock:
                    self._transformer = new_tf
                    self._transformer_mtime_ns = m
        finally:
            self._reload_lock.release()

    def _verify_gnn_swap(self, new_gnn, mtime_ns) -> Tuple[bool, Dict]:
        """Golden-graph gate for a road-GNN replacement → ``(accept,
        verdict)``. ``new_gnn`` None accepts as a removal (file deleted
        → pricing falls down the stack) unless a model is live and the
        file still EXISTS (an unloadable overwrite must not take down a
        working pricer). A loadable replacement scores the whole edge
        set at the current hour: any non-finite output rejects, and —
        when a model is already serving — a median absolute divergence
        beyond ``RTPU_ROAD_SWAP_MAX_DIV`` edge-seconds rejects too."""
        with self._gnn_lock:
            cur = self._gnn
        if new_gnn is None:
            if cur is not None and mtime_ns is not None:
                return False, {"reason": "replacement failed to load"}
            return True, {"result": "removed" if mtime_ns is None
                          else "accepted"}
        import datetime as _dt

        from routest_tpu.models.gnn import GraphBatch, edge_feature_array

        hour = _dt.datetime.now().hour
        model, params = new_gnn
        e = len(self.length_m)
        batch = GraphBatch(
            senders=self._d_senders, receivers=self._d_receivers,
            edge_feats=jnp.asarray(edge_feature_array(
                self.length_m, self.speed_limit, self.road_class, hour)),
            length_m=self._d_length, speed_limit=self._d_speed,
            targets=jnp.zeros((e,), jnp.float32),
            weights=jnp.ones((e,), jnp.float32))
        try:
            pred = np.asarray(
                model.apply(params, jnp.asarray(self.coords), batch),
                np.float32)
        except Exception as exc:
            return False, {"reason": "verification forward failed: "
                                     f"{type(exc).__name__}: {exc}"}
        if not np.isfinite(pred).all():
            return False, {"reason": "non-finite edge predictions",
                           "bad_edges": int((~np.isfinite(pred)).sum())}
        bound = _road_swap_divergence()
        if cur is not None and bound > 0:
            pred_f = np.maximum(pred, self.length_m / 16.7)
            cur_f = self.edge_time_s(hour)  # live pricer, same floor
            div = float(np.median(np.abs(pred_f - cur_f)))
            if div > bound:
                return False, {"reason": "divergence beyond bound",
                               "divergence_s": round(div, 2),
                               "bound_s": bound}
            return True, {"divergence_s": round(div, 3), "bound_s": bound}
        return True, {}

    def _load_transformer(self, path: str):
        """(model, params, trained_seq_len) when a fingerprint-compatible
        route-transformer artifact exists, else None."""
        from routest_tpu.train.checkpoint import load_transformer

        loaded = self._load_leg_model(load_transformer, path,
                                      "route_transformer")
        if loaded is None:
            return None
        model, params, meta = loaded
        return model, params, int(meta.get("seq_len", 24))

    def edge_time_s(self, hour: int) -> np.ndarray:
        """(E,) per-edge car travel seconds at the given hour-of-day.

        GNN-predicted when the trained artifact matches this graph
        (cached per hour — 24 small tables max), free-flow physics
        otherwise. This is the on-device replacement for the reference's
        "ask ORS how long this leg takes" (``Flaskr/utils.py:97-109``).
        """
        h = int(hour) % 24
        # ONE consistent snapshot of (model, cache, generation): a
        # concurrent hot-reload can null self._gnn between a bare check
        # and a later read, and its cache clear must invalidate THIS
        # call's eventual write (stale-generation writes are discarded).
        with self._gnn_lock:
            gnn = self._gnn
            gen = self._model_gen
            cached = self._hour_times.get(h)
        if gnn is None:
            return self.freeflow_time_s
        if cached is not None:
            return cached
        from routest_tpu.models.gnn import GraphBatch, edge_feature_array

        model, params = gnn
        e = len(self.length_m)
        batch = GraphBatch(
            senders=self._d_senders,
            receivers=self._d_receivers,
            edge_feats=jnp.asarray(edge_feature_array(
                self.length_m, self.speed_limit, self.road_class, h)),
            length_m=self._d_length,
            speed_limit=self._d_speed,
            targets=jnp.zeros((e,), jnp.float32),
            weights=jnp.ones((e,), jnp.float32),
        )
        try:
            pred = np.asarray(
                model.apply(params, jnp.asarray(self.coords), batch),
                np.float32)
        except Exception as e:
            # A loaded-but-unusable artifact (foreign shapes, backend
            # quirk) must degrade to physics, not 500 the request path;
            # drop it so the cost is paid once, not per request.
            get_logger("routest.road").error(
                "road_gnn_apply_failed", error=f"{type(e).__name__}: {e}")
            with self._gnn_lock:
                if self._model_gen == gen:
                    self._gnn = None
                    self._model_gen += 1
                    self._hour_times.clear()
            return self.freeflow_time_s
        # Physical floor: no edge is faster than free-flow at an
        # arterial ceiling — guards against a degenerate prediction
        # pricing an edge at ~0 s and distorting every route through it.
        pred = np.maximum(pred, self.length_m / 16.7)  # 60 km/h cap
        with self._gnn_lock:
            if self._model_gen == gen:  # don't poison a reloaded cache
                self._hour_times[h] = pred
        return pred

    def _bridge_components(self, senders, receivers, length, road_class,
                           speed_limit):
        """kNN graphs can come out disconnected; bridge every component to
        the largest with an edge between their closest node pair so every
        snap target is reachable. Pure numpy union-find — scipy is a test
        oracle here, not a runtime dependency."""
        n = len(self.coords)
        parent = np.arange(n)

        def find(a: int) -> int:
            root = a
            while parent[root] != root:
                root = parent[root]
            while parent[a] != root:  # path compression
                parent[a], a = root, parent[a]
            return root

        for s, r in zip(senders, receivers):
            ra, rb = find(int(s)), find(int(r))
            if ra != rb:
                parent[rb] = ra
        labels_raw = np.fromiter((find(i) for i in range(n)), np.int64, n)
        _, labels = np.unique(labels_raw, return_inverse=True)
        n_comp = int(labels.max()) + 1
        if n_comp <= 1:
            return senders, receivers, length, road_class, speed_limit
        sizes = np.bincount(labels)
        main = int(np.argmax(sizes))
        add_s, add_r = [], []
        main_nodes = np.flatnonzero(labels == main)
        for comp in range(n_comp):
            if comp == main:
                continue
            nodes = np.flatnonzero(labels == comp)
            d = haversine_np(
                self.coords[nodes, 0][:, None], self.coords[nodes, 1][:, None],
                self.coords[main_nodes, 0][None, :],
                self.coords[main_nodes, 1][None, :])
            i, j = np.unravel_index(np.argmin(d), d.shape)
            add_s.append(nodes[i])
            add_r.append(main_nodes[j])
        add_s = np.asarray(add_s, np.int32)
        add_r = np.asarray(add_r, np.int32)
        bridge_len = (haversine_np(
            self.coords[add_s, 0], self.coords[add_s, 1],
            self.coords[add_r, 0], self.coords[add_r, 1]) * 1.2).astype(np.float32)
        bridge_class = np.full(len(add_s), 1, np.int32)  # collector
        bridge_speed = np.full(len(add_s), _CLASS_SPEED_MPS[1], np.float32)
        return (np.concatenate([senders, add_s, add_r]),
                np.concatenate([receivers, add_r, add_s]),
                np.concatenate([length, bridge_len, bridge_len]),
                np.concatenate([road_class, bridge_class, bridge_class]),
                np.concatenate([speed_limit, bridge_speed, bridge_speed]))

    def snap(self, latlon: np.ndarray) -> np.ndarray:
        """(M, 2) lat/lon → (M,) nearest graph node ids."""
        latlon = np.asarray(latlon, np.float32)
        d = haversine_np(latlon[:, 0][:, None], latlon[:, 1][:, None],
                          self.coords[None, :, 0], self.coords[None, :, 1])
        return np.argmin(d, axis=1).astype(np.int32)

    def shortest(self, source_nodes: np.ndarray,
                 live: Optional[_LiveMetric] = None):
        """(S,) nodes → ((S, N) distances m, (S, N) predecessor edge ids).

        The source axis is padded to power-of-two buckets (duplicating
        source 0) so varying waypoint counts reuse one compiled program
        instead of recompiling the while_loop on the request path — the
        same bucket trick as the serving batcher. Concurrent callers
        whose metric generation matches merge into ONE device dispatch
        through the solve batcher (``_SolveBatcher``) — the row axis is
        batched by construction, so merged results are bitwise what a
        lone solve returns.

        With ``live`` (a snapshot of ``self._live`` taken ONCE by the
        caller, so one request batch never straddles a flip) and its
        route metric armed, the solve runs over the live travel-TIME
        metric instead of meters: distances come back in seconds, and
        predecessor trees are time-shortest (``route_legs_batch``
        recovers leg meters along those trees separately).
        """
        source_nodes = np.asarray(source_nodes, np.int32)
        batcher = self._solve_batcher
        if batcher is not None and 0 < len(source_nodes) <= batcher.max_rows:
            return batcher.solve(source_nodes, live)
        # Direct path (batcher off, or oversized request): still a
        # padded device launch the goodput ledger must see.
        n = len(source_nodes)
        t0 = time.perf_counter()
        out = self._solve_rows(source_nodes, live)
        if n > 0:
            bucket = 1 << max(0, n - 1).bit_length()
            get_ledger().record(
                "route_solve", real_rows=n, padded_rows=bucket,
                bucket=bucket, compute_s=time.perf_counter() - t0,
                oversized=batcher is not None and n > batcher.max_rows)
        return out

    def _solve_rows(self, source_nodes: np.ndarray,
                    live: Optional[_LiveMetric] = None):
        """The real dispatch body behind :meth:`shortest` (the batcher
        calls this with merged rows)."""
        source_nodes = np.asarray(source_nodes, np.int32)
        n_src = len(source_nodes)
        bucket = 1 << max(0, (n_src - 1)).bit_length()
        padded = np.full(bucket, source_nodes[0] if n_src else 0, np.int32)
        padded[:n_src] = source_nodes
        if live is not None and live.route:
            t0 = time.perf_counter()
            if live.hier is not None:
                p_cells, seed_pos, seed_val = live.hier.prep_sources(padded)
                solve = live.aot.get(bucket, live.solve)
                dist, pred = jax.device_get(solve(
                    p_cells, seed_pos, seed_val, jnp.asarray(padded)))
                _router_metrics()["phase"].labels(phase="solve").observe(
                    time.perf_counter() - t0)
                # full_solve_fn already returns ORIGINAL edge ids.
                return dist[:n_src], pred[:n_src]
            # Flat graphs re-dispatch the SAME compiled program with
            # the time weights as arguments — a metric flip costs
            # zero recompiles here.
            dist, pred, converged = jax.device_get(_bellman_ford(
                self._bf_senders, self._bf_receivers, live.d_time_bf,
                jnp.asarray(padded),
                n_nodes=self.n_nodes, max_iters=self.max_iters))
            if not bool(converged):
                dist, pred, _ = jax.device_get(_bellman_ford(
                    self._bf_senders, self._bf_receivers,
                    live.d_time_bf, jnp.asarray(padded),
                    n_nodes=self.n_nodes, max_iters=self.n_nodes))
            _router_metrics()["phase"].labels(phase="solve").observe(
                time.perf_counter() - t0)
            pred = pred[:n_src]
            pred = np.where(pred >= 0, self._bf_perm[np.maximum(pred, 0)],
                            -1)
            return dist[:n_src], pred
        if self._hier is not None:
            # Overlay path: exact distances in O(top-cells-across)
            # sweeps (or one hub-label fold), polish + predecessor
            # recovery on the CONTRACTED graph, and exact chain
            # synthesis back to full-graph rows — all one fused
            # program returning ORIGINAL edge predecessors.
            # Convergence is guaranteed by construction (the overlay
            # loop's bound is its exact node count), so no exhaustion
            # re-run exists. Buckets AOT-compiled at init dispatch the
            # ready executable directly.
            t0 = time.perf_counter()
            p_cells, seed_pos, seed_val = self._hier.prep_sources(padded)
            solve = self._aot.get(bucket, self._overlay_solve)
            dist, pred = jax.device_get(solve(
                p_cells, seed_pos, seed_val, jnp.asarray(padded)))
            _router_metrics()["phase"].labels(phase="solve").observe(
                time.perf_counter() - t0)
            return dist[:n_src], pred[:n_src]
        # ONE batched device_get for (dist, pred, converged): separate
        # np.asarray fetches each pay a full tunnel round trip (~70 ms),
        # which dominated small-graph request latency (252 → 102 ms
        # measured on the 2k serving graph).
        t0 = time.perf_counter()
        dist, pred, converged = jax.device_get(_bellman_ford(
            self._bf_senders, self._bf_receivers, self._bf_length,
            jnp.asarray(padded),
            n_nodes=self.n_nodes, max_iters=self.max_iters))
        if not bool(converged):
            # The O(√N) diameter heuristic was exhausted while distances
            # were still improving (possible on long chains, e.g. after
            # component bridging, or user-supplied path-like graphs).
            # Silently-wrong distances are never acceptable: re-run with
            # the exact N-1 Bellman-Ford bound.
            get_logger("routest.road").warning(
                "bellman_ford_bound_exhausted", heuristic=self.max_iters,
                exact=self.n_nodes, n_sources=n_src)
            dist, pred, converged = jax.device_get(_bellman_ford(
                self._bf_senders, self._bf_receivers, self._bf_length,
                jnp.asarray(padded),
                n_nodes=self.n_nodes, max_iters=self.n_nodes))
        _router_metrics()["phase"].labels(phase="solve").observe(
            time.perf_counter() - t0)
        pred = pred[:n_src]
        # sorted-edge ids → original edge ids (RoadLegs/_walk index the
        # original arrays, which also carry the GNN's per-edge times)
        pred = np.where(pred >= 0, self._bf_perm[np.maximum(pred, 0)], -1)
        return dist[:n_src], pred

    def _meters_along(self, pred: np.ndarray,
                      metric_rows: np.ndarray) -> np.ndarray:
        """(S, N) meters accumulated along the given predecessor trees
        (pointer doubling — the ``_time_table`` machinery with lengths
        as the per-edge cost). Live-metric solves are time-shortest, so
        leg DISTANCES must be recovered along those trees rather than
        read from the solve's own (seconds) table."""
        m = len(pred)
        bucket = 1 << max(0, (m - 1)).bit_length()
        pad = [(0, bucket - m), (0, 0)]
        n_rounds = max(1, (max(self.n_nodes - 1, 1)).bit_length())
        meters = np.asarray(_time_table(
            self._d_senders, jnp.asarray(np.pad(pred, pad, mode="edge")),
            self._d_length,
            jnp.asarray(np.pad(metric_rows, pad, mode="edge")),
            n_rounds=n_rounds))[:m]
        # Same unreachable sentinel as the distance solve (3e38, finite)
        # so downstream consumers see one convention either way.
        return np.where(np.isfinite(meters), meters,
                        np.float32(3e38)).astype(np.float32)

    def _walk(self, pred_row: np.ndarray, source: int, target: int) -> List[int]:
        """Predecessor edges → node sequence source..target (host-side)."""
        path = [int(target)]
        node = int(target)
        for _ in range(self.n_nodes):
            if node == source:
                break
            e = int(pred_row[node])
            if e < 0:
                return []  # unreachable
            node = int(self.senders[e])
            path.append(node)
        if node != source:
            # Iteration budget exhausted without reaching the source — a
            # predecessor cycle (possible with degenerate zero-length
            # edges). Unreachable beats a garbage path.
            return []
        return path[::-1]

    def route_legs(self, points_latlon: np.ndarray,
                   time_scale: float = 1.0,
                   hour: Optional[int] = None) -> "RoadLegs":
        """Legs between M waypoints over the road graph.

        One batched shortest-path solve up front (all M sources at once —
        the device-friendly part); per-leg predecessor walks, durations,
        and polylines are LAZY and memoized, because the VRP consumes the
        full (M, M) distance matrix but the response only renders the ~M
        legs of the solved trips. ``time_scale`` maps free-flow car times
        to the vehicle profile. ``hour`` (0-23, pickup hour) selects the
        learned congestion regime when the GNN is active; None prices at
        noon off-peak.
        """
        return self.route_legs_batch([(points_latlon, time_scale, hour)])[0]

    def route_legs_batch(self, problems) -> List["RoadLegs"]:
        """Traced entry: the ``router.route_legs`` span carries the
        per-request provenance the PR 10–12 fast paths added — route-
        cache hits/misses/waits, hub-labels vs top-BF solver path,
        serving metric epoch, road-model generation — so a tail-sampled
        slow route trace says WHICH path it took. Body in
        :meth:`_route_legs_batch_traced`."""
        with trace_span("router.route_legs",
                        problems=len(problems)) as span:
            return self._route_legs_batch_traced(problems, span)

    def _route_legs_batch_traced(self, problems, span) -> List["RoadLegs"]:
        """Many waypoint sets → one :class:`RoadLegs` each, sharing as
        FEW device solves as memory allows.

        ``problems``: list of ``(points_latlon, time_scale, hour)``
        triples (``route_legs``'s arguments — the single path IS the
        one-problem batch, so the two can never diverge). The
        shortest-path solver is batched over sources by design, so
        problems concatenate along the source axis and split back as
        row slices — each source row's distances are computed
        independently, so results are bitwise identical to
        per-problem solves. Groups are sized so one fetch (dist f32 +
        pred i32 rows over every node) stays under ~64 MB:
        serving-default graphs take a single call, metro graphs chunk
        instead of materializing a (ΣM, N) table.

        Problems first consult the route fastlane
        (``optimize/route_cache.py``): a cached identical problem —
        same waypoint bytes, time scale, hour, live-metric epoch and
        road-model generation — skips snap AND solve entirely, and
        concurrent identical problems collapse onto one solve
        (singleflight). Only the uncached remainder reaches the
        grouped solves below.
        """
        self._maybe_reload_models()  # once for the whole batch
        pts_list = [np.asarray(p, np.float32) for p, _, _ in problems]
        counts = [len(p) for p in pts_list]
        # ONE live-metric snapshot for the whole batch: every problem in
        # it prices (and, with the route metric armed, routes) against
        # the same metric generation — a concurrent flip affects only
        # later batches, never tears this one.
        live = self._live
        out: List[Optional[RoadLegs]] = [None] * len(problems)
        cache = self._route_cache
        keys: List = [None] * len(problems)
        aliases: List[Tuple[int, int]] = []        # (idx, lead idx)
        waits: List[Tuple[int, object]] = []       # (idx, flight)
        solve_idx: List[int] = list(range(len(problems)))
        if cache is not None:
            epoch = ((live.epoch, live.gen) if live is not None
                     else (0, 0))
            gen = self._model_gen
            my_leads: Dict = {}
            solve_idx = []
            for i, pts in enumerate(pts_list):
                _, time_scale, hour = problems[i]
                eff_hour = 12 if hour is None else int(hour) % 24
                key = (pts.tobytes(), len(pts), float(time_scale),
                       eff_hour, epoch, gen)
                keys[i] = key
                lead = my_leads.get(key)
                if lead is not None:
                    # duplicate inside this batch: share the lead's
                    # legs (waiting on our own flight would deadlock)
                    aliases.append((i, lead))
                    continue
                state, val = cache.lookup(key)
                if state == "hit":
                    out[i] = val
                elif state == "wait":
                    waits.append((i, val))
                else:
                    my_leads[key] = i
                    solve_idx.append(i)

        # Trace provenance: which solver regime, metric generation, and
        # cache outcome served THIS batch (the attrs a tail-sampled
        # slow trace needs to say which path it took).
        span.set_attr(
            "solver",
            "hub_labels" if (self._hier is not None
                             and self._hier._labels is not None)
            else ("overlay_top_bf" if self._hier is not None
                  else "flat_bf"))
        span.set_attr("metric_epoch",
                      live.epoch if live is not None else 0)
        span.set_attr("model_generation", self._model_gen)
        if cache is None:
            span.set_attr("route_cache", "off")
        else:
            span.set_attr("route_cache_hits",
                          sum(1 for o in out if o is not None))
            span.set_attr("route_cache_misses", len(solve_idx))
            span.set_attr("route_cache_waits", len(waits))
            span.set_attr("route_cache_aliases", len(aliases))

        try:
            if solve_idx:
                self._solve_problems(problems, pts_list, counts,
                                     solve_idx, live, out,
                                     copy_rows=cache is not None)
        except BaseException as e:
            if cache is not None:
                for i in solve_idx:
                    cache.abort(keys[i], e)
            raise
        if cache is not None:
            for i in solve_idx:
                legs = out[i]
                cache.commit(keys[i], legs, legs.nbytes())
        for i, lead in aliases:
            out[i] = out[lead]
        if waits:
            # Respect the request budget like the ETA fast lane: a
            # parked waiter must not outlive its deadline waiting on a
            # slow leader.
            from routest_tpu.serve.deadline import current_deadline

            dl = current_deadline()
            budget = (None if dl is None
                      else max(0.0, dl - time.monotonic()))
            for i, flight in waits:
                out[i] = cache.wait(flight, budget)
        return out

    def _solve_problems(self, problems, pts_list, counts, solve_idx,
                        live, out, *, copy_rows: bool) -> None:
        """Snap + grouped solves + :class:`RoadLegs` construction for
        the selected problem indices (the cache-miss remainder).
        ``copy_rows`` detaches each problem's rows from the group
        solve's big arrays so a cached entry can never pin a whole
        (Σrows, N) result."""
        sel_counts = [counts[i] for i in solve_idx]
        offsets = np.concatenate([[0], np.cumsum(sel_counts)])
        all_pts = np.concatenate([pts_list[i] for i in solve_idx], axis=0)
        # snap() materializes an (M, N) haversine table — chunk its row
        # axis too, or a full road batch on a country-scale graph would
        # build the multi-GB host tensor the solve grouping avoids.
        t0 = time.perf_counter()
        snap_chunk = max(1, (16 << 20) // max(self.n_nodes, 1))
        all_nodes = np.concatenate([
            self.snap(all_pts[i:i + snap_chunk])
            for i in range(0, len(all_pts), snap_chunk)])
        _router_metrics()["phase"].labels(phase="snap").observe(
            time.perf_counter() - t0)
        # First/last mile: the request point is rarely ON the network;
        # charge the point↔snapped-node gap into every leg (at collector
        # free-flow for the duration) so far-off-network points see
        # physically sensible totals instead of intra-graph-only paths.
        all_snap = haversine_np(
            all_pts[:, 0], all_pts[:, 1],
            self.coords[all_nodes, 0],
            self.coords[all_nodes, 1]).astype(np.float32)

        budget = _legs_batch_row_budget(self.n_nodes)
        groups: List[List[int]] = []
        cur: List[int] = []
        rows = 0
        for j, m in enumerate(sel_counts):
            if cur and rows + m > budget:
                groups.append(cur)
                cur, rows = [], 0
            cur.append(j)
            rows += m
        if cur:
            groups.append(cur)

        def _rows(a, lo, hi):
            return a[lo:hi].copy() if copy_rows else a[lo:hi]

        for g in groups:
            sel = np.concatenate([np.arange(offsets[j], offsets[j + 1])
                                  for j in g])
            dist, pred = self.shortest(all_nodes[sel], live=live)
            meters = (self._meters_along(pred, dist)
                      if live is not None and live.route else None)
            pos = 0
            for j in g:
                i = solve_idx[j]
                m = sel_counts[j]
                _, time_scale, hour = problems[i]
                eff_hour = 12 if hour is None else int(hour) % 24
                if live is not None:
                    # Live pricing: the legs' per-edge seconds ARE the
                    # installed metric — route solves, leg durations and
                    # the oracle-facing export stay coherent by
                    # construction (hour blending happens at flip time).
                    time_arr = live.time_s
                    cost_model = f"live+{self.leg_cost_model}"
                else:
                    time_arr = self.edge_time_s(eff_hour)
                    cost_model = self.leg_cost_model
                out[i] = RoadLegs(
                    self, pts_list[i],
                    all_nodes[offsets[j]:offsets[j + 1]],
                    _rows(dist, pos, pos + m), _rows(pred, pos, pos + m),
                    all_snap[offsets[j]:offsets[j + 1]],
                    time_scale, time_arr,
                    cost_model, hour=eff_hour,
                    meters_rows=(_rows(meters, pos, pos + m)
                                 if meters is not None else None))
                pos += m


_SNAP_SPEED_MPS = 8.3  # first/last-mile charged at collector free-flow


def _legs_batch_row_budget(n_nodes: int) -> int:
    """Max source rows per grouped batch solve: bounds each dist f32 +
    pred i32 fetch to ~64 MB whatever the graph size (clamped so tiny
    graphs still group generously and huge ones keep ≥16 rows)."""
    return max(16, min(512, (64 << 20) // (8 * max(n_nodes, 1))))


class RoadLegs:
    """Lazy, memoized per-leg view over one batched shortest-path solve."""

    def __init__(self, router: RoadRouter, points: np.ndarray,
                 nodes: np.ndarray, dist: np.ndarray, pred: np.ndarray,
                 snap_m: np.ndarray, time_scale: float,
                 time_s: Optional[np.ndarray] = None,
                 cost_model: str = "freeflow",
                 hour: int = 12,
                 meters_rows: Optional[np.ndarray] = None) -> None:
        self._r = router
        self._hour = hour
        self._points = points
        self._nodes = nodes
        self._pred = pred
        self._snap_m = snap_m
        self._time_scale = time_scale
        self._time_s = time_s if time_s is not None else router.freeflow_time_s
        self.cost_model = cost_model
        # Live-metric solves are TIME-shortest: ``dist`` rows are
        # seconds and ``meters_rows`` carries the meters recovered
        # along those trees — the VRP/ABI distance fields must stay in
        # meters whatever metric chose the paths.
        self._live_metric = meters_rows is not None
        m = len(points)
        # Full matrix (the VRP input): graph distance + first/last mile.
        phys = meters_rows if meters_rows is not None else dist
        self.dist_m = phys[np.arange(m)[:, None], nodes[None, :]] \
            + snap_m[:, None] + snap_m[None, :]
        np.fill_diagonal(self.dist_m, 0.0)
        self._dist_rows = dist            # (M, N): duration_matrix masks by it
        self._dur_rows: Optional[np.ndarray] = None
        self._memo: Dict[Tuple[int, int], Tuple[float, float, list]] = {}
        self._cost_memo: Dict[Tuple[int, int], Tuple[float, float]] = {}

    def nbytes(self) -> int:
        """Resident bytes a cached entry pins (the route fastlane's
        byte-budget input) — the (M, N) solve rows dominate."""
        n = self._pred.nbytes + self._dist_rows.nbytes + self.dist_m.nbytes
        if self._dur_rows is not None:
            n += self._dur_rows.nbytes
        return int(n)

    def _walk_cost(self, i: int, j: int):
        """Memoized shared core: (node_seq, distance_m, duration_s) for
        leg i→j — ONE place owns the predecessor walk and the duration
        formula so the cost-only and geometry accessors can never price
        a leg differently. ``node_seq`` is [] when unreachable."""
        cached = self._cost_memo.get((i, j))
        if cached is not None:
            return cached
        t0 = time.perf_counter()
        node_seq = self._r._walk(self._pred[i], int(self._nodes[i]),
                                 int(self._nodes[j]))
        _router_metrics()["phase"].labels(phase="walk").observe(
            time.perf_counter() - t0)
        if not node_seq:
            out = ([], float("inf"), float("inf"))
        else:
            # pred[i][b] is by construction the edge that enters b here
            dur = self._time_scale * (
                float(sum(self._time_s[int(self._pred[i][b])]
                          for b in node_seq[1:]))
                + (self._snap_m[i] + self._snap_m[j]) / _SNAP_SPEED_MPS)
            out = (node_seq, float(self.dist_m[i, j]), float(dur))
        self._cost_memo[(i, j)] = out
        return out

    def reprice_trips(self, trips) -> Dict[Tuple[int, int], float]:
        """Route-context leg durations from the route transformer.

        ``trips`` is the solved assignment (lists of destination indices,
        ``solve_host`` form). Each trip's legs concatenate into ONE edge
        sequence (origin → stops → origin) and the transformer re-prices
        every edge with route context — per-leg times then depend on
        where in the tour the leg sits, which per-edge pricers (GNN,
        free-flow) cannot express. Returns ``{(i, j): duration_s}`` per
        leg, or ``{}`` when no transformer artifact serves this graph /
        any leg is unwalkable (callers keep base pricing — the same
        graceful-degradation contract as every model here).

        Trips in the solved assignment are stop-disjoint, so (i, j) leg
        keys cannot collide across trips. For ALTERNATIVE orders over
        the same stops use :meth:`reprice_orders` (list-shaped, no keys).
        """
        per_trip = self._reprice([[int(s) for s in t] for t in trips])
        if per_trip is None:
            return {}
        out: Dict[Tuple[int, int], float] = {}
        for legs in per_trip:
            out.update(legs)
        return out

    def reprice_orders(self, orders):
        """Transformer durations for CANDIDATE single-trip orders:
        list of stop-index orders → list of total route seconds (None
        per order when unavailable). One batched forward prices every
        candidate, so alternatives stay comparable with the
        transformer-priced main summary."""
        per_trip = self._reprice([[int(s) for s in o] for o in orders])
        if per_trip is None:
            return [None] * len(orders)
        return [sum(d for _, d in legs.items()) for legs in per_trip]

    def _reprice(self, trips):
        """Shared core: list of trips (stop-index lists) → list of
        ``{(i, j): duration_s}`` per trip, or None when the transformer
        is unavailable / any leg is unwalkable.

        Tours longer than the artifact's trained ``seq_len`` are CHUNKED
        into seq_len windows with window-local positions — exactly the
        training distribution (each training route starts at position 0
        and is ≤ seq_len legs) — so long metro tours never push the
        model out of its validated envelope, and attention cost stays
        O(seq_len²) per window instead of O(tour²).
        """
        t = self._r._transformer
        if t is None or not trips:
            return None
        if self._live_metric:
            # The transformer was trained on the frozen world (free-flow
            # features, no live context); letting it re-price legs would
            # silently overwrite the live-blended durations the metric
            # flip just installed. Base (live) pricing stands.
            return None
        from routest_tpu.models.gnn import edge_feature_array

        model, params, seq_len = t
        r = self._r
        # (trip index, leg key, edge ids) per leg, in tour order.
        trip_legs: list = []
        for trip in trips:
            seq = [0] + [s + 1 for s in trip] + [0]
            legs = []
            for a, b in zip(seq[:-1], seq[1:]):
                if a == b:
                    continue
                node_seq, _m, _s = self._walk_cost(a, b)
                if not node_seq:
                    return None  # unwalkable leg: keep base pricing
                legs.append(((a, b),
                             [int(self._pred[a][n]) for n in node_seq[1:]]))
            trip_legs.append(legs)

        # Flatten every trip's edge sequence into seq_len windows.
        windows: list = []   # (trip_idx, [edge ids])
        for ti, legs in enumerate(trip_legs):
            edges = [e for _, leg_edges in legs for e in leg_edges]
            for start in range(0, len(edges), seq_len):
                windows.append((ti, edges[start: start + seq_len]))
        if not windows:
            return [dict() for _ in trip_legs]
        s_max = max(len(w) for _, w in windows)
        feats = np.zeros((len(windows), s_max, model.n_features), np.float32)
        freeflow = np.zeros((len(windows), s_max), np.float32)
        mask = np.zeros((len(windows), s_max), np.float32)
        for wi, (_, edges) in enumerate(windows):
            e_ids = np.asarray(edges, np.int64)
            k = len(e_ids)
            feats[wi, :k] = edge_feature_array(
                r.length_m[e_ids], r.speed_limit[e_ids],
                r.road_class[e_ids], self._hour)
            freeflow[wi, :k] = r.freeflow_time_s[e_ids]
            mask[wi, :k] = 1.0
        import jax.numpy as jnp

        try:
            pred = np.asarray(model.apply(
                params, jnp.asarray(feats), jnp.asarray(freeflow),
                jnp.arange(s_max), key_mask=jnp.asarray(mask)), np.float32)
        except Exception as e:  # degrade to base pricing, drop the model
            get_logger("routest.road").error(
                "route_transformer_apply_failed",
                error=f"{type(e).__name__}: {e}")
            with r._gnn_lock:
                r._transformer = None
            return None

        # Stitch window predictions back into per-trip edge streams.
        stream: Dict[int, list] = {ti: [] for ti in range(len(trip_legs))}
        for wi, (ti, edges) in enumerate(windows):
            stream[ti].extend(pred[wi, : len(edges)].tolist())
        out: list = []
        for ti, legs in enumerate(trip_legs):
            flat = stream[ti]
            offset = 0
            priced: Dict[Tuple[int, int], float] = {}
            for (a, b), edges in legs:
                k = len(edges)
                e_ids = np.asarray(edges, np.int64)
                # Same physical floor as the GNN pricer: no edge beats
                # free-flow at an arterial ceiling.
                leg_pred = np.maximum(
                    np.asarray(flat[offset: offset + k], np.float32),
                    r.length_m[e_ids] / 16.7)
                offset += k
                priced[(a, b)] = float(self._time_scale * (
                    float(leg_pred.sum())
                    + (self._snap_m[a] + self._snap_m[b]) / _SNAP_SPEED_MPS))
            out.append(priced)
        return out

    def cost(self, i: int, j: int) -> Tuple[float, float]:
        """(distance_m, duration_s) for waypoint leg i→j WITHOUT
        building the polyline — for callers pricing many pairs none of
        which may render (matrix responses, candidate orders). Same
        memoized walk core as :meth:`leg`, so the two can never
        disagree; a later ``leg`` call only adds the geometry pass."""
        if i == j:
            return 0.0, 0.0
        _, dist_m, dur = self._walk_cost(i, j)
        return dist_m, dur

    def duration_matrix(self) -> np.ndarray:
        """(M, M) leg seconds for EVERY waypoint pair in one device
        dispatch. The per-pair walk in :meth:`cost` is O(path length)
        host pointer chasing — fine for a handful of response legs,
        seconds for a full matrix at metro scale. Here the whole
        (M, N) time table accumulates on device via pointer doubling
        (``_time_table``) and the matrix is one gather; values match
        the walk to f32 rounding (sums re-associate). Computed lazily,
        once per solve."""
        if self._dur_rows is None:
            t0 = time.perf_counter()
            r = self._r
            n_rounds = max(1, (max(r.n_nodes - 1, 1)).bit_length())
            # Same bucket trick as shortest(): pad the waypoint axis to
            # a power of two (repeating the last row) so varying M reuses
            # one compiled table program instead of recompiling per count.
            m = len(self._pred)
            bucket = 1 << max(0, (m - 1)).bit_length()
            pad = [(0, bucket - m), (0, 0)]
            self._dur_rows = np.asarray(_time_table(
                r._d_senders,
                jnp.asarray(np.pad(self._pred, pad, mode="edge")),
                jnp.asarray(self._time_s),
                jnp.asarray(np.pad(self._dist_rows, pad, mode="edge")),
                n_rounds=n_rounds))[:m]
            _router_metrics()["phase"].labels(phase="matrix").observe(
                time.perf_counter() - t0)
        dur = self._dur_rows[:, self._nodes].astype(np.float64)
        dur = self._time_scale * (
            dur + (self._snap_m[:, None] + self._snap_m[None, :])
            / _SNAP_SPEED_MPS)
        np.fill_diagonal(dur, 0.0)
        return dur

    def leg(self, i: int, j: int) -> Tuple[float, float, List[List[float]]]:
        """(distance_m, duration_s, [[lon, lat], …]) for waypoint leg i→j."""
        if i == j:
            return 0.0, 0.0, []
        key = (i, j)
        if key in self._memo:
            return self._memo[key]
        node_seq, dist_m, dur = self._walk_cost(i, j)
        if not node_seq:
            out = (float("inf"), float("inf"), [])
        else:
            poly = [[float(self._r.coords[n, 1]), float(self._r.coords[n, 0])]
                    for n in node_seq]
            # endpoints: exact request coordinates, not snapped nodes
            poly.insert(0, [float(self._points[i, 1]), float(self._points[i, 0])])
            poly.append([float(self._points[j, 1]), float(self._points[j, 0])])
            # plain python floats: np.float32 would survive into the JSON
            # serializer (json.dumps rejects it)
            out = (dist_m, dur, poly)
        self._memo[key] = out
        return out


_default_router: Optional[RoadRouter] = None
_default_lock = threading.Lock()


def default_router() -> RoadRouter:
    """Process-wide router: a real OSM extract when ``ROAD_GRAPH_OSM``
    points at one (``data/osm.py``), else the generated Metro Manila
    network. A bad extract degrades to the generator with a log line
    rather than taking down routing."""
    import os

    global _default_router
    with _default_lock:
        if _default_router is None:
            osm_path = os.environ.get("ROAD_GRAPH_OSM")
            if osm_path:
                from routest_tpu.data.osm import load_osm

                try:
                    _default_router = RoadRouter(graph=load_osm(osm_path))
                except Exception as e:
                    get_logger("routest.road").error(
                        "osm_extract_unusable", path=osm_path,
                        error=f"{type(e).__name__}: {e}")
            if _default_router is None:
                _default_router = RoadRouter()
        return _default_router
