"""Two-level partition overlay for metro-scale shortest paths.

The flat batched Bellman-Ford in ``optimize/road_router.py`` is
*diameter-bound*: every sweep advances the frontier one hop, so a
street network's O(sqrt(N)) hop diameter costs ~900 dependent device
sweeps at 50k nodes and grows without bound (VERDICT r3 weak #2 — the
rented engine this framework replaces, ORS, answers matrix calls on
country-scale graphs in tens of ms;
``/root/reference/backend/route_optimizer_twx2/Flaskr/utils.py:97-103``).

This module removes the diameter from the critical path with the
classic two-level *overlay* decomposition (the "customizable route
planning" family), re-designed for the TPU's strength — big dense
batched relaxations instead of priority queues:

1. **Partition**: recursive coordinate bisection splits the node set
   into geometrically compact cells of bounded size. Pure numpy, one
   time, O(N log N).
2. **Precompute** (device, batched over every cell at once): a
   restricted Bellman-Ford *inside each cell* from each of its
   boundary nodes (nodes incident to a cell-crossing edge) gives
   - ``table[cell, b, v]``: exact in-cell distance boundary→node, and
   - a boundary→boundary *clique* per cell (the overlay shortcuts),
     pruned of edges implied by two-hop boundary paths.
   Cells are independent, so the sweep vmaps over (cell, boundary
   source) — exactly the wide, regular batch shape XLA tiles well.
3. **Query** (device): for S sources at once,
   - phase 1: tiny restricted BF inside each source's cell;
   - phase 2: Bellman-Ford over the *overlay graph* (boundary nodes,
     clique + original cross-cell edges), seeded with phase 1 — its
     hop count is the number of cells across the metro, not nodes;
   - phase 3: a min-plus stitch ``min_b(overlay[s,b] + table[cell,b,v])``
     folds boundary distances through the precomputed tables to every
     node, as a fori accumulation over the boundary axis (never
     materializing the (S, P, b, c) proposal tensor).

Exactness: any shortest path decomposes at cell crossings into
maximal within-cell segments between boundary nodes; each segment's
restricted length equals a clique weight, so the overlay metric is the
true metric on boundary nodes, and the stitched suffix is the true
in-cell tail. Same-cell journeys that never leave the cell are covered
by phase 1; journeys that leave and re-enter are covered by phase 3.
The query therefore returns *exact* distances (up to f32 rounding from
re-associated sums), and ``road_router.shortest`` re-uses its existing
tight-edge predecessor recovery unchanged — after a few polish sweeps
of the flat relaxation that re-anchor ties to bit-identical
``dist[s] + w`` assignments.

Directed graphs (OSM one-ways) are handled: tables, cliques and the
phase-3 stitch are all forward-direction restricted distances.
"""

from __future__ import annotations

import functools
import os
import time
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

_INF = jnp.float32(3e38)
_INF_NP = np.float32(3e38)
# Number of flat relaxation sweeps fused per while_loop iteration: the
# convergence check costs a device sync, which dominates small graphs
# (measured in road_router._bellman_ford — same constant, same reason).
_K_SWEEPS = 4

_CACHE_VERSION = 1


# ---------------------------------------------------------------------------
# Shared flat-relaxation primitives (road_router builds on these too).
# ---------------------------------------------------------------------------

@functools.partial(jax.jit, static_argnames=("n_nodes", "max_iters"))
def relax_from(senders: jax.Array, receivers: jax.Array, w: jax.Array,
               dist0: jax.Array, *, n_nodes: int,
               max_iters: int) -> Tuple[jax.Array, jax.Array]:
    """Bellman-Ford relaxation sweeps from an arbitrary initial
    distance table. ``dist0`` is (S, n_nodes); edges must be sorted by
    receiver (``segment_min(indices_are_sorted=True)``). Returns the
    relaxed table and a scalar bool: True iff a sweep changed nothing
    (converged) rather than the iteration bound being exhausted."""

    def seg_min(p):
        return jax.ops.segment_min(p, receivers, num_segments=n_nodes,
                                   indices_are_sorted=True)

    def one_sweep(dist):
        proposals = dist[:, senders] + w[None, :]
        return jnp.minimum(dist, jax.vmap(seg_min)(proposals))

    def relax(state):
        dist, _, it = state
        new = dist
        for _ in range(_K_SWEEPS):
            new = one_sweep(new)
        return new, jnp.any(new < dist), it + _K_SWEEPS

    def keep_going(state):
        _, changed, it = state
        return changed & (it < max_iters)

    dist, still_changing, _ = jax.lax.while_loop(
        keep_going, relax,
        (dist0, jnp.asarray(True), jnp.zeros((), jnp.int32)))
    return dist, jnp.logical_not(still_changing)


@functools.partial(jax.jit, static_argnames=("n_nodes",))
def tight_pred(senders: jax.Array, receivers: jax.Array, w: jax.Array,
               dist: jax.Array, sources: jax.Array, *,
               n_nodes: int) -> jax.Array:
    """Predecessor recovery from a converged distance table: the edge
    entering each node with *minimal slack* (``dist[s] + w - dist[r]``)
    lies on a shortest path; segment-max of the edge id among
    minimal-slack edges picks one deterministically.

    Min-slack (not "any edge within a tolerance") matters on real
    street data: short edges exist (sub-meter OSM segments), so a fixed
    tolerance wide enough for the hierarchy's re-associated f32 sums
    could mark a short edge tight in BOTH directions and hand ``_walk``
    a predecessor 2-cycle. The minimal-slack edge is near-exact by
    construction — a relaxation sweep *assigned* ``dist[r]`` from its
    argmin proposal, so its slack is ~0 bitwise and a reverse edge
    (slack ≥ w + w') can never tie with it past the 1 cm merge slack
    below."""
    slack = dist[:, senders] + w[None, :] - dist[:, receivers]

    def seg_min(s):
        return jax.ops.segment_min(s, receivers, num_segments=n_nodes,
                                   indices_are_sorted=True)

    min_slack = jax.vmap(seg_min)(slack)           # (S, N)
    tight = slack <= min_slack[:, receivers] + 1e-2
    e_ids = jnp.arange(senders.shape[0], dtype=jnp.int32)

    def seg_max(t):
        return jax.ops.segment_max(jnp.where(t, e_ids, -1), receivers,
                                   num_segments=n_nodes,
                                   indices_are_sorted=True)

    pred = jnp.maximum(jax.vmap(seg_max)(tight), -1)
    n_src = dist.shape[0]
    return pred.at[jnp.arange(n_src), sources].set(-1)


# ---------------------------------------------------------------------------
# Partition
# ---------------------------------------------------------------------------

def partition_cells(coords: np.ndarray,
                    cell_target: int) -> Tuple[np.ndarray, int]:
    """(N, 2) coords → (N,) cell ids via recursive median bisection on
    the wider coordinate axis: cells are size-balanced (≤ cell_target)
    and geometrically compact, which keeps boundary sets small — the
    quantity every overlay cost scales with."""
    n = len(coords)
    cell = np.zeros(n, np.int32)
    stack = [np.arange(n)]
    parts = []
    while stack:
        idx = stack.pop()
        if len(idx) <= cell_target:
            parts.append(idx)
            continue
        c = coords[idx]
        axis = int(np.argmax(c.max(axis=0) - c.min(axis=0)))
        order = np.argsort(c[:, axis], kind="stable")
        half = len(idx) // 2
        stack.append(idx[order[:half]])
        stack.append(idx[order[half:]])
    for ci, idx in enumerate(parts):
        cell[idx] = ci
    return cell, len(parts)


# ---------------------------------------------------------------------------
# Batched within-cell relaxation (precompute + query phase 1)
# ---------------------------------------------------------------------------

@functools.partial(jax.jit, static_argnames=("c_max", "max_iters"))
def _relax_cells(cs: jax.Array, cr: jax.Array, cw: jax.Array,
                 dist0: jax.Array, *, c_max: int,
                 max_iters: int) -> jax.Array:
    """Restricted Bellman-Ford inside many cells at once.

    ``cs``/``cr``/``cw``: (G, e_max) cell-local edge arrays, sorted by
    local receiver, padded with (0, c_max-1, INF) edges whose proposals
    can never win. ``dist0``: (G, R, c_max) initial distances (R source
    rows per cell). One while_loop converges the whole batch."""

    def seg_min(p, r):
        return jax.ops.segment_min(p, r, num_segments=c_max,
                                   indices_are_sorted=True)

    def cell_sweep(dist, s, r, w):          # (R, c_max) one cell
        proposals = dist[:, s] + w[None, :]
        return jnp.minimum(dist, jax.vmap(lambda p: seg_min(p, r))(proposals))

    sweep_all = jax.vmap(cell_sweep)

    def relax(state):
        dist, _, it = state
        new = dist
        for _ in range(_K_SWEEPS):
            new = sweep_all(new, cs, cr, cw)
        return new, jnp.any(new < dist), it + _K_SWEEPS

    def keep_going(state):
        _, changed, it = state
        return changed & (it < max_iters)

    dist, _, _ = jax.lax.while_loop(
        keep_going, relax,
        (dist0, jnp.asarray(True), jnp.zeros((), jnp.int32)))
    return dist


@functools.partial(jax.jit, static_argnames=())
def _prune_cliques(T: jax.Array) -> jax.Array:
    """(P, b, b) restricted boundary metric → keep mask for clique
    edges. An edge (i, j) is *implied* when some third boundary node k
    gives ``T[i,k] + T[k,j] ≤ T[i,j]`` (within rounding): the overlay
    metric closure is unchanged by dropping it, because T is itself the
    restricted metric (triangle inequality holds), both legs are
    strictly shorter than the whole (legs below 1 m are excluded so the
    induction bottoms out), and the implication chain therefore
    terminates at kept edges."""
    P, b, _ = T.shape
    inf = _INF

    def body(k, acc):
        a = T[:, :, k]
        a = a.at[:, k].set(inf)                       # exclude i == k
        a = jnp.where(a < 1.0, inf, a)                # zero-length guard
        c = T[:, k, :]
        c = c.at[:, k].set(inf)                       # exclude j == k
        c = jnp.where(c < 1.0, inf, c)
        return jnp.minimum(acc, a[:, :, None] + c[:, None, :])

    via = jax.lax.fori_loop(0, b, body, jnp.full_like(T, inf))
    # Ulp-tight: a positive absolute slack here would *inflate* the
    # overlay metric by that slack per pruning level (a pruned edge's
    # traffic reroutes over the bypass, which may itself be pruned). At
    # ~2 ulps relative, the inflation stays inside the f32 rounding the
    # module already owns; near-ties the slack would have pruned are
    # merely kept — a few % more clique edges, never a wrong distance.
    implied = via <= T * (1 + 2e-7)
    finite = T < 1e37
    eye = jnp.eye(b, dtype=bool)[None]
    return finite & ~eye & ~implied


class HierarchicalIndex:
    """Built once per graph; answers batched exact multi-source
    shortest-path distance queries in O(cells-across) device sweeps."""

    def __init__(self, *, cell: np.ndarray, n_cells: int,
                 local_of_node: np.ndarray, c_max: int, b_max: int,
                 d_ces: jax.Array, d_cer: jax.Array, d_cew: jax.Array,
                 d_bl: jax.Array, d_cbo: jax.Array, d_table: jax.Array,
                 d_perm_of_node: jax.Array, d_ovl_s: jax.Array,
                 d_ovl_r: jax.Array, d_ovl_w: jax.Array, n_overlay: int,
                 stats: Dict[str, float]) -> None:
        self.cell = cell
        self.n_cells = n_cells
        self.local_of_node = local_of_node
        self.n_nodes = len(cell)
        self.c_max = c_max
        self.b_max = b_max
        self._d_ces, self._d_cer, self._d_cew = d_ces, d_cer, d_cew
        self._d_bl, self._d_cbo, self._d_table = d_bl, d_cbo, d_table
        self._d_perm_of_node = d_perm_of_node
        self._d_ovl_s, self._d_ovl_r, self._d_ovl_w = d_ovl_s, d_ovl_r, d_ovl_w
        self.n_overlay = n_overlay
        self.stats = stats
        # ``query_fn`` is the raw traceable function: callers chain
        # further device work (the router's polish + predecessor
        # recovery) by inlining it inside ONE outer jit, so a warm
        # solve is a single dispatch+fetch — on the axon tunnel every
        # extra dispatch is a host round trip.
        self.query_fn = self._build_query()

    # -- construction -----------------------------------------------------

    @classmethod
    def build(cls, coords: np.ndarray, senders: np.ndarray,
              receivers: np.ndarray, w: np.ndarray, *,
              cell_target: Optional[int] = None,
              chunk_cells: int = 64,
              cache_path: Optional[str] = None,
              fingerprint: Optional[Dict] = None) -> Optional["HierarchicalIndex"]:
        """Returns None when the graph is too small to benefit (a
        single cell, or no cell-crossing edges). With ``cache_path``,
        the host-side payload is written there (npz) before device
        upload so later processes skip the whole precompute
        (:meth:`load` — metro-extract serving spawns N workers, and
        each would otherwise pay the batched in-cell relaxation);
        ``fingerprint`` (the router's graph fingerprint) is embedded so
        a loaded payload is bound to ITS graph by content, not by the
        predictable cache filename."""
        t0 = time.perf_counter()
        n = len(coords)
        if cell_target is None:
            # Balance the phases: cell work ~ c, overlay hops ~ sqrt(N/c).
            cell_target = max(192, int(2.2 * np.sqrt(n)))
        cell, P = partition_cells(np.asarray(coords, np.float32), cell_target)
        if P < 2:
            return None

        order = np.argsort(cell, kind="stable")
        sizes = np.bincount(cell, minlength=P)
        starts = np.zeros(P + 1, np.int64)
        np.cumsum(sizes, out=starts[1:])
        c_max = int(sizes.max())
        local_of_node = np.empty(n, np.int32)
        local_of_node[order] = (np.arange(n) - starts[cell[order]]).astype(np.int32)

        # Internal edges, grouped by cell and sorted by local receiver.
        s_cell, r_cell = cell[senders], cell[receivers]
        internal = s_cell == r_cell
        ie = np.flatnonzero(internal)
        ie_cell = s_cell[ie]
        ie_s = local_of_node[senders[ie]]
        ie_r = local_of_node[receivers[ie]]
        ie_w = np.asarray(w, np.float32)[ie]
        eorder = np.lexsort((ie_r, ie_cell))
        ie_cell, ie_s, ie_r, ie_w = (a[eorder] for a in (ie_cell, ie_s, ie_r, ie_w))
        ecounts = np.bincount(ie_cell, minlength=P)
        e_max = max(1, int(ecounts.max()))
        ces = np.zeros((P, e_max), np.int32)
        cer = np.full((P, e_max), c_max - 1, np.int32)
        cew = np.full((P, e_max), _INF_NP, np.float32)
        estarts = np.zeros(P + 1, np.int64)
        np.cumsum(ecounts, out=estarts[1:])
        flat_pos = np.arange(len(ie)) - estarts[ie_cell]
        ces[ie_cell, flat_pos] = ie_s
        cer[ie_cell, flat_pos] = ie_r
        cew[ie_cell, flat_pos] = ie_w

        # Boundary nodes: endpoints of cell-crossing edges.
        cross = np.flatnonzero(~internal)
        if len(cross) == 0:
            return None
        is_b = np.zeros(n, bool)
        is_b[senders[cross]] = True
        is_b[receivers[cross]] = True
        b_global = order[is_b[order]]            # cell-grouped boundary list
        b_cell = cell[b_global]
        bcounts = np.bincount(b_cell, minlength=P)
        b_max = int(bcounts.max())
        B = len(b_global)
        bstarts = np.zeros(P + 1, np.int64)
        np.cumsum(bcounts, out=bstarts[1:])
        b_pos = np.arange(B) - bstarts[b_cell]
        bl = np.zeros((P, b_max), np.int32)      # local idx, pad 0 (masked later)
        bl[b_cell, b_pos] = local_of_node[b_global]
        ovl_of_node = np.full(n, -1, np.int64)
        ovl_of_node[b_global] = np.arange(B)
        cbo = np.full((P, b_max), B, np.int32)   # overlay id, pad B (= INF slot)
        cbo[b_cell, b_pos] = np.arange(B)

        # Batched in-cell tables, chunked so the (chunk, b_max, e_max)
        # proposal tensor stays bounded whatever the graph size.
        table = np.empty((P, b_max, c_max), np.float32)
        max_iters = c_max + _K_SWEEPS
        for lo in range(0, P, chunk_cells):
            hi = min(lo + chunk_cells, P)
            pad = chunk_cells - (hi - lo)
            g_ces = np.concatenate([ces[lo:hi], np.zeros((pad, e_max), np.int32)])
            g_cer = np.concatenate([cer[lo:hi],
                                    np.full((pad, e_max), c_max - 1, np.int32)])
            g_cew = np.concatenate([cew[lo:hi],
                                    np.full((pad, e_max), _INF_NP, np.float32)])
            g_bl = np.concatenate([bl[lo:hi], np.zeros((pad, b_max), np.int32)])
            d0 = jnp.full((chunk_cells, b_max, c_max), _INF)
            d0 = d0.at[jnp.arange(chunk_cells)[:, None],
                       jnp.arange(b_max)[None, :], jnp.asarray(g_bl)].set(0.0)
            out = _relax_cells(jnp.asarray(g_ces), jnp.asarray(g_cer),
                               jnp.asarray(g_cew), d0,
                               c_max=c_max, max_iters=max_iters)
            table[lo:hi] = np.asarray(out)[: hi - lo]
        # Pad boundary rows carry garbage (seeded at local 0): mask.
        row = np.arange(b_max)[None, :]
        table[row >= bcounts[:, None]] = _INF_NP

        # Cliques: the boundary↔boundary submatrix of each table.
        T = table[np.arange(P)[:, None, None],
                  np.arange(b_max)[None, :, None], bl[:, None, :]]
        T = np.where((row[..., None] >= bcounts[:, None, None])
                     | (row[:, None, :] >= bcounts[:, None, None]),
                     _INF_NP, T)
        keep = np.asarray(_prune_cliques(jnp.asarray(T)))
        candidates = ((T < 1e37)
                      & ~np.eye(b_max, dtype=bool)[None])
        kp, ki, kj = np.nonzero(keep)
        clique_s = cbo[kp, ki].astype(np.int64)
        clique_r = cbo[kp, kj].astype(np.int64)
        clique_w = T[kp, ki, kj]

        # Overlay graph: pruned cliques + the original crossing edges.
        ovl_s = np.concatenate([clique_s, ovl_of_node[senders[cross]]])
        ovl_r = np.concatenate([clique_r, ovl_of_node[receivers[cross]]])
        ovl_w = np.concatenate([clique_w,
                                np.asarray(w, np.float32)[cross]]).astype(np.float32)
        oorder = np.argsort(ovl_r, kind="stable")
        ovl_s, ovl_r, ovl_w = ovl_s[oorder], ovl_r[oorder], ovl_w[oorder]

        perm_of_node = (cell.astype(np.int64) * c_max + local_of_node).astype(np.int32)
        stats = {
            "n_cells": P, "c_max": c_max, "b_max": b_max,
            "n_overlay_nodes": B, "n_overlay_edges": int(len(ovl_s)),
            "clique_edges_kept": int(len(clique_s)),
            "clique_edges_pruned": int(candidates.sum() - keep.sum()),
            "build_s": 0.0,
        }
        payload = {
            "cell": cell, "local_of_node": local_of_node,
            "ces": ces, "cer": cer, "cew": cew, "bl": bl, "cbo": cbo,
            "table": table, "perm_of_node": perm_of_node,
            "ovl_s": ovl_s.astype(np.int32),
            "ovl_r": ovl_r.astype(np.int32), "ovl_w": ovl_w,
        }
        stats["build_s"] = round(time.perf_counter() - t0, 3)
        if cache_path:
            import json

            tmp = f"{cache_path}.tmp{os.getpid()}.npz"
            try:
                np.savez_compressed(
                    tmp, _version=np.int64(_CACHE_VERSION),
                    _stats=np.frombuffer(json.dumps(stats).encode(),
                                         dtype=np.uint8),
                    _fp=np.frombuffer(
                        json.dumps(fingerprint or {},
                                   sort_keys=True).encode(), dtype=np.uint8),
                    **payload)
                os.replace(tmp, cache_path)
            except OSError:
                # cache is an optimization, never a dependency — but a
                # half-written tmp must not accumulate
                try:
                    os.unlink(tmp)
                except OSError:
                    pass
        return cls._from_payload(payload, stats)

    @classmethod
    def _from_payload(cls, p: Dict[str, np.ndarray],
                      stats: Dict) -> "HierarchicalIndex":
        P, b_max = p["cbo"].shape
        c_max = p["table"].shape[2]
        return cls(
            cell=np.asarray(p["cell"]), n_cells=P,
            local_of_node=np.asarray(p["local_of_node"]),
            c_max=c_max, b_max=b_max,
            d_ces=jnp.asarray(p["ces"]), d_cer=jnp.asarray(p["cer"]),
            d_cew=jnp.asarray(p["cew"]), d_bl=jnp.asarray(p["bl"]),
            d_cbo=jnp.asarray(p["cbo"]), d_table=jnp.asarray(p["table"]),
            d_perm_of_node=jnp.asarray(p["perm_of_node"]),
            d_ovl_s=jnp.asarray(p["ovl_s"]), d_ovl_r=jnp.asarray(p["ovl_r"]),
            d_ovl_w=jnp.asarray(p["ovl_w"]),
            n_overlay=int(stats["n_overlay_nodes"]), stats=stats)

    @classmethod
    def load(cls, cache_path: str,
             fingerprint: Optional[Dict] = None) -> Optional["HierarchicalIndex"]:
        """Rehydrate a cached overlay; None on any mismatch/corruption
        (callers rebuild). The embedded fingerprint must match the
        caller's graph — the filename alone is predictable, so a
        payload at the right name but for the wrong (or tampered)
        graph is rejected by content, and the worst a poisoned entry
        can do is force a rebuild."""
        try:
            import json

            with np.load(cache_path, allow_pickle=False) as z:
                if int(z["_version"]) != _CACHE_VERSION:
                    return None
                if fingerprint is not None:
                    cached_fp = json.loads(bytes(z["_fp"]).decode())
                    if cached_fp != json.loads(
                            json.dumps(fingerprint, sort_keys=True)):
                        return None
                stats = json.loads(bytes(z["_stats"]).decode())
                payload = {k: z[k] for k in
                           ("cell", "local_of_node", "ces", "cer", "cew",
                            "bl", "cbo", "table", "perm_of_node",
                            "ovl_s", "ovl_r", "ovl_w")}
            stats["loaded_from_cache"] = True
            return cls._from_payload(payload, stats)
        except Exception:
            return None

    # -- query ------------------------------------------------------------

    def _build_query(self):
        ces, cer, cew = self._d_ces, self._d_cer, self._d_cew
        bl, cbo, table = self._d_bl, self._d_cbo, self._d_table
        perm_of_node = self._d_perm_of_node
        ovl_s, ovl_r, ovl_w = self._d_ovl_s, self._d_ovl_r, self._d_ovl_w
        P, c_max, b_max, B = self.n_cells, self.c_max, self.b_max, self.n_overlay
        cell_iters = c_max + _K_SWEEPS
        ovl_iters = B + _K_SWEEPS

        def query(p_s: jax.Array, src_local: jax.Array) -> jax.Array:
            S = p_s.shape[0]
            rows = jnp.arange(S)
            # Phase 1: restricted BF inside each source's cell.
            d0 = jnp.full((S, 1, c_max), _INF)
            d0 = d0.at[rows, 0, src_local].set(0.0)
            local = _relax_cells(ces[p_s], cer[p_s], cew[p_s], d0,
                                 c_max=c_max, max_iters=cell_iters)[:, 0]
            # Phase 2: overlay BF seeded with the cell-exit distances.
            seed = jnp.take_along_axis(local, bl[p_s], axis=1)   # (S, b_max)
            ovl0 = jnp.full((S, B + 1), _INF)
            ovl0 = ovl0.at[rows[:, None], cbo[p_s]].min(seed)
            ovl, _ = relax_from(ovl_s, ovl_r, ovl_w, ovl0[:, :B],
                                n_nodes=B, max_iters=ovl_iters)
            ovl_pad = jnp.concatenate([ovl, jnp.full((S, 1), _INF)], axis=1)
            # Phase 3: stitch through the tables, accumulating over the
            # boundary axis so no (S, P, b, c) tensor ever materializes.

            def body(b, acc):
                o_b = ovl_pad[:, cbo[:, b]]                       # (S, P)
                return jnp.minimum(acc, o_b[:, :, None] + table[None, :, b, :])

            acc = jax.lax.fori_loop(
                0, b_max, body, jnp.full((S, P, c_max), _INF))
            flat = acc.reshape(S, P * c_max)
            # Fold in phase 1 (the only candidate for paths that never
            # leave the source cell); layout is already cell-major, so
            # the final answer is one gather, not a scatter.
            pos = (p_s * c_max)[:, None] + jnp.arange(c_max)[None, :]
            flat = flat.at[rows[:, None], pos].min(local)
            # Unreachable sums overflow f32 (3e38 + 3e38 = inf); clamp
            # back to the finite sentinel so downstream slack arithmetic
            # (tight_pred) never sees inf - inf = nan.
            return jnp.minimum(flat[:, perm_of_node], _INF)

        return query

    def prep_sources(self, sources: np.ndarray) -> Tuple[jax.Array, jax.Array]:
        """(S,) global source nodes → the ``query_fn`` argument pair
        (source cell ids, source cell-local ids). The ONE place the
        source encoding lives — every query goes through it."""
        sources = np.asarray(sources, np.int64)
        return (jnp.asarray(self.cell[sources]),
                jnp.asarray(self.local_of_node[sources]))


def hier_cache_path(fingerprint: Dict) -> Optional[str]:
    """Where this graph's overlay payload caches, or None when caching
    is off (``ROUTEST_HIER_CACHE=0``; a path value overrides the
    per-user secure default). Keyed by the same graph fingerprint that
    gates learned leg models, so a changed extract can never be served
    a stale overlay — and the payload format is npz with pickling
    disabled, so a poisoned cache can at worst fail to load (callers
    rebuild)."""
    knob = os.environ.get("ROUTEST_HIER_CACHE", "")
    if knob.lower() in ("0", "off", "false", "no"):
        return None
    if knob:
        base = knob
        try:
            os.makedirs(base, exist_ok=True)
        except OSError:
            return None
    else:
        from routest_tpu.utils.paths import secure_user_cache_dir

        base = secure_user_cache_dir("routest-hier")
        if base is None:
            return None
    key = "-".join(str(fingerprint[k]) for k in sorted(fingerprint))
    return os.path.join(base, f"hier-v{_CACHE_VERSION}-{key}.npz")


def hier_min_nodes() -> int:
    """Graphs at or above this node count route through the overlay
    (``ROUTEST_HIER_MIN_NODES`` overrides; 0 disables entirely). Below
    it the flat sweep's ~O(sqrt(N)) iterations are already cheap and
    skipping the precompute keeps serving-default init instant."""
    try:
        return int(os.environ.get("ROUTEST_HIER_MIN_NODES", "4096"))
    except ValueError:
        return 4096
