"""Multi-level partition overlay for metro-scale shortest paths.

The flat batched Bellman-Ford in ``optimize/road_router.py`` is
*diameter-bound*: every sweep advances the frontier one hop, so a
street network's O(sqrt(N)) hop diameter costs ~900 dependent device
sweeps at 50k nodes and grows without bound (VERDICT r3 weak #2 — the
rented engine this framework replaces, ORS, answers matrix calls on
country-scale graphs in tens of ms).

This module removes the diameter from the critical path with a
*recursive* overlay decomposition (the "customizable route planning"
family applied level over level), re-designed for the TPU's strength —
big dense batched relaxations instead of priority queues:

1. **Partition**: ONE recursive coordinate-bisection tree, cut at
   several size thresholds, gives a NESTED multi-level partition:
   every level-(k+1) cell is a union of level-k cells. Nesting is what
   makes the recursive query exact — the boundary nodes of a level-k
   cell always live inside one level-(k+1) cell.
2. **Precompute** (device, batched over every cell of a level at
   once): a restricted Bellman-Ford inside each cell from each of its
   boundary nodes (nodes incident to a cell-crossing edge) gives
   ``table[cell, b, v]`` — exact in-cell distance boundary→node — and
   a boundary→boundary *clique* per cell, pruned of edges implied by
   two-hop boundary paths. The cliques plus the original cell-crossing
   edges form the level's *overlay graph*, which is the next level's
   input graph; levels stack until the top overlay is small.
3. **Query** (device): for S sources at once,
   - *ascend*: a tiny restricted BF inside the source's level-1 cell,
     then per level a restricted BF inside the source's level-k cell
     over the level-(k-1) overlay graph, seeded with the previous
     level's boundary distances;
   - *top*: Bellman-Ford over the topmost overlay graph — its hop
     count is the number of top-level cells across the metro, not
     nodes, not even level-1 cells;
   - *descend*: per level, a min-plus stitch
     ``min_b(ovl[s,b] + table[cell,b,v])`` folds boundary distances
     through the precomputed tables down one graph, as a fori
     accumulation over the boundary axis (never materializing the
     (S, P, b, c) proposal tensor). Cells are ordered by DESCENDING
     boundary count at build time so the fold runs in tiers, paying
     each tier's actual boundary count instead of the global ``b_max``.

Exactness (per level, hence by induction for the stack): any shortest
path decomposes at cell crossings into maximal within-cell segments
between boundary nodes; each segment's restricted length equals a
clique weight, so the overlay metric is the true metric on boundary
nodes, and the stitched suffix is the true in-cell tail. Same-cell
journeys that never leave the cell are covered by the ascend locals
(folded back in during descent); journeys that leave and re-enter are
covered by the stitch. The query therefore returns *exact* distances
(up to f32 rounding from re-associated sums), and
``road_router.shortest`` re-uses its existing tight-edge predecessor
recovery unchanged — after a couple of polish sweeps of the flat
relaxation that re-anchor ties to bit-identical ``dist[s] + w``
assignments.

Directed graphs (OSM one-ways) are handled: tables, cliques and the
stitches are all forward-direction restricted distances.
"""

from __future__ import annotations

import functools
import hashlib
import json
import os
import time
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

_INF = jnp.float32(3e38)
_INF_NP = np.float32(3e38)
# Number of flat relaxation sweeps fused per while_loop iteration: the
# convergence check costs a device sync, which dominates small graphs
# (measured in road_router._bellman_ford — same constant, same reason).
_K_SWEEPS = 4

# v4: v3 (customization structure) + hub labels (the precomputed
# all-pairs top-overlay distance table), the chain FILL structure
# (direction-start offsets + last-hop edges that let the solve
# synthesize full-graph distances/predecessors from a contracted
# solve), and the contracted level-0 edge arrays the polish/predecessor
# sweeps now run over.
_CACHE_VERSION = 4


def _log():
    from routest_tpu.utils.logging import get_logger

    return get_logger("routest.hier")


# ---------------------------------------------------------------------------
# Shared flat-relaxation primitives (road_router builds on these too).
# ---------------------------------------------------------------------------

@functools.partial(jax.jit, static_argnames=("n_nodes", "max_iters"))
def relax_from(senders: jax.Array, receivers: jax.Array, w: jax.Array,
               dist0: jax.Array, *, n_nodes: int,
               max_iters: int) -> Tuple[jax.Array, jax.Array]:
    """Bellman-Ford relaxation sweeps from an arbitrary initial
    distance table. ``dist0`` is (S, n_nodes); edges must be sorted by
    receiver (``segment_min(indices_are_sorted=True)``). Returns the
    relaxed table and a scalar bool: True iff a sweep changed nothing
    (converged) rather than the iteration bound being exhausted."""

    def seg_min(p):
        return jax.ops.segment_min(p, receivers, num_segments=n_nodes,
                                   indices_are_sorted=True)

    def one_sweep(dist):
        proposals = dist[:, senders] + w[None, :]
        return jnp.minimum(dist, jax.vmap(seg_min)(proposals))

    def relax(state):
        dist, _, it = state
        new = dist
        for _ in range(_K_SWEEPS):
            new = one_sweep(new)
        return new, jnp.any(new < dist), it + _K_SWEEPS

    def keep_going(state):
        _, changed, it = state
        return changed & (it < max_iters)

    dist, still_changing, _ = jax.lax.while_loop(
        keep_going, relax,
        (dist0, jnp.asarray(True), jnp.zeros((), jnp.int32)))
    return dist, jnp.logical_not(still_changing)


@functools.partial(jax.jit, static_argnames=("n_nodes", "n_sweeps"))
def polish(senders: jax.Array, receivers: jax.Array, w: jax.Array,
           dist: jax.Array, *, n_nodes: int, n_sweeps: int) -> jax.Array:
    """``n_sweeps`` UNROLLED relaxation sweeps with no convergence
    check. Overlay distances are already exact ± a few ulps of f32
    re-association; what predecessor recovery needs is that every
    node's value was *assigned* from a ``dist[s] + w`` proposal so the
    minimal-slack edge is ~0 bitwise — one sweep re-anchors that, a
    second covers senders that moved in the first. The while_loop in
    :func:`relax_from` would pay a device-synced ``any()`` per round
    for a loop that, by construction, never exits early here."""

    def seg_min(p):
        return jax.ops.segment_min(p, receivers, num_segments=n_nodes,
                                   indices_are_sorted=True)

    for _ in range(n_sweeps):
        proposals = dist[:, senders] + w[None, :]
        dist = jnp.minimum(dist, jax.vmap(seg_min)(proposals))
    return dist


def tight_edges(senders: jax.Array, receivers: jax.Array, w: jax.Array,
                dist: jax.Array, *, n_nodes: int) -> jax.Array:
    """Predecessor recovery from a converged distance table: the edge
    entering each node with *minimal slack* (``dist[s] + w - dist[r]``)
    lies on a shortest path; segment-max of the edge id among
    minimal-slack edges picks one deterministically. Traceable core
    with NO source zeroing — the contracted full solve picks its own
    roots (an interior source has no contracted node to zero).

    Min-slack (not "any edge within a tolerance") matters on real
    street data: short edges exist (sub-meter OSM segments), so a fixed
    tolerance wide enough for the hierarchy's re-associated f32 sums
    could mark a short edge tight in BOTH directions and hand ``_walk``
    a predecessor 2-cycle. The minimal-slack edge is near-exact by
    construction — a relaxation sweep *assigned* ``dist[r]`` from its
    argmin proposal, so its slack is ~0 bitwise and a reverse edge
    (slack ≥ w + w') can never tie with it past the 1 cm merge slack
    below."""
    slack = dist[:, senders] + w[None, :] - dist[:, receivers]

    def seg_min(s):
        return jax.ops.segment_min(s, receivers, num_segments=n_nodes,
                                   indices_are_sorted=True)

    min_slack = jax.vmap(seg_min)(slack)           # (S, N)
    tight = slack <= min_slack[:, receivers] + 1e-2
    # Among tight edges, prefer the one whose SENDER is strictly
    # closest (then max edge id deterministically): zero-weight edges
    # make equal-distance neighbor pairs where both directions are
    # tight, and two nodes independently picking each other is a
    # predecessor 2-cycle (observed on a 1M street extract through a
    # zero-length contracted chain). The minimal-sender-distance edge
    # always exists for a finitely-reached node and points strictly
    # "upstream" whenever any positive-weight tight in-edge does.
    sd = jnp.where(tight, dist[:, senders], _INF)
    best_sd = jax.vmap(seg_min)(sd)                # (S, N)
    pick = tight & (sd <= best_sd[:, receivers])
    e_ids = jnp.arange(senders.shape[0], dtype=jnp.int32)

    def seg_max(t):
        return jax.ops.segment_max(jnp.where(t, e_ids, -1), receivers,
                                   num_segments=n_nodes,
                                   indices_are_sorted=True)

    return jnp.maximum(jax.vmap(seg_max)(pick), -1)


@functools.partial(jax.jit, static_argnames=("n_nodes",))
def tight_pred(senders: jax.Array, receivers: jax.Array, w: jax.Array,
               dist: jax.Array, sources: jax.Array, *,
               n_nodes: int) -> jax.Array:
    """:func:`tight_edges` with each row's source zeroed to -1 (the
    flat-solver entry point)."""
    pred = tight_edges(senders, receivers, w, dist, n_nodes=n_nodes)
    n_src = dist.shape[0]
    return pred.at[jnp.arange(n_src), sources].set(-1)


def _build_labels(top_s: np.ndarray, top_r: np.ndarray, top_w: np.ndarray,
                  n_top: int) -> Tuple[np.ndarray, Dict]:
    """Hub labels: the exact all-pairs distance table over the top
    overlay graph, built as a device-batched identity-seeded BF —
    exactly the machinery the per-query top BF runs, with the source
    axis widened from a request bucket to every top boundary node.
    Rows chunk to bound the (rows, E) proposal tensor; the chunk shape
    is fixed so every chunk reuses one compiled program. Returns the
    (n_top, n_top) f32 table + build stats.

    Because the overlay metric is the true metric on boundary nodes
    (the level-stack induction), this table is EXACT — the query-time
    fold ``min_b(seed[s, b] + labels[b, v])`` over a source's top-cell
    boundary seeds reproduces the top BF's fixed point by definition,
    so the label path needs no approximation fallback: parity with the
    iterative top BF holds by construction, and routers that skip the
    build (top too big, knob off) simply keep the BF stage."""
    t0 = time.perf_counter()
    e_top = max(1, len(top_s))
    chunk = int(np.clip((256 << 20) // (4 * e_top), 64, n_top))
    d_s = jnp.asarray(top_s)
    d_r = jnp.asarray(top_r)
    d_w = jnp.asarray(top_w)
    labels = np.empty((n_top, n_top), np.float32)
    for lo in range(0, n_top, chunk):
        hi = min(lo + chunk, n_top)
        d0 = np.full((chunk, n_top), _INF_NP, np.float32)
        d0[np.arange(hi - lo), lo + np.arange(hi - lo)] = 0.0
        d0[hi - lo:, 0] = 0.0          # pad rows: harmless re-solves
        out, _ = relax_from(d_s, d_r, d_w, jnp.asarray(d0),
                            n_nodes=n_top, max_iters=n_top + _K_SWEEPS)
        labels[lo:hi] = np.asarray(out)[: hi - lo]
    stats = {
        "nodes": int(n_top),
        "bytes": int(labels.nbytes),
        "build_s": round(time.perf_counter() - t0, 3),
    }
    return labels, stats


# ---------------------------------------------------------------------------
# Partition
# ---------------------------------------------------------------------------

def partition_cells_nested(
        coords: np.ndarray,
        targets: Sequence[int]) -> List[Tuple[np.ndarray, int]]:
    """(N, 2) coords + finest-first cell-size targets → one (N,) cell
    assignment per level, finest first, **nested**: every level-(k+1)
    cell is a union of level-k cells, because all levels are cuts of
    the SAME recursive-median-bisection tree at different size
    thresholds. Cells are size-balanced (≤ target) and geometrically
    compact, which keeps boundary sets small — the quantity every
    overlay cost scales with."""
    n = len(coords)
    L = len(targets)
    cells = [np.zeros(n, np.int32) for _ in range(L)]
    counts = [0] * L
    stack: List[Tuple[np.ndarray, int]] = [(np.arange(n), L - 1)]
    while stack:
        idx, lvl = stack.pop()
        if len(idx) <= targets[lvl]:
            cells[lvl][idx] = counts[lvl]
            counts[lvl] += 1
            if lvl > 0:
                stack.append((idx, lvl - 1))
            continue
        c = coords[idx]
        axis = int(np.argmax(c.max(axis=0) - c.min(axis=0)))
        order = np.argsort(c[:, axis], kind="stable")
        half = len(idx) // 2
        stack.append((idx[order[:half]], lvl))
        stack.append((idx[order[half:]], lvl))
    return [(cells[k], counts[k]) for k in range(L)]


def partition_cells(coords: np.ndarray,
                    cell_target: int) -> Tuple[np.ndarray, int]:
    """Single-level cut of the bisection tree (the multi-level
    machinery with one threshold)."""
    (cell, n_cells), = partition_cells_nested(
        np.asarray(coords, np.float32), [cell_target])
    return cell, n_cells


def _level_targets(n: int, cell_target: Optional[int] = None,
                   max_levels: Optional[int] = None) -> List[int]:
    """Finest-first cell-size ladder. Each coarser level groups ~ratio
    finer cells; levels stack while the next one would still have ≥ 4
    cells — past that the top overlay BF is already tiny."""
    if cell_target is None:
        try:
            cell_target = int(
                os.environ.get("ROUTEST_HIER_CELL_TARGET", "0") or 0)
        except ValueError:
            cell_target = 0
    # Hub labels change the balance at the top: the top phase is a
    # precomputed table fold instead of an iterative BF, so the ladder
    # no longer needs to stop while the top is still large enough to
    # matter — it should instead use SMALLER level-1 cells (every
    # query phase is cheaper in small cells; the top grows, but the
    # fold doesn't care) and stack GENTLER (ratio-4) levels until the
    # top fits the label budget. Measured at 250k: 1.45√n cells cut
    # the non-top query phases 225→154 ms vs the 2.2√n BF balance.
    labels_on = _labels_max() > 0
    if not cell_target:
        # Balance the phases: cell work ~ c, overlay hops ~ sqrt(N/c).
        cell_target = max(160, int((1.45 if labels_on else 2.2)
                                   * np.sqrt(n)))
    try:
        ratio = int(os.environ.get("ROUTEST_HIER_RATIO", "0") or 0)
    except ValueError:
        ratio = 0
    if not ratio:
        ratio = 4 if labels_on else 16
    ratio = max(2, ratio)
    if max_levels is None:
        try:
            max_levels = int(
                os.environ.get("ROUTEST_HIER_MAX_LEVELS", "0") or 0)
        except ValueError:
            max_levels = 0
    max_levels = max_levels or 8
    # With labels the ladder runs all the way down to a 2-cell cut —
    # every extra level shrinks the top boundary, and the label build
    # cost is quadratic-ish in it; without labels a <4-cell level's
    # stitch cost outweighs the top-BF hops it saves.
    min_cells = 1 if labels_on else 4
    targets = [int(cell_target)]
    while (len(targets) < max_levels
           and n // (targets[-1] * ratio) >= min_cells):
        targets.append(targets[-1] * ratio)
    return targets


# Stop stacking levels once the top boundary fits this budget: by
# here the label fold is already cheap, and the next level's cells
# would be few and DENSE (clique-dominated), making its ascend cost
# more than the label-build seconds it saves (measured at 250k: the
# final 2-cell level cost 211 ms of ascend to save 44 s of one-time
# label build).
_LABEL_STOP = 2560


def _labels_max() -> int:
    """Hub labels build when the top overlay has at most this many
    boundary nodes (``ROUTEST_HIER_LABELS``; 0/off disables). The label
    table is (top, top) f32 — 4096 nodes = 64 MB resident and an
    all-pairs device BF at build time — so the cap bounds both."""
    raw = os.environ.get("ROUTEST_HIER_LABELS", "4096").strip().lower()
    if raw in ("", "0", "off", "false", "no"):
        return 0
    try:
        return max(0, int(raw))
    except ValueError:
        return 4096


def _prune_slack() -> float:
    try:
        return float(os.environ.get("ROUTEST_HIER_PRUNE_SLACK", "2e-7"))
    except ValueError:
        return 2e-7


def _contract_interior() -> int:
    """Max interior nodes per contracted chain segment
    (``ROUTEST_HIER_CONTRACT``; 0 disables contraction). The router's
    polish pass must run at least this many sweeps — that is what fills
    chain-interior distances back in — so the two knobs are coupled in
    ``road_router``."""
    try:
        return max(0, int(os.environ.get("ROUTEST_HIER_CONTRACT", "2")))
    except ValueError:
        return 2


# ---------------------------------------------------------------------------
# Degree-2 chain contraction
# ---------------------------------------------------------------------------

def _contract_chains(coords: np.ndarray, senders: np.ndarray,
                     receivers: np.ndarray, w: np.ndarray,
                     max_interior: int) -> Optional[Dict[str, np.ndarray]]:
    """Collapse degree-2 chains (OSM bend nodes — ~80% of a real street
    extract) into single weighted edges before the overlay is built.

    Every overlay cost scales with the boundary-node count, and bend
    nodes on cell-border streets are boundary nodes that carry zero
    routing information: contracting them shrinks the overlay's node,
    clique and edge counts by the bend ratio (~2.5–6×) while keeping
    the metric EXACT — a chain is a forced path, so its length is a
    constant.

    A node is chain-interior iff it has exactly two distinct neighbors
    and is a pure pass-through (two-way to both, or one-in/one-out
    across them); mixed two-way/one-way junctions, parallel-edge and
    self-loop endpoints stay. Chains longer than ``max_interior`` are
    split (every ``max_interior``-th interior node is promoted) so the
    router's polish sweeps — which re-derive interior distances from
    the contracted solution — need only ``max_interior`` sweeps.
    All-interior cycles (roundabouts) promote their smallest node.

    Returns None when nothing contracts, else:
      ``cid_of``      (N,) contracted id per original node, -1 interior
      ``kept``        (N',) original id per contracted node
      ``c_senders``/``c_receivers``/``c_w`` contracted edge list
      ``seed_node``   (N, 2) contracted ids reachable FROM each
                      original node along its chain (pad -1)
      ``seed_w``      (N, 2) the along-chain cost to each (pad INF)
      ``edge_comp_ptr``/``edge_comp`` ragged ORIGINAL-edge composition
                      per contracted edge — a contracted weight is the
                      sum of its composition under ANY metric, which is
                      what lets :meth:`HierarchicalIndex.customize`
                      re-price the contraction without re-walking it
      ``seed_comp_ptr``/``seed_comp`` same, per (node, slot) seed
    """
    n = len(coords)
    senders = np.asarray(senders, np.int64)
    receivers = np.asarray(receivers, np.int64)
    w = np.asarray(w, np.float32)
    loop = senders == receivers
    out_deg = np.bincount(senders, minlength=n)
    in_deg = np.bincount(receivers, minlength=n)
    # Distinct undirected neighbors + parallel-edge detection.
    a = np.minimum(senders, receivers)
    b = np.maximum(senders, receivers)
    und = np.unique(a * n + b)
    ua, ub = und // n, und % n
    und_deg = np.bincount(ua, minlength=n) + np.bincount(ub, minlength=n)
    ordered, counts = np.unique(senders * n + receivers, return_counts=True)
    dup = ordered[counts > 1]
    blocked = np.zeros(n, bool)
    blocked[senders[loop]] = True
    blocked[(dup // n)] = True
    blocked[(dup % n)] = True
    interior = (~blocked & (und_deg == 2)
                & (((out_deg == 2) & (in_deg == 2))
                   | ((out_deg == 1) & (in_deg == 1))))
    if not interior.any():
        return None

    # Adjacency restricted to edges touching interiors (python walk —
    # chains are short and each interior is visited once). ``eid``
    # remembers WHICH original edge carries each (s, r) hop so chain
    # weights stay re-derivable under a different metric (interior
    # endpoints are never parallel-edge endpoints — those are blocked —
    # so the hop→edge mapping is unique).
    touch = interior[senders] | interior[receivers]
    ew: Dict[Tuple[int, int], float] = {}
    eid: Dict[Tuple[int, int], int] = {}
    for e, s, r, wt in zip(np.flatnonzero(touch), senders[touch],
                           receivers[touch], w[touch]):
        key = (int(s), int(r))
        if key not in ew or wt < ew[key]:
            ew[key] = float(wt)
            eid[key] = int(e)

    # Undirected neighbor map for interiors (both directions known from
    # the degree pattern: 2-2 has adj both ways; 1-1 only forward, so
    # fold the reverse in from the incoming side).
    nbrs: Dict[int, List[int]] = {}
    for s, r in zip(senders[touch], receivers[touch]):
        s, r = int(s), int(r)
        if interior[s]:
            nbrs.setdefault(s, [])
            if r not in nbrs[s]:
                nbrs[s].append(r)
        if interior[r]:
            nbrs.setdefault(r, [])
            if s not in nbrs[r]:
                nbrs[r].append(s)

    promoted = np.zeros(n, bool)
    visited = np.zeros(n, bool)
    chains: List[List[int]] = []
    for v0 in np.flatnonzero(interior):
        v0 = int(v0)
        if visited[v0]:
            continue
        # Expand to both ends.
        chain = [v0]
        visited[v0] = True
        for direction in (0, 1):
            prev, cur = v0, nbrs[v0][direction] if len(
                nbrs[v0]) > direction else None
            if cur is None:
                continue
            while interior[cur] and not visited[cur]:
                visited[cur] = True
                if direction == 0:
                    chain.append(cur)
                else:
                    chain.insert(0, cur)
                nxt = [x for x in nbrs[cur] if x != prev]
                if not nxt:
                    cur = None
                    break
                prev, cur = cur, nxt[0]
            if cur is not None and not interior[cur]:
                if direction == 0:
                    chain.append(cur)
                else:
                    chain.insert(0, cur)
            elif cur is not None and visited[cur] and cur == (
                    chain[0] if direction == 0 else chain[-1]):
                # closed all-interior cycle: break it at the smallest id
                break
        # Ensure endpoints are non-interior; cycles promote min node.
        if interior[chain[0]] and interior[chain[-1]]:
            keep_node = min(chain)
            promoted[keep_node] = True
            i = chain.index(keep_node)
            chain = chain[i:] + chain[:i + 1]
        # Split long runs: promote every max_interior-th interior.
        run = 0
        for node in chain[1:-1]:
            run += 1
            if run > max_interior:
                promoted[node] = True
                run = 0
        chains.append(chain)

    interior &= ~promoted
    cid_of = np.full(n, -1, np.int64)
    kept = np.flatnonzero(~interior)
    cid_of[kept] = np.arange(len(kept))

    # Contracted edges: originals not touching interiors + one summed
    # edge per traversable chain-segment direction.
    keep_edge = ~(interior[senders] | interior[receivers])
    kept_edge_ids = np.flatnonzero(keep_edge)
    c_s = [cid_of[senders[keep_edge]]]
    c_r = [cid_of[receivers[keep_edge]]]
    c_w = [w[keep_edge]]
    chain_edge_comp: List[List[int]] = []      # per chain-emitted edge
    seed_comp: Dict[int, List[int]] = {}       # (node*2 + slot) → edges
    fill_comp: Dict[int, List[int]] = {}       # (node*2 + slot) → edges
    seed_node = np.full((n, 2), -1, np.int64)
    seed_w = np.full((n, 2), np.inf, np.float64)
    seed_last = np.full((n, 2), -1, np.int64)
    seed_node[kept, 0] = cid_of[kept]
    seed_w[kept, 0] = 0.0
    # Fill structure (the inverse of seeds): which contracted node
    # REACHES each interior along its chain, at what along-chain cost,
    # entering through which original edge. The solve uses it to
    # synthesize exact full-graph distances and predecessors from a
    # contracted solve — interiors are never relaxed on device.
    fill_node = np.full((n, 2), -1, np.int64)
    fill_w = np.full((n, 2), np.inf, np.float64)
    fill_last = np.full((n, 2), -1, np.int64)
    fill_dir = np.full((n, 2), -1, np.int64)   # emitted-direction id
    n_dirs = 0

    def emit(seg: List[int]) -> None:
        """One kept→kept segment: summed edges per direction + seed and
        fill entries for its interiors."""
        nonlocal n_dirs
        for s_dir in (0, 1):
            nodes = seg if s_dir == 0 else seg[::-1]
            total = 0.0
            ok = True
            partial = [0.0]
            hop_ids: List[int] = []
            for x, y in zip(nodes[:-1], nodes[1:]):
                wt = ew.get((x, y))
                if wt is None:
                    ok = False
                    break
                total += wt
                partial.append(total)
                hop_ids.append(eid[(x, y)])
            if not ok:
                continue
            c_s.append(np.asarray([cid_of[nodes[0]]]))
            c_r.append(np.asarray([cid_of[nodes[-1]]]))
            c_w.append(np.asarray([total], np.float32))
            chain_edge_comp.append(hop_ids)
            dir_id = n_dirs
            n_dirs += 1
            # Seeds: every interior can reach the segment's END in this
            # direction at cost (total - partial). Fill: the segment's
            # START reaches every interior at cost partial, entering
            # through hop i-1.
            for i, node in enumerate(nodes[1:-1], start=1):
                slot = 0 if seed_node[node, 0] < 0 else 1
                seed_node[node, slot] = cid_of[nodes[-1]]
                seed_w[node, slot] = total - partial[i]
                seed_last[node, slot] = hop_ids[-1]
                seed_comp[node * 2 + slot] = hop_ids[i:]
                fill_node[node, slot] = cid_of[nodes[0]]
                fill_w[node, slot] = partial[i]
                fill_last[node, slot] = hop_ids[i - 1]
                fill_dir[node, slot] = dir_id
                fill_comp[node * 2 + slot] = hop_ids[:i]

    for chain in chains:
        seg: List[int] = [chain[0]]
        for node in chain[1:]:
            seg.append(node)
            if not interior[node]:
                if len(seg) > 1:
                    emit(seg)
                seg = [node]
        if len(seg) > 1:
            emit(seg)

    c_senders = np.concatenate(c_s)
    c_receivers = np.concatenate(c_r)
    c_weights = np.concatenate(c_w).astype(np.float32)
    # Ragged composition arrays: kept originals are singleton
    # compositions (vectorized block), chain edges append their hop
    # lists in emit order — aligned with c_senders.
    chain_lens = np.asarray([len(ids) for ids in chain_edge_comp],
                            np.int64)
    k0 = len(kept_edge_ids)
    edge_comp_ptr = np.concatenate([
        np.arange(k0 + 1, dtype=np.int64),
        k0 + np.cumsum(chain_lens)])
    edge_comp = np.concatenate(
        [kept_edge_ids]
        + [np.asarray(ids, np.int64) for ids in chain_edge_comp]
        if chain_edge_comp else [kept_edge_ids]).astype(np.int64)
    def _ragged(comp: Dict[int, List[int]]):
        lens = np.zeros(2 * n, np.int64)
        for slot_key, ids in comp.items():
            lens[slot_key] = len(ids)
        ptr = np.zeros(2 * n + 1, np.int64)
        np.cumsum(lens, out=ptr[1:])
        flat = np.zeros(int(ptr[-1]), np.int64)
        for slot_key, ids in comp.items():
            lo = ptr[slot_key]
            flat[lo:lo + len(ids)] = ids
        return ptr, flat

    seed_comp_ptr, seed_comp_flat = _ragged(seed_comp)
    fill_comp_ptr, fill_comp_flat = _ragged(fill_comp)
    return {
        "cid_of": cid_of, "kept": kept,
        "c_senders": c_senders, "c_receivers": c_receivers,
        "c_w": c_weights,
        "seed_node": seed_node.astype(np.int64),
        "seed_w": np.where(np.isfinite(seed_w), seed_w,
                           _INF_NP).astype(np.float32),
        "seed_last": seed_last,
        "fill_node": fill_node, "fill_last": fill_last,
        "fill_dir": fill_dir,
        "fill_w": np.where(np.isfinite(fill_w), fill_w,
                           _INF_NP).astype(np.float32),
        "edge_comp_ptr": edge_comp_ptr,
        "edge_comp": edge_comp,
        "seed_comp_ptr": seed_comp_ptr,
        "seed_comp": seed_comp_flat,
        "fill_comp_ptr": fill_comp_ptr,
        "fill_comp": fill_comp_flat,
    }


def _pack_ell_flat(senders: np.ndarray, receivers: np.ndarray,
                   w: np.ndarray, tags: np.ndarray, n_nodes: int):
    """Receiver-sorted flat edge list → width-8 ELL minirows
    ``(m, W) senders/weights/tags + (m,) receivers`` (the
    :func:`_ell_pack` layout for ONE graph instead of per-cell).
    ``tags`` rides along per lane (pad -1) — the fused solve stores
    the ORIGINAL entering edge there so predecessor recovery needs no
    later remap. Pad lanes carry (0, INF, -1); pad minirows receive
    into ``n_nodes - 1`` (sorted order kept, INF never wins)."""
    E = len(senders)
    if E == 0:
        return (np.zeros((1, _ELL_W), np.int32),
                np.full((1, _ELL_W), _INF_NP, np.float32),
                np.full((1, _ELL_W), -1, np.int32),
                np.full((1,), max(n_nodes - 1, 0), np.int32))
    new_run = np.empty(E, bool)
    new_run[0] = True
    new_run[1:] = receivers[1:] != receivers[:-1]
    run_start = np.maximum.accumulate(np.where(new_run, np.arange(E), 0))
    rank = np.arange(E) - run_start
    new_mini = new_run | (rank % _ELL_W == 0)
    mini_id = np.cumsum(new_mini) - 1
    lane = rank % _ELL_W
    m = int(mini_id[-1]) + 1
    ell_s = np.zeros((m, _ELL_W), np.int32)
    ell_w = np.full((m, _ELL_W), _INF_NP, np.float32)
    ell_t = np.full((m, _ELL_W), -1, np.int32)
    ell_r = np.full((m,), max(n_nodes - 1, 0), np.int32)
    ell_s[mini_id, lane] = senders
    ell_w[mini_id, lane] = w
    ell_t[mini_id, lane] = tags
    ell_r[mini_id] = receivers
    return ell_s, ell_w, ell_t, ell_r


def _identity_fill(n: int) -> Dict[str, np.ndarray]:
    """Fill structure of an uncontracted graph: no interiors, every
    slot a pad — the synthesis stage degenerates to the kept-node
    gather."""
    ids = np.full((n, 2), -1, np.int64)
    return {"node": ids, "w": np.full((n, 2), _INF_NP, np.float32),
            "last": ids.copy(), "dir": ids.copy(),
            "seed_last": ids.copy()}


# ---------------------------------------------------------------------------
# Batched within-cell relaxation (precompute + query ascend)
# ---------------------------------------------------------------------------

def _relax_blockdiag(cs: jax.Array, cr: jax.Array, cw: jax.Array,
                     dist0: jax.Array, *, c_max: int,
                     max_iters: int) -> jax.Array:
    """Restricted Bellman-Ford inside many cells at once, as ONE
    block-diagonal graph.

    ``cs``/``cr``/``cw``: (G, e_max) cell-local edge arrays, sorted by
    local receiver, padded with (0, c_max-1, INF) edges whose proposals
    can never win. ``dist0``: (R, G*c_max) distance rows laid out
    cell-major. Offsetting each cell's local ids by ``g*c_max`` turns
    the G independent cells into one graph whose edge list stays
    receiver-sorted, so each sweep is a single wide
    ``segment_min(indices_are_sorted=True)`` — the layout the flat
    solver is fast in. The previous vmap-of-vmap (cells × rows of tiny
    segment reductions) measured ~10× slower PER ELEMENT on CPU than
    this flattening at identical sweep counts."""
    G, e_max = cs.shape
    offs = (jnp.arange(G, dtype=jnp.int32) * c_max)[:, None]
    s_flat = (cs + offs).reshape(-1)
    r_flat = (cr + offs).reshape(-1)
    w_flat = cw.reshape(-1)
    dist, _ = relax_from(s_flat, r_flat, w_flat, dist0,
                         n_nodes=G * c_max, max_iters=max_iters)
    return dist


# ELL minirow width: per-receiver edge runs pad to multiples of this
# and reduce densely. 8 keeps street-node padding waste ≤ ~40% while
# cutting the (single-row) segment reduction to m_max elements.
_ELL_W = 8


def _ell_pack(ie_cell: np.ndarray, ie_s: np.ndarray, ie_r: np.ndarray,
              ie_w: np.ndarray, P: int,
              c_max: int) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Cell-grouped receiver-sorted edges → per-cell ELL minirows:
    ``(P, m_max, W)`` senders/weights + ``(P, m_max)`` minirow
    receivers. Each receiver's edge run is chunked into width-W
    minirows, so a query sweep is one dense ``(m, W)`` gather+min (the
    fast layout at ANY row count) followed by a segment-min over only
    ``m ≈ E/W`` elements instead of E. Pad lanes carry (0, INF); pad
    minirows receive into local ``c_max - 1`` (sorted order kept, INF
    never wins)."""
    E = len(ie_cell)
    if E == 0:
        return (np.zeros((P, 1, _ELL_W), np.int32),
                np.full((P, 1, _ELL_W), _INF_NP, np.float32),
                np.full((P, 1), max(c_max - 1, 0), np.int32))
    key = ie_cell.astype(np.int64) * c_max + ie_r
    new_run = np.empty(E, bool)
    new_run[0] = True
    new_run[1:] = key[1:] != key[:-1]
    run_start = np.maximum.accumulate(np.where(new_run, np.arange(E), 0))
    rank = np.arange(E) - run_start
    new_mini = new_run | (rank % _ELL_W == 0)
    mini_id = np.cumsum(new_mini) - 1                 # global minirow id
    lane = rank % _ELL_W
    mini_cell = ie_cell[new_mini]
    m_counts = np.bincount(mini_cell, minlength=P)
    m_max = max(1, int(m_counts.max()))
    m_starts = np.zeros(P + 1, np.int64)
    np.cumsum(m_counts, out=m_starts[1:])
    mini_local = mini_id - m_starts[ie_cell]
    ell_s = np.zeros((P, m_max, _ELL_W), np.int32)
    ell_w = np.full((P, m_max, _ELL_W), _INF_NP, np.float32)
    ell_r = np.full((P, m_max), max(c_max - 1, 0), np.int32)
    ell_s[ie_cell, mini_local, lane] = ie_s
    ell_w[ie_cell, mini_local, lane] = ie_w
    ell_r[ie_cell, mini_local] = ie_r
    return ell_s, ell_w, ell_r


def _relax_ell(es: jax.Array, ew_: jax.Array, er: jax.Array,
               dist0: jax.Array, *, c_max: int,
               max_iters: int) -> jax.Array:
    """Block-diagonal restricted Bellman-Ford over ELL-packed cells —
    the ONE-ROW query layout (one selected cell per source). ``es``/
    ``ew_``: (S, m_max, W); ``er``: (S, m_max); ``dist0``: (S, c_max).
    Per sweep: dense (S*m, W) gather+lane-min, then a segment-min over
    S*m minirows — ~5× less segment traffic than edge-wise reduction,
    which is what the single-row shape is slow at."""
    S, m_max, W = es.shape
    offs = (jnp.arange(S, dtype=jnp.int32) * c_max)
    s_flat = (es + offs[:, None, None]).reshape(S * m_max, W)
    r_flat = (er + offs[:, None]).reshape(-1)
    w_flat = ew_.reshape(S * m_max, W)
    n_flat = S * c_max

    def one_sweep(dist):                         # dist (n_flat,)
        prop = (dist[s_flat] + w_flat).min(axis=1)
        seg = jax.ops.segment_min(prop, r_flat, num_segments=n_flat,
                                  indices_are_sorted=True)
        return jnp.minimum(dist, seg)

    def relax(state):
        dist, _, it = state
        new = dist
        for _ in range(_K_SWEEPS):
            new = one_sweep(new)
        return new, jnp.any(new < dist), it + _K_SWEEPS

    def keep_going(state):
        _, changed, it = state
        return changed & (it < max_iters)

    dist, _, _ = jax.lax.while_loop(
        keep_going, relax,
        (dist0.reshape(-1), jnp.asarray(True), jnp.zeros((), jnp.int32)))
    return dist.reshape(S, c_max)


def _cell_all_pairs(ces: np.ndarray, cer: np.ndarray, cew: np.ndarray,
                    sizes: np.ndarray, c_max: int) -> np.ndarray:
    """(P, c_max, c_max) EXACT in-cell all-pairs tables — the
    dense-level ascend's precompute. High overlay levels are
    clique-dominated (hundreds of edges per node), so the per-query
    in-cell relaxation that is cheap at street density costs hundreds
    of ms there (measured 435/1013 ms for levels 4/5 of the 1M
    stack); with the full table the ascend is a fold over the entry
    seeds instead. Identity-seeded restricted BF per cell,
    source-chunked to bound the (rows, E) proposal tensor; rows at or
    beyond the cell's size are masked INF."""
    P, e_max = ces.shape
    pt = np.empty((P, c_max, c_max), np.float32)
    chunk = int(np.clip((192 << 20) // (4 * max(e_max, c_max, 1)),
                        32, c_max))
    for p in range(P):
        d_s = jnp.asarray(ces[p])
        d_r = jnp.asarray(cer[p])
        d_w = jnp.asarray(cew[p])
        for lo in range(0, c_max, chunk):
            hi = min(lo + chunk, c_max)
            d0 = np.full((chunk, c_max), _INF_NP, np.float32)
            d0[np.arange(hi - lo), lo + np.arange(hi - lo)] = 0.0
            d0[hi - lo:, 0] = 0.0      # pad rows: harmless re-solves
            out, _ = relax_from(d_s, d_r, d_w, jnp.asarray(d0),
                                n_nodes=c_max,
                                max_iters=c_max + _K_SWEEPS)
            pt[p, lo:hi] = np.asarray(out)[: hi - lo]
        pt[p, sizes[p]:] = _INF_NP
    return pt


@functools.partial(jax.jit, static_argnames=("slack",))
def _prune_cliques(T: jax.Array, *, slack: float = 2e-7) -> jax.Array:
    """(P, b, b) restricted boundary metric → keep mask for clique
    edges. An edge (i, j) is *implied* when some third boundary node k
    gives ``T[i,k] + T[k,j] ≤ T[i,j]`` (within ``slack``): the overlay
    metric closure is unchanged by dropping it, because T is itself the
    restricted metric (triangle inequality holds), both legs are
    strictly shorter than the whole (legs below 1 m are excluded so the
    induction bottoms out), and the implication chain therefore
    terminates at kept edges.

    ``slack`` trades exactness for edge count: a pruned near-tie's
    traffic reroutes over a bypass at most ``(1+slack)`` longer, and
    bypasses chain, so the overlay metric can inflate by ~slack ×
    cascade-depth per level. At the default ~2 ulps the inflation stays
    inside the f32 rounding the module already owns; the knob
    (``ROUTEST_HIER_PRUNE_SLACK``) exists because upper-level cliques
    on grid-like street networks are dominated by near-ties whose
    pruning is worth a bounded, measured error (the scale benches
    record oracle parity per run — the budget is ≤ 1e-5 relative)."""
    P, b, _ = T.shape
    inf = _INF

    def body(k, acc):
        a = T[:, :, k]
        a = a.at[:, k].set(inf)                       # exclude i == k
        a = jnp.where(a < 1.0, inf, a)                # zero-length guard
        c = T[:, k, :]
        c = c.at[:, k].set(inf)                       # exclude j == k
        c = jnp.where(c < 1.0, inf, c)
        return jnp.minimum(acc, a[:, :, None] + c[:, None, :])

    via = jax.lax.fori_loop(0, b, body, jnp.full_like(T, inf))
    implied = via <= T * (1 + slack)
    finite = T < 1e37
    eye = jnp.eye(b, dtype=bool)[None]
    return finite & ~eye & ~implied


# ---------------------------------------------------------------------------
# One level of the stack
# ---------------------------------------------------------------------------

_LEVEL_KEYS = ("cell", "local_of_node", "src_cell", "ell_s", "ell_w",
               "ell_r", "bl", "cbo", "table", "perm_of_node", "b_global")


def _stitch_tiers(bcounts: np.ndarray, max_tiers: int = 4,
                  min_cells: int = 8) -> Tuple[Tuple[int, int, int], ...]:
    """Cells are build-ordered by DESCENDING boundary count; split them
    into ≤ ``max_tiers`` contiguous ranges, each folding only its own
    max boundary count. The descend stitch then pays
    Σ tier_cells × tier_b instead of P × b_max — and trailing
    boundary-free cells (disconnected pockets) cost zero iterations."""
    P = len(bcounts)
    tiers: List[Tuple[int, int, int]] = []
    lo = 0
    while lo < P:
        bb = int(bcounts[lo])
        if bb == 0 or len(tiers) == max_tiers - 1:
            tiers.append((lo, P, bb))
            break
        hi = lo + 1
        while hi < P and (int(bcounts[hi]) * 2 > bb or hi - lo < min_cells):
            hi += 1
        tiers.append((lo, hi, bb))
        lo = hi
    return tuple(tiers)


def _table_chunk(P: int, b_max: int, e_max: int, c_max: int) -> int:
    """Cells per batched precompute dispatch, from a ~256 MB budget on
    the (chunk, b_max, max(e_max, c_max)) proposal tensor: big graphs
    chunk to bound memory, small ones batch the whole level in one
    dispatch instead of 64-cell driblets (the 1M-node build spent most
    of its wall time on dispatch count, not FLOPs)."""
    per_cell = 4 * max(b_max, 1) * max(e_max, c_max, 1)
    return int(np.clip((256 << 20) // per_cell, 8, max(P, 8)))


class _Level:
    """Device-resident arrays + query metadata for one level."""

    def __init__(self, p: Dict[str, np.ndarray], stats: Dict) -> None:
        self.cell = np.asarray(p["cell"])
        self.local_of_node = np.asarray(p["local_of_node"])
        self.src_cell = np.asarray(p["src_cell"])
        self.b_global = np.asarray(p["b_global"])
        P, b_max = p["cbo"].shape
        self.n_cells = P
        self.b_max = b_max
        self.c_max = int(p["table"].shape[2])
        self.n_overlay = int(len(p["b_global"]))
        self.d_ell_s = jnp.asarray(p["ell_s"])
        self.d_ell_w = jnp.asarray(p["ell_w"])
        self.d_ell_r = jnp.asarray(p["ell_r"])
        self.d_bl = jnp.asarray(p["bl"])
        self.d_cbo = jnp.asarray(p["cbo"])
        self.d_table = jnp.asarray(p["table"])
        self.d_perm = jnp.asarray(p["perm_of_node"])
        # Dense-level all-pairs table (+ one INF pad row per cell so
        # pad entry positions fold to INF); None at street density.
        pt = p.get("pt")
        self.d_pt = (jnp.asarray(np.concatenate(
            [pt, np.full((pt.shape[0], 1, self.c_max), _INF_NP,
                         np.float32)], axis=1))
            if pt is not None else None)
        # G_{k-1}-node → local slot, padded with a dump slot (= c_max)
        # so the next level's seed scatter can route pad entries there.
        self.d_local_pad = jnp.asarray(np.concatenate(
            [np.asarray(p["local_of_node"], np.int32),
             np.asarray([self.c_max], np.int32)]))
        bcounts = (np.asarray(p["cbo"]) < self.n_overlay).sum(axis=1)
        self.tiers = _stitch_tiers(bcounts)
        self.stats = stats

    def payload(self) -> Dict[str, np.ndarray]:
        out = {
            "cell": self.cell, "local_of_node": self.local_of_node,
            "src_cell": self.src_cell, "b_global": self.b_global,
            "ell_s": np.asarray(self.d_ell_s),
            "ell_w": np.asarray(self.d_ell_w),
            "ell_r": np.asarray(self.d_ell_r),
            "bl": np.asarray(self.d_bl), "cbo": np.asarray(self.d_cbo),
            "table": np.asarray(self.d_table),
            "perm_of_node": np.asarray(self.d_perm),
        }
        if self.d_pt is not None:
            out["pt"] = np.asarray(self.d_pt)[:, :-1, :]  # drop pad row
        return out


def _build_level(senders: np.ndarray, receivers: np.ndarray, w: np.ndarray,
                 cell: np.ndarray, n_cells: int, *,
                 chunk_cells: Optional[int] = None,
                 prune_slack: float = 2e-7) -> Optional[Tuple[Dict, Dict,
                                                              Tuple]]:
    """One overlay level over an arbitrary input graph: cell-grouped
    edge arrays, boundary tables, pruned cliques. Returns
    ``(payload, stats, (ovl_s, ovl_r, ovl_w))`` — the overlay graph is
    the next level's input — or None when the level cannot help (a
    single cell, or no cell-crossing edges)."""
    n = len(cell)
    P = int(n_cells)
    if P < 2:
        return None
    senders = np.asarray(senders, np.int64)
    receivers = np.asarray(receivers, np.int64)
    w = np.asarray(w, np.float32)

    s_cell, r_cell = cell[senders], cell[receivers]
    internal = s_cell == r_cell
    cross = np.flatnonzero(~internal)
    if len(cross) == 0:
        return None

    # Boundary nodes: endpoints of cell-crossing edges. Cells are
    # RENUMBERED by descending boundary count so the descend stitch can
    # run in contiguous tiers (``_stitch_tiers``).
    is_b = np.zeros(n, bool)
    is_b[senders[cross]] = True
    is_b[receivers[cross]] = True
    bcounts_raw = np.bincount(cell[is_b], minlength=P)
    remap = np.empty(P, np.int32)
    remap[np.argsort(-bcounts_raw, kind="stable")] = np.arange(
        P, dtype=np.int32)
    cell = remap[cell]
    s_cell, r_cell = cell[senders], cell[receivers]

    order = np.argsort(cell, kind="stable")
    sizes = np.bincount(cell, minlength=P)
    starts = np.zeros(P + 1, np.int64)
    np.cumsum(sizes, out=starts[1:])
    c_max = int(sizes.max())
    local_of_node = np.empty(n, np.int32)
    local_of_node[order] = (np.arange(n) - starts[cell[order]]).astype(
        np.int32)

    # Internal edges, grouped by cell and sorted by local receiver.
    ie = np.flatnonzero(internal)
    ie_cell = s_cell[ie]
    ie_s = local_of_node[senders[ie]]
    ie_r = local_of_node[receivers[ie]]
    ie_w = w[ie]
    eorder = np.lexsort((ie_r, ie_cell))
    ie_cell, ie_s, ie_r, ie_w = (a[eorder] for a in (ie_cell, ie_s, ie_r,
                                                     ie_w))
    ecounts = np.bincount(ie_cell, minlength=P)
    e_max = max(1, int(ecounts.max()))
    ces = np.zeros((P, e_max), np.int32)
    cer = np.full((P, e_max), c_max - 1, np.int32)
    cew = np.full((P, e_max), _INF_NP, np.float32)
    estarts = np.zeros(P + 1, np.int64)
    np.cumsum(ecounts, out=estarts[1:])
    flat_pos = np.arange(len(ie)) - estarts[ie_cell]
    ces[ie_cell, flat_pos] = ie_s
    cer[ie_cell, flat_pos] = ie_r
    cew[ie_cell, flat_pos] = ie_w

    b_global = order[is_b[order]]            # cell-grouped boundary list
    b_cell = cell[b_global]
    bcounts = np.bincount(b_cell, minlength=P)
    b_max = int(bcounts.max())
    B = len(b_global)
    bstarts = np.zeros(P + 1, np.int64)
    np.cumsum(bcounts, out=bstarts[1:])
    b_pos = np.arange(B) - bstarts[b_cell]
    bl = np.zeros((P, b_max), np.int32)      # local idx, pad 0 (masked later)
    bl[b_cell, b_pos] = local_of_node[b_global]
    ovl_of_node = np.full(n, -1, np.int64)
    ovl_of_node[b_global] = np.arange(B)
    cbo = np.full((P, b_max), B, np.int32)   # overlay id, pad B (= INF slot)
    cbo[b_cell, b_pos] = np.arange(B)

    # Batched in-cell tables. Clique-DENSE levels (≥ 64 edges/node —
    # upper overlay levels, never street-density level 1) build the
    # FULL in-cell all-pairs table instead: the boundary table is a
    # row subset of it, and the query's ascend into such a cell
    # becomes a fold over the table rather than a relaxation over
    # hundreds of thousands of clique edges per request.
    t_pt = time.perf_counter()
    pt: Optional[np.ndarray] = None
    if (e_max >= 64 * c_max
            and P * c_max * c_max * 4 <= (512 << 20)):
        pt = _cell_all_pairs(ces, cer, cew, sizes, c_max)
        table = np.ascontiguousarray(
            pt[np.arange(P)[:, None], bl, :])
        row = np.arange(b_max)[None, :]
        table[row >= bcounts[:, None]] = _INF_NP
    else:
        # Chunked so the (chunk, b_max, e_max) proposal tensor stays
        # bounded whatever the graph size — and so small levels run in
        # ONE dispatch rather than many.
        if chunk_cells is None:
            chunk_cells = _table_chunk(P, b_max, e_max, c_max)
        chunk_cells = min(chunk_cells, P)
        table = np.empty((P, b_max, c_max), np.float32)
        max_iters = c_max + _K_SWEEPS
        for lo in range(0, P, chunk_cells):
            hi = min(lo + chunk_cells, P)
            pad = chunk_cells - (hi - lo)
            g_ces = np.concatenate([ces[lo:hi],
                                    np.zeros((pad, e_max), np.int32)])
            g_cer = np.concatenate([cer[lo:hi],
                                    np.full((pad, e_max), c_max - 1,
                                            np.int32)])
            g_cew = np.concatenate([cew[lo:hi],
                                    np.full((pad, e_max), _INF_NP,
                                            np.float32)])
            g_bl = np.concatenate([bl[lo:hi],
                                   np.zeros((pad, b_max), np.int32)])
            # Row b of the block-flat table seeds boundary b of EVERY
            # cell in the chunk at once: (b_max, chunk*c_max).
            d0 = jnp.full((b_max, chunk_cells * c_max), _INF)
            pos = (np.arange(chunk_cells, dtype=np.int64)[:, None] * c_max
                   + g_bl).T                              # (b_max, chunk)
            d0 = d0.at[jnp.arange(b_max)[:, None],
                       jnp.asarray(pos)].set(0.0)
            out = _relax_blockdiag(jnp.asarray(g_ces), jnp.asarray(g_cer),
                                   jnp.asarray(g_cew), d0,
                                   c_max=c_max, max_iters=max_iters)
            out = np.asarray(out).reshape(b_max, chunk_cells, c_max)
            table[lo:hi] = out.transpose(1, 0, 2)[: hi - lo]
        # Pad boundary rows carry garbage (seeded at local 0): mask.
        row = np.arange(b_max)[None, :]
        table[row >= bcounts[:, None]] = _INF_NP

    # Cliques: the boundary↔boundary submatrix of each table.
    T = table[np.arange(P)[:, None, None],
              np.arange(b_max)[None, :, None], bl[:, None, :]]
    T = np.where((row[..., None] >= bcounts[:, None, None])
                 | (row[:, None, :] >= bcounts[:, None, None]),
                 _INF_NP, T)
    keep = np.asarray(_prune_cliques(jnp.asarray(T), slack=prune_slack))
    candidates = ((T < 1e37) & ~np.eye(b_max, dtype=bool)[None])
    kp, ki, kj = np.nonzero(keep)
    clique_s = cbo[kp, ki].astype(np.int64)
    clique_r = cbo[kp, kj].astype(np.int64)
    clique_w = T[kp, ki, kj]

    # Overlay graph: pruned cliques + the original crossing edges.
    ovl_s = np.concatenate([clique_s, ovl_of_node[senders[cross]]])
    ovl_r = np.concatenate([clique_r, ovl_of_node[receivers[cross]]])
    ovl_w = np.concatenate([clique_w, w[cross]]).astype(np.float32)
    oorder = np.argsort(ovl_r, kind="stable")
    ovl_s = ovl_s[oorder].astype(np.int32)
    ovl_r = ovl_r[oorder].astype(np.int32)
    ovl_w = ovl_w[oorder]

    ell_s, ell_w, ell_r = _ell_pack(ie_cell, ie_s, ie_r, ie_w, P, c_max)
    perm_of_node = (cell.astype(np.int64) * c_max
                    + local_of_node).astype(np.int32)
    stats = {
        "n_nodes": n, "n_cells": P, "c_max": c_max, "b_max": b_max,
        "n_overlay_nodes": B, "n_overlay_edges": int(len(ovl_s)),
        "clique_edges_kept": int(len(clique_s)),
        "clique_edges_pruned": int(candidates.sum() - keep.sum()),
    }
    payload = {
        "cell": cell.astype(np.int32), "local_of_node": local_of_node,
        "ell_s": ell_s, "ell_w": ell_w, "ell_r": ell_r,
        "bl": bl, "cbo": cbo,
        "table": table, "perm_of_node": perm_of_node,
        "b_global": b_global.astype(np.int64),
        "cell_remap": remap,
    }
    if pt is not None:
        payload["pt"] = pt
        stats["pt"] = {"bytes": int(pt.nbytes),
                       "build_s": round(time.perf_counter() - t_pt, 3)}
    return payload, stats, (ovl_s, ovl_r, ovl_w)


# ---------------------------------------------------------------------------
# The index
# ---------------------------------------------------------------------------

class HierarchicalIndex:
    """Built once per graph; answers batched exact multi-source
    shortest-path distance queries in O(top-cells-across) device sweeps
    regardless of node count."""

    def __init__(self, levels: List[_Level], top_s: np.ndarray,
                 top_r: np.ndarray, top_w: np.ndarray, stats: Dict, *,
                 expand_idx: np.ndarray, seed_node: np.ndarray,
                 seed_w: np.ndarray, l0: Optional[Dict] = None,
                 fill: Optional[Dict] = None,
                 labels: Optional[np.ndarray] = None) -> None:
        self.levels = levels
        self.n_levels = len(levels)
        l1 = levels[0]
        self.cell = l1.cell
        self.n_cells = l1.n_cells
        self.local_of_node = l1.local_of_node
        self.c_max = l1.c_max
        self.b_max = l1.b_max
        self.n_overlay = l1.n_overlay
        self.n_top = levels[-1].n_overlay
        # Chain contraction mapping: the overlay lives on the
        # contracted graph; ``expand_idx`` gathers contracted rows back
        # to full-graph node order (pad slot = INF), ``seed_node``/
        # ``seed_w`` turn an arbitrary full-graph source into ≤2
        # (contracted node, along-chain offset) seeds.
        self._expand_idx = np.asarray(expand_idx, np.int64)
        self._seed_node = np.asarray(seed_node, np.int64)
        self._seed_w = np.asarray(seed_w, np.float32)
        self.n_contracted = len(l1.cell)
        self.n_nodes = len(expand_idx)
        self._contracted = self.n_nodes != self.n_contracted or bool(
            (self._expand_idx != np.arange(self.n_nodes)).any())
        self._d_expand = jnp.asarray(np.where(
            self._expand_idx >= 0, self._expand_idx,
            self.n_contracted).astype(np.int32))
        # contracted node → its G_k overlay id per level (-1 when the
        # node is not a level-k boundary node) — seed entry lookup.
        gk = [np.arange(self.n_contracted, dtype=np.int64)]
        for lvl in levels:
            inv = np.full(len(lvl.cell), -1, np.int64)
            inv[lvl.b_global] = np.arange(lvl.n_overlay)
            prev = gk[-1]
            gk.append(np.where(prev >= 0, inv[np.maximum(prev, 0)], -1))
        self._gk = gk
        self._top_s = np.asarray(top_s, np.int32)
        self._top_r = np.asarray(top_r, np.int32)
        self._top_w = np.asarray(top_w, np.float32)
        self._d_top_s = jnp.asarray(self._top_s)
        self._d_top_r = jnp.asarray(self._top_r)
        self._d_top_w = jnp.asarray(self._top_w)
        # Hub labels: the exact all-pairs top-overlay table. When
        # present the query's top stage is one gather-fold over the
        # source's top-cell boundary seeds; when absent the iterative
        # top BF runs as before (same answers — the table IS its fixed
        # point).
        self._labels = (np.asarray(labels, np.float32)
                        if labels is not None else None)
        self._d_labels = (jnp.asarray(self._labels)
                          if self._labels is not None else None)
        # Level-0 (contracted) edge arrays: what the full solve's
        # polish + predecessor sweeps run over — the bend-chain ratio
        # cheaper than the full graph. ``edge_last`` maps a contracted
        # edge to the ORIGINAL edge entering its receiver, which is
        # what predecessor synthesis hands back to walkers.
        self._l0 = l0
        self._fill = fill
        if l0 is not None:
            l0_r = np.asarray(l0["receivers"], np.int64)
            perm = np.argsort(l0_r, kind="stable")
            s_sorted = np.asarray(l0["senders"],
                                  np.int64)[perm].astype(np.int32)
            r_sorted = l0_r[perm].astype(np.int32)
            w_sorted = np.asarray(l0["w"], np.float32)[perm]
            last_sorted = np.asarray(l0["edge_last"],
                                     np.int64)[perm].astype(np.int32)
            self._d_l0_s = jnp.asarray(s_sorted)
            self._d_l0_r = jnp.asarray(r_sorted)
            self._d_l0_w = jnp.asarray(w_sorted)
            self._d_l0_last = jnp.asarray(last_sorted)
            # ELL minirows for the fused solve's polish + predecessor
            # sweeps: ~8× less segment traffic than edge-wise
            # reductions (the _relax_ell rationale, applied to the
            # whole contracted graph). Lane tags carry the ORIGINAL
            # entering edge so recovered predecessors need no remap.
            nc = self.n_contracted
            es, ew_, et, er = _pack_ell_flat(s_sorted, r_sorted,
                                             w_sorted, last_sorted, nc)
            self._d_l0_ell = (jnp.asarray(es), jnp.asarray(ew_),
                              jnp.asarray(et), jnp.asarray(er))
        if fill is not None:
            nc = self.n_contracted

            def _pad_ids(a):
                a = np.asarray(a, np.int64)
                return jnp.asarray(np.where(a >= 0, a, nc).astype(np.int32))

            self._d_fill_node = _pad_ids(fill["node"])
            self._d_fill_w = jnp.asarray(
                np.asarray(fill["w"], np.float32))
            self._d_fill_last = jnp.asarray(
                np.asarray(fill["last"], np.int64).astype(np.int32))
            self._d_fill_dir = jnp.asarray(
                np.asarray(fill["dir"], np.int64).astype(np.int32))
            self._d_seed_node_full = _pad_ids(self._seed_node)
            self._d_seed_w_full = jnp.asarray(self._seed_w)
            self._d_seed_last = jnp.asarray(
                np.asarray(fill["seed_last"], np.int64).astype(np.int32))
            # Direction tables for the interior-source same-segment
            # correction: each emitted chain direction carries at most
            # ``interior_cap`` interiors, so the correction is a
            # handful of per-source scatters over (n_dirs, k_max)
            # tables instead of dense (S, N) compare passes (measured
            # 36 ms/solve at 250k). Pad row = n_dirs, pad node id =
            # n_nodes — scatters there are dropped by JAX's
            # out-of-bounds update semantics.
            fd = np.asarray(fill["dir"], np.int64)
            fw_np = np.asarray(fill["w"], np.float32)
            fl_np = np.asarray(fill["last"], np.int64)
            mask = fd >= 0
            self._n_dirs = int(fd.max()) + 1 if mask.any() else 0
            kmax = 1
            dir_nodes = np.full((self._n_dirs + 1, 1), self.n_nodes,
                                np.int64)
            dir_w = np.full((self._n_dirs + 1, 1), _INF_NP, np.float32)
            dir_last = np.full((self._n_dirs + 1, 1), -1, np.int64)
            if self._n_dirs:
                vv, ss = np.nonzero(mask)
                dd = fd[vv, ss]
                order = np.argsort(dd, kind="stable")
                dd, vv, ss = dd[order], vv[order], ss[order]
                counts = np.bincount(dd, minlength=self._n_dirs)
                kmax = max(1, int(counts.max()))
                starts = np.zeros(self._n_dirs + 1, np.int64)
                np.cumsum(counts, out=starts[1:])
                ranks = np.arange(len(dd)) - starts[dd]
                dir_nodes = np.full((self._n_dirs + 1, kmax),
                                    self.n_nodes, np.int64)
                dir_w = np.full((self._n_dirs + 1, kmax), _INF_NP,
                                np.float32)
                dir_last = np.full((self._n_dirs + 1, kmax), -1, np.int64)
                dir_nodes[dd, ranks] = vv
                dir_w[dd, ranks] = fw_np[vv, ss]
                dir_last[dd, ranks] = fl_np[vv, ss]
            self._dir_kmax = kmax
            self._d_dir_nodes = jnp.asarray(dir_nodes.astype(np.int32))
            self._d_dir_w = jnp.asarray(dir_w)
            self._d_dir_last = jnp.asarray(dir_last.astype(np.int32))
        self.stats = stats
        # Topology-only customization structure (partition-tree cuts +
        # contraction composition), attached by ``build``/``load``/
        # ``customize``; None for indexes constructed directly.
        self._structure: Optional[Dict] = None
        self._stage_jits: Optional[List[Tuple[str, object]]] = None
        # ``query_fn`` is the raw traceable function: callers chain
        # further device work (the router's polish + predecessor
        # recovery) by inlining it inside ONE outer jit, so a warm
        # solve is a single dispatch+fetch — on the axon tunnel every
        # extra dispatch is a host round trip.
        self.query_fn = self._build_query()

    # -- construction -----------------------------------------------------

    @classmethod
    def build(cls, coords: np.ndarray, senders: np.ndarray,
              receivers: np.ndarray, w: np.ndarray, *,
              cell_target: Optional[int] = None,
              cell_targets: Optional[Sequence[int]] = None,
              max_levels: Optional[int] = None,
              chunk_cells: Optional[int] = None,
              cache_path: Optional[str] = None,
              fingerprint: Optional[Dict] = None
              ) -> Optional["HierarchicalIndex"]:
        """Returns None when the graph is too small to benefit (a
        single cell, or no cell-crossing edges). With ``cache_path``,
        the host-side payload is written there (npz) before device
        upload so later processes skip the whole precompute
        (:meth:`load` — metro-extract serving spawns N workers, and
        each would otherwise pay the batched in-cell relaxation);
        ``fingerprint`` (the router's graph fingerprint) is embedded so
        a loaded payload is bound to ITS graph by content, not by the
        predictable cache filename. ``cell_targets`` (finest first)
        overrides the auto ladder — tests force deep stacks on small
        graphs with it."""
        t0 = time.perf_counter()
        n_full = len(coords)
        coords = np.asarray(coords, np.float32)
        senders = np.asarray(senders, np.int64)
        receivers = np.asarray(receivers, np.int64)
        w = np.asarray(w, np.float32)
        # Degree-2 chain contraction: the overlay is built on the
        # contracted graph (intersections + chain shortcuts), which
        # shrinks every boundary-scaled cost by the bend ratio.
        interior_cap = _contract_interior()
        contraction = (_contract_chains(coords, senders, receivers, w,
                                        interior_cap)
                       if interior_cap else None)
        if contraction is not None:
            kept = contraction["kept"]
            c_coords = coords[kept]
            g_s = contraction["c_senders"]
            g_r = contraction["c_receivers"]
            g_w = contraction["c_w"]
            expand_idx = contraction["cid_of"]
            seed_node = contraction["seed_node"]
            seed_w = contraction["seed_w"]
            edge_last = contraction["edge_comp"][
                contraction["edge_comp_ptr"][1:] - 1]
            fill = {"node": contraction["fill_node"],
                    "w": contraction["fill_w"],
                    "last": contraction["fill_last"],
                    "dir": contraction["fill_dir"],
                    "seed_last": contraction["seed_last"]}
        else:
            c_coords = coords
            g_s, g_r, g_w = senders, receivers, w
            expand_idx = np.arange(n_full, dtype=np.int64)
            seed_node = np.stack([np.arange(n_full, dtype=np.int64),
                                  np.full(n_full, -1, np.int64)], axis=1)
            seed_w = np.stack([np.zeros(n_full, np.float32),
                               np.full(n_full, _INF_NP, np.float32)], axis=1)
            edge_last = np.arange(len(g_s), dtype=np.int64)
            fill = _identity_fill(n_full)
        l0 = {"senders": np.asarray(g_s, np.int64),
              "receivers": np.asarray(g_r, np.int64),
              "w": np.asarray(g_w, np.float32),
              "edge_last": edge_last}
        n = len(c_coords)
        contract_s = round(time.perf_counter() - t0, 3)
        auto_ladder = cell_targets is None
        if cell_targets is None:
            cell_targets = _level_targets(n, cell_target,
                                          max_levels=max_levels)
        t_part = time.perf_counter()
        parts = partition_cells_nested(c_coords,
                                       [int(t) for t in cell_targets])
        partition_s = round(time.perf_counter() - t_part, 3)
        # Everything a metric customization can reuse: the level-0 input
        # topology, the bisection-tree cuts, and the contraction's
        # original-edge composition. All of it is weight-independent —
        # re-pricing starts from here and skips the contraction walk and
        # the partition entirely (the CRP customization/offline split).
        structure: Dict = {
            "c_senders": np.asarray(g_s, np.int64),
            "c_receivers": np.asarray(g_r, np.int64),
            "parts": [(np.asarray(c0, np.int32), int(P))
                      for c0, P in parts],
        }
        if contraction is not None:
            for key in ("edge_comp_ptr", "edge_comp",
                        "seed_comp_ptr", "seed_comp",
                        "fill_comp_ptr", "fill_comp"):
                structure[key] = contraction[key]
        prune_slack = _prune_slack()
        lmax = _labels_max()
        # Early label-stop applies only to the auto ladder: explicit
        # ``cell_targets`` (tests forcing deep stacks) build every
        # requested level. ``B * 8 <= n`` keeps small auto builds
        # multi-level too — the stop exists to skip DENSE top levels
        # at scale, not to flatten every small graph to one level.
        label_stop = min(lmax, _LABEL_STOP) if lmax and auto_ladder else 0
        node_origin = np.arange(n)        # current-graph node → G0 node
        levels: List[_Level] = []
        for li, (cell0, P) in enumerate(parts):
            t_lvl = time.perf_counter()
            built = _build_level(g_s, g_r, g_w,
                                 cell0[node_origin].astype(np.int32), P,
                                 chunk_cells=chunk_cells,
                                 prune_slack=prune_slack)
            if built is None:
                if li == 0:
                    return None
                break
            payload, lstats, ovl = built
            B = len(payload["b_global"])
            stalled = (B >= len(node_origin) if lmax
                       else 2 * B > len(node_origin))
            if li > 0 and stalled:
                # The overlay stopped shrinking — another level would
                # cost more stitch work than its BF saves. With labels
                # on, ANY shrink is worth stacking: the top phase is a
                # table fold (not a BF whose hop count the level must
                # pay back), and every node shaved off the top cuts
                # the all-pairs label build quadratically.
                break
            # Source lookup: G0 node → this level's (renumbered) cell.
            payload["src_cell"] = payload["cell_remap"][
                cell0].astype(np.int32)
            lstats["level"] = li + 1
            lstats["build_s"] = round(time.perf_counter() - t_lvl, 3)
            levels.append(_Level(payload, lstats))
            g_s, g_r, g_w = ovl
            node_origin = node_origin[payload["b_global"]]
            if label_stop and B <= label_stop and B * 8 <= n:
                break
        if not levels:
            return None

        # Hub labels over the top overlay: built with the same batched
        # relaxation the per-query top BF runs, so the table is exact
        # and the query's top phase becomes a fold over it. Skipped
        # (with the BF kept as the serving path) when the top is bigger
        # than the label budget or the knob is off.
        labels = None
        n_top = levels[-1].n_overlay
        label_stats: Optional[Dict] = None
        if lmax and 2 <= n_top <= lmax and len(g_s):
            labels, label_stats = _build_labels(g_s, g_r, g_w, n_top)

        l1 = levels[0].stats
        stats = {
            # Legacy single-level keys = level 1 (health/test consumers).
            "n_cells": l1["n_cells"], "c_max": l1["c_max"],
            "b_max": l1["b_max"],
            "n_overlay_nodes": l1["n_overlay_nodes"],
            "n_overlay_edges": l1["n_overlay_edges"],
            "clique_edges_kept": l1["clique_edges_kept"],
            "clique_edges_pruned": l1["clique_edges_pruned"],
            "n_levels": len(levels),
            "top_nodes": levels[-1].n_overlay,
            "top_edges": int(len(g_s)),
            "prune_slack": prune_slack,
            "partition_s": partition_s,
            "contraction": {
                "interior_cap": interior_cap,
                "n_full": n_full, "n_contracted": n,
                "contract_s": contract_s,
            },
            "levels": [dict(lvl.stats) for lvl in levels],
            "build_s": 0.0,
        }
        if label_stats is not None:
            stats["labels"] = label_stats
        index = cls(levels, g_s, g_r, g_w, stats,
                    expand_idx=expand_idx, seed_node=seed_node,
                    seed_w=seed_w, l0=l0, fill=fill, labels=labels)
        index._structure = structure
        stats["build_s"] = round(time.perf_counter() - t0, 3)
        if cache_path:
            index._save(cache_path, fingerprint)
        return index

    # -- metric customization (CRP-style re-pricing) ----------------------

    def customize(self, w_full: np.ndarray) -> "HierarchicalIndex":
        """Re-price this overlay against a NEW per-edge metric without
        rebuilding its structure — the CRP metric-customization phase.

        ``w_full`` is the full-graph edge weight array (same edge order
        as the ``senders``/``receivers`` the index was built from; any
        positive metric — live travel seconds, tolled meters). Reused
        as-is: the bisection-tree cuts, the chain-contraction walk
        (new chain weights are composition sums over ``w_full``), every
        level's cell membership and boundary sets (all topology-only —
        boundaries are endpoints of cell-crossing edges, and nesting
        keeps cliques inside cells at every level). Recomputed: in-cell
        boundary tables, clique pruning, overlay weights — the batched
        device relaxations, whose kernels are already compiled from the
        build (same shapes → jit cache hits, no recompile).

        Returns a NEW index (the current one keeps serving — callers
        flip atomically); raises ``ValueError`` when the index carries
        no structure (direct construction or a pre-v3 cache)."""
        s = self._structure
        if s is None:
            raise ValueError(
                "index has no customization structure (built by an "
                "older cache version? rebuild the overlay)")
        t0 = time.perf_counter()
        w_full = np.asarray(w_full, np.float32)
        ecp = s.get("edge_comp_ptr")
        if ecp is not None:
            # Chain-contracted graph: contracted edge k's weight is the
            # sum of its original-edge composition; seed offsets
            # likewise. Cumulative-sum ragged reduction (reduceat
            # misbehaves on empty segments, which kept-node seeds are).
            comp = s["edge_comp"]
            cs = np.concatenate([
                [0.0], np.cumsum(w_full[comp], dtype=np.float64)])
            g_w = (cs[ecp[1:]] - cs[ecp[:-1]]).astype(np.float32)
            scp = s["seed_comp_ptr"]
            scs = np.concatenate([
                [0.0], np.cumsum(w_full[s["seed_comp"]],
                                 dtype=np.float64)])
            seed_sums = (scs[scp[1:]] - scs[scp[:-1]]).reshape(-1, 2)
            seed_w = np.where(self._seed_node >= 0, seed_sums,
                              _INF_NP).astype(np.float32)
            fcp = s["fill_comp_ptr"]
            fcs = np.concatenate([
                [0.0], np.cumsum(w_full[s["fill_comp"]],
                                 dtype=np.float64)])
            fill_sums = (fcs[fcp[1:]] - fcs[fcp[:-1]]).reshape(-1, 2)
            fill = dict(self._fill or _identity_fill(len(w_full)))
            fill["w"] = np.where(
                np.asarray(fill["node"]) >= 0, fill_sums,
                _INF_NP).astype(np.float32)
        else:
            g_w = w_full
            seed_w = self._seed_w  # identity contraction: col0 = 0,
            #                        col1 = INF — weight-independent
            fill = self._fill      # all pads — weight-independent
        g_s = s["c_senders"]
        g_r = s["c_receivers"]
        g_w0 = g_w                 # level-0 weights, before the loop
        #                            rebinds g_w to overlay weights
        prune_slack = float(self.stats.get("prune_slack", _prune_slack()))
        lmax = _labels_max()
        node_origin = np.arange(len(self.levels[0].cell))
        levels: List[_Level] = []
        for li, (cell0, P) in enumerate(s["parts"]):
            t_lvl = time.perf_counter()
            built = _build_level(g_s, g_r, g_w,
                                 cell0[node_origin].astype(np.int32), P,
                                 prune_slack=prune_slack)
            if built is None:
                if li == 0:
                    raise ValueError("customization built no levels — "
                                     "graph/structure mismatch")
                break
            payload, lstats, ovl = built
            B = len(payload["b_global"])
            stalled = (B >= len(node_origin) if lmax
                       else 2 * B > len(node_origin))
            if li > 0 and stalled:
                break
            payload["src_cell"] = payload["cell_remap"][
                cell0].astype(np.int32)
            lstats["level"] = li + 1
            lstats["build_s"] = round(time.perf_counter() - t_lvl, 3)
            levels.append(_Level(payload, lstats))
            g_s, g_r, g_w = ovl
            node_origin = node_origin[payload["b_global"]]
            if (lmax and B <= min(lmax, _LABEL_STOP)
                    and B * 8 <= len(self.levels[0].cell)):
                break
        # Re-price the labels too (same build, new top weights): a
        # live-metric flip then keeps the fold path instead of falling
        # back to the iterative top BF.
        labels = None
        lmax = _labels_max()
        n_top = levels[-1].n_overlay
        label_stats: Optional[Dict] = None
        if lmax and 2 <= n_top <= lmax and len(g_s):
            labels, label_stats = _build_labels(g_s, g_r, g_w, n_top)
        l1 = levels[0].stats
        stats = {
            "n_cells": l1["n_cells"], "c_max": l1["c_max"],
            "b_max": l1["b_max"],
            "n_overlay_nodes": l1["n_overlay_nodes"],
            "n_overlay_edges": l1["n_overlay_edges"],
            "clique_edges_kept": l1["clique_edges_kept"],
            "clique_edges_pruned": l1["clique_edges_pruned"],
            "n_levels": len(levels),
            "top_nodes": levels[-1].n_overlay,
            "top_edges": int(len(g_s)),
            "prune_slack": prune_slack,
            "partition_s": 0.0,        # reused — that is the point
            "contraction": dict(self.stats.get("contraction", {})),
            "levels": [dict(lvl.stats) for lvl in levels],
            "customized": True,
            "full_build_s": self.stats.get("build_s", 0.0),
        }
        if label_stats is not None:
            stats["labels"] = label_stats
        l0 = dict(self._l0) if self._l0 is not None else None
        if l0 is not None:
            l0["w"] = np.asarray(g_w0, np.float32)
        out = type(self)(levels, g_s, g_r, g_w, stats,
                         expand_idx=self._expand_idx,
                         seed_node=self._seed_node, seed_w=seed_w,
                         l0=l0, fill=fill, labels=labels)
        out._structure = s
        stats["build_s"] = round(time.perf_counter() - t0, 3)
        return out

    def _save(self, cache_path: str, fingerprint: Optional[Dict]) -> None:
        flat: Dict[str, np.ndarray] = {
            "top_s": self._top_s, "top_r": self._top_r, "top_w": self._top_w,
            "expand_idx": self._expand_idx,
            "seed_node": self._seed_node, "seed_w": self._seed_w,
        }
        if self._labels is not None:
            flat["labels"] = self._labels
        if self._l0 is not None:
            for name in ("senders", "receivers", "w", "edge_last"):
                flat[f"g0_{name}"] = np.asarray(self._l0[name])
        if self._fill is not None:
            for name in ("node", "w", "last", "dir", "seed_last"):
                flat[f"fill_{name}"] = np.asarray(self._fill[name])
        for k, lvl in enumerate(self.levels):
            p = lvl.payload()
            for name in _LEVEL_KEYS:
                flat[f"l{k}_{name}"] = p[name]
            if "pt" in p:
                flat[f"l{k}_pt"] = p["pt"]
        # v3: the customization structure rides along, so a worker that
        # REHYDRATES the overlay can still re-price it against a live
        # metric (the whole point of shipping structure, not just
        # payload).
        s = self._structure
        if s is not None:
            flat["s_c_senders"] = s["c_senders"]
            flat["s_c_receivers"] = s["c_receivers"]
            flat["s_parts"] = np.stack(
                [c0 for c0, _ in s["parts"]]).astype(np.int32)
            flat["s_parts_counts"] = np.asarray(
                [P for _, P in s["parts"]], np.int64)
            if "edge_comp_ptr" in s:
                for name in ("edge_comp_ptr", "edge_comp",
                             "seed_comp_ptr", "seed_comp",
                             "fill_comp_ptr", "fill_comp"):
                    flat[f"s_{name}"] = s[name]
        tmp = f"{cache_path}.tmp{os.getpid()}.npz"
        try:
            np.savez_compressed(
                tmp, _version=np.int64(_CACHE_VERSION),
                _n_levels=np.int64(self.n_levels),
                _stats=np.frombuffer(json.dumps(self.stats).encode(),
                                     dtype=np.uint8),
                _fp=np.frombuffer(
                    json.dumps(fingerprint or {},
                               sort_keys=True).encode(), dtype=np.uint8),
                **flat)
            os.replace(tmp, cache_path)
        except OSError:
            # cache is an optimization, never a dependency — but a
            # half-written tmp must not accumulate
            try:
                os.unlink(tmp)
            except OSError:
                pass

    @classmethod
    def load(cls, cache_path: str,
             fingerprint: Optional[Dict] = None
             ) -> Optional["HierarchicalIndex"]:
        """Rehydrate a cached overlay; None on any mismatch/corruption
        (callers rebuild) — LOUDLY, so a fleet whose replicas silently
        re-spend minutes of precompute per boot is visible in logs. The
        embedded fingerprint must match the caller's graph — the
        filename alone is predictable, so a payload at the right name
        but for the wrong (or tampered) graph is rejected by content,
        and the worst a poisoned entry can do is force a rebuild."""
        try:
            with np.load(cache_path, allow_pickle=False) as z:
                version = int(z["_version"])
                if version != _CACHE_VERSION:
                    _log().warning("overlay_cache_rejected",
                                   path=cache_path, reason="version",
                                   found=version, want=_CACHE_VERSION)
                    return None
                if fingerprint is not None:
                    cached_fp = json.loads(bytes(z["_fp"]).decode())
                    if cached_fp != json.loads(
                            json.dumps(fingerprint, sort_keys=True)):
                        _log().warning("overlay_cache_rejected",
                                       path=cache_path,
                                       reason="fingerprint_mismatch",
                                       found=cached_fp, want=fingerprint)
                        return None
                stats = json.loads(bytes(z["_stats"]).decode())
                n_levels = int(z["_n_levels"])
                levels = []
                for k in range(n_levels):
                    p = {name: z[f"l{k}_{name}"] for name in _LEVEL_KEYS}
                    if f"l{k}_pt" in z.files:
                        p["pt"] = z[f"l{k}_pt"]
                    levels.append(_Level(p, stats["levels"][k]))
                top_s, top_r, top_w = z["top_s"], z["top_r"], z["top_w"]
                expand_idx = z["expand_idx"]
                seed_node, seed_w = z["seed_node"], z["seed_w"]
                labels = z["labels"] if "labels" in z.files else None
                l0 = fill = None
                if "g0_senders" in z.files:
                    l0 = {name: z[f"g0_{name}"]
                          for name in ("senders", "receivers", "w",
                                       "edge_last")}
                if "fill_node" in z.files:
                    fill = {name: z[f"fill_{name}"]
                            for name in ("node", "w", "last", "dir",
                                         "seed_last")}
                structure: Optional[Dict] = None
                if "s_parts" in z.files:
                    parts_arr = z["s_parts"]
                    counts = z["s_parts_counts"]
                    structure = {
                        "c_senders": z["s_c_senders"],
                        "c_receivers": z["s_c_receivers"],
                        "parts": [(parts_arr[k], int(counts[k]))
                                  for k in range(len(counts))],
                    }
                    if "s_edge_comp_ptr" in z.files:
                        for name in ("edge_comp_ptr", "edge_comp",
                                     "seed_comp_ptr", "seed_comp",
                                     "fill_comp_ptr", "fill_comp"):
                            structure[name] = z[f"s_{name}"]
        except Exception as e:
            _log().warning("overlay_cache_rejected", path=cache_path,
                           reason=f"{type(e).__name__}: {e}")
            return None
        stats["loaded_from_cache"] = True
        index = cls(levels, top_s, top_r, top_w, stats,
                    expand_idx=expand_idx, seed_node=seed_node,
                    seed_w=seed_w, l0=l0, fill=fill, labels=labels)
        index._structure = structure
        return index

    # -- query ------------------------------------------------------------

    def _stages(self) -> List[Tuple[str, object]]:
        """The query pipeline as (name, traceable fn) pairs over a
        carry dict — ONE decomposition shared by the fused
        ``query_fn`` (single dispatch, serving) and ``timed_query``
        (stage-per-dispatch, the benches' per-phase breakdown)."""
        lvls = self.levels
        L = self.n_levels
        top_s, top_r, top_w = self._d_top_s, self._d_top_r, self._d_top_w
        Bt = self.n_top

        def phase1(c: Dict) -> Dict:
            l = lvls[0]
            p = c["p_cells"][0]
            sp = c["seed_pos"][0]                # (S, 2) local ids|dump
            sv = c["seed_val"][0]
            S = sp.shape[0]
            rows = jnp.arange(S)
            d0 = jnp.full((S, l.c_max + 1), _INF)
            d0 = d0.at[rows[:, None], sp].min(sv)[:, :l.c_max]
            local = _relax_ell(l.d_ell_s[p], l.d_ell_w[p], l.d_ell_r[p],
                               d0, c_max=l.c_max,
                               max_iters=l.c_max + _K_SWEEPS)
            return {**c, "local0": local}

        def make_ascend(k: int):
            lp, l = lvls[k - 1], lvls[k]

            def ascend(c: Dict) -> Dict:
                p_prev = c["p_cells"][k - 1]
                p = c["p_cells"][k]
                local_prev = c[f"local{k - 1}"]
                S = local_prev.shape[0]
                rows = jnp.arange(S)
                seed = jnp.take_along_axis(local_prev, lp.d_bl[p_prev],
                                           axis=1)
                pos = l.d_local_pad[lp.d_cbo[p_prev]]
                if l.d_pt is not None:
                    # Dense level: fold the entry seeds through the
                    # precomputed in-cell all-pairs table — same fixed
                    # point as the relaxation below, minus the
                    # per-query sweeps over clique-dense edges. Pad
                    # seeds land on the per-cell INF row.
                    bp = seed.shape[1]

                    def body(j, acc):
                        row = l.d_pt[p, pos[:, j]]       # (S, c_max)
                        return jnp.minimum(
                            acc, jnp.expand_dims(seed[:, j], 1) + row)

                    local = jax.lax.fori_loop(
                        0, bp, body, jnp.full((S, l.c_max), _INF))
                    for j2 in (0, 1):
                        row = l.d_pt[p, c["seed_pos"][k][:, j2]]
                        local = jnp.minimum(
                            local,
                            c["seed_val"][k][:, j2, None] + row)
                    return {**c, f"local{k}": jnp.minimum(local, _INF)}
                d0 = jnp.full((S, l.c_max + 1), _INF)
                d0 = d0.at[rows[:, None], pos].min(seed)
                # Chain-interior sources whose second endpoint lands in
                # a different cell below this level enter here.
                d0 = d0.at[rows[:, None], c["seed_pos"][k]].min(
                    c["seed_val"][k])
                d0 = d0[:, :l.c_max]
                local = _relax_ell(l.d_ell_s[p], l.d_ell_w[p], l.d_ell_r[p],
                                   d0, c_max=l.c_max,
                                   max_iters=l.c_max + _K_SWEEPS)
                return {**c, f"local{k}": local}

            return ascend

        def top_bf(c: Dict) -> Dict:
            l = lvls[L - 1]
            p = c["p_cells"][L - 1]
            local = c[f"local{L - 1}"]
            S = local.shape[0]
            rows = jnp.arange(S)
            seed = jnp.take_along_axis(local, l.d_bl[p], axis=1)
            ovl0 = jnp.full((S, Bt + 1), _INF)
            ovl0 = ovl0.at[rows[:, None], l.d_cbo[p]].min(seed)
            ovl0 = ovl0.at[rows[:, None], c["seed_pos"][L]].min(
                c["seed_val"][L])
            ovl, _ = relax_from(top_s, top_r, top_w, ovl0[:, :Bt],
                                n_nodes=Bt, max_iters=Bt + _K_SWEEPS)
            return {**c, "ovl": ovl}

        d_labels = self._d_labels

        def top_labels(c: Dict) -> Dict:
            """Hub-label fold: the top BF's fixed point read off the
            precomputed all-pairs table. A source's only finite top
            seeds are its top-cell boundary distances (+ ≤2 chain
            seeds), so ``min_b(seed_b + labels[b, v])`` IS the top BF
            answer — one gather-min over the seed axis instead of a
            diameter-bound while_loop."""
            l = lvls[L - 1]
            p = c["p_cells"][L - 1]
            local = c[f"local{L - 1}"]
            S = local.shape[0]
            seed = jnp.take_along_axis(local, l.d_bl[p], axis=1)
            ids = l.d_cbo[p]                     # (S, b), pad = Bt
            lab_pad = jnp.concatenate(
                [d_labels, jnp.full((1, Bt), _INF)], axis=0)
            b = seed.shape[1]
            if S * b * Bt * 4 <= (192 << 20):
                acc = jnp.min(seed[:, :, None] + lab_pad[ids], axis=1)
            else:  # bound the (S, b, Bt) proposal on huge tops

                def body(i, acc):
                    return jnp.minimum(
                        acc, seed[:, i, None] + lab_pad[ids[:, i]])

                acc = jax.lax.fori_loop(0, b, body,
                                        jnp.full((S, Bt), _INF))
            for j in (0, 1):
                sid = c["seed_pos"][L][:, j]     # pad = Bt (INF row)
                acc = jnp.minimum(
                    acc, c["seed_val"][L][:, j, None] + lab_pad[sid])
            return {**c, "ovl": jnp.minimum(acc, _INF)}

        def make_descend(k: int):
            l = lvls[k]

            def descend(c: Dict) -> Dict:
                p = c["p_cells"][k]
                local = c[f"local{k}"]
                ovl = c["ovl"]
                S = ovl.shape[0]
                rows = jnp.arange(S)
                ovl_pad = jnp.concatenate(
                    [ovl, jnp.full((S, 1), _INF)], axis=1)
                parts = []
                for lo, hi, bb in l.tiers:
                    cbo_t = l.d_cbo[lo:hi]
                    tab_t = l.d_table[lo:hi]

                    def body(b, acc, cbo_t=cbo_t, tab_t=tab_t):
                        o_b = ovl_pad[:, cbo_t[:, b]]       # (S, tier)
                        return jnp.minimum(
                            acc, o_b[:, :, None] + tab_t[None, :, b, :])

                    parts.append(jax.lax.fori_loop(
                        0, bb, body,
                        jnp.full((S, hi - lo, l.c_max), _INF)))
                acc = (jnp.concatenate(parts, axis=1)
                       if len(parts) > 1 else parts[0])
                flat = acc.reshape(S, l.n_cells * l.c_max)
                # Fold in the ascend local (the only candidate for paths
                # that never leave the source's cell at this level);
                # layout is already cell-major, so the final answer is
                # one gather, not a scatter.
                pos = (p * l.c_max)[:, None] + jnp.arange(l.c_max)[None, :]
                flat = flat.at[rows[:, None], pos].min(local)
                # Unreachable sums overflow f32 (3e38 + 3e38 = inf);
                # clamp back to the finite sentinel so downstream slack
                # arithmetic (tight_pred) never sees inf - inf = nan.
                return {**c, "ovl": jnp.minimum(flat[:, l.d_perm], _INF)}

            return descend

        def expand(c: Dict) -> Dict:
            """Contracted → full-graph distances: kept nodes gather
            their row; chain interiors take ``min`` over their ≤2 fill
            entries (direction-start distance + along-chain offset).
            Exact for every path that touches a kept node — which is
            every path except an interior source's own-segment tail;
            :meth:`full_solve_fn` refines that case (and recovers
            predecessors), so callers needing interior-source-to-
            same-chain exactness go through the full solve."""
            ovl = c["ovl"]                        # (S, n_contracted)
            S = ovl.shape[0]
            pad = jnp.concatenate([ovl, jnp.full((S, 1), _INF)], axis=1)
            out = pad[:, self._d_expand]
            if self._fill is not None:
                for j in (0, 1):
                    fn = self._d_fill_node[:, j]
                    fw = self._d_fill_w[:, j]
                    out = jnp.minimum(out, pad[:, fn] + fw[None, :])
            return {**c, "ovl": jnp.minimum(out, _INF)}

        stages: List[Tuple[str, object]] = [("phase1", phase1)]
        for k in range(1, L):
            stages.append((f"ascend_l{k + 1}", make_ascend(k)))
        stages.append(("top_labels", top_labels) if d_labels is not None
                      else ("top_bf", top_bf))
        for k in range(L - 1, -1, -1):
            stages.append((f"descend_l{k + 1}", make_descend(k)))
        if self._contracted:
            stages.append(("expand", expand))
        return stages

    def _build_query(self):
        stages = self._stages()

        def query(p_cells: jax.Array, seed_pos: jax.Array,
                  seed_val: jax.Array) -> jax.Array:
            carry = {"p_cells": p_cells, "seed_pos": seed_pos,
                     "seed_val": seed_val}
            for _name, fn in stages:
                carry = fn(carry)
            return carry["ovl"]

        return query

    def full_solve_fn(self, n_sweeps: int = 2):
        """The router's fused warm-solve program: overlay query +
        polish + predecessor recovery ON THE CONTRACTED GRAPH, then an
        exact synthesis of full-graph distances and ORIGINAL-edge
        predecessors from the chain fill structure.

        Before this, polish and predecessor sweeps ran over the FULL
        edge list — 2-3 passes over (S, E_full) that dominated warm
        latency once the overlay phases shrank (the bend ratio makes
        the contracted graph ~6× smaller on real street extracts).
        Synthesis rules (all exact):

        - kept node: distance = its contracted row; predecessor = the
          last ORIGINAL edge of its contracted predecessor edge.
        - chain interior v: min over its ≤2 fill slots of
          ``dist[direction start] + along-chain offset``, plus — when
          the SOURCE sits on the same emitted direction upstream — the
          direct along-chain offset difference (the one path family
          that never touches a kept node). Predecessor = that
          direction's entering hop.
        - seed endpoints of an interior source whose distance still
          equals the seed offset take the chain's last hop as
          predecessor (no contracted edge carried that assignment).

        Returns a traceable ``(p_cells, seed_pos, seed_val,
        src_full) -> (dist (S, N), pred (S, N) original edge ids)``;
        callers jit/AOT-compile it per bucket."""
        if self._l0 is None or self._fill is None:
            raise ValueError("index lacks level-0/fill arrays (pre-v4 "
                             "cache or direct construction) — rebuild "
                             "the overlay")
        stages = [st for st in self._stages() if st[0] != "expand"]
        nc = self.n_contracted
        ell_s, ell_w, ell_t, ell_r = self._d_l0_ell
        d_expand = self._d_expand
        d_fill_node = self._d_fill_node
        d_fill_w = self._d_fill_w
        d_fill_last = self._d_fill_last
        d_fill_dir = self._d_fill_dir
        d_seed_node = self._d_seed_node_full
        d_seed_w = self._d_seed_w_full
        d_seed_last = self._d_seed_last

        def solve(p_cells: jax.Array, seed_pos: jax.Array,
                  seed_val: jax.Array, src_full: jax.Array):
            carry = {"p_cells": p_cells, "seed_pos": seed_pos,
                     "seed_val": seed_val}
            for _name, fn in stages:
                carry = fn(carry)
            dist_c = carry["ovl"]                    # (S, n_contracted)
            S = dist_c.shape[0]
            rows = jnp.arange(S)

            # Polish + tight-edge recovery over the ELL minirows: the
            # same math as :func:`polish`/:func:`tight_edges`, with
            # segment reductions over E/8 minirows instead of E edges
            # — on CPU the segment op, not the gather, is the cost.
            # Lane tags ARE the original entering edges, so recovered
            # predecessors need no later remap.
            def seg_min_rows(x):
                return jax.vmap(lambda v: jax.ops.segment_min(
                    v, ell_r, num_segments=nc,
                    indices_are_sorted=True))(x)

            for _ in range(n_sweeps):
                prop = (dist_c[:, ell_s] + ell_w[None]).min(axis=2)
                dist_c = jnp.minimum(dist_c, seg_min_rows(prop))
            prop3 = dist_c[:, ell_s] + ell_w[None]       # (S, m, 8)
            slack3 = prop3 - dist_c[:, ell_r][:, :, None]
            min_slack = seg_min_rows(slack3.min(axis=2))
            tight3 = slack3 <= min_slack[:, ell_r][:, :, None] + 1e-2
            # Min-sender-dist disambiguation (see tight_edges).
            sd3 = jnp.where(tight3, dist_c[:, ell_s], _INF)
            best_sd = seg_min_rows(sd3.min(axis=2))
            pick3 = tight3 & (sd3 <= best_sd[:, ell_r][:, :, None])
            ids3 = jnp.where(pick3, ell_t[None], -1)
            pred_c = jnp.maximum(jax.vmap(
                lambda v: jax.ops.segment_max(
                    v, ell_r, num_segments=nc,
                    indices_are_sorted=True))(ids3.max(axis=2)), -1)
            dist_pad = jnp.concatenate(
                [dist_c, jnp.full((S, 1), _INF)], axis=1)
            pred_pad = jnp.concatenate(
                [pred_c, jnp.full((S, 1), -1, jnp.int32)], axis=1)
            # Interior-source seed endpoints still carrying their seed
            # assignment: encode the chain's last hop as -2 - edge so
            # synthesis can tell it from a contracted edge id.
            sn = d_seed_node[src_full]               # (S, 2), pad = nc
            sw = d_seed_w[src_full]
            sl = d_seed_last[src_full]
            for j in (0, 1):
                cur = pred_pad[rows, sn[:, j]]
                cond = ((sl[:, j] >= 0)
                        & (dist_pad[rows, sn[:, j]] >= sw[:, j]))
                pred_pad = pred_pad.at[rows, sn[:, j]].set(
                    jnp.where(cond, -2 - sl[:, j], cur))
            # Synthesis: kept gather + fill fold. Direction choice is
            # ulp-TOLERANT with a smaller-START-distance tie-break:
            # zero-length chain hops make equal-distance neighbor pairs
            # (interior ↔ kept endpoint) whose independent pred choices
            # could otherwise point at each other — a walk 2-cycle the
            # 250k extract actually produced. Preferring the direction
            # whose start is strictly closer makes every within-chain
            # walk step monotone toward a kept node, so the synthesized
            # forest is acyclic wherever the contracted tree is.
            base = dist_pad[:, d_expand]
            pc = pred_pad[:, d_expand]
            # pred_c lanes already carry ORIGINAL edge ids; -2 - e
            # encodes an interior source's chain hop (above).
            bpred_k = jnp.where(pc <= -2, -2 - pc, pc)
            # Fill fold over the two slots in ONE vectorized pick:
            # kept nodes have pad (INF) fills so their contracted row
            # always wins; interiors choose between their two
            # directions with an ulp-tolerant, nearer-start tie-break.
            start0 = dist_pad[:, d_fill_node[:, 0]]
            val0 = start0 + d_fill_w[None, :, 0]
            start1 = dist_pad[:, d_fill_node[:, 1]]
            val1 = start1 + d_fill_w[None, :, 1]
            close = jnp.abs(val0 - val1) <= 4e-7 * val0 + 1e-6
            pick1 = jnp.where(close, start1 < start0, val1 < val0)
            fval = jnp.where(pick1, val1, val0)
            fstart = jnp.where(pick1, start1, start0)
            fpred = jnp.where(pick1, d_fill_last[None, :, 1],
                              d_fill_last[None, :, 0])
            take = (fval < 1e37) & (fval < base)
            best = jnp.where(take, fval, base)
            best_start = jnp.where(take, fstart, -jnp.inf)
            bpred = jnp.where(take, fpred, bpred_k)

            def closer(val, start, cur, cur_start):
                finite = val < 1e37
                close_ = jnp.abs(val - cur) <= 4e-7 * val + 1e-6
                return finite & jnp.where(close_, start < cur_start,
                                          val < cur)
            # Same-direction along-chain candidates for interior
            # sources — the one path family that never touches a kept
            # node; their "start" is the source itself (distance 0, the
            # minimal possible, so they win every tie). Each emitted
            # direction holds ≤ interior_cap interiors, so this is a
            # few (S,)-sized scatters through the direction tables
            # (pads scatter out of bounds and are dropped), not dense
            # (S, N) compare passes.
            sdir = d_fill_dir[src_full]              # (S, 2)
            sfw = d_fill_w[src_full]
            for i in (0, 1):
                d = jnp.where(sdir[:, i] >= 0, sdir[:, i], self._n_dirs)
                ok_dir = sdir[:, i] >= 0
                for k in range(self._dir_kmax):
                    v = self._d_dir_nodes[d, k]          # (S,), pad = N
                    off = self._d_dir_w[d, k] - sfw[:, i]
                    ok = ok_dir & (off >= 0)
                    val = jnp.where(ok, off, _INF)
                    v_safe = jnp.minimum(v, best.shape[1] - 1)
                    cur = best[rows, v_safe]
                    curp = bpred[rows, v_safe]
                    cur_start = best_start[rows, v_safe]
                    take = ok & closer(val, jnp.zeros_like(val), cur,
                                       cur_start)
                    bpred = bpred.at[rows, v].set(
                        jnp.where(take, self._d_dir_last[d, k], curp))
                    best = best.at[rows, v].set(
                        jnp.where(take, val, cur))
                    best_start = best_start.at[rows, v].set(
                        jnp.where(take, 0.0, cur_start))
            best = jnp.minimum(best, _INF)
            best = best.at[rows, src_full].set(0.0)
            bpred = bpred.at[rows, src_full].set(-1)
            return best, bpred

        return solve

    def timed_query(self, sources: np.ndarray
                    ) -> Tuple[np.ndarray, Dict[str, float]]:
        """(S, N) distances + per-stage wall milliseconds, each stage
        its own jitted dispatch (bench instrumentation — serving uses
        the fused ``query_fn``). Stage jits are cached on the index so
        repeat calls measure warm execution, not tracing."""
        if self._stage_jits is None:
            self._stage_jits = [(name, jax.jit(fn))
                                for name, fn in self._stages()]
        p_cells, seed_pos, seed_val = self.prep_sources(np.asarray(sources))
        carry = {"p_cells": p_cells, "seed_pos": seed_pos,
                 "seed_val": seed_val}
        phases: Dict[str, float] = {}
        for name, fn in self._stage_jits:
            t0 = time.perf_counter()
            carry = fn(carry)
            jax.block_until_ready(carry)
            phases[name] = round(1000 * (time.perf_counter() - t0), 2)
        return np.asarray(carry["ovl"]), phases

    def prep_sources(self, sources: np.ndarray
                     ) -> Tuple[jax.Array, jax.Array, jax.Array]:
        """(S,) global source nodes → the ``query_fn`` argument triple:
        (L, S) per-level cell ids of each source's PRIMARY seed, plus
        (L+1, S, 2) seed positions / values. The ONE place the source
        encoding lives — every query goes through it.

        A contracted (kept) source is one zero-weight seed in its own
        level-1 cell. A chain-interior source becomes ≤2 (endpoint,
        along-chain offset) seeds; each enters the query at the FIRST
        level whose cell contains both it and the primary — nesting
        guarantees a seed that differs below level k is a level-k
        boundary node, so the entry position always exists (the top
        row of ``seed_pos`` holds raw overlay ids)."""
        sources = np.asarray(sources, np.int64)
        S = len(sources)
        L = self.n_levels
        sn = self._seed_node[sources]            # (S, 2) contracted ids
        sw = self._seed_w[sources]               # (S, 2)
        primary = np.maximum(sn[:, 0], 0)
        p_cells = np.stack([lvl.src_cell[primary].astype(np.int64)
                            for lvl in self.levels])
        seed_pos = np.empty((L + 1, S, 2), np.int32)
        seed_val = np.full((L + 1, S, 2), _INF_NP, np.float32)
        for k, lvl in enumerate(self.levels):
            seed_pos[k] = lvl.c_max              # dump slot
        seed_pos[L] = self.n_top
        for j in (0, 1):
            cv = sn[:, j]
            cvs = np.maximum(cv, 0)
            remaining = cv >= 0
            for k, lvl in enumerate(self.levels):
                g = self._gk[k][cvs]
                ok = (remaining & (lvl.src_cell[cvs] == p_cells[k])
                      & (g >= 0))
                pos = lvl.local_of_node[np.maximum(g, 0)]
                seed_pos[k][ok, j] = pos[ok]
                seed_val[k][ok, j] = sw[ok, j]
                remaining &= ~ok
            g = self._gk[L][cvs]
            ok = remaining & (g >= 0)
            seed_pos[L][ok, j] = g[ok]
            seed_val[L][ok, j] = sw[ok, j]
        return (jnp.asarray(p_cells.astype(np.int32)),
                jnp.asarray(seed_pos), jnp.asarray(seed_val))


def build_params() -> Dict:
    """The env-tunable knobs that change a BUILT overlay's content for
    the same graph — part of the cache key, so flipping a knob can
    never serve a payload built under the old one."""
    try:
        # 0 = auto (4 with labels, 16 without) — see _level_targets.
        ratio = int(os.environ.get("ROUTEST_HIER_RATIO", "0") or 0)
    except ValueError:
        ratio = 0
    try:
        max_levels = int(os.environ.get("ROUTEST_HIER_MAX_LEVELS", "0") or 0)
    except ValueError:
        max_levels = 0
    try:
        cell_target = int(
            os.environ.get("ROUTEST_HIER_CELL_TARGET", "0") or 0)
    except ValueError:
        cell_target = 0
    return {"prune_slack": _prune_slack(), "ratio": ratio,
            "max_levels": max_levels, "cell_target": cell_target,
            "contract": _contract_interior(), "labels": _labels_max()}


def _fingerprint_digest(fingerprint: Dict) -> str:
    """Short stable content hash of the graph fingerprint AND the
    build knobs — the cache FILENAME key, so ``ls`` on the cache dir
    maps files to graphs and a changed extract (or changed build
    parameters) changes the name (the embedded copy still guards
    against collisions/tampering by content)."""
    blob = json.dumps({"fp": fingerprint, "params": build_params()},
                      sort_keys=True).encode()
    return hashlib.blake2b(blob, digest_size=10).hexdigest()


def hier_cache_path(fingerprint: Dict) -> Optional[str]:
    """Where this graph's overlay payload caches, or None when caching
    is off (``ROUTEST_HIER_CACHE=0``; a path value overrides the
    per-user secure default). Keyed by a content hash of the same graph
    fingerprint that gates learned leg models, so a changed extract can
    never be served a stale overlay — and the payload format is npz
    with pickling disabled, so a poisoned cache can at worst fail to
    load (callers rebuild)."""
    knob = os.environ.get("ROUTEST_HIER_CACHE", "")
    if knob.lower() in ("0", "off", "false", "no"):
        return None
    if knob:
        base = knob
        try:
            os.makedirs(base, exist_ok=True)
        except OSError:
            return None
    else:
        from routest_tpu.utils.paths import secure_user_cache_dir

        base = secure_user_cache_dir("routest-hier")
        if base is None:
            return None
    key = _fingerprint_digest(fingerprint)
    return os.path.join(base, f"hier-v{_CACHE_VERSION}-{key}.npz")


def hier_min_nodes() -> int:
    """Graphs at or above this node count route through the overlay
    (``ROUTEST_HIER_MIN_NODES`` overrides; 0 disables entirely). Below
    it the flat sweep's ~O(sqrt(N)) iterations are already cheap and
    skipping the precompute keeps serving-default init instant."""
    try:
        return int(os.environ.get("ROUTEST_HIER_MIN_NODES", "4096"))
    except ValueError:
        return 4096
