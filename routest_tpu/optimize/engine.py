"""The routing engine: ORS-shaped results computed on device.

Where the reference makes 2+N HTTPS calls to OpenRouteService per request
(matrix + per-trip directions, ``Flaskr/utils.py:94-175``), this engine
computes the distance matrix and the greedy multi-trip order on the
accelerator and synthesizes the geometry host-side (great-circle polylines
with per-profile road factors — a static road-graph engine is the planned
upgrade, SURVEY.md §7.3 item 5).

Output is wire-ABI compatible with the reference (SURVEY.md Appendix A):
a GeoJSON Feature with ``properties.optimized_order``, ``source``,
``destinations``, ``segments[].steps[]``, ``summary{distance,duration
[,trips]}``, bbox — plus the common annotations (vehicle_type,
driver_name, engine). Errors are ``{"error": "..."}`` dicts with the same
messages the frontend already handles.
"""

from __future__ import annotations

import datetime as dt
import math
from typing import Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from routest_tpu.data import geo
from routest_tpu.optimize.vrp import solve_host, solve_host_batch

ENGINE_TAG = "backend:jax-tpu"

_COMPASS = ("north", "north-east", "east", "south-east",
            "south", "south-west", "west", "north-west")


def _compass(bearing: float) -> str:
    return _COMPASS[int(((bearing + 22.5) % 360.0) // 45.0)]


def _leg_geometry(p0, p1, n_points: int = 24) -> np.ndarray:
    return geo.great_circle_interpolate(p0, p1, n_points)


def _leg_steps(p0, p1, name: str, distance_m: float, duration_s: float,
               wp_start: int, wp_end: int) -> List[Dict]:
    """ORS-shaped step list for one leg: depart instruction + arrival."""
    bearing = geo.bearing_deg(p0, p1)
    return [
        {
            "distance": round(distance_m, 1),
            "duration": round(duration_s, 1),
            "type": 11,  # depart
            "instruction": f"Head {_compass(bearing)} toward {name}",
            "name": "-",
            "way_points": [wp_start, wp_end],
        },
        {
            "distance": 0.0,
            "duration": 0.0,
            "type": 10,  # arrive
            "instruction": f"Arrive at {name}",
            "name": "-",
            "way_points": [wp_end, wp_end],
        },
    ]


def _pickup_hour(pickup_time) -> int:
    """Hour-of-day for leg pricing; mirrors the ETA model's pickup_time
    semantics (``Flaskr/ml.py:30-33``): parse ISO if given, else now."""
    if pickup_time:
        try:
            return dt.datetime.fromisoformat(str(pickup_time)).hour
        except ValueError:
            pass
    return dt.datetime.now().hour


def _stop_name(point: Dict, idx: Optional[int]) -> str:
    if point.get("name"):
        return str(point["name"])
    return "origin" if idx is None else f"stop {idx + 1}"


def _gc_legs(all_points: List[Dict], dist: np.ndarray, speed_mps: float):
    """Default leg provider: great-circle geometry, duration = d/speed."""
    def leg_cost(a: int, b: int):
        return float(dist[a, b]), float(dist[a, b]) / speed_mps

    def leg_geom(a: int, b: int) -> List[List[float]]:
        pa, pb = all_points[a], all_points[b]
        return _leg_geometry((pa["lat"], pa["lon"]),
                             (pb["lat"], pb["lon"])).tolist()

    return leg_cost, leg_geom


def _build_trip_feature_parts(all_points: List[Dict], trip: Sequence[int],
                              leg_cost, leg_geom):
    """One trip (origin → stops → origin): geometry, segments, totals.

    ``leg_cost(a, b) -> (meters, seconds)`` and ``leg_geom(a, b) ->
    [[lon, lat], …]`` abstract the leg provider: great-circle by default,
    road-graph shortest paths when the road router is active.
    """
    node_seq = [0] + [i + 1 for i in trip] + [0]
    coords: List[List[float]] = []
    segments: List[Dict] = []
    total_dist = 0.0
    total_dur = 0.0
    for a, b in zip(node_seq[:-1], node_seq[1:]):
        pa, pb = all_points[a], all_points[b]
        leg_m, leg_s = leg_cost(a, b)
        g = leg_geom(a, b)
        wp_start = len(coords)
        pts = g if not coords else g[1:]
        coords.extend(pts)
        wp_end = len(coords) - 1
        name = _stop_name(pb, b - 1 if b > 0 else None)
        segments.append(
            {
                "distance": round(leg_m, 1),
                "duration": round(leg_s, 1),
                "steps": _leg_steps((pa["lat"], pa["lon"]), (pb["lat"], pb["lon"]),
                                    name, leg_m, leg_s, wp_start, wp_end),
            }
        )
        total_dist += leg_m
        total_dur += leg_s
    return coords, segments, total_dist, total_dur


def _parse_problem(input_data: dict) -> dict:
    """Validate one optimize-route request body → either ``{"error"}``
    or the parsed problem dict (shared by the single and batch paths so
    a malformed item fails identically on both)."""
    if not input_data or not input_data.get("destination_points"):
        return {"error": "no destination points specified."}
    if not input_data.get("source_point"):
        return {"error": "no source point specified."}

    driver_details = input_data.get("driver_details") or {}
    if not isinstance(driver_details, dict):
        return {"error": "invalid driver_details: must be an object"}
    vehicle_type = driver_details.get("vehicle_type")
    vehicle_type = ((vehicle_type if isinstance(vehicle_type, str) else "car")
                    or "car").lower().strip()
    profile = geo.profile_for_vehicle(vehicle_type)

    source = input_data["source_point"]
    destinations = input_data["destination_points"]
    if not isinstance(destinations, (list, tuple)):
        return {"error": "invalid coordinates: each point needs numeric lat/lon"}

    try:
        cap = float(driver_details.get("vehicle_capacity", 9e12))
        max_dist = float(driver_details.get("maximum_distance", 9e12))
    except (TypeError, ValueError):
        return {"error": "invalid driver_details: vehicle_capacity/maximum_distance must be numeric"}
    # Non-finite constraints would make the solver's feasibility mask
    # vacuous and its while_loop spin forever on device (NaN compares
    # False both ways; json.loads happily parses NaN/Infinity) — reject
    # up front, before any item reaches a (possibly shared batch) solve.
    if not (math.isfinite(cap) and math.isfinite(max_dist)):
        return {"error": "invalid driver_details: vehicle_capacity/maximum_distance must be finite"}

    all_points = [source] + list(destinations)
    try:
        latlon = np.asarray([[float(p["lat"]), float(p["lon"])] for p in all_points],
                            dtype=np.float32)
    except (KeyError, TypeError, ValueError):
        return {"error": "invalid coordinates: each point needs numeric lat/lon"}
    if not np.isfinite(latlon).all():
        return {"error": "invalid coordinates: each point needs numeric lat/lon"}
    # Validate top_k UP FRONT: the same malformed value must fail the
    # same way on every path, before any matrix/solve work is spent.
    try:
        top_k = int(input_data.get("top_k", 0) or 0)
    except (TypeError, ValueError, OverflowError):  # int(inf) overflows
        return {"error": "top_k must be an integer"}
    try:
        demands = np.asarray(
            [float(p.get("payload", 0) or 0) for p in destinations],
            dtype=np.float32)
    except (TypeError, ValueError, AttributeError):
        return {"error": "invalid destination payload: must be numeric"}
    if not np.isfinite(demands).all():
        return {"error": "invalid destination payload: must be finite"}

    return {
        "source": source,
        "destinations": destinations,
        "all_points": all_points,
        "latlon": latlon,
        "demands": demands,
        "driver_details": driver_details,
        "vehicle_type": vehicle_type,
        "road_factor": geo.PROFILE_ROAD_FACTOR[profile],
        "speed": geo.PROFILE_SPEED_MPS[profile],
        "cap": cap,
        "max_dist": max_dist,
        "top_k": top_k,
        "refine": bool(input_data.get("refine")),
        "use_road": bool(input_data.get("road_graph")),
        "pickup_time": input_data.get("pickup_time"),
    }


def optimize_route(input_data: dict) -> dict:
    """Drop-in equivalent of the reference's optimizer entry point
    (``Flaskr/utils.py:10-48``): dict in, GeoJSON Feature (or error) out."""
    p = _parse_problem(input_data)
    if "error" in p:
        return p
    driver_details = p["driver_details"]
    vehicle_type = p["vehicle_type"]
    road_factor = p["road_factor"]
    speed = p["speed"]
    source = p["source"]
    destinations = p["destinations"]
    all_points = p["all_points"]
    latlon = p["latlon"]
    cap = p["cap"]
    max_dist = p["max_dist"]
    top_k = p["top_k"]

    # Leg provider: great-circle × road factor by default; with
    # {"road_graph": true} (additive ABI) legs become true shortest paths
    # over the on-device road network — street-following geometry,
    # congestion-model durations (optimize/road_router.py).
    use_road = p["use_road"]
    legs = None
    if use_road:
        from routest_tpu.optimize.road_router import default_router

        car_speed = geo.PROFILE_SPEED_MPS[geo.profile_for_vehicle("car")]
        legs = default_router().route_legs(
            latlon, car_speed / speed,
            hour=_pickup_hour(p["pickup_time"]))
        dist = legs.dist_m
        leg_cost, leg_geom = _road_leg_fns(legs)
    else:
        dist = np.asarray(geo.distance_matrix_m(jnp.asarray(latlon), road_factor))
        leg_cost, leg_geom = _gc_legs(all_points, dist, speed)

    if len(destinations) == 1:
        return _finish_point_to_point(p, leg_cost, leg_geom, legs)

    # Additive ABI: {"refine": true} runs 2-opt on the greedy order —
    # strictly shorter or equal routes, same response shape. Default off
    # to keep exact reference-greedy semantics.
    sol = solve_host(dist, p["demands"], cap, max_dist, refine=p["refine"])
    return _assemble_multi(p, sol, dist, leg_cost, leg_geom, legs)


MAX_MATRIX_POINTS = 64


def travel_matrix(input_data: dict) -> dict:
    """S×D travel matrix — the ORS capability the reference RENTS.

    The reference posts its waypoints to openrouteservice's
    ``distance_matrix`` per optimize request
    (``/root/reference/backend/route_optimizer_twx2/Flaskr/utils.py:97-103``)
    but never exposes the capability to its own callers; here it is a
    first-class API. ``{"points": [{"lat","lon"}, …]}`` → distances and
    durations between every pair (or the ``sources``/``destinations``
    index subsets, ORS-style). With ``road_graph: true`` the matrix is
    true shortest paths over the street network priced by the live leg
    models (learned congestion at ``pickup_time``'s hour); otherwise
    great-circle × the vehicle profile's road factor. Unreachable pairs
    come back ``None``. One batched device solve either way.
    """
    points = input_data.get("points") if isinstance(input_data, dict) else None
    if not isinstance(points, (list, tuple)) or len(points) < 2:
        return {"error": "points must be a list of at least 2 {lat, lon}"}
    if len(points) > MAX_MATRIX_POINTS:
        return {"error": f"too many points (max {MAX_MATRIX_POINTS})"}
    try:
        latlon = np.asarray([[float(p["lat"]), float(p["lon"])]
                             for p in points], dtype=np.float32)
    except (KeyError, TypeError, ValueError):
        return {"error": "invalid coordinates: each point needs numeric lat/lon"}
    if not np.isfinite(latlon).all():
        return {"error": "invalid coordinates: each point needs numeric lat/lon"}

    def _subset(key):
        idx = input_data.get(key)
        if idx is None:
            return list(range(len(points))), None
        if not isinstance(idx, (list, tuple)) or not idx:
            return None, {"error": f"{key} must be a non-empty index list"}
        if len(idx) > MAX_MATRIX_POINTS:
            # The points cap must bound the OUTPUT too: unbounded index
            # lists would let a few-KB body demand a giant S×D response.
            return None, {"error": f"too many {key} (max {MAX_MATRIX_POINTS})"}
        try:
            idx = [int(i) for i in idx]
        except (TypeError, ValueError):
            return None, {"error": f"{key} must be a non-empty index list"}
        if any(i < 0 or i >= len(points) for i in idx):
            return None, {"error": f"{key} index out of range"}
        return idx, None

    sources, err = _subset("sources")
    if err:
        return err
    dests, err = _subset("destinations")
    if err:
        return err

    vehicle_type = "car"
    vt = input_data.get("vehicle_type")
    if isinstance(vt, str) and vt.strip():
        vehicle_type = vt.lower().strip()
    profile = geo.profile_for_vehicle(vehicle_type)
    speed = geo.PROFILE_SPEED_MPS[profile]

    if input_data.get("road_graph"):
        from routest_tpu.optimize.road_router import default_router

        car_speed = geo.PROFILE_SPEED_MPS[geo.profile_for_vehicle("car")]
        # Solve only the waypoints the response can reference: with
        # ``sources``/``destinations`` subsets, the solve's row count is
        # |sources ∪ dests|, not the full point list — each row is an
        # independent one-source-vs-all-destinations device solve, so
        # the subset's values are bitwise the full matrix's. The solve
        # itself rides the router's batched path (shared dispatches
        # with concurrent request_route traffic) and the route
        # fastlane.
        need = sorted(set(sources) | set(dests))
        pos = {p: k for k, p in enumerate(need)}
        legs = default_router().route_legs(
            latlon[need], car_speed / speed,
            hour=_pickup_hour(input_data.get("pickup_time")))
        dist_sub = legs.dist_m
        durm = legs.duration_matrix()   # one device dispatch, no walks
        dist = np.full((len(points), len(points)), np.inf)
        dist[np.ix_(need, need)] = dist_sub
        durations = [[float(durm[pos[i], pos[j]]) for j in dests]
                     for i in sources]
        meta = {"road_graph": True, "leg_cost_model": legs.cost_model}
    else:
        dist = np.asarray(geo.distance_matrix_m(
            jnp.asarray(latlon), geo.PROFILE_ROAD_FACTOR[profile]))
        durations = [[float(dist[i, j]) / speed for j in dests]
                     for i in sources]
        meta = {"road_graph": False, "leg_cost_model": "haversine"}

    def _clean(v):
        return round(float(v), 1) if math.isfinite(v) else None

    return {
        "distances_m": [[_clean(dist[i, j]) for j in dests]
                        for i in sources],
        "durations_s": [[_clean(durations[si][dj])
                         for dj in range(len(dests))]
                        for si in range(len(sources))],
        "sources": sources,
        "destinations": dests,
        "vehicle_type": vehicle_type,
        **meta,
    }


def _road_leg_fns(legs) -> tuple:
    """(leg_cost, leg_geom) adapters over one :class:`RoadLegs` — the
    ONE encoding of its accessor contract, shared by the single and
    batch paths. Costs avoid polyline construction entirely; geometry
    is built only for the legs a response actually renders."""
    return (legs.cost, lambda a, b: legs.leg(a, b)[2])


def _finish_point_to_point(p: dict, leg_cost, leg_geom, legs) -> dict:
    """Single-destination finishing shared by the single path and the
    batch path. Same pricer precedence as multi-stop: the transformer
    (when an artifact serves this graph) re-prices the out-and-back
    pair so point-to-point and multi-stop responses never disagree on
    ``leg_cost_model`` for the same deployment. ``legs`` is the
    problem's :class:`RoadLegs` (road-graph items) or None."""
    use_road = legs is not None
    p2p_model = None
    if use_road:
        rep = legs.reprice_trips([[0]])
        if rep:
            base_cost = leg_cost

            def leg_cost(a: int, b: int, _base=base_cost, _r=rep):
                meters, seconds = _base(a, b)
                return meters, _r.get((a, b), seconds)

            p2p_model = "transformer"
    feature = _point_to_point(p["source"], p["destinations"][0],
                              p["all_points"], leg_cost, leg_geom,
                              p["driver_details"], p["vehicle_type"],
                              p["cap"], p["max_dist"], use_road)
    if use_road and "error" not in feature:
        feature["properties"]["leg_cost_model"] = (
            p2p_model or legs.cost_model)
    return feature


def _assemble_multi(p: dict, sol: dict, dist, leg_cost, leg_geom,
                    legs) -> dict:
    """Solved multi-stop problem → GeoJSON Feature (host-side geometry,
    segments, summary, top-k alternatives). Shared by the single path
    and ``optimize_route_batch``."""
    source = p["source"]
    destinations = p["destinations"]
    all_points = p["all_points"]
    driver_details = p["driver_details"]
    vehicle_type = p["vehicle_type"]
    speed = p["speed"]
    max_dist = p["max_dist"]
    top_k = p["top_k"]
    use_road = p["use_road"]
    refine = p["refine"]
    if sol["unroutable"]:
        which = ", ".join(str(i) for i in sol["unroutable"])
        return {"error": f"stops not routable under constraints (indices: {which})"}

    # Route-context pricing: once the order is SOLVED, the transformer
    # (when an artifact serves this graph) re-prices each trip's whole
    # edge sequence in one forward — leg durations gain tour context no
    # per-edge pricer can express. Distances and geometry stay from the
    # base provider; empty dict ⇒ base pricing throughout.
    repriced: Dict = {}
    if legs is not None:
        repriced = legs.reprice_trips(sol["trips"])
        if repriced:
            base_cost = leg_cost

            def leg_cost(a: int, b: int, _base=base_cost, _r=repriced):
                meters, seconds = _base(a, b)
                return meters, _r.get((a, b), seconds)

    coords: List[List[float]] = []
    segments: List[Dict] = []
    total_dist = 0.0
    total_dur = 0.0
    for trip in sol["trips"]:
        c, s, d, t = _build_trip_feature_parts(all_points, trip,
                                               leg_cost, leg_geom)
        coords.extend(c)
        segments.extend(s)
        total_dist += d
        total_dur += t
    if not (math.isfinite(total_dist) and math.isfinite(total_dur)):
        # A leg the solver accepted turned out unwalkable (e.g. a
        # one-way-disconnected caller graph). Error out rather than emit
        # `Infinity`, which is not valid JSON.
        return {"error": "stops not routable over the road graph"}

    lons = [c[0] for c in coords]
    lats = [c[1] for c in coords]
    feature = {
        "bbox": [min(lons), min(lats), max(lons), max(lats)],
        "type": "Feature",
        "geometry": {"type": "LineString", "coordinates": coords},
        "properties": {
            "source": source,
            "destinations": list(destinations),
            "optimized_order": sol["optimized_order"],
            "segments": segments,
            "summary": {
                "distance": round(total_dist, 1),
                "duration": round(total_dur, 1),
                "trips": sol["n_trips"],
            },
        },
    }
    if refine:
        feature["properties"]["refined"] = True

    # Additive ABI: {"top_k": N} returns up to N ALTERNATIVE visit orders
    # (BASELINE config 3 — top-k candidate-path ranking — on the request
    # path, not just the bench). Candidates are scored on device over the
    # distance matrix (perturbed-greedy pool + this solution as seed),
    # then the winners are re-priced with the live leg provider — COST
    # ONLY, no polyline construction — so alternative distances/durations
    # are exactly comparable to the main summary without its geometry
    # work. The shipped order itself is excluded (these are alternatives,
    # not echoes). Single-trip solutions only: reordering within one trip
    # keeps the load identical, so every alternative that fits
    # maximum_distance is feasible by construction.
    if top_k > 1 and sol["n_trips"] == 1 and len(destinations) >= 2:
        from routest_tpu.optimize.ranking import rank_routes

        price = legs.cost if use_road else leg_cost
        k_want = min(top_k, 10)
        # Over-request candidates: the seed order eats one slot, and on
        # the symmetric great-circle path EVERY tour occupies two ranked
        # slots (its reversal scores identically), so k+2 would
        # under-fill the response — verified: 4 stops, top_k=5 returned
        # only 3 of 11 distinct tours.
        k_ask = (k_want + 2) if use_road else (2 * k_want + 2)
        ranked = rank_routes(
            dist, k=k_ask, speed_mps=speed, max_candidates=2048,
            greedy_order=np.asarray(sol["optimized_order"], np.int32))
        main_key = tuple(int(i) for i in sol["optimized_order"])
        seen = {main_key}
        if not use_road:  # great-circle matrix is symmetric; a closed
            seen.add(main_key[::-1])  # tour costs the same reversed
        alternatives = []
        for order_alt in ranked.orders:
            if len(alternatives) >= k_want:
                break
            key = tuple(int(i) for i in order_alt)
            if key in seen:
                continue
            seen.add(key)
            if not use_road:
                # reversal twins waste slots ONLY when costs are
                # symmetric — road graphs respect one-ways (directed)
                seen.add(key[::-1])
            seq = [0] + [int(i) + 1 for i in order_alt] + [0]
            alt_m = alt_s = 0.0
            for a, b in zip(seq[:-1], seq[1:]):
                leg_m, leg_s = price(a, b)
                alt_m += leg_m
                alt_s += leg_s
            if not math.isfinite(alt_m) or alt_m > max_dist:
                continue
            alternatives.append({
                "optimized_order": [int(i) for i in order_alt],
                "distance": round(alt_m, 1),
                "duration": round(alt_s, 1),
            })
        if repriced and alternatives:
            # The main summary is transformer-priced; alternatives must
            # be priced by the SAME model or their durations are not
            # comparable (a base-priced "alternative" could look faster
            # purely from pricer mismatch). One batched forward covers
            # every candidate.
            rep_durs = legs.reprice_orders(
                [a["optimized_order"] for a in alternatives])
            for alt, dur in zip(alternatives, rep_durs):
                if dur is not None and math.isfinite(dur):
                    alt["duration"] = round(dur, 1)
        feature["properties"]["alternatives"] = alternatives

    if use_road:
        feature["properties"]["road_graph"] = True
        # Which pricer produced the durations: "transformer" (route-
        # context leg pricing), "gnn" (learned per-edge congestion), or
        # "freeflow" physics — additive ABI for clients and tests to
        # confirm learned costs are live.
        feature["properties"]["leg_cost_model"] = (
            "transformer" if repriced else legs.cost_model)
    _annotate(feature, driver_details, vehicle_type)
    return feature


MAX_BATCH_PROBLEMS = 256

# (B, P, 2) points + (B,) road factors → (B, P, P) matrices in one call.
_distance_matrix_batch = jax.jit(jax.vmap(geo.distance_matrix_m))


def optimize_route_batch(items) -> list:
    """Solve MANY optimize-route requests in one vmapped device call.

    Additive capability (the reference optimizes one problem per HTTP
    request, each costing it an ORS matrix round trip —
    ``Flaskr/utils.py:94-109``): one batched haversine builds every
    problem's distance matrix, then all multi-stop problems run the
    greedy solver (plus refiners when requested) as one ``(B, P+1,
    P+1)`` device program via ``solve_host_batch``. Geometry/segment
    assembly stays host-side per item, identical to the single path
    (shared ``_assemble_multi``).

    Per-item errors are returned in place — one malformed problem never
    poisons the batch. ``road_graph`` items batch too: every road
    problem's waypoints concatenate into shared shortest-path solves
    (``RoadRouter.route_legs_batch`` — the solver's source axis is
    batched by design, so B problems cost a few wide solves instead of
    B narrow ones). ``top_k > 1`` items are rejected here (candidate
    ranking is a per-problem device program; the single endpoint
    serves them). Point-to-point items are priced host-side directly.
    """
    if not isinstance(items, list) or not items:
        return [{"error": "items must be a non-empty list"}]
    if len(items) > MAX_BATCH_PROBLEMS:
        # One error PER item: library callers zip results against their
        # inputs (the HTTP layer pre-checks, so only direct callers ever
        # see this), and a single-element list would silently misalign.
        return [{"error": f"batch too large (max {MAX_BATCH_PROBLEMS} "
                          f"problems)"} for _ in items]
    results: list = [None] * len(items)
    solve: list = []  # (index, parsed, dist, leg_cost, leg_geom, legs)

    for i, item in enumerate(items):
        p = _parse_problem(item if isinstance(item, dict) else {})
        if "error" in p:
            results[i] = p
            continue
        # top_k == 1 is a no-op on the single path (alternatives only
        # trigger above 1) — reject only what genuinely needs a
        # per-problem device program.
        if p["top_k"] > 1:
            results[i] = {"error": "top_k is a per-problem feature; "
                                   "use /api/optimize_route"}
            continue
        solve.append([i, p, None, None, None, None])

    # Road-graph problems: ONE grouped shortest-path solve set builds
    # every problem's true street-network matrix (identical numerics to
    # the single path — source rows are independent). A router failure
    # errors the road items in place, never the whole batch.
    road = [s for s in solve if s[1]["use_road"]]
    if road:
        from routest_tpu.optimize.road_router import default_router

        car_speed = geo.PROFILE_SPEED_MPS[geo.profile_for_vehicle("car")]
        try:
            legs_list = default_router().route_legs_batch([
                (s[1]["latlon"], car_speed / s[1]["speed"],
                 _pickup_hour(s[1]["pickup_time"])) for s in road])
        except Exception as e:  # mirror the per-item error contract
            for s in road:
                results[s[0]] = {"error": f"road graph unavailable: "
                                          f"{type(e).__name__}: {e}"}
            solve = [s for s in solve if not s[1]["use_road"]]
        else:
            for s, legs in zip(road, legs_list):
                s[2] = legs.dist_m
                s[3], s[4] = _road_leg_fns(legs)
                s[5] = legs

    # ONE batched haversine builds every remaining problem's distance
    # matrix (points padded with origin copies; the pad region is never
    # read — solve_host_batch re-masks it and assembly slices the real
    # block).
    gc = [s for s in solve if not s[1]["use_road"]]
    if gc:
        max_pts = max(len(s[1]["all_points"]) for s in gc)
        pts_pad = 1 << max(0, (max_pts - 1)).bit_length()
        latlon_b = np.zeros((len(gc), pts_pad, 2), np.float32)
        factor_b = np.zeros((len(gc),), np.float32)
        for j, s in enumerate(gc):
            ll = s[1]["latlon"]
            latlon_b[j] = ll[0]  # origin copies fill the pad
            latlon_b[j, : len(ll)] = ll
            factor_b[j] = s[1]["road_factor"]
        mats = np.asarray(_distance_matrix_batch(
            jnp.asarray(latlon_b), jnp.asarray(factor_b)))
        for j, s in enumerate(gc):
            n_pts = len(s[1]["all_points"])
            s[2] = mats[j, :n_pts, :n_pts]
            s[3], s[4] = _gc_legs(s[1]["all_points"], s[2], s[1]["speed"])

    # Point-to-point items price host-side directly (one leg each).
    still: list = []
    for s in solve:
        i, p, dist, leg_cost, leg_geom, legs = s
        if len(p["destinations"]) == 1:
            results[i] = _finish_point_to_point(p, leg_cost, leg_geom, legs)
        else:
            still.append(s)
    solve = still

    # One batched device solve per refine flavor (refiners change the
    # program; two compiled variants max).
    for flavor in (False, True):
        group = [s for s in solve if s[1]["refine"] is flavor]
        if not group:
            continue
        sols = solve_host_batch(
            [g[2] for g in group],
            [g[1]["demands"] for g in group],
            [g[1]["cap"] for g in group],
            [g[1]["max_dist"] for g in group],
            refine=flavor,
        )
        for (i, p, dist, leg_cost, leg_geom, legs), sol in zip(group, sols):
            results[i] = _assemble_multi(p, sol, dist, leg_cost, leg_geom,
                                         legs)
    return results


def _point_to_point(source, destination, all_points,
                    leg_cost, leg_geom, driver_details, vehicle_type,
                    cap, max_dist, use_road: bool = False) -> dict:
    """Single-destination path with the reference's feasibility semantics
    (``Flaskr/utils.py:53-82``): payload > capacity and distance >
    maximum_distance produce the same joined error strings."""
    d_m = leg_cost(0, 1)[0]
    payload = float(destination.get("payload", 0) or 0)
    errors = []
    if payload > cap:
        errors.append("payload exceeds vehicle capacity")
    if not math.isfinite(d_m):
        errors.append("stops not routable over the road graph")
    elif d_m > max_dist:
        errors.append("route distance exceeds maximum_distance")
    if errors:
        return {"error": " | ".join(errors)}

    coords, segments, total_dist, total_dur = _build_trip_feature_parts(
        all_points, [0], leg_cost, leg_geom
    )
    # Reference point-to-point is one-way (no return leg): use only the
    # outbound segment.
    out_seg = segments[0]
    out_coords = coords[: out_seg["steps"][0]["way_points"][1] + 1]
    lons = [c[0] for c in out_coords]
    lats = [c[1] for c in out_coords]
    feature = {
        "bbox": [min(lons), min(lats), max(lons), max(lats)],
        "type": "Feature",
        "geometry": {"type": "LineString", "coordinates": out_coords},
        "properties": {
            "segments": [out_seg],
            "summary": {
                "distance": round(out_seg["distance"], 1),
                "duration": round(out_seg["duration"], 1),
            },
            "way_points": [0, len(out_coords) - 1],
            "optimized_order": [0],
            "source": source,
            "destinations": [destination],
        },
    }
    if use_road:
        feature["properties"]["road_graph"] = True
    _annotate(feature, driver_details, vehicle_type)
    return feature


def _annotate(feature: dict, driver_details: dict, vehicle_type: str) -> None:
    """Common properties the frontend reads (``Flaskr/utils.py:196-201``)."""
    p = feature.setdefault("properties", {})
    p["vehicle_type"] = vehicle_type
    p["driver_name"] = driver_details.get("driver_name")
    p["engine"] = ENGINE_TAG
