"""Route-level fastlane: epoch-keyed solved-route cache + singleflight.

The ETA fast lane (``serve/fastlane.py``) proved the pattern on
predictions: Zipf-skewed traffic re-asks the same questions, and the
cheapest answer is the one already computed. Routing traffic has the
same shape — loadgen measured a 0.97 hit rate on ETA keys over the
same OD vocabulary — but every repeated ``request_route`` still paid a
full snap + device solve + predecessor fetch. This module caches the
SOLVED leg set (the :class:`~routest_tpu.optimize.road_router.RoadLegs`
behind a request) keyed by::

    (waypoint fingerprint, time_scale, hour,
     live metric epoch, road-model generation)

- **Exact invalidation, no TTL races**: the live-metric epoch
  (``routest_tpu.live.metric_epoch`` — bumped by every
  ``install_live_metric`` flip) and the router's model generation
  (bumped by every verified road-GNN swap) are IN the key, so no
  cached route can outlive either flip — the same coherency contract
  the prediction cache carries (docs/PERFORMANCE.md "Cache coherency").
  TTL is a freshness backstop on top, not the correctness mechanism.
- **Byte-budgeted LRU**: a cached solve pins (M, N) predecessor and
  distance rows — megabytes per entry at metro scale — so the budget
  is bytes, not entries (``ROUTEST_ROUTE_CACHE_MB``).
- **Singleflight**: N concurrent identical OD requests cost ONE solve;
  followers park on an event and read the leader's legs (the PR-4
  pattern). A leader failure propagates to every waiter and caches
  nothing.
"""

from __future__ import annotations

import os
import threading
import time
from collections import OrderedDict
from typing import Dict, Optional, Tuple

_metrics = None


def _cache_metrics():
    global _metrics
    if _metrics is None:
        from routest_tpu.obs import get_registry

        reg = get_registry()
        _metrics = {
            "hits": reg.counter(
                "rtpu_route_cache_hits_total",
                "Route problems served from the route fastlane."),
            "misses": reg.counter(
                "rtpu_route_cache_misses_total",
                "Route problems that had to be solved."),
            "coalesced": reg.counter(
                "rtpu_route_cache_coalesced_total",
                "Route problems served by waiting on another request's "
                "in-flight solve (singleflight)."),
            "evictions": reg.counter(
                "rtpu_route_cache_evictions_total",
                "Route-cache entries evicted by the byte-budget LRU."),
            "bytes": reg.gauge(
                "rtpu_route_cache_bytes", "Route-cache resident bytes."),
            "entries": reg.gauge(
                "rtpu_route_cache_entries", "Live route-cache entries."),
        }
    return _metrics


def route_cache_config() -> Tuple[bool, int, float]:
    """(enabled, byte budget, ttl seconds) from the env knobs
    (``ROUTEST_ROUTE_CACHE`` on/off, ``ROUTEST_ROUTE_CACHE_MB``,
    ``ROUTEST_ROUTE_CACHE_TTL_S``)."""
    raw = os.environ.get("ROUTEST_ROUTE_CACHE", "1").strip().lower()
    enabled = raw not in ("0", "off", "false", "no")
    try:
        budget_mb = float(os.environ.get("ROUTEST_ROUTE_CACHE_MB", "256"))
    except ValueError:
        budget_mb = 256.0
    try:
        ttl_s = float(os.environ.get("ROUTEST_ROUTE_CACHE_TTL_S", "300"))
    except ValueError:
        ttl_s = 300.0
    return enabled, int(budget_mb * 1e6), ttl_s


class _Flight:
    """One in-progress solve other threads can wait on."""

    __slots__ = ("event", "value", "error")

    def __init__(self) -> None:
        self.event = threading.Event()
        self.value = None
        self.error: Optional[BaseException] = None


class RouteCache:
    """Byte-budgeted LRU + TTL + singleflight over solved leg sets.

    The protocol is split (unlike ``FastLane.predict``) because the
    router solves MANY problems per call and wants cache misses from
    one request batch grouped into shared device solves:

    - :meth:`lookup` classifies a key → ``("hit", legs)``,
      ``("wait", flight)`` or ``("lead", flight)``;
    - the caller solves every lead, then :meth:`commit`\\ s (or
      :meth:`abort`\\ s on failure);
    - ``("wait", flight)`` resolves with :meth:`wait`.
    """

    WAIT_HARD_CAP_S = 120.0

    def __init__(self, budget_bytes: int = 256_000_000,
                 ttl_s: float = 300.0) -> None:
        self.budget_bytes = int(budget_bytes)
        self.ttl_s = float(ttl_s)
        self._lock = threading.Lock()
        # key -> (stored_monotonic, nbytes, legs)
        self._cache: "OrderedDict[Tuple, Tuple[float, int, object]]" = \
            OrderedDict()
        self._bytes = 0
        self._inflight: Dict[Tuple, _Flight] = {}
        self._hits = self._misses = self._coalesced = self._evictions = 0

    # ── bookkeeping ───────────────────────────────────────────────────

    def stats(self) -> dict:
        with self._lock:
            total = self._hits + self._misses + self._coalesced
            return {
                "entries": len(self._cache),
                "bytes": self._bytes,
                "budget_bytes": self.budget_bytes,
                "ttl_s": self.ttl_s,
                "hits": self._hits,
                "misses": self._misses,
                "coalesced": self._coalesced,
                "evictions": self._evictions,
                "hit_rate": round((self._hits + self._coalesced)
                                  / total, 4) if total else 0.0,
            }

    def invalidate(self) -> None:
        """Drop everything (hygiene only — correctness comes from the
        epoch/generation halves of the key)."""
        with self._lock:
            self._cache.clear()
            self._bytes = 0
            m = _cache_metrics()
            m["bytes"].set(0)
            m["entries"].set(0)

    # ── the protocol ──────────────────────────────────────────────────

    def lookup(self, key: Tuple):
        """→ ("hit", legs) | ("wait", flight) | ("lead", flight)."""
        m = _cache_metrics()
        now = time.monotonic()
        with self._lock:
            hit = self._cache.get(key)
            if hit is not None:
                stored, nbytes, legs = hit
                if self.ttl_s <= 0 or now - stored <= self.ttl_s:
                    self._cache.move_to_end(key)
                    self._hits += 1
                    m["hits"].inc()
                    return "hit", legs
                del self._cache[key]
                self._bytes -= nbytes
            flight = self._inflight.get(key)
            if flight is not None:
                self._coalesced += 1
                m["coalesced"].inc()
                return "wait", flight
            flight = _Flight()
            self._inflight[key] = flight
            self._misses += 1
            m["misses"].inc()
            return "lead", flight

    def commit(self, key: Tuple, legs, nbytes: int) -> None:
        """Leader publishes its solved legs; waiters wake; the LRU
        evicts from the cold end until the byte budget holds. Entries
        bigger than the whole budget publish to waiters but skip the
        cache (they would evict everything for one key)."""
        m = _cache_metrics()
        now = time.monotonic()
        with self._lock:
            flight = self._inflight.pop(key, None)
            if nbytes <= self.budget_bytes:
                old = self._cache.pop(key, None)
                if old is not None:
                    self._bytes -= old[1]
                self._cache[key] = (now, int(nbytes), legs)
                self._bytes += int(nbytes)
                self._evict_locked()
            m["bytes"].set(self._bytes)
            m["entries"].set(len(self._cache))
        if flight is not None:
            flight.value = legs
            flight.event.set()

    def _evict_locked(self) -> None:
        m = _cache_metrics()
        while self._bytes > self.budget_bytes and self._cache:
            _, (_, nb, _) = self._cache.popitem(last=False)
            self._bytes -= nb
            self._evictions += 1
            m["evictions"].inc()

    def abort(self, key: Tuple, error: BaseException) -> None:
        """Leader failed: nothing cached, every waiter gets the error,
        the next request solves fresh."""
        with self._lock:
            flight = self._inflight.pop(key, None)
        if flight is not None:
            flight.error = error
            flight.event.set()

    def wait(self, flight: _Flight, deadline_s: Optional[float] = None):
        budget = self.WAIT_HARD_CAP_S if deadline_s is None \
            else min(self.WAIT_HARD_CAP_S, deadline_s)
        if not flight.event.wait(budget):
            raise TimeoutError(
                "route-fastlane wait exceeded the request budget")
        if flight.error is not None:
            raise flight.error
        return flight.value
