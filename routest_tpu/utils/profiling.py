"""Profiling: per-request latency stats + JAX device traces.

The reference's only timing is wall-clock deltas inside health probes
(``Flaskr/routes.py:285,300,331`` — SURVEY.md §5.1). This module adds:

- ``RequestStats``: per-route latency view kept for the serving layer's
  existing ``/api/metrics`` JSON shape, now backed by the unified
  metric types in ``routest_tpu/obs/registry.py`` (a log-bucket
  histogram + error counter per route) instead of a private reservoir —
  one implementation of "how do we measure a latency" process-wide;
- ``device_trace``: context manager around ``jax.profiler`` writing a
  TensorBoard-loadable trace of device execution (attachable to a
  sampled request span via ``obs.export.maybe_device_trace``).
"""

from __future__ import annotations

import contextlib
import time
from typing import Dict, Iterator, Optional

from routest_tpu.obs.registry import MetricsRegistry


class RequestStats:
    """Per-route latency/error accumulators with the historical snapshot
    shape (count, errors, mean_ms, p50/p95/p99_ms). Each instance owns a
    private :class:`MetricsRegistry`, so per-``App`` isolation holds
    (test apps must not see each other's counts); pass ``registry`` to
    aggregate several components into one.

    Percentiles are interpolated from the fixed log-scale buckets —
    coarser than the old 512-sample reservoir per route, but mergeable
    across processes and strictly bounded in memory.
    """

    def __init__(self, registry: Optional[MetricsRegistry] = None) -> None:
        self.registry = registry or MetricsRegistry()
        self._hist = self.registry.histogram(
            "request_duration_seconds", "Per-route request latency.",
            ("route",))
        self._errors = self.registry.counter(
            "request_errors_total", "Per-route server errors (>=500).",
            ("route",))
        self.started = time.time()

    @contextlib.contextmanager
    def measure(self, route: str) -> Iterator[None]:
        t0 = time.perf_counter()
        error = False
        try:
            yield
        except Exception:
            error = True
            raise
        finally:
            self.add(route, time.perf_counter() - t0, error)

    def add(self, route: str, seconds: float, error: bool = False) -> None:
        self._hist.labels(route=route).observe(seconds)
        if error:
            self._errors.labels(route=route).inc()

    def snapshot(self) -> Dict:
        routes: Dict[str, Dict] = {}
        errors = {key[0]: c.value for key, c in self._errors.items()}
        for key, h in self._hist.items():
            route = key[0]
            if not h.count:
                routes[route] = {"count": 0}
                continue
            routes[route] = {
                "count": h.count,
                "errors": int(errors.get(route, 0)),
                "mean_ms": round(1000.0 * h.sum / h.count, 3),
                "p50_ms": round(1000.0 * h.quantile(0.50), 3),
                "p95_ms": round(1000.0 * h.quantile(0.95), 3),
                "p99_ms": round(1000.0 * h.quantile(0.99), 3),
            }
        return {
            "uptime_s": round(time.time() - self.started, 1),
            "routes": routes,
        }


@contextlib.contextmanager
def device_trace(log_dir: str) -> Iterator[None]:
    """TensorBoard-loadable device trace (xplane) around a code region."""
    import jax

    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
