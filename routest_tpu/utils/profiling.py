"""Profiling: per-request latency stats + JAX device traces.

The reference's only timing is wall-clock deltas inside health probes
(``Flaskr/routes.py:285,300,331`` — SURVEY.md §5.1). This module adds:

- ``RequestStats``: lock-protected per-route latency accumulators
  (count, errors, mean, p50/p95/p99 from a bounded reservoir) that the
  serving layer samples into and ``/api/metrics`` reports;
- ``device_trace``: context manager around ``jax.profiler`` writing a
  TensorBoard-loadable trace of device execution.
"""

from __future__ import annotations

import contextlib
import random
import threading
import time
from typing import Dict, Iterator, List


class _RouteStats:
    __slots__ = ("count", "errors", "total_s", "reservoir", "_rng")
    RESERVOIR = 512

    def __init__(self) -> None:
        self.count = 0
        self.errors = 0
        self.total_s = 0.0
        self.reservoir: List[float] = []
        self._rng = random.Random(0)

    def add(self, seconds: float, error: bool) -> None:
        self.count += 1
        self.errors += int(error)
        self.total_s += seconds
        if len(self.reservoir) < self.RESERVOIR:
            self.reservoir.append(seconds)
        else:  # reservoir sampling keeps percentiles unbiased over time
            j = self._rng.randrange(self.count)
            if j < self.RESERVOIR:
                self.reservoir[j] = seconds

    def summary(self) -> Dict:
        if not self.count:
            return {"count": 0}
        ordered = sorted(self.reservoir)

        def pct(p: float) -> float:
            return ordered[min(len(ordered) - 1, int(p * len(ordered)))]

        return {
            "count": self.count,
            "errors": self.errors,
            "mean_ms": round(1000.0 * self.total_s / self.count, 3),
            "p50_ms": round(1000.0 * pct(0.50), 3),
            "p95_ms": round(1000.0 * pct(0.95), 3),
            "p99_ms": round(1000.0 * pct(0.99), 3),
        }


class RequestStats:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._routes: Dict[str, _RouteStats] = {}
        self.started = time.time()

    @contextlib.contextmanager
    def measure(self, route: str) -> Iterator[None]:
        t0 = time.perf_counter()
        error = False
        try:
            yield
        except Exception:
            error = True
            raise
        finally:
            self.add(route, time.perf_counter() - t0, error)

    def add(self, route: str, seconds: float, error: bool = False) -> None:
        with self._lock:
            if route not in self._routes:
                self._routes[route] = _RouteStats()
            self._routes[route].add(seconds, error)

    def snapshot(self) -> Dict:
        with self._lock:
            return {
                "uptime_s": round(time.time() - self.started, 1),
                "routes": {r: s.summary() for r, s in self._routes.items()},
            }


@contextlib.contextmanager
def device_trace(log_dir: str) -> Iterator[None]:
    """TensorBoard-loadable device trace (xplane) around a code region."""
    import jax

    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
