"""Structured logging: JSON lines instead of the reference's bare prints.

The reference logs failures with ``print()`` (``Flaskr/routes.py:125,158``,
``Flaskr/utils.py:223-225`` — SURVEY.md §5.5). Here every event is one
JSON object on stderr: machine-parseable, with logger name, level,
monotonic-ordered wall time, and free-form fields.
"""

from __future__ import annotations

import contextvars
import datetime as dt
import json
import sys
import threading
from typing import Any, Optional, TextIO

_LEVELS = {"debug": 10, "info": 20, "warning": 30, "error": 40}

# Per-request correlation id (set by the WSGI layer): every log line
# emitted while handling a request carries it, so one request's events
# can be grepped out of interleaved multi-threaded logs. Contextvars are
# per-thread-context, so concurrent handlers never see each other's id.
_request_id: contextvars.ContextVar[Optional[str]] = contextvars.ContextVar(
    "rtpu_request_id", default=None)

# Trace correlation: every line emitted inside an active span carries
# the span's trace/span ids automatically, so the flight recorder (and
# a grep) can pull one request's log lines with no per-call-site
# changes. The lookup is deferred-imported: obs.trace imports nothing
# from this module, so this cannot cycle, and utils stays importable
# without the obs package initialized.
_trace_context = None


def _ambient_span_ids():
    global _trace_context
    if _trace_context is None:
        from routest_tpu.obs.trace import current_context

        _trace_context = current_context
    return _trace_context()


# Log tee: the flight recorder installs a callback here to keep a
# bounded ring of recent records (dicts, post-stamping). One slot, not
# a list — there is one process recorder; tests may swap it.
_tee = None


def set_log_tee(fn) -> None:
    """Install (or clear, with None) the process log tee. ``fn`` gets
    every record dict AFTER level filtering and id stamping; it must
    not raise (the recorder's ring append cannot)."""
    global _tee
    _tee = fn


def set_request_id(rid: Optional[str]):
    """Bind the current context's request id; returns the reset token."""
    return _request_id.set(rid)


def reset_request_id(token) -> None:
    _request_id.reset(token)


def current_request_id() -> Optional[str]:
    return _request_id.get()


class JsonLogger:
    def __init__(self, name: str, stream: Optional[TextIO] = None,
                 level: str = "info") -> None:
        self.name = name
        self._stream = stream if stream is not None else sys.stderr
        self._min = _LEVELS[level]
        self._lock = threading.Lock()

    def _emit(self, level: str, event: str, **fields: Any) -> None:
        if _LEVELS[level] < self._min:
            return
        record = {
            "ts": dt.datetime.now(dt.timezone.utc).isoformat(),
            "level": level,
            "logger": self.name,
            "event": event,
            **fields,
        }
        rid = _request_id.get()
        if rid is not None and "request_id" not in record:
            record["request_id"] = rid
        ctx = _ambient_span_ids()
        if ctx is not None:
            # Ids flow even for unsampled traces (same rule the tracer
            # applies to header propagation): correlation must not
            # depend on the sampling coin.
            record.setdefault("trace_id", ctx.trace_id)
            record.setdefault("span_id", ctx.span_id)
        tee = _tee
        if tee is not None:
            tee(record)
        line = json.dumps(record, default=str)
        with self._lock:
            try:
                print(line, file=self._stream, flush=True)
            except ValueError:
                # The stream can be closed under us (pytest tears its
                # capture stream down while daemon threads — SLO
                # ticker, timeline ticker, triggered profiler — are
                # still finishing). The tee above already delivered the
                # record to the flight recorder; a log line must never
                # crash the thread that emitted it.
                pass

    def debug(self, event: str, **fields: Any) -> None:
        self._emit("debug", event, **fields)

    def info(self, event: str, **fields: Any) -> None:
        self._emit("info", event, **fields)

    def warning(self, event: str, **fields: Any) -> None:
        self._emit("warning", event, **fields)

    def error(self, event: str, **fields: Any) -> None:
        self._emit("error", event, **fields)


_loggers: dict = {}
_lock = threading.Lock()


def get_logger(name: str) -> JsonLogger:
    with _lock:
        if name not in _loggers:
            _loggers[name] = JsonLogger(name)
        return _loggers[name]
