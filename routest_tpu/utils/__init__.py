from routest_tpu.utils.logging import get_logger  # noqa: F401
from routest_tpu.utils.profiling import RequestStats, device_trace  # noqa: F401
