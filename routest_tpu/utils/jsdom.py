"""Headless DOM/browser host for executing page glue under minijs.

``utils/minijs.py`` executes the dashboard's pure-logic modules; this
module supplies the browser half so CI can run the PAGE GLUE too —
``boot()``, the calculate click, the SSE tracker, CSV export — against
a real (werkzeug test client) server, with no node/browser in the
sandbox. The reference gets this assurance manually, by people loading
the Next.js app (``frontend/map-app/app/ui/page.jsx``); here it is a
deterministic test fixture.

Scope — exactly what the shipped pages touch (inventoried from
``serve/static/dashboard.html`` / ``mvp.html``):

- a DOM built by PARSING THE REAL PAGE HTML (``html.parser``), so
  ``getElementById`` resolves the page's actual ids;
- elements: textContent/innerHTML (fragment-parsed), className,
  classList, style, value/checked/disabled, appendChild, setAttribute,
  querySelector(All) for the ``tag``/``#id``/``.class``/``:checked``
  selector subset, parentElement, event-handler properties, click();
- ``document.createElement(NS)/createTextNode``, ``querySelectorAll``;
- ``fetch`` bridged SYNCHRONOUSLY to a werkzeug test client (returns a
  settled promise — minijs has no event loop);
- ``EventSource`` (instances recorded; tests fire ``onmessage``),
  ``localStorage``, ``setTimeout/setInterval`` (recorded, fired by the
  test), ``Blob``/``URL.createObjectURL`` + anchor ``click()``
  (downloads recorded), ``Date`` (ISO parsing + display methods),
  ``Option``.

Everything is synchronous and deterministic: timers never auto-fire,
promises settle eagerly, and all side effects (downloads, event
sources, timers) are recorded on the :class:`DomHost` for assertions.
"""

from __future__ import annotations

import datetime as _dt
import json as _json
import re as _re
from html.parser import HTMLParser
from typing import Any, Dict, List, Optional

from routest_tpu.utils.minijs import (
    UNDEFINED,
    Interpreter,
    JSPromise,
)

__all__ = ["DomHost", "Element", "Event"]


# ---------------------------------------------------------------------------
# DOM nodes
# ---------------------------------------------------------------------------

_VOID_TAGS = {"input", "br", "img", "hr", "meta", "link"}


class _ClassList:
    def __init__(self, el: "Element"):
        self._el = el

    def _classes(self) -> List[str]:
        return [c for c in self._el.props.get("className", "").split()
                if c]

    def _store(self, classes: List[str]):
        self._el.props["className"] = " ".join(classes)

    def js_get_member(self, name: str):
        if name == "add":
            def add(*cs):
                classes = self._classes()
                for c in cs:
                    if c not in classes:
                        classes.append(str(c))
                self._store(classes)
            return add
        if name == "remove":
            def remove(*cs):
                self._store([c for c in self._classes() if c not in cs])
            return remove
        if name == "toggle":
            def toggle(c):
                classes = self._classes()
                if c in classes:
                    classes.remove(c)
                    self._store(classes)
                    return False
                classes.append(str(c))
                self._store(classes)
                return True
            return toggle
        if name == "contains":
            return lambda c: c in self._classes()
        return UNDEFINED

    def js_set_member(self, name: str, value):
        raise AttributeError(f"classList.{name} is read-only")


class _Style:
    def __init__(self):
        self.props: Dict[str, Any] = {}

    def js_get_member(self, name: str):
        return self.props.get(name, "")

    def js_set_member(self, name: str, value):
        self.props[name] = value


class Event:
    """Minimal DOM event: tests pass one into recorded handlers."""

    def __init__(self, data: Any = UNDEFINED):
        self.data = data
        self.propagation_stopped = False

    def js_get_member(self, name: str):
        if name == "data":
            return self.data
        if name == "stopPropagation":
            def stop():
                self.propagation_stopped = True
            return stop
        if name == "preventDefault":
            return lambda: None
        return UNDEFINED

    def js_set_member(self, name: str, value):
        setattr(self, name, value)


class Element:
    def __init__(self, tag: str, host: "DomHost",
                 attrs: Optional[Dict[str, str]] = None):
        self.tag = tag.lower()
        self.host = host
        self.attrs: Dict[str, str] = dict(attrs or {})
        self.children: List[Any] = []   # Element | str (text)
        self.parent: Optional[Element] = None
        self.props: Dict[str, Any] = {}
        self.style = _Style()
        if "class" in self.attrs:
            self.props["className"] = self.attrs["class"]
        if "value" in self.attrs:
            self.props["value"] = self.attrs["value"]
        if "checked" in self.attrs:
            self.props["checked"] = True
        if "selected" in self.attrs:
            self.props["selected"] = True

    # -- tree ------------------------------------------------------------
    def append(self, child):
        if isinstance(child, Element):
            child.parent = self
        self.children.append(child)
        return child

    def walk(self):
        for c in self.children:
            if isinstance(c, Element):
                yield c
                yield from c.walk()

    # -- text ------------------------------------------------------------
    def _text(self) -> str:
        out = []
        for c in self.children:
            out.append(c._text() if isinstance(c, Element) else str(c))
        return "".join(out)

    # -- selectors -------------------------------------------------------
    def matches(self, part: str) -> bool:
        m = _re.fullmatch(
            r"(?P<tag>[a-zA-Z][\w-]*)?(?:#(?P<id>[\w-]+))?"
            r"(?P<classes>(?:\.[\w-]+)*)(?P<checked>:checked)?", part)
        if not m:
            return False
        if m.group("tag") and self.tag != m.group("tag").lower():
            return False
        if m.group("id") and self.attrs.get("id") != m.group("id"):
            return False
        classes = [c for c in (m.group("classes") or "").split(".") if c]
        have = set(self.props.get("className", "").split())
        if any(c not in have for c in classes):
            return False
        if m.group("checked") and not self.props.get("checked"):
            return False
        return True

    def select(self, selector: str) -> List["Element"]:
        parts = selector.strip().split()
        matched: List[Element] = [self]
        for part in parts:
            nxt: List[Element] = []
            for scope in matched:
                for el in scope.walk():
                    if el.matches(part) and el not in nxt:
                        nxt.append(el)
            matched = nxt
        return matched

    # -- minijs host protocol --------------------------------------------
    def js_get_member(self, name: str):
        if name == "textContent":
            return self._text()
        if name == "innerHTML":
            return _serialize_children(self)
        if name in ("className", "value", "checked", "disabled",
                    "selected", "href", "download", "title", "id"):
            if name == "id":
                return self.attrs.get("id", "")
            default = False if name in ("checked", "disabled",
                                        "selected") else ""
            if name == "value" and self.tag == "select":
                return self._select_value()
            return self.props.get(name, default)
        if name == "style":
            return self.style
        if name == "classList":
            return _ClassList(self)
        if name == "parentElement":
            return self.parent
        if name == "children":
            return [c for c in self.children if isinstance(c, Element)]
        if name == "appendChild":
            return self.append
        if name == "setAttribute":
            def set_attr(k, v):
                k, v = _to_text(k), _to_text(v)
                self.attrs[k] = v
                if k == "class":
                    self.props["className"] = v
            return set_attr
        if name == "getAttribute":
            return lambda k: self.attrs.get(str(k), None)
        if name == "querySelector":
            def qs(sel):
                got = self.select(str(sel))
                return got[0] if got else None
            return qs
        if name == "querySelectorAll":
            return lambda sel: self.select(str(sel))
        if name == "add" and self.tag == "select":
            return self.append          # select.add(new Option(...))
        if name == "click":
            return lambda: self.host._click(self)
        if name.startswith("on"):
            return self.props.get(name, UNDEFINED)
        return UNDEFINED

    def js_set_member(self, name: str, value):
        if name == "textContent":
            self.children = [] if value in (None, UNDEFINED) \
                else [_to_text(value)]
            return
        if name == "innerHTML":
            self.children = []
            _parse_fragment(_to_text(value), self, self.host)
            return
        if name == "className":
            self.props["className"] = _to_text(value)
            return
        self.props[name] = value

    def _select_value(self) -> str:
        opts = [c for c in self.walk() if c.tag == "option"]
        if "value" in self.props:        # explicitly set by script
            return self.props["value"]
        for o in opts:
            if o.props.get("selected"):
                return o.props.get("value", o._text())
        return opts[0].props.get("value", opts[0]._text()) if opts \
            else ""

    def __repr__(self):
        return f"<Element {self.tag} id={self.attrs.get('id')!r}>"


def _to_text(v) -> str:
    from routest_tpu.utils.minijs import _js_str

    return _js_str(v)


def _serialize_children(el: Element) -> str:
    out = []
    for c in el.children:
        if isinstance(c, Element):
            attrs = "".join(f' {k}="{v}"' for k, v in c.attrs.items())
            if c.tag in _VOID_TAGS:
                out.append(f"<{c.tag}{attrs}>")
            else:
                out.append(f"<{c.tag}{attrs}>"
                           f"{_serialize_children(c)}</{c.tag}>")
        else:
            out.append(str(c))
    return "".join(out)


class _FragmentParser(HTMLParser):
    def __init__(self, root: Element, host: "DomHost"):
        super().__init__(convert_charrefs=True)
        self.stack = [root]
        self.host = host

    def handle_starttag(self, tag, attrs):
        el = Element(tag, self.host, dict(attrs))
        self.stack[-1].append(el)
        if tag.lower() not in _VOID_TAGS:
            self.stack.append(el)

    def handle_startendtag(self, tag, attrs):
        self.stack[-1].append(Element(tag, self.host, dict(attrs)))

    def handle_endtag(self, tag):
        for i in range(len(self.stack) - 1, 0, -1):
            if self.stack[i].tag == tag.lower():
                del self.stack[i:]
                return

    def handle_data(self, data):
        if data:
            self.stack[-1].append(data)


def _parse_fragment(html: str, into: Element, host: "DomHost"):
    p = _FragmentParser(into, host)
    p.feed(html)
    p.close()


# ---------------------------------------------------------------------------
# Browser host objects
# ---------------------------------------------------------------------------

class _Document:
    def __init__(self, host: "DomHost"):
        self.host = host

    def js_get_member(self, name: str):
        host = self.host
        if name == "getElementById":
            def by_id(i):
                for el in host.root.walk():
                    if el.attrs.get("id") == str(i):
                        return el
                return None
            return by_id
        if name == "querySelectorAll":
            return lambda sel: host.root.select(str(sel))
        if name == "querySelector":
            def qs(sel):
                got = host.root.select(str(sel))
                return got[0] if got else None
            return qs
        if name in ("createElement", "createTextNode"):
            if name == "createTextNode":
                return lambda text="": _to_text(text)
            return lambda tag: Element(str(tag), host)
        if name == "createElementNS":
            return lambda ns, tag: Element(str(tag), host)
        if name == "body":
            return host.root
        return UNDEFINED

    def js_set_member(self, name, value):
        raise AttributeError(f"document.{name} is read-only")


class _LocalStorage:
    def __init__(self):
        self.data: Dict[str, str] = {}

    def js_get_member(self, name: str):
        if name == "getItem":
            return lambda k: self.data.get(_to_text(k), None)
        if name == "setItem":
            def set_item(k, v):
                self.data[_to_text(k)] = _to_text(v)
            return set_item
        if name == "removeItem":
            return lambda k: self.data.pop(_to_text(k), None)
        if name == "clear":
            return lambda: self.data.clear()
        return UNDEFINED

    def js_set_member(self, name, value):
        self.data[name] = _to_text(value)


class _Response:
    def __init__(self, status: int, body: bytes,
                 content_type: str = "application/json"):
        self.status_code = status
        self.body = body
        self.content_type = content_type

    def js_get_member(self, name: str):
        if name == "ok":
            return 200 <= self.status_code < 300
        if name == "status":
            return float(self.status_code)
        if name == "json":
            def json_():
                try:
                    return JSPromise.fulfilled(
                        Interpreter.to_js(_json.loads(self.body)))
                except Exception:
                    return JSPromise.rejected(
                        {"name": "SyntaxError",
                         "message": "invalid JSON body"})
            return json_
        if name == "text":
            return lambda: JSPromise.fulfilled(
                self.body.decode("utf-8", "replace"))
        return UNDEFINED

    def js_set_member(self, name, value):
        raise AttributeError("responses are read-only")


class _EventSource:
    def __init__(self, host: "DomHost", url: str):
        self.host = host
        self.url = url
        self.closed = False
        self.handlers: Dict[str, Any] = {}
        host.event_sources.append(self)

    def js_get_member(self, name: str):
        if name == "close":
            def close():
                self.closed = True
            return close
        if name == "url":
            return self.url
        return self.handlers.get(name, UNDEFINED)

    def js_set_member(self, name: str, value):
        self.handlers[name] = value

    def fire_message(self, data: str):
        """Test hook: deliver one SSE frame to onmessage."""
        fn = self.handlers.get("onmessage")
        if fn is not None:
            self.host.interp.invoke(fn, [Event(data=data)])

    def fire_error(self):
        fn = self.handlers.get("onerror")
        if fn is not None:
            self.host.interp.invoke(fn, [Event()])


class _Blob:
    def __init__(self, parts, opts=None):
        self.content = "".join(_to_text(p) for p in (parts or []))

    def js_get_member(self, name):
        if name == "size":
            return float(len(self.content))
        return UNDEFINED

    def js_set_member(self, name, value):
        raise AttributeError("blobs are read-only")


class _Date:
    def __init__(self, iso=None):
        if iso is None or iso is UNDEFINED:
            self.dt = _dt.datetime(2026, 1, 1)  # deterministic "now"
        else:
            text = _to_text(iso).replace("Z", "+00:00")
            try:
                self.dt = _dt.datetime.fromisoformat(text)
            except ValueError:
                self.dt = _dt.datetime(1970, 1, 1)

    def js_get_member(self, name):
        if name == "toLocaleTimeString":
            return lambda *a: self.dt.strftime("%H:%M:%S")
        if name == "toISOString":
            return lambda: self.dt.strftime("%Y-%m-%dT%H:%M:%S.000Z")
        if name == "getTime":
            return lambda: self.dt.timestamp() * 1000.0
        if name == "getHours":
            return lambda: float(self.dt.hour)
        return UNDEFINED

    def js_set_member(self, name, value):
        raise AttributeError("dates are read-only")


class _URL:
    def __init__(self, host: "DomHost"):
        self.host = host

    def js_get_member(self, name):
        if name == "createObjectURL":
            def create(blob):
                url = f"blob:{len(self.host.blobs)}"
                self.host.blobs[url] = getattr(blob, "content", "")
                return url
            return create
        if name == "revokeObjectURL":
            return lambda url: None
        return UNDEFINED

    def js_set_member(self, name, value):
        raise AttributeError("URL is read-only")


# ---------------------------------------------------------------------------
# The host
# ---------------------------------------------------------------------------

class DomHost:
    """Wires a parsed page + browser shims into a minijs interpreter.

    >>> host = DomHost(page_html, client)   # werkzeug test Client
    >>> host.run_scripts()                  # lib modules + inline glue
    >>> host.click("calc")                  # fire a recorded handler
    >>> host.by_id("c-dist").js_get_member("textContent")
    """

    def __init__(self, page_html: str, client,
                 rng=lambda: 0.5) -> None:
        self.client = client
        self.root = Element("html", self)
        _parse_fragment(_strip_head(page_html), self.root, self)
        self.interp = Interpreter(rng=rng)
        self.localStorage = _LocalStorage()
        self.event_sources: List[_EventSource] = []
        self.timers: List[dict] = []
        self.blobs: Dict[str, str] = {}
        self.downloads: List[dict] = []
        self.fetch_log: List[str] = []
        self._install()
        self.page_html = page_html

    # -- installation ----------------------------------------------------
    def _install(self):
        it = self.interp
        it.set_global("document", _Document(self))
        it.set_global("localStorage", self.localStorage)
        it.set_global("fetch", self._fetch)
        it.set_global("EventSource",
                      lambda url: _EventSource(self, _to_text(url)))
        it.set_global("Blob", _Blob)
        it.set_global("URL", _URL(self))
        it.set_global("Date", _Date)
        it.set_global("Option", self._option)
        it.set_global("setTimeout", self._set_timer(False))
        it.set_global("setInterval", self._set_timer(True))
        it.set_global("clearTimeout", lambda i: None)
        it.set_global("clearInterval", lambda i: None)

    def _option(self, text="", value=""):
        el = Element("option", self)
        el.append(_to_text(text))
        el.props["value"] = _to_text(value)
        return el

    def _set_timer(self, repeating: bool):
        def setter(fn, delay=0.0, *a):
            self.timers.append({"fn": fn, "delay": delay,
                                "repeating": repeating})
            return float(len(self.timers))
        return setter

    def _fetch(self, url, opts=None):
        url = _to_text(url)
        self.fetch_log.append(url)
        opts = opts if isinstance(opts, dict) else {}
        method = _to_text(opts.get("method", "GET")).upper()
        headers = opts.get("headers") or {}
        body = opts.get("body")
        kwargs: Dict[str, Any] = {"headers": dict(headers)}
        if body is not None and body is not UNDEFINED:
            kwargs["data"] = _to_text(body)
        try:
            r = self.client.open(url, method=method, **kwargs)
        except Exception as e:  # connection-level failure → rejection
            return JSPromise.rejected({"name": "TypeError",
                                       "message": f"fetch failed: {e}"})
        return JSPromise.fulfilled(
            _Response(r.status_code, r.get_data(),
                      r.headers.get("Content-Type", "")))

    # -- script execution ------------------------------------------------
    def run_scripts(self):
        """Execute the page's scripts in order: each ``<script src>``
        is fetched from the live client; inline blocks run as-is."""
        for src, inline in _page_scripts(self.page_html):
            if src:
                r = self.client.get(src)
                assert r.status_code == 200, f"missing script {src}"
                self.interp.run(r.get_data(as_text=True))
            else:
                self.interp.run(inline)

    # -- test conveniences -----------------------------------------------
    def by_id(self, el_id: str) -> Element:
        for el in self.root.walk():
            if el.attrs.get("id") == el_id:
                return el
        raise KeyError(el_id)

    def text(self, el_id: str) -> str:
        return self.by_id(el_id)._text()

    def click(self, el_id: str, event: Optional[Event] = None):
        """Invoke an element's recorded onclick; unwrap the promise."""
        return self._click(self.by_id(el_id), event)

    def _click(self, el: Element, event: Optional[Event] = None):
        if el.tag == "a":
            name = el.props.get("download", "")
            href = _to_text(el.props.get("href", ""))
            self.downloads.append(
                {"download": name, "href": href,
                 "content": self.blobs.get(href, "")})
            return UNDEFINED
        fn = el.props.get("onclick")
        if fn is None or fn is UNDEFINED:
            raise AssertionError(f"no onclick on {el!r}")
        out = self.interp.invoke(fn, [event or Event()])
        value = self.interp.await_value(out)
        # a handler's fire-and-forget async work must not fail silently
        self.interp.check_unhandled_rejections()
        return value


def _strip_head(page_html: str) -> str:
    """Body only: the <style>/<head> content isn't DOM under test, and
    <script> bodies must not be parsed as markup."""
    body = page_html
    if "<body>" in body:
        body = body.split("<body>", 1)[1]
    body = _re.sub(r"<script\b[^>]*>.*?</script>", "", body,
                   flags=_re.S)
    return body.split("</body>")[0]


def _page_scripts(page_html: str):
    """Yield (src, inline) for each <script> in document order."""
    for m in _re.finditer(
            r"<script\b([^>]*)>(.*?)</script>", page_html, _re.S):
        attrs, body = m.group(1), m.group(2)
        src = _re.search(r'src="([^"]+)"', attrs)
        yield (src.group(1) if src else None,
               None if src else body)
