"""A small JavaScript (ES5 + a slice of ES2015) interpreter.

Why this exists: the dashboard ships ~500 lines of browser JS
(``serve/static/lib/dashboard_logic.js`` + the inline glue in
``dashboard.html``), and this sandbox has **no** JS runtime — no node,
no bun, no quickjs, no browser (VERDICT r4 missing #1 / next #5). The
reference's frontend logic is exercised by its authors in a browser;
ours must be exercised in CI or regressions ship silently. So the test
suite hosts its own engine: this module lexes, parses and evaluates the
*exact shipped file*, and ``tests/test_dashboard_logic.py`` drives it
with golden vectors generated from the same live-server corpus the
contract tests use (reference behavior map:
``/root/reference/frontend/map-app/app/ui/page.jsx``).

Scope — deliberately the subset the frontend logic modules are written
in (and ``tests/test_minijs.py`` pins the semantics):

- values: IEEE doubles (Python float), strings, booleans, ``null``,
  ``undefined``, arrays (list), plain objects (insertion-ordered dict),
  first-class functions/closures, regex literals;
- statements: ``const/let/var``, function declarations, ``if/else``,
  classic ``for``, ``for..of``, ``while``, ``return/break/continue``,
  expression statements, blocks;
- expressions: arrows (expression + block body), calls, member access,
  ``new``-less object/array literals (with spread), template literals
  with ``${}``, ternary, ``&&/||/??`` (value-returning), comparisons
  (strict + loose-null), arithmetic (incl. ``**``, string ``+``),
  unary (``! - + typeof``), pre/postfix ``++/--``, compound assignment,
  array/object destructuring in params and declarations;
- builtins: ``Math``, ``JSON``, ``String/Number/Boolean/Array``,
  ``Object.keys/values/entries/assign``, ``parseFloat/parseInt``,
  ``isFinite/isNaN``, ``encodeURIComponent``, number ``toFixed``,
  the common string/array methods, and regex ``test/exec`` +
  ``String.replace/match/split`` with the ``g`` flag.

Also supported, for executing the PAGE GLUE (not just the pure-logic
modules) under a host DOM (``utils/jsdom.py``):

- ``async function`` / ``async () =>``: the body runs eagerly and
  synchronously; the call returns a settled :class:`JSPromise`
  (fulfilled with the return value, rejected if the body threw);
- ``await expr``: unwraps a settled JSPromise (rethrows a rejection);
  non-promise values pass through; awaiting a PENDING promise raises —
  the host must settle promises before handing them over (there is no
  event loop, by design: CI wants deterministic, synchronous runs);
- ``new Ctor(args)``: invokes the callee like a call — host
  constructors (Date, Blob, EventSource, Option, ...) are plain
  factories injected as globals; ``new Promise(executor)`` runs the
  executor immediately with capturing resolve/reject;
- host objects: any Python object exposing ``js_get_member(name)`` /
  ``js_set_member(name, value)`` participates in member access and
  method calls — the seam jsdom's elements/fetch/localStorage use.

Not implemented (the modules don't use them): ``this``/classes/
prototypes, generators, labels, ``switch``, getters/setters,
``Symbol``, sparse arrays, a microtask queue. Unknown syntax raises
``JSSyntaxError`` at parse time, so an accidental use of an
unsupported feature fails CI loudly instead of silently skipping the
file.

JS-semantics corners handled on purpose (each pinned by a test):
- truthiness (``0 "" null undefined NaN`` falsy; ``[] {}`` truthy);
- ``x == null`` matches null AND undefined (the file's idiom);
- ``+`` concatenates when either side is a string, with JS number
  formatting (``5`` not ``5.0``, up to 17 significant digits);
- ``toFixed`` rounds ties away from zero on the decimal expansion of
  the double (``(0.5).toFixed(0) === "1"`` where Python's ``%.0f``
  gives ``"0"``);
- ``Array.prototype.map(fn)`` passes ``(element, index)``;
- ``sort()`` default comparator is lexicographic on String(x).
"""

from __future__ import annotations

import json as _json
import math
import re as _re
from decimal import ROUND_HALF_UP, Decimal
from typing import Any, Callable, Dict, List, Optional, Tuple

__all__ = [
    "JSSyntaxError",
    "JSError",
    "JSUndefined",
    "UNDEFINED",
    "Interpreter",
    "run_file",
    "run_source",
]


class JSSyntaxError(SyntaxError):
    """Tokenizer/parser rejection — unsupported or malformed JS."""


class JSError(RuntimeError):
    """Runtime error inside interpreted JS (incl. thrown values)."""


class JSUndefined:
    """The single ``undefined`` value (distinct from ``null``/None)."""

    _instance: Optional["JSUndefined"] = None

    def __new__(cls):
        if cls._instance is None:
            cls._instance = super().__new__(cls)
        return cls._instance

    def __repr__(self):
        return "undefined"

    def __bool__(self):
        return False


UNDEFINED = JSUndefined()


# ---------------------------------------------------------------------------
# Lexer
# ---------------------------------------------------------------------------

_KEYWORDS = {
    "const", "let", "var", "function", "return", "if", "else", "for",
    "while", "break", "continue", "true", "false", "null", "undefined",
    "typeof", "of", "in", "new", "throw", "try", "catch", "finally",
    "delete", "instanceof", "do", "void",
    # reserved so accidental use fails at parse time, not as a name
    "class", "async", "await", "yield", "import", "export", "switch",
    "case", "default", "this", "super", "extends", "static", "get",
    "set",
}

# Multi-char operators, longest first so the scanner is greedy.
_PUNCT = [
    "...", "===", "!==", "**=", "<<=", ">>=", "&&=", "||=", "??=",
    "=>", "==", "!=", "<=", ">=", "&&", "||", "??", "**", "++", "--",
    "+=", "-=", "*=", "/=", "%=", "?.",
    "{", "}", "(", ")", "[", "]", ";", ",", "<", ">", "+", "-", "*",
    "/", "%", "=", "!", "?", ":", ".", "~", "&", "|", "^",
]


class _Tok:
    __slots__ = ("kind", "value", "line")

    def __init__(self, kind: str, value: Any, line: int):
        self.kind = kind      # num str tpl ident kw punct regex eof
        self.value = value
        self.line = line

    def __repr__(self):
        return f"Tok({self.kind},{self.value!r})"


def _tokenize(src: str) -> List[_Tok]:
    toks: List[_Tok] = []
    i, n, line = 0, len(src), 1
    ident_re = _re.compile(r"[A-Za-z_$][A-Za-z0-9_$]*")
    num_re = _re.compile(
        r"0[xX][0-9a-fA-F]+|(?:\d+\.?\d*|\.\d+)(?:[eE][+-]?\d+)?")

    def prev_allows_regex() -> bool:
        # A '/' starts a regex unless the previous token can end an
        # expression (ident, literal, ')', ']', postfix ++/--).
        for t in reversed(toks):
            if t.kind in ("num", "str", "tpl", "regex"):
                return False
            if t.kind == "ident":
                return False
            if t.kind == "kw":
                return t.value not in ("true", "false", "null",
                                       "undefined")
            if t.kind == "punct":
                return t.value not in (")", "]", "++", "--")
            return True
        return True

    while i < n:
        c = src[i]
        if c == "\n":
            line += 1
            i += 1
            continue
        if c in " \t\r":
            i += 1
            continue
        if src.startswith("//", i):
            j = src.find("\n", i)
            i = n if j < 0 else j
            continue
        if src.startswith("/*", i):
            j = src.find("*/", i + 2)
            if j < 0:
                raise JSSyntaxError(f"line {line}: unterminated comment")
            line += src.count("\n", i, j)
            i = j + 2
            continue
        if c in "'\"":
            j, buf = i + 1, []
            while j < n and src[j] != c:
                if src[j] == "\\":
                    buf.append(_unescape(src[j + 1], line))
                    j += 2
                elif src[j] == "\n":
                    raise JSSyntaxError(f"line {line}: newline in string")
                else:
                    buf.append(src[j])
                    j += 1
            if j >= n:
                raise JSSyntaxError(f"line {line}: unterminated string")
            toks.append(_Tok("str", "".join(buf), line))
            i = j + 1
            continue
        if c == "`":
            # Template literal → tok value is a list of ("str", s) and
            # ("expr", token-list) parts; the parser assembles them.
            parts: List[Tuple[str, Any]] = []
            buf: List[str] = []
            j = i + 1
            while j < n and src[j] != "`":
                if src[j] == "\\":
                    buf.append(_unescape(src[j + 1], line))
                    j += 2
                elif src.startswith("${", j):
                    parts.append(("str", "".join(buf)))
                    buf = []
                    # brace-count to the closing }, skipping braces that
                    # sit inside string/template literals of the
                    # embedded expression (e.g. `${xs.join("}")}`)
                    depth, k = 1, j + 2
                    while k < n and depth:
                        ck = src[k]
                        if ck in "'\"`":
                            k += 1
                            while k < n and src[k] != ck:
                                k += 2 if src[k] == "\\" else 1
                            k += 1
                            continue
                        if ck == "{":
                            depth += 1
                        elif ck == "}":
                            depth -= 1
                        k += 1
                    if depth:
                        raise JSSyntaxError(
                            f"line {line}: unterminated ${{}} in template")
                    parts.append(("expr", _tokenize(src[j + 2:k - 1])))
                    line += src.count("\n", j, k)
                    j = k
                else:
                    if src[j] == "\n":
                        line += 1
                    buf.append(src[j])
                    j += 1
            if j >= n:
                raise JSSyntaxError(f"line {line}: unterminated template")
            parts.append(("str", "".join(buf)))
            toks.append(_Tok("tpl", parts, line))
            i = j + 1
            continue
        if c == "/" and prev_allows_regex():
            j, in_class = i + 1, False
            while j < n:
                if src[j] == "\\":
                    j += 2
                    continue
                if src[j] == "[":
                    in_class = True
                elif src[j] == "]":
                    in_class = False
                elif src[j] == "/" and not in_class:
                    break
                elif src[j] == "\n":
                    raise JSSyntaxError(f"line {line}: newline in regex")
                j += 1
            if j >= n:
                raise JSSyntaxError(f"line {line}: unterminated regex")
            body = src[i + 1:j]
            k = j + 1
            while k < n and src[k] in "gimsuy":
                k += 1
            toks.append(_Tok("regex", (body, src[j + 1:k]), line))
            i = k
            continue
        m = num_re.match(src, i)
        if m and c.isdigit() or (c == "." and m and m.start() == i
                                 and len(m.group()) > 1):
            text = m.group()
            val = float(int(text, 16)) if text[:2].lower() == "0x" \
                else float(text)
            toks.append(_Tok("num", val, line))
            i = m.end()
            continue
        m = ident_re.match(src, i)
        if m:
            word = m.group()
            toks.append(_Tok("kw" if word in _KEYWORDS else "ident",
                             word, line))
            i = m.end()
            continue
        for p in _PUNCT:
            if src.startswith(p, i):
                toks.append(_Tok("punct", p, line))
                i += len(p)
                break
        else:
            raise JSSyntaxError(f"line {line}: unexpected character {c!r}")
    toks.append(_Tok("eof", None, line))
    return toks


def _unescape(c: str, line: int) -> str:
    table = {"n": "\n", "t": "\t", "r": "\r", "b": "\b", "f": "\f",
             "v": "\v", "0": "\0"}
    return table.get(c, c)


# ---------------------------------------------------------------------------
# Parser — AST nodes are plain tuples: (kind, *fields)
# ---------------------------------------------------------------------------

_ASSIGN_OPS = {"=", "+=", "-=", "*=", "/=", "%=", "**=", "&&=", "||=",
               "??="}

# Binary precedence (higher binds tighter). Ternary/assignment handled
# separately below this table; unary above it.
_BIN_PREC = {
    "??": 1, "||": 2, "&&": 3,
    "==": 7, "!=": 7, "===": 7, "!==": 7,
    "<": 8, ">": 8, "<=": 8, ">=": 8, "in": 8, "instanceof": 8,
    "+": 10, "-": 10,
    "*": 11, "/": 11, "%": 11,
    "**": 12,
}


class _Parser:
    def __init__(self, toks: List[_Tok]):
        self.toks = toks
        self.i = 0

    # -- token helpers ---------------------------------------------------
    def peek(self, k: int = 0) -> _Tok:
        return self.toks[min(self.i + k, len(self.toks) - 1)]

    def next(self) -> _Tok:
        t = self.toks[self.i]
        self.i += 1
        return t

    def at(self, kind: str, value: Any = None) -> bool:
        t = self.peek()
        return t.kind == kind and (value is None or t.value == value)

    def eat(self, kind: str, value: Any = None) -> _Tok:
        if not self.at(kind, value):
            t = self.peek()
            raise JSSyntaxError(
                f"line {t.line}: expected {value or kind}, "
                f"got {t.value!r}")
        return self.next()

    def opt(self, kind: str, value: Any = None) -> bool:
        if self.at(kind, value):
            self.next()
            return True
        return False

    # -- program ---------------------------------------------------------
    def parse_program(self):
        body = []
        while not self.at("eof"):
            body.append(self.statement())
        return ("block", body)

    # -- statements ------------------------------------------------------
    def statement(self):
        t = self.peek()
        if t.kind == "punct" and t.value == "{":
            return self.block()
        if t.kind == "punct" and t.value == ";":
            self.next()
            return ("empty",)
        if t.kind == "kw":
            if t.value in ("const", "let", "var"):
                d = self.var_decl()
                self.opt("punct", ";")
                return d
            if t.value == "function":
                return self.function_decl()
            if t.value == "async" and self.peek(1).kind == "kw" \
                    and self.peek(1).value == "function":
                self.next()
                return self.function_decl(is_async=True)
            if t.value == "if":
                return self.if_stmt()
            if t.value == "for":
                return self.for_stmt()
            if t.value == "while":
                self.next()
                self.eat("punct", "(")
                cond = self.expression()
                self.eat("punct", ")")
                return ("while", cond, self.statement())
            if t.value == "do":
                self.next()
                body = self.statement()
                self.eat("kw", "while")
                self.eat("punct", "(")
                cond = self.expression()
                self.eat("punct", ")")
                self.opt("punct", ";")
                return ("dowhile", cond, body)
            if t.value == "return":
                self.next()
                if self.at("punct", ";") or self.at("punct", "}") \
                        or self.at("eof"):
                    self.opt("punct", ";")
                    return ("return", None)
                e = self.expression()
                self.opt("punct", ";")
                return ("return", e)
            if t.value == "break":
                self.next()
                self.opt("punct", ";")
                return ("break",)
            if t.value == "continue":
                self.next()
                self.opt("punct", ";")
                return ("continue",)
            if t.value == "throw":
                self.next()
                e = self.expression()
                self.opt("punct", ";")
                return ("throw", e)
            if t.value == "try":
                return self.try_stmt()
        e = self.expression()
        self.opt("punct", ";")
        return ("expr", e)

    def block(self):
        self.eat("punct", "{")
        body = []
        while not self.at("punct", "}"):
            body.append(self.statement())
        self.eat("punct", "}")
        return ("block", body)

    def var_decl(self):
        kind = self.next().value
        decls = []
        while True:
            target = self.binding_target()
            init = None
            if self.opt("punct", "="):
                init = self.assignment()
            decls.append((target, init))
            if not self.opt("punct", ","):
                break
        return ("decl", kind, decls)

    def binding_target(self):
        """ident | [a, b] | {a, b} destructuring pattern."""
        if self.at("punct", "["):
            self.next()
            elems = []
            while not self.at("punct", "]"):
                if self.opt("punct", ","):
                    elems.append(None)  # hole
                    continue
                elems.append(self.binding_target())
                if not self.at("punct", "]"):
                    self.eat("punct", ",")
            self.eat("punct", "]")
            return ("arr_pat", elems)
        if self.at("punct", "{"):
            self.next()
            props = []
            while not self.at("punct", "}"):
                name = self.next()
                if name.kind not in ("ident", "kw"):
                    raise JSSyntaxError(
                        f"line {name.line}: bad destructuring key")
                default = None
                if self.opt("punct", "="):
                    default = self.assignment()
                props.append((name.value, default))
                if not self.at("punct", "}"):
                    self.eat("punct", ",")
            self.eat("punct", "}")
            return ("obj_pat", props)
        t = self.next()
        if t.kind != "ident":
            raise JSSyntaxError(f"line {t.line}: bad binding {t.value!r}")
        return ("ident_pat", t.value)

    def function_decl(self, is_async: bool = False):
        self.eat("kw", "function")
        name = self.eat("ident").value
        params = self.param_list()
        body = self.block()
        return ("funcdecl", name, params, body, is_async)

    def param_list(self):
        self.eat("punct", "(")
        params = []
        while not self.at("punct", ")"):
            if self.opt("punct", "..."):
                params.append(("rest", self.eat("ident").value))
            else:
                target = self.binding_target()
                default = None
                if self.opt("punct", "="):
                    default = self.assignment()
                params.append(("param", target, default))
            if not self.at("punct", ")"):
                self.eat("punct", ",")
        self.eat("punct", ")")
        return params

    def if_stmt(self):
        self.eat("kw", "if")
        self.eat("punct", "(")
        cond = self.expression()
        self.eat("punct", ")")
        then = self.statement()
        alt = None
        if self.opt("kw", "else"):
            alt = self.statement()
        return ("if", cond, then, alt)

    def for_stmt(self):
        self.eat("kw", "for")
        self.eat("punct", "(")
        init = None
        if not self.at("punct", ";"):
            if self.peek().kind == "kw" and self.peek().value in (
                    "const", "let", "var"):
                kind = self.next().value
                target = self.binding_target()
                if self.at("kw", "of") or self.at("kw", "in"):
                    mode = self.next().value
                    it = self.expression()
                    self.eat("punct", ")")
                    return ("for" + mode, kind, target, it,
                            self.statement())
                initdecls = []
                i0 = None
                if self.opt("punct", "="):
                    i0 = self.assignment()
                initdecls.append((target, i0))
                while self.opt("punct", ","):
                    t2 = self.binding_target()
                    i2 = None
                    if self.opt("punct", "="):
                        i2 = self.assignment()
                    initdecls.append((t2, i2))
                init = ("decl", kind, initdecls)
            else:
                e = self.expression()
                if self.at("kw", "of") or self.at("kw", "in"):
                    raise JSSyntaxError(
                        f"line {self.peek().line}: for..of needs a "
                        "declaration")
                init = ("expr", e)
        self.eat("punct", ";")
        cond = None if self.at("punct", ";") else self.expression()
        self.eat("punct", ";")
        step = None if self.at("punct", ")") else self.expression()
        self.eat("punct", ")")
        return ("for", init, cond, step, self.statement())

    def try_stmt(self):
        self.eat("kw", "try")
        body = self.block()
        param, handler, finalizer = None, None, None
        if self.opt("kw", "catch"):
            if self.opt("punct", "("):
                param = self.eat("ident").value
                self.eat("punct", ")")
            handler = self.block()
        if self.opt("kw", "finally"):
            finalizer = self.block()
        return ("try", body, param, handler, finalizer)

    # -- expressions -----------------------------------------------------
    def expression(self):
        e = self.assignment()
        while self.at("punct", ","):
            self.next()
            e = ("comma", e, self.assignment())
        return e

    def assignment(self):
        if self.at("kw", "async"):
            # `async x => ...` / `async (a, b) => ...`; anything else
            # (async function, stray token) restores and falls through
            save = self.i
            self.next()
            if self.is_arrow_ahead():
                return self.arrow_function(is_async=True)
            self.i = save
        if self.is_arrow_ahead():
            return self.arrow_function()
        left = self.ternary()
        t = self.peek()
        if t.kind == "punct" and t.value in _ASSIGN_OPS:
            self.next()
            right = self.assignment()
            return ("assign", t.value, left, right)
        return left

    def is_arrow_ahead(self) -> bool:
        """Lookahead for ``x =>`` or ``(a, b) =>`` / ``([x]) =>`` etc."""
        t = self.peek()
        if t.kind == "ident" and self.peek(1).kind == "punct" \
                and self.peek(1).value == "=>":
            return True
        if t.kind == "punct" and t.value == "(":
            depth, j = 0, self.i
            while j < len(self.toks):
                tk = self.toks[j]
                if tk.kind == "punct" and tk.value == "(":
                    depth += 1
                elif tk.kind == "punct" and tk.value == ")":
                    depth -= 1
                    if depth == 0:
                        nxt = self.toks[j + 1] if j + 1 < len(self.toks) \
                            else None
                        return (nxt is not None and nxt.kind == "punct"
                                and nxt.value == "=>")
                elif tk.kind == "eof":
                    return False
                j += 1
        return False

    def arrow_function(self, is_async: bool = False):
        if self.peek().kind == "ident":
            params = [("param", ("ident_pat", self.next().value), None)]
        else:
            params = self.param_list()
        self.eat("punct", "=>")
        if self.at("punct", "{"):
            body = self.block()
            return ("func", None, params, body, is_async)
        return ("func", None, params, ("return", self.assignment()),
                is_async)

    def ternary(self):
        cond = self.binary(0)
        if self.opt("punct", "?"):
            a = self.assignment()
            self.eat("punct", ":")
            b = self.assignment()
            return ("cond", cond, a, b)
        return cond

    def binary(self, min_prec: int):
        left = self.unary()
        while True:
            t = self.peek()
            op = t.value if (t.kind == "punct" or
                             (t.kind == "kw" and t.value in
                              ("in", "instanceof"))) else None
            prec = _BIN_PREC.get(op)
            if prec is None or prec < min_prec:
                return left
            self.next()
            # ** is right-associative; the rest left.
            right = self.binary(prec if op == "**" else prec + 1)
            left = ("bin", op, left, right)

    def unary(self):
        t = self.peek()
        if t.kind == "punct" and t.value in ("!", "-", "+", "~"):
            self.next()
            return ("unary", t.value, self.unary())
        if t.kind == "kw" and t.value in ("typeof", "void", "delete"):
            self.next()
            return ("unary", t.value, self.unary())
        if t.kind == "kw" and t.value == "await":
            self.next()
            return ("await", self.unary())
        if t.kind == "punct" and t.value in ("++", "--"):
            self.next()
            return ("update", t.value, self.unary(), True)
        e = self.postfix()
        t = self.peek()
        if t.kind == "punct" and t.value in ("++", "--"):
            self.next()
            return ("update", t.value, e, False)
        return e

    def postfix(self):
        e = self.primary()
        while True:
            t = self.peek()
            if t.kind == "punct" and t.value == ".":
                self.next()
                name = self.next()
                if name.kind not in ("ident", "kw"):
                    raise JSSyntaxError(
                        f"line {name.line}: bad property name")
                e = ("member", e, ("lit", name.value), False)
            elif t.kind == "punct" and t.value == "?.":
                self.next()
                if self.at("punct", "("):
                    e = ("call", e, self.args(), True)
                else:
                    name = self.next()
                    e = ("member", e, ("lit", name.value), True)
            elif t.kind == "punct" and t.value == "[":
                self.next()
                idx = self.expression()
                self.eat("punct", "]")
                e = ("member", e, idx, False)
            elif t.kind == "punct" and t.value == "(":
                e = ("call", e, self.args(), False)
            else:
                return e

    def args(self):
        self.eat("punct", "(")
        out = []
        while not self.at("punct", ")"):
            if self.opt("punct", "..."):
                out.append(("spread", self.assignment()))
            else:
                out.append(("arg", self.assignment()))
            if not self.at("punct", ")"):
                self.eat("punct", ",")
        self.eat("punct", ")")
        return out

    def primary(self):
        t = self.next()
        if t.kind == "num":
            return ("lit", t.value)
        if t.kind == "str":
            return ("lit", t.value)
        if t.kind == "regex":
            return ("regex", t.value[0], t.value[1])
        if t.kind == "tpl":
            parts = []
            for k, v in t.value:
                if k == "str":
                    parts.append(("lit", v))
                else:
                    parts.append(_Parser(v + [_Tok("eof", None, t.line)])
                                 .expression())
            return ("template", parts)
        if t.kind == "kw":
            if t.value == "true":
                return ("lit", True)
            if t.value == "false":
                return ("lit", False)
            if t.value == "null":
                return ("lit", None)
            if t.value == "undefined":
                return ("lit", UNDEFINED)
            if t.value == "function":
                name = None
                if self.peek().kind == "ident":
                    name = self.next().value
                params = self.param_list()
                return ("func", name, params, self.block(), False)
            if t.value == "async" and self.at("kw", "function"):
                self.next()
                name = None
                if self.peek().kind == "ident":
                    name = self.next().value
                params = self.param_list()
                return ("func", name, params, self.block(), True)
            if t.value == "new":
                # `new Ctor(args)` / `new Ctor` — host constructors are
                # plain factories, so construction == invocation
                target = self.postfix()
                if target[0] == "call":
                    return ("new", target[1], target[2])
                return ("new", target, [])
            raise JSSyntaxError(
                f"line {t.line}: unexpected keyword {t.value!r}")
        if t.kind == "ident":
            return ("name", t.value)
        if t.kind == "punct" and t.value == "(":
            e = self.expression()
            self.eat("punct", ")")
            return e
        if t.kind == "punct" and t.value == "[":
            elems = []
            while not self.at("punct", "]"):
                if self.opt("punct", "..."):
                    elems.append(("spread", self.assignment()))
                else:
                    elems.append(("arg", self.assignment()))
                if not self.at("punct", "]"):
                    self.eat("punct", ",")
            self.eat("punct", "]")
            return ("array", elems)
        if t.kind == "punct" and t.value == "{":
            props = []
            while not self.at("punct", "}"):
                if self.opt("punct", "..."):
                    props.append(("spread", self.assignment()))
                else:
                    k = self.next()
                    if k.kind in ("ident", "kw"):
                        key = ("lit", k.value)
                    elif k.kind == "str":
                        key = ("lit", k.value)
                    elif k.kind == "num":
                        key = ("lit", _js_num_to_key(k.value))
                    elif k.kind == "punct" and k.value == "[":
                        key = self.assignment()
                        self.eat("punct", "]")
                    else:
                        raise JSSyntaxError(
                            f"line {k.line}: bad object key {k.value!r}")
                    if self.opt("punct", ":"):
                        props.append(("kv", key, self.assignment()))
                    elif self.at("punct", "(") and k.kind in ("ident",
                                                              "kw"):
                        params = self.param_list()
                        body = self.block()
                        props.append(("kv", key,
                                      ("func", k.value, params, body,
                                       False)))
                    else:  # shorthand {a}
                        props.append(("kv", key, ("name", k.value)))
                if not self.at("punct", "}"):
                    self.eat("punct", ",")
            self.eat("punct", "}")
            return ("object", props)
        raise JSSyntaxError(f"line {t.line}: unexpected token {t.value!r}")


def _js_num_to_key(v: float) -> str:
    return _js_number_str(v)


# ---------------------------------------------------------------------------
# Runtime values
# ---------------------------------------------------------------------------

class JSFunction:
    __slots__ = ("name", "params", "body", "env", "interp", "is_async")

    def __init__(self, name, params, body, env, interp,
                 is_async: bool = False):
        self.name = name or "<anonymous>"
        self.params = params
        self.body = body
        self.env = env
        self.interp = interp
        self.is_async = is_async

    def __call__(self, *args):
        return self.interp.call_function(self, list(args))

    def __repr__(self):
        return f"<JSFunction {self.name}>"


class JSPromise:
    """A settled-or-pending promise value — NO event loop.

    Async functions run eagerly and return one of these already
    settled; ``new Promise(executor)`` runs the executor immediately
    and is pending until the captured resolve/reject fires (the host
    drives that, e.g. a dialog's button handler). Reactions attached
    with ``then/catch/finally`` run synchronously when settled — and a
    reaction attached while PENDING is queued and runs the moment the
    host settles the promise.

    ``handled`` supports the unhandled-rejection check: awaiting or
    attaching any reaction marks a promise handled; a rejected promise
    nobody ever observed is surfaced loudly by ``Interpreter.run``."""

    __slots__ = ("state", "value", "error", "handled", "_callbacks")

    def __init__(self):
        self.state = "pending"
        self.value = UNDEFINED
        self.error = UNDEFINED
        self.handled = False
        self._callbacks: List[Callable[["JSPromise"], None]] = []

    @classmethod
    def fulfilled(cls, value):
        p = cls()
        p.state = "fulfilled"
        p.value = value
        return p

    @classmethod
    def rejected(cls, error):
        p = cls()
        p.state = "rejected"
        p.error = error
        return p

    def resolve(self, value=UNDEFINED):
        if self.state == "pending":
            self.state = "fulfilled"
            self.value = value
            self._flush()

    def reject(self, error=UNDEFINED):
        if self.state == "pending":
            self.state = "rejected"
            self.error = error
            self._flush()

    def subscribe(self, cb: Callable[["JSPromise"], None]):
        """Run ``cb(self)`` now if settled, else when settled."""
        self.handled = True
        if self.state == "pending":
            self._callbacks.append(cb)
        else:
            cb(self)

    def _flush(self):
        cbs, self._callbacks = self._callbacks, []
        for cb in cbs:
            cb(self)

    def __repr__(self):
        return f"<JSPromise {self.state}>"


class JSRegex:
    __slots__ = ("source", "flags", "compiled")

    def __init__(self, source: str, flags: str):
        self.source = source
        self.flags = flags
        pyflags = 0
        if "i" in flags:
            pyflags |= _re.IGNORECASE
        if "m" in flags:
            pyflags |= _re.MULTILINE
        if "s" in flags:
            pyflags |= _re.DOTALL
        try:
            self.compiled = _re.compile(_js_regex_to_py(source), pyflags)
        except _re.error as e:
            raise JSSyntaxError(f"bad regex /{source}/: {e}") from e

    def __repr__(self):
        return f"/{self.source}/{self.flags}"


def _js_regex_to_py(source: str) -> str:
    """Translate the JS regex subset to Python ``re`` syntax.

    The logic modules stick to the shared subset (char classes,
    quantifiers, anchors, groups, alternation, \\d \\w \\s \\b); the
    only rewrite needed is ``\\/`` (escaped slash, meaningless to re)
    → ``/``.
    """
    return source.replace(r"\/", "/")


class _Env:
    __slots__ = ("vars", "parent")

    def __init__(self, parent: Optional["_Env"] = None):
        self.vars: Dict[str, Any] = {}
        self.parent = parent

    def lookup(self, name: str):
        env = self
        while env is not None:
            if name in env.vars:
                return env.vars[name]
            env = env.parent
        raise JSError(f"ReferenceError: {name} is not defined")

    def set(self, name: str, value: Any):
        env = self
        while env is not None:
            if name in env.vars:
                env.vars[name] = value
                return
            env = env.parent
        raise JSError(f"ReferenceError: {name} is not defined")

    def declare(self, name: str, value: Any):
        self.vars[name] = value


# Control-flow signals
class _Return(Exception):
    def __init__(self, value):
        self.value = value


class _Break(Exception):
    pass


class _Continue(Exception):
    pass


class _Thrown(JSError):
    def __init__(self, value):
        self.value = value
        super().__init__(f"uncaught JS throw: {_js_display(value)}")


# ---------------------------------------------------------------------------
# JS semantics helpers
# ---------------------------------------------------------------------------

def _truthy(v: Any) -> bool:
    if v is None or v is UNDEFINED or v is False:
        return False
    if isinstance(v, float):
        return not (v == 0.0 or math.isnan(v))
    if isinstance(v, bool):
        return v
    if isinstance(v, str):
        return len(v) > 0
    return True  # arrays, objects, functions, regexes


def _js_number_str(v: float) -> str:
    """ToString(number): '5' not '5.0'; shortest round-trip digits."""
    if math.isnan(v):
        return "NaN"
    if v == math.inf:
        return "Infinity"
    if v == -math.inf:
        return "-Infinity"
    if v == int(v) and abs(v) < 1e21:
        return str(int(v))
    r = repr(v)
    if "e" in r or "E" in r:
        # JS uses e+21 style for big, e-7 for small; repr is close
        # enough for the logic modules' ranges (they format with
        # toFixed for display anyway).
        return r
    return r


def _js_str(v: Any) -> str:
    if v is UNDEFINED:
        return "undefined"
    if v is None:
        return "null"
    if v is True:
        return "true"
    if v is False:
        return "false"
    if isinstance(v, float):
        return _js_number_str(v)
    if isinstance(v, str):
        return v
    if isinstance(v, list):
        return ",".join("" if x is None or x is UNDEFINED else _js_str(x)
                        for x in v)
    if isinstance(v, dict):
        return "[object Object]"
    if isinstance(v, JSRegex):
        return repr(v)
    if isinstance(v, (JSFunction,)) or callable(v):
        return f"function {getattr(v, 'name', '')}() {{ ... }}"
    return str(v)


def _js_display(v: Any) -> str:
    return _js_str(v)


def _to_number(v: Any) -> float:
    if isinstance(v, bool):
        return 1.0 if v else 0.0
    if isinstance(v, float):
        return v
    if v is None:
        return 0.0
    if v is UNDEFINED:
        return math.nan
    if isinstance(v, str):
        s = v.strip()
        if not s:
            return 0.0
        try:
            if s[:2].lower() == "0x":
                return float(int(s, 16))
            return float(s)
        except ValueError:
            return math.nan
    if isinstance(v, list):
        if not v:
            return 0.0
        if len(v) == 1:
            return _to_number(v[0])
        return math.nan
    return math.nan


def _strict_eq(a: Any, b: Any) -> bool:
    if a is UNDEFINED or b is UNDEFINED:
        return a is b
    if a is None or b is None:
        return a is b
    if isinstance(a, bool) or isinstance(b, bool):
        return isinstance(a, bool) and isinstance(b, bool) and a == b
    if isinstance(a, float) and isinstance(b, float):
        return a == b  # NaN != NaN handled by float eq
    if isinstance(a, str) and isinstance(b, str):
        return a == b
    return a is b  # objects/arrays/functions: identity


def _loose_eq(a: Any, b: Any) -> bool:
    nullish = (None, UNDEFINED)
    if (a in nullish if not isinstance(a, (list, dict)) else False) or \
       (b in nullish if not isinstance(b, (list, dict)) else False):
        a_n = a is None or a is UNDEFINED
        b_n = b is None or b is UNDEFINED
        return a_n and b_n
    if isinstance(a, bool):
        return _loose_eq(_to_number(a), b)
    if isinstance(b, bool):
        return _loose_eq(a, _to_number(b))
    if isinstance(a, float) and isinstance(b, str):
        return a == _to_number(b)
    if isinstance(a, str) and isinstance(b, float):
        return _to_number(a) == b
    return _strict_eq(a, b)


def _to_int(v: Any) -> int:
    n = _to_number(v)
    if math.isnan(n) or math.isinf(n):
        return 0
    return int(n)


def _js_tofixed(x: float, digits: int) -> str:
    """Number.prototype.toFixed: per spec the sign is peeled first and
    ties pick the LARGER n, so ties round away from zero on the exact
    decimal expansion of the double — (0.5).toFixed(0) === '1' and
    (-0.5).toFixed(0) === '-1', where Python's ``%.0f`` gives '0'."""
    if math.isnan(x):
        return "NaN"
    d = Decimal(abs(x)).quantize(Decimal(1).scaleb(-digits),
                                 rounding=ROUND_HALF_UP)
    s = f"{d:.{digits}f}"
    return "-" + s if x < 0 else s


def _json_stringify(v: Any, indent: Any = None) -> Any:
    def conv(x):
        if x is UNDEFINED or isinstance(x, (JSFunction, JSRegex)) \
                or callable(x):
            return _SKIP
        if isinstance(x, float):
            if math.isnan(x) or math.isinf(x):
                return None
            # integral doubles serialize as "1", not "1.0"
            return int(x) if x == int(x) and abs(x) < 2**53 else x
        if isinstance(x, list):
            return [None if (c := conv(e)) is _SKIP else c for e in x]
        if isinstance(x, dict):
            return {k: c for k, e in x.items()
                    if (c := conv(e)) is not _SKIP}
        return x

    _SKIP = object()
    c = conv(v)
    if c is _SKIP:
        return UNDEFINED
    kwargs: Dict[str, Any] = {"ensure_ascii": False,
                              "separators": (",", ":")}
    if indent is not None and indent is not UNDEFINED:
        n = _to_int(indent)
        if n > 0:
            kwargs = {"ensure_ascii": False, "indent": n,
                      "separators": (",", ": ")}
    return _json.dumps(c, **kwargs)


def _json_parse(s: str) -> Any:
    def hook(x):
        return x

    def fix(x):
        if isinstance(x, bool):
            return x
        if isinstance(x, (int, float)):
            return float(x)
        if isinstance(x, list):
            return [fix(e) for e in x]
        if isinstance(x, dict):
            return {k: fix(v) for k, v in x.items()}
        return x

    try:
        return fix(_json.loads(s))
    except Exception as e:
        raise _Thrown({"name": "SyntaxError", "message": str(e)}) from e


# ---------------------------------------------------------------------------
# Interpreter
# ---------------------------------------------------------------------------

class Interpreter:
    """Evaluate a parsed program; expose its top-level bindings.

    >>> it = Interpreter(); it.run("function f(x) { return x * 2; }")
    >>> it.call("f", 21.0)
    42.0
    """

    def __init__(self, rng: Optional[Callable[[], float]] = None):
        self.globals = _Env()
        self._promises: List[JSPromise] = []
        self._install_builtins(rng or (lambda: 0.5))

    def _track(self, p: JSPromise) -> JSPromise:
        self._promises.append(p)
        return p

    def check_unhandled_rejections(self):
        """Raise if any tracked promise was rejected and never observed
        (no await, no then/catch/finally) — an async code path failed
        silently otherwise, eroding the fail-loudly guarantee. Hosts
        driving handlers across run() boundaries should call this after
        each interaction."""
        bad = [p for p in self._promises
               if p.state == "rejected" and not p.handled]
        self._promises = [p for p in self._promises
                          if p.state == "pending"]
        if bad:
            err = bad[0].error
            if isinstance(err, dict):  # Error-shaped: show the payload
                try:
                    err = _json_stringify(err)
                except (TypeError, ValueError, RecursionError):
                    pass  # non-JSON members: fall back to [object Object]
            raise JSError(f"unhandled promise rejection: "
                          f"{_js_display(err)}")

    # -- public API ------------------------------------------------------
    def run(self, source: str):
        ast = _Parser(_tokenize(source)).parse_program()
        self.exec_block(ast, self.globals)
        self.check_unhandled_rejections()

    def call(self, name: str, *args) -> Any:
        fn = self.globals.lookup(name)
        if isinstance(fn, JSFunction):
            return self.call_function(fn, [self.to_js(a) for a in args])
        if callable(fn):
            return fn(*[self.to_js(a) for a in args])
        raise JSError(f"{name} is not a function")

    def get(self, name: str) -> Any:
        return self.globals.lookup(name)

    def set_global(self, name: str, value: Any):
        self.globals.declare(name, self.to_js(value))

    @staticmethod
    def to_js(v: Any) -> Any:
        """Python → interpreter value (ints become doubles)."""
        if isinstance(v, bool) or v is None or v is UNDEFINED:
            return v
        if isinstance(v, int):
            return float(v)
        if isinstance(v, float) or isinstance(v, str):
            return v
        if isinstance(v, (list, tuple)):
            return [Interpreter.to_js(x) for x in v]
        if isinstance(v, dict):
            return {str(k): Interpreter.to_js(x) for k, x in v.items()}
        return v

    @staticmethod
    def to_py(v: Any) -> Any:
        """Interpreter value → plain Python (undefined → None)."""
        if v is UNDEFINED:
            return None
        if isinstance(v, list):
            return [Interpreter.to_py(x) for x in v]
        if isinstance(v, dict):
            return {k: Interpreter.to_py(x) for k, x in v.items()}
        return v

    # -- builtins --------------------------------------------------------
    def _install_builtins(self, rng: Callable[[], float]):
        g = self.globals
        g.declare("NaN", math.nan)
        g.declare("Infinity", math.inf)
        g.declare("undefined", UNDEFINED)
        g.declare("Math", {
            "PI": math.pi, "E": math.e,
            "abs": lambda x: abs(_to_number(x)),
            "min": lambda *a: min((_to_number(x) for x in a),
                                  default=math.inf),
            "max": lambda *a: max((_to_number(x) for x in a),
                                  default=-math.inf),
            "floor": lambda x: float(math.floor(_to_number(x))),
            "ceil": lambda x: float(math.ceil(_to_number(x))),
            "round": lambda x: _js_math_round(_to_number(x)),
            "trunc": lambda x: float(math.trunc(_to_number(x))),
            "sqrt": lambda x: math.sqrt(_to_number(x))
            if _to_number(x) >= 0 else math.nan,
            "pow": lambda a, b: float(_to_number(a) ** _to_number(b)),
            "sin": lambda x: math.sin(_to_number(x)),
            "cos": lambda x: math.cos(_to_number(x)),
            "tan": lambda x: math.tan(_to_number(x)),
            "asin": lambda x: math.asin(_to_number(x)),
            "acos": lambda x: math.acos(_to_number(x)),
            "atan": lambda x: math.atan(_to_number(x)),
            "atan2": lambda y, x: math.atan2(_to_number(y),
                                             _to_number(x)),
            "log": lambda x: math.log(_to_number(x))
            if _to_number(x) > 0 else (-math.inf if _to_number(x) == 0
                                       else math.nan),
            "log2": lambda x: math.log2(_to_number(x))
            if _to_number(x) > 0 else math.nan,
            "hypot": lambda *a: math.hypot(*[_to_number(x) for x in a]),
            "sign": lambda x: math.copysign(1.0, _to_number(x))
            if _to_number(x) != 0 and not math.isnan(_to_number(x))
            else _to_number(x),
            "random": lambda: float(rng()),
        })
        g.declare("JSON", {
            "stringify": lambda v, replacer=None, indent=None:
                _json_stringify(v, indent),
            "parse": lambda s, *_: _json_parse(_js_str(s)),
        })
        g.declare("Object", {
            "keys": lambda o: list(o.keys())
            if isinstance(o, dict)
            else [str(i) for i in range(len(o))]
            if isinstance(o, list) else [],
            "values": lambda o: list(o.values())
            if isinstance(o, dict) else list(o)
            if isinstance(o, list) else [],
            "entries": lambda o: [[k, v] for k, v in o.items()]
            if isinstance(o, dict)
            else [[str(i), v] for i, v in enumerate(o)]
            if isinstance(o, list) else [],
            "assign": _object_assign,
            "freeze": lambda o: o,
        })
        g.declare("Array", {
            "isArray": lambda v=UNDEFINED: isinstance(v, list),
            "from": _array_from,
            "of": lambda *a: list(a),
        })
        g.declare("String", _js_string_fn)
        g.declare("Number", _js_number_fn)
        g.declare("Boolean", lambda v=UNDEFINED: _truthy(v))
        g.declare("parseFloat", _parse_float)
        g.declare("parseInt", _parse_int)
        g.declare("isFinite", lambda v=UNDEFINED: (
            not math.isnan(_to_number(v))
            and not math.isinf(_to_number(v))))
        g.declare("isNaN", lambda v=UNDEFINED: math.isnan(_to_number(v)))
        g.declare("encodeURIComponent", _encode_uri_component)
        g.declare("decodeURIComponent", _decode_uri_component)
        g.declare("console", {
            "log": lambda *a: None, "warn": lambda *a: None,
            "error": lambda *a: None,
        })

        def _promise(executor=UNDEFINED):
            # `new Promise(executor)`: run the executor NOW; resolve/
            # reject capture into the (possibly still pending) promise
            p = self._track(JSPromise())
            if executor is not UNDEFINED and executor is not None:
                self.invoke(executor,
                            [lambda v=UNDEFINED: p.resolve(v),
                             lambda e=UNDEFINED: p.reject(e)])
            return p

        g.declare("Promise", _promise)

    # -- statement execution ---------------------------------------------
    def exec_block(self, node, env: _Env):
        assert node[0] == "block"
        # hoist function declarations (the modules call helpers defined
        # later in the file)
        for stmt in node[1]:
            if stmt[0] == "funcdecl":
                env.declare(stmt[1],
                            JSFunction(stmt[1], stmt[2], stmt[3], env,
                                       self, is_async=stmt[4]))
        for stmt in node[1]:
            self.exec_stmt(stmt, env)

    def exec_stmt(self, node, env: _Env):
        kind = node[0]
        if kind == "expr":
            self.eval(node[1], env)
        elif kind == "decl":
            for target, init in node[2]:
                value = UNDEFINED if init is None else self.eval(init,
                                                                 env)
                self.bind_pattern(target, value, env, declare=True)
        elif kind == "funcdecl":
            env.declare(node[1], JSFunction(node[1], node[2], node[3],
                                            env, self,
                                            is_async=node[4]))
        elif kind == "block":
            self.exec_block(node, _Env(env))
        elif kind == "if":
            if _truthy(self.eval(node[1], env)):
                self.exec_stmt(node[2], _Env(env))
            elif node[3] is not None:
                self.exec_stmt(node[3], _Env(env))
        elif kind == "while":
            while _truthy(self.eval(node[1], env)):
                try:
                    self.exec_stmt(node[2], _Env(env))
                except _Break:
                    break
                except _Continue:
                    continue
        elif kind == "dowhile":
            while True:
                try:
                    self.exec_stmt(node[2], _Env(env))
                except _Break:
                    break
                except _Continue:
                    pass
                if not _truthy(self.eval(node[1], env)):
                    break
        elif kind == "for":
            loop_env = _Env(env)
            if node[1] is not None:
                self.exec_stmt(node[1], loop_env)
            while node[2] is None or _truthy(self.eval(node[2],
                                                       loop_env)):
                try:
                    self.exec_stmt(node[4], _Env(loop_env))
                except _Break:
                    break
                except _Continue:
                    pass
                if node[3] is not None:
                    self.eval(node[3], loop_env)
        elif kind == "forof":
            it = self.eval(node[3], env)
            if isinstance(it, str):
                seq: Any = list(it)
            elif isinstance(it, list):
                seq = list(it)
            elif isinstance(it, dict):
                raise JSError("TypeError: object is not iterable "
                              "(use Object.keys/entries)")
            else:
                raise JSError(f"TypeError: {_js_str(it)} is not "
                              "iterable")
            for item in seq:
                body_env = _Env(env)
                self.bind_pattern(node[2], item, body_env, declare=True)
                try:
                    self.exec_stmt(node[4], body_env)
                except _Break:
                    break
                except _Continue:
                    continue
        elif kind == "forin":
            it = self.eval(node[3], env)
            if isinstance(it, dict):
                keys = list(it.keys())
            elif isinstance(it, list):
                keys = [str(i) for i in range(len(it))]
            else:
                keys = []
            for key in keys:
                body_env = _Env(env)
                self.bind_pattern(node[2], key, body_env, declare=True)
                try:
                    self.exec_stmt(node[4], body_env)
                except _Break:
                    break
                except _Continue:
                    continue
        elif kind == "return":
            raise _Return(UNDEFINED if node[1] is None
                          else self.eval(node[1], env))
        elif kind == "break":
            raise _Break()
        elif kind == "continue":
            raise _Continue()
        elif kind == "throw":
            raise _Thrown(self.eval(node[1], env))
        elif kind == "try":
            _, body, param, handler, finalizer = node
            try:
                self.exec_block(body, _Env(env))
            except _Thrown as e:
                if handler is None:   # try/finally with no catch:
                    raise             # the finally below runs, then
                henv = _Env(env)      # the exception propagates (JS)
                if param:
                    henv.declare(param, e.value)
                self.exec_block(handler, henv)
            except JSError as e:
                if handler is None:
                    raise
                henv = _Env(env)
                if param:
                    henv.declare(param, {
                        "name": "Error", "message": str(e)})
                self.exec_block(handler, henv)
            finally:
                if finalizer is not None:
                    self.exec_block(finalizer, _Env(env))
        elif kind == "empty":
            pass
        else:  # pragma: no cover - parser emits only the kinds above
            raise JSError(f"unknown statement {kind}")

    def bind_pattern(self, target, value, env: _Env, declare: bool):
        kind = target[0]
        if kind == "ident_pat":
            if declare:
                env.declare(target[1], value)
            else:
                env.set(target[1], value)
        elif kind == "arr_pat":
            seq = value if isinstance(value, list) else \
                list(value) if isinstance(value, str) else None
            if seq is None:
                raise JSError("TypeError: cannot destructure "
                              f"{_js_str(value)} as an array")
            for i, sub in enumerate(target[1]):
                if sub is None:
                    continue
                item = seq[i] if i < len(seq) else UNDEFINED
                self.bind_pattern(sub, item, env, declare)
        elif kind == "obj_pat":
            if not isinstance(value, dict):
                raise JSError("TypeError: cannot destructure "
                              f"{_js_str(value)} as an object")
            for name, default in target[1]:
                item = value.get(name, UNDEFINED)
                if item is UNDEFINED and default is not None:
                    item = self.eval(default, env)
                if declare:
                    env.declare(name, item)
                else:
                    env.set(name, item)
        else:  # pragma: no cover
            raise JSError(f"unknown pattern {kind}")

    # -- function calls --------------------------------------------------
    def await_value(self, v):
        """``await``: unwrap a settled promise; rethrow rejections."""
        if isinstance(v, JSPromise):
            v.handled = True
            if v.state == "pending":
                raise JSError(
                    "await on a PENDING promise — no event loop here; "
                    "the host must settle it first (see module "
                    "docstring)")
            if v.state == "rejected":
                raise _Thrown(v.error)
            return v.value
        return v

    def call_function(self, fn: JSFunction, args: List[Any]):
        if fn.is_async:
            try:
                out = self._call_sync(fn, args)
                if isinstance(out, JSPromise):  # returned a promise:
                    return out                  # adopt, don't re-wrap
                return JSPromise.fulfilled(out)
            except _Thrown as e:
                return self._track(JSPromise.rejected(e.value))
            except JSError as e:
                return self._track(JSPromise.rejected(
                    {"name": "Error", "message": str(e)}))
        return self._call_sync(fn, args)

    def _call_sync(self, fn: JSFunction, args: List[Any]):
        env = _Env(fn.env)
        i = 0
        for p in fn.params:
            if p[0] == "rest":
                env.declare(p[1], list(args[i:]))
                i = len(args)
                continue
            _, target, default = p
            value = args[i] if i < len(args) else UNDEFINED
            if value is UNDEFINED and default is not None:
                value = self.eval(default, env)
            self.bind_pattern(target, value, env, declare=True)
            i += 1
        try:
            if fn.body[0] == "block":
                self.exec_block(fn.body, env)
            else:  # arrow expression body: ("return", expr)
                self.exec_stmt(fn.body, env)
        except _Return as r:
            return r.value
        return UNDEFINED

    # -- expression evaluation -------------------------------------------
    def eval(self, node, env: _Env):
        kind = node[0]
        if kind == "lit":
            v = node[1]
            return float(v) if isinstance(v, int) and not \
                isinstance(v, bool) else v
        if kind == "name":
            return env.lookup(node[1])
        if kind == "regex":
            return JSRegex(node[1], node[2])
        if kind == "template":
            return "".join(_js_str(self.eval(p, env)) for p in node[1])
        if kind == "array":
            out = []
            for k, e in node[1]:
                v = self.eval(e, env)
                if k == "spread":
                    if isinstance(v, list):
                        out.extend(v)
                    elif isinstance(v, str):
                        out.extend(list(v))
                    else:
                        raise JSError("TypeError: spread of "
                                      f"non-iterable {_js_str(v)}")
                else:
                    out.append(v)
            return out
        if kind == "object":
            out: Dict[str, Any] = {}
            for p in node[1]:
                if p[0] == "spread":
                    v = self.eval(p[1], env)
                    if isinstance(v, dict):
                        out.update(v)
                    elif isinstance(v, list):
                        out.update({str(i): x for i, x in enumerate(v)})
                    elif v is None or v is UNDEFINED:
                        pass
                    else:
                        raise JSError("TypeError: cannot spread "
                                      f"{_js_str(v)} into an object")
                else:
                    _, key_node, val_node = p
                    key = self.eval(key_node, env)
                    out[_js_str(key)] = self.eval(val_node, env)
            return out
        if kind == "func":
            return JSFunction(node[1], node[2], node[3], env, self,
                              is_async=node[4])
        if kind == "await":
            return self.await_value(self.eval(node[1], env))
        if kind == "new":
            args = []
            for k, e in node[2]:
                v = self.eval(e, env)
                if k == "spread":
                    args.extend(v if isinstance(v, list) else [v])
                else:
                    args.append(v)
            return self.invoke(self.eval(node[1], env), args)
        if kind == "cond":
            return self.eval(node[2] if _truthy(self.eval(node[1], env))
                             else node[3], env)
        if kind == "comma":
            self.eval(node[1], env)
            return self.eval(node[2], env)
        if kind == "bin":
            return self.eval_bin(node, env)
        if kind == "unary":
            return self.eval_unary(node, env)
        if kind == "update":
            return self.eval_update(node, env)
        if kind == "assign":
            return self.eval_assign(node, env)
        if kind == "member":
            obj = self.eval(node[1], env)
            if node[3] and (obj is None or obj is UNDEFINED):
                return UNDEFINED
            return self.get_member(obj, self.eval(node[2], env))
        if kind == "call":
            return self.eval_call(node, env)
        raise JSError(f"unknown expression {kind}")  # pragma: no cover

    def eval_bin(self, node, env: _Env):
        op = node[1]
        if op == "&&":
            left = self.eval(node[2], env)
            return self.eval(node[3], env) if _truthy(left) else left
        if op == "||":
            left = self.eval(node[2], env)
            return left if _truthy(left) else self.eval(node[3], env)
        if op == "??":
            left = self.eval(node[2], env)
            return self.eval(node[3], env) \
                if left is None or left is UNDEFINED else left
        a = self.eval(node[2], env)
        b = self.eval(node[3], env)
        return _binop(op, a, b)

    def eval_unary(self, node, env: _Env):
        op = node[1]
        if op == "typeof":
            try:
                v = self.eval(node[2], env)
            except JSError:
                return "undefined"
            return _typeof(v)
        v = self.eval(node[2], env)
        if op == "!":
            return not _truthy(v)
        if op == "-":
            return -_to_number(v)
        if op == "+":
            return _to_number(v)
        if op == "~":
            return float(~_to_int32(v))
        if op == "void":
            return UNDEFINED
        if op == "delete":
            return True
        raise JSError(f"unknown unary {op}")  # pragma: no cover

    def eval_update(self, node, env: _Env):
        _, op, target, prefix = node
        old = _to_number(self.eval(target, env))
        new = old + (1.0 if op == "++" else -1.0)
        self.write_target(target, new, env)
        return new if prefix else old

    def eval_assign(self, node, env: _Env):
        _, op, target, value_node = node
        if op == "=":
            value = self.eval(value_node, env)
        elif op in ("&&=", "||=", "??="):
            cur = self.eval(target, env)
            if op == "&&=" and not _truthy(cur):
                return cur
            if op == "||=" and _truthy(cur):
                return cur
            if op == "??=" and not (cur is None or cur is UNDEFINED):
                return cur
            value = self.eval(value_node, env)
        else:
            cur = self.eval(target, env)
            value = _binop(op[:-1], cur, self.eval(value_node, env))
        self.write_target(target, value, env)
        return value

    def write_target(self, target, value, env: _Env):
        if target[0] == "name":
            env.set(target[1], value)
        elif target[0] == "member":
            obj = self.eval(target[1], env)
            key = self.eval(target[2], env)
            if hasattr(obj, "js_set_member"):   # host objects
                obj.js_set_member(_js_str(key), value)
            elif isinstance(obj, dict):
                obj[_js_str(key)] = value
            elif isinstance(obj, list):
                idx = _to_int(key)
                if idx == len(obj):
                    obj.append(value)
                elif 0 <= idx < len(obj):
                    obj[idx] = value
                elif idx > len(obj):
                    obj.extend([UNDEFINED] * (idx - len(obj)))
                    obj.append(value)
                else:
                    raise JSError(f"bad array index {idx}")
            else:
                raise JSError("TypeError: cannot set property on "
                              f"{_js_str(obj)}")
        elif target[0] == "array":
            # [a, b] = expr — assignment destructuring
            if not isinstance(value, list):
                raise JSError("TypeError: destructuring non-array")
            for i, (k, e) in enumerate(target[1]):
                if k == "spread":
                    self.write_target(e, value[i:], env)
                    break
                self.write_target(e, value[i] if i < len(value)
                                  else UNDEFINED, env)
        else:
            raise JSError("invalid assignment target")

    def eval_call(self, node, env: _Env):
        _, callee, arg_nodes, optional = node
        args: List[Any] = []
        for k, e in arg_nodes:
            v = self.eval(e, env)
            if k == "spread":
                if isinstance(v, list):
                    args.extend(v)
                else:
                    raise JSError("TypeError: spread of non-array")
            else:
                args.append(v)
        # Method call: evaluate the object once so mutations stick.
        if callee[0] == "member":
            obj = self.eval(callee[1], env)
            if callee[3] and (obj is None or obj is UNDEFINED):
                return UNDEFINED
            key = self.eval(callee[2], env)
            method = self.get_member(obj, key)
            if method is UNDEFINED:
                raise JSError(
                    f"TypeError: {_js_str(key)} is not a function on "
                    f"{_typeof(obj)}")
            return self.invoke(method, args)
        fn = self.eval(callee, env)
        if optional and (fn is None or fn is UNDEFINED):
            return UNDEFINED
        return self.invoke(fn, args)

    def invoke(self, fn, args: List[Any]):
        if isinstance(fn, JSFunction):
            return fn.interp.call_function(fn, args)
        if callable(fn):
            out = fn(*args)
            if isinstance(out, int) and not isinstance(out, bool):
                return float(out)
            return out
        raise JSError(f"TypeError: {_js_str(fn)} is not a function")

    # -- member access ---------------------------------------------------
    def get_member(self, obj, key):
        name = _js_str(key)
        if obj is None or obj is UNDEFINED:
            raise JSError(
                f"TypeError: cannot read property {name!r} of "
                f"{_js_str(obj)}")
        if isinstance(obj, JSPromise):
            return self._promise_member(obj, name)
        if hasattr(obj, "js_get_member"):  # host objects (jsdom etc.)
            return obj.js_get_member(name)
        if isinstance(obj, dict):
            if name in obj:
                return obj[name]
            return UNDEFINED
        if isinstance(obj, list):
            if name == "length":
                return float(len(obj))
            if isinstance(key, float) or name.lstrip("-").isdigit():
                idx = _to_int(key)
                return obj[idx] if 0 <= idx < len(obj) else UNDEFINED
            return _array_method(self, obj, name)
        if isinstance(obj, str):
            if name == "length":
                return float(len(obj))
            if isinstance(key, float) or name.isdigit():
                idx = _to_int(key)
                return obj[idx] if 0 <= idx < len(obj) else UNDEFINED
            return _string_method(self, obj, name)
        if isinstance(obj, bool):
            return UNDEFINED
        if isinstance(obj, float):
            return _number_method(obj, name)
        if isinstance(obj, JSRegex):
            return _regex_method(obj, name)
        if isinstance(obj, JSFunction) or callable(obj):
            if name == "name" and isinstance(obj, JSFunction):
                return obj.name
            if name == "call":
                return lambda _this=UNDEFINED, *a: self.invoke(obj,
                                                               list(a))
            if name == "apply":
                return lambda _this=UNDEFINED, a=None: self.invoke(
                    obj, list(a or []))
            return UNDEFINED
        raise JSError(f"TypeError: cannot read {name!r} of "
                      f"{type(obj).__name__}")

    def _promise_member(self, p: JSPromise, name: str):
        """then/catch/finally: reactions run synchronously once the
        promise is settled (queued if attached while pending), with
        SYMMETRIC semantics for both branches — handler results are
        flattened through await_value and handler throws become
        downstream rejections."""

        def settle_with(handler, arg, d: JSPromise):
            try:
                out = self.invoke(handler, [arg])
            except _Thrown as e:
                d.reject(e.value)
                return
            except JSError as e:
                d.reject({"name": "Error", "message": str(e)})
                return
            if isinstance(out, JSPromise):
                # ADOPT a returned promise (even a pending one — the
                # chain resumes when the host settles it); `await` is
                # the only place pending is an error
                out.subscribe(
                    lambda pp: d.resolve(pp.value)
                    if pp.state == "fulfilled" else d.reject(pp.error))
            else:
                d.resolve(out)

        def make_then(on_ok=UNDEFINED, on_err=UNDEFINED):
            d = self._track(JSPromise())

            def react(pp: JSPromise):
                if pp.state == "fulfilled":
                    if on_ok is not None and on_ok is not UNDEFINED:
                        settle_with(on_ok, pp.value, d)
                    else:
                        d.resolve(pp.value)
                else:
                    if on_err is not None and on_err is not UNDEFINED:
                        settle_with(on_err, pp.error, d)
                    else:
                        d.reject(pp.error)

            p.subscribe(react)
            return d

        if name == "then":
            return make_then
        if name == "catch":
            return lambda on_err=UNDEFINED: make_then(UNDEFINED, on_err)
        if name == "finally":
            def finally_(fn=UNDEFINED):
                d = self._track(JSPromise())

                def react(pp: JSPromise):
                    if fn is not None and fn is not UNDEFINED:
                        try:
                            self.invoke(fn, [])
                        except _Thrown as e:
                            d.reject(e.value)
                            return
                    if pp.state == "fulfilled":
                        d.resolve(pp.value)
                    else:
                        d.reject(pp.error)

                p.subscribe(react)
                return d
            return finally_
        return UNDEFINED


# ---------------------------------------------------------------------------
# Operators
# ---------------------------------------------------------------------------

def _binop(op: str, a, b):
    if op == "+":
        if isinstance(a, str) or isinstance(b, str) or \
                isinstance(a, (list, dict)) or isinstance(b, (list,
                                                              dict)):
            return _js_str(a) + _js_str(b)
        return _to_number(a) + _to_number(b)
    if op == "-":
        return _to_number(a) - _to_number(b)
    if op == "*":
        return _to_number(a) * _to_number(b)
    if op == "/":
        x, y = _to_number(a), _to_number(b)
        if y == 0:
            if x == 0 or math.isnan(x):
                return math.nan
            return math.copysign(math.inf, x) * math.copysign(1, y)
        return x / y
    if op == "%":
        x, y = _to_number(a), _to_number(b)
        if y == 0 or math.isnan(x) or math.isnan(y) or math.isinf(x):
            return math.nan
        return math.fmod(x, y)
    if op == "**":
        return float(_to_number(a) ** _to_number(b))
    if op == "===":
        return _strict_eq(a, b)
    if op == "!==":
        return not _strict_eq(a, b)
    if op == "==":
        return _loose_eq(a, b)
    if op == "!=":
        return not _loose_eq(a, b)
    if op in ("<", ">", "<=", ">="):
        if isinstance(a, str) and isinstance(b, str):
            pass
        else:
            a, b = _to_number(a), _to_number(b)
            if math.isnan(a) or math.isnan(b):
                return False
        return {"<": a < b, ">": a > b,
                "<=": a <= b, ">=": a >= b}[op]
    if op == "in":
        if isinstance(b, dict):
            return _js_str(a) in b
        if isinstance(b, list):
            return 0 <= _to_int(a) < len(b)
        raise JSError("TypeError: 'in' on non-object")
    if op == "instanceof":
        return False
    raise JSError(f"unknown operator {op}")  # pragma: no cover


def _typeof(v) -> str:
    if v is UNDEFINED:
        return "undefined"
    if v is None:
        return "object"
    if isinstance(v, bool):
        return "boolean"
    if isinstance(v, float):
        return "number"
    if isinstance(v, str):
        return "string"
    if isinstance(v, JSFunction) or callable(v):
        return "function"
    return "object"


def _to_int32(v) -> int:
    n = _to_number(v)
    if math.isnan(n) or math.isinf(n):
        return 0
    n = int(n) & 0xFFFFFFFF
    return n - 0x100000000 if n >= 0x80000000 else n


def _js_math_round(x: float) -> float:
    """Math.round: half toward +Infinity (round(-0.5) === -0)."""
    if math.isnan(x) or math.isinf(x):
        return x
    return float(math.floor(x + 0.5))


# ---------------------------------------------------------------------------
# Methods on builtin types
# ---------------------------------------------------------------------------

def _array_method(interp: Interpreter, arr: list, name: str):
    def fn_index(v, default):
        for i, x in enumerate(arr):
            if _strict_eq(x, v):
                return float(i)
        return default

    table: Dict[str, Callable] = {
        "push": lambda *a: (arr.extend(a), float(len(arr)))[1],
        "pop": lambda: arr.pop() if arr else UNDEFINED,
        "shift": lambda: arr.pop(0) if arr else UNDEFINED,
        "unshift": lambda *a: (arr.__setitem__(slice(0, 0), list(a)),
                               float(len(arr)))[1],
        "slice": lambda start=UNDEFINED, end=UNDEFINED:
            arr[_slice_idx(start, len(arr), 0):
                _slice_idx(end, len(arr), len(arr))],
        "splice": lambda start=0.0, count=None, *items:
            _splice(arr, start, count, items),
        "concat": lambda *a: arr + [x for b in a for x in
                                    (b if isinstance(b, list) else
                                     [b])],
        "join": lambda sep=",": _js_str(sep if sep is not UNDEFINED
                                        else ",").join(
            "" if x is None or x is UNDEFINED else _js_str(x)
            for x in arr),
        "indexOf": lambda v=UNDEFINED: fn_index(v, -1.0),
        "includes": lambda v=UNDEFINED: fn_index(v, None) is not None,
        "find": lambda f: next((x for i, x in enumerate(arr)
                                if _truthy(interp.invoke(f,
                                                         [x, float(i)]))),
                               UNDEFINED),
        "findIndex": lambda f: next(
            (float(i) for i, x in enumerate(arr)
             if _truthy(interp.invoke(f, [x, float(i)]))), -1.0),
        "map": lambda f: [interp.invoke(f, [x, float(i), arr])
                          for i, x in enumerate(arr)],
        "filter": lambda f: [x for i, x in enumerate(arr)
                             if _truthy(interp.invoke(
                                 f, [x, float(i), arr]))],
        "forEach": lambda f: ([interp.invoke(f, [x, float(i), arr])
                               for i, x in enumerate(arr)],
                              UNDEFINED)[1],
        "reduce": lambda f, *init: _reduce(interp, arr, f, init),
        "some": lambda f: any(_truthy(interp.invoke(f, [x, float(i)]))
                              for i, x in enumerate(arr)),
        "every": lambda f: all(_truthy(interp.invoke(f, [x, float(i)]))
                               for i, x in enumerate(arr)),
        "reverse": lambda: (arr.reverse(), arr)[1],
        "flat": lambda depth=1.0: _flat(arr, _to_int(depth)),
        "sort": lambda cmp=None: _sort(interp, arr, cmp),
        "fill": lambda v=UNDEFINED: (arr.__setitem__(
            slice(None), [v] * len(arr)), arr)[1],
        "keys": lambda: [float(i) for i in range(len(arr))],
        "flatMap": lambda f: _flat(
            [interp.invoke(f, [x, float(i), arr])
             for i, x in enumerate(arr)], 1),
    }
    if name in table:
        return table[name]
    return UNDEFINED


def _splice(arr, start, count, items):
    n = len(arr)
    s = _to_int(start)
    s = max(n + s, 0) if s < 0 else min(s, n)
    c = n - s if count is None or count is UNDEFINED \
        else max(0, _to_int(count))
    removed = arr[s:s + c]
    arr[s:s + c] = list(items)
    return removed


def _reduce(interp, arr, f, init):
    items = list(arr)
    if init:
        acc = init[0]
        start = 0
    else:
        if not items:
            raise _Thrown({"name": "TypeError",
                           "message": "Reduce of empty array with no "
                                      "initial value"})
        acc = items[0]
        start = 1
    for i in range(start, len(items)):
        acc = interp.invoke(f, [acc, items[i], float(i), arr])
    return acc


def _flat(arr, depth: int):
    out = []
    for x in arr:
        if isinstance(x, list) and depth > 0:
            out.extend(_flat(x, depth - 1))
        else:
            out.append(x)
    return out


def _sort(interp, arr, cmp):
    import functools

    if cmp is None or cmp is UNDEFINED:
        arr.sort(key=_js_str)
    else:
        def compare(a, b):
            r = _to_number(interp.invoke(cmp, [a, b]))  # once per pair
            return -1 if r < 0 else (1 if r > 0 else 0)

        arr.sort(key=functools.cmp_to_key(compare))
    return arr


def _slice_idx(v, n: int, default: int) -> int:
    if v is UNDEFINED or v is None:
        return default
    i = _to_int(v)
    if i < 0:
        return max(n + i, 0)
    return min(i, n)


def _string_method(interp: Interpreter, s: str, name: str):
    table: Dict[str, Callable] = {
        "split": lambda sep=UNDEFINED, limit=UNDEFINED:
            _str_split(s, sep, limit),
        "slice": lambda a=UNDEFINED, b=UNDEFINED:
            s[_slice_idx(a, len(s), 0):_slice_idx(b, len(s), len(s))],
        "substring": lambda a=0.0, b=UNDEFINED: _substring(s, a, b),
        "indexOf": lambda sub="": float(s.find(_js_str(sub))),
        "lastIndexOf": lambda sub="": float(s.rfind(_js_str(sub))),
        "includes": lambda sub="": _js_str(sub) in s,
        "startsWith": lambda sub="": s.startswith(_js_str(sub)),
        "endsWith": lambda sub="": s.endswith(_js_str(sub)),
        "toLowerCase": lambda: s.lower(),
        "toUpperCase": lambda: s.upper(),
        "trim": lambda: s.strip(),
        "trimStart": lambda: s.lstrip(),
        "trimEnd": lambda: s.rstrip(),
        "charAt": lambda i=0.0: s[_to_int(i)]
        if 0 <= _to_int(i) < len(s) else "",
        "charCodeAt": lambda i=0.0: float(ord(s[_to_int(i)]))
        if 0 <= _to_int(i) < len(s) else math.nan,
        "padStart": lambda n, fill=" ": _pad(s, n, fill, True),
        "padEnd": lambda n, fill=" ": _pad(s, n, fill, False),
        "repeat": lambda n=0.0: s * _to_int(n),
        "concat": lambda *a: s + "".join(_js_str(x) for x in a),
        "replace": lambda pat, rep: _str_replace(interp, s, pat, rep,
                                                 first_only=True),
        "replaceAll": lambda pat, rep: _str_replace(interp, s, pat, rep,
                                                    first_only=False),
        "match": lambda pat: _str_match(s, pat),
        "search": lambda pat: _str_search(s, pat),
        "toString": lambda: s,
        "localeCompare": lambda o="": float((s > _js_str(o)) -
                                            (s < _js_str(o))),
    }
    if name in table:
        return table[name]
    return UNDEFINED


def _substring(s: str, a, b):
    n = len(s)
    ia = min(max(_to_int(a), 0), n)
    ib = n if b is UNDEFINED else min(max(_to_int(b), 0), n)
    if ia > ib:
        ia, ib = ib, ia
    return s[ia:ib]


def _pad(s: str, n, fill, start: bool) -> str:
    target = _to_int(n)
    fill = _js_str(fill) or " "
    if len(s) >= target:
        return s
    pad = (fill * target)[: target - len(s)]
    return pad + s if start else s + pad


def _str_split(s: str, sep, limit):
    if sep is UNDEFINED:
        out = [s]
    elif isinstance(sep, JSRegex):
        out = sep.compiled.split(s)
    else:
        sep = _js_str(sep)
        out = list(s) if sep == "" else s.split(sep)
    if limit is not UNDEFINED:
        out = out[:_to_int(limit)]
    return out


def _replacement(template: str, m: "_re.Match") -> str:
    out, i = [], 0
    while i < len(template):
        c = template[i]
        if c == "$" and i + 1 < len(template):
            nxt = template[i + 1]
            if nxt == "$":
                out.append("$")
                i += 2
                continue
            if nxt == "&":
                out.append(m.group(0))
                i += 2
                continue
            if nxt.isdigit():
                j = i + 1
                while j < len(template) and template[j].isdigit():
                    j += 1
                idx = int(template[i + 1:j])
                try:
                    out.append(m.group(idx) or "")
                except (IndexError, _re.error):
                    out.append(template[i:j])
                i = j
                continue
        out.append(c)
        i += 1
    return "".join(out)


def _str_replace(interp, s: str, pat, rep, first_only: bool) -> str:
    def do_one(m):
        if isinstance(rep, JSFunction) or callable(rep):
            groups = [m.group(0)] + [g if g is not None else UNDEFINED
                                     for g in m.groups()]
            return _js_str(interp.invoke(rep, [*groups,
                                               float(m.start()), s]))
        return _replacement(_js_str(rep), m)

    if isinstance(pat, JSRegex):
        count = 0 if "g" in pat.flags else 1
        return pat.compiled.sub(do_one, s, count=count)
    target = _js_str(pat)
    idx = s.find(target)
    if idx < 0:
        return s

    def one(at: int) -> str:
        if isinstance(rep, JSFunction) or callable(rep):
            # per-occurrence callback with ITS offset, as in JS
            return _js_str(interp.invoke(rep, [target, float(at), s]))
        return _js_str(rep).replace("$&", target)

    if first_only:
        return s[:idx] + one(idx) + s[idx + len(target):]
    if target == "":
        return s  # JS inserts between chars; not needed by the modules
    out, pos = [], 0
    while True:
        idx = s.find(target, pos)
        if idx < 0:
            out.append(s[pos:])
            return "".join(out)
        out.append(s[pos:idx])
        out.append(one(idx))
        pos = idx + len(target)


def _str_match(s: str, pat):
    if not isinstance(pat, JSRegex):
        pat = JSRegex(_re.escape(_js_str(pat)), "")
    if "g" in pat.flags:
        out = [m.group(0) for m in pat.compiled.finditer(s)]
        return out if out else None
    m = pat.compiled.search(s)
    if not m:
        return None
    return [m.group(0)] + [g if g is not None else UNDEFINED
                           for g in m.groups()]


def _str_search(s: str, pat):
    if not isinstance(pat, JSRegex):
        pat = JSRegex(_re.escape(_js_str(pat)), "")
    m = pat.compiled.search(s)
    return float(m.start()) if m else -1.0


def _number_method(x: float, name: str):
    table: Dict[str, Callable] = {
        "toFixed": lambda digits=0.0: _js_tofixed(x, _to_int(digits)),
        "toString": lambda base=10.0: _num_to_string(x, _to_int(base)),
        # en-US default: thousands separators, ≤3 fraction digits,
        # ties away from zero via _js_tofixed (the pinned semantics)
        "toLocaleString": lambda *a: _num_to_locale(x),
        "toPrecision": lambda p=UNDEFINED: _js_number_str(x)
        if p is UNDEFINED else f"{x:.{_to_int(p)}g}",
        "valueOf": lambda: x,
    }
    if name in table:
        return table[name]
    return UNDEFINED


def _num_to_locale(x: float) -> str:
    """Number.prototype.toLocaleString, en-US defaults: grouping +
    up to 3 fraction digits, ties away from zero (Intl halfExpand —
    same rule _js_tofixed pins for toFixed)."""
    if not math.isfinite(x):
        return _js_number_str(x)
    if x == int(x):
        return f"{int(x):,}"
    fixed = _js_tofixed(x, 3)           # sign + tie handling pinned
    sign = "-" if fixed.startswith("-") else ""
    whole, frac = fixed.lstrip("-").split(".")
    frac = frac.rstrip("0")
    grouped = f"{int(whole):,}"
    return sign + grouped + ("." + frac if frac else "")


def _num_to_string(x: float, base: int) -> str:
    if base == 10:
        return _js_number_str(x)
    if x != int(x):
        raise _Thrown({"name": "RangeError",
                       "message": "non-integer toString(base)"})
    digits = "0123456789abcdefghijklmnopqrstuvwxyz"
    n = int(abs(x))
    out = ""
    while True:
        out = digits[n % base] + out
        n //= base
        if n == 0:
            break
    return ("-" if x < 0 else "") + out


def _regex_method(rx: JSRegex, name: str):
    if name == "test":
        return lambda s=UNDEFINED: rx.compiled.search(_js_str(s)) \
            is not None
    if name == "source":
        return rx.source
    if name == "flags":
        return rx.flags
    if name == "exec":
        def exec_(s=UNDEFINED):
            m = rx.compiled.search(_js_str(s))
            if not m:
                return None
            return [m.group(0)] + [g if g is not None else UNDEFINED
                                   for g in m.groups()]
        return exec_
    return UNDEFINED


def _object_assign(target=None, *sources):
    if not isinstance(target, dict):
        raise JSError("TypeError: Object.assign target must be an "
                      "object")
    for s in sources:
        if isinstance(s, dict):
            target.update(s)
    return target


def _array_from(v=UNDEFINED, fn=None):
    if isinstance(v, list):
        out = list(v)
    elif isinstance(v, str):
        out = list(v)
    elif isinstance(v, dict) and "length" in v:
        out = [v.get(str(i), UNDEFINED)
               for i in range(_to_int(v["length"]))]
    else:
        out = []
    if fn is not None and fn is not UNDEFINED:
        raise JSError("Array.from map fn unsupported; map after")
    return out


def _js_string_fn(v=UNDEFINED):
    return _js_str(v) if v is not UNDEFINED else ""


def _js_number_fn(v=UNDEFINED):
    return _to_number(v) if v is not UNDEFINED else 0.0


def _parse_float(v=UNDEFINED):
    s = _js_str(v).strip()
    m = _re.match(r"[+-]?(\d+\.?\d*|\.\d+)([eE][+-]?\d+)?", s)
    return float(m.group()) if m else math.nan


def _parse_int(v=UNDEFINED, base=UNDEFINED):
    s = _js_str(v).strip()
    b = 10 if base is UNDEFINED else (_to_int(base) or 10)
    if b < 2 or b > 36:
        return math.nan
    if b == 16 or (b == 10 and s[:2].lower() == "0x"):
        m = _re.match(r"[+-]?(0[xX])?[0-9a-fA-F]+", s)
        if not m:
            return math.nan
        return float(int(m.group(), 16))
    # JS: parse the longest prefix of digits VALID FOR THE BASE
    # (parseInt('19', 8) === 1), never raise
    digits = "0123456789abcdefghijklmnopqrstuvwxyz"[:b]
    i = 0
    sign = 1
    if i < len(s) and s[i] in "+-":
        sign = -1 if s[i] == "-" else 1
        i += 1
    j = i
    while j < len(s) and s[j].lower() in digits:
        j += 1
    if j == i:
        return math.nan
    return float(sign * int(s[i:j], b))


_URI_SAFE = ("ABCDEFGHIJKLMNOPQRSTUVWXYZabcdefghijklmnopqrstuvwxyz"
             "0123456789-_.!~*'()")


def _encode_uri_component(v=UNDEFINED) -> str:
    out = []
    for ch in _js_str(v):
        if ch in _URI_SAFE:
            out.append(ch)
        else:
            out.extend(f"%{b:02X}" for b in ch.encode("utf-8"))
    return "".join(out)


def _decode_uri_component(v=UNDEFINED) -> str:
    from urllib.parse import unquote

    return unquote(_js_str(v))


# ---------------------------------------------------------------------------
# Convenience entry points
# ---------------------------------------------------------------------------

def run_source(source: str,
               rng: Optional[Callable[[], float]] = None) -> Interpreter:
    it = Interpreter(rng=rng)
    it.run(source)
    return it


def run_file(path: str,
             rng: Optional[Callable[[], float]] = None) -> Interpreter:
    with open(path, "r", encoding="utf-8") as f:
        return run_source(f.read(), rng=rng)
