"""Filesystem helpers shared by the on-disk caches."""

from __future__ import annotations

import os
import tempfile
from typing import Optional


def secure_user_cache_dir(prefix: str) -> Optional[str]:
    """A per-user 0700 cache directory under the system temp dir, or None
    when it cannot be created or is not trustworthy.

    Both native-library and XLA-executable caches deserialize their
    contents into the process, so a path another local user could have
    planted (not ours, group/world-writable, or a pre-existing non-dir /
    symlink) is rejected rather than trusted.
    """
    base = os.path.join(tempfile.gettempdir(), f"{prefix}_{os.getuid()}")
    try:
        os.makedirs(base, mode=0o700, exist_ok=True)
        st = os.lstat(base)
    except OSError:
        return None  # planted file / unwritable tmp: degrade, don't crash
    if not os.path.isdir(base) or os.path.islink(base):
        return None
    if st.st_uid != os.getuid() or (st.st_mode & 0o022):
        return None
    return base
