"""Deterministic fault injection at named IO boundaries.

Chaos-engineering practice (Basiri et al., IEEE Software 2016) says
resilience untested by fault injection is resilience assumed, not had.
This package is the test rig: every IO boundary in the serving stack
calls ``inject("<point>")`` at its entry — a no-op in production, a
seeded fault generator when ``RTPU_CHAOS_SPEC`` names that point.

Registered fault points (see docs/ROBUSTNESS.md for the full table):

- ``store.http``       — every store backend call (inside the retry loop,
  so each attempt can fail independently)
- ``netbus.publish`` / ``netbus.subscribe`` — broker socket operations
- ``device.compute``   — the batcher's device scoring call
- ``gateway.forward`` and ``gateway.forward.<replica-id>`` — each
  proxied upstream exchange (per-replica points let a spec slow or kill
  exactly one replica's hops)
- ``replica.kill``     — actuated manually via
  ``ReplicaSupervisor.kill_replica`` (a process kill cannot be a
  probability draw inside the victim); recorded here for one unified
  injection ledger
- ``replica.boot`` and ``replica.boot.<version>`` — supervisor spawn:
  an ``error``/``drop`` substitutes an argv that exits immediately (the
  bad-deploy crash loop, deterministic), ``latency`` delays the spawn
  (slow boot); the per-version point lets a spec doom exactly one
  rollout's spawns
- ``model.load``       — serving-artifact load (startup AND hot-swap
  replacement builds): an injected fault degrades exactly like a
  corrupt file — load_error set, the old model keeps serving

Four fault kinds per point, each with its own probability:

- ``latency`` — sleep ``arg`` milliseconds, then continue (the call
  still happens; stacks with error/drop)
- ``error``   — raise :class:`ChaosError` (application-level failure:
  an HTTP 5xx, a dead device)
- ``drop``    — raise :class:`ChaosConnectionDrop` (a
  ``ConnectionError`` subclass, so existing transport-failure handling
  — gateway retry, breaker charging, store journaling — takes over)
- ``skew``    — return ``arg`` as a perturbation magnitude the CALL
  SITE applies to its own result (``inject`` returns the summed fired
  magnitudes; sites that ignore the return are unaffected). This is
  the silent-wrongness fault: at ``device.compute`` the batcher adds
  the magnitude (output minutes) to every scored row, so the replica
  keeps answering 200s — confidently, and wrong. Nothing inside the
  serving path can see it; only the blackbox prober's oracle
  comparison (docs/OBSERVABILITY.md "Synthetic probing") does.

Spec grammar (``RTPU_CHAOS_SPEC``)::

    spec   ::= point ( ";" point )*
    point  ::= name ":" fault ( "," fault )*
    fault  ::= kind "=" prob [ "/" arg_ms ] [ "@" limit ]

    e.g.  store.http:error=1.0@40
          device.compute:latency=0.3/250,error=0.05
          gateway.forward.r1:latency=1.0/300

``@limit`` bounds how many times a rule fires — the deterministic way
to model an outage that ENDS (first N calls fail, then the backend is
healthy again). Draws come from one ``random.Random`` per point, seeded
by ``RTPU_CHAOS_SEED`` xor the point name, so a given (spec, seed)
replays the exact same failure sequence every run — the property the
regression tests pin.
"""

from __future__ import annotations

import dataclasses
import random
import threading
import time
import zlib
from typing import Dict, List, Mapping, Optional

from routest_tpu.obs import get_registry
from routest_tpu.utils.logging import get_logger

_log = get_logger("routest_tpu.chaos")

KINDS = ("latency", "error", "drop", "skew")


class ChaosError(RuntimeError):
    """Injected application-level failure (a 5xx, a dead device)."""


class ChaosConnectionDrop(ChaosError, ConnectionError):
    """Injected transport-level drop. Subclasses ``ConnectionError``
    (hence ``OSError``) so every existing transport-failure path —
    gateway retry/breaker, store journaling, netbus buffering — handles
    it exactly like a real dead socket."""


@dataclasses.dataclass
class FaultRule:
    """One (kind, probability) rule at a point. ``arg_ms`` is the
    latency to add (latency kind only); ``limit`` caps total fires
    (None = unbounded)."""

    kind: str
    prob: float
    arg_ms: float = 100.0
    limit: Optional[int] = None
    fired: int = 0

    def exhausted(self) -> bool:
        return self.limit is not None and self.fired >= self.limit


def parse_spec(spec: str) -> Dict[str, List[FaultRule]]:
    """Spec string → {point: [rules]}. Malformed tokens are skipped
    with a logged warning — a typo in an ops knob must degrade to
    "that fault doesn't fire", never crash the server it configures."""
    points: Dict[str, List[FaultRule]] = {}
    for point_tok in (spec or "").split(";"):
        point_tok = point_tok.strip()
        if not point_tok:
            continue
        name, sep, faults = point_tok.partition(":")
        name = name.strip()
        if not sep or not name:
            _log.warning("chaos_spec_malformed", token=point_tok)
            continue
        rules: List[FaultRule] = []
        for fault_tok in faults.split(","):
            fault_tok = fault_tok.strip()
            if not fault_tok:
                continue
            rule = _parse_fault(fault_tok)
            if rule is None:
                _log.warning("chaos_spec_malformed", point=name,
                             token=fault_tok)
                continue
            rules.append(rule)
        if rules:
            points.setdefault(name, []).extend(rules)
    return points


def _parse_fault(tok: str) -> Optional[FaultRule]:
    kind, sep, rest = tok.partition("=")
    kind = kind.strip()
    if not sep or kind not in KINDS:
        return None
    limit: Optional[int] = None
    if "@" in rest:
        rest, _, limit_s = rest.partition("@")
        try:
            limit = int(limit_s)
        except ValueError:
            return None
        if limit < 0:
            return None
    arg_ms = 100.0
    if "/" in rest:
        rest, _, arg_s = rest.partition("/")
        try:
            arg_ms = float(arg_s)
        except ValueError:
            return None
        if not (arg_ms >= 0):  # NaN-proof
            return None
    try:
        prob = float(rest)
    except ValueError:
        return None
    if not (0.0 <= prob <= 1.0):  # NaN-proof
        return None
    return FaultRule(kind=kind, prob=prob, arg_ms=arg_ms, limit=limit)


class FaultPoint:
    """One named injection site: its rules plus a dedicated seeded RNG.

    The RNG is per-point (seed xor crc32(name)) so adding a point to a
    spec never perturbs another point's failure sequence — each point's
    outcome stream depends only on (seed, name, call index)."""

    def __init__(self, name: str, rules: List[FaultRule], seed: int) -> None:
        self.name = name
        self.rules = rules
        self.calls = 0
        self._rng = random.Random((seed << 32) ^ zlib.crc32(name.encode()))
        self._lock = threading.Lock()

    def fire(self) -> float:
        """One injection decision: may sleep, may raise; returns the
        summed ``skew`` magnitudes that fired (0.0 normally) for the
        call site to apply to its own result. Decisions are made under
        the lock (one RNG draw per rule per call, in rule order) so
        the outcome SEQUENCE is deterministic; the sleep and raise
        happen outside it."""
        delay_ms = 0.0
        skew = 0.0
        exc: Optional[ChaosError] = None
        fired = []
        first_fired = []
        with self._lock:
            self.calls += 1
            for rule in self.rules:
                if rule.exhausted():
                    continue
                if self._rng.random() >= rule.prob:
                    continue
                rule.fired += 1
                fired.append(rule.kind)
                if rule.fired == 1:
                    first_fired.append(rule.kind)
                if rule.kind == "latency":
                    delay_ms += rule.arg_ms
                elif rule.kind == "skew":
                    skew += rule.arg_ms
                elif exc is None:
                    exc = (ChaosError(f"injected error at {self.name}")
                           if rule.kind == "error" else
                           ChaosConnectionDrop(
                               f"injected connection drop at {self.name}"))
        for kind in fired:
            _INJECTIONS.labels(point=self.name, kind=kind).inc()
        # The change ledger records only each rule's FIRST fire: a
        # hot-path point at prob 1.0 is one state change (the fault
        # became live), not thousands of ledger entries.
        for kind in first_fired:
            from routest_tpu.obs.ledger import record_change

            record_change("chaos.fire",
                          detail={"point": self.name, "kind": kind})
        if delay_ms:
            time.sleep(delay_ms / 1000.0)
        if exc is not None:
            raise exc
        return skew


_INJECTIONS = get_registry().counter(
    "rtpu_chaos_injections_total",
    "Faults injected, by point and kind.", ("point", "kind"))


class ChaosEngine:
    """All fault points for one (spec, seed). ``inject`` is the hot-path
    entry: a dict miss + enabled check when the point isn't configured,
    so production cost is negligible."""

    def __init__(self, spec: str = "", seed: int = 0,
                 enabled: bool = True) -> None:
        self.spec = spec or ""
        self.seed = seed
        self.enabled = enabled and bool(self.spec.strip())
        self._points = {name: FaultPoint(name, rules, seed)
                        for name, rules in parse_spec(self.spec).items()}
        if self.enabled:
            from routest_tpu.obs.ledger import record_change

            record_change("chaos.arm",
                          detail={"spec": self.spec, "seed": seed,
                                  "points": sorted(self._points)})
            _log.warning("chaos_enabled", seed=seed,
                         points=sorted(self._points))

    def inject(self, name: str) -> float:
        """→ the summed ``skew`` magnitudes that fired (0.0 when the
        point is unconfigured or nothing fired); may sleep or raise
        for the other kinds. Call sites that ignore the return keep
        their historical latency/error/drop semantics untouched."""
        if not self.enabled:
            return 0.0
        point = self._points.get(name)
        if point is None:
            return 0.0
        return point.fire()

    def record(self, name: str, kind: str) -> None:
        """Ledger entry for a fault actuated OUTSIDE the engine (e.g.
        ``replica.kill`` — the supervisor kills the process; the engine
        only counts it). Externally-actuated faults are rare and each
        IS a state change, so every one lands in the change ledger."""
        _INJECTIONS.labels(point=name, kind=kind).inc()
        from routest_tpu.obs.ledger import record_change

        record_change("chaos.fire",
                      detail={"point": name, "kind": kind,
                              "actuated": "external"})

    def snapshot(self) -> dict:
        """Per-point injection counts (for /api/metrics debugging and
        the chaos bench artifact)."""
        return {
            name: {
                "calls": p.calls,
                "rules": [{"kind": r.kind, "prob": r.prob,
                           "arg_ms": r.arg_ms, "limit": r.limit,
                           "fired": r.fired} for r in p.rules],
            }
            for name, p in sorted(self._points.items())
        }


_engine: Optional[ChaosEngine] = None
_engine_lock = threading.Lock()


def get_chaos() -> ChaosEngine:
    """The process-wide engine, built lazily from ``RTPU_CHAOS_*`` env
    (disabled when no spec is set)."""
    global _engine
    if _engine is None:
        with _engine_lock:
            if _engine is None:
                from routest_tpu.core.config import load_chaos_config

                cfg = load_chaos_config()
                _engine = ChaosEngine(spec=cfg.spec, seed=cfg.seed,
                                      enabled=cfg.enabled)
    return _engine


def configure(engine: Optional[ChaosEngine]) -> None:
    """Install an engine explicitly (tests, the chaos bench); ``None``
    resets to lazy env-driven construction."""
    global _engine
    with _engine_lock:
        _engine = engine


def inject(name: str) -> float:
    """Module-level convenience: ``chaos.inject("store.http")``.
    Returns the fired ``skew`` magnitude (0.0 normally) — only sites
    that can meaningfully perturb their result read it."""
    return get_chaos().inject(name)


def current_engine() -> Optional[ChaosEngine]:
    """The installed engine when injection is LIVE, else None — without
    building one from env (readers like the flight recorder stamp chaos
    state onto every request record and must not pay a config parse
    when chaos was never configured)."""
    engine = _engine
    return engine if engine is not None and engine.enabled else None
