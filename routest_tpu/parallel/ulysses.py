"""Ulysses-style sequence parallelism: all-to-all seq↔head re-sharding.

The alternative long-context strategy to the ring (DeepSpeed-Ulysses
pattern): instead of rotating K/V blocks, one ``lax.all_to_all`` converts
the sequence sharding into a head sharding — every device then attends
over the whole sequence for its slice of heads (streamed blockwise, so
the per-device score residency is O(S·chunk) per resident head, not
O(S²)), and a second all-to-all restores the sequence sharding.
Collective count is constant in mesh size — four all_to_alls (q, k, v,
out) plus an all_gather of the key mask when one is supplied — vs the
ring's ``n-1`` hops of three ppermutes each; the trade is requiring
``n_heads % axis_size == 0`` and holding full-sequence K/V (not score)
activations per device.

Ring keeps even K/V residency at O(S/n) and overlaps its hops; Ulysses
wins at moderate S where collective count dominates. Both are exposed
so a sequence model can pick per workload
(``artifacts/transformer_report.json`` ``seq_scaling`` carries the
measured curve).
"""

from __future__ import annotations

from typing import Optional

import jax
from jax.sharding import Mesh

from routest_tpu.parallel.ring import (blockwise_attention, full_attention,
                                       sharded_attention)


def ulysses_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                      *, axis_name: str, axis_size: int,
                      key_mask: Optional[jax.Array] = None,
                      causal: bool = False) -> jax.Array:
    """Per-device program: (B, S_local, H, D) in, same shape out.

    Call inside shard_map with the sequence axis sharded over
    ``axis_name``. Requires H % axis_size == 0.
    """
    if axis_size == 1:
        return full_attention(q, k, v, key_mask, causal)
    if q.shape[2] % axis_size:
        raise ValueError(
            f"n_heads={q.shape[2]} not divisible by axis_size={axis_size}")

    def seq_to_heads(x):  # (B, S/n, H, D) → (B, S, H/n, D)
        return jax.lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                                  tiled=True)

    def heads_to_seq(x):  # (B, S, H/n, D) → (B, S/n, H, D)
        return jax.lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                                  tiled=True)

    q_h, k_h, v_h = seq_to_heads(q), seq_to_heads(k), seq_to_heads(v)
    full_mask = None
    if key_mask is not None:
        full_mask = jax.lax.all_gather(key_mask, axis_name, axis=1, tiled=True)
    # Blockwise (flash-style) per head shard: long sequences would
    # otherwise materialize the whole (S, S) score matrix per device —
    # the ceiling the ring never had. Short sequences take the exact
    # full_attention early-out inside.
    out = blockwise_attention(q_h, k_h, v_h, full_mask, causal)
    return heads_to_seq(out)


def ulysses_attention_sharded(q: jax.Array, k: jax.Array, v: jax.Array,
                              mesh: Mesh, seq_axis: str = "seq",
                              data_axis: Optional[str] = None,
                              key_mask: Optional[jax.Array] = None,
                              causal: bool = False) -> jax.Array:
    """Convenience wrapper over full (B, S, H, D) arrays (cf. ring)."""
    return sharded_attention(ulysses_attention, q, k, v, mesh, seq_axis,
                             data_axis, key_mask, causal)
