"""Sequence/context parallelism: ring attention and Ulysses all-to-all.

The reference has no sequence models and no parallelism of any kind
(SURVEY.md §5.7 — its longest "sequence" is a polyline walked in Python
lists, reference ``Flaskr/utils.py:162-167``). Here the long axis is a
route: a delivery run expressed as a sequence of legs/polyline points,
potentially far longer than one chip's HBM wants to hold at attention's
O(S²) cost. This package scales that axis across the mesh:

- :mod:`routest_tpu.parallel.ring` — ring attention: K/V blocks rotate
  around the ICI ring via ``lax.ppermute`` while each device accumulates
  its queries' attention with a running (online) softmax;
- :mod:`routest_tpu.parallel.ulysses` — all-to-all sequence parallelism:
  ``lax.all_to_all`` re-shards sequence↔heads so every device runs full
  attention over a head shard;
- :mod:`routest_tpu.parallel.tensor` — Megatron column/row tensor
  parallelism over the ``model`` mesh axis (forward, training, serving);
- :mod:`routest_tpu.parallel.pipeline` — GPipe fill-drain pipeline
  parallelism mapping model stages onto a ``stage`` mesh axis;
- :mod:`routest_tpu.parallel.expert` — Switch-style expert parallelism
  (capacity-bounded all_to_all MoE dispatch) over an ``expert`` axis.

All are pure shard_map programs — XLA emits the collectives over ICI;
gradients flow through them, so the same code paths train.
"""

from routest_tpu.parallel.expert import (init_moe_params, make_moe_apply,
                                         shard_moe_params)
from routest_tpu.parallel.pipeline import (make_pipeline_apply,
                                           make_pipeline_train_step,
                                           microbatch, shard_stage_params,
                                           stack_stage_params)
from routest_tpu.parallel.ring import ring_attention, ring_attention_sharded
from routest_tpu.parallel.tensor import (make_tp_apply, make_tp_train_step,
                                         shard_tp_params)
from routest_tpu.parallel.ulysses import ulysses_attention, ulysses_attention_sharded

__all__ = [
    "ring_attention",
    "ring_attention_sharded",
    "ulysses_attention",
    "ulysses_attention_sharded",
    "make_tp_apply",
    "make_tp_train_step",
    "shard_tp_params",
    "make_pipeline_apply",
    "make_pipeline_train_step",
    "microbatch",
    "stack_stage_params",
    "shard_stage_params",
    "init_moe_params",
    "make_moe_apply",
    "shard_moe_params",
]
