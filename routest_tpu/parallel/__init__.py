"""Sequence/context parallelism: ring attention and Ulysses all-to-all.

The reference has no sequence models and no parallelism of any kind
(SURVEY.md §5.7 — its longest "sequence" is a polyline walked in Python
lists, reference ``Flaskr/utils.py:162-167``). Here the long axis is a
route: a delivery run expressed as a sequence of legs/polyline points,
potentially far longer than one chip's HBM wants to hold at attention's
O(S²) cost. This package scales that axis across the mesh:

- :mod:`routest_tpu.parallel.ring` — ring attention: K/V blocks rotate
  around the ICI ring via ``lax.ppermute`` while each device accumulates
  its queries' attention with a running (online) softmax;
- :mod:`routest_tpu.parallel.ulysses` — all-to-all sequence parallelism:
  ``lax.all_to_all`` re-shards sequence↔heads so every device runs full
  attention over a head shard.

Both are pure shard_map programs — XLA emits the collectives over ICI;
gradients flow through them, so the same code paths train.
"""

from routest_tpu.parallel.ring import ring_attention, ring_attention_sharded
from routest_tpu.parallel.ulysses import ulysses_attention, ulysses_attention_sharded

__all__ = [
    "ring_attention",
    "ring_attention_sharded",
    "ulysses_attention",
    "ulysses_attention_sharded",
]
