"""Ring attention: exact attention over a mesh-sharded sequence axis.

Each device holds one block of the sequence. K/V blocks travel around the
ICI ring (``lax.ppermute``) while every device accumulates attention for
its resident queries with an online softmax — the running max/denominator
rescaling that makes blockwise attention exact, not approximate. After
``axis_size`` hops every query has seen every key, yet no device ever
materialized more than a (local_q × local_k) score tile: O(S²) compute,
O(S²/n²) memory per step, O(S/n) activation residency.

The reference has nothing like this (no attention, no collectives —
SURVEY.md §5.7/§5.8); this is the TPU-native scaling path for
long-route sequence models built on this package.

Layouts: q/k/v are (B, S, H, D); masks are (B, S) with 1.0 = real token.
``ring_attention`` is the per-device program (call it inside shard_map
with the sequence axis sharded); ``ring_attention_sharded`` wraps it for
callers holding unsharded arrays.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from routest_tpu.core.smap import shard_map

_NEG = -1e30  # finite "minus infinity": keeps exp() NaN-free on all-masked tiles
DEFAULT_CHUNK = 1024  # blockwise K/V streaming granularity (bench imports it)


def full_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                   key_mask: Optional[jax.Array] = None,
                   causal: bool = False,
                   scale: Optional[float] = None) -> jax.Array:
    """Single-device reference: (B, S, H, D) → (B, S, H, D).

    The oracle ring/Ulysses must match bit-for-bit in f32 (up to summation
    order); also the fallback when the mesh has one device on the axis.
    """
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    mask = jnp.ones(s.shape[-1], bool)[None, None, None, :]
    if key_mask is not None:
        mask = mask & (key_mask[:, None, None, :] > 0)
    if causal:
        q_pos = jnp.arange(q.shape[1])[:, None]
        k_pos = jnp.arange(k.shape[1])[None, :]
        mask = mask & (q_pos >= k_pos)[None, None]
    s = jnp.where(mask, s, _NEG)
    p = jax.nn.softmax(s, axis=-1) * mask
    denom = jnp.maximum(p.sum(-1, keepdims=True), 1e-30)
    p = p / denom * jnp.clip(mask.sum(-1, keepdims=True), 0, 1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v)


def blockwise_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                        key_mask: Optional[jax.Array] = None,
                        causal: bool = False,
                        scale: Optional[float] = None,
                        chunk: int = DEFAULT_CHUNK) -> jax.Array:
    """Exact attention that never materializes the (S, S) score matrix:
    K/V stream through in ``chunk``-sized blocks under the same online
    softmax the ring uses — a single-device flash-style loop. Peak score
    memory is (B, H, S, chunk) instead of (B, H, S, S), so one device's
    sequence ceiling is set by bandwidth, not by the score tensor; the
    ring/Ulysses collectives then multiply ceiling AND compute across
    chips. (Blockwise composes with Ulysses: each head-shard can stream
    its full-row scores chunk-by-chunk.)

    The scan body is ``jax.checkpoint``-ed: without it, backprop would
    stash every chunk's (B, H, S, chunk) score/prob tensors as
    residuals — O(S²) total, the very tensor this function exists to
    avoid. Rematerialization recomputes each tile in the backward pass,
    keeping TRAINING memory at the same O(S·chunk) bound as inference
    (grad parity is tested against the full oracle).

    Known trade under ``causal=True``: chunks wholly in a query's
    future still pay their QK einsum before masking to zero (~2× FLOPs
    at large S). The consumers here are non-causal route encoders, so
    simplicity wins over a bounded scan until a causal consumer exists.

    Same layouts and mask/causal semantics as :func:`full_attention`
    (the parity oracle)."""
    b, s_q, h, d = q.shape
    s_k = k.shape[1]
    if s_k <= chunk:
        return full_attention(q, k, v, key_mask, causal, scale)
    scale = scale if scale is not None else d ** -0.5
    n_chunks = (s_k + chunk - 1) // chunk
    pad = n_chunks * chunk - s_k
    if pad:
        k = jnp.pad(k, ((0, 0), (0, pad), (0, 0), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, pad), (0, 0), (0, 0)))
    # Padded keys are masked off; an absent mask gains one that covers
    # only the padding.
    km = (jnp.ones((b, s_k), q.dtype) if key_mask is None
          else key_mask.astype(q.dtype))
    if pad:
        km = jnp.pad(km, ((0, 0), (0, pad)))
    k_blocks = k.reshape(b, n_chunks, chunk, h, d).transpose(1, 0, 2, 3, 4)
    v_blocks = v.reshape(b, n_chunks, chunk, h, d).transpose(1, 0, 2, 3, 4)
    m_blocks = km.reshape(b, n_chunks, chunk).transpose(1, 0, 2)
    q_pos = jnp.arange(s_q)

    def body(carry, blk):
        acc, m, denom, start = carry
        k_blk, v_blk, km_blk = blk
        s = jnp.einsum("bqhd,bkhd->bhqk", q, k_blk,
                       preferred_element_type=jnp.float32) * scale
        tile_mask = km_blk[:, None, None, :] > 0
        if causal:
            k_pos = start + jnp.arange(chunk)
            tile_mask = tile_mask & (q_pos[:, None] >= k_pos[None, :])[None, None]
        s = jnp.where(tile_mask, s, _NEG)
        m_new = jnp.maximum(m, s.max(-1))
        p = jnp.exp(s - m_new[..., None]) * tile_mask
        correction = jnp.exp(m - m_new)
        denom = denom * correction + p.sum(-1)
        acc = acc * correction[..., None] + jnp.einsum(
            "bhqk,bkhd->bhqd", p, v_blk.astype(jnp.float32))
        return (acc, m_new, denom, start + chunk), None

    acc0 = jnp.zeros((b, h, s_q, d), jnp.float32)
    m0 = jnp.full((b, h, s_q), _NEG, jnp.float32)
    den0 = jnp.zeros((b, h, s_q), jnp.float32)
    (acc, _, denom, _), _ = jax.lax.scan(
        jax.checkpoint(body), (acc0, m0, den0, jnp.zeros((), jnp.int32)),
        (k_blocks, v_blocks, m_blocks))
    out = acc / jnp.maximum(denom, 1e-30)[..., None]
    return out.transpose(0, 2, 1, 3).astype(q.dtype)


def ring_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                   *, axis_name: str, axis_size: int,
                   key_mask: Optional[jax.Array] = None,
                   causal: bool = False,
                   scale: Optional[float] = None) -> jax.Array:
    """Per-device ring attention. Call inside shard_map.

    q/k/v: (B, S_local, H, D) — this device's sequence block.
    key_mask: (B, S_local) for the local key block (rotates with K/V).
    Returns (B, S_local, H, D) for the resident queries.
    """
    if axis_size == 1:
        return full_attention(q, k, v, key_mask, causal, scale)

    scale = scale if scale is not None else q.shape[-1] ** -0.5
    b, s_q, h, _ = q.shape
    s_k = k.shape[1]
    my = jax.lax.axis_index(axis_name)
    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]
    kmask = None if key_mask is None else key_mask.astype(q.dtype)
    q_pos = my * s_q + jnp.arange(s_q)

    acc = jnp.zeros((b, h, s_q, q.shape[-1]), jnp.float32)
    m = jnp.full((b, h, s_q), _NEG, jnp.float32)
    denom = jnp.zeros((b, h, s_q), jnp.float32)

    def tile_update(acc, m, denom, k_blk, v_blk, km, step):
        # after `step` clockwise hops we hold the block born on device my-step
        src = (my - step) % axis_size
        s = jnp.einsum("bqhd,bkhd->bhqk", q, k_blk,
                       preferred_element_type=jnp.float32) * scale
        tile_mask = None if km is None else km[:, None, None, :] > 0
        if causal:
            k_pos = src * s_k + jnp.arange(s_k)
            cmask = (q_pos[:, None] >= k_pos[None, :])[None, None]
            tile_mask = cmask if tile_mask is None else tile_mask & cmask
        if tile_mask is not None:
            s = jnp.where(tile_mask, s, _NEG)
        m_new = jnp.maximum(m, s.max(-1))
        p = jnp.exp(s - m_new[..., None])
        if tile_mask is not None:
            # explicit mask multiply: on an all-masked tile exp(NEG-NEG)=1
            # would otherwise inject phantom probability mass
            p = p * tile_mask
        correction = jnp.exp(m - m_new)
        denom = denom * correction + p.sum(-1)
        acc = acc * correction[..., None] + jnp.einsum(
            "bhqk,bkhd->bhqd", p, v_blk.astype(jnp.float32))
        return acc, m_new, denom

    rotate = functools.partial(jax.lax.ppermute, axis_name=axis_name,
                               perm=perm)

    # resident block first, then axis_size-1 rotate+compute hops — no
    # final dead rotation riding the ICI; the mask block only travels
    # the ring when a mask exists at all
    if kmask is None:
        def hop(carry, step):
            k_blk, v_blk, acc, m, denom = carry
            k_blk, v_blk = rotate(k_blk), rotate(v_blk)
            acc, m, denom = tile_update(acc, m, denom, k_blk, v_blk, None, step)
            return (k_blk, v_blk, acc, m, denom), None

        acc, m, denom = tile_update(acc, m, denom, k, v, None, 0)
        (_, _, acc, _, denom), _ = jax.lax.scan(
            hop, (k, v, acc, m, denom), jnp.arange(1, axis_size))
    else:
        def hop(carry, step):
            k_blk, v_blk, km, acc, m, denom = carry
            k_blk, v_blk, km = rotate(k_blk), rotate(v_blk), rotate(km)
            acc, m, denom = tile_update(acc, m, denom, k_blk, v_blk, km, step)
            return (k_blk, v_blk, km, acc, m, denom), None

        acc, m, denom = tile_update(acc, m, denom, k, v, kmask, 0)
        (_, _, _, acc, _, denom), _ = jax.lax.scan(
            hop, (k, v, kmask, acc, m, denom), jnp.arange(1, axis_size))
    out = acc / jnp.maximum(denom, 1e-30)[..., None]
    return out.transpose(0, 2, 1, 3).astype(q.dtype)


def sharded_attention(attn_fn, q: jax.Array, k: jax.Array, v: jax.Array,
                      mesh: Mesh, seq_axis: str,
                      data_axis: Optional[str],
                      key_mask: Optional[jax.Array],
                      causal: bool) -> jax.Array:
    """Shared shard_map wrapper for the per-device attention programs.

    Builds the spec/arg tuples conditionally so a masked call adds the
    mask input while an unmasked one omits it entirely — letting the
    per-device program (which receives ``key_mask=None``) skip its mask
    collectives and per-tile compare/multiply.
    """
    axis_size = mesh.shape[seq_axis]
    qkv_spec = P(data_axis, seq_axis, None, None)
    specs = (qkv_spec, qkv_spec, qkv_spec)
    args = (q, k, v)
    if key_mask is not None:
        specs += (P(data_axis, seq_axis),)
        args += (key_mask,)

    @functools.partial(shard_map, mesh=mesh, in_specs=specs,
                       out_specs=qkv_spec)
    def run(q, k, v, *maybe_mask):
        return attn_fn(q, k, v, axis_name=seq_axis, axis_size=axis_size,
                       key_mask=maybe_mask[0] if maybe_mask else None,
                       causal=causal)

    return run(*args)


def ring_attention_sharded(q: jax.Array, k: jax.Array, v: jax.Array,
                           mesh: Mesh, seq_axis: str = "seq",
                           data_axis: Optional[str] = None,
                           key_mask: Optional[jax.Array] = None,
                           causal: bool = False) -> jax.Array:
    """Shard the sequence axis of full (B, S, H, D) arrays and run the ring.

    The mesh's ``seq_axis`` size must divide S; batch optionally shards
    over ``data_axis``. This is the convenience wrapper — models compose
    :func:`ring_attention` directly inside their own shard_map programs.
    """
    return sharded_attention(ring_attention, q, k, v, mesh, seq_axis,
                             data_axis, key_mask, causal)
