"""Pipeline parallelism: model stages mapped onto a mesh axis.

SURVEY.md §2.4 scopes pipeline parallelism out of the minimum slice but
requires the runner API be designed "so stages *could* map to mesh axes
later" — this module is that API, implemented rather than sketched: a
GPipe-style fill-drain schedule as a fixed-shape ``shard_map`` program
over a ``stage`` mesh axis. (The reference has no parallelism of any
kind — its model is a batch-1 CPU tree walk, ``Flaskr/ml.py:51-53``.)

Design:

- a *stage* is any shape-preserving function ``stage_fn(stage_params, x)
  -> x`` — the same callable runs on every device, closed over nothing;
- per-stage parameters are STACKED along a leading axis of size
  ``n_stages`` and sharded ``P(stage_axis)``, so device *s* holds only
  stage *s*'s weights — the HBM-scaling point of PP;
- microbatches stream through the pipe: tick *t* feeds microbatch *t*
  into stage 0, every stage transforms the activation it holds, and one
  ``ppermute`` per tick shifts activations forward. After
  ``n_stages + n_micro - 1`` ticks every microbatch has drained through
  the last stage;
- the whole schedule is one ``lax.scan`` (static trip count), so it
  jits, differentiates (XLA transposes the ``ppermute``s — gradients
  counter-rotate backward through the pipe), and composes with the
  ``data`` axis for DP×PP meshes.

Bubble fraction is the classic (S-1)/(S+M-1); pick ``n_micro ≫ stages``.
"""

from __future__ import annotations

import functools
from typing import Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from routest_tpu.core.smap import shard_map


def stack_stage_params(per_stage_params: list):
    """[stage0_tree, stage1_tree, ...] → one tree with a leading stage
    axis (leaf shapes must match across stages)."""
    return jax.tree_util.tree_map(
        lambda *leaves: jnp.stack(leaves), *per_stage_params)


def shard_stage_params(stacked, mesh: Mesh, stage_axis: str = "stage"):
    """device_put the stacked tree so device s holds stage s's slice."""
    sharding = NamedSharding(mesh, P(stage_axis))
    return jax.tree_util.tree_map(
        lambda x: jax.device_put(x, sharding), stacked)


def microbatch(x: jax.Array, n_micro: int) -> jax.Array:
    """(B, ...) → (M, B/M, ...) microbatch stack."""
    if x.shape[0] % n_micro:
        raise ValueError(
            f"batch {x.shape[0]} not divisible by n_micro={n_micro}")
    return x.reshape((n_micro, x.shape[0] // n_micro) + x.shape[1:])


def make_pipeline_apply(stage_fn: Callable, mesh: Mesh,
                        stage_axis: str = "stage"):
    """jitted (stacked_params, xs) → ys.

    ``xs``: (M, b, ...) microbatches (see :func:`microbatch`),
    replicated; ``stacked_params``: leading stage axis sharded over
    ``stage_axis`` (see :func:`shard_stage_params`). Returns (M, b, ...)
    outputs, replicated — numerically identical to applying the stages
    sequentially (:func:`sequential_apply`).
    """
    n_stages = mesh.shape[stage_axis]

    @functools.partial(shard_map, mesh=mesh,
                       in_specs=(P(stage_axis), P()), out_specs=P())
    def run(stacked_local, xs):
        # shard_map hands each device a (1, ...) slice of every leaf
        local = jax.tree_util.tree_map(lambda a: a[0], stacked_local)
        s = jax.lax.axis_index(stage_axis)
        m_total = xs.shape[0]
        zero = jnp.zeros_like(xs[0])
        fwd = [(i, (i + 1) % n_stages) for i in range(n_stages)]

        def tick(carry, t):
            buf, outs = carry
            # stage 0 ingests microbatch t (past the fill window it
            # processes zeros that are never recorded)
            x_in = jnp.where(t < m_total, xs[jnp.minimum(t, m_total - 1)],
                             zero)
            buf = jnp.where(s == 0, x_in, buf)
            y = stage_fn(local, buf)
            # the LAST stage emits microbatch m = t - (n_stages - 1)
            m = t - (n_stages - 1)
            mc = jnp.clip(m, 0, m_total - 1)
            valid = (s == n_stages - 1) & (m >= 0) & (m < m_total)
            outs = outs.at[mc].set(jnp.where(valid, y, outs[mc]))
            # one hop forward per tick; stage 0's wrap-around input is
            # overwritten by the next ingest
            buf = jax.lax.ppermute(y, stage_axis, fwd)
            return (buf, outs), None

        ticks = jnp.arange(n_stages + m_total - 1)
        (_, outs), _ = jax.lax.scan(tick, (zero, jnp.zeros_like(xs)), ticks)
        # outputs live on the last stage only; psum replicates them
        # (every other stage contributes zeros)
        return jax.lax.psum(outs, stage_axis)

    return jax.jit(run)


def sequential_apply(stage_fn: Callable, per_stage_params: list,
                     x: jax.Array) -> jax.Array:
    """The single-device oracle the pipeline must match."""
    for p in per_stage_params:
        x = stage_fn(p, x)
    return x


def make_pipeline_train_step(stage_fn: Callable, optimizer, mesh: Mesh,
                             stage_axis: str = "stage"):
    """jitted (stacked_params, opt_state, xs, ys) → (params, opt_state,
    loss): train THROUGH the pipeline.

    The loss differentiates across every ``ppermute`` hop (XLA's
    transpose rule counter-rotates cotangents), so each device ends up
    with exactly its own stage's gradient slice — stage-sharded
    optimizer state updates locally, no gradient resharding.
    """
    import optax

    apply_fn = make_pipeline_apply(stage_fn, mesh, stage_axis)

    def loss_fn(stacked, xs, ys):
        preds = apply_fn(stacked, xs)
        return jnp.mean((preds - ys) ** 2)

    @jax.jit
    def step(stacked, opt_state, xs, ys):
        loss, grads = jax.value_and_grad(loss_fn)(stacked, xs, ys)
        updates, opt_state = optimizer.update(grads, opt_state, stacked)
        stacked = optax.apply_updates(stacked, updates)
        return stacked, opt_state, loss

    return step
