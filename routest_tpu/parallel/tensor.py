"""Tensor parallelism: MLP trunks sharded over the mesh ``model`` axis.

The reference has no parallelism of any kind (SURVEY.md §2.4 — its model
is a batch-size-1 CPU tree walk, reference ``Flaskr/ml.py:51-53``); the
``model`` mesh axis existed here since round 1 but carried only
replicated weights. This module makes it real: Megatron-style sharding
of the ETA trunk's weight matrices, the scaling path for when a scoring
model outgrows one chip's HBM.

Layout — alternating column/row parallelism, one ``psum`` per pair:

- even matmuls are **column-parallel**: ``W (d_in, d_out)`` splits along
  ``d_out``; each device computes its activation slice locally (bias is
  sharded with it, gelu is elementwise — no communication);
- odd matmuls are **row-parallel**: ``W`` splits along ``d_in``, which
  matches the sharded activation from the previous layer; the partial
  products are combined with one ``psum`` over the model axis and the
  (replicated) bias is added after.

So a (col, row) pair costs exactly one all-reduce — the canonical
Megatron MLP schedule. The 2-wide output head is never worth sharding:
when the schedule would end column-parallel, the final layer runs
replicated instead (identical tiny matmul on every device, zero
communication).

Everything is a plain shard_map program over the existing params pytree:
no new parameter format, gradients flow through the collectives, and the
``data`` axis keeps sharding the batch orthogonally (the mesh is
(data, model) — e.g. 4×2 on a v5e-8).
"""

from __future__ import annotations

import functools
from typing import Dict

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from routest_tpu.core.smap import shard_map
from routest_tpu.models.eta_mlp import EtaMLP

Params = Dict


def _layer_modes(n_layers: int) -> list:
    """Per-layer schedule: "col" (shard outputs), "row" (shard inputs +
    psum), or "rep" (replicated — only for a final layer whose input
    arrives unsharded)."""
    modes = []
    sharded = False  # is the activation entering this layer sharded?
    for i in range(n_layers):
        if sharded:
            modes.append("row")
            sharded = False
        elif i == n_layers - 1:
            modes.append("rep")
        else:
            modes.append("col")
            sharded = True
    return modes


_MODE_SPECS = {
    "col": lambda ax: {"w": P(None, ax), "b": P(ax)},
    "row": lambda ax: {"w": P(ax, None), "b": P()},
    "rep": lambda ax: {"w": P(), "b": P()},
}


def tp_param_specs(model: EtaMLP, data_axis: str = "data",
                   model_axis: str = "model") -> Params:
    """PartitionSpec pytree matching the EtaMLP params tree."""
    modes = _layer_modes(len(model.hidden) + 1)
    return {"layers": [_MODE_SPECS[m](model_axis) for m in modes],
            "norm": {"mean": P(), "std": P()}}


def _validate(model: EtaMLP, tp: int) -> None:
    dims = tuple(model.hidden) + (model.n_heads,)
    modes = _layer_modes(len(dims))
    for i, (mode, d_out) in enumerate(zip(modes, dims)):
        if mode == "col" and d_out % tp:
            raise ValueError(
                f"column-parallel layer {i} output width {d_out} is not "
                f"divisible by model-axis size {tp}")
        if mode == "row" and dims[i - 1] % tp:
            raise ValueError(
                f"row-parallel layer {i} input width {dims[i - 1]} is not "
                f"divisible by model-axis size {tp}")


def shard_tp_params(params: Params, model: EtaMLP, mesh: Mesh,
                    data_axis: str = "data",
                    model_axis: str = "model") -> Params:
    """device_put the params with the tensor-parallel layout."""
    specs = tp_param_specs(model, data_axis, model_axis)
    # tree_map's structure comes from the FIRST tree; params' array leaves
    # line up with whole P objects in the spec tree (P is a tuple subclass,
    # but it is never traversed because the zip stops at params' leaves).
    return jax.tree_util.tree_map(
        lambda x, spec: jax.device_put(x, NamedSharding(mesh, spec)),
        params, specs,
    )


def make_tp_apply(model: EtaMLP, mesh: Mesh, data_axis: str = "data",
                  model_axis: str = "model"):
    """jitted (params, x) → (B,) ETA minutes with weights sharded over
    ``model_axis`` and the batch over ``data_axis``.

    Numerically matches ``EtaMLP.apply`` (row-parallel psum changes only
    the f32 summation order). Params must be laid out per
    :func:`tp_param_specs` (see :func:`shard_tp_params`).
    """
    tp = mesh.shape[model_axis]
    _validate(model, tp)
    param_specs = tp_param_specs(model, data_axis, model_axis)
    n_layers = len(model.hidden) + 1
    modes = _layer_modes(n_layers)
    c = model.policy.compute_dtype

    @functools.partial(shard_map, mesh=mesh,
                       in_specs=(param_specs, P(data_axis)),
                       out_specs=P(data_axis))
    def tp_forward(params, x):
        feats, dist_km = model._expand(params, x)
        h = feats.astype(c)
        for i, (mode, layer) in enumerate(zip(modes, params["layers"])):
            w = layer["w"].astype(c)
            b = layer["b"].astype(c)
            if mode == "row":
                h = jax.lax.psum(h @ w, model_axis) + b  # combine the pair
            else:  # "col" computes its local slice; "rep" the full (tiny) head
                h = h @ w + b
            if i < n_layers - 1:
                h = jax.nn.gelu(h)
        out = h.astype(model.policy.output_dtype)
        d = dist_km.astype(model.policy.output_dtype)
        n_q = len(getattr(model, "quantiles", ()) or ())
        if n_q:
            # Same non-crossing cumulative epilogue as
            # EtaMLP.apply_quantiles — the head activation is full-width
            # on every device here (a row-parallel final layer psums, a
            # replicated one never sharded), so the epilogue is
            # layout-independent. Output (B, Q).
            pace = jnp.cumsum(jax.nn.softplus(out[..., :n_q]), axis=-1)
            overhead = jnp.cumsum(jax.nn.softplus(out[..., n_q:]), axis=-1)
            return pace * d[..., None] + overhead
        pace = jax.nn.softplus(out[..., 0])
        overhead = jax.nn.softplus(out[..., 1])
        return pace * d + overhead

    return jax.jit(tp_forward)


def make_tp_loss(model: EtaMLP, mesh: Mesh, data_axis: str = "data",
                 model_axis: str = "model"):
    """jitted (params, x, y) → scalar weighted MSE under the TP layout.

    Differentiable end-to-end (XLA differentiates psum/all_gather), so
    ``jax.grad`` of this IS the tensor-parallel training step's core.
    Point models only: the quantile objective is pinball, not MSE — TP
    *serving* of quantile models goes through :func:`make_tp_apply`.
    """
    if getattr(model, "quantiles", ()):
        raise ValueError("TP training implements the point-model MSE "
                         "objective; train quantile models data-parallel")
    tp_apply_inner = make_tp_apply(model, mesh, data_axis, model_axis)

    def loss(params, x, y):
        pred = tp_apply_inner(params, x)
        return jnp.mean((pred - y) ** 2)

    return jax.jit(loss)


def make_tp_train_step(model: EtaMLP, optimizer, mesh: Mesh,
                       data_axis: str = "data", model_axis: str = "model"):
    """jitted (params, opt_state, x, y) → (params, opt_state, loss):
    a full TENSOR-PARALLEL training step.

    Gradients flow backward through the Megatron collectives (the
    transpose of a row-parallel ``psum`` is an identity broadcast onto
    the already-sharded activation grad; XLA emits it automatically), so
    each device computes exactly the gradient slice matching its weight
    shard — grads, optimizer state, and updates all inherit the TP
    layout of :func:`tp_param_specs` with zero resharding. This is the
    piece round 2 lacked: TP that *trains*, not just a forward parity
    demo (cf. SURVEY.md §2.4 TP row).

    ``opt_state`` must be built from TP-sharded params
    (``optimizer.init(shard_tp_params(...))``) so its moment buffers
    start on the right devices.
    """
    import optax

    loss_fn = make_tp_loss(model, mesh, data_axis, model_axis)

    @jax.jit
    def step(params, opt_state, x, y):
        loss, grads = jax.value_and_grad(loss_fn)(params, x, y)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    return step
