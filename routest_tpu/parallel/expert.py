"""Expert parallelism: a Switch-style MoE layer over an ``expert`` axis.

The last §2.4 row (SURVEY.md marks EP "n/a; keep mesh abstraction
general" — the reference has no parallelism of any kind). Implemented
rather than waived so the mesh abstraction is proven general: per-expert
MLPs live on their own devices, tokens travel to their expert and back
via ``all_to_all`` — the EP pattern that scales conditional-compute
models past one chip's HBM.

Schedule (top-1 routing, capacity-bounded — the Switch Transformer
recipe):

1. tokens are sharded over the ``expert`` axis (which doubles as the
   data axis for the token batch, the standard EP layout);
2. each device routes its local tokens (argmax over router logits) and
   packs, per destination expert, up to ``capacity`` tokens into a
   fixed-shape (E, C, D) dispatch buffer (overflow tokens are dropped —
   their output is the zero vector, recorded in the combine mask);
3. ONE ``all_to_all`` turns (dest_expert, C, D) into (source_device, C,
   D) on every expert's device — each device now holds every token
   routed to ITS expert;
4. the local expert MLP runs on its (E·C, D) slab — dense matmuls, MXU
   territory;
5. a second ``all_to_all`` returns expert outputs to the tokens' home
   devices, where they scatter back into sequence order, scaled by the
   router gate (straight-through for top-1).

Everything is fixed-shape; gradients flow through both all_to_alls and
the gather/scatter (router grads via the gate multiplication). A
``load_balance_loss`` (mean expert load × mean router prob, scaled E²)
is returned for training, as in the Switch paper.
"""

from __future__ import annotations

import functools
from typing import Dict, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from routest_tpu.core.smap import shard_map

Params = Dict


def init_moe_params(key: jax.Array, n_experts: int, d_model: int,
                    d_hidden: int) -> Params:
    """Router + stacked expert FFNs (leading axis = expert)."""
    kr, k1, k2 = jax.random.split(key, 3)
    s1 = 1.0 / jnp.sqrt(d_model)
    s2 = 1.0 / jnp.sqrt(d_hidden)
    return {
        "router": jax.random.normal(kr, (d_model, n_experts)) * s1,
        "w1": jax.random.normal(k1, (n_experts, d_model, d_hidden)) * s1,
        "b1": jnp.zeros((n_experts, d_hidden)),
        "w2": jax.random.normal(k2, (n_experts, d_hidden, d_model)) * s2,
        "b2": jnp.zeros((n_experts, d_model)),
    }


def shard_moe_params(params: Params, mesh: Mesh,
                     expert_axis: str = "expert") -> Params:
    """Experts to their devices; the router is replicated."""
    ex = NamedSharding(mesh, P(expert_axis))
    rep = NamedSharding(mesh, P())
    return {k: jax.device_put(v, rep if k == "router" else ex)
            for k, v in params.items()}


def _expert_ffn(w1, b1, w2, b2, x):
    return jax.nn.gelu(x @ w1 + b1) @ w2 + b2


def moe_apply_dense(params: Params, tokens: jax.Array) -> jax.Array:
    """Single-device oracle: every token through its argmax expert, no
    capacity limit. The EP layer must match this wherever no token
    overflowed."""
    logits = tokens @ params["router"]
    gates = jax.nn.softmax(logits, axis=-1)
    choice = jnp.argmax(logits, axis=-1)                       # (B,)
    outs = jax.vmap(_expert_ffn, in_axes=(0, 0, 0, 0, None))(
        params["w1"], params["b1"], params["w2"], params["b2"], tokens)
    # outs: (E, B, D); pick each token's expert, scale by its gate
    picked = jnp.take_along_axis(
        outs, choice[None, :, None], axis=0)[0]                # (B, D)
    gate = jnp.take_along_axis(gates, choice[:, None], axis=1)
    return picked * gate


def make_moe_apply(mesh: Mesh, expert_axis: str = "expert",
                   capacity_factor: float = 2.0):
    """jitted (params, tokens) → (outputs, aux) with experts sharded over
    ``expert_axis`` and tokens sharded over the same axis.

    ``aux``: dict with ``load_balance_loss`` (scalar) and
    ``dropped_frac`` (scalar fraction of tokens past capacity, whose
    output is zero).
    """
    n_exp = mesh.shape[expert_axis]

    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=({"router": P(), "w1": P(expert_axis), "b1": P(expert_axis),
                   "w2": P(expert_axis), "b2": P(expert_axis)},
                  P(expert_axis)),
        out_specs=(P(expert_axis), P()))
    def run(params, tokens):
        b_local, d = tokens.shape
        capacity = max(1, int(capacity_factor * b_local / n_exp))

        logits = tokens @ params["router"]                  # (b, E)
        gates = jax.nn.softmax(logits, axis=-1)
        choice = jnp.argmax(logits, axis=-1)                # (b,)
        gate = jnp.take_along_axis(gates, choice[:, None], axis=1)[:, 0]

        # position of each token within its expert's capacity window
        one_hot = jax.nn.one_hot(choice, n_exp, dtype=jnp.int32)  # (b, E)
        # already zero outside each token's chosen column, so the row sum
        # IS the token's slot index within its expert
        pos_in_expert = (jnp.cumsum(one_hot, axis=0) - 1) * one_hot
        slot = pos_in_expert.sum(axis=1)                    # (b,)
        keep = slot < capacity                              # overflow drops

        # pack: dispatch[e, c] = token routed to expert e at slot c
        dispatch = jnp.zeros((n_exp, capacity, d), tokens.dtype)
        src = jnp.where(keep, choice, 0)
        slot_c = jnp.clip(slot, 0, capacity - 1)
        dispatch = dispatch.at[src, slot_c].add(
            tokens * keep[:, None].astype(tokens.dtype))

        # (dest_expert, C, D) → every device receives its expert's slab
        # from all source devices: (n_source, C, D)
        arriving = jax.lax.all_to_all(dispatch, expert_axis, split_axis=0,
                                      concat_axis=0, tiled=True)
        local = jax.tree_util.tree_map(lambda a: a[0], (
            params["w1"], params["b1"], params["w2"], params["b2"]))
        out = _expert_ffn(*local, arriving.reshape(-1, d))
        out = out.reshape(n_exp, capacity, d)
        # route results back to the tokens' home devices
        returned = jax.lax.all_to_all(out, expert_axis, split_axis=0,
                                      concat_axis=0, tiled=True)

        # unpack: token i's output sits at returned[choice[i], slot[i]]
        gathered = returned[src, slot_c]                    # (b, D)
        y = gathered * (gate * keep.astype(gate.dtype))[:, None]

        # Switch load-balance loss: E · Σ_e (frac tokens to e)(mean prob e),
        # psum'd so every shard reports the GLOBAL value.
        frac = one_hot.astype(jnp.float32).mean(axis=0)
        prob = gates.mean(axis=0)
        lbl = n_exp * jnp.sum(
            jax.lax.pmean(frac, expert_axis)
            * jax.lax.pmean(prob, expert_axis))
        dropped = jax.lax.pmean(1.0 - keep.mean(), expert_axis)
        return y, {"load_balance_loss": lbl, "dropped_frac": dropped}

    return jax.jit(run)
