"""Benchmark: OD-pair ETA scoring throughput on the available accelerator.

BASELINE.json config 2 ("route_optimizer_twx2 batch scoring") scaled up:
HBM-resident OD batches through the ETA model. The reference scores one
row per HTTP request on CPU (``Flaskr/ml.py:51-53``); the north-star
target is >=10,000 preds/sec (v5e-8). Prints ONE JSON line on stdout,
always — even when the accelerator is unreachable.

Architecture (hardened after round 1, where backend init hung >400 s and
the driver captured rc=1 with no JSON; re-hardened after round 3, where
a wedged tunnel burned the whole 250 s TPU window and the round record
fell back to CPU with no accelerator evidence):

* The PARENT process never imports jax. It first launches a PROBE child
  (backend init + one 1-element dispatch+fetch under a ~25 s deadline)
  to find out cheaply whether the tunnel is alive, then spends the
  remaining budget where the probe says it is worth spending: a healthy
  probe buys the full TPU attempt; a dead probe goes straight to the
  CPU fallback and then RE-probes (wedges clear) for one short TPU
  attempt. Probe outcomes (latency or timeout) are recorded in the
  final JSON either way, so a CPU record carries the evidence that the
  tunnel was down across the whole window rather than an unexplained
  fallback. Whatever happens, the parent prints exactly one
  ``{"metric": ...}`` JSON line within the driver's ~400 s kill window.
* The CHILD (``ROUTEST_BENCH_CHILD=1``) does the actual timing.

Methodology — the TPU is reached through a tunnel whose dispatch+fetch
round trip is ~70 ms and highly variable, so host-side loops measure
noise. Instead the scoring step is chained inside a device-side
``lax.fori_loop`` (each iteration's input depends on the previous output:
no dead-code elimination, strict serialization) and the per-step time is
the SLOPE between a short and a long loop, cancelling the fixed
round-trip cost. Two forward paths are measured — the jit-compiled XLA
model and the fused Pallas kernel (``ops/fused_mlp.py``, TPU only) — and
the faster wins. A successful accelerator run is recorded to
``artifacts/bench_tpu.json`` for audit.

Roofline accounting (VERDICT r3 weak #7): the record carries achieved
``tflops`` (analytic matmul FLOPs x measured rate), ``mfu`` vs the
detected chip's dense peak for the model's compute dtype, and
``hbm_gbps_lower_bound`` (minimum-traffic model: batch in+out plus one
weight stream per step), so the "bandwidth-bound at ~2 FLOPs/byte"
explanation is auditable from the artifact alone.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

TARGET_PREDS_PER_SEC = 10_000.0  # BASELINE.json north star

# Child workload knobs (overridable so the parent can shrink runs).
BATCH = 1 << 17                  # 131,072 OD pairs per device call
N_SHORT, N_LONG = 100, 400       # fori_loop lengths for the slope
REPEATS = 3

# Parent deadlines (seconds). The driver kills at ~400 s; every path
# through the attempt ladder must finish (incl. two 10 s post-kill pipe
# drains) below that:
#   probe ok:    12 + 250 + (95 fallback)        = 357
#   probe dead:  12 + 95 + 8 + 160               = 275
# Probe timeouts are deliberately SHORT (fail-fast): a healthy tunnel
# answers in ~2-5 s, and when it is down every probe second is stolen
# from the CPU fallback (BENCH_r05 burned 45 s on two dead probes).
# Dead-probe runs record the skip structurally (``skipped`` in the
# final JSON) instead of polluting ``note``.
PROBE_TIMEOUT = float(os.environ.get("BENCH_PROBE_TIMEOUT", "12"))
TPU_ATTEMPT_TIMEOUT = float(os.environ.get("BENCH_TPU_TIMEOUT", "250"))
CPU_ATTEMPT_TIMEOUT = float(os.environ.get("BENCH_CPU_TIMEOUT", "95"))
RETRY_PROBE_TIMEOUT = float(os.environ.get("BENCH_RETRY_PROBE_TIMEOUT", "8"))
RETRY_TPU_TIMEOUT = 160.0

_REPO_DIR = os.path.dirname(os.path.abspath(__file__)) or "."

# Dense peak (TFLOP/s for bf16 matmul, HBM GB/s) by device_kind
# substring, lowercase. Sources: public TPU spec sheets.
_CHIP_PEAKS = {
    "v5 lite": (197.0, 819.0), "v5e": (197.0, 819.0),
    "v5p": (459.0, 2765.0),
    "v4": (275.0, 1228.0),
    "v3": (123.0, 900.0),
    "v6": (918.0, 1640.0), "trillium": (918.0, 1640.0),
}


def chip_peaks(device_kind: str):
    """(peak_tflops_bf16, peak_hbm_gbps) or (None, None) if unknown."""
    kind = (device_kind or "").lower()
    for key, peaks in _CHIP_PEAKS.items():
        if key in kind:
            return peaks
    return None, None


# ---------------------------------------------------------------------------
# Probe child: is the tunnel alive at all? One tiny dispatch, no model.
# ---------------------------------------------------------------------------

def probe_main() -> None:
    t0 = time.perf_counter()
    import jax

    if os.environ.get("BENCH_FORCE_CPU") == "1":  # hermetic test path
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp

    backend = jax.default_backend()
    x = jnp.asarray([1.0])
    y = float((x + 1.0)[0])  # dispatch + host fetch round trip
    print(json.dumps({
        "probe": "ok", "backend": backend,
        "probe_s": round(time.perf_counter() - t0, 2), "check": y == 2.0,
    }))


# ---------------------------------------------------------------------------
# Child: the actual measurement (runs with jax imported, backend decided by
# the environment the parent set).
# ---------------------------------------------------------------------------

def child_main() -> None:
    import jax

    # The sandbox's axon site customization re-exports JAX_PLATFORMS, so the
    # env var cannot force the CPU backend — only the config API can
    # (same workaround as tests/conftest.py).
    if os.environ.get("BENCH_FORCE_CPU") == "1":
        jax.config.update("jax_platforms", "cpu")

    # Persistent XLA cache: repeat bench runs (and the driver's end-of-round
    # run after a warm dev session) skip recompilation of the loop programs.
    from routest_tpu.core.cache import enable_compile_cache

    enable_compile_cache()

    import jax.numpy as jnp
    import numpy as np

    from routest_tpu.data.features import batch_from_mapping
    from routest_tpu.data.synthetic import generate_dataset
    from routest_tpu.models.eta_mlp import EtaMLP
    from routest_tpu.train.checkpoint import default_model_path, load_model

    batch = int(os.environ.get("BENCH_BATCH", str(BATCH)))
    n_short = int(os.environ.get("BENCH_N_SHORT", str(N_SHORT)))
    n_long = int(os.environ.get("BENCH_N_LONG", str(N_LONG)))
    repeats = int(os.environ.get("BENCH_REPEATS", str(REPEATS)))

    t0 = time.perf_counter()
    backend = jax.default_backend()  # forces backend init
    init_s = time.perf_counter() - t0
    print(f"bench: backend={backend} init={init_s:.1f}s", file=sys.stderr)

    try:
        model, params = load_model(default_model_path())
    except Exception:
        model = EtaMLP()
        params = model.init(jax.random.PRNGKey(0))
    # CPU fallback serves f32 compute (bf16 there is emulation, ~1.8x
    # slower — core/dtypes.backend_compute_policy); measure what a CPU
    # host would actually run.
    from routest_tpu.core.dtypes import backend_compute_policy

    model = backend_compute_policy(model)
    # load_model returns host numpy arrays; without an explicit device_put
    # every jit call re-uploads the params.
    params = jax.device_put(params)

    data = generate_dataset(batch, seed=123)
    x = jax.device_put(jnp.asarray(batch_from_mapping(data)))

    def make_runner(forward):
        # The loop bound is a traced argument: ONE compile per path (the
        # remote tunnel makes each compile expensive), short and long
        # runs share it (fori_loop with a dynamic bound is a while_loop).
        @jax.jit
        def run(xx, n_iters):
            def body(_, carry):
                xx, _eta = carry
                eta = forward(xx)
                return xx.at[:, 10].add(eta * 1e-12), eta

            return jax.lax.fori_loop(
                0, n_iters, body, (xx, jnp.zeros((batch,), jnp.float32)),
            )

        return run

    def measure(forward) -> float:
        run = make_runner(forward)

        def timed(n: int) -> float:
            t0 = time.perf_counter()
            _, eta = run(x, n)
            np.asarray(eta[:1])  # host fetch = the only real barrier
            return time.perf_counter() - t0

        timed(2)  # compile + warm
        slopes = []
        for _ in range(repeats):
            t_short = timed(n_short)
            t_long = timed(n_long)
            slopes.append((t_long - t_short) / (n_long - n_short))
        return max(float(np.median(slopes)), 1e-9)

    # Quantile-headed artifacts (the serving default since round 4) score
    # through apply_quantiles; the chained loop feeds the MEDIAN back so
    # both model families time the same scalar-per-row dependency chain.
    n_q = len(getattr(model, "quantiles", ()) or ())
    if n_q:
        xla_forward = lambda xx: model.apply_quantiles(  # noqa: E731
            params, xx)[:, n_q // 2]
    else:
        xla_forward = lambda xx: model.apply(params, xx)  # noqa: E731
    candidates = {"xla": measure(xla_forward)}
    fused_times = {}

    if backend == "tpu":
        try:
            from routest_tpu.ops import fused_eta_forward, pack_eta_params

            packed = jax.device_put(pack_eta_params(model, params))
            # Default tile plus the serving bench's recorded winner for
            # this batch (scripts/bench_serving_kernel.py sweeps tiles;
            # without the record the kernel would be timed at a tile
            # the sweep already beat). ONE parser owns the record —
            # EtaService's, which also rejects non-TPU (interpreter)
            # records and honors ROUTEST_KERNEL_BENCH relocation.
            from routest_tpu.serve.ml_service import EtaService

            tiles = {2048}
            _, tile_by_batch = EtaService._fused_win_bucket()
            if batch in tile_by_batch:
                tiles.add(tile_by_batch[batch])
            for tile in sorted(tiles):
                fused = lambda xx, _t=tile: fused_eta_forward(  # noqa: E731
                    packed, xx, n_q=n_q, tile=_t)
                if n_q:
                    # quantile path returns (B, Q); time the same scalar
                    # chain as XLA by feeding the median back
                    fused_times[tile] = measure(
                        lambda xx, _f=fused: _f(xx)[:, n_q // 2])
                else:
                    fused_times[tile] = measure(fused)
                # Consumers key on the literal "pallas_fused" name, so
                # the candidate table carries the best-timed tile under
                # that stable key; per-tile timings ride a separate
                # field. Updated per tile so a later tile's failure
                # (e.g. a stale recorded tile) keeps this one's timing.
                candidates["pallas_fused"] = min(fused_times.values())
        except Exception as e:  # kernel is an optimization, never a dependency
            print(f"bench: fused kernel unavailable: {type(e).__name__}: {e}",
                  file=sys.stderr)

    path = min(candidates, key=candidates.get)
    per_iter = candidates[path]
    preds_per_sec = batch / per_iter

    # Roofline: analytic FLOPs/bytes from the parameter tree (every 2D
    # weight is one m x n matmul per row), measured rate from the slope.
    leaves = jax.tree_util.tree_leaves(params)
    weight_mats = [l for l in leaves if getattr(l, "ndim", 0) == 2]
    flops_per_pred = float(sum(2 * l.shape[0] * l.shape[1]
                               for l in weight_mats))
    weight_bytes = float(sum(l.size * l.dtype.itemsize for l in leaves))
    feat_bytes = x.shape[1] * x.dtype.itemsize
    act_itemsize = jnp.dtype(model.policy.compute_dtype).itemsize
    # Two traffic models bracket reality: the LOWER bound counts only
    # the carried batch (read+write), the eta output, and one weight
    # stream — true if every inter-layer activation stays in VMEM. The
    # UPPER model adds every matmul output written to and re-read from
    # HBM (batch x hidden_width x 2 passes), which is where a
    # 131k-row batch actually lands (67 MB per 256-wide activation).
    # Measured MFU far below the lower-bound arithmetic intensity's
    # prediction ⇒ the upper model governs ⇒ bandwidth-bound.
    io_bytes = batch * (2 * feat_bytes + 4) + weight_bytes
    act_bytes = float(batch * sum(l.shape[1] for l in weight_mats)
                      * act_itemsize * 2)
    tflops = flops_per_pred * preds_per_sec / 1e12
    kind = str(getattr(jax.devices()[0], "device_kind", backend))
    peak_tflops, peak_hbm = chip_peaks(kind)
    compute_dtype = jnp.dtype(model.policy.compute_dtype).name
    roofline = {
        "device_kind": kind,
        "compute_dtype": compute_dtype,
        "flops_per_pred": flops_per_pred,
        "tflops": round(tflops, 2),
        "hbm_gbps_lower_bound": round(io_bytes / per_iter / 1e9, 1),
        "hbm_gbps_upper_model": round(
            (io_bytes + act_bytes) / per_iter / 1e9, 1),
        "arithmetic_intensity_flops_per_byte": round(
            flops_per_pred * batch / (io_bytes + act_bytes), 2),
    }
    if peak_tflops is not None and compute_dtype == "bfloat16":
        roofline["peak_tflops_bf16"] = peak_tflops
        roofline["peak_hbm_gbps"] = peak_hbm
        roofline["mfu"] = round(tflops / peak_tflops, 4)
        roofline["hbm_frac_upper_model"] = round(
            (io_bytes + act_bytes) / per_iter / 1e9 / peak_hbm, 4)

    print(json.dumps({
        "metric": "od_eta_preds_per_sec",
        "value": round(preds_per_sec, 1),
        "unit": "preds/s",
        "vs_baseline": round(preds_per_sec / TARGET_PREDS_PER_SEC, 3),
        "backend": backend,
        "path": path,
        "batch": batch,
        "init_s": round(init_s, 1),
        "paths_mps": {k: round(batch / v / 1e6, 2)
                      for k, v in candidates.items()},
        **({"pallas_tiles_mps": {str(t): round(batch / v / 1e6, 2)
                                 for t, v in sorted(fused_times.items())}}
           if len(fused_times) > 1 else {}),
        "roofline": roofline,
    }))


# ---------------------------------------------------------------------------
# Parent: watchdog. Never imports jax; always prints one JSON line.
# ---------------------------------------------------------------------------

def _scan_result(stdout, key: str = '"metric"') -> dict | None:
    if isinstance(stdout, bytes):  # TimeoutExpired may carry raw bytes
        stdout = stdout.decode("utf-8", "replace")
    for line in reversed((stdout or "").splitlines()):
        line = line.strip()
        if line.startswith("{") and key in line:
            try:
                return json.loads(line)
            except json.JSONDecodeError:
                continue
    return None


def _run_child(env_extra: dict, timeout_s: float,
               scan_key: str = '"metric"') -> tuple[dict | None, str]:
    """Run a measurement/probe child; return (parsed JSON, diagnostic)."""
    import signal

    env = dict(os.environ)
    env.update(env_extra)
    timed_out = False
    try:
        # Own session so the deadline can killpg the whole tree: the JAX
        # tunnel runtime may spawn helpers that inherit the pipes, and a
        # plain child-kill would leave subprocess blocked on the pipe.
        proc = subprocess.Popen(
            [sys.executable, os.path.abspath(__file__)],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            env=env, cwd=_REPO_DIR, start_new_session=True,
        )
    except Exception as e:  # noqa: BLE001 - diagnostic path
        return None, f"spawn failed: {type(e).__name__}: {e}"
    try:
        stdout, stderr = proc.communicate(timeout=timeout_s)
    except subprocess.TimeoutExpired:
        timed_out = True
        try:
            os.killpg(proc.pid, signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            proc.kill()
        try:
            stdout, stderr = proc.communicate(timeout=10)
        except subprocess.TimeoutExpired as e:
            # A setsid'd tunnel helper outside the killed group can hold
            # the pipe open; keep whatever the child managed to print.
            stdout = e.stdout or ""
            stderr = e.stderr or ""
    if isinstance(stderr, bytes):
        stderr = stderr.decode("utf-8", "replace")
    sys.stderr.write((stderr or "")[-2000:])
    # A child that printed its result and then hung in interpreter/backend
    # teardown (a known tunnel failure mode) still counts as a success.
    rec = _scan_result(stdout, scan_key)
    if rec is not None:
        return rec, ""
    if timed_out:
        return None, f"timeout after {timeout_s:.0f}s"
    tail = (stderr or stdout or "").strip().splitlines()[-3:]
    return None, f"rc={proc.returncode} no result line; tail={' | '.join(tail)}"


def _probe(timeout_s: float) -> dict:
    """Cheap tunnel-liveness check; always returns a record for the
    final JSON (latency on success, the failure diagnostic otherwise)."""
    t0 = time.perf_counter()
    rec, diag = _run_child({"ROUTEST_BENCH_PROBE": "1"}, timeout_s,
                           scan_key='"probe"')
    wall = round(time.perf_counter() - t0, 1)
    if rec is not None and rec.get("probe") == "ok":
        return {"ok": rec.get("backend") == "tpu", "wall_s": wall,
                "backend": rec.get("backend"),
                "dispatch_s": rec.get("probe_s")}
    return {"ok": False, "wall_s": wall, "error": diag}


def _cpu_env() -> dict:
    """CPU-fallback workload shrink — but never clobber knobs the
    operator (or a test) set explicitly; forcing the backend is the
    only non-negotiable part."""
    shrink = {"BENCH_BATCH": str(1 << 14), "BENCH_N_SHORT": "10",
              "BENCH_N_LONG": "40", "BENCH_REPEATS": "2"}
    out = {k: v for k, v in shrink.items() if k not in os.environ}
    out["BENCH_FORCE_CPU"] = "1"
    return out
# Short second-chance TPU attempt: half-length loops, two repeats.
_TPU_RETRY_ENV = {"BENCH_N_SHORT": "50", "BENCH_N_LONG": "200",
                  "BENCH_REPEATS": "2"}


def main() -> None:
    if os.environ.get("ROUTEST_BENCH_PROBE") == "1":
        probe_main()
        return
    if os.environ.get("ROUTEST_BENCH_CHILD") == "1":
        child_main()
        return

    diags = []
    probes = []
    # TPU-path skips, recorded structurally: a CPU record must carry WHY
    # the accelerator window was not spent without the probe's timeout
    # leaking into ``note`` (which is for measurement anomalies). Each
    # entry names the ladder stage that was skipped and the probe's
    # verbatim reason, so downstream tooling (the battery, the driver's
    # round parser) can branch on the stage instead of grepping prose.
    skipped = []
    rec = None

    probe = _probe(PROBE_TIMEOUT)
    probes.append(probe)
    if probe["ok"]:
        # Tunnel alive: the full TPU window is worth spending.
        rec, diag = _run_child({"ROUTEST_BENCH_CHILD": "1"},
                               TPU_ATTEMPT_TIMEOUT)
        if rec is None:
            diags.append(f"accel: {diag}")
    else:
        reason = probe.get("error") or f"backend is {probe.get('backend')}"
        skipped.append({"stage": "tpu_probe", "reason": reason})

    if rec is None:
        # CPU fallback keeps the record non-empty whatever the tunnel does.
        rec, diag = _run_child(dict(_cpu_env(), ROUTEST_BENCH_CHILD="1"),
                               CPU_ATTEMPT_TIMEOUT)
        if rec is None:
            diags.append(f"cpu: {diag}")
        if probe.get("error"):
            # The probe DIED (wedge/timeout) rather than answering
            # "backend is cpu"; wedges clear, so spend leftover budget
            # on one more try. A definitive cpu answer is final — no
            # amount of retrying conjures a TPU.
            probe2 = _probe(RETRY_PROBE_TIMEOUT)
            probes.append(probe2)
            if probe2["ok"]:
                rec2, diag = _run_child(
                    dict(_TPU_RETRY_ENV, ROUTEST_BENCH_CHILD="1"),
                    RETRY_TPU_TIMEOUT)
                if rec2 is not None:
                    rec = rec2
                else:
                    diags.append(f"accel-retry: {diag}")
            else:
                reason = (probe2.get("error")
                          or f"backend is {probe2.get('backend')}")
                skipped.append({"stage": "tpu_retry_probe",
                                "reason": reason})

    skip_prose = [f"{s['stage']}: {s['reason']}" for s in skipped]
    if rec is None:
        # Total failure: still emit a parseable record with diagnostics.
        print(json.dumps({
            "metric": "od_eta_preds_per_sec", "value": 0.0,
            "unit": "preds/s", "vs_baseline": 0.0,
            "error": "; ".join(diags + skip_prose),
            "skipped": skipped, "probes": probes,
        }))
        return

    if diags:
        rec["note"] = "; ".join(diags)
    if skipped:
        rec["skipped"] = skipped
        # Same caveat contract as every battery artifact: a fallback
        # record says on its face what host actually measured it.
        rec["host_caveat"] = (
            f"cpu fallback record: {'; '.join(skip_prose)} — "
            "re-record when a TPU answers the probe")
    rec["probes"] = probes
    if rec.get("backend") == "tpu":
        try:
            art_dir = os.path.join(_REPO_DIR, "artifacts")
            os.makedirs(art_dir, exist_ok=True)
            with open(os.path.join(art_dir, "bench_tpu.json"), "w") as f:
                json.dump(dict(rec, recorded_unix=int(time.time())), f,
                          indent=2)
        except OSError as e:
            print(f"bench: could not record artifact: {e}", file=sys.stderr)
    print(json.dumps(rec))


if __name__ == "__main__":
    main()
