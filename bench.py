"""Benchmark: OD-pair ETA scoring throughput on the available accelerator.

BASELINE.json config 2 ("route_optimizer_twx2 batch scoring") scaled up:
HBM-resident OD batches through the ETA model. The reference scores one
row per HTTP request on CPU (``Flaskr/ml.py:51-53``); the north-star
target is >=10,000 preds/sec (v5e-8). Prints ONE JSON line on stdout,
always — even when the accelerator is unreachable.

Architecture (hardened after round 1, where backend init hung >400 s and
the driver captured rc=1 with no JSON):

* The PARENT process never imports jax. It launches the measurement as a
  CHILD subprocess under a hard wall-clock deadline, first on the default
  (TPU/axon) backend, then — if that child dies, hangs, or emits no
  result — on the CPU backend with a smaller workload. Whatever happens,
  the parent prints exactly one ``{"metric": ...}`` JSON line.
* The CHILD (``ROUTEST_BENCH_CHILD=1``) does the actual timing.

Methodology — the TPU is reached through a tunnel whose dispatch+fetch
round trip is ~70 ms and highly variable, so host-side loops measure
noise. Instead the scoring step is chained inside a device-side
``lax.fori_loop`` (each iteration's input depends on the previous output:
no dead-code elimination, strict serialization) and the per-step time is
the SLOPE between a short and a long loop, cancelling the fixed
round-trip cost. Two forward paths are measured — the jit-compiled XLA
model and the fused Pallas kernel (``ops/fused_mlp.py``, TPU only) — and
the faster wins. A successful accelerator run is recorded to
``artifacts/bench_tpu.json`` for audit.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

TARGET_PREDS_PER_SEC = 10_000.0  # BASELINE.json north star

# Child workload knobs (overridable so the parent can shrink the CPU run).
BATCH = 1 << 17                  # 131,072 OD pairs per device call
N_SHORT, N_LONG = 100, 400       # fori_loop lengths for the slope
REPEATS = 3

# Parent deadlines (seconds). The driver killed round 1 at ~400 s with no
# output, so both attempts PLUS the two 10 s post-kill pipe drains must
# sum below that: 250 + 110 + 2*10 = 390 s worst case.
TPU_ATTEMPT_TIMEOUT = float(os.environ.get("BENCH_TPU_TIMEOUT", "250"))
CPU_ATTEMPT_TIMEOUT = float(os.environ.get("BENCH_CPU_TIMEOUT", "110"))

_REPO_DIR = os.path.dirname(os.path.abspath(__file__)) or "."


# ---------------------------------------------------------------------------
# Child: the actual measurement (runs with jax imported, backend decided by
# the environment the parent set).
# ---------------------------------------------------------------------------

def child_main() -> None:
    import jax

    # The sandbox's axon site customization re-exports JAX_PLATFORMS, so the
    # env var cannot force the CPU backend — only the config API can
    # (same workaround as tests/conftest.py).
    if os.environ.get("BENCH_FORCE_CPU") == "1":
        jax.config.update("jax_platforms", "cpu")

    # Persistent XLA cache: repeat bench runs (and the driver's end-of-round
    # run after a warm dev session) skip recompilation of the loop programs.
    from routest_tpu.core.cache import enable_compile_cache

    enable_compile_cache()

    import jax.numpy as jnp
    import numpy as np

    from routest_tpu.data.features import batch_from_mapping
    from routest_tpu.data.synthetic import generate_dataset
    from routest_tpu.models.eta_mlp import EtaMLP
    from routest_tpu.train.checkpoint import default_model_path, load_model

    batch = int(os.environ.get("BENCH_BATCH", str(BATCH)))
    n_short = int(os.environ.get("BENCH_N_SHORT", str(N_SHORT)))
    n_long = int(os.environ.get("BENCH_N_LONG", str(N_LONG)))
    repeats = int(os.environ.get("BENCH_REPEATS", str(REPEATS)))

    t0 = time.perf_counter()
    backend = jax.default_backend()  # forces backend init
    init_s = time.perf_counter() - t0
    print(f"bench: backend={backend} init={init_s:.1f}s", file=sys.stderr)

    try:
        model, params = load_model(default_model_path())
    except Exception:
        model = EtaMLP()
        params = model.init(jax.random.PRNGKey(0))
    # load_model returns host numpy arrays; without an explicit device_put
    # every jit call re-uploads the params.
    params = jax.device_put(params)

    data = generate_dataset(batch, seed=123)
    x = jax.device_put(jnp.asarray(batch_from_mapping(data)))

    def make_runner(forward):
        # The loop bound is a traced argument: ONE compile per path (the
        # remote tunnel makes each compile expensive), short and long
        # runs share it (fori_loop with a dynamic bound is a while_loop).
        @jax.jit
        def run(xx, n_iters):
            def body(_, carry):
                xx, _eta = carry
                eta = forward(xx)
                return xx.at[:, 10].add(eta * 1e-12), eta

            return jax.lax.fori_loop(
                0, n_iters, body, (xx, jnp.zeros((batch,), jnp.float32)),
            )

        return run

    def measure(forward) -> float:
        run = make_runner(forward)

        def timed(n: int) -> float:
            t0 = time.perf_counter()
            _, eta = run(x, n)
            np.asarray(eta[:1])  # host fetch = the only real barrier
            return time.perf_counter() - t0

        timed(2)  # compile + warm
        slopes = []
        for _ in range(repeats):
            t_short = timed(n_short)
            t_long = timed(n_long)
            slopes.append((t_long - t_short) / (n_long - n_short))
        return max(float(np.median(slopes)), 1e-9)

    candidates = {"xla": measure(lambda xx: model.apply(params, xx))}

    if backend == "tpu":
        try:
            from routest_tpu.ops import fused_eta_forward, pack_eta_params

            packed = jax.device_put(pack_eta_params(model, params))
            candidates["pallas_fused"] = measure(
                lambda xx: fused_eta_forward(packed, xx))
        except Exception as e:  # kernel is an optimization, never a dependency
            print(f"bench: fused kernel unavailable: {type(e).__name__}: {e}",
                  file=sys.stderr)

    path = min(candidates, key=candidates.get)
    per_iter = candidates[path]
    preds_per_sec = batch / per_iter
    print(json.dumps({
        "metric": "od_eta_preds_per_sec",
        "value": round(preds_per_sec, 1),
        "unit": "preds/s",
        "vs_baseline": round(preds_per_sec / TARGET_PREDS_PER_SEC, 3),
        "backend": backend,
        "path": path,
        "batch": batch,
        "init_s": round(init_s, 1),
        "paths_mps": {k: round(batch / v / 1e6, 2)
                      for k, v in candidates.items()},
    }))


# ---------------------------------------------------------------------------
# Parent: watchdog. Never imports jax; always prints one JSON line.
# ---------------------------------------------------------------------------

def _scan_result(stdout) -> dict | None:
    if isinstance(stdout, bytes):  # TimeoutExpired may carry raw bytes
        stdout = stdout.decode("utf-8", "replace")
    for line in reversed((stdout or "").splitlines()):
        line = line.strip()
        if line.startswith("{") and '"metric"' in line:
            try:
                return json.loads(line)
            except json.JSONDecodeError:
                continue
    return None


def _run_child(env_extra: dict, timeout_s: float) -> tuple[dict | None, str]:
    """Run the measurement child; return (parsed JSON record, diagnostic)."""
    import signal

    env = dict(os.environ)
    env.update(env_extra)
    env["ROUTEST_BENCH_CHILD"] = "1"
    timed_out = False
    try:
        # Own session so the deadline can killpg the whole tree: the JAX
        # tunnel runtime may spawn helpers that inherit the pipes, and a
        # plain child-kill would leave subprocess blocked on the pipe.
        proc = subprocess.Popen(
            [sys.executable, os.path.abspath(__file__)],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            env=env, cwd=_REPO_DIR, start_new_session=True,
        )
    except Exception as e:  # noqa: BLE001 - diagnostic path
        return None, f"spawn failed: {type(e).__name__}: {e}"
    try:
        stdout, stderr = proc.communicate(timeout=timeout_s)
    except subprocess.TimeoutExpired:
        timed_out = True
        try:
            os.killpg(proc.pid, signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            proc.kill()
        try:
            stdout, stderr = proc.communicate(timeout=10)
        except subprocess.TimeoutExpired as e:
            # A setsid'd tunnel helper outside the killed group can hold
            # the pipe open; keep whatever the child managed to print.
            stdout = e.stdout or ""
            stderr = e.stderr or ""
    if isinstance(stderr, bytes):
        stderr = stderr.decode("utf-8", "replace")
    sys.stderr.write((stderr or "")[-2000:])
    # A child that printed its result and then hung in interpreter/backend
    # teardown (a known tunnel failure mode) still counts as a success.
    rec = _scan_result(stdout)
    if rec is not None:
        return rec, ""
    if timed_out:
        return None, f"timeout after {timeout_s:.0f}s"
    tail = (stderr or stdout or "").strip().splitlines()[-3:]
    return None, f"rc={proc.returncode} no result line; tail={' | '.join(tail)}"


def main() -> None:
    if os.environ.get("ROUTEST_BENCH_CHILD") == "1":
        child_main()
        return

    diags = []
    # Attempt 1: default backend (TPU via axon when available).
    rec, diag = _run_child({}, TPU_ATTEMPT_TIMEOUT)
    if rec is None:
        diags.append(f"accel: {diag}")
        # Attempt 2: CPU fallback, smaller workload so it finishes fast.
        rec, diag = _run_child(
            {"BENCH_FORCE_CPU": "1", "BENCH_BATCH": str(1 << 14),
             "BENCH_N_SHORT": "10", "BENCH_N_LONG": "40",
             "BENCH_REPEATS": "2"},
            CPU_ATTEMPT_TIMEOUT)
        if rec is None:
            diags.append(f"cpu: {diag}")

    if rec is None:
        # Total failure: still emit a parseable record with diagnostics.
        print(json.dumps({
            "metric": "od_eta_preds_per_sec", "value": 0.0,
            "unit": "preds/s", "vs_baseline": 0.0,
            "error": "; ".join(diags),
        }))
        return

    if diags:
        rec["note"] = "; ".join(diags)
    if rec.get("backend") == "tpu":
        try:
            art_dir = os.path.join(_REPO_DIR, "artifacts")
            os.makedirs(art_dir, exist_ok=True)
            with open(os.path.join(art_dir, "bench_tpu.json"), "w") as f:
                json.dump(dict(rec, recorded_unix=int(time.time())), f,
                          indent=2)
        except OSError as e:
            print(f"bench: could not record artifact: {e}", file=sys.stderr)
    print(json.dumps(rec))


if __name__ == "__main__":
    main()
