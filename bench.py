"""Benchmark: OD-pair ETA scoring throughput on the available accelerator.

BASELINE.json config 2 ("route_optimizer_twx2 batch scoring") scaled up:
HBM-resident OD batches through the ETA model. The reference scores one
row per HTTP request on CPU (``Flaskr/ml.py:51-53``); the north-star
target is ≥10,000 preds/sec (v5e-8). Prints ONE JSON line.

Methodology — the TPU is reached through a tunnel whose dispatch+fetch
round trip is ~70 ms and highly variable, so host-side loops measure
noise. Instead the scoring step is chained inside a device-side
``lax.fori_loop`` (each iteration's input depends on the previous output:
no dead-code elimination, strict serialization) and the per-step time is
the SLOPE between a short and a long loop, cancelling the fixed
round-trip cost. Two forward paths are measured — the jit-compiled XLA
model and the fused Pallas kernel (``ops/fused_mlp.py``, TPU only) — and
the faster wins.
"""

from __future__ import annotations

import json
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

TARGET_PREDS_PER_SEC = 10_000.0  # BASELINE.json north star
BATCH = 1 << 17                  # 131,072 OD pairs per device call
N_SHORT, N_LONG = 100, 400       # fori_loop lengths for the slope
REPEATS = 3


def main() -> None:
    from routest_tpu.data.features import batch_from_mapping
    from routest_tpu.data.synthetic import generate_dataset
    from routest_tpu.models.eta_mlp import EtaMLP
    from routest_tpu.train.checkpoint import default_model_path, load_model

    try:
        model, params = load_model(default_model_path())
    except Exception:
        model = EtaMLP()
        params = model.init(jax.random.PRNGKey(0))
    # load_model returns host numpy arrays; without an explicit device_put
    # every jit call re-uploads the params.
    params = jax.device_put(params)

    data = generate_dataset(BATCH, seed=123)
    x = jax.device_put(jnp.asarray(batch_from_mapping(data)))

    def make_runner(forward):
        # The loop bound is a traced argument: ONE compile per path (the
        # remote tunnel makes each compile expensive), short and long
        # runs share it (fori_loop with a dynamic bound is a while_loop).
        @jax.jit
        def run(xx, n_iters):
            def body(_, carry):
                xx, _eta = carry
                eta = forward(xx)
                return xx.at[:, 10].add(eta * 1e-12), eta

            return jax.lax.fori_loop(
                0, n_iters, body, (xx, jnp.zeros((BATCH,), jnp.float32)),
            )

        return run

    def measure(forward) -> float:
        run = make_runner(forward)

        def timed(n: int) -> float:
            t0 = time.perf_counter()
            _, eta = run(x, n)
            np.asarray(eta[:1])  # host fetch = the only real barrier
            return time.perf_counter() - t0

        timed(2)  # compile + warm
        slopes = []
        for _ in range(REPEATS):
            t_short = timed(N_SHORT)
            t_long = timed(N_LONG)
            slopes.append((t_long - t_short) / (N_LONG - N_SHORT))
        return max(float(np.median(slopes)), 1e-9)

    candidates = {"xla": measure(lambda xx: model.apply(params, xx))}

    if jax.default_backend() == "tpu":
        try:
            from routest_tpu.ops import fused_eta_forward, pack_eta_params

            packed = jax.device_put(pack_eta_params(model, params))
            candidates["pallas_fused"] = measure(
                lambda xx: fused_eta_forward(packed, xx))
        except Exception as e:  # kernel is an optimization, never a dependency
            print(f"bench: fused kernel unavailable: {type(e).__name__}: {e}",
                  file=sys.stderr)

    path = min(candidates, key=candidates.get)
    per_iter = candidates[path]
    preds_per_sec = BATCH / per_iter
    print(json.dumps({
        "metric": "od_eta_preds_per_sec",
        "value": round(preds_per_sec, 1),
        "unit": "preds/s",
        "vs_baseline": round(preds_per_sec / TARGET_PREDS_PER_SEC, 3),
    }))
    print(f"bench: path={path} " + " ".join(
        f"{k}={BATCH / v / 1e6:.1f}M/s" for k, v in candidates.items()),
        file=sys.stderr)


if __name__ == "__main__":
    main()
