"""Benchmark: OD-pair ETA scoring throughput on the available accelerator.

BASELINE.json config 2 ("route_optimizer_twx2 batch scoring") scaled up:
HBM-resident OD batches through the jit-compiled ETA model. The reference
scores one row per HTTP request on CPU (``Flaskr/ml.py:51-53``); the
north-star target is ≥10,000 preds/sec (v5e-8). Prints ONE JSON line.
"""

from __future__ import annotations

import json
import time

import jax
import jax.numpy as jnp
import numpy as np

TARGET_PREDS_PER_SEC = 10_000.0  # BASELINE.json north star
BATCH = 1 << 17                  # 131,072 OD pairs per device call
ITERS = 200
REPEATS = 5


def main() -> None:
    from routest_tpu.data.features import batch_from_mapping
    from routest_tpu.data.synthetic import generate_dataset
    from routest_tpu.models.eta_mlp import EtaMLP
    from routest_tpu.train.checkpoint import default_model_path, load_model

    try:
        model, params = load_model(default_model_path())
    except Exception:
        model = EtaMLP()
        params = model.init(jax.random.PRNGKey(0))
    # load_model returns host numpy arrays; without an explicit device_put
    # every jit call re-uploads the params.
    params = jax.device_put(params)

    data = generate_dataset(BATCH, seed=123)
    x = jnp.asarray(batch_from_mapping(data))
    x = jax.device_put(x)

    # Timing on the tunneled TPU platform needs care: block_until_ready
    # returns before remote execution finishes, and results that are never
    # fetched are never executed. So (a) each iteration's input depends on
    # the previous output — no dead code, strict serial execution — and
    # (b) the clock stops on a device→host fetch, with fixed round-trip
    # latency removed by differencing two run lengths.
    @jax.jit
    def step(p, xx):
        eta = model.apply(p, xx)
        return xx.at[:, 10].add(eta * 1e-12), eta

    def timed(iters: int) -> float:
        xx = x
        t0 = time.perf_counter()
        eta = None
        for _ in range(iters):
            xx, eta = step(params, xx)
        np.asarray(eta[:1])  # host fetch = the only real barrier
        return time.perf_counter() - t0

    timed(2)  # compile + warmup
    diffs = []
    for _ in range(REPEATS):
        t_short = timed(ITERS)
        t_long = timed(2 * ITERS)
        diffs.append((t_long - t_short) / ITERS)
    per_iter = max(float(np.median(diffs)), 1e-9)

    preds_per_sec = BATCH / per_iter
    print(json.dumps({
        "metric": "od_eta_preds_per_sec",
        "value": round(preds_per_sec, 1),
        "unit": "preds/s",
        "vs_baseline": round(preds_per_sec / TARGET_PREDS_PER_SEC, 3),
    }))


if __name__ == "__main__":
    main()
