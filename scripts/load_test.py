"""Config-5 workload: the serving API under concurrent map-app-style load.

Emulates the Laravel-proxy scenario of BASELINE.json config 5: many
concurrent clients calling ``/api/predict_eta`` (the batched hot path)
and a sprinkling of ``/api/optimize_route`` (the heavier VRP+geometry
path), against a server that is by default spawned in-process here.
Reports RPS and latency percentiles per endpoint, plus the server's own
``/api/metrics`` view (batcher coalescing stats).

Usage: python scripts/load_test.py [--threads 32] [--requests 50]
       [--base-url http://host:port]  (target an already-running server)
"""

from __future__ import annotations

import argparse
import json
import os
import queue
import random
import sys
import threading
import time
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _post(base: str, path: str, payload: dict, timeout: float = 30.0):
    req = urllib.request.Request(
        f"{base}{path}", data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"}, method="POST",
    )
    t0 = time.perf_counter()
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        body = resp.read()
        return time.perf_counter() - t0, resp.status, body


def _get(base: str, path: str, timeout: float = 10.0):
    with urllib.request.urlopen(f"{base}{path}", timeout=timeout) as resp:
        return json.loads(resp.read())


def _percentiles(samples):
    ordered = sorted(samples)

    def pct(p):
        return ordered[min(len(ordered) - 1, int(p * len(ordered)))] * 1000

    return {"p50_ms": round(pct(0.5), 2), "p95_ms": round(pct(0.95), 2),
            "p99_ms": round(pct(0.99), 2), "mean_ms":
            round(1000 * sum(samples) / len(samples), 2)}


def run_load(base: str, n_threads: int, n_requests: int):
    from routest_tpu.data.locations import SEED_LOCATIONS

    eta_lat: list = []
    opt_lat: list = []
    errors: list = []
    lock = threading.Lock()

    def eta_payload(rng):
        return {
            "summary": {"distance": rng.uniform(500, 40_000)},
            "weather": rng.choice(["Sunny", "Cloudy", "Stormy", "Windy", "Fog"]),
            "traffic": rng.choice(["Low", "Medium", "High", "Jam"]),
            "driver_age": rng.uniform(19, 60),
            "pickup_time": "2026-07-29T18:00:00",
        }

    def opt_payload(rng):
        picks = rng.sample(range(1, len(SEED_LOCATIONS)), 3)
        return {
            "source_point": {"lat": SEED_LOCATIONS[0][1], "lon": SEED_LOCATIONS[0][2]},
            "destination_points": [
                {"lat": SEED_LOCATIONS[i][1], "lon": SEED_LOCATIONS[i][2], "payload": 1}
                for i in picks
            ],
            "driver_details": {"driver_name": f"lt-{rng.random():.4f}",
                               "vehicle_type": "car",
                               "vehicle_capacity": 100,
                               "maximum_distance": 200_000},
            "use_ml_eta": True,
            "context": {"weather": "Sunny", "traffic": "Medium"},
        }

    def worker(seed: int):
        rng = random.Random(seed)
        for i in range(n_requests):
            try:
                if i % 10 == 9:  # 10% heavy optimize calls
                    dt_s, status, _ = _post(base, "/api/optimize_route",
                                            opt_payload(rng))
                    with lock:
                        opt_lat.append(dt_s)
                else:
                    dt_s, status, _ = _post(base, "/api/predict_eta",
                                            eta_payload(rng))
                    with lock:
                        eta_lat.append(dt_s)
                if status != 200:
                    with lock:
                        errors.append(status)
            except Exception as e:
                with lock:
                    errors.append(str(e)[:80])

    threads = [threading.Thread(target=worker, args=(s,)) for s in range(n_threads)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0

    total = len(eta_lat) + len(opt_lat)
    report = {
        "threads": n_threads,
        "requests": total,
        "wall_seconds": round(wall, 2),
        "rps": round(total / wall, 1),
        "errors": len(errors),
        "predict_eta": _percentiles(eta_lat) if eta_lat else {},
        "optimize_route": _percentiles(opt_lat) if opt_lat else {},
    }
    try:
        report["server_metrics"] = _get(base, "/api/metrics")
    except Exception:
        pass
    return report, errors


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--threads", type=int, default=32)
    parser.add_argument("--requests", type=int, default=50,
                        help="requests per thread")
    parser.add_argument("--base-url", default=None,
                        help="target a running server instead of self-spawning")
    args = parser.parse_args()

    if args.base_url:
        base = args.base_url.rstrip("/")
    else:
        # self-spawn on a free port with an in-memory stack
        from werkzeug.serving import make_server

        from routest_tpu.serve.__main__ import ensure_model
        from routest_tpu.serve.app import create_app
        from routest_tpu.train.checkpoint import default_model_path

        ensure_model(default_model_path())
        app = create_app()
        server = make_server("127.0.0.1", 0, app, threaded=True)
        threading.Thread(target=server.serve_forever, daemon=True).start()
        base = f"http://127.0.0.1:{server.server_port}"
        print(f"[load_test] self-spawned server at {base}")

    report, errors = run_load(base, args.threads, args.requests)
    print(json.dumps(report, indent=2))
    if errors:
        print(f"first errors: {errors[:5]}", file=sys.stderr)
    out = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                       "artifacts", "load_test.json")
    os.makedirs(os.path.dirname(out), exist_ok=True)
    with open(out, "w") as f:
        json.dump(report, f, indent=2)
    sys.exit(1 if errors else 0)


if __name__ == "__main__":
    main()
