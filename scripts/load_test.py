"""Config-5 workload: the serving API under concurrent map-app-style load.

Emulates the Laravel-proxy scenario of BASELINE.json config 5: many
concurrent clients calling ``/api/predict_eta`` (the batched hot path)
and a sprinkling of ``/api/optimize_route`` (the heavier VRP+geometry
path), against a server that is by default spawned in-process here.
Reports RPS and latency percentiles per endpoint, plus the server's own
``/api/metrics`` view (batcher coalescing stats).

Usage: python scripts/load_test.py [--threads 32] [--requests 50]
       [--base-url http://host:port]  (target an already-running server)

All phases here are CLOSED-LOOP (each client waits for its response
before sending again) and their artifacts say so (``"loop":
"closed"``): under overload they self-throttle and under-report the
user-visible tail (coordinated omission). ``--open-loop --rate R``
switches to the ``routest_tpu/loadgen`` engine — a seeded arrival
schedule fired independently of the server, Zipf-skewed OD keys,
latency measured from intended send time. See docs/LOADGEN.md.
"""

from __future__ import annotations

import argparse
import http.client
import json
import os
import queue
import random
import sys
import threading
import time
import urllib.parse
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


class PersistentPoster:
    """One HTTP/1.1 keep-alive connection with a reconnect-once retry.

    Shared by the single-row and batch phases so both measure the server
    under the identical retry/timing contract: a keep-alive close
    reconnects once and the FULL exchange (including the reconnect) stays
    in the timed window.
    """

    def __init__(self, base: str, timeout: float = 30.0) -> None:
        self._parts = urllib.parse.urlsplit(base)
        self._cls = (http.client.HTTPSConnection
                     if self._parts.scheme == "https"
                     else http.client.HTTPConnection)
        self._timeout = timeout
        self._conn = self._make()

    def _make(self):
        return self._cls(self._parts.hostname, self._parts.port,
                         timeout=self._timeout)

    def reset(self) -> None:
        self._conn.close()
        self._conn = self._make()

    def close(self) -> None:
        self._conn.close()

    def post(self, path: str, payload: dict):
        """→ (seconds, status, raw_body)."""
        body = json.dumps(payload).encode()
        headers = {"Content-Type": "application/json"}
        t0 = time.perf_counter()
        try:
            self._conn.request("POST", path, body=body, headers=headers)
            resp = self._conn.getresponse()
            raw = resp.read()
        except (http.client.HTTPException, OSError):
            self.reset()
            self._conn.request("POST", path, body=body, headers=headers)
            resp = self._conn.getresponse()
            raw = resp.read()
        return time.perf_counter() - t0, resp.status, raw


def _get(base: str, path: str, timeout: float = 10.0):
    with urllib.request.urlopen(f"{base}{path}", timeout=timeout) as resp:
        return json.loads(resp.read())


def _percentiles(samples):
    ordered = sorted(samples)

    def pct(p):
        return ordered[min(len(ordered) - 1, int(p * len(ordered)))] * 1000

    return {"p50_ms": round(pct(0.5), 2), "p95_ms": round(pct(0.95), 2),
            "p99_ms": round(pct(0.99), 2), "mean_ms":
            round(1000 * sum(samples) / len(samples), 2)}


def run_load(bases, n_threads: int, n_requests: int):
    """``bases``: one or more server base URLs; client threads round-robin
    across them (multi-worker mode shares one SSE broker behind them)."""
    from routest_tpu.data.locations import SEED_LOCATIONS

    eta_lat: list = []
    opt_lat: list = []
    errors: list = []
    lock = threading.Lock()

    def eta_payload(rng):
        return {
            "summary": {"distance": rng.uniform(500, 40_000)},
            "weather": rng.choice(["Sunny", "Cloudy", "Stormy", "Windy", "Fog"]),
            "traffic": rng.choice(["Low", "Medium", "High", "Jam"]),
            "driver_age": rng.uniform(19, 60),
            "pickup_time": "2026-07-29T18:00:00",
        }

    def opt_payload(rng):
        picks = rng.sample(range(1, len(SEED_LOCATIONS)), 3)
        return {
            "source_point": {"lat": SEED_LOCATIONS[0][1], "lon": SEED_LOCATIONS[0][2]},
            "destination_points": [
                {"lat": SEED_LOCATIONS[i][1], "lon": SEED_LOCATIONS[i][2], "payload": 1}
                for i in picks
            ],
            "driver_details": {"driver_name": f"lt-{rng.random():.4f}",
                               "vehicle_type": "car",
                               "vehicle_capacity": 100,
                               "maximum_distance": 200_000},
            "use_ml_eta": True,
            "context": {"weather": "Sunny", "traffic": "Medium"},
        }

    def worker(seed: int):
        rng = random.Random(seed)
        # One persistent HTTP/1.1 connection per worker: measures the
        # server, not per-request TCP/thread setup.
        poster = PersistentPoster(bases[seed % len(bases)])
        for i in range(n_requests):
            try:
                if i % 10 == 9:  # 10% heavy optimize calls
                    dt_s, status, _ = poster.post("/api/optimize_route",
                                                  opt_payload(rng))
                    with lock:
                        opt_lat.append(dt_s)
                else:
                    dt_s, status, _ = poster.post("/api/predict_eta",
                                                  eta_payload(rng))
                    with lock:
                        eta_lat.append(dt_s)
                if status != 200:
                    with lock:
                        errors.append(status)
            except Exception as e:
                poster.reset()
                with lock:
                    errors.append(str(e)[:80])
        poster.close()

    threads = [threading.Thread(target=worker, args=(s,)) for s in range(n_threads)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0

    total = len(eta_lat) + len(opt_lat)
    report = {
        "threads": n_threads,
        "workers": len(bases),
        "requests": total,
        "wall_seconds": round(wall, 2),
        "rps": round(total / wall, 1),
        "errors": len(errors),
        "predict_eta": _percentiles(eta_lat) if eta_lat else {},
        "optimize_route": _percentiles(opt_lat) if opt_lat else {},
    }
    try:
        # one entry per worker — scraping only worker 0 would present
        # ~1/N of the traffic as if it were the whole run's server view
        report["server_metrics"] = [_get(b, "/api/metrics") for b in bases]
    except Exception:
        pass
    return report, errors


def run_vrp_batch_load(bases, n_threads: int, n_requests: int,
                       problems_per_request: int = 32,
                       road_frac: float = 0.25):
    """Batched route OPTIMIZATION phase: many VRPs per request through
    ``/api/optimize_route_batch`` (one vmapped device solve per request
    — the batch-of-problems axis on the serving path). ``road_frac``
    of the problems carry ``road_graph: true``, exercising the grouped
    street-network solves (``RoadRouter.route_legs_batch``) under the
    same budget. Reports problems/sec and per-request latency."""
    from routest_tpu.data.locations import SEED_LOCATIONS

    latencies: list = []
    solved = [0]
    road_solved = [0]
    errors: list = []
    lock = threading.Lock()

    def payload(rng):
        items = []
        for _ in range(problems_per_request):
            picks = rng.sample(range(1, len(SEED_LOCATIONS)),
                               rng.randint(2, 6))
            item = {
                "source_point": {"lat": SEED_LOCATIONS[0][1],
                                 "lon": SEED_LOCATIONS[0][2]},
                "destination_points": [
                    {"lat": SEED_LOCATIONS[i][1],
                     "lon": SEED_LOCATIONS[i][2], "payload": 1}
                    for i in picks],
                "driver_details": {"vehicle_capacity": 100,
                                   "maximum_distance": 200_000},
                "refine": rng.random() < 0.5,
            }
            if rng.random() < road_frac:
                item["road_graph"] = True
                item["pickup_time"] = (
                    f"2026-03-02T{rng.randint(0, 23):02d}:30:00")
            items.append(item)
        return {"items": items, "use_ml_eta": True}

    def worker(seed: int):
        rng = random.Random(seed)
        poster = PersistentPoster(bases[seed % len(bases)], timeout=120)
        for _ in range(n_requests):
            try:
                dt_s, status, raw = poster.post("/api/optimize_route_batch",
                                                payload(rng))
                out = json.loads(raw)
                with lock:
                    if status == 200:
                        got = [it for it in out.get("items", [])
                               if isinstance(it, dict)
                               and "error" not in it]
                        latencies.append(dt_s)
                        solved[0] += len(got)
                        road_solved[0] += sum(
                            1 for it in got
                            if (it.get("properties") or {}).get("road_graph"))
                    else:
                        errors.append(status)
            except Exception as e:
                poster.reset()
                with lock:
                    errors.append(str(e)[:80])
        poster.close()

    # untimed warmup per worker base (same rationale as the ETA batch)
    for base in bases:
        warm = PersistentPoster(base, timeout=120)
        try:
            warm.post("/api/optimize_route_batch", payload(random.Random(0)))
        except Exception:
            pass
        warm.close()

    threads = [threading.Thread(target=worker, args=(3000 + s,))
               for s in range(n_threads)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    lat_ms = sorted(x * 1000 for x in latencies)

    def pct(p):
        return round(lat_ms[min(len(lat_ms) - 1,
                                int(p * len(lat_ms)))], 2) if lat_ms else None

    return {
        "problems_per_request": problems_per_request,
        "road_frac": road_frac,
        "threads": n_threads,
        "requests": len(latencies),
        "problems_solved": solved[0],
        "road_problems_solved": road_solved[0],
        "wall_seconds": round(wall, 2),
        "problems_per_s": round(solved[0] / wall, 1) if wall else 0.0,
        "errors": len(errors),
        "p50_ms": pct(0.50),
        "p95_ms": pct(0.95),
    }, errors


def run_road_route_load(bases, n_threads: int, n_requests: int):
    """Road-graph routing phase: ``/api/optimize_route`` with
    ``road_graph: true`` — true shortest paths over the street network,
    repriced by whichever learned leg pricer serves (GNN per-edge or
    route-transformer; the response's ``leg_cost_model`` records which,
    so the artifact shows the transformer path was actually exercised).
    The endpoint class the reference rents from ORS
    (``Flaskr/utils.py:97-109``)."""
    from routest_tpu.data.locations import SEED_LOCATIONS

    latencies: list = []
    errors: list = []
    pricers: dict = {}
    lock = threading.Lock()

    def payload(rng):
        picks = rng.sample(range(1, len(SEED_LOCATIONS)), rng.randint(2, 5))
        return {
            "source_point": {"lat": SEED_LOCATIONS[0][1],
                             "lon": SEED_LOCATIONS[0][2]},
            "destination_points": [
                {"lat": SEED_LOCATIONS[i][1], "lon": SEED_LOCATIONS[i][2],
                 "payload": 1} for i in picks],
            "driver_details": {"vehicle_capacity": 100,
                               "maximum_distance": 200_000},
            "road_graph": True,
            "refine": rng.random() < 0.5,
            "use_ml_eta": True,
            "context": {"weather": "Sunny", "traffic": "Medium"},
        }

    def worker(seed: int):
        rng = random.Random(seed)
        poster = PersistentPoster(bases[seed % len(bases)], timeout=120)
        for _ in range(n_requests):
            try:
                dt_s, status, raw = poster.post("/api/optimize_route",
                                                payload(rng))
                with lock:
                    if status == 200:
                        latencies.append(dt_s)
                        model = json.loads(raw).get("properties", {}).get(
                            "leg_cost_model", "unknown")
                        pricers[model] = pricers.get(model, 0) + 1
                    else:
                        errors.append(status)
            except Exception as e:
                poster.reset()
                with lock:
                    errors.append(str(e)[:80])
        poster.close()

    for base in bases:  # untimed warmup: first road solve builds the graph
        warm = PersistentPoster(base, timeout=180)
        try:
            warm.post("/api/optimize_route", payload(random.Random(0)))
        except Exception:
            pass
        warm.close()

    threads = [threading.Thread(target=worker, args=(5000 + s,))
               for s in range(n_threads)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    report = {
        "threads": n_threads,
        "requests": len(latencies),
        "wall_seconds": round(wall, 2),
        "rps": round(len(latencies) / wall, 1) if wall else 0.0,
        "errors": len(errors),
        "leg_cost_models_served": pricers,
        **(_percentiles(latencies) if latencies else {}),
    }
    return report, errors


def run_quantile_probe(bases):
    """Uncertainty-band phase: when the serving artifact carries
    quantile heads, every /api/predict_eta response must include a
    coherent p10 ≤ eta ≤ p90 band. Probes a spread of distances and
    reports coverage + coherence (skipped cleanly for point models)."""
    poster = PersistentPoster(bases[0])
    total, banded, incoherent = 0, 0, 0
    try:
        for dist in (500, 2_000, 8_000, 20_000, 40_000):
            _, status, raw = poster.post("/api/predict_eta", {
                "summary": {"distance": dist},
                "weather": "Stormy", "traffic": "Jam",
                "driver_age": 44,
                "pickup_time": "2026-07-29T18:00:00",
            })
            if status != 200:
                continue
            body = json.loads(raw)
            total += 1
            p10 = body.get("eta_minutes_ml_p10")
            p90 = body.get("eta_minutes_ml_p90")
            eta = body.get("eta_minutes_ml")
            if p10 is not None and p90 is not None:
                banded += 1
                if not (p10 <= eta <= p90):
                    incoherent += 1
    finally:
        poster.close()
    return {"probes": total, "with_band": banded,
            "band_incoherent": incoherent,
            "quantile_model_serving": banded > 0}


def run_latency_decomposition(bases):
    """Tunnel-vs-compute split for the batch path (VERDICT r3 weak #5:
    the TPU p95 miss was ATTRIBUTED to tunnel round trips but never
    measured). Single-threaded ``/api/predict_eta_batch`` at two batch
    sizes: the slope is the server's per-row cost (device compute +
    marshalling), the intercept is the fixed per-request overhead —
    HTTP + dispatch + tunnel round trips — which no batch size
    amortizes away. On a locally-attached-TPU production host the
    intercept shrinks by the tunnel RT; the slope is what this
    framework owns."""
    import numpy as np

    poster = PersistentPoster(bases[0], timeout=120)
    sizes = (1024, 16384)
    med = {}
    try:
        rng = random.Random(11)
        for size in sizes:
            payload = {
                "distance_m": [rng.uniform(500, 40_000) for _ in range(size)],
                "weather": ["Sunny"] * size,
                "traffic": ["Medium"] * size,
                "driver_age": [35.0] * size,
                "pickup_time": ["2026-07-29T18:00:00"] * size,
            }
            poster.post("/api/predict_eta_batch", payload)  # warm bucket
            times = []
            for _ in range(5):
                dt_s, status, _ = poster.post("/api/predict_eta_batch",
                                              payload)
                if status == 200:
                    times.append(dt_s)
            if times:
                med[size] = float(np.median(times))
    except Exception:
        pass
    finally:
        poster.close()
    if len(med) != 2:
        return {"error": "decomposition probes failed"}
    b1, b2 = sizes
    slope_s = (med[b2] - med[b1]) / (b2 - b1)
    fixed_s = med[b1] - slope_s * b1
    return {
        "batch_sizes": list(sizes),
        "median_latency_ms": {str(k): round(v * 1000, 2)
                              for k, v in med.items()},
        "per_row_us": round(max(slope_s, 0.0) * 1e6, 3),
        "fixed_overhead_ms": round(max(fixed_s, 0.0) * 1000, 2),
    }


def run_batch_load(bases, n_threads: int, n_requests: int,
                   batch_size: int):
    """North-star phase: OD *batches* through ``/api/predict_eta_batch``.

    The reference serves one OD pair per HTTP request
    (``Flaskr/routes.py:365-383``); BASELINE.json's target is ≥10k
    OD-pair preds/sec through the serving path. Columnar payloads, a few
    persistent connections, preds/sec = rows acknowledged / wall.
    """
    latencies: list = []
    rows_done = [0]
    errors: list = []
    lock = threading.Lock()

    def payload(rng):
        return {
            "distance_m": [rng.uniform(500, 40_000) for _ in range(batch_size)],
            "weather": rng.choice(["Sunny", "Cloudy", "Stormy", "Windy"]),
            "traffic": [rng.choice(["Low", "Medium", "High", "Jam"])
                        for _ in range(batch_size)],
            "driver_age": [rng.uniform(19, 60) for _ in range(batch_size)],
            "pickup_time": "2026-07-29T18:00:00",
        }

    def worker(seed: int):
        rng = random.Random(seed)
        poster = PersistentPoster(bases[seed % len(bases)], timeout=120)
        for _ in range(n_requests):
            try:
                dt_s, status, raw = poster.post("/api/predict_eta_batch",
                                                payload(rng))
                out = json.loads(raw)
                with lock:
                    if status == 200:
                        latencies.append(dt_s)
                        rows_done[0] += out.get("count", 0)
                    else:
                        errors.append(status)
            except Exception as e:
                poster.reset()
                with lock:
                    errors.append(str(e)[:80])
        poster.close()

    # One untimed warmup request PER WORKER: the very first batch
    # through a fresh connection pays one-off setup (TCP + device-path
    # first touch — ~3.9 s observed over the TPU tunnel vs 250 ms
    # steady-state) that is startup cost, not steady-state serving
    # latency. Standard load-testing methodology; the measured phase
    # starts warm on every base.
    for base in bases:
        warm = PersistentPoster(base, timeout=120)
        try:
            warm.post("/api/predict_eta_batch", payload(random.Random(0)))
        except Exception:
            pass
        warm.close()

    threads = [threading.Thread(target=worker, args=(1000 + s,))
               for s in range(n_threads)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    report = {
        "batch_size": batch_size,
        "threads": n_threads,
        "requests": len(latencies),
        "rows": rows_done[0],
        "wall_seconds": round(wall, 2),
        "preds_per_s": round(rows_done[0] / wall, 1) if wall else 0.0,
        "errors": len(errors),
        **(_percentiles(latencies) if latencies else {}),
    }
    return report, errors


def run_open_loop_mode(bases, args):
    """The ``--open-loop`` path: delegate arrival scheduling to
    ``routest_tpu/loadgen`` (this script stays the CLI; the engine owns
    the semantics). Reports CO-correct percentiles plus the fast-lane
    cache delta the Zipf key skew produced server-side."""
    from routest_tpu.loadgen import (RateCurve, ZipfODWorkload, cache_delta,
                                     fetch_metrics, paced_schedule,
                                     poisson_schedule, run_open_loop,
                                     summarize)

    curve = RateCurve.constant(args.rate)
    if args.arrival == "poisson":
        offsets = poisson_schedule(curve, args.duration, seed=args.seed)
    else:
        offsets = paced_schedule(curve, args.duration)
    workload = ZipfODWorkload(s=args.zipf_s, seed=args.seed)
    requests = workload.sequence(len(offsets))

    def metrics_all():
        out = {}
        for i, base in enumerate(bases):
            try:
                out[f"w{i}"] = fetch_metrics(base)
            except Exception:
                out[f"w{i}"] = {}
        return {"replica_metrics": out}

    before = metrics_all()
    records = run_open_loop(bases, offsets, requests,
                            workers=args.open_workers)
    report = summarize(records, args.duration, len(offsets))
    report.update({
        "arrival": curve.spec | {"process": args.arrival},
        "workload": {"kind": "zipf_od", "s": args.zipf_s,
                     "seed": args.seed, "od_pairs": len(workload.pairs)},
        "seed": args.seed,
        "workers": len(bases),
        "cache": cache_delta(before, metrics_all()),
    })
    return report


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--threads", type=int, default=None,
                        help="concurrent clients (default: min(32, 8 x "
                             "cores) — beyond ~8 in-flight requests per "
                             "core, client-side latency measures queueing "
                             "on the box, not the server; Little's law "
                             "puts the floor at threads/throughput)")
    parser.add_argument("--requests", type=int, default=50,
                        help="requests per thread")
    parser.add_argument("--base-url", default=None,
                        help="target a running server instead of self-spawning")
    parser.add_argument("--workers", type=int, default=1,
                        help="self-spawn N server worker processes sharing "
                             "one SSE broker (serve/netbus.py); clients "
                             "round-robin across workers")
    parser.add_argument("--p95-budget-ms", type=float, default=50.0,
                        help="fail if /api/predict_eta client p95 exceeds "
                             "this (0 disables)")
    parser.add_argument("--opt-budget-ms", type=float, default=750.0,
                        help="p95 budget for /api/optimize_route (0 off)")
    parser.add_argument("--road-budget-ms", type=float, default=1500.0,
                        help="p95 budget for road-graph optimize_route "
                             "(0 off)")
    parser.add_argument("--vrp-budget-ms", type=float, default=4000.0,
                        help="p95 budget for /api/optimize_route_batch "
                             "requests (32 VRPs each; 0 off)")
    parser.add_argument("--eta-batch-budget-ms", type=float, default=1000.0,
                        help="p95 budget for /api/predict_eta_batch "
                             "requests (0 off)")
    parser.add_argument("--road-requests", type=int, default=6,
                        help="road-graph requests per road worker "
                             "(0 skips the phase)")
    parser.add_argument("--cpu-budget-scale", type=float, default=8.0,
                        help="budget multiplier applied when the server "
                             "runs the CPU fallback backend — the stated "
                             "budgets are production (TPU-host) SLOs; a "
                             "1-core hermetic box is not the target they "
                             "bind (the artifact records the scaling)")
    parser.add_argument("--cpu", action="store_true",
                        help="hermetic CPU backend for the self-spawned "
                             "server (use when the TPU tunnel is down)")
    parser.add_argument("--batch-size", type=int, default=4096,
                        help="OD pairs per /api/predict_eta_batch request "
                             "(0 skips the batch phase)")
    parser.add_argument("--batch-requests", type=int, default=16,
                        help="batch requests per batch worker")
    parser.add_argument("--batch-threads", type=int, default=4,
                        help="concurrent batch clients")
    parser.add_argument("--out", default=None,
                        help="report artifact path (default: artifacts/"
                             "load_test.json, or load_test_tpu.json on "
                             "an accelerator backend). Name it for "
                             "one-off runs so the canonical artifacts "
                             "survive")
    parser.add_argument("--open-loop", action="store_true",
                        help="open-loop mode via routest_tpu/loadgen: "
                             "a seeded arrival schedule at --rate rps "
                             "fired independently of the server, "
                             "latency from INTENDED send time "
                             "(coordinated-omission-correct). Replaces "
                             "the closed-loop phases.")
    parser.add_argument("--rate", type=float, default=50.0,
                        help="open-loop offered rate in requests/s")
    parser.add_argument("--duration", type=float, default=30.0,
                        help="open-loop run length in seconds")
    parser.add_argument("--arrival", choices=("poisson", "paced"),
                        default="poisson",
                        help="open-loop arrival process (poisson = "
                             "memoryless users; paced = deterministic)")
    parser.add_argument("--zipf-s", type=float, default=1.1,
                        help="open-loop OD-key skew exponent (0 = "
                             "uniform)")
    parser.add_argument("--seed", type=int, default=42,
                        help="open-loop schedule + workload seed (same "
                             "seed ⇒ identical offered load)")
    parser.add_argument("--open-workers", type=int, default=64,
                        help="open-loop sender threads")
    args = parser.parse_args()
    # NB: --cpu configures the SERVER subprocess (via ROUTEST_FORCE_CPU
    # below); the load generator itself never touches jax.

    # A supervisor timeout (SIGTERM) must still tear down the spawned
    # server subprocesses — they hold live accelerator clients, and an
    # orphaned client is exactly the churn that wedges the TPU relay.
    # SystemExit rides the BaseException cleanup below.
    import signal as _signal

    _signal.signal(_signal.SIGTERM, lambda *_: sys.exit(143))

    server_procs = []
    broker = None
    if args.base_url:
        if args.workers > 1:
            parser.error("--workers spawns local servers and cannot be "
                         "combined with --base-url (target N external "
                         "workers by running one load_test per base)")
        bases = [args.base_url.rstrip("/")]
    else:
        # Self-spawn server(s) in SUBPROCESSES: an in-process server
        # would share the load generator's GIL, inflating client-side
        # percentiles with generator scheduling delay rather than
        # measuring the server (round 1 measured exactly that artifact).
        # --workers N spawns N worker processes sharing one SSE broker
        # (the cross-process bus, serve/netbus.py).
        import socket
        import subprocess

        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        env = dict(os.environ)
        if args.cpu or os.environ.get("ROUTEST_FORCE_CPU") == "1":
            env["ROUTEST_FORCE_CPU"] = "1"
        n_workers = max(1, args.workers)
        if n_workers > 1:
            from routest_tpu.serve.netbus import start_broker

            broker, _ = start_broker()
            env["REDIS_URL"] = f"tcp://127.0.0.1:{broker.port}"
            print(f"[load_test] broker at {env['REDIS_URL']}")
        ports = []
        for _ in range(n_workers):
            with socket.socket() as s:
                s.bind(("127.0.0.1", 0))
                ports.append(s.getsockname()[1])
        for port in ports:
            e = dict(env)
            e["PORT"] = str(port)
            server_procs.append(subprocess.Popen(
                [sys.executable, "-m", "routest_tpu.serve"], env=e, cwd=repo))
        bases = [f"http://127.0.0.1:{p}" for p in ports]
        print(f"[load_test] spawned {n_workers} server worker(s): "
              f"{', '.join(bases)}")
        deadline = time.time() + 240  # first boot may train + warm buckets
        for base in bases:
            while True:
                try:
                    if _get(base, "/api/ping", timeout=2).get("ok"):
                        break
                except Exception:
                    pass
                if any(p.poll() is not None for p in server_procs):
                    print("[load_test] a server process died", file=sys.stderr)
                    sys.exit(2)
                if time.time() > deadline:
                    for p in server_procs:
                        p.kill()
                    print("[load_test] server never became ready",
                          file=sys.stderr)
                    sys.exit(2)
                time.sleep(0.5)

    if args.open_loop:
        try:
            report = run_open_loop_mode(bases, args)
        except BaseException:
            for p_ in server_procs:
                p_.terminate()
            raise
        report["cpu_count"] = os.cpu_count() or 1
        print(json.dumps(report, indent=2))
        out = args.out or os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "artifacts", "load_test_open_loop.json")
        out_dir = os.path.dirname(out)
        if out_dir:
            os.makedirs(out_dir, exist_ok=True)
        with open(out, "w") as f:
            json.dump(report, f, indent=2)
        print(f"[load_test] open-loop report → {out}", file=sys.stderr)
        for p_ in server_procs:
            p_.terminate()
        sys.exit(1 if report["errors"] else 0)

    try:
        cores = os.cpu_count() or 1
        n_threads = args.threads if args.threads else min(32, 8 * cores)
        if n_threads > 8 * cores:
            print(f"[load_test] WARNING: {n_threads} threads on {cores} "
                  f"core(s): client p95 will be dominated by host queueing",
                  file=sys.stderr)
        report, errors = run_load(bases, n_threads, args.requests)
        if args.batch_size > 0:
            batch_report, batch_errors = run_batch_load(
                bases, args.batch_threads, args.batch_requests,
                args.batch_size)
            report["predict_eta_batch"] = batch_report
            errors.extend(batch_errors)
            vrp_report, vrp_errors = run_vrp_batch_load(
                bases, args.batch_threads, max(4, args.batch_requests // 2))
            report["optimize_route_batch"] = vrp_report
            errors.extend(vrp_errors)
        if args.road_requests > 0:
            # 2 clients: road solves are device-wide (one shortest-path
            # batch each); beyond ~2 in flight the tail measures queue
            # depth, not the solver.
            road_report, road_errors = run_road_route_load(
                bases, min(2, n_threads), args.road_requests)
            report["optimize_route_road"] = road_report
            errors.extend(road_errors)
        report["quantile_band"] = run_quantile_probe(bases)
        report["latency_decomposition"] = run_latency_decomposition(bases)
    except BaseException:
        # Don't leak spawned servers on any failure/abort path.
        for p_ in server_procs:
            p_.terminate()
        raise
    report["cpu_count"] = cores
    # Self-describing measurement regime: every phase above is closed-
    # loop (clients self-throttle to the server's pace), which under-
    # reports tails under overload — the open-loop artifact is the one
    # that binds there (docs/LOADGEN.md).
    report["loop"] = "closed"
    # TPU-backed servers record to their own artifact so the CPU and
    # accelerator evidence never overwrite each other — and the budgets
    # bind at full strength only there (they are production-host SLOs).
    on_tpu = False
    try:
        health = _get(bases[0], "/api/health")
        devs = health.get("checks", {}).get("tpu", {}).get("devices", [])
        on_tpu = any("cpu" not in str(d).lower() for d in devs)
        report["server_devices"] = devs
    except Exception:
        pass
    # Per-endpoint-class p95 budgets (VERDICT r3 #3: every class gets a
    # stated budget and a pass/fail, not just predict_eta). The whole
    # point of warming every bucket at startup is that no customer
    # request ever pays a compile, so tails must stay interactive.
    scale = 1.0 if on_tpu else max(args.cpu_budget_scale, 1.0)
    report["budget_scale"] = scale
    budgets = {
        "predict_eta": args.p95_budget_ms,      # binds unscaled everywhere
        "optimize_route": args.opt_budget_ms * scale,
        "optimize_route_road": args.road_budget_ms * scale,
        "optimize_route_batch": args.vrp_budget_ms * scale,
        "predict_eta_batch": args.eta_batch_budget_ms * scale,
    }
    budget_failures = []
    for section, budget in budgets.items():
        sec = report.get(section)
        if not sec or not budget:
            continue
        p95 = sec.get("p95_ms")
        ok = p95 is not None and p95 <= budget
        sec["p95_budget_ms"] = budget
        sec["within_budget"] = bool(ok)
        if not ok:
            budget_failures.append((section, p95, budget))
    budget_ok = not budget_failures
    # Back-compat keys (round-2/3 artifact consumers); a disabled budget
    # reads as "within", matching the old budget_ok semantics.
    report["p95_budget_ms"] = args.p95_budget_ms
    report["p95_within_budget"] = bool(
        report.get("predict_eta", {}).get("within_budget",
                                          not args.p95_budget_ms))
    preds_s = report.get("predict_eta_batch", {}).get("preds_per_s")
    if preds_s is not None:
        report["north_star_preds_per_s"] = preds_s
        report["north_star_met"] = bool(preds_s >= 10_000)
    print(json.dumps(report, indent=2))
    if errors:
        print(f"first errors: {errors[:5]}", file=sys.stderr)
    for section, p95, budget in budget_failures:
        print(f"FAIL: {section} p95 {p95} ms exceeds budget {budget} ms",
              file=sys.stderr)
    name = "load_test_tpu.json" if on_tpu else "load_test.json"
    out = args.out or os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "artifacts", name)
    out_dir = os.path.dirname(out)
    if out_dir:  # bare filename ⇒ cwd; makedirs("") would raise
        os.makedirs(out_dir, exist_ok=True)
    with open(out, "w") as f:
        json.dump(report, f, indent=2)
    print(f"[load_test] report → {out}", file=sys.stderr)
    for p_ in server_procs:
        p_.terminate()
    sys.exit(1 if errors or not budget_ok else 0)


if __name__ == "__main__":
    main()
