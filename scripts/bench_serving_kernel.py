"""Per-path, per-bucket curves for the compiled scoring artifact.

Three serving paths, head-to-head at every batch bucket the serving
layer actually flushes:

- **xla** — the jit forward (the reference path), device per-iteration
  cost via the same ``lax.fori_loop`` slope method as bench.py (the
  tunnel's ~70 ms round trip would otherwise swamp a sub-ms step);
- **pallas** — the fused kernel (``ops/fused_mlp.py``) with a tile
  sweep per batch; compiled mode needs a TPU (a CPU run measures the
  interpreter and writes an explicitly non-binding selection record);
- **aot** — the per-bucket ``jit().lower().compile()`` serving entry:
  measured as WALL time per single call (dispatch included — the whole
  point of AOT is what the fori_loop slope hides), against the jit
  call's wall time at the same bucket.

Plus fused-vs-unfused quantile-head rows (``quantile_heads`` vs the
scan-form ``quantile_heads_unfused`` epilogue) so the head-fusion claim
has a measured number on every host.

Writes TWO artifacts:
- ``artifacts/serving_kernel.json`` — the full per-path record (this
  bench's own curve, re-recorded at HEAD);
- ``artifacts/kernel_bench.json`` — the serving-selection win table
  (``serve/ml_service.py:_fused_selection`` reads it; only a TPU run
  can enable the kernel).

``--gate`` (the TPU battery) exits nonzero if the Pallas path loses at
any bucket the PREVIOUS record claimed it wins — the "fused ≥ XLA at
its win buckets" regression check.

Usage: python scripts/bench_serving_kernel.py [--quick] [--cpu] [--gate]
       [--batches 8 64 512 1024 2048 4096 32768 131072]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
import warnings

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--batches", type=int, nargs="+",
                        default=[8, 64, 512, 1024, 2048, 4096, 32768,
                                 131072])
    parser.add_argument("--repeats", type=int, default=3)
    parser.add_argument("--tiles", type=int, nargs="+",
                        default=[512, 2048, 8192],
                        help="kernel batch-tile candidates (clamped to the "
                             "row-padded batch, deduped, per batch size)")
    parser.add_argument("--cpu", action="store_true",
                        help="hermetic CPU run (interpreter-mode kernel; "
                             "the selection record will not enable serving)")
    parser.add_argument("--quick", action="store_true",
                        help="small batches + 1 repeat: the CI guardband "
                             "configuration (tests/test_serving_kernel_"
                             "bench.py)")
    parser.add_argument("--no-pallas", action="store_true",
                        help="skip the Pallas rows (interpret mode is "
                             "minutes-slow at large batches on CPU)")
    parser.add_argument("--gate", action="store_true",
                        help="exit 2 if the kernel now loses at a bucket "
                             "the previous record claimed it wins (TPU "
                             "battery regression check)")
    parser.add_argument("--out", default=os.path.join(
        REPO, "artifacts", "serving_kernel.json"))
    args = parser.parse_args()
    if args.quick:
        args.batches = [8, 512, 4096]
        args.repeats = 1
    if args.cpu or os.environ.get("ROUTEST_FORCE_CPU") == "1":
        import jax

        jax.config.update("jax_platforms", "cpu")

    import jax
    import jax.numpy as jnp
    import numpy as np

    from routest_tpu.core.cache import enable_compile_cache
    from routest_tpu.data.features import batch_from_mapping
    from routest_tpu.data.synthetic import generate_dataset
    from routest_tpu.models.eta_mlp import (EtaMLP, quantile_heads,
                                            quantile_heads_unfused)
    from routest_tpu.ops import (fused_eta_forward, pack_eta_params,
                                 resolve_kernel_dtype)
    from routest_tpu.train.checkpoint import default_model_path, load_model

    enable_compile_cache()
    backend = jax.default_backend()
    interpret = backend != "tpu"
    run_pallas = not args.no_pallas

    prior_wins = _prior_win_buckets()

    try:
        model, params = load_model(default_model_path())
    except Exception:
        model = EtaMLP()
        params = model.init(jax.random.PRNGKey(0))
    params = jax.device_put(params)
    n_q = len(getattr(model, "quantiles", ()) or ())
    dtype = resolve_kernel_dtype(model)
    packed = jax.device_put(pack_eta_params(model, params, dtype=dtype))
    forward_xla = (model.apply_quantiles if n_q else model.apply)

    data = generate_dataset(max(args.batches), seed=7)
    x_all = np.asarray(batch_from_mapping(data), np.float32)

    def make_runner(forward, batch):
        @jax.jit
        def run(xx, n_iters):
            def body(_, carry):
                xx, _out = carry
                out = forward(xx)
                eta0 = out[:, 0] if out.ndim == 2 else out
                return xx.at[:, 10].add(eta0 * 1e-12), eta0

            return jax.lax.fori_loop(
                0, n_iters, body, (xx, jnp.zeros((batch,), jnp.float32)))

        return run

    def measure(forward, batch) -> float:
        """Per-iteration seconds via the short/long slope."""
        x = jax.device_put(jnp.asarray(x_all[:batch]))
        run = make_runner(forward, batch)
        # Small batches need long loops for the slope to rise above
        # timer noise; keep total device time ~comparable per size.
        # CPU hosts get ~16× shorter loops: the XLA CPU step is ~ms
        # scale, so TPU-sized loops would cost an hour per curve while
        # adding nothing over the ~2% noise floor the guardbands allow.
        budget = (1 << 22) if backend == "tpu" else (1 << 18)
        n_short = max(8, min(400, budget // max(batch, 1)))
        n_long = 4 * n_short
        if args.quick:
            n_short, n_long = max(4, n_short // 8), max(16, n_long // 8)

        def timed(n):
            t0 = time.perf_counter()
            _, eta = run(x, n)
            np.asarray(eta[:1])
            return time.perf_counter() - t0

        timed(2)
        slopes = []
        for _ in range(args.repeats):
            slopes.append((timed(n_long) - timed(n_short))
                          / (n_long - n_short))
        return max(float(np.median(slopes)), 1e-9)

    def wall_per_call(fn, x, calls=20) -> float:
        """Median wall seconds per single dispatch (python overhead
        INCLUDED — this is the number AOT exists to shrink)."""
        fn(x)  # warm / compile
        samples = []
        for _ in range(max(3, args.repeats)):
            t0 = time.perf_counter()
            for _ in range(calls):
                np.asarray(fn(x))
            samples.append((time.perf_counter() - t0) / calls)
        return float(np.median(samples))

    # ── per-path rows ─────────────────────────────────────────────────
    jit_forward = jax.jit(forward_xla)
    rows = []
    for batch in args.batches:
        row = {"batch": batch}
        xla_s = measure(lambda xx: forward_xla(params, xx), batch)
        row["xla_us"] = round(xla_s * 1e6, 1)
        row["xla_mpreds_s"] = round(batch / xla_s / 1e6, 2)

        # AOT vs jit dispatch at this bucket (wall time per call).
        xb = np.ascontiguousarray(x_all[:batch])
        with warnings.catch_warnings():
            warnings.filterwarnings(
                "ignore", message="Some donated buffers were not usable")
            compiled = jax.jit(forward_xla, donate_argnums=(1,)).lower(
                params, jax.ShapeDtypeStruct((batch, xb.shape[1]),
                                             np.float32)).compile()
        calls = max(3, min(30, (1 << 17) // max(batch, 1)))
        row["jit_call_us"] = round(wall_per_call(
            lambda v: jit_forward(params, v), xb, calls) * 1e6, 1)
        row["aot_call_us"] = round(wall_per_call(
            lambda v: compiled(params, v), xb, calls) * 1e6, 1)
        row["aot_mpreds_s"] = round(
            batch / (row["aot_call_us"] / 1e6) / 1e6, 2)
        row["dispatch_saved_us"] = round(
            row["jit_call_us"] - row["aot_call_us"], 1)

        # Pallas tile sweep (the serving-selection measurement).
        if run_pallas:
            cap = ((batch + 7) // 8) * 8
            tiles = sorted({min(t, cap) for t in args.tiles})
            pal_s, pal_tile, err = None, None, None
            for t in tiles:
                try:
                    s = measure(
                        lambda xx: fused_eta_forward(
                            packed, xx, n_q=n_q, tile=t,
                            interpret=interpret), batch)
                except Exception as e:  # Mosaic failure: record, no crash
                    err = f"{type(e).__name__}: {e}"[:200]
                    continue
                if pal_s is None or s < pal_s:
                    pal_s, pal_tile = s, t
            if pal_s is None:
                row.update({"pallas_us": None, "error": err})
            else:
                row.update({
                    "pallas_us": round(pal_s * 1e6, 1),
                    "pallas_mpreds_s": round(batch / pal_s / 1e6, 2),
                    "pallas_tile": pal_tile,
                    "winner": "pallas" if pal_s < xla_s else "xla",
                    "speedup": round(xla_s / pal_s, 2),
                })
        rows.append(row)
        print("  batch {:>7,}: xla {:>8} us ({} Mpreds/s) | aot call "
              "{:>8} us (jit {} us) | pallas {}".format(
                  batch, row["xla_us"], row["xla_mpreds_s"],
                  row["aot_call_us"], row["jit_call_us"],
                  row.get("pallas_us", "skipped")), flush=True)

    # ── fused vs unfused quantile heads (any host) ────────────────────
    heads = None
    if n_q:
        def fwd_with(epilogue):
            def f(xx):
                out, dist = model._trunk(params, xx)
                return epilogue(out, dist, n_q)
            return f

        hb = min(16384, max(args.batches))
        fused_s = measure(fwd_with(quantile_heads), hb)
        unfused_s = measure(fwd_with(quantile_heads_unfused), hb)
        heads = {
            "batch": hb,
            "quantiles": n_q,
            "fused_us": round(fused_s * 1e6, 1),
            "unfused_us": round(unfused_s * 1e6, 1),
            "fused_mpreds_s": round(hb / fused_s / 1e6, 2),
            "unfused_mpreds_s": round(hb / unfused_s / 1e6, 2),
            "fused_over_unfused": round(unfused_s / fused_s, 3),
        }
        print(f"  quantile heads @ {hb:,}: fused {heads['fused_us']} us "
              f"vs unfused {heads['unfused_us']} us "
              f"({heads['fused_over_unfused']}x)", flush=True)

    # ── selection win table (same contract as before) ─────────────────
    win_max = 0
    for row in sorted(rows, key=lambda r: r["batch"]):
        if row.get("winner") == "pallas":
            win_max = row["batch"]
        else:
            break
    record = {
        "backend": backend,
        "interpret_mode": interpret,
        "quantiles": n_q,
        "kernel_dtype": dtype,
        "quick": bool(args.quick),
        "cpu_count": os.cpu_count(),
        "rows": rows,
        "quantile_heads": heads,
        "pallas_wins_max_bucket": win_max if backend == "tpu" else 0,
        "recorded_unix": int(time.time()),
    }
    if backend != "tpu":
        # Structural caveat, PR-4 style: a CPU record must be
        # self-describing about what it can and cannot bind.
        record["caveat"] = (
            "CPU host: pallas rows are interpreter-mode (non-binding for "
            "serving selection); xla/aot rows measure the XLA CPU "
            "backend on this box, not the TPU production path")
    out_dir = os.path.dirname(args.out)
    if out_dir:
        os.makedirs(out_dir, exist_ok=True)
    with open(args.out, "w") as f:
        json.dump(record, f, indent=2)
    print(f"serving-kernel record → {args.out}")
    if not args.quick:
        selection = {k: record[k] for k in
                     ("backend", "interpret_mode", "quantiles",
                      "kernel_dtype", "rows", "pallas_wins_max_bucket",
                      "recorded_unix")}
        sel_path = os.path.join(REPO, "artifacts", "kernel_bench.json")
        with open(sel_path, "w") as f:
            json.dump(selection, f, indent=2)
        print(f"pallas_wins_max_bucket={record['pallas_wins_max_bucket']}"
              f" → {sel_path}")

    if args.gate and backend == "tpu" and prior_wins:
        fresh = {r["batch"]: r.get("winner") for r in rows}
        regressed = [b for b in prior_wins
                     if fresh.get(b) not in (None, "pallas")]
        if regressed:
            print(f"GATE FAIL: pallas lost at previously-won buckets "
                  f"{regressed}", file=sys.stderr)
            sys.exit(2)
        print("gate ok: fused ≥ XLA at its recorded win buckets")


def _prior_win_buckets():
    """Buckets the existing selection record claims the kernel wins —
    read BEFORE this run overwrites the record."""
    try:
        with open(os.path.join(REPO, "artifacts",
                               "kernel_bench.json")) as f:
            rec = json.load(f)
        if rec.get("backend") != "tpu":
            return []
        return [int(r["batch"]) for r in rec.get("rows", ())
                if isinstance(r, dict) and r.get("winner") == "pallas"]
    except Exception:
        return []


if __name__ == "__main__":
    main()
