"""Per-batch-size head-to-head: XLA forward vs fused Pallas kernel.

VERDICT r3 weak #3/#4: the Pallas kernel lost 2x at the 131k-row bench
batch and had no winning configuration. The serving path's real batch
sizes are the batcher's buckets (8 / 64 / 512 / 4096) — the regime
where ONE fused dispatch can beat XLA's kernel chain on fixed
overheads. This script measures both paths per bucket with the same
device-side ``lax.fori_loop`` slope method as bench.py (the tunnel's
~70 ms round trip would otherwise swamp a sub-millisecond step), writes
``artifacts/kernel_bench.json``, and the serving layer auto-selects the
kernel per batch from that record
(``serve/ml_service.py:_fused_win_bucket``).

Run on the real chip (the kernel needs Mosaic): the artifact records
backend; a CPU run writes an explicitly non-binding record.

Usage: python scripts/bench_serving_kernel.py [--batches 8 64 512 4096 32768 131072]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--batches", type=int, nargs="+",
                        default=[8, 64, 512, 4096, 32768, 131072])
    parser.add_argument("--repeats", type=int, default=3)
    parser.add_argument("--tiles", type=int, nargs="+",
                        default=[512, 2048, 8192],
                        help="kernel batch-tile candidates (clamped to the "
                             "row-padded batch, deduped, per batch size)")
    parser.add_argument("--cpu", action="store_true",
                        help="interpreter-mode CPU run (correctness/dev "
                             "only; the artifact will not enable serving)")
    args = parser.parse_args()
    if args.cpu or os.environ.get("ROUTEST_FORCE_CPU") == "1":
        import jax

        jax.config.update("jax_platforms", "cpu")

    import jax
    import jax.numpy as jnp
    import numpy as np

    from routest_tpu.core.cache import enable_compile_cache
    from routest_tpu.data.features import batch_from_mapping
    from routest_tpu.data.synthetic import generate_dataset
    from routest_tpu.models.eta_mlp import EtaMLP
    from routest_tpu.ops import fused_eta_forward, pack_eta_params
    from routest_tpu.train.checkpoint import default_model_path, load_model

    enable_compile_cache()
    backend = jax.default_backend()
    interpret = backend != "tpu"

    try:
        model, params = load_model(default_model_path())
    except Exception:
        model = EtaMLP()
        params = model.init(jax.random.PRNGKey(0))
    params = jax.device_put(params)
    n_q = len(getattr(model, "quantiles", ()) or ())
    packed = jax.device_put(pack_eta_params(model, params))
    forward_xla = (model.apply_quantiles if n_q else model.apply)

    data = generate_dataset(max(args.batches), seed=7)
    x_all = np.asarray(batch_from_mapping(data), np.float32)

    def make_runner(forward, batch):
        @jax.jit
        def run(xx, n_iters):
            def body(_, carry):
                xx, _out = carry
                out = forward(xx)
                eta0 = out[:, 0] if out.ndim == 2 else out
                return xx.at[:, 10].add(eta0 * 1e-12), eta0

            return jax.lax.fori_loop(
                0, n_iters, body, (xx, jnp.zeros((batch,), jnp.float32)))

        return run

    def measure(forward, batch) -> float:
        """Per-iteration seconds via the short/long slope."""
        x = jax.device_put(jnp.asarray(x_all[:batch]))
        run = make_runner(forward, batch)
        # Small batches need long loops for the slope to rise above
        # timer noise; keep total device time ~comparable per size.
        n_short = max(20, min(400, (1 << 22) // max(batch, 1)))
        n_long = 4 * n_short

        def timed(n):
            t0 = time.perf_counter()
            _, eta = run(x, n)
            np.asarray(eta[:1])
            return time.perf_counter() - t0

        timed(2)
        slopes = []
        for _ in range(args.repeats):
            slopes.append((timed(n_long) - timed(n_short))
                          / (n_long - n_short))
        return max(float(np.median(slopes)), 1e-9)

    rows = []
    for batch in args.batches:
        xla_s = measure(lambda xx: forward_xla(params, xx), batch)
        # Tile sweep: the grid-step count (batch/tile) sets the kernel's
        # fixed overhead while VMEM bounds the tile from above — the
        # best point moves with batch size, so it is measured, not
        # asserted, and serving replays the recorded winner. Candidates
        # collapse to what the kernel would actually run (it clamps the
        # tile to the row-padded batch), so every recorded pallas_tile
        # is a configuration that really executed.
        cap = ((batch + 7) // 8) * 8
        tiles = sorted({min(t, cap) for t in args.tiles})
        pal_s, pal_tile, err = None, None, None
        for t in tiles:
            try:
                s = measure(
                    lambda xx: fused_eta_forward(packed, xx, n_q=n_q,
                                                 tile=t,
                                                 interpret=interpret), batch)
            except Exception as e:  # Mosaic failure: record, don't crash
                err = f"{type(e).__name__}: {e}"[:200]
                continue
            if pal_s is None or s < pal_s:
                pal_s, pal_tile = s, t
        if pal_s is None:
            rows.append({"batch": batch, "xla_us": round(xla_s * 1e6, 1),
                         "pallas_us": None, "error": err})
            continue
        rows.append({
            "batch": batch,
            "xla_us": round(xla_s * 1e6, 1),
            "pallas_us": round(pal_s * 1e6, 1),
            "pallas_tile": pal_tile,
            "winner": "pallas" if pal_s < xla_s else "xla",
            "speedup": round(xla_s / pal_s, 2),
        })
        print(f"  batch {batch:>7,}: xla {rows[-1]['xla_us']:>9} us | "
              f"pallas {rows[-1]['pallas_us']:>9} us (tile {pal_tile}) | "
              f"{rows[-1]['winner']} ({rows[-1]['speedup']}x)", flush=True)

    # The largest batch the kernel wins at, provided it wins every size
    # below it too (serving dispatches by "batch <= threshold": a
    # non-contiguous win region must not enable the kernel for sizes
    # where it loses). A row where every tile FAILED breaks the chain
    # the same as a loss — serving must never route a shape through a
    # kernel that could not compile at that shape.
    win_max = 0
    for row in sorted(rows, key=lambda r: r["batch"]):
        if row.get("winner") == "pallas":
            win_max = row["batch"]
        else:
            break
    record = {
        "backend": backend,
        "interpret_mode": interpret,
        "quantiles": n_q,
        "rows": rows,
        "pallas_wins_max_bucket": win_max if backend == "tpu" else 0,
        "recorded_unix": int(time.time()),
    }
    out = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "artifacts", "kernel_bench.json")
    with open(out, "w") as f:
        json.dump(record, f, indent=2)
    print(f"pallas_wins_max_bucket={record['pallas_wins_max_bucket']} → {out}")


if __name__ == "__main__":
    main()
