"""Goodput ledger + efficiency watchdog end to end → artifacts/efficiency.json.

The ISSUE-17 acceptance scenario: real 2-replica fleets (supervisor +
workers + in-process gateway, live traffic where the scenario needs
metric flips) under open-loop load, with the per-replica efficiency
watchdog pinned to the committed battery curves. Two injected
efficiency regressions — each invisible to latency SLOs at this load,
because every request still answers a healthy 200 —

- ``device_slowdown``  — one replica rolled onto
  ``device.compute:latency`` chaos (the device computes 400 ms slower
  per launch; goodput craters while answers stay right);
- ``padding_blowup``   — one replica rolled onto a pathological
  single-bucket config (``RTPU_BATCH_BUCKETS=4096``: every 8-row
  launch pays a 4096-wide batch — designed-in padding waste past the
  threshold)

must each be detected by the watchdog, page the dedicated efficiency
SLO within a bounded window, and produce a flight-recorder bundle
naming the program, replica, and bucket and embedding the
expected-vs-measured curve. The ``clean`` scenario proves the other
half: across ≥1 legitimate metric flip and ≥1 verified model swap the
fleet raises ZERO efficiency pages, every replica's watchdog stays
armed on the backend-matched pin, the new families are visible in the
timeline, and the gateway's fleet rollup counts the goodput. The
``overhead`` scenario isolates the always-on ledger's cost
(``RTPU_EFF=0`` vs on, everything else off) inside the existing ≤5%
p95 observability budget.

Caches are shared across scenarios AND battery rounds via
``--cache-dir`` (default ``artifacts/bench_cache/efficiency``).

Usage: python scripts/bench_efficiency.py [--quick]
       [--out artifacts/efficiency.json] [--cache-dir DIR]
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import socket
import sys
import tempfile
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.join(REPO, "scripts"))

import bench_probing as bp  # noqa: E402  (the shared fleet harness)

DETECT_BOUND_S = 120.0
# Efficiency knobs for the fleet under test: second-scale ticks and
# windows so sustained regressions page inside the bench's bound, and
# bench-calibrated thresholds — the measured clean/faulty separation
# is ~70× on ratio (clean ≥0.2 vs faulty ~0.001) and ~0.2 absolute on
# waste (clean ≤0.8 under merge, blowup ≥0.99).
EFF_ENV = {
    "RTPU_EFF": "1",
    "RTPU_EFF_WATCHDOG": "1",
    "RTPU_EFF_TICK_S": "1.0",
    "RTPU_EFF_WINDOW_S": "15",
    "RTPU_EFF_MIN_ROWS": "64",
    "RTPU_EFF_AFTER": "3",
    "RTPU_EFF_MIN_RATIO": "0.02",
    "RTPU_EFF_MAX_WASTE": "0.9",
    "RTPU_EFF_FAST_S": "10",
    "RTPU_EFF_SLOW_S": "30",
}
BATCH_ROWS = 8           # full bucket-8 launches: clean waste ≈ 0
OVERHEAD_PCT = 5.0
OVERHEAD_FLOOR_MS = 2.0


def open_loop_batch(base: str, rate: float, duration_s: float,
                    stop=None, salt: int = 0):
    """Open-loop predict_eta_batch load, every row unique (cache-miss
    by construction — cached rows are goodput the device never pays
    for, and this bench measures the device)."""
    from routest_tpu.loadgen.arrivals import RateCurve, paced_schedule
    from routest_tpu.loadgen.engine import run_open_loop
    from routest_tpu.loadgen.workload import PlannedRequest

    offsets = paced_schedule(RateCurve.constant(rate), duration_s)
    requests = [PlannedRequest(
        method="POST", path="/api/predict_eta_batch",
        body={"items": [
            {"summary": {"distance": 3000 + salt + i * BATCH_ROWS + j},
             "weather": "Sunny", "traffic": "Medium", "driver_age": 33,
             "pickup_time": "2026-08-05T18:00:00"}
            for j in range(BATCH_ROWS)]},
        route="predict_eta_batch") for i in range(len(offsets))]
    return run_open_loop([base], offsets, requests, workers=8,
                         timeout=30.0, stop=stop)


def replica_efficiency(port: int) -> dict:
    return bp._fetch(f"http://127.0.0.1:{port}/api/efficiency",
                     timeout=30)


def wait_for_efficiency_page(port: int, bound_s: float) -> dict:
    """Poll one replica's watchdog until the efficiency SLO pages
    (each poll of an armed watchdog also runs a comparison tick)."""
    t0 = time.monotonic()
    while time.monotonic() - t0 < bound_s:
        try:
            wd = replica_efficiency(port).get("watchdog") or {}
        except OSError:
            wd = {}
        if (wd.get("pages") or 0) >= 1:
            return {"paged": True,
                    "detect_s": round(time.monotonic() - t0, 2),
                    "verdicts": wd.get("verdicts"),
                    "last_bundle": wd.get("last_bundle")}
        time.sleep(1.0)
    return {"paged": False, "detect_s": None}


def efficiency_bundles(workers_dir: str):
    """Replica-side flight-recorder bundles for efficiency pages."""
    out = []
    if not os.path.isdir(workers_dir):
        return out
    for name in sorted(os.listdir(workers_dir)):
        if "efficiency" not in name:
            continue
        bundle = os.path.join(workers_dir, name)
        try:
            evidence = json.load(open(
                os.path.join(bundle, "efficiency_evidence.json")))
            manifest = json.load(open(
                os.path.join(bundle, "manifest.json")))
        except (OSError, ValueError):
            continue
        out.append({"name": name, "evidence": evidence,
                    "manifest_reason": manifest.get("reason")})
    return out


def judge_efficiency_bundle(bundles, faulty_label: str,
                            check_prefix: str) -> dict:
    """An efficiency bundle must name the program, replica, and bucket
    and embed the expected-vs-measured curve with the offending bucket
    measured live."""
    for b in bundles:
        ev = b["evidence"]
        if ev.get("replica") != faulty_label:
            continue
        if not str(ev.get("check", "")).startswith(check_prefix):
            continue
        curve = ev.get("expected_vs_measured") or []
        by_bucket = {row.get("bucket"): row for row in curve}
        offending = by_bucket.get(ev.get("bucket"))
        named = (ev.get("program") in ("eta_score", "route_solve",
                                       "dispatch_solve", "dispatch_reopt")
                 and ev.get("bucket") is not None)
        embedded = (bool(curve)
                    and all(r.get("expected_rows_per_s") for r in curve)
                    and offending is not None
                    and offending.get("measured_rows_per_s") is not None)
        if named and embedded:
            return {"ok": True, "bundle": b["name"],
                    "program": ev["program"], "bucket": ev["bucket"],
                    "check": ev["check"],
                    "curve_points": len(curve),
                    "offending_bucket": offending}
    return {"ok": False,
            "bundles_seen": [b["name"] for b in bundles]}


def _timeline_has_efficiency(base: str) -> bool:
    try:
        tl = bp._fetch(f"{base}/api/timeline?family=rtpu_efficiency",
                       timeout=30)
    except OSError:
        return False
    return "rtpu_efficiency_rows_total" in json.dumps(tl)


def fleet_ports(fleet) -> list:
    return list(fleet.ports)


def workers_dir(fleet) -> str:
    return fleet.env["RTPU_RECORDER_DIR"]


# ── scenarios ────────────────────────────────────────────────────────


def scenario_clean(extract, cache_dir, rate, quick) -> dict:
    """Live fleet, ≥1 verified model swap + ≥1 metric flip under load:
    zero efficiency pages, watchdogs armed throughout, families in the
    timeline on both tiers, gateway rollup counting the goodput."""
    work = tempfile.mkdtemp(prefix="efficiency-clean-")
    out: dict = {"scenario": "clean"}
    fleet = bp.Fleet(live=True, extract=extract, cache_dir=cache_dir,
                     work_dir=work)
    load_stop = threading.Event()
    try:
        fleet.start_probe_drivers()

        def _load():
            salt = 0
            while not load_stop.is_set():
                try:
                    open_loop_batch(fleet.base, rate, 10.0,
                                    stop=load_stop, salt=salt)
                except Exception:
                    pass
                salt += 1_000_000

        load_thread = threading.Thread(target=_load, daemon=True)
        load_thread.start()

        # Verified model swap mid-run (within-gate perturbation; both
        # replicas' reload watchers land it through the golden gate).
        import jax

        from routest_tpu.train.checkpoint import load_model, save_model

        model, params = load_model(fleet.model_path)
        close = jax.tree_util.tree_map(lambda x: x * (1.0 + 1e-4),
                                       params)
        save_model(fleet.model_path, model, close)
        st = os.stat(fleet.model_path)
        os.utime(fleet.model_path,
                 ns=(st.st_atime_ns, st.st_mtime_ns + 1_000_000))

        def swaps_accepted() -> int:
            total = 0
            for port in fleet_ports(fleet):
                reg = bp._fetch(f"http://127.0.0.1:{port}/api/metrics",
                                timeout=30).get("registry", {})
                for s in reg.get("rtpu_model_swaps_total",
                                 {}).get("series", ()):
                    if s.get("labels", {}).get("result") == "accepted":
                        total += int(s.get("value", 0))
            return total

        def fleet_epoch() -> int:
            return max(bp._fetch(f"http://127.0.0.1:{p}/api/live",
                                 timeout=30).get("epoch", 0)
                       for p in fleet_ports(fleet))

        epoch0 = fleet_epoch()
        deadline = time.time() + (90 if quick else 180)
        flips = 0
        while time.time() < deadline:
            flips = fleet_epoch() - epoch0
            if flips >= 1 and swaps_accepted() >= 2:
                break
            time.sleep(1.0)
        out["swaps_accepted"] = swaps_accepted()
        out["metric_flips"] = flips
        # A few more watchdog rounds under steady load post-flip.
        time.sleep(6.0)

        per_replica = {}
        for port in fleet_ports(fleet):
            snap = replica_efficiency(port)
            wd = snap.get("watchdog") or {}
            eta = snap["ledger"]["programs"]["eta_score"]
            per_replica[port] = {
                "armed": wd.get("armed"), "status": wd.get("status"),
                "pages": wd.get("pages"), "verdicts": wd.get("verdicts"),
                "eta_rows": eta["rows"], "eta_calls": eta["calls"],
                "waste_fraction": eta["waste_fraction"],
            }
        out["replicas"] = per_replica
        gw_eff = bp._fetch(f"{fleet.base}/api/efficiency", timeout=30)
        out["fleet_rollup"] = gw_eff.get("fleet")
        out["timeline_replica"] = _timeline_has_efficiency(
            f"http://127.0.0.1:{fleet_ports(fleet)[0]}")
        out["timeline_gateway"] = _timeline_has_efficiency(fleet.base)
        bundles = efficiency_bundles(workers_dir(fleet))
        out["efficiency_bundles"] = [b["name"] for b in bundles]

        checks = {
            "metric_flip_ge_1": flips >= 1,
            "verified_swap_ge_1": out["swaps_accepted"] >= 1,
            "watchdogs_armed_and_pinned": all(
                r["armed"] and r["status"] == "pinned"
                for r in per_replica.values()),
            "ledger_counted_device_rows": all(
                r["eta_rows"] > 0 for r in per_replica.values()),
            "zero_efficiency_pages": (
                all((r["pages"] or 0) == 0 for r in per_replica.values())
                and not bundles),
            "all_verdicts_pass": all(
                v == "pass"
                for r in per_replica.values()
                for v in (r["verdicts"] or {}).values()),
            "fleet_rollup_counts_goodput": (
                (gw_eff.get("fleet", {}).get("programs", {})
                 .get("eta_score", {}).get("rows") or 0) > 0
                and not gw_eff.get("fleet", {}).get("degraded")),
            "timeline_family_visible_both_tiers": bool(
                out["timeline_replica"] and out["timeline_gateway"]),
        }
        out["checks"] = checks
        out["pass"] = all(checks.values())
    finally:
        load_stop.set()
        try:
            load_thread.join(timeout=20)
        except (NameError, RuntimeError):
            pass
        fleet.stop()
        shutil.rmtree(work, ignore_errors=True)
    return out


def scenario_fault(name, extract, cache_dir, rate, quick, *,
                   overlay: dict, check_prefix: str) -> dict:
    """Shared fault harness: boot → healthy baseline → roll one replica
    onto the degrading overlay → efficiency page within bound → bundle
    names program/replica/bucket with the curve embedded."""
    work = tempfile.mkdtemp(prefix=f"efficiency-{name}-")
    out: dict = {"scenario": name}
    fleet = bp.Fleet(live=False, extract=extract, cache_dir=cache_dir,
                     work_dir=work)
    load_stop = threading.Event()
    try:
        def _load():
            salt = 0
            while not load_stop.is_set():
                try:
                    open_loop_batch(fleet.base, rate, 10.0,
                                    stop=load_stop, salt=salt)
                except Exception:
                    pass
                salt += 1_000_000

        load_thread = threading.Thread(target=_load, daemon=True)
        load_thread.start()

        # Healthy baseline: both watchdogs armed, no pages, device rows
        # flowing (the evidence floor is met before the fault lands).
        baseline_deadline = time.time() + (45 if quick else 90)
        while time.time() < baseline_deadline:
            snaps = [replica_efficiency(p) for p in fleet_ports(fleet)]
            if all((s.get("watchdog") or {}).get("armed")
                   and s["ledger"]["programs"]["eta_score"]["rows"] >= 64
                   for s in snaps):
                break
            time.sleep(1.0)
        out["baseline"] = {
            p: {"armed": (s.get("watchdog") or {}).get("armed"),
                "pages": (s.get("watchdog") or {}).get("pages"),
                "eta_rows": s["ledger"]["programs"]["eta_score"]["rows"]}
            for p, s in zip(fleet_ports(fleet), snaps)}

        victim = fleet.replica_rids()[0]
        t_fault = time.time()
        faulty_rid = fleet.inject_replacement(victim, dict(overlay),
                                              version=f"v-{name}")
        faulty_port = fleet.ports[-1]
        faulty_label = f"{socket.gethostname()}:{faulty_port}"
        healthy_ports = [p for p in fleet_ports(fleet)
                         if p != faulty_port]
        out.update({"victim": victim, "faulty_rid": faulty_rid,
                    "faulty_port": faulty_port,
                    "faulty_label": faulty_label,
                    "inject_wall_s": round(time.time() - t_fault, 1)})

        page = wait_for_efficiency_page(faulty_port, DETECT_BOUND_S)
        out["page"] = page
        out["detect_bound_s"] = DETECT_BOUND_S

        # The page lands the bundle synchronously; poll briefly for the
        # directory scan to see it.
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline:
            bundles = efficiency_bundles(workers_dir(fleet))
            out["bundle"] = judge_efficiency_bundle(
                bundles, faulty_label, check_prefix)
            if out["bundle"]["ok"]:
                break
            time.sleep(1.0)

        healthy = {p: (replica_efficiency(p).get("watchdog") or {})
                   for p in healthy_ports}
        out["healthy_pages"] = {p: w.get("pages") for p, w in
                                healthy.items()}
        checks = {
            "detected_and_paged": bool(page["paged"]),
            "within_bound": bool(page["paged"]
                                 and page["detect_s"] <= DETECT_BOUND_S),
            "bundle_names_program_replica_bucket": out["bundle"]["ok"],
            "healthy_replica_zero_pages": all(
                (v or 0) == 0 for v in out["healthy_pages"].values()),
        }
        out["checks"] = checks
        out["pass"] = all(checks.values())
    finally:
        load_stop.set()
        try:
            load_thread.join(timeout=20)
        except (NameError, RuntimeError):
            pass
        fleet.stop()
        shutil.rmtree(work, ignore_errors=True)
    return out


def scenario_overhead(quick) -> dict:
    """The always-on ledger's p95 cost, isolated: everything else off,
    ``RTPU_EFF=0`` vs on (watchdog armed, second-scale ticks) — the
    obs-overhead bench's best-of-both-orders protocol against the same
    ≤5% budget with the same 1-core noise floor."""
    import bench_obs_overhead as bo

    out: dict = {"scenario": "overhead"}
    lt = bo._load_load_test()
    threads = 4 if quick else 8
    requests = 20 if quick else 40
    repeats = 2 if quick else 3
    base_off = {"RTPU_OBS_TRACE": "0", "RTPU_RECORDER": "0",
                "RTPU_SLO": "0", "RTPU_TIMELINE": "0",
                "RTPU_TAIL_SAMPLE": "0"}
    modes = (
        ("ledger_off", dict(base_off, RTPU_EFF="0")),
        ("ledger_on", dict(base_off, RTPU_EFF="1",
                           RTPU_EFF_TICK_S="1.0")),
    )
    results: dict = {}
    for order in (modes, tuple(reversed(modes))):
        for mode, env in order:
            r = bo.run_mode(lt, env, threads, requests,
                            batch_size=512, repeats=repeats)
            prev = results.get(mode)
            if prev is not None and \
                    (prev["predict_eta"].get("p95_ms") or 1e9) < \
                    (r["predict_eta"].get("p95_ms") or 1e9):
                r["predict_eta"] = prev["predict_eta"]
            results[mode] = r
    p_off = results["ledger_off"]["predict_eta"].get("p95_ms")
    p_on = results["ledger_on"]["predict_eta"].get("p95_ms")
    overhead_pct = (p_on - p_off) / p_off * 100.0
    ok = (overhead_pct <= OVERHEAD_PCT
          or p_on - p_off <= OVERHEAD_FLOOR_MS)
    out.update({
        "p95_off_ms": p_off, "p95_on_ms": p_on,
        "p95_overhead_pct": round(overhead_pct, 2),
        "budget_pct": OVERHEAD_PCT,
        "noise_floor_ms": OVERHEAD_FLOOR_MS,
        "modes": {m: r.get("predict_eta") for m, r in results.items()},
    })
    out["checks"] = {"ledger_within_p95_budget": bool(ok)}
    out["pass"] = bool(ok)
    return out


def main() -> None:
    parser = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    parser.add_argument("--quick", action="store_true",
                        help="smaller extract + shorter phases (CI)")
    parser.add_argument("--nodes", type=int, default=6000)
    parser.add_argument("--rate", type=float, default=4.0,
                        help="predict_eta_batch requests/s "
                             f"(×{BATCH_ROWS} rows each)")
    parser.add_argument("--cache-dir", default=os.path.join(
        REPO, "artifacts", "bench_cache", "efficiency"))
    parser.add_argument("--out", default=os.path.join(
        REPO, "artifacts", "efficiency.json"))
    parser.add_argument("--scenario", default=None,
                        help="run one scenario (debug)")
    args = parser.parse_args()
    if args.quick:
        args.nodes = min(args.nodes, 4000)

    os.environ.setdefault("ROUTEST_FORCE_CPU", "1")
    import jax

    jax.config.update("jax_platforms", "cpu")
    os.makedirs(args.cache_dir, exist_ok=True)
    os.environ["ROUTEST_HIER_CACHE"] = os.path.join(args.cache_dir,
                                                    "hier")
    from routest_tpu.core.cache import enable_compile_cache

    enable_compile_cache(os.path.join(args.cache_dir, "xla"))
    # The fleet inherits the bench's environment: the efficiency knobs
    # reach every replica (and their rollout successors) verbatim.
    os.environ.update(EFF_ENV)

    t0 = time.time()
    print(f"[1/6] extract + overlay cache ({args.nodes:,} nodes)…",
          flush=True)
    extract = bp.build_extract(args.nodes, args.cache_dir)

    scenarios: dict = {}
    plan = [
        ("clean", lambda: scenario_clean(
            extract, args.cache_dir, args.rate, args.quick)),
        ("device_slowdown", lambda: scenario_fault(
            "device_slowdown", extract, args.cache_dir, args.rate,
            args.quick,
            overlay={"RTPU_CHAOS_SPEC": "device.compute:latency=1.0/400",
                     "RTPU_CHAOS_SEED": "7"},
            check_prefix="throughput")),
        ("padding_blowup", lambda: scenario_fault(
            "padding_blowup", extract, args.cache_dir, args.rate,
            args.quick,
            overlay={"RTPU_BATCH_BUCKETS": "4096"},
            check_prefix="padding")),
        ("overhead", lambda: scenario_overhead(args.quick)),
    ]
    for i, (name, run) in enumerate(plan):
        if args.scenario and name != args.scenario:
            continue
        print(f"[{i + 2}/6] scenario {name}…", flush=True)
        t = time.perf_counter()
        try:
            scenarios[name] = run()
        except Exception as e:
            scenarios[name] = {"scenario": name, "pass": False,
                               "error": f"{type(e).__name__}: {e}"}
        scenarios[name]["wall_s"] = round(time.perf_counter() - t, 1)
        print(f"  {name}: "
              f"{'PASS' if scenarios[name].get('pass') else 'FAIL'} "
              f"({scenarios[name]['wall_s']}s)", flush=True)

    try:
        n_cpus = len(os.sched_getaffinity(0))
    except AttributeError:
        n_cpus = os.cpu_count() or 1
    backend = jax.devices()[0].platform
    record = {
        "generated_unix": int(t0),
        "host": {"cpus": n_cpus, "platform": sys.platform,
                 "backend": backend},
        "host_caveat": (
            f"cpu-backend record on {n_cpus} core(s): detection "
            "latencies and p95s are time-shared-host numbers; judge "
            "the structural checks (paged within bound, bundle names "
            "program/replica/bucket with the curve, clean run green, "
            "ledger within budget), not wall-ms"
            if backend != "tpu" else None),
        "skipped": ("tpu probe: CPU fallback rows — re-record when a "
                    "tunnel appears (scripts/run_tpu_battery.sh does "
                    "it automatically)" if backend != "tpu" else None),
        "config": {
            "nodes": args.nodes, "rate_rps": args.rate,
            "batch_rows": BATCH_ROWS,
            "detect_bound_s": DETECT_BOUND_S,
            "eff_env": EFF_ENV,
            "overhead_budget_pct": OVERHEAD_PCT,
            "overhead_noise_floor_ms": OVERHEAD_FLOOR_MS,
            "cache_dir": args.cache_dir,
            "quick": bool(args.quick),
        },
        "scenarios": scenarios,
    }
    if args.scenario:
        record["partial"] = f"--scenario {args.scenario} (debug run)"
    record["checks"] = {name: bool(s.get("pass"))
                        for name, s in scenarios.items()}
    record["all_pass"] = (bool(record["checks"])
                          and all(record["checks"].values())
                          and (args.scenario is not None
                               or len(scenarios) == 4))
    record["wall_s"] = round(time.time() - t0, 1)
    os.makedirs(os.path.dirname(args.out), exist_ok=True)
    with open(args.out, "w") as f:
        json.dump(record, f, indent=2)
    print(f"\n[6/6] checks: "
          + " ".join(f"{k}={'PASS' if v else 'FAIL'}"
                     for k, v in record["checks"].items())
          + f"\n→ {args.out} (all_pass={record['all_pass']}, "
            f"{record['wall_s']}s)", flush=True)
    # _exit, not sys.exit: loadgen daemon threads racing interpreter
    # teardown must not turn a written verdict into a crash.
    os._exit(0 if record["all_pass"] else 1)


if __name__ == "__main__":
    main()
