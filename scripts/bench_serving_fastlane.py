"""Serving fast-lane bench: closed-loop load with the fast lane off/on.

The measurement of record for ISSUE 4's acceptance criteria. Boots ONE
real replica (the full WSGI app over a threaded werkzeug server) with
the fleet gateway in front — the exact production path client →
gateway → WSGI → fastlane → batcher → device — and drives a closed
loop of single-row ``/api/predict_eta`` requests through it in four
configurations:

  {fast lane OFF, fast lane ON} × {repeated-OD-pair, all-unique}

OFF is the PR-3 serving path exactly: no prediction cache, no
singleflight, fixed 2 ms flush window. ON adds the content-addressed
cache + singleflight (``serve/fastlane.py``) and the adaptive flush
window. The repeated workload draws every request from a small pool of
OD pairs (a dispatch dashboard refreshing the same routes — the
Clipper-motivating distribution); the all-unique workload never repeats
a feature row, so the cache can only add overhead — it is the
no-regression guard.

Per mode: client-side p50/p95 latency and preds/s, plus server-side
registry deltas (cache hit rate, coalesced rows, batcher fill ratio,
zero-copy flushes). Writes ``artifacts/serving_fastlane.json`` with
pass/fail against the acceptance gates (≥20% p95 cut OR ≥1.3×
throughput on repeated; no p95 regression beyond the guardband on
unique).

Usage: python scripts/bench_serving_fastlane.py [--quick]
       [--threads 4] [--seconds 4.0] [--pool 32]
       [--out artifacts/serving_fastlane.json]
"""

from __future__ import annotations

import argparse
import json
import os
import socket
import sys
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

# Hermetic + fast: the bench must measure the serving path, not a TPU
# tunnel's round trips — and it must run identically in CI.
os.environ.setdefault("JAX_PLATFORMS", "cpu")

# The acceptance gates (ISSUE 4): EITHER of the repeated-workload gates
# must pass; the unique workload must stay inside the guardband.
P95_CUT_GATE = 0.20          # ≥20% p95 reduction, fast lane on vs off
THROUGHPUT_GATE = 1.30       # or ≥1.3× preds/s
UNIQUE_GUARDBAND = 1.15      # unique workload: p95_on ≤ 1.15 × p95_off


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _percentile(samples, p):
    if not samples:
        return None
    xs = sorted(samples)
    idx = min(len(xs) - 1, max(0, int(round(p * (len(xs) - 1)))))
    return xs[idx]


def _registry_totals():
    """Cumulative counters/histogram sums we diff around each run (the
    registry is process-wide; deltas isolate one mode's traffic)."""
    from routest_tpu.obs import get_registry

    snap = get_registry().snapshot()

    def total(name, field="value"):
        fam = snap.get(name)
        if not fam:
            return 0.0
        return sum(s.get(field, 0.0) or 0.0 for s in fam["series"])

    return {
        "hits": total("rtpu_cache_hits_total"),
        "misses": total("rtpu_cache_misses_total"),
        "coalesced": total("rtpu_cache_coalesced_total"),
        "rows": total("rtpu_batcher_rows_total"),
        "flushes": total("rtpu_batcher_flushes_total"),
        "zero_copy": total("rtpu_batcher_zero_copy_flushes_total"),
        "fill_sum": total("rtpu_batcher_fill_ratio", "sum"),
        "fill_count": total("rtpu_batcher_fill_ratio", "count"),
    }


def _make_stack(fastlane_on: bool, model_path: str):
    """One replica + gateway, fast lane configured per mode. Returns
    (gateway_base, shutdown_fn)."""
    import logging

    from werkzeug.serving import make_server

    from routest_tpu.core.config import Config, FleetConfig, ServeConfig

    # Per-request access-log lines are stderr writes on the hot path —
    # measurement pollution, not signal.
    logging.getLogger("werkzeug").setLevel(logging.ERROR)
    from routest_tpu.serve.app import create_app
    from routest_tpu.serve.fleet.gateway import Gateway
    from routest_tpu.serve.ml_service import EtaService

    serve_cfg = ServeConfig(
        fastlane_cache=fastlane_on,
        fastlane_singleflight=fastlane_on,
        adaptive_wait=fastlane_on,
    )
    eta = EtaService(serve_cfg, model_path=model_path)
    assert eta.available, eta.load_error
    app = create_app(Config(serve=serve_cfg), eta_service=eta)
    rep_port = _free_port()
    server = make_server("127.0.0.1", rep_port, app, threaded=True)
    rep_thread = threading.Thread(target=server.serve_forever, daemon=True)
    rep_thread.start()

    gw = Gateway([("127.0.0.1", rep_port)],
                 FleetConfig(max_inflight=128, queue_depth=256, hedge=False))
    gw_port = _free_port()
    httpd = gw.serve("127.0.0.1", gw_port)

    def shutdown():
        httpd.shutdown()
        httpd.server_close()
        server.shutdown()
        server.server_close()

    return f"http://127.0.0.1:{gw_port}", shutdown


def _payloads(workload: str, pool: int):
    """Request-body factory. ``repeated``: a fixed pool of OD pairs (the
    pickup_time is pinned so the encoded feature row is bit-identical
    per pool entry). ``unique``: a per-call novel distance, so no two
    feature rows ever match."""
    base_time = "2026-08-04T08:30:00"
    weathers = ("Sunny", "Rainy", "Cloudy")
    traffics = ("Low", "Medium", "High")
    if workload == "repeated":
        bodies = [json.dumps({
            "summary": {"distance": 2000.0 + 137.0 * i},
            "weather": weathers[i % 3], "traffic": traffics[(i // 3) % 3],
            "driver_age": 25 + (i % 20), "pickup_time": base_time,
        }).encode() for i in range(pool)]

        def make(thread_id: int, i: int) -> bytes:
            return bodies[(thread_id * 7919 + i) % pool]

        return make

    def make_unique(thread_id: int, i: int) -> bytes:
        return json.dumps({
            "summary": {"distance": 1000.0 + thread_id * 1e6 + i * 0.25},
            "weather": weathers[i % 3], "traffic": traffics[i % 3],
            "driver_age": 25 + (i % 20), "pickup_time": base_time,
        }).encode()

    return make_unique


def _drive(base: str, workload: str, pool: int, threads: int,
           seconds: float) -> dict:
    """Closed loop: each thread posts back-to-back until the clock runs
    out. Persistent keep-alive connections (the client cost must not
    mask the server-side win)."""
    import http.client
    from urllib.parse import urlsplit

    host, port = urlsplit(base).hostname, urlsplit(base).port
    make = _payloads(workload, pool)
    latencies = [[] for _ in range(threads)]
    errors = [0] * threads
    stop_at = [0.0]
    barrier = threading.Barrier(threads + 1)

    def worker(t: int) -> None:
        conn = http.client.HTTPConnection(host, port, timeout=30)
        barrier.wait()
        i = 0
        while time.monotonic() < stop_at[0]:
            body = make(t, i)
            t0 = time.perf_counter()
            try:
                conn.request("POST", "/api/predict_eta", body=body,
                             headers={"Content-Type": "application/json"})
                resp = conn.getresponse()
                resp.read()
                ok = resp.status == 200
            except (http.client.HTTPException, OSError):
                conn.close()
                conn = http.client.HTTPConnection(host, port, timeout=30)
                ok = False
            if ok:
                latencies[t].append(time.perf_counter() - t0)
            else:
                errors[t] += 1
            i += 1
        conn.close()

    ths = [threading.Thread(target=worker, args=(t,)) for t in range(threads)]
    for th in ths:
        th.start()
    # Warmup outside the window: first requests pay route/bucket JIT.
    warm = _payloads(workload, pool)
    import urllib.request

    for i in range(8):
        req = urllib.request.Request(base + "/api/predict_eta",
                                     data=warm(99, i),
                                     headers={"Content-Type":
                                              "application/json"})
        try:
            urllib.request.urlopen(req, timeout=30).read()
        except OSError:
            pass
    before = _registry_totals()
    t_start = time.monotonic()
    stop_at[0] = t_start + seconds
    barrier.wait()
    for th in ths:
        th.join(timeout=seconds + 60)
    wall = time.monotonic() - t_start
    after = _registry_totals()
    lat = [x for per in latencies for x in per]
    delta = {k: after[k] - before[k] for k in after}
    lookups = delta["hits"] + delta["misses"] + delta["coalesced"]
    return {
        "requests": len(lat),
        "errors": sum(errors),
        "wall_s": round(wall, 3),
        "preds_per_sec": round(len(lat) / wall, 1),
        "p50_ms": round(1000 * _percentile(lat, 0.50), 3) if lat else None,
        "p95_ms": round(1000 * _percentile(lat, 0.95), 3) if lat else None,
        "p99_ms": round(1000 * _percentile(lat, 0.99), 3) if lat else None,
        "cache_hit_rate": round(delta["hits"] / lookups, 4) if lookups
        else None,
        "coalesced_rows": int(delta["coalesced"]),
        "device_rows": int(delta["rows"]),
        "device_flushes": int(delta["flushes"]),
        "zero_copy_flushes": int(delta["zero_copy"]),
        "fill_ratio_mean": round(delta["fill_sum"] / delta["fill_count"], 4)
        if delta["fill_count"] else None,
    }


def run(args) -> dict:
    import tempfile

    import jax

    from routest_tpu.core.dtypes import F32_POLICY
    from routest_tpu.models.eta_mlp import EtaMLP
    from routest_tpu.train.checkpoint import default_model_path, save_model

    model_path = default_model_path()
    tmp = None
    if not os.path.exists(model_path):
        # No trained artifact (fresh checkout/CI): a randomly
        # initialized trunk times identically — the bench measures the
        # serving path, not the weights.
        tmp = tempfile.mkdtemp(prefix="fastlane_bench_")
        model_path = os.path.join(tmp, "m.msgpack")
        model = EtaMLP(policy=F32_POLICY)
        save_model(model_path, model, model.init(jax.random.PRNGKey(0)))

    out: dict = {
        "bench": "serving_fastlane",
        "quick": bool(args.quick),
        "threads": args.threads,
        "seconds": args.seconds,
        "pool": args.pool,
        "topology": "client -> gateway -> replica (1 replica, in-process)",
        "host": {"cpu_count": os.cpu_count(),
                 "backend": "cpu"},
        "workloads": {},
    }
    for workload in ("repeated", "unique"):
        modes = {}
        for label, fastlane_on in (("off", False), ("on", True)):
            base, shutdown = _make_stack(fastlane_on, model_path)
            try:
                modes[label] = _drive(base, workload, args.pool,
                                      args.threads, args.seconds)
            finally:
                shutdown()
            print(f"fastlane bench: {workload}/{label}: {modes[label]}",
                  file=sys.stderr)
        off, on = modes["off"], modes["on"]
        summary = {
            "p95_cut": round(1.0 - on["p95_ms"] / off["p95_ms"], 4)
            if off["p95_ms"] else None,
            "throughput_ratio": round(
                on["preds_per_sec"] / off["preds_per_sec"], 4)
            if off["preds_per_sec"] else None,
        }
        if workload == "repeated":
            summary["pass"] = bool(
                (summary["p95_cut"] or 0) >= P95_CUT_GATE
                or (summary["throughput_ratio"] or 0) >= THROUGHPUT_GATE)
            summary["gate"] = (f"p95_cut>={P95_CUT_GATE} or "
                               f"throughput_ratio>={THROUGHPUT_GATE}")
        else:
            summary["pass"] = bool(
                on["p95_ms"] is not None and off["p95_ms"] is not None
                and on["p95_ms"] <= off["p95_ms"] * UNIQUE_GUARDBAND)
            summary["gate"] = f"p95_on <= {UNIQUE_GUARDBAND} * p95_off"
        out["workloads"][workload] = {"off": off, "on": on,
                                      "summary": summary}
    out["pass"] = all(w["summary"]["pass"] for w in out["workloads"].values())
    out["recorded_unix"] = int(time.time())
    return out


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--quick", action="store_true",
                    help="short windows for CI (the slow-marked "
                         "regression test uses this)")
    # Default 2: the win under test is latency-mode + cache on the
    # request path, which saturation queueing hides — on an N-core host
    # keep the closed loop just below the serving stack's capacity.
    ap.add_argument("--threads", type=int,
                    default=max(2, min(4, (os.cpu_count() or 1))))
    ap.add_argument("--seconds", type=float, default=4.0)
    ap.add_argument("--pool", type=int, default=32,
                    help="distinct OD pairs in the repeated workload")
    ap.add_argument("--out", default=os.path.join(REPO, "artifacts",
                                                  "serving_fastlane.json"))
    args = ap.parse_args()
    if args.quick:
        args.seconds = min(args.seconds, 1.5)
        args.threads = min(args.threads, 2)
    rec = run(args)
    os.makedirs(os.path.dirname(args.out), exist_ok=True)
    with open(args.out, "w") as f:
        json.dump(rec, f, indent=2)
        f.write("\n")
    print(json.dumps({k: rec[k] for k in ("bench", "pass")}
                     | {w: rec["workloads"][w]["summary"]
                        for w in rec["workloads"]}))


if __name__ == "__main__":
    main()
