#!/bin/bash
# TPU evidence battery: wait for the axon tunnel, then produce every
# real-chip artifact in one long-lived session (rapid client churn
# wedges the relay — see .claude/skills/verify/SKILL.md; that is also
# why the probe interval below is 20 min: each probe is itself churn
# and probing faster can PROLONG a wedge).
#
# Round-4 context: the tunnel was down for the entire round (backend
# init hung ~50 min then UNAVAILABLE; 26 probes over ~7 h all timed
# out), so the repo carries CPU fallback artifacts plus this script to
# regenerate the TPU records the moment the environment recovers:
#   artifacts/router_scale.json   (250k-row overlay solve, oracle-verified)
#   artifacts/kernel_bench.json   (per-batch XLA vs Pallas -> serving auto-select)
#   artifacts/serving_kernel.json (per-path xla/pallas/aot Mpreds/s curves)
#   artifacts/load_test_tpu.json  (5 endpoint-class budgets + decomposition)
#   artifacts/bench_tpu.json      (throughput + roofline record)
#
# Usage: scripts/run_tpu_battery.sh [max_probes] [probe_interval_s]
set -u
cd "$(dirname "$0")/.."
export PYTHONPATH="$PWD:/root/.axon_site"
MAX_PROBES="${1:-14}"
INTERVAL="${2:-1200}"
for i in $(seq 1 "$MAX_PROBES"); do
  out=$(ROUTEST_BENCH_PROBE=1 timeout 45 python bench.py 2>/dev/null)
  if echo "$out" | grep -q '"probe": "ok"' \
     && echo "$out" | grep -q '"backend": "tpu"'; then
    echo "tunnel alive after $i probe(s): $out"
    break
  fi
  echo "probe $i/$MAX_PROBES: tunnel down ($(date -u +%H:%M))"
  [ "$i" = "$MAX_PROBES" ] && { echo "giving up"; exit 3; }
  sleep "$INTERVAL"
done

# One step at a time, one TPU client at a time (load_test uses a single
# worker here for that reason; its SIGTERM handler tears its server
# down if the timeout fires). Failures don't stop later steps, but the
# battery reports them and exits nonzero so stale artifacts are never
# mistaken for fresh real-chip evidence.
failed=""
run_step() {
  local name="$1"; shift
  echo "=== $name ==="
  local rc=0
  "$@" || rc=$?
  if [ "$rc" -ne 0 ]; then
    echo "=== $name FAILED (rc=$rc) ==="
    failed="$failed $name"
  fi
}
# Static-analysis gate first (docs/ANALYSIS.md): whole-repo, one
# process, ~1 s — a drifted knob/metric/route registry or a broken
# invariant should fail the battery before an hour of bench time is
# spent producing artifacts for a commit that can't merge anyway.
run_step rtpulint timeout 60 python -m routest_tpu.analysis --gate
# Shortest steps first: a tunnel that recovers for only part of the
# window should still yield the highest-value artifacts (the bench
# record the driver compares, then the serving-selection table) before
# the hour-scale router runs start.
run_step bench timeout 600 python bench.py
# Many-query routing curve: merged K-source dispatches vs scalar solves
# at oracle parity (artifacts/batch_solve.json; the router-side batcher
# serves exactly these shapes).
run_step batch_solve timeout 1800 python scripts/bench_batch_solve.py
# Per-path (xla / pallas / aot) Mpreds/s rows per serving bucket, the
# refreshed selection table, and the regression gate: --gate fails the
# battery if the fused kernel now LOSES at a bucket the previous record
# said it wins (serving would keep auto-selecting a slower path).
run_step kernel_bench timeout 2400 python scripts/bench_serving_kernel.py --gate
# Per-chip fleet scaling: the chips={1,2,4,8} preds/s curve, the
# 8-chip placement comparison (8x1 vs 2x4 vs 1x8), weighted routing
# shares, and the overlay-preserving rolling restart
# (artifacts/fleet_chips.json; host_caveat is structural and clears
# on a real TPU backend — this is the BASELINE >=10k preds/s/chip
# claim measured PER CHIP for the first time).
run_step fleet_chips timeout 2400 python scripts/bench_fleet_chips.py
# Telemetry end-to-end (ISSUE 13): an injected latency regression must
# be visible in the gateway fleet timeline within a tick, tail-kept as
# a slow trace with provenance, and captured in a bundle embedding the
# timeline slice (artifacts/telemetry.json).
run_step telemetry timeout 1500 python scripts/bench_telemetry.py
# Blackbox probing end-to-end (ISSUE 15): three injected correctness
# faults (compute skew, stale metric epoch, divergent model) must each
# page the prober's correctness SLO with a bundle naming the faulty
# replica; the clean run stays green across a metric flip and a
# verified swap (artifacts/probing.json). The probe-subgraph extract +
# overlay + XLA caches persist under artifacts/bench_cache/probing so
# later battery rounds skip the cold hierarchy build.
run_step probing timeout 2400 python scripts/bench_probing.py
# Dispatch workload end to end (ISSUE 16): batched VRP solves/s must
# scale with batch size at host-oracle parity; a corridor jam on a live
# 2-replica fleet must re-dispatch exactly the affected routes within a
# bounded window (plan_update over SSE, user SLO green); an injected
# dispatch.solve skew must page the prober's dispatch kind
# (artifacts/dispatch.json). Extract + hierarchy + XLA caches persist
# under artifacts/bench_cache/dispatch across battery rounds.
run_step dispatch timeout 2400 python scripts/bench_dispatch.py
# Multi-region failover end to end (ISSUE 18): two full fleets behind
# the geo-front with the probe-bus bridge — a corridor jam in region
# east must reach region west's served metric within a bounded window;
# a region.kill on east must page the cross-region fan-out probe by
# name while the survivor absorbs the redirected traffic (shed
# bounded, staleness bounded+metered, journal holding every write);
# the rejoined region must catch up (journal drained, bridge replay)
# with a quiet clean window (artifacts/region_failover.json, with
# structural host_caveat/skipped fields). Extract + hierarchy + XLA
# caches persist under artifacts/bench_cache/region_failover across
# battery rounds.
run_step region_failover timeout 2400 python scripts/bench_region_failover.py
# Binary wire serving end to end (ISSUE 19): the length-prefixed
# columnar format must answer bitwise-identically to the JSON path
# through a real gateway, beat it by >=2x rows/s on small batches,
# add <1ms p95 over a direct channel hop, and sustain >=100k rows/s
# through one gateway; the prober's wire parity kind must stay green
# across a metric flip and a verified model swap under open-loop
# binary load (artifacts/wire.json). Extract + hierarchy + XLA caches
# persist under artifacts/bench_cache/wire across battery rounds.
run_step wire timeout 2400 python scripts/bench_wire.py
# Device efficiency end to end (ISSUE 17): the goodput ledger +
# throughput-regression watchdog on a live 2-replica fleet — an
# injected device.compute slowdown and a forced pathological bucket
# config must each page the efficiency SLO with a bundle naming
# program/replica/bucket and the expected-vs-measured curve; the clean
# fleet stays green across a flip and a verified swap; the always-on
# ledger stays inside the ≤5% p95 budget (artifacts/efficiency.json).
# Extract + hierarchy + XLA caches persist under
# artifacts/bench_cache/efficiency across battery rounds.
run_step efficiency timeout 2400 python scripts/bench_efficiency.py
# Incident correlation end to end (ISSUE 20): a bad deploy via the
# canary state machine, a chaos-jammed customize cycle, and a
# geo-front region.kill each page with the injected cause ranked
# suspect #1 in the bundle's suspects.json; a clean window of ≥20
# legitimate metric flips + ≥2 verified swaps yields zero pages and
# zero false attributions (artifacts/incidents.json). XLA cache
# persists under artifacts/bench_cache/incidents across rounds.
run_step incidents timeout 900 python scripts/bench_incidents.py
run_step load_test timeout 2400 python scripts/load_test.py --workers 1
run_step router_scale timeout 3600 python scripts/bench_router_scale.py \
  --osm-nodes 250000 --verify --flat-compare
# Country-scale probe (PARITY's 1M-node record, as a regenerable
# artifact): osm-topology row only, oracle-verified, own file so the
# canonical router_scale.json keeps its standard sizes.
run_step router_scale_xl timeout 3600 python scripts/bench_router_scale.py \
  --sizes 0 --osm-nodes 1000000 --verify \
  --out artifacts/router_scale_xl.json
if [ -n "$failed" ]; then
  echo "battery finished with failures:$failed"
  exit 1
fi
echo "battery complete: all real-chip artifacts regenerated"
