"""Train the ETA models and freeze the CPU-baseline golden RMSE.

This is the ``notebooks/`` training pipeline the reference promised but
never committed (README "Coming Soon", empty notebooks/ — SURVEY.md §0):

1. generate the delivery dataset (schema of ``Flaskr/ml.py:35-48``);
2. train the CPU baseline (sklearn HistGradientBoosting — same model
   family as the reference's pickled XGBoost) → ``artifacts/baseline.json``;
3. train the JAX MLP on the accelerator → ``artifacts/eta_mlp.msgpack``;
4. assert the TPU model meets the CPU-baseline RMSE (BASELINE.json
   acceptance bar) and write ``artifacts/training_report.json``.

Usage: python scripts/train_eta.py [--n 500000] [--epochs 30] [--quick]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--n", type=int, default=500_000)
    parser.add_argument("--epochs", type=int, default=30)
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--csv", type=str, default=None,
                        help="train from a delivery-history CSV "
                             "(data/csv_io.py schema) instead of the "
                             "synthetic generator")
    parser.add_argument("--quick", action="store_true",
                        help="small run for smoke testing")
    parser.add_argument("--quantiles", type=str, default=None,
                        help="comma-separated quantile levels (must include "
                             "0.5), e.g. 0.1,0.5,0.9 — trains calibrated "
                             "uncertainty heads with pinball loss")
    args = parser.parse_args()
    if args.quick:
        args.n, args.epochs = 50_000, 8

    import numpy as np

    from routest_tpu.core.config import TrainConfig
    from routest_tpu.data.synthetic import generate_dataset, train_eval_split
    from routest_tpu.models.eta_mlp import EtaMLP
    from routest_tpu.train.baseline import save_baseline, train_cpu_baseline
    from routest_tpu.train.checkpoint import default_model_path, save_model
    from routest_tpu.train.loop import fit

    if args.csv:
        from routest_tpu.data.csv_io import load_csv

        print(f"[1/4] dataset: {args.csv}")
        data = load_csv(args.csv)
    else:
        print(f"[1/4] dataset: n={args.n}")
        data = generate_dataset(args.n, seed=args.seed)
    train, ev = train_eval_split(data)
    print(f"      train={len(train['eta_minutes'])} eval={len(ev['eta_minutes'])} "
          f"target std={float(np.std(ev['eta_minutes'])):.2f} min")

    print("[2/4] CPU baseline (HistGradientBoosting)…")
    baseline = train_cpu_baseline(train, ev)
    path = save_baseline(baseline)
    print(f"      RMSE={baseline['rmse_minutes']:.3f} min  "
          f"single-row={baseline['single_row_preds_per_sec']:.0f}/s  "
          f"bulk={baseline['bulk_preds_per_sec']:.0f}/s → {path}")

    quantiles = (tuple(float(v) for v in args.quantiles.split(","))
                 if args.quantiles else ())
    print(f"[3/4] JAX MLP: epochs={args.epochs}"
          + (f" quantiles={list(quantiles)}" if quantiles else ""))
    model = EtaMLP(quantiles=quantiles)
    t0 = time.time()
    result = fit(model, train, ev, TrainConfig(epochs=args.epochs, seed=args.seed),
                 log_every=max(1, args.epochs // 5))
    fit_s = time.time() - t0
    print(f"      RMSE={result.eval_rmse:.3f} min in {fit_s:.1f}s")

    model_path = default_model_path()
    save_model(model_path, model, result.state.params)
    print(f"      artifact → {model_path}")

    # Pinball-trained medians minimize absolute error, not squared error;
    # on skewed heteroscedastic targets the conditional median carries a
    # systematic RMSE penalty vs the squared-error-trained baseline, so
    # quantile runs get headroom (1.10) where point runs must match (1.02).
    margin = 1.10 if quantiles else 1.02
    print(f"[4/4] acceptance: TPU RMSE ≤ CPU baseline RMSE × {margin}")
    ok = result.eval_rmse <= baseline["rmse_minutes"] * margin
    report = {
        "n": args.n,
        "epochs": args.epochs,
        "cpu_baseline_rmse_minutes": baseline["rmse_minutes"],
        "mlp_rmse_minutes": result.eval_rmse,
        "rmse_ratio": result.eval_rmse / baseline["rmse_minutes"],
        "rmse_margin": margin,
        "mlp_fit_seconds": fit_s,
        "passed": bool(ok),
    }
    if quantiles:
        from routest_tpu.data.features import batch_from_mapping

        x = batch_from_mapping(ev)
        y = np.asarray(ev["eta_minutes"], np.float32)
        preds = np.asarray(
            model.apply_quantiles(result.state.params, x))
        report["quantiles"] = list(quantiles)
        report["coverage"] = {
            f"{q:g}": float((y <= preds[:, i]).mean())
            for i, q in enumerate(quantiles)}
        print(f"      coverage: {report['coverage']}")
    report_path = os.path.join(os.path.dirname(path), "training_report.json")
    with open(report_path, "w") as f:
        json.dump(report, f, indent=2)
    print(f"      {'PASS' if ok else 'FAIL'} "
          f"(ratio {report['rmse_ratio']:.4f}) → {report_path}")
    sys.exit(0 if ok else 1)


if __name__ == "__main__":
    main()
