"""Point-vs-quantile serving-throughput ladder at HEAD.

VERDICT r5 weak #2: the README's 75.1k → 65.9k preds/s drift between
the point-head and quantile-head serving artifacts was a claim, not a
measurement. This script measures it: one full ``scripts/load_test.py``
run per mode (same host, same HEAD, same load shape), differing only in
``ETA_MODEL_PATH`` — the shipped quantile artifact vs a point-head
artifact of the identical trunk architecture (trained quickly if
absent; throughput depends on the head width, not the fit quality).
Writes ``artifacts/quantile_ladder.json``.

Usage: python scripts/bench_quantile_ladder.py [--quick]
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

QUANTILE_ARTIFACT = os.path.join(REPO, "artifacts", "eta_mlp.msgpack")
POINT_ARTIFACT = os.path.join(REPO, "artifacts", "eta_mlp_point.msgpack")


def ensure_point_artifact() -> None:
    """Train a point-head EtaMLP (same trunk as the shipped quantile
    artifact) if none exists — serving cost is a function of the head
    shape, so a quick fit measures the same forward pass."""
    if os.path.exists(POINT_ARTIFACT):
        return
    print("[ladder] training point-head artifact …", file=sys.stderr)
    from routest_tpu.core.config import TrainConfig
    from routest_tpu.data.synthetic import generate_dataset, train_eval_split
    from routest_tpu.models.eta_mlp import EtaMLP
    from routest_tpu.train.checkpoint import save_model
    from routest_tpu.train.loop import fit

    train, ev = train_eval_split(generate_dataset(100_000, seed=0))
    model = EtaMLP()  # point head, default (256, 256, 128) trunk
    result = fit(model, train, ev, TrainConfig(epochs=5))
    save_model(POINT_ARTIFACT, model, result.state.params)
    print(f"[ladder] point artifact (eval RMSE "
          f"{result.eval_rmse:.2f} min) → {POINT_ARTIFACT}",
          file=sys.stderr)


def run_mode(mode: str, model_path: str, args) -> dict:
    """One load_test run against a self-spawned server on this
    artifact; returns the sections the ladder compares."""
    out = os.path.join(tempfile.gettempdir(),
                       f"rtpu_ladder_{mode}_{os.getpid()}.json")
    env = dict(os.environ)
    env["ETA_MODEL_PATH"] = model_path
    cmd = [sys.executable, os.path.join(REPO, "scripts", "load_test.py"),
           "--cpu", "--threads", str(args.threads),
           "--requests", str(args.requests),
           "--road-requests", "0",
           "--batch-size", str(args.batch_size),
           "--batch-requests", str(args.batch_requests),
           "--batch-threads", str(args.batch_threads),
           "--out", out]
    print(f"[ladder] mode={mode}: {' '.join(cmd[1:])}", file=sys.stderr)
    # Budget failures exit 1 but still write the artifact — the ladder
    # wants the numbers either way (1-core hosts miss CPU-scaled SLOs).
    subprocess.run(cmd, env=env, cwd=REPO, check=False,
                   stdout=subprocess.DEVNULL)
    with open(out) as f:
        report = json.load(f)
    os.unlink(out)
    return {
        "model_path": os.path.relpath(model_path, REPO),
        "preds_per_s": report.get("predict_eta_batch", {}).get("preds_per_s"),
        "predict_eta_batch": {
            k: report.get("predict_eta_batch", {}).get(k)
            for k in ("batch_size", "requests", "rows", "p50_ms",
                      "p95_ms", "errors")},
        "predict_eta": report.get("predict_eta", {}),
        "single_row_rps": report.get("rps"),
        "quantile_band": report.get("quantile_band", {}),
        "latency_decomposition": report.get("latency_decomposition", {}),
    }


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--threads", type=int, default=8)
    parser.add_argument("--requests", type=int, default=20)
    parser.add_argument("--batch-size", type=int, default=4096)
    parser.add_argument("--batch-requests", type=int, default=8)
    parser.add_argument("--batch-threads", type=int, default=2)
    parser.add_argument("--quick", action="store_true")
    parser.add_argument("--out", default=os.path.join(
        REPO, "artifacts", "quantile_ladder.json"))
    args = parser.parse_args()
    if args.quick:
        args.requests, args.batch_requests = 8, 4

    ensure_point_artifact()
    modes = {
        "quantile": run_mode("quantile", QUANTILE_ARTIFACT, args),
        "point": run_mode("point", POINT_ARTIFACT, args),
    }
    # Sanity: the quantile run must actually have served bands, and the
    # point run must not — otherwise the ladder compared nothing.
    q_served = modes["quantile"]["quantile_band"].get(
        "quantile_model_serving")
    p_served = modes["point"]["quantile_band"].get("quantile_model_serving")
    q_tp = modes["quantile"]["preds_per_s"] or 0.0
    p_tp = modes["point"]["preds_per_s"] or 0.0
    report = {
        "recorded_unix": int(time.time()),
        "cpu_count": os.cpu_count(),
        # Structural host caveat (PR-4 convention): this ladder runs the
        # hermetic CPU backend; absolute preds/s bind only to this box,
        # the point-vs-quantile RATIO is the portable claim.
        "host_caveat": "cpu-backend ladder: compare the ratio, not the "
                       "absolute throughput",
        "quick": bool(args.quick),
        "modes_valid": bool(q_served) and not p_served,
        "modes": modes,
        "point_over_quantile": round(p_tp / q_tp, 4) if q_tp else None,
        "quantile_head_cost_pct": round(100.0 * (1 - q_tp / p_tp), 2)
        if p_tp else None,
    }
    out_dir = os.path.dirname(args.out)
    if out_dir:
        os.makedirs(out_dir, exist_ok=True)
    with open(args.out, "w") as f:
        json.dump(report, f, indent=2)
    print(json.dumps(report, indent=2))
    print(f"[ladder] report → {args.out}", file=sys.stderr)


if __name__ == "__main__":
    main()
