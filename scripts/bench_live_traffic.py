"""Live traffic end to end → artifacts/live_traffic.json.

The payoff bench for the live subsystem (docs/ARCHITECTURE.md "Live
traffic"): a real fleet (supervisor + worker + gateway + netbus
broker) serves the Manila metro extract under the open-loop mixed
load generator while a simulated probe fleet streams per-edge speed
observations. A third of the way in, the scenario driver jams a named
corridor; the run passes iff

- served ETAs and chosen routes for a probe OD pair straddling the
  corridor measurably shift, within the configured staleness bound
  (probe-injection → served-effect latency is measured and reported);
- post-flip served durations match a scipy Dijkstra oracle re-solved
  on the replica's OWN exported live metric (``/api/live?metric=1``);
- zero client 5xx and the SLO engine stays green on BOTH tiers across
  ≥ 3 metric-generation flips and ≥ 3 verified road-GNN hot-swaps
  (the continuous trainer runs in this driver process, landing
  artifacts through the router's verified swap);
- overlay metric customization is reported ≪ the full overlay build
  per flip (CRP-style re-pricing, not a rebuild).

Usage: python scripts/bench_live_traffic.py [--nodes 30000]
       [--duration 150] [--drivers 250] [--quick]
       [--out artifacts/live_traffic.json]
"""

from __future__ import annotations

import argparse
import json
import math
import os
import socket
import sys
import tempfile
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

MODEL = os.path.join(REPO, "artifacts", "eta_mlp.msgpack")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def build_extract(n_nodes: int, out_dir: str):
    """Manila metro extract (same recipe as the router scale benches) +
    its overlay cache, prebuilt in-process so the worker rehydrates."""
    from routest_tpu.data.osm import load_osm, save_osm
    from routest_tpu.data.road_graph import (generate_road_graph,
                                             subdivide_graph)
    from routest_tpu.optimize.road_router import RoadRouter

    n_int = max(1024, int(n_nodes / 5.86))
    base = generate_road_graph(n_nodes=n_int, k=4, seed=0)
    streets = subdivide_graph(base, bends_per_edge=2, oneway_frac=0.1,
                              seed=0)
    path = os.path.join(out_dir, f"manila_{n_nodes}.osm.gz")
    save_osm(path, streets)
    extract = load_osm(path)
    t0 = time.perf_counter()
    router = RoadRouter(graph=extract, use_gnn=False,
                        use_transformer=False)
    print(f"  overlay prebuilt in {time.perf_counter() - t0:.1f}s "
          f"({router.n_nodes:,} nodes, {len(router.senders):,} edges)",
          flush=True)
    return path, router


def _fetch(url: str, timeout: float = 30.0):
    import urllib.request

    with urllib.request.urlopen(url, timeout=timeout) as r:
        return json.loads(r.read())


def _post(url: str, body: dict, timeout: float = 120.0):
    import urllib.request

    req = urllib.request.Request(
        url, data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return json.loads(r.read())


def main() -> None:
    parser = argparse.ArgumentParser()
    # Defaults are sized for the 1-core CI/dev host every bench here
    # records on (the worker, the driver-side trainer, the probe fleet
    # and the load generator all time-slice one core); on a real
    # multi-core box, raise --nodes/--drivers/--rps freely.
    parser.add_argument("--nodes", type=int, default=20_000)
    parser.add_argument("--duration", type=float, default=180.0)
    parser.add_argument("--drivers", type=int, default=160)
    parser.add_argument("--rps", type=float, default=1.5)
    parser.add_argument("--customize-s", type=float, default=8.0)
    parser.add_argument("--half-life-s", type=float, default=15.0)
    parser.add_argument("--staleness-bound", type=float, default=None,
                        help="max allowed probe-injection → served-"
                             "effect latency. Default derives from the "
                             "loop's own physics: two estimator half-"
                             "lives (EWMA convergence to the new "
                             "regime) + two customize intervals (one "
                             "may be mid-flight at injection) + 15 s "
                             "ingest/sampler margin")
    parser.add_argument("--retrain-steps", type=int, default=10)
    parser.add_argument("--obs-per-tick", type=int, default=6)
    parser.add_argument("--slo-ms", type=float, default=8000.0)
    parser.add_argument("--quick", action="store_true",
                        help="10k extract, 100 s, 96 drivers — the "
                             "slow-test preset")
    parser.add_argument("--seed", type=int, default=42)
    parser.add_argument("--out", default=None)
    args = parser.parse_args()
    if args.quick:
        args.nodes = min(args.nodes, 10_000)
        args.duration = min(args.duration, 100.0)
        args.drivers = min(args.drivers, 96)
        args.customize_s = min(args.customize_s, 6.0)
    if args.staleness_bound is None:
        args.staleness_bound = (2 * args.half_life_s
                                + 2 * args.customize_s + 15.0)

    os.environ.setdefault("ROUTEST_FORCE_CPU", "1")
    import jax

    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    from routest_tpu.core.cache import enable_compile_cache
    from routest_tpu.core.config import FleetConfig
    from routest_tpu.data.locations import SEED_LOCATIONS
    from routest_tpu.live.ingest import ProbeIngester
    from routest_tpu.live.probes import (CongestionScenario, ProbeFleet,
                                         corridor_edges)
    from routest_tpu.live.state import CongestionState
    from routest_tpu.live.trainer import ContinuousTrainer
    from routest_tpu.loadgen import (MixedWorkload, RateCurve,
                                     SseClients, poisson_schedule,
                                     run_open_loop, summarize)
    from routest_tpu.serve.fleet.gateway import Gateway
    from routest_tpu.serve.fleet.supervisor import ReplicaSupervisor
    from routest_tpu.serve.netbus import NetBus, start_broker

    work_dir = tempfile.mkdtemp(prefix="live-traffic-")
    hier_cache = os.path.join(work_dir, "hier")
    xla_cache = os.path.join(work_dir, "xla")
    gnn_path = os.path.join(work_dir, "road_gnn_live.msgpack")
    os.environ["ROUTEST_HIER_CACHE"] = hier_cache
    os.environ["RTPU_RECORDER_DIR"] = os.path.join(work_dir,
                                                   "postmortems")
    enable_compile_cache(xla_cache)
    channel = "rtpu.probes"
    slo_spec = (f"/api/request_route:latency_ms={args.slo_ms:.0f},"
                f"latency_target=0.9,availability=0.99;"
                f"/api/predict_eta:latency_ms=2500,latency_target=0.9,"
                f"availability=0.99")
    # The in-process GATEWAY's engine reads this env too — without it
    # the gateway would judge by the built-in defaults (tighter
    # latency thresholds than this 1-core host can honor).
    os.environ["RTPU_SLO_OBJECTIVES"] = slo_spec

    print(f"[1/6] building {args.nodes:,}-node Manila extract + overlay "
          f"cache…", flush=True)
    extract, oracle_router = build_extract(args.nodes, work_dir)
    n_edges = len(oracle_router.senders)

    # Corridor: between two seed sites, wide enough to carry traffic.
    a = (SEED_LOCATIONS[2][1], SEED_LOCATIONS[2][2])
    b = (SEED_LOCATIONS[11][1], SEED_LOCATIONS[11][2])
    # Narrow band: wide enough to jam every lane ALONG the line, narrow
    # enough that parallel streets outside it offer real detours — the
    # route-shift half of the acceptance needs an escape to exist.
    corridor_width = 220.0
    corridor = corridor_edges(oracle_router.coords,
                              oracle_router.senders,
                              oracle_router.receivers, a, b,
                              width_m=corridor_width)
    print(f"  corridor {len(corridor)} edges between "
          f"{SEED_LOCATIONS[2][0]} and {SEED_LOCATIONS[11][0]}",
          flush=True)

    def corridor_overlap(coords_lonlat) -> float:
        """Fraction of a served polyline's vertices inside the corridor
        band — the route-shift witness (drops when routes detour)."""
        pts = np.asarray(coords_lonlat, np.float64)
        if len(pts) == 0:
            return 0.0
        latlon = pts[:, ::-1]
        lat0 = math.radians((a[0] + b[0]) / 2.0)
        scale = np.asarray([111_194.9, 111_194.9 * math.cos(lat0)])
        p = (latlon - np.asarray(a)) * scale
        seg = (np.asarray(b) - np.asarray(a)) * scale
        t = np.clip((p @ seg) / float(seg @ seg), 0.0, 1.0)
        d = np.sqrt(((p - t[:, None] * seg[None, :]) ** 2).sum(axis=1))
        return float((d <= corridor_width).mean())

    print("[2/6] starting broker + fleet (1 worker + gateway)…",
          flush=True)
    broker, _bt = start_broker()
    bus_url = f"tcp://127.0.0.1:{broker.port}"
    env = dict(os.environ)
    env.update({
        "ROAD_GRAPH_OSM": extract,
        "ROUTEST_HIER_CACHE": hier_cache,
        "RTPU_COMPILE_CACHE": xla_cache,
        "ROUTEST_MESH": "0",
        "ROUTEST_WARM_BUCKETS": "0",
        "ETA_MODEL_PATH": MODEL,
        "ROAD_GNN_PATH": gnn_path,
        "REDIS_URL": bus_url,
        "RTPU_SLO_OBJECTIVES": slo_spec,
        "RTPU_LIVE": "1",
        "RTPU_LIVE_CHANNEL": channel,
        "RTPU_LIVE_CUSTOMIZE_S": str(args.customize_s),
        "RTPU_LIVE_HALF_LIFE_S": str(args.half_life_s),
        "RTPU_LIVE_MIN_OBS_EDGES": "50",
    })
    ports = [_free_port()]
    sup = ReplicaSupervisor(ports, env=env, cwd=REPO,
                            probe_interval_s=0.5, backoff_base_s=0.2,
                            backoff_cap_s=2.0)
    sup.start()
    gw = httpd = None
    fleet = ingester = trainer = None
    record: dict = {}
    try:
        if not sup.ready(timeout=600):
            raise RuntimeError("fleet worker never became ready")
        replica_base = f"http://127.0.0.1:{ports[0]}"
        gw = Gateway([("127.0.0.1", p) for p in ports],
                     FleetConfig(hedge=False, max_inflight=32,
                                 queue_depth=64), supervisor=sup)
        httpd = gw.serve("127.0.0.1", 0)
        base = f"http://127.0.0.1:{httpd.server_address[1]}"

        print("[3/6] warming worker (router from cache) + arming "
              "probes/trainer…", flush=True)
        od_body = {
            "source_point": {"lat": a[0], "lon": a[1]},
            "destination_points": [{"lat": b[0], "lon": b[1],
                                    "payload": 1}],
            "driver_details": {"vehicle_type": "car",
                               "vehicle_capacity": 100,
                               "maximum_distance": 900_000},
            "road_graph": True,
        }
        t0 = time.perf_counter()
        _post(f"{base}/api/request_route", od_body, timeout=600)
        warm_s = time.perf_counter() - t0
        deadline = time.time() + 300
        while time.time() < deadline:
            if _fetch(f"{replica_base}/api/live").get("ready"):
                break
            time.sleep(0.5)
        else:
            raise RuntimeError("replica live service never armed")

        scenario = CongestionScenario(corridor, speed_factor=0.25)
        graph = oracle_router.graph_dict()
        probe_bus = NetBus(bus_url)
        fleet = ProbeFleet(graph, args.drivers, probe_bus.publish,
                           seed=args.seed, channel=channel,
                           obs_per_tick=args.obs_per_tick,
                           scenario=scenario)
        fleet.start(tick_s=1.0)
        # Driver-side estimator feeding the continuous trainer (its own
        # subscription on the same stream the replicas fold).
        train_bus = NetBus(bus_url)
        state = CongestionState(oracle_router.freeflow_time_s,
                                half_life_s=args.half_life_s,
                                stale_s=600.0)
        ingester = ProbeIngester(train_bus, state,
                                 oracle_router.length_m,
                                 channel=channel)
        ingester.start()
        trainer = ContinuousTrainer(oracle_router, state, gnn_path,
                                    steps=args.retrain_steps,
                                    min_obs=400)
        swap_stop = threading.Event()

        def retrain_loop() -> None:
            while not swap_stop.wait(2.0):
                trainer.run_once()

        retrain_thread = threading.Thread(target=retrain_loop,
                                          daemon=True)
        retrain_thread.start()

        # Probe OD sampler: the served route/ETA timeline the staleness
        # measurement reads.
        samples: list = []
        sample_stop = threading.Event()

        def sample_loop() -> None:
            while not sample_stop.is_set():
                try:
                    t = time.time()
                    feat = _post(f"{base}/api/request_route", od_body,
                                 timeout=120)
                    summary = feat.get("properties", {}).get("summary",
                                                             {})
                    samples.append({
                        "t": t,
                        "duration_s": float(summary.get("duration", 0)),
                        "distance_m": float(summary.get("distance", 0)),
                        "overlap": corridor_overlap(
                            feat.get("geometry", {}).get("coordinates",
                                                         [])),
                    })
                except Exception as e:
                    samples.append({"t": time.time(),
                                    "error": f"{type(e).__name__}: {e}"})
                sample_stop.wait(1.5)

        threading.Thread(target=sample_loop, daemon=True).start()

        print(f"[4/6] open loop {args.rps} rps × {args.duration:.0f}s, "
              f"{args.drivers} probe drivers; corridor jam at "
              f"t+{args.duration / 3:.0f}s…", flush=True)
        workload = MixedWorkload(
            mix={"request_route": 0.25, "predict_eta": 0.45,
                 "history": 0.1, "update_tracker": 0.1, "probe": 0.1},
            seed=args.seed, road_graph=True, probe_edges=n_edges)
        sse = SseClients(base, 2, channel=workload.sse_channel)
        sse.__enter__()
        curve = RateCurve.constant(args.rps)
        offsets = poisson_schedule(curve, args.duration, seed=args.seed)
        requests = workload.sequence(len(offsets))
        t_start = time.time()
        t_inject = t_start + args.duration / 3.0

        def inject_later() -> None:
            delay = t_inject - time.time()
            if delay > 0:
                time.sleep(delay)
            scenario.set_active(True)
            print(f"  corridor jam ACTIVE at t+{time.time() - t_start:.0f}s",
                  flush=True)

        threading.Thread(target=inject_later, daemon=True).start()
        records = run_open_loop([base], offsets, requests, workers=16,
                                timeout=max(60.0, 4 * args.slo_ms / 1000))
        report = summarize(records, args.duration, len(offsets))
        sample_stop.set()
        swap_stop.set()
        # Let an in-flight retrain cycle finish before teardown — a
        # daemon thread mid-jax-dispatch at interpreter exit segfaults.
        retrain_thread.join(timeout=60.0)
        sse.__exit__()
        sse_events = sse.snapshot()

        print("[5/6] oracle check + fleet judgement…", flush=True)
        # Post-flip oracle: served duration vs scipy Dijkstra on the
        # replica's OWN exported metric, fetched at a stable epoch.
        oracle = {"checked": False}
        for _attempt in range(5):
            live0 = _fetch(f"{replica_base}/api/live?metric=1",
                           timeout=120)
            feat = _post(f"{base}/api/request_route", od_body,
                         timeout=120)
            live1 = _fetch(f"{replica_base}/api/live")
            if live0.get("epoch") != live1.get("epoch"):
                continue  # flipped mid-check: retry at the next epoch
            import scipy.sparse as sp
            from scipy.sparse.csgraph import dijkstra

            metric = np.asarray(live0["edge_time_s"], np.float64)
            n = oracle_router.n_nodes
            adj = sp.coo_matrix(
                (metric, (oracle_router.senders,
                          oracle_router.receivers)),
                shape=(n, n)).tocsr()
            src = oracle_router.snap(np.asarray([a, b], np.float32))
            want = dijkstra(adj, directed=True,
                            indices=np.asarray(src[:1], np.int64))
            from routest_tpu.data.road_graph import haversine_np

            snap_m = haversine_np(
                np.asarray([a[0], b[0]]), np.asarray([a[1], b[1]]),
                oracle_router.coords[src, 0],
                oracle_router.coords[src, 1])
            oracle_s = float(want[0, src[1]]) \
                + float(snap_m.sum()) / 8.3
            served_s = float(feat["properties"]["summary"]["duration"])
            rel = abs(served_s - oracle_s) / max(oracle_s, 1.0)
            oracle = {"checked": True, "epoch": live0.get("epoch"),
                      "served_duration_s": round(served_s, 2),
                      "oracle_duration_s": round(oracle_s, 2),
                      "rel_err": round(rel, 6),
                      "pass": rel < 2e-3}
            break

        live_final = _fetch(f"{replica_base}/api/live", timeout=60)
        replica_metrics = _fetch(f"{replica_base}/api/metrics",
                                 timeout=60)
        replica_slo = _fetch(f"{replica_base}/api/slo", timeout=60)
        gw.slo.tick()
        gateway_slo = gw.slo.snapshot()
        health = _fetch(f"{replica_base}/api/health", timeout=60)
    finally:
        for part in (fleet, ingester):
            if part is not None:
                part.stop()
        try:
            if httpd is not None:
                gw.drain(timeout=5)
        finally:
            sup.drain(timeout=20)
            broker.shutdown()

    # ── staleness + shift analysis ────────────────────────────────────
    good = [s for s in samples if "duration_s" in s]
    pre = [s for s in good if s["t"] < t_inject]
    post = [s for s in good if s["t"] >= t_inject]
    base_dur = (sorted(s["duration_s"] for s in pre)[len(pre) // 2]
                if pre else float("nan"))
    base_dist = (sorted(s["distance_m"] for s in pre)[len(pre) // 2]
                 if pre else float("nan"))
    base_overlap = (sorted(s["overlap"] for s in pre)[len(pre) // 2]
                    if pre else float("nan"))
    # Detection = TWO consecutive over-threshold samples: a single
    # sample can cross 1.10× on baseline noise (a model swap re-pricing
    # unobserved edges), which would report a physically impossible
    # sub-second staleness.
    detect_t = None
    for i in range(len(post) - 1):
        if (post[i]["duration_s"] >= base_dur * 1.10
                and post[i + 1]["duration_s"] >= base_dur * 1.10):
            detect_t = post[i]["t"]
            break
    staleness_s = (detect_t - t_inject) if detect_t is not None else None
    tail = [s for s in post if detect_t is not None and s["t"] >= detect_t]
    tail_dur = (sorted(s["duration_s"] for s in tail)[len(tail) // 2]
                if tail else float("nan"))
    tail_dist = (sorted(s["distance_m"] for s in tail)[len(tail) // 2]
                 if tail else float("nan"))
    tail_overlap = (sorted(s["overlap"] for s in tail)[len(tail) // 2]
                    if tail else float("nan"))
    eta_shift = (tail_dur / base_dur - 1.0) if base_dur else 0.0
    # Route shift: the served geometry leaves the jammed band (overlap
    # drops) and/or the chosen path's length changes.
    dist_changed = (abs(tail_dist - base_dist) / base_dist > 0.002
                    if base_dist and not math.isnan(tail_dist) else False)
    overlap_dropped = (not math.isnan(tail_overlap)
                       and not math.isnan(base_overlap)
                       and tail_overlap <= base_overlap - 0.05)
    route_shift = dist_changed or overlap_dropped

    # ── fleet-level verdicts ──────────────────────────────────────────
    flips = int(live_final.get("customize", {}).get("flips", 0))
    registry = replica_metrics.get("registry", {})

    def _counter(name: str, **labels) -> int:
        total = 0
        for series in registry.get(name, {}).get("series", ()):
            if all(series.get("labels", {}).get(k) == v
                   for k, v in labels.items()):
                total += int(series.get("value", 0))
        return total

    swaps_accepted = _counter("rtpu_road_model_swaps_total",
                              result="accepted")
    client_5xx = sum(1 for r in records
                     if r.status is not None and r.status >= 500)
    slo_green = (gateway_slo.get("state") == "ok"
                 and replica_slo.get("state") == "ok")
    # Customization vs rebuild: the flip re-prices the overlay against
    # the new metric reusing partition + contraction; the honest
    # comparison is the recorded FULL build (which a per-flip rebuild
    # would pay, contraction walk and partition included). The gap
    # widens with scale — at quick/10k the python contraction walk is
    # small, at metro/250k it dominates — so the gate is directional
    # (strictly faster) and the ratio is reported for the record.
    metric_info = live_final.get("metric") or {}
    customize_s = metric_info.get("customize_s")
    full_build_s = metric_info.get("full_build_s")
    customization_fast = (customize_s is not None
                          and full_build_s is not None
                          and customize_s < full_build_s)
    customize_ratio = (round(full_build_s / customize_s, 2)
                       if customization_fast and customize_s else None)

    checks = {
        "eta_shifted": eta_shift >= 0.10,
        "route_shifted": bool(route_shift),
        "staleness_within_bound": (staleness_s is not None
                                   and staleness_s
                                   <= args.staleness_bound),
        "oracle_parity": bool(oracle.get("pass")),
        "zero_client_5xx": client_5xx == 0,
        "slo_green_both_tiers": slo_green,
        "metric_flips_ge_3": flips >= 3,
        "verified_swaps_ge_3": swaps_accepted >= 3,
        "customize_beats_full_build": bool(customization_fast),
    }
    passed = all(checks.values())
    try:
        n_cpus = len(os.sched_getaffinity(0))
    except AttributeError:
        n_cpus = os.cpu_count() or 1
    record = {
        "host": {"cpus": n_cpus,
                 "note": "1 worker + driver-side trainer share the "
                         "host; wall latency scales with cores"},
        "extract_nodes": args.nodes,
        "edges": n_edges,
        "corridor_edges": int(len(corridor)),
        "drivers": args.drivers,
        "duration_s": args.duration,
        "customize_interval_s": args.customize_s,
        "staleness_bound_s": args.staleness_bound,
        "warm_first_request_s": round(warm_s, 1),
        "workload": workload.describe(),
        "load": report,
        "sse_events": sse_events,
        "timeline": {
            "inject_at_s": round(t_inject - t_start, 1),
            "baseline_median_duration_s": round(base_dur, 1),
            "post_detect_median_duration_s": round(tail_dur, 1)
            if not math.isnan(tail_dur) else None,
            "baseline_median_distance_m": round(base_dist, 1),
            "post_detect_median_distance_m": round(tail_dist, 1)
            if not math.isnan(tail_dist) else None,
            "baseline_corridor_overlap": round(base_overlap, 3)
            if not math.isnan(base_overlap) else None,
            "post_detect_corridor_overlap": round(tail_overlap, 3)
            if not math.isnan(tail_overlap) else None,
            "eta_shift_frac": round(eta_shift, 4),
            "injection_to_served_effect_s":
                round(staleness_s, 1) if staleness_s is not None
                else None,
            "samples": len(good),
        },
        "oracle": oracle,
        "live": {"flips": flips,
                 "final_epoch": live_final.get("epoch"),
                 "ingest": live_final.get("ingest"),
                 "customize_s_last": customize_s,
                 "full_build_s": full_build_s,
                 "customize_speedup": customize_ratio,
                 "retrain_cycles": trainer.cycles if trainer else 0,
                 "swaps_accepted": swaps_accepted,
                 "swaps_rejected": _counter(
                     "rtpu_road_model_swaps_total", result="rejected")},
        "slo": {"gateway_state": gateway_slo.get("state"),
                "replica_state": replica_slo.get("state"),
                "green": slo_green},
        "client_5xx": client_5xx,
        "road_router": (health.get("checks", {}).get("engine", {})
                        .get("road_router")),
        "checks": checks,
        "pass": passed,
    }
    out = args.out or os.path.join(REPO, "artifacts",
                                   "live_traffic.json")
    os.makedirs(os.path.dirname(out), exist_ok=True)
    with open(out, "w") as f:
        json.dump(record, f, indent=2)
    print(f"\n[6/6] checks: "
          + " ".join(f"{k}={'PASS' if v else 'FAIL'}"
                     for k, v in checks.items()))
    print(f"ETA shift {eta_shift:+.0%}, injection→served "
          f"{record['timeline']['injection_to_served_effect_s']}s "
          f"(bound {args.staleness_bound:.0f}s), flips {flips}, "
          f"verified swaps {swaps_accepted}, customize "
          f"{customize_s}s vs build {full_build_s}s → {out}")
    sys.stdout.flush()
    # _exit, not sys.exit: lingering daemon threads (probe fleet /
    # ingester jax work) racing interpreter teardown can segfault AFTER
    # the verdict is decided and written — the exit code must reflect
    # the bench, not the teardown.
    os._exit(0 if passed else 1)


if __name__ == "__main__":
    main()
