"""Multi-region failover end to end → artifacts/region_failover.json.

The ISSUE-18 acceptance scenario: two full fleets (each its own
supervisor + workers + gateway + broker) behind the geo-front, live
probe state bridged both directions, the cross-region fan-out prober
armed — then a whole region is SIGKILLed and brought back:

- ``bridged_convergence`` — a corridor jam observed ONLY by region
  east's drivers (and published only into east's probe bus) must show
  up in region west's served live metric within a bounded convergence
  window: the ProbeBridge is the only path it can take.
- ``region_loss``        — ``region.kill`` on east (fleet process
  group AND broker die at once, no drain): the survivor absorbs the
  redirected traffic within SLO, store-mutating writes taken during
  the outage land in east's replication journal (zero lost, zero
  dropped), the survivor's live-metric staleness stays bounded and
  metered, and the fan-out probe's ``reach`` dimension pages naming
  the dead region.
- ``rejoin``             — east comes back (same broker port, fresh
  fleet): the journal drains to zero with every write replayed, live
  state catches up through bridge replay (the degraded-mode publish
  buffers on every bus that kept feeding east), the reach offender
  clears, and a clean watch window records zero new correctness
  failures and no page.

Caches (overlay hierarchy, XLA compiles, the synthetic extract) are
shared across scenarios AND battery rounds via ``--cache-dir``
(default ``artifacts/bench_cache/region_failover``), so only the
first run pays the cold road-graph build.

Usage: python scripts/bench_region_failover.py [--quick]
       [--out artifacts/region_failover.json] [--cache-dir DIR]
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import sys
import tempfile
import threading
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.join(REPO, "scripts"))

import bench_probing as bp  # noqa: E402  (extract/load/page helpers)

DRIVERS = 24                   # ambient probe drivers per region
JAM_SPEED_FACTOR = 0.25        # corridor traffic at quarter speed
JAM_WIDTH_M = 1500.0
JAM_RATIO = 1.5                # corridor metric must rise ≥ this
CALM_RATIO = 1.25              # …while off-corridor stays under this
CONVERGE_BOUND_S = 90.0        # jam → bridged region's served metric
PAGE_BOUND_S = 90.0            # region death → reach page naming it
SLO_RECOVER_BOUND_S = 60.0     # survivor user SLO back to ok
CATCHUP_BOUND_S = 120.0        # rejoin → journal drained + live ready
CLEAN_S = 15.0                 # quiet watch after recovery
STALE_BOUND_S = 30.0           # survivor live staleness bound
K_WRITES = 24                  # tracker writes taken during the outage
SLO_FAST_S, SLO_SLOW_S = 15.0, 45.0


# ── topology ─────────────────────────────────────────────────────────


class Region:
    """One region: broker + fleet subprocess + ambient probe drivers.
    ``kill()`` is a true region loss — the fleet process group AND the
    broker (with its live handler sockets) die at once — and every
    bus the bench keeps pointed at the region is reset so degraded-
    mode publish buffering kicks in instead of zombie-handler ACKs."""

    def __init__(self, name: str, *, extract: str, cache_dir: str,
                 work: str, replicas: int = 1) -> None:
        from routest_tpu.serve.fleet.geofront import FleetProcess

        self.name = name
        self.broker_port = bp._free_port()
        self.bus_url = f"tcp://127.0.0.1:{self.broker_port}"
        self.broker = None
        self.model_path = os.path.join(work, f"eta_{name}.msgpack")
        shutil.copy(bp.MODEL, self.model_path)
        env = dict(os.environ)
        env.update({
            "ROUTEST_FORCE_CPU": "1",
            "ROUTEST_WARM_BUCKETS": "0",
            "ROUTEST_MESH": "0",
            "ETA_MODEL_PATH": self.model_path,
            "ROUTEST_RELOAD_SEC": "0.5",
            "RTPU_SWAP_MAX_DIV": f"{bp.SWAP_MAX_DIV_MIN:g}",
            "RTPU_RECORDER_DIR": os.path.join(work, f"workers_{name}"),
            "RTPU_COMPILE_CACHE": os.path.join(cache_dir, "xla"),
            "ROAD_GRAPH_OSM": extract,
            "ROUTEST_HIER_CACHE": os.path.join(cache_dir, "hier"),
            "RTPU_LIVE": "1",
            "RTPU_LIVE_CUSTOMIZE_S": "3",
            "RTPU_LIVE_HALF_LIFE_S": "10",
            "RTPU_LIVE_MIN_OBS_EDGES": "10",
            # Probe-scale SLO windows so a burn decays inside the bench.
            "RTPU_SLO_FAST_S": f"{SLO_FAST_S:g}",
            "RTPU_SLO_SLOW_S": f"{SLO_SLOW_S:g}",
            "RTPU_SLO_TICK_S": "1",
            # The survivor's autoscaler is armed for redirected load.
            "RTPU_AUTOSCALE": "1",
            "RTPU_AUTOSCALE_MIN": "1",
            "RTPU_AUTOSCALE_MAX": "2",
            "RTPU_AUTOSCALE_TICK_S": "1",
        })
        env.pop("RTPU_REGIONS", None)   # the bench owns the topology
        self.fleet = FleetProcess(
            name, gateway_port=bp._free_port(),
            base_port=bp._free_port(), replicas=replicas,
            redis_url=self.bus_url, env=env)
        self.base = self.fleet.base
        self.probe_bus = None
        self.probe_fleet = None
        self._reset_on_kill = []       # buses that publish INTO us

    def start(self) -> None:
        from routest_tpu.serve.netbus import start_broker

        if self.broker is None:
            self.broker, _ = start_broker(port=self.broker_port)
        self.fleet.start()

    def start_drivers(self, graph, scenario=None, seed: int = 0) -> None:
        from routest_tpu.live.probes import ProbeFleet
        from routest_tpu.serve.netbus import NetBus

        self.probe_bus = NetBus(self.bus_url, reconnect_s=0.5)
        self.probe_fleet = ProbeFleet(graph, DRIVERS,
                                      self.probe_bus.publish, seed=seed,
                                      obs_per_tick=6, scenario=scenario)
        self.probe_fleet.start(tick_s=1.0)
        self._reset_on_kill.append(self.probe_bus)

    def watch_bus(self, bus) -> None:
        """Register a bus whose cached conns must drop on kill()."""
        self._reset_on_kill.append(bus)

    def kill(self) -> None:
        self.fleet.kill()
        self._stop_broker()
        # Drop cached keep-alive conns: a zombie handler thread of the
        # dead broker would otherwise keep ACKing publishes into its
        # memory; a fresh connect fails and the frame buffers instead.
        for bus in self._reset_on_kill:
            bus._reset()

    def rejoin(self) -> None:
        self.start()

    def _stop_broker(self) -> None:
        if self.broker is None:
            return
        with self.broker._subs_lock:
            handlers = {h for hs in self.broker._subs.values()
                        for h in hs}
        self.broker.shutdown()
        self.broker.server_close()
        for h in handlers:
            try:
                h.connection.close()
            except OSError:
                pass
        self.broker = None

    def stop(self) -> None:
        if self.probe_fleet is not None:
            self.probe_fleet.stop()
        self.fleet.terminate(timeout=30)
        self._stop_broker()


def _build_topology(extract: str, cache_dir: str, work: str):
    """Boot east+west fleets, the geo-front, and both bridges; start
    ambient drivers (east's are scenario-priced — the jam is a region-
    east physical event). Returns a context namespace."""
    from types import SimpleNamespace

    from routest_tpu.core.config import ProberConfig, RegionConfig
    from routest_tpu.data.locations import SEED_LOCATIONS
    from routest_tpu.data.osm import load_osm
    from routest_tpu.live.bridge import ProbeBridge
    from routest_tpu.live.probes import CongestionScenario, corridor_edges
    from routest_tpu.optimize.road_router import RoadRouter
    from routest_tpu.serve.fleet.geofront import GeoFront, RegionHandle
    from routest_tpu.serve.netbus import NetBus

    east = Region("east", extract=extract, cache_dir=cache_dir,
                  work=work)
    west = Region("west", extract=extract, cache_dir=cache_dir,
                  work=work)
    east.start()
    west.start()
    for r in (east, west):
        if not r.fleet.wait_ready(timeout=600):
            raise RuntimeError(f"region {r.name} fleet never ready")

    rc = RegionConfig(enabled=True, regions=("east", "west"),
                      default="east", bridge=True, health_s=0.5,
                      unhealthy_after=2, failover=True,
                      stale_bound_s=STALE_BOUND_S, journal_limit=4096,
                      replay_s=0.25, prober=True)
    front = GeoFront([
        RegionHandle("east", east.base, bus_url=east.bus_url,
                     kill=east.kill, rejoin=east.rejoin),
        RegionHandle("west", west.base, bus_url=west.bus_url,
                     kill=west.kill, rejoin=west.rejoin),
    ], rc)
    front.serve("127.0.0.1", 0)

    # Bridges both directions; reconnect_s buses so a dead endpoint
    # means buffering + replay, never a crashed bridge thread.
    bridges = []
    for src, dst in ((east, west), (west, east)):
        src_bus = NetBus(src.bus_url, reconnect_s=0.5)
        dst_bus = NetBus(dst.bus_url, reconnect_s=0.5)
        dst.watch_bus(dst_bus)
        b = ProbeBridge(src.name, dst.name, src_bus, dst_bus)
        b.start()
        bridges.append(b)
    front.bridges.extend(bridges)

    # Corridor geometry + the jam scenario (east-only physical event).
    router = RoadRouter(graph=load_osm(extract), use_gnn=False,
                        use_transformer=False)
    g = router.graph_dict()
    a = (SEED_LOCATIONS[2][1], SEED_LOCATIONS[2][2])
    b_ = (SEED_LOCATIONS[11][1], SEED_LOCATIONS[11][2])
    corridor = corridor_edges(g["node_coords"], g["senders"],
                              g["receivers"], a, b_, width_m=JAM_WIDTH_M)
    scenario = CongestionScenario(corridor,
                                  speed_factor=JAM_SPEED_FACTOR)
    scenario.set_active(False)
    east.start_drivers(g, scenario=scenario, seed=42)
    west.start_drivers(g, scenario=None, seed=1042)

    prober_cfg = ProberConfig(
        enabled=True, interval_s=1.0, timeout_s=20.0,
        eta_tolerance=bp.SWAP_MAX_DIV_MIN,
        # No pinned route probes: their self-consistency pin assumes
        # ONE fleet over ONE shared live metric — a failover legally
        # flips the serving region (and its metric), which is exactly
        # what the pin would call divergence. The golden fan-out
        # (model correctness per region) and reach (region liveness)
        # dimensions are the cross-region correctness probes.
        routes="",
        skew_after=3,
        # Live epochs count customize flips since each region's OWN
        # boot — never comparable across regions (and a rejoined
        # region restarts at 0). The reach dimension is the pager
        # here; epoch skew stays replica-scope.
        epoch_gap=10 ** 6,
        fast_window_s=bp.PROBE_FAST_S, slow_window_s=bp.PROBE_SLOW_S,
        fanout_reach=True)

    return SimpleNamespace(east=east, west=west, front=front,
                           bridges=bridges, graph=g, corridor=corridor,
                           scenario=scenario, prober_cfg=prober_cfg)


# ── metric helpers ───────────────────────────────────────────────────


def _edge_export(front_base: str, region: str):
    payload = bp._fetch(f"{front_base}/api/live?metric=1&region={region}",
                        timeout=30)
    arr = payload.get("edge_time_s")
    return (np.asarray(arr, np.float64) if arr else None), payload


def _median_ratio(base: np.ndarray, now: np.ndarray, idx) -> float:
    r = now[idx] / np.maximum(base[idx], 1e-6)
    return float(np.median(r))


def _wait_live(front_base: str, region: str, timeout: float) -> bool:
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        try:
            p = bp._fetch(f"{front_base}/api/live?region={region}",
                          timeout=10)
            if p.get("ready") and (p.get("epoch") or 0) >= 1:
                return True
        except OSError:
            pass
        time.sleep(0.5)
    return False


def _tracker_body(i: int) -> dict:
    return {"route_id": f"rf-{i}", "driver_name": f"driver-{i % 4}",
            "vehicle_type": "motorcycle", "duration": 1200.0,
            "distance": 5200.0, "trips": 1,
            "destinations": [f"stop-{i}"],
            "route": [[14.55 + 0.001 * i, 121.02]],
            "pickup_time": "2026-08-05T18:00:00"}


# ── scenarios ────────────────────────────────────────────────────────


def scenario_bridged_convergence(ctx) -> dict:
    """Jam east's corridor (east bus only); west's served metric must
    converge to the jammed prices through the bridge."""
    from bench_dispatch import CorridorSweep  # guaranteed coverage
    from routest_tpu.serve.netbus import NetBus

    out: dict = {"scenario": "bridged_convergence"}
    g, corridor = ctx.graph, ctx.corridor
    rng = np.random.default_rng(7)
    off = rng.choice(np.setdiff1d(np.arange(len(g["length_m"])),
                                  corridor),
                     size=min(2000, len(g["length_m"]) - len(corridor)),
                     replace=False)
    out["corridor_edges"] = int(len(corridor))

    ready = {r: _wait_live(ctx.front.base, r, 300.0)
             for r in ("east", "west")}
    base = {}
    for r in ("east", "west"):
        arr, _ = _edge_export(ctx.front.base, r)
        base[r] = arr
    fwd0 = [b.forwarded for b in ctx.bridges]

    # The sweep publishes ONLY into east's bus: every corridor edge,
    # scenario-priced, once a second — the jam as region-east sees it.
    sweep_bus = NetBus(ctx.east.bus_url, reconnect_s=0.5)
    ctx.east.watch_bus(sweep_bus)
    sweep = CorridorSweep(sweep_bus.publish, corridor, g["length_m"],
                          g["road_class"], ctx.scenario)
    converge = {"east": None, "west": None}
    try:
        time.sleep(5.0)                 # pre-jam coverage settles
        ctx.scenario.set_active(True)
        t0 = time.monotonic()
        while time.monotonic() - t0 < CONVERGE_BOUND_S:
            for r in ("east", "west"):
                if converge[r] is not None or base[r] is None:
                    continue
                arr, _ = _edge_export(ctx.front.base, r)
                if arr is not None and \
                        _median_ratio(base[r], arr, corridor) >= JAM_RATIO:
                    converge[r] = round(time.monotonic() - t0, 1)
            if all(v is not None for v in converge.values()):
                break
            time.sleep(2.0)
        final = {}
        for r in ("east", "west"):
            arr, _ = _edge_export(ctx.front.base, r)
            if arr is not None and base[r] is not None:
                final[r] = {
                    "corridor_ratio": round(
                        _median_ratio(base[r], arr, corridor), 3),
                    "off_corridor_ratio": round(
                        _median_ratio(base[r], arr, off), 3)}
        out["converge_s"] = converge
        out["bound_s"] = CONVERGE_BOUND_S
        out["ratios"] = final
        out["bridge_forwarded"] = [
            {"src": b.src_region, "dst": b.dst_region,
             "frames": b.forwarded - f0, "dropped": b.dropped}
            for b, f0 in zip(ctx.bridges, fwd0)]
    finally:
        ctx.scenario.set_active(False)
        sweep.stop()

    checks = {
        "both_regions_live_ready": all(ready.values()),
        "east_jam_visible": converge["east"] is not None,
        "west_converged_within_bound": converge["west"] is not None,
        "off_corridor_calm": all(
            v["off_corridor_ratio"] <= CALM_RATIO
            for v in out.get("ratios", {}).values()) and bool(out.get("ratios")),
        "bridges_forwarding": all(
            row["frames"] > 0 for row in out["bridge_forwarded"]),
    }
    out["checks"] = checks
    out["pass"] = all(checks.values())
    return out


def scenario_region_loss(ctx, rate: float) -> dict:
    """``region.kill`` east: survivor absorbs, journal holds every
    write, staleness bounded+metered, the reach probe pages by name."""
    from routest_tpu.chaos import _INJECTIONS
    from routest_tpu.serve.fleet.geofront import _front_metrics

    out: dict = {"scenario": "region_loss"}
    front = ctx.front
    # Settle after the jam, then arm the cross-region prober and
    # require a clean baseline before pulling the trigger.
    time.sleep(15.0)
    prober = front.arm_prober(ctx.prober_cfg)
    time.sleep(8.0)
    pre_states = {n: o["state"] for n, o in
                  prober.slo.snapshot()["objectives"].items()}
    out["pre_kill_slo"] = pre_states

    m = _front_metrics()
    chaos0 = _INJECTIONS.labels(point="region.kill", kind="kill").value
    dropped0 = m["journal_dropped"].labels(region="east").value
    west_fleet0 = bp._fetch(f"{front.base}/api/metrics?region=west",
                            timeout=30).get("fleet", {})

    front.kill_region("east")
    t_kill = time.monotonic()
    chaos1 = _INJECTIONS.labels(point="region.kill", kind="kill").value

    # Store-mutating writes taken DURING the outage: served by the
    # survivor, journaled for the corpse.
    for i in range(K_WRITES):
        bp._post(f"{front.base}/api/update_tracker", _tracker_body(i),
                 timeout=60.0)
    # Redirected open-loop user load through the front.
    stop = threading.Event()
    records = bp.open_loop(front.base, rate, 20.0, stop=stop)
    ok = sum(1 for r in records if 200 <= r.status < 400)
    out["survivor_load"] = {"requests": len(records), "ok": ok,
                            "success_ratio": round(ok / max(1, len(records)), 4)}

    page = bp.wait_for_page(prober, PAGE_BOUND_S)
    page["since_kill_s"] = round(time.monotonic() - t_kill, 1)
    out["page"] = page
    out["reach_offenders"] = list(prober._skew_offenders.get("reach", []))

    west_fleet1 = bp._fetch(f"{front.base}/api/metrics?region=west",
                            timeout=30).get("fleet", {})
    shed_delta = (west_fleet1.get("shed", 0) or 0) \
        - (west_fleet0.get("shed", 0) or 0)
    out["survivor_shed"] = {"delta": shed_delta,
                           "frac": round(shed_delta / max(1, len(records)), 4)}
    out["survivor_autoscale"] = bp._fetch(
        f"{front.base}/api/autoscale?region=west", timeout=30)

    snap = front.snapshot()["regions"]
    out["survivor_staleness_s"] = snap["west"]["staleness_s"]
    out["journal"] = {
        "depth_east": front.journal_depth("east"),
        "dropped": m["journal_dropped"].labels(region="east").value
        - dropped0}

    # The survivor's user SLO must come back to ok inside the bound
    # (probe traffic and the region death never burn user budget).
    slo_ok_s = None
    t0 = time.monotonic()
    while time.monotonic() - t0 < SLO_RECOVER_BOUND_S:
        worst = bp._fetch(f"{front.base}/api/slo", timeout=30)["worst"]
        if worst == "ok":
            slo_ok_s = round(time.monotonic() - t0, 1)
            break
        time.sleep(1.0)
    out["user_slo_ok_s"] = slo_ok_s

    checks = {
        "pre_kill_clean": all(s == "ok" for s in pre_states.values()),
        "chaos_recorded": chaos1 == chaos0 + 1,
        "survivor_absorbs": out["survivor_load"]["success_ratio"] >= 0.8,
        "shed_bounded": out["survivor_shed"]["frac"] <= 0.2,
        "paged_within_bound": bool(page.get("paged")),
        "dead_region_named": out["reach_offenders"] == ["east"],
        "journal_holds_writes":
            out["journal"]["depth_east"] == K_WRITES
            and out["journal"]["dropped"] == 0,
        "survivor_staleness_bounded":
            0.0 <= out["survivor_staleness_s"] <= STALE_BOUND_S,
        "user_slo_recovers": slo_ok_s is not None,
    }
    out["checks"] = checks
    out["pass"] = all(checks.values())
    return out


def scenario_rejoin(ctx) -> dict:
    """East returns: journal drains (zero lost writes), live state
    catches up through bridge replay, the page clears, clean window."""
    from routest_tpu.serve.fleet.geofront import _front_metrics

    out: dict = {"scenario": "rejoin"}
    front, prober = ctx.front, ctx.front.prober
    m = _front_metrics()
    replayed0 = m["journal_replayed"].labels(region="east").value
    dropped0 = m["journal_dropped"].labels(region="east").value
    depth0 = front.journal_depth("east")
    out["journal_depth_at_rejoin"] = depth0

    front.rejoin_region("east")
    ready = ctx.east.fleet.wait_ready(timeout=600)

    drained_s = caught_up_s = None
    t0 = time.monotonic()
    while time.monotonic() - t0 < CATCHUP_BOUND_S:
        if drained_s is None and front.journal_depth("east") == 0:
            drained_s = round(time.monotonic() - t0, 1)
        if caught_up_s is None:
            try:
                p = bp._fetch(f"{front.base}/api/live?region=east",
                              timeout=10)
                ingest = p.get("ingest") or {}
                if p.get("ready") and (p.get("epoch") or 0) >= 1 \
                        and (ingest.get("total_observations") or 0) > 0:
                    caught_up_s = round(time.monotonic() - t0, 1)
            except OSError:
                pass
        if drained_s is not None and caught_up_s is not None:
            break
        time.sleep(1.0)
    out["drained_s"] = drained_s
    out["caught_up_s"] = caught_up_s
    out["bound_s"] = CATCHUP_BOUND_S
    out["journal"] = {
        "replayed": m["journal_replayed"].labels(region="east").value
        - replayed0,
        "dropped": m["journal_dropped"].labels(region="east").value
        - dropped0}

    # The reach offender and the page must clear…
    reach_clear_s = no_page_s = None
    t0 = time.monotonic()
    while time.monotonic() - t0 < CATCHUP_BOUND_S:
        if not prober._skew_offenders.get("reach"):
            reach_clear_s = reach_clear_s or round(
                time.monotonic() - t0, 1)
            snap = prober.slo.snapshot()["objectives"]
            if all(o["state"] != "page" for o in snap.values()):
                no_page_s = round(time.monotonic() - t0, 1)
                break
        time.sleep(1.0)
    out["reach_clear_s"] = reach_clear_s
    out["no_page_s"] = no_page_s

    # …and a quiet watch window records zero NEW correctness failures.
    fail0 = len(prober._failures)
    time.sleep(CLEAN_S)
    out["clean_window"] = {"seconds": CLEAN_S,
                           "new_failures": len(prober._failures) - fail0}
    out["regions"] = ctx.front.snapshot()["regions"]

    checks = {
        "rejoined_ready": ready,
        "journal_drained": drained_s is not None,
        "all_writes_replayed":
            out["journal"]["replayed"] == depth0
            and out["journal"]["dropped"] == 0,
        "live_caught_up": caught_up_s is not None,
        "reach_clears": reach_clear_s is not None,
        "page_clears": no_page_s is not None,
        "clean_window_quiet": out["clean_window"]["new_failures"] == 0,
    }
    out["checks"] = checks
    out["pass"] = all(checks.values())
    return out


# ── record ───────────────────────────────────────────────────────────


def main() -> None:
    parser = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    parser.add_argument("--quick", action="store_true",
                        help="smaller extract (CI)")
    parser.add_argument("--nodes", type=int, default=6000)
    parser.add_argument("--rate", type=float, default=2.0)
    parser.add_argument("--cache-dir", default=os.path.join(
        REPO, "artifacts", "bench_cache", "region_failover"))
    parser.add_argument("--out", default=os.path.join(
        REPO, "artifacts", "region_failover.json"))
    args = parser.parse_args()
    if args.quick:
        args.nodes = min(args.nodes, 4000)

    os.environ.setdefault("ROUTEST_FORCE_CPU", "1")
    import jax

    jax.config.update("jax_platforms", "cpu")
    os.makedirs(args.cache_dir, exist_ok=True)
    os.environ["ROUTEST_HIER_CACHE"] = os.path.join(args.cache_dir,
                                                    "hier")
    from routest_tpu.core.cache import enable_compile_cache

    enable_compile_cache(os.path.join(args.cache_dir, "xla"))

    t0 = time.time()
    print(f"[1/5] extract + overlay cache ({args.nodes:,} nodes)…",
          flush=True)
    extract = bp.build_extract(args.nodes, args.cache_dir)

    work = tempfile.mkdtemp(prefix="region-failover-")
    record: dict = {}
    checks: dict = {}
    scenarios: dict = {}
    ctx = None
    print("[2/5] booting two regions + geo-front + bridges…",
          flush=True)
    try:
        ctx = _build_topology(extract, args.cache_dir, work)
        plan = [
            ("bridged_convergence",
             lambda: scenario_bridged_convergence(ctx)),
            ("region_loss", lambda: scenario_region_loss(ctx, args.rate)),
            ("rejoin", lambda: scenario_rejoin(ctx)),
        ]
        for i, (name, run) in enumerate(plan):
            print(f"[{i + 3}/5] scenario {name}…", flush=True)
            t = time.perf_counter()
            try:
                scenarios[name] = run()
            except Exception as e:
                scenarios[name] = {"scenario": name, "pass": False,
                                   "error": f"{type(e).__name__}: {e}"}
            scenarios[name]["wall_s"] = round(time.perf_counter() - t, 1)
            checks[name] = bool(scenarios[name].get("pass"))
            print(f"  {name}: {'PASS' if checks[name] else 'FAIL'} "
                  f"({scenarios[name]['wall_s']}s)", flush=True)
    finally:
        if ctx is not None:
            ctx.front.drain(timeout=10)
            ctx.east.stop()
            ctx.west.stop()
        shutil.rmtree(work, ignore_errors=True)
    record["scenarios"] = scenarios

    try:
        n_cpus = len(os.sched_getaffinity(0))
    except AttributeError:
        n_cpus = os.cpu_count() or 1
    backend = jax.devices()[0].platform
    record.update({
        "generated_unix": int(t0),
        "host": {"cpus": n_cpus, "platform": sys.platform,
                 "backend": backend},
        # Structural caveats (skip reasons are fields, never prose in
        # `note`): convergence/page/catch-up seconds are host-scaled;
        # the invariants (jam crosses only the bridge, dead region
        # named, zero lost writes, clean recovery) are not.
        "host_caveat": (
            f"cpu-backend record on {n_cpus} core(s): convergence, "
            "page, and catch-up latencies are time-shared-host "
            "numbers; judge the structural checks (bridged jam "
            "visible in the peer region, reach page naming the dead "
            "region, journal drained with zero drops, quiet clean "
            "window), not wall-seconds"
            if backend != "tpu" else None),
        "skipped": ("tpu serving rows: CPU fallback — re-record when "
                    "a tunnel appears (scripts/run_tpu_battery.sh "
                    "does it automatically)" if backend != "tpu"
                    else None),
        "config": {
            "nodes": args.nodes, "rate_rps": args.rate,
            "drivers_per_region": DRIVERS,
            "jam_speed_factor": JAM_SPEED_FACTOR,
            "jam_width_m": JAM_WIDTH_M,
            "jam_ratio": JAM_RATIO, "calm_ratio": CALM_RATIO,
            "converge_bound_s": CONVERGE_BOUND_S,
            "page_bound_s": PAGE_BOUND_S,
            "slo_recover_bound_s": SLO_RECOVER_BOUND_S,
            "catchup_bound_s": CATCHUP_BOUND_S,
            "clean_s": CLEAN_S,
            "stale_bound_s": STALE_BOUND_S,
            "journal_writes": K_WRITES,
            "cache_dir": args.cache_dir,
            "quick": bool(args.quick),
        },
        "checks": checks,
    })
    record["all_pass"] = (len(checks) == 3 and all(checks.values()))
    record["wall_s"] = round(time.time() - t0, 1)
    os.makedirs(os.path.dirname(args.out), exist_ok=True)
    with open(args.out, "w") as f:
        json.dump(record, f, indent=2)
    print(f"\nwrote {args.out} "
          f"(all_pass={record['all_pass']}, {record['wall_s']}s)",
          flush=True)
    sys.exit(0 if record["all_pass"] else 1)


if __name__ == "__main__":
    main()
