"""Sequence-parallel scaling curve: where 1-device attention stops and
ring/Ulysses keep going (VERDICT r3 weak/next #5).

Round 3 proved SP *correct* (parity + train step) but only at toy
lengths; this benchmark proves it *necessary*. Each (mode, seq_len)
cell runs in a child process under a hard address-space limit
(``RLIMIT_AS``) standing in for one accelerator's memory: full
attention materializes the (H, S, S) score tensor and dies past the
limit; blockwise streams K/V chunks on one device (peak (H, S, chunk));
the ring rotates K/V blocks (peak (H, S/n, S/n) per tile) and Ulysses
all-to-alls heads onto blockwise streaming (peak (H/n, S, chunk)) so
the SAME budget reaches far longer sequences.
That is the long-context mandate in memory terms, measured, not
asserted; the analytic bytes are recorded per cell so the curve maps
onto any real chip (v5e: 16 GB HBM ⇒ full attention caps around
S≈30k at 4 heads f32; 8-way ring raises the ceiling ~64x).

Wall-clock per step is recorded too, with the honest caveat that the
hermetic "devices" are 8 XLA host-platform shards on ONE machine —
step time shows SP's overhead is modest, not a speedup (speedups need
real chips; total attention FLOPs are invariant under SP).

Writes the ``seq_scaling`` section of artifacts/transformer_report.json
(the trainer preserves it across its own runs).

Usage: python scripts/bench_sp_scaling.py [--limit-gb 12]
       [--seqs 4096 16384 32768 65536] [--modes full ring ulysses]
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# 8 heads: Ulysses requires n_heads % n_devices == 0 on the 8-way mesh.
N_HEADS = 8
D_MODEL = 64
N_DEVICES = 8


def child_main() -> None:
    """One (mode, seq) measurement under the inherited rlimit."""
    mode = os.environ["SP_MODE"]
    seq = int(os.environ["SP_SEQ"])
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + f" --xla_force_host_platform_device_count"
                                 f"={N_DEVICES}").strip()
    import jax

    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh

    from routest_tpu.models.route_transformer import (RouteTransformer,
                                                      make_sp_apply)

    # One layer: this measures the attention scaling law, not the MLP.
    model = RouteTransformer(d_model=D_MODEL, n_heads=N_HEADS, n_layers=1)
    params = model.init(jax.random.PRNGKey(0))
    rng = np.random.default_rng(0)
    feats = jnp.asarray(rng.normal(size=(1, seq, model.n_features)),
                        jnp.float32)
    freeflow = jnp.ones((1, seq), jnp.float32)
    mask = jnp.ones((1, seq), jnp.float32)

    if mode in ("full", "blockwise"):
        from routest_tpu.parallel.ring import blockwise_attention

        positions = jnp.arange(seq)
        attn = None if mode == "full" else blockwise_attention

        @jax.jit
        def fwd(p, f, ff, m):
            return model.apply(p, f, ff, positions, key_mask=m,
                               attn_impl=attn)

        run = lambda: fwd(params, feats, freeflow, mask)  # noqa: E731
    else:
        devs = np.asarray(jax.devices()[:N_DEVICES])
        mesh = Mesh(devs, ("seq",))
        sp = make_sp_apply(model, mesh, flavor=mode)
        run = lambda: sp(params, feats, freeflow, mask)  # noqa: E731

    t0 = time.perf_counter()
    out = run()
    out.block_until_ready()
    compile_s = time.perf_counter() - t0
    times = []
    for _ in range(2):
        t0 = time.perf_counter()
        run().block_until_ready()
        times.append(time.perf_counter() - t0)
    print(json.dumps({"status": "ok", "step_ms": round(1000 * min(times), 1),
                      "compile_s": round(compile_s, 1)}))


def _analytic_bytes(mode: str, seq: int) -> int:
    """Peak score-tensor bytes per device, f32."""
    from routest_tpu.parallel.ring import DEFAULT_CHUNK

    if mode == "full":
        return N_HEADS * seq * seq * 4
    if mode == "blockwise":
        # flash-style streaming on ONE device: (S x chunk) tiles
        return N_HEADS * seq * min(seq, DEFAULT_CHUNK) * 4
    if mode == "ring":
        # one (S/n x S/n) tile per hop
        return N_HEADS * (seq // N_DEVICES) ** 2 * 4
    # ulysses: H/n resident heads, streamed blockwise over the full row
    return (N_HEADS // N_DEVICES or 1) * seq * min(seq, DEFAULT_CHUNK) * 4


def main() -> None:
    if os.environ.get("SP_MODE"):
        child_main()
        return

    parser = argparse.ArgumentParser()
    parser.add_argument("--limit-gb", type=float, default=12.0,
                        help="per-child RLIMIT_AS — the stand-in for one "
                             "device's memory")
    parser.add_argument("--seqs", type=int, nargs="+",
                        default=[4096, 16384, 32768, 65536])
    parser.add_argument("--modes", nargs="+",
                        default=["full", "blockwise", "ring", "ulysses"])
    parser.add_argument("--timeout", type=float, default=900.0)
    args = parser.parse_args()

    import resource

    limit = int(args.limit_gb * (1 << 30))

    def preexec():
        resource.setrlimit(resource.RLIMIT_AS, (limit, limit))

    rows = []
    dead: dict = {}
    for seq in args.seqs:
        for mode in args.modes:
            cell = {"mode": mode, "seq_len": seq,
                    "score_bytes_per_device": _analytic_bytes(mode, seq)}
            if dead.get(mode):
                # Larger seq cannot revive a mode that already OOMed.
                cell["status"] = "skipped_after_oom"
                rows.append(cell)
                continue
            env = dict(os.environ, SP_MODE=mode, SP_SEQ=str(seq))
            t0 = time.perf_counter()
            try:
                proc = subprocess.run(
                    [sys.executable, os.path.abspath(__file__)],
                    capture_output=True, text=True, env=env,
                    timeout=args.timeout, preexec_fn=preexec)
            except subprocess.TimeoutExpired:
                cell["status"] = "timeout"
                dead[mode] = True
                rows.append(cell)
                continue
            out = None
            for line in reversed(proc.stdout.splitlines()):
                if line.startswith("{"):
                    out = json.loads(line)
                    break
            if proc.returncode == 0 and out:
                cell.update(out)
            else:
                # MemoryError / std::bad_alloc / RESOURCE_EXHAUSTED / a
                # SIGKILL from the allocator all mean the same thing
                # under RLIMIT_AS: this mode cannot fit this sequence.
                # Anything else (e.g. a shape/config error) must NOT be
                # scored as a memory ceiling.
                tail = (proc.stderr or "")[-4000:]
                is_oom = (proc.returncode < 0
                          or "MemoryError" in tail
                          or "RESOURCE_EXHAUSTED" in tail
                          or "bad_alloc" in tail
                          or "alloc" in tail.lower())
                cell["status"] = "oom" if is_oom else "error"
                if not is_oom:
                    cell["error"] = tail.strip().splitlines()[-1][:200] \
                        if tail.strip() else f"rc={proc.returncode}"
                cell["rc"] = proc.returncode
                dead[mode] = True
            cell["wall_s"] = round(time.perf_counter() - t0, 1)
            rows.append(cell)
            print(f"  {mode:8s} seq={seq:>7,} → {cell['status']}"
                  + (f" step {cell['step_ms']} ms"
                     if cell["status"] == "ok" else ""), flush=True)

    max_seq = {m: max([r["seq_len"] for r in rows
                       if r["mode"] == m and r.get("status") == "ok"],
                      default=0) for m in args.modes}
    summary = {
        "device_limit_gb": args.limit_gb,
        "n_devices": N_DEVICES,
        "heads": N_HEADS,
        "d_model": D_MODEL,
        "backend": "cpu (8 virtual devices, one host — memory ceiling is "
                   "the hermetic demonstrand; step-time speedups need "
                   "real chips)",
        "max_seq": max_seq,
        "sp_extends_seq_by": (max(max_seq.get("ring", 0),
                                  max_seq.get("ulysses", 0))
                              / max(max_seq.get("full", 1), 1)),
        "rows": rows,
    }

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    out_path = os.path.join(repo, "artifacts", "transformer_report.json")
    report = {}
    if os.path.exists(out_path):
        try:
            with open(out_path) as f:
                report = json.load(f)
        except (ValueError, OSError):
            report = {}
    report["seq_scaling"] = summary
    with open(out_path, "w") as f:
        json.dump(report, f, indent=2)
    print(json.dumps({"max_seq": max_seq,
                      "sp_extends_seq_by": summary["sp_extends_seq_by"]}))
    print(f"→ {out_path}")


if __name__ == "__main__":
    main()
